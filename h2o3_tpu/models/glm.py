"""GLM — generalized linear models with elastic net.

Reference: hex/glm/GLM.java:65 — IRLSM (Gram + Cholesky + ADMM for L1,
GLM.java:1451,1995), L-BFGS (GLM.java:2056), coordinate descent; lambda
search along a regularization path; families gaussian/binomial/
quasibinomial/poisson/gamma/tweedie/multinomial/negativebinomial/ordinal.

TPU redesign (SURVEY §3.4): one IRLS iteration = one einsum Gram pass
over the row-sharded design matrix (`ops/gram.py`, psum over ICI) + a
replicated Cholesky/ADMM solve. X'WX for P coefficients costs one
[P,N]x[N,P] contraction on the MXU — the reference's careful
single-threaded Cholesky bottleneck disappears into LAX. Multinomial
runs L-BFGS on the full softmax objective (the reference's default for
multinomial is also L_BFGS).

All reference families are supported: gaussian, binomial,
quasibinomial, fractionalbinomial, poisson, gamma, tweedie,
negativebinomial (theta), multinomial, ordinal (proportional-odds
L-BFGS path) — see the Family class below and tests/test_glm_surface.py.
"""

from __future__ import annotations

import time

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.frame.datainfo import (DataInfo, build_datainfo,
                                     coef_stats, stats_of)
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import (Model, ModelBuilder, ModelCategory,
                                   adapt_domain, infer_category)
from h2o3_tpu.ops.gram import gram
from h2o3_tpu.ops.optimize import (admm_l1_quadratic,
                                   cholesky_solve_regularized, lbfgs)
from h2o3_tpu.parallel.mesh import (get_mesh, put_sharded,
                                    row_sharding)
from h2o3_tpu.telemetry import observed_jit
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.glm")


# ---- family/link layer (hex/glm/GLMModel.GLMParameters.Family) ----------
class Family:
    """linkinv/variance/deviance on mu; link derivative for IRLS."""

    def __init__(self, name: str, tweedie_power: float = 1.5,
                 link: Optional[str] = None, theta: float = 1e-5):
        self.name = name
        self.p = tweedie_power
        self.theta = theta       # negativebinomial inverse dispersion
        # (may be a traced scalar inside jit — no host float() here)
        defaults = {"gaussian": "identity", "binomial": "logit",
                    "quasibinomial": "logit", "fractionalbinomial": "logit",
                    "poisson": "log", "gamma": "log", "tweedie": "tweedie",
                    "negativebinomial": "log",
                    "multinomial": "multinomial"}
        # "family_default" is the wire spelling of "use the default link"
        # (hex/glm/GLMModel.GLMParameters.Link.family_default)
        if link in ("family_default", "auto", ""):
            link = None
        allowed = {"gaussian": {"identity", "log", "inverse"},
                   "binomial": {"logit"},
                   "quasibinomial": {"logit"},
                   "fractionalbinomial": {"logit"},
                   "poisson": {"log", "identity"},
                   "gamma": {"log", "identity", "inverse"},
                   "tweedie": {"tweedie"},
                   "negativebinomial": {"log", "identity"},
                   "multinomial": {"multinomial"}}
        if link is not None and name in allowed \
                and link not in allowed[name]:
            # family-link compatibility matrix
            # (hex/glm/GLMModel.GLMParameters validation)
            raise ValueError(
                f"Incompatible link function for selected family: "
                f"link {link} is not supported for family {name}")
        self.link = link or defaults[name]

    # mu = linkinv(eta)
    def linkinv(self, eta):
        if self.link == "identity":
            return eta
        if self.link == "logit":
            return jnp.clip(jax.nn.sigmoid(eta), 1e-7, 1 - 1e-7)
        if self.link == "log":
            return jnp.exp(jnp.clip(eta, -30.0, 30.0))
        if self.link == "inverse":
            return 1.0 / jnp.where(jnp.abs(eta) < 1e-6,
                                   jnp.sign(eta) * 1e-6 + 1e-12, eta)
        if self.link == "tweedie":
            return jnp.exp(jnp.clip(eta, -30.0, 30.0))  # log link for tweedie
        raise ValueError(self.link)

    def dmu_deta(self, eta, mu):
        if self.link == "identity":
            return jnp.ones_like(eta)
        if self.link == "logit":
            return mu * (1.0 - mu)
        if self.link in ("log", "tweedie"):
            return mu
        if self.link == "inverse":
            return -mu * mu
        raise ValueError(self.link)

    def variance(self, mu):
        if self.name == "gaussian":
            return jnp.ones_like(mu)
        if self.name in ("binomial", "quasibinomial", "fractionalbinomial"):
            return mu * (1.0 - mu)
        if self.name == "poisson":
            return jnp.maximum(mu, 1e-10)
        if self.name == "gamma":
            return jnp.maximum(mu * mu, 1e-10)
        if self.name == "tweedie":
            return jnp.maximum(mu, 1e-10) ** self.p
        if self.name == "negativebinomial":
            # var = mu + theta*mu^2 (hex/glm/GLMModel Family
            # negativebinomial; theta = inverse dispersion)
            th = jnp.maximum(self.theta, 1e-10)
            return jnp.maximum(mu * (1.0 + th * mu), 1e-10)
        raise ValueError(self.name)

    def deviance(self, y, mu):
        """Unit deviance (ModelMetricsRegressionGLM residual deviance)."""
        if self.name == "gaussian":
            return (y - mu) ** 2
        if self.name == "binomial":
            mu = jnp.clip(mu, 1e-7, 1 - 1e-7)
            return -2.0 * (y * jnp.log(mu) + (1 - y) * jnp.log1p(-mu))
        if self.name == "poisson":
            ylogy = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, 1e-10) / mu), 0.0)
            return 2.0 * (ylogy - (y - mu))
        if self.name == "gamma":
            yr = jnp.maximum(y, 1e-10) / jnp.maximum(mu, 1e-10)
            return 2.0 * (-jnp.log(yr) + yr - 1.0)
        if self.name == "tweedie":
            p = self.p
            return 2.0 * (jnp.maximum(y, 0.0) ** (2 - p) / ((1 - p) * (2 - p))
                          - y * mu ** (1 - p) / (1 - p)
                          + mu ** (2 - p) / (2 - p))
        if self.name in ("quasibinomial", "fractionalbinomial"):
            # binomial log-likelihood deviance with real-valued y
            mu = jnp.clip(mu, 1e-7, 1 - 1e-7)
            return -2.0 * (y * jnp.log(mu) + (1 - y) * jnp.log1p(-mu))
        if self.name == "negativebinomial":
            th = jnp.maximum(self.theta, 1e-10)
            ylogy = jnp.where(
                y > 0, y * jnp.log(jnp.maximum(y, 1e-10) / mu), 0.0)
            return 2.0 * (ylogy - (y + 1.0 / th) * jnp.log(
                (1.0 + th * y) / (1.0 + th * mu)))
        raise ValueError(self.name)


@partial(jax.jit, static_argnames=("family", "link", "use_l1"))
def _irls_iter(X1, coef, y, w, off, l1, l2, family: str, link: str,
               tweedie_power, theta=1e-5, *, use_l1: bool):
    """One full IRLS iteration on device: re-weight → Gram (psum over the
    mesh) → penalized solve. λ enters as traced scalars so the lambda
    path reuses one compiled program (GLM.java fitIRLSM per-lambda loop).
    """
    fam = Family(family, tweedie_power, link, theta=theta)
    eta = X1 @ coef + off
    mu = fam.linkinv(eta)
    d = fam.dmu_deta(eta, mu)
    var = fam.variance(mu)
    # working response net of the fixed offset (GLMTask with offset)
    z = eta - off + (y - mu) / jnp.where(jnp.abs(d) < 1e-10, 1e-10, d)
    w_irls = w * d * d / jnp.maximum(var, 1e-10)
    dev = jnp.sum(w * fam.deviance(y, mu))

    mesh = get_mesh()
    from h2o3_tpu.parallel.mesh import MODEL_AXIS
    if mesh.shape.get(MODEL_AXIS, 1) > 1:
        # wide one-hot designs on a (data, model) mesh: column-sharded
        # Gram via the ppermute ring (SURVEY §2.4 item 6 TP-like axis)
        from h2o3_tpu.ops.gram import gram_model_sharded
        xtx, xtz, _ = gram_model_sharded(X1, w_irls, z, mesh=mesh)
    else:
        xtx, xtz, _ = gram(X1, w_irls, z, mesh=mesh)
    nobs = jnp.maximum(jnp.sum(w), 1.0)
    A = xtx / nobs
    q = xtz / nobs
    Pp1 = X1.shape[1]
    penalize = jnp.concatenate([jnp.ones(Pp1 - 1), jnp.zeros(1)]).astype(A.dtype)
    if use_l1:
        new_coef = admm_l1_quadratic(A + l2 * jnp.diag(penalize), q, l1,
                                     penalize)
    else:
        new_coef = cholesky_solve_regularized(A, q, l2, penalize)
    delta = jnp.max(jnp.abs(new_coef - coef))
    return new_coef, delta, dev


@observed_jit("glm.irls_solve")
@partial(jax.jit, static_argnames=("family", "link", "use_l1"))
def _irls_solve(X1, coef, y, w, off, l1, l2, beta_eps, max_iter,
                family: str, link: str, tweedie_power, theta=1e-5,
                obj_eps=1e-6, *, use_l1: bool):
    """The whole IRLS loop as one compiled ``while_loop`` — per-iteration
    host syncs (one device round trip each) previously dominated GLM
    wall time on a remote-attached chip.

    Three reference behaviors (GLM.java fitIRLSM):
    - beta_epsilon stop on the coefficient delta;
    - objective_epsilon stop on relative penalized-objective change —
      load-bearing under L1, where ADMM's inexact solves jitter coef by
      more than beta_epsilon forever (every lambda burned the full
      max_iterations budget → pyunit_glm_seed's 600s timeout);
    - objective LINE SEARCH on the IRLS step (GLM.java line-search on
      quasi-separable data): undamped Newton oscillates when the MLE
      diverges, so the step is chosen as the best of {full, 1/2, ...,
      1/128, none} by penalized objective — nine cheap matvecs, all
      fused on device."""
    fam = Family(family, tweedie_power, link, theta=theta)
    steps = jnp.concatenate([2.0 ** -jnp.arange(8, dtype=jnp.float32),
                             jnp.zeros(1, jnp.float32)])

    def pen_of(c):
        return l1 * jnp.sum(jnp.abs(c[:-1])) \
            + 0.5 * l2 * jnp.sum(c[:-1] * c[:-1])

    def cond(state):
        coef, delta, obj_prev, obj, it = state
        rel = jnp.abs(obj_prev - obj) / jnp.maximum(jnp.abs(obj), 1e-10)
        return (delta > beta_eps) & (rel > obj_eps) & (it < max_iter)

    def body(state):
        coef, _, _, obj, it = state
        full, _, _ = _irls_iter(X1, coef, y, w, off, l1, l2,
                                family, link, tweedie_power,
                                theta, use_l1=use_l1)
        # candidates coef + s*(full-coef); objectives in ONE batched pass
        cands = coef[None, :] + steps[:, None] * (full - coef)[None, :]
        mus = fam.linkinv(X1 @ cands.T + off[:, None])       # [N, 9]
        devs = jnp.sum(w[:, None] * fam.deviance(y[:, None], mus), axis=0)
        pens = jax.vmap(pen_of)(cands)
        objs = devs + pens
        k = jnp.argmin(objs)
        new_coef = cands[k]
        delta = jnp.max(jnp.abs(new_coef - coef))
        return new_coef, delta, obj, objs[k], it + 1

    # finite sentinels: ±inf would make rel = inf/inf = NaN and the
    # NaN > eps comparison (False) would skip the loop entirely
    coef, _, _, _, _ = jax.lax.while_loop(
        cond, body, (coef, jnp.float32(1e30), jnp.float32(-1e30),
                     jnp.float32(1e30), jnp.int32(0)))
    return coef


@partial(jax.jit, static_argnames=("family", "link", "use_l1"))
def _irls_solve_path(X1, coef, y, w, off, l1s, l2s, beta_eps, max_iter,
                     family: str, link: str, tweedie_power, theta=1e-5,
                     obj_eps=1e-4, *, use_l1: bool):
    """The WHOLE lambda path as one compiled ``scan`` of IRLS solves,
    warm-starting each lambda from the previous solution (GLM.java
    lambda-search semantics). A 30-step search previously paid 30
    dispatches per fit; with 3-fold CV and multiple models that
    multiplied into pyunit_glm_seed's 600s timeout. Returns the final
    (smallest-lambda) coefficients — what the single-model path keeps."""

    def solve_one(c, l12):
        l1, l2 = l12
        c = _irls_solve(X1, c, y, w, off, l1, l2, beta_eps, max_iter,
                        family, link, tweedie_power, theta, obj_eps,
                        use_l1=use_l1)
        return c, c

    coef, path = jax.lax.scan(solve_one, coef, (l1s, l2s))
    return coef, path


@observed_jit("glm.irls_solve_batched")
@partial(jax.jit, static_argnames=("family", "link", "use_l1"))
def _irls_solve_batched(X1, coef0, y, w, off, l1s, l2s, beta_eps,
                        max_iter, family: str, link: str, tweedie_power,
                        theta=1e-5, obj_epss=None, *, use_l1: bool):
    """Model-batched IRLS: ``vmap`` over the (alpha, lambda) product of
    a grid/AutoML shape bucket — each lane is an INDEPENDENT fit from
    the zero start (exactly what the sequential grid walk solves per
    combo; contrast _irls_solve_path, whose lambdas warm-start
    sequentially within ONE model). l1s/l2s/obj_epss ride the vmapped
    axis; X1/y/w/off broadcast. The vmapped while_loop runs until every
    lane converges, freezing finished lanes, so an M-combo sweep costs
    one dispatch instead of M."""

    def one(l1, l2, oe):
        return _irls_solve(X1, coef0, y, w, off, l1, l2, beta_eps,
                           max_iter, family, link, tweedie_power, theta,
                           oe, use_l1=use_l1)

    return jax.vmap(one)(l1s, l2s, obj_epss)


@partial(jax.jit, static_argnames=("family", "link", "sweeps"))
def _irls_iter_cod(X1, coef, y, w, off, l1, l2, lo, hi, family: str,
                   link: str, tweedie_power, theta=1e-5, *,
                   sweeps: int = 50):
    """One IRLS iteration solved by (optionally box-constrained) cyclic
    coordinate descent — GLM.java:1495 fitCOD and the beta_constraints /
    non_negative projected path."""
    from h2o3_tpu.ops.optimize import coordinate_descent_quadratic
    fam = Family(family, tweedie_power, link, theta=theta)
    eta = X1 @ coef + off
    mu = fam.linkinv(eta)
    d = fam.dmu_deta(eta, mu)
    var = fam.variance(mu)
    z = eta - off + (y - mu) / jnp.where(jnp.abs(d) < 1e-10, 1e-10, d)
    w_irls = w * d * d / jnp.maximum(var, 1e-10)
    mesh = get_mesh()
    xtx, xtz, _ = gram(X1, w_irls, z, mesh=mesh)
    nobs = jnp.maximum(jnp.sum(w), 1.0)
    A = xtx / nobs
    q = xtz / nobs
    Pp1 = X1.shape[1]
    penalize = jnp.concatenate([jnp.ones(Pp1 - 1),
                                jnp.zeros(1)]).astype(A.dtype)
    new_coef = coordinate_descent_quadratic(A, q, l1, l2, penalize,
                                            lower=lo, upper=hi,
                                            sweeps=sweeps)
    delta = jnp.max(jnp.abs(new_coef - coef))
    return new_coef, delta


@partial(jax.jit, static_argnames=("family", "link"))
def _glm_value_grad(coef, X1, y, w, off, l2, family: str, link: str,
                    tweedie_power, theta=1e-5):
    """Penalized deviance objective + gradient (GLMGradientTask role)."""
    fam = Family(family, tweedie_power, link, theta=theta)
    Pp1 = X1.shape[1]
    penalize = jnp.concatenate([jnp.ones(Pp1 - 1), jnp.zeros(1)]).astype(jnp.float32)
    nobs = jnp.maximum(jnp.sum(w), 1.0)

    def obj(c):
        mu = fam.linkinv(X1 @ c.astype(jnp.float32) + off)
        dev = jnp.sum(w * fam.deviance(y, mu)) / (2.0 * nobs)
        return dev + 0.5 * l2 * jnp.sum(penalize * c * c)

    return jax.value_and_grad(obj)(coef)


@partial(jax.jit, static_argnames=("K",))
def _multinomial_value_grad(flat, X1, y_int, w, l2, K: int):
    Pp1 = X1.shape[1]
    penalize = jnp.concatenate([jnp.ones(Pp1 - 1), jnp.zeros(1)]).astype(jnp.float32)
    Y = (y_int[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)
    nobs = jnp.maximum(jnp.sum(w), 1.0)

    def obj(fl):
        B = fl.reshape(Pp1, K).astype(jnp.float32)
        logp = jax.nn.log_softmax(X1 @ B, axis=1)
        nll = -jnp.sum(w[:, None] * Y * logp) / nobs
        return nll + 0.5 * l2 * jnp.sum((penalize[:, None] * B) ** 2)

    return jax.value_and_grad(obj)(flat)


@partial(jax.jit, static_argnames=("K", "use_l1"))
def _multinomial_irls_solve(X1, B, y_int, w, l1, l2, beta_eps, max_iter,
                            *, K: int, use_l1: bool):
    """Multinomial IRLSM: block-coordinate IRLS over classes
    (hex/glm/GLM.java:1995 fitIRLSM multinomial path — one weighted
    least-squares subproblem per class per sweep, cycled to
    convergence). Working weights p_c(1-p_c), working response from the
    class margin; L1 via the same ADMM inner solver as binomial.
    The whole sweep loop is one compiled while_loop."""
    Pp1 = X1.shape[1]
    penalize = jnp.concatenate([jnp.ones(Pp1 - 1),
                                jnp.zeros(1)]).astype(jnp.float32)
    nobs = jnp.maximum(jnp.sum(w), 1.0)
    mesh = get_mesh()

    def one_class(B, c):
        eta = X1 @ B
        p = jax.nn.softmax(eta, axis=1)
        pc = p[:, c]
        yc = (y_int == c).astype(jnp.float32)
        d = jnp.maximum(pc * (1.0 - pc), 1e-10)
        z = eta[:, c] + (yc - pc) / d
        wc = w * d
        xtx, xtz, _ = gram(X1, wc, z, mesh=mesh)
        A = xtx / nobs
        q = xtz / nobs
        if use_l1:
            bc = admm_l1_quadratic(A + l2 * jnp.diag(penalize), q, l1,
                                   penalize)
        else:
            bc = cholesky_solve_regularized(A, q, l2, penalize)
        return B.at[:, c].set(bc)

    def body(state):
        B, _, it = state
        Bn = B
        for c in range(K):            # K static: unrolled class sweep
            Bn = one_class(Bn, c)
        return Bn, jnp.max(jnp.abs(Bn - B)), it + 1

    def cond(state):
        return (state[1] > beta_eps) & (state[2] < max_iter)

    B, _, _ = jax.lax.while_loop(
        cond, body, (B, jnp.float32(jnp.inf), jnp.int32(0)))
    return B


@partial(jax.jit, static_argnames=("K",))
def _ordinal_value_grad(flat, X1, y_int, w, l2, K: int):
    """Proportional-odds (cumulative logit) NLL + gradient
    (hex/glm Family.ordinal — GLM.java ordinal path).

    Params: [beta (P, no intercept term used), raw thresholds (K-1)]
    with thresholds alpha_k = a0 + cumsum(exp(d_k)) to keep them ordered.
    P(y<=k) = sigmoid(alpha_k - eta).
    """
    P = X1.shape[1] - 1            # design carries a ones column; unused
    Xb = X1[:, :P]

    def obj(fl):
        beta = fl[:P].astype(jnp.float32)
        a0 = fl[P]
        deltas = fl[P + 1:]
        alphas = jnp.concatenate(
            [a0[None], a0 + jnp.cumsum(jnp.exp(deltas))]).astype(jnp.float32)
        eta = Xb @ beta
        # cumulative probs for k = 0..K-2, bracketed by 0 and 1
        cum = jax.nn.sigmoid(alphas[None, :] - eta[:, None])
        cum = jnp.concatenate([jnp.zeros((eta.shape[0], 1)), cum,
                               jnp.ones((eta.shape[0], 1))], axis=1)
        pk = jnp.take_along_axis(cum, y_int[:, None] + 1, axis=1)[:, 0] - \
            jnp.take_along_axis(cum, y_int[:, None], axis=1)[:, 0]
        nll = -jnp.sum(w * jnp.log(jnp.clip(pk, 1e-9, 1.0))) \
            / jnp.maximum(jnp.sum(w), 1.0)
        return nll + 0.5 * l2 * jnp.sum(beta * beta)

    return jax.value_and_grad(obj)(flat)


def expand_interactions(frame: Frame, inter_cols: Sequence[str]) -> Frame:
    """Augment a frame with pairwise interaction columns among
    ``inter_cols`` (hex/DataInfo.java:16 interactions /
    InteractionWrappedVec semantics):

      num x num   → product column  a_b
      enum x enum → combined factor a_b with observed level pairs
      enum x num  → per-level masked numerics a.<level>_b

    Original Column objects are shared (no device copies)."""
    import itertools
    from h2o3_tpu.frame.column import Column, T_CAT, T_NUM
    from h2o3_tpu.parallel import mesh as mesh_mod
    cols = [frame.col(n) for n in frame.names]
    n = frame.nrows
    npad = cols[0].data.shape[0] if cols and cols[0].data is not None \
        else mesh_mod.padded_rows(n)
    shard = mesh_mod.row_sharding()
    new_cols = list(cols)
    for a, b in itertools.combinations(inter_cols, 2):
        ca, cb = frame.col(a), frame.col(b)
        if not ca.is_categorical and not cb.is_categorical:
            va, vb = ca.numeric_view(), cb.numeric_view()
            prod = va * vb
            na = jnp.isnan(prod)
            new_cols.append(Column(
                name=f"{a}_{b}", type=T_NUM,
                data=jax.device_put(jnp.where(na, 0.0, prod), shard),
                na_mask=jax.device_put(na, shard), nrows=n))
        elif ca.is_categorical and cb.is_categorical:
            ka = _fetch_np(ca.data)[:n]
            kb = _fetch_np(cb.data)[:n]
            na = (_fetch_np(ca.na_mask)[:n] | _fetch_np(cb.na_mask)[:n])
            combo = ka.astype(np.int64) * len(cb.domain or []) + kb
            combo[na] = -1
            seen = np.unique(combo[combo >= 0])
            lut = {int(c): i for i, c in enumerate(seen)}
            codes = np.array([lut.get(int(c), -1) for c in combo],
                             np.int32)
            dom = [f"{ca.domain[c // len(cb.domain)]}_"
                   f"{cb.domain[c % len(cb.domain)]}" for c in seen]
            codes_p = np.pad(np.where(codes < 0, 0, codes),
                             (0, npad - n))
            na_p = np.pad(codes < 0, (0, npad - n),
                          constant_values=True)
            new_cols.append(Column(
                name=f"{a}_{b}", type=T_CAT,
                data=jax.device_put(jnp.asarray(codes_p), shard),
                na_mask=jax.device_put(jnp.asarray(na_p), shard),
                nrows=n, domain=dom))
        else:
            cat, num = (ca, cb) if ca.is_categorical else (cb, ca)
            cname, nname = (a, b) if ca.is_categorical else (b, a)
            vnum = num.numeric_view()
            codes = jnp.asarray(np.pad(
                _fetch_np(cat.data)[:n], (0, npad - n)))
            cna = jnp.asarray(np.pad(
                _fetch_np(cat.na_mask)[:n], (0, npad - n),
                constant_values=True))
            for li, lvl in enumerate(cat.domain or []):
                v = jnp.where((codes == li) & ~cna, vnum, 0.0)
                na = jnp.isnan(v)
                new_cols.append(Column(
                    name=f"{cname}.{lvl}_{nname}", type=T_NUM,
                    data=jax.device_put(jnp.where(na, 0.0, v), shard),
                    na_mask=jax.device_put(na, shard), nrows=n))
    out = Frame(new_cols, n)
    from h2o3_tpu.core.kv import DKV
    DKV.remove(out.key)      # transient view, keep it out of the store
    return out


class GLMModel(Model):
    algo = "glm"

    def __init__(self, params, output, coef: np.ndarray, family: Family,
                 di_stats: dict, features: List[str],
                 coef_multinomial: Optional[np.ndarray] = None):
        super().__init__(params, output)
        self.coef = coef                       # [P+1] (last = intercept)
        self.coef_multinomial = coef_multinomial  # [P+1, K] or None
        self.family = family
        self.di_stats = di_stats
        self.features = features

    def _design(self, frame: Frame) -> jax.Array:
        inter = self.params.get("interactions")
        if inter:
            frame = expand_interactions(frame, inter)
        di = build_datainfo(frame, self.features,
                            standardize=self.params.get("standardize", True),
                            use_all_factor_levels=self.params.get(
                                "use_all_factor_levels", False),
                            stats_override=self.di_stats)
        ones = jnp.ones((di.X.shape[0], 1), jnp.float32)
        return jnp.concatenate([di.X, ones], axis=1)

    def _frame_offset(self, frame: Frame):
        oc = self.params.get("offset_column")
        if not oc or oc not in frame:
            return None
        ov = frame.col(oc).numeric_view()
        return jnp.where(jnp.isnan(ov), 0.0, ov).astype(jnp.float32)

    def _eta(self, frame: Frame):
        X1 = self._design(frame)
        off = self._frame_offset(frame)
        if self.coef_multinomial is not None:
            # offset is deliberately NOT applied: a per-row constant
            # added to every class margin cancels in softmax, so the
            # reference ignores it for multinomial with a warning
            # (hex/glm/GLM.java:978 "offset has no effect on
            # multinomial and will be ignored")
            return X1 @ jnp.asarray(self.coef_multinomial, jnp.float32)
        eta = X1 @ jnp.asarray(self.coef, jnp.float32)
        return eta if off is None else eta + off

    def _ordinal_probs(self, frame: Frame) -> jax.Array:
        """Device-resident ordinal class probabilities [Npad, K]
        (proportional-odds P(y<=k) differences), like the other
        families' device scoring paths."""
        X1 = self._design(frame)
        P = X1.shape[1] - 1
        eta = X1[:, :P] @ jnp.asarray(self.coef[:P], jnp.float32)
        alphas = jnp.asarray(self.output["ordinal_alphas"], jnp.float32)
        cum = jax.nn.sigmoid(alphas[None, :] - eta[:, None])
        cum = jnp.concatenate(
            [jnp.zeros((eta.shape[0], 1), jnp.float32), cum,
             jnp.ones((eta.shape[0], 1), jnp.float32)], axis=1)
        return jnp.diff(cum, axis=1)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        n = frame.nrows
        cat = self.output["category"]
        off = self._frame_offset(frame)
        ordinal = self.output.get("family") == "ordinal"
        if off is None or ordinal or self.coef_multinomial is not None:
            # the model's ONE compiled scoring program — the same
            # executable the serving tier dispatches, so row-payload
            # predictions match bit-for-bit (Model._serve_jit; the
            # whole pipeline stays on device, ONE fetch at the end —
            # offset is a no-op for multinomial/ordinal, GLM.java:978)
            X1 = self._design(frame)
            return self._serve_finish(_fetch_np(self._serve_jit()(X1)), n)
        eta = self._eta(frame)
        mu = _fetch_np(self.family.linkinv(eta))[:n]
        if cat == ModelCategory.BINOMIAL:
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (mu >= t).astype(np.int32),
                    "p0": 1.0 - mu, "p1": mu}
        return {"predict": mu}

    def _serve_dev(self, X1):
        """Device half of the serving fast path (serving/engine.py jits
        this per row bucket): EXACTLY the device math of ``_score_raw``
        on a prepared design matrix (``_design`` output, intercept
        column included). Offset/interactions models take the engine's
        eager fallback."""
        if self.output.get("family") == "ordinal":
            P = X1.shape[1] - 1
            eta = X1[:, :P] @ jnp.asarray(self.coef[:P], jnp.float32)
            alphas = jnp.asarray(self.output["ordinal_alphas"], jnp.float32)
            cum = jax.nn.sigmoid(alphas[None, :] - eta[:, None])
            cum = jnp.concatenate(
                [jnp.zeros((eta.shape[0], 1), jnp.float32), cum,
                 jnp.ones((eta.shape[0], 1), jnp.float32)], axis=1)
            return jnp.diff(cum, axis=1)
        if self.coef_multinomial is not None:
            return jax.nn.softmax(
                X1 @ jnp.asarray(self.coef_multinomial, jnp.float32), axis=1)
        return self.family.linkinv(X1 @ jnp.asarray(self.coef, jnp.float32))

    def _serve_finish(self, fetched: np.ndarray, n: int) -> Dict[str, np.ndarray]:
        """Host half of the serving fast path: the exact host tail of
        ``_score_raw`` applied to the fetched device output."""
        cat = self.output["category"]
        if self.output.get("family") == "ordinal" or \
                cat == ModelCategory.MULTINOMIAL:
            p = fetched[:n]
            out = {"predict": p.argmax(axis=1).astype(np.int32)}
            for k in range(p.shape[1]):
                out[f"p{k}"] = p[:, k]
            return out
        mu = fetched[:n]
        if cat == ModelCategory.BINOMIAL:
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (mu >= t).astype(np.int32),
                    "p0": 1.0 - mu, "p1": mu}
        return {"predict": mu}

    def model_performance(self, frame: Frame, mask_weights=None):
        """``mask_weights``: see GBMModel.model_performance (CV fast
        path holdout metrics on the parent frame)."""
        y = self.output["response"]
        cat = self.output["category"]
        eta = self._eta(frame)
        w = frame.valid_weights()
        wc_name = self.params.get("weights_column")
        if wc_name and wc_name in frame:
            wc = frame.col(wc_name).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        if mask_weights is not None:
            w = w * jnp.asarray(mask_weights, jnp.float32)
        npad = eta.shape[0]
        if cat == ModelCategory.BINOMIAL:
            yv = adapt_domain(frame.col(y), self.output["domain"])
            yv = np.pad(yv, (0, npad - frame.nrows), constant_values=-1)
            w = w * jnp.asarray((yv >= 0).astype(np.float32))
            p = self.family.linkinv(eta)
            return mm.binomial_metrics(p, jnp.asarray(np.maximum(yv, 0).astype(np.float32)), w)
        if cat == ModelCategory.MULTINOMIAL:
            yv = adapt_domain(frame.col(y), self.output["domain"])
            yv = np.pad(yv, (0, npad - frame.nrows), constant_values=-1)
            w = w * jnp.asarray((yv >= 0).astype(np.float32))
            p = jax.nn.softmax(eta, axis=1)
            return mm.multinomial_metrics(p, jnp.asarray(np.maximum(yv, 0)), w,
                                          domain=self.output["domain"])
        yv = frame.col(y).numeric_view()
        w = w * jnp.where(jnp.isnan(yv), 0.0, 1.0)
        yv = jnp.where(jnp.isnan(yv), 0.0, yv)
        mu = self.family.linkinv(eta)
        return mm.regression_metrics(mu, yv, w,
                                     deviance_fn=lambda a, b: self.family.deviance(a, b))

    @property
    def coefficients(self) -> Dict[str, float]:
        """RAW-scale coefficients (h2o-py model.coef() semantics): when
        the model trained on a standardized design, model-space coefs
        de-standardize exactly like the wire coefficients_table does.
        Multinomial/ordinal keep model space (same exclusions as the
        wire table — ordinal's trailing coef is a placeholder, the real
        thresholds live in output['ordinal_alphas'])."""
        names = self.output["coef_names"] + ["Intercept"]
        if self.coef_multinomial is not None:
            K = self.coef_multinomial.shape[1]
            return {f"{nm}_class{k}": float(self.coef_multinomial[i, k])
                    for i, nm in enumerate(names) for k in range(K)}
        coefs = np.asarray(self.coef, np.float64)
        if self.output.get("standardized") and \
                self.output.get("family") != "ordinal":
            coefs = destandardize_coefs(
                coefs,
                self.output.get("coef_means"),
                self.output.get("coef_sds"))
        return {nm: float(c) for nm, c in zip(names, coefs)}


def destandardize_coefs(coefs: np.ndarray, mus, sds) -> np.ndarray:
    """Standardized-design coefs → raw scale: raw_j = std_j/σ_j,
    intercept shifts by Σ std_j·μ_j/σ_j. ONE implementation shared by
    the python surface and the wire coefficients_table
    (hex/glm GLMModel coefficients semantics)."""
    p = len(coefs) - 1
    mus = np.asarray(mus if mus is not None else [0.0] * p, np.float64)
    sds = np.asarray(sds if sds is not None else [1.0] * p, np.float64)
    raw = np.asarray(coefs, np.float64).copy()
    raw[:-1] = coefs[:-1] / sds
    raw[-1] = coefs[-1] - float(np.sum(coefs[:-1] * mus / sds))
    return raw


class GLMEstimator(ModelBuilder):
    """h2o-py H2OGeneralizedLinearEstimator surface
    (h2o-py/h2o/estimators/glm.py)."""

    algo = "glm"
    cv_fold_masking = True   # ml/cv.py fast path: folds = masked weights

    DEFAULTS = dict(
        family="auto", link=None, solver="auto", alpha=0.5,
        lambda_=None, lambda_search=False, nlambdas=30,
        lambda_min_ratio=1e-4, standardize=True,
        use_all_factor_levels=False, max_iterations=50,
        beta_epsilon=1e-4, objective_epsilon=-1,
        tweedie_power=1.5, theta=1e-5, seed=-1, nfolds=0,
        fold_assignment="auto",
        weights_column=None, fold_column=None, offset_column=None,
        ignored_columns=None,
        missing_values_handling="mean_imputation",
        compute_p_values=False, intercept=True,
        beta_constraints=None, non_negative=False, interactions=None,
        keep_cross_validation_models=True,
        keep_cross_validation_predictions=False,
        keep_cross_validation_fold_assignment=False,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        # h2o-py spells it "Lambda", "lambda_", or bare "lambda" (the
        # grid wire sends the raw schema name)
        for alias in ("Lambda", "lambda"):
            if alias in params:
                params["lambda_"] = params.pop(alias)
        # h2o-py's name for the tweedie power (GLMModel.GLMParameters)
        if "tweedie_variance_power" in params:
            params["tweedie_power"] = params.pop("tweedie_variance_power")
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown GLM params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    # ---- solvers -----------------------------------------------------
    def _objective_eps(self) -> float:
        """GLM.java:1176 default: -1 → 1e-4 under lambda search or any
        nonzero lambda, 1e-6 for unpenalized fits."""
        oe = self.params.get("objective_epsilon")
        if oe is not None and float(oe) > 0:
            return float(oe)
        lam = self.params.get("lambda_")
        lam0 = (lam[0] if isinstance(lam, (list, tuple)) and lam
                else (lam or 0.0))
        if self.params.get("lambda_search") or float(lam0) != 0.0:
            return 1e-4
        return 1e-6

    def _fit_irlsm(self, X1, yv, w, fam: Family, l1: float, l2: float,
                   coef0, nobs: float, max_iter: int,
                   beta_eps: float, off=None) -> jax.Array:
        if off is None:
            off = jnp.zeros((X1.shape[0],), jnp.float32)
        coef = jnp.asarray(coef0, jnp.float32)
        coef = _irls_solve(X1, coef, yv, w, off, jnp.float32(l1),
                           jnp.float32(l2), jnp.float32(beta_eps),
                           jnp.int32(max_iter),
                           fam.name, fam.link, jnp.float32(fam.p),
                           jnp.float32(fam.theta),
                           jnp.float32(self._objective_eps()),
                           use_l1=l1 > 0)
        return coef   # device array: the lambda path warm-starts from it
        # without a host sync per lambda (30-step searches × CV folds
        # paid a blocking round trip each — pyunit_glm_seed timeout)

    def _fit_cod(self, X1, yv, w, fam: Family, l1: float, l2: float,
                 coef0: np.ndarray, max_iter: int, beta_eps: float,
                 bounds, off=None) -> np.ndarray:
        """IRLS outer loop with a COD (box-constrained) inner solve."""
        Pp1 = X1.shape[1]
        if bounds is None:
            lo = jnp.full((Pp1,), -jnp.inf, jnp.float32)
            hi = jnp.full((Pp1,), jnp.inf, jnp.float32)
        else:
            lo = jnp.asarray(bounds[0], jnp.float32)
            hi = jnp.asarray(bounds[1], jnp.float32)
        if off is None:
            off = jnp.zeros((X1.shape[0],), jnp.float32)
        coef = jnp.asarray(coef0, jnp.float32)
        for _ in range(max_iter):
            coef, delta = _irls_iter_cod(
                X1, coef, yv, w, off, jnp.float32(l1), jnp.float32(l2),
                lo, hi, fam.name, fam.link, jnp.float32(fam.p),
                jnp.float32(fam.theta))
            if float(delta) < beta_eps:
                break
        return np.asarray(coef)

    def _bounds_of(self, p, coef_names) -> Optional[tuple]:
        """lower/upper coefficient bounds from beta_constraints /
        non_negative (hex/glm/GLM.java BetaConstraints; the client ships
        a frame with names/lower_bounds/upper_bounds columns)."""
        Pp1 = len(coef_names) + 1
        lo = np.full(Pp1, -np.inf)
        hi = np.full(Pp1, np.inf)
        if p.get("non_negative"):
            lo[:-1] = 0.0
        bc = p.get("beta_constraints")
        if bc is not None:
            from h2o3_tpu.core.kv import DKV
            if isinstance(bc, str):
                bc = DKV.get(bc)
            rows: Dict[str, tuple] = {}
            if isinstance(bc, Frame):
                nm_col = bc.col("names")
                if nm_col.is_categorical and nm_col.domain:
                    codes = _fetch_np(nm_col.data)[: bc.nrows]
                    labels = [nm_col.domain[int(c)] if c >= 0 else None
                              for c in codes]
                else:
                    labels = [str(v) for v in nm_col.to_numpy()]
                lob = (bc.col("lower_bounds").to_numpy()
                       if "lower_bounds" in bc else [None] * bc.nrows)
                upb = (bc.col("upper_bounds").to_numpy()
                       if "upper_bounds" in bc else [None] * bc.nrows)
                for i, nm in enumerate(labels):
                    rows[str(nm)] = (lob[i], upb[i])
            elif isinstance(bc, dict):
                rows = {k: tuple(v) for k, v in bc.items()}
            for j, nm in enumerate(coef_names):
                if nm in rows:
                    l_, u_ = rows[nm]
                    if l_ is not None and not (isinstance(l_, float)
                                               and np.isnan(l_)):
                        lo[j] = float(l_)
                    if u_ is not None and not (isinstance(u_, float)
                                               and np.isnan(u_)):
                        hi[j] = float(u_)
        if not (np.isfinite(lo).any() or np.isfinite(hi).any()):
            return None
        return lo, hi

    def _fit_lbfgs(self, X1, yv, w, fam: Family, l2: float,
                   coef0: np.ndarray, nobs: float, max_iter: int,
                   off=None) -> np.ndarray:
        if off is None:
            off = jnp.zeros((X1.shape[0],), jnp.float32)
        l2d = jnp.float32(l2)
        pw = jnp.float32(fam.p)
        th = jnp.float32(fam.theta)

        def vgrad(c):
            return _glm_value_grad(jnp.asarray(c, jnp.float32), X1, yv, w,
                                   off, l2d, fam.name, fam.link, pw, th)

        coef, _, _ = lbfgs(vgrad, coef0, max_iter=max_iter)
        return np.asarray(coef)

    def _fit_multinomial(self, X1, y_int, w, K: int, l2: float,
                         nobs: float, max_iter: int,
                         solver: str = "l_bfgs", l1: float = 0.0):
        Pp1 = X1.shape[1]
        if solver in ("irlsm", "coordinate_descent",
                      "coordinate_descent_naive"):
            B0 = jnp.zeros((Pp1, K), jnp.float32)
            B = _multinomial_irls_solve(
                X1, B0, y_int, w, jnp.float32(l1), jnp.float32(l2),
                jnp.float32(1e-5), jnp.int32(max_iter), K=K,
                use_l1=l1 > 0)
            return np.asarray(B)
        l2d = jnp.float32(l2)

        def vgrad(c):
            return _multinomial_value_grad(jnp.asarray(c, jnp.float32), X1,
                                           y_int, w, l2d, K)

        sol, _, _ = lbfgs(vgrad, np.zeros(Pp1 * K), max_iter=max_iter)
        return sol.reshape(Pp1, K)

    # ---- training ----------------------------------------------------
    def _resolve_family(self, category: str) -> str:
        f = str(self.params["family"]).lower()
        if f != "auto":
            return f
        return {"Binomial": "binomial", "Multinomial": "multinomial",
                "Regression": "gaussian"}[category]

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        category = infer_category(frame, y)
        fam_name = self._resolve_family(category)
        fam = Family(fam_name, float(p["tweedie_power"]), p["link"],
                     theta=float(p.get("theta") or 1e-5)) \
            if fam_name not in ("multinomial", "ordinal") else None

        di_frame = frame
        if p.get("interactions"):
            inter = p["interactions"]
            if isinstance(inter, str):
                inter = [c.strip().strip('"') for c in
                         inter.strip("[]").split(",")]
                p["interactions"] = inter
            di_frame = expand_interactions(frame, inter)
            x = list(x) + [c for c in di_frame.names
                           if c not in frame.names]
        di = build_datainfo(di_frame, x, standardize=bool(p["standardize"]),
                            use_all_factor_levels=bool(p["use_all_factor_levels"]),
                            missing_values_handling=p["missing_values_handling"])
        ones = jnp.ones((di.X.shape[0], 1), jnp.float32)
        X1 = jax.device_put(jnp.concatenate([di.X, ones], axis=1),
                            row_sharding(mesh))

        w = frame.valid_weights()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
        # (CV fast path: standardization stats stay full-frame, like
        # the shared bin edges on the tree side)
        w = self._cv_masked_weights(w, frame)

        # offset_column: fixed per-row addition to eta (GLM.java offset)
        off = None
        if p.get("offset_column") and p["offset_column"] in frame:
            if fam_name == "multinomial":
                # class-uniform offsets cancel in softmax — warn and
                # ignore like the reference (hex/glm/GLM.java:978)
                log.warning("offset_column has no effect on multinomial "
                            "and will be ignored")
            else:
                ov = frame.col(p["offset_column"]).numeric_view()
                off = jnp.where(jnp.isnan(ov), 0.0,
                                ov).astype(jnp.float32)
        off_or0 = off if off is not None else \
            jnp.zeros((X1.shape[0],), jnp.float32)

        rc = frame.col(y)
        cmus, csds = coef_stats(di)
        output = {"category": category, "response": y, "names": list(x),
                  "coef_names": di.coef_names, "domain": rc.domain,
                  "coef_means": cmus.tolist(), "coef_sds": csds.tolist(),
                  "standardized": bool(p["standardize"]),
                  "nclasses": rc.cardinality if rc.is_categorical else 1}

        if fam_name == "ordinal":
            if not rc.is_categorical:
                raise ValueError("ordinal family requires a categorical "
                                 "response (ordered levels)")
            K = rc.cardinality
            yv = _fetch_np(rc.data)[: frame.nrows].astype(np.int32)
            resp_na = _fetch_np(rc.na_mask)[: frame.nrows]
            yv = np.pad(yv, (0, X1.shape[0] - frame.nrows))
            w = w * jnp.asarray(np.pad((~resp_na).astype(np.float32),
                                       (0, X1.shape[0] - frame.nrows)))
            y_dev = put_sharded(yv, row_sharding(mesh))
            l2 = _l2_of(p)
            P = X1.shape[1] - 1
            l2d = jnp.float32(l2)

            def vgrad(c):
                return _ordinal_value_grad(jnp.asarray(c, jnp.float32),
                                           X1, y_dev, w, l2d, K)

            x0 = np.zeros(P + K - 1)
            x0[P + 1:] = np.log(0.5)       # small increasing gaps
            sol, _, _ = lbfgs(vgrad, x0,
                              max_iter=int(p["max_iterations"]) * 4)
            beta = np.asarray(sol[:P])
            a0 = float(sol[P])
            alphas = np.concatenate(
                [[a0], a0 + np.cumsum(np.exp(np.asarray(sol[P + 1:])))])
            output["category"] = "Ordinal"
            output["family"] = "ordinal"
            output["ordinal_alphas"] = alphas.tolist()
            coef_full = np.concatenate([beta, [0.0]])
            model = GLMModel(p, output, coef_full, Family("binomial"),
                             stats_of(di), list(x))
            probs_np = model._score_raw(frame)
            probs = jnp.asarray(np.stack(
                [np.pad(probs_np[f"p{k}"],
                        (0, X1.shape[0] - frame.nrows))
                 for k in range(K)], axis=1))
            model.training_metrics = mm.multinomial_metrics(
                probs, y_dev, w, domain=rc.domain)
            model.training_metrics.kind = "Ordinal"
            job.update(1.0)
            _finish(model, frame, validation_frame)
            return model

        if category == ModelCategory.MULTINOMIAL:
            if p.get("compute_p_values"):
                raise ValueError("compute_p_values is not supported for "
                                 "multinomial GLM (reference restriction)")
            K = rc.cardinality
            yv = _fetch_np(rc.data)[: frame.nrows].astype(np.int32)
            resp_na = _fetch_np(rc.na_mask)[: frame.nrows]
            yv = np.pad(yv, (0, X1.shape[0] - frame.nrows))
            w = w * jnp.asarray(np.pad((~resp_na).astype(np.float32),
                                       (0, X1.shape[0] - frame.nrows)))
            y_dev = put_sharded(yv, row_sharding(mesh))
            nobs = float(jnp.sum(w))
            l2 = _l2_of(p)
            msolver = str(p["solver"]).lower()
            if msolver == "auto":
                # wide designs: K unrolled P×P grams + Cholesky per
                # sweep is O(K·P²) memory — follow the reference's
                # AUTO heuristic and fall back to L-BFGS (GLM.java
                # defaultSolver picks L_BFGS for large column counts)
                msolver = "irlsm" if X1.shape[1] <= 2000 else "l_bfgs"
            alpha_m = float(p["alpha"] if p["alpha"] is not None else 0.5)
            lam_m = p.get("lambda_") or 0.0
            if isinstance(lam_m, (list, tuple)):
                lam_m = lam_m[0] if lam_m else 0.0
            l1_m = float(alpha_m) * float(lam_m)
            B = self._fit_multinomial(X1, y_dev, w, K, l2, nobs,
                                      int(p["max_iterations"]),
                                      solver=msolver, l1=l1_m)
            model = GLMModel(p, output, B[:, 0], Family("binomial"),
                             stats_of(di), list(x), coef_multinomial=B)
            probs = jax.nn.softmax(X1 @ jnp.asarray(B, jnp.float32), axis=1)
            model.training_metrics = mm.multinomial_metrics(
                probs, y_dev, w, domain=rc.domain)
            job.update(1.0)
            _finish(model, frame, validation_frame)
            return model

        # single-coefficient-vector families
        if category == ModelCategory.BINOMIAL:
            yraw = adapt_domain(rc, rc.domain)
            yv = np.pad(np.maximum(yraw, 0).astype(np.float32),
                        (0, X1.shape[0] - frame.nrows))
            wna = np.pad((yraw >= 0).astype(np.float32),
                         (0, X1.shape[0] - frame.nrows))
            w = w * jnp.asarray(wna)
        else:
            yn = rc.to_numpy()
            wna = np.pad((~np.isnan(yn)).astype(np.float32),
                         (0, X1.shape[0] - frame.nrows))
            w = w * jnp.asarray(wna)
            yv = np.pad(np.nan_to_num(yn).astype(np.float32),
                        (0, X1.shape[0] - frame.nrows))
        y_dev = put_sharded(yv, row_sharding(mesh))
        nobs = float(jnp.sum(w))

        alpha = float(p["alpha"] if p["alpha"] is not None else 0.5)
        lambdas = _lambda_path(p, X1, y_dev, w, nobs, alpha, mesh)
        if p.get("compute_p_values") and any(l != 0.0 for l in lambdas):
            # fail before the (possibly long) lambda-path fit
            raise ValueError("compute_p_values requires no regularization "
                             "(lambda = 0)")
        solver = str(p["solver"]).lower()
        bounds = self._bounds_of(p, di.coef_names)
        if solver == "auto":
            solver = "coordinate_descent" if bounds is not None else "irlsm"
        elif bounds is not None:
            # constrained solves go through the projected COD path
            solver = "coordinate_descent"

        coef = np.zeros(X1.shape[1])
        best = None
        coef_path = None
        fuse_path = (len(lambdas) > 1 and bounds is None
                     and solver not in ("coordinate_descent",
                                        "coordinate_descent_naive",
                                        "l_bfgs", "lbfgs"))
        from h2o3_tpu import telemetry
        from h2o3_tpu.core import recovery as _recovery
        from h2o3_tpu.core.watchdog import maybe_fail
        from h2o3_tpu.telemetry import stepprof
        if fuse_path:
            # whole regularization path in ONE compiled scan of IRLS
            # while_loops (pyunit_glm_seed: 30 lambdas x CV folds paid a
            # dispatch each — the fused path pays one per FIT)
            l1s = jnp.asarray([lam * alpha for lam in lambdas], jnp.float32)
            l2s = jnp.asarray([lam * (1.0 - alpha) for lam in lambdas],
                              jnp.float32)
            _st0 = time.time()
            stepprof.chunk_begin()
            with telemetry.span("glm.solve", solver=solver,
                                lambdas=len(lambdas)):
                best, coef_path = _irls_solve_path(
                    X1, jnp.asarray(coef, jnp.float32), y_dev, w, off_or0,
                    l1s, l2s, jnp.float32(p["beta_epsilon"]),
                    jnp.int32(p["max_iterations"]), fam.name, fam.link,
                    jnp.float32(fam.p), jnp.float32(fam.theta),
                    jnp.float32(self._objective_eps()),
                    use_l1=alpha > 0)
                stepprof.compute_done((best, coef_path))
            telemetry.histogram("train_chunk_seconds",
                                algo="glm").observe(time.time() - _st0)
            stepprof.chunk_end(lambdas=len(lambdas))
            telemetry.counter("train_iterations_total", algo="glm").inc(
                len(lambdas) * int(p["max_iterations"]))
            job.update(1.0, f"lambda path ({len(lambdas)})")
        else:
            # in-fit checkpointer (core/recovery.py): the IRLS outer
            # walk's host boundary is the lambda step — snapshot the
            # warm-start coefficients + path position so a killed
            # multi-lambda fit resumes at the next lambda, bit-identical
            # (the fused path is ONE dispatch and has no mid-state)
            fc = None
            li0 = 0
            if len(lambdas) > 1 and \
                    getattr(self, "_cv_fold_mask", None) is None:
                fc = _recovery.fit_checkpointer(
                    "glm", p, y, x, frame.nrows, default_every=1)
                if fc is not None:
                    _loaded = fc.load()
                    if _loaded is not None:
                        _st = _loaded[1]
                        li0 = int(_st["li"])
                        coef = np.asarray(_st["coef"])
                        best = coef
            for li, lam in enumerate(lambdas):
                if li < li0:
                    continue            # resumed past this lambda
                l1 = lam * alpha
                l2 = lam * (1.0 - alpha)
                _st0 = time.time()
                stepprof.chunk_begin()
                with telemetry.span("glm.solve", solver=solver,
                                    lam=float(lam)):
                    if solver in ("coordinate_descent",
                                  "coordinate_descent_naive"):
                        coef = self._fit_cod(X1, y_dev, w, fam, l1, l2,
                                             coef,
                                             int(p["max_iterations"]),
                                             float(p["beta_epsilon"]),
                                             bounds, off=off_or0)
                    elif solver in ("l_bfgs", "lbfgs") and l1 == 0:
                        coef = self._fit_lbfgs(X1, y_dev, w, fam, l2,
                                               coef, nobs,
                                               int(p["max_iterations"]),
                                               off=off_or0)
                    else:
                        coef = self._fit_irlsm(X1, y_dev, w, fam, l1, l2,
                                               coef, nobs,
                                               int(p["max_iterations"]),
                                               float(p["beta_epsilon"]),
                                               off=off_or0)
                    stepprof.compute_done(coef)
                telemetry.histogram("train_chunk_seconds",
                                    algo="glm").observe(time.time() - _st0)
                telemetry.counter("train_iterations_total",
                                  algo="glm").inc(int(p["max_iterations"]))
                stepprof.chunk_end(lam=float(lam))
                job.update(1.0 / len(lambdas),
                           f"lambda {li + 1}/{len(lambdas)}")
                best = coef
                if fc is not None:
                    _li, _c = li + 1, coef
                    fc.maybe_save(li + 1, lambda: {
                        "li": _li, "coef": _recovery.snapshot_host(_c)})
                maybe_fail("fit_chunk")
                maybe_fail("device_oom")
            if fc is not None:
                fc.clear()
        coef = np.asarray(best)   # ONE host materialization after the path

        output["lambda_best"] = float(lambdas[-1])
        # a CV sweep selects lambda by summed holdout deviance over this
        # path (GLM.java xval-deviance lambda selection) — stash it once
        # as host arrays (ml/cv.py train_with_cv picks them up)
        sel_lambda = p.get("_cv_selected_lambda")
        if sel_lambda is not None and coef_path is not None:
            li = int(np.argmin(np.abs(np.asarray(lambdas) - sel_lambda)))
            coef = np.asarray(coef_path[li])
            output["lambda_best"] = float(lambdas[li])

        if p.get("compute_p_values"):
            # std errors / z / p from the Fisher information at the MLE
            # (GLM.java compute_p_values; lambda==0 validated up front)
            output["coefficients_table"] = _p_values_table(
                X1, y_dev, w, jnp.asarray(coef, jnp.float32), fam,
                di.coef_names + ["Intercept"], nobs, off=off_or0)

        model = GLMModel(p, output, coef, fam, stats_of(di), list(x))
        if coef_path is not None:
            model._coef_path = np.asarray(coef_path)      # [L, P+1]
            model._lambda_path_vals = list(lambdas)
        mu = fam.linkinv(X1 @ jnp.asarray(coef, jnp.float32) + off_or0)
        if category == ModelCategory.BINOMIAL:
            model.training_metrics = mm.binomial_metrics(mu, y_dev, w)
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        else:
            model.training_metrics = mm.regression_metrics(
                mu, y_dev, w, deviance_fn=lambda a, b: fam.deviance(a, b))
        _finish(model, frame, validation_frame)
        return model


def _l2_of(p) -> float:
    lam = p["lambda_"]
    if lam is None:
        return 0.0
    lam = lam[0] if isinstance(lam, (list, tuple)) else lam
    return float(lam) * (1.0 - float(p["alpha"] or 0.0))


def _lambda_path(p, X1, y, w, nobs, alpha, mesh) -> List[float]:
    """Regularization path (GLM.java lambda search semantics)."""
    if p.get("_lambda_path_override"):
        # CV fold fits share the MAIN model's full-frame path so their
        # per-lambda holdout deviances align index-wise (the reference
        # likewise evaluates every fold on one shared path)
        return list(p["_lambda_path_override"])
    lam = p["lambda_"]
    if not p["lambda_search"]:
        if lam is None:
            return [0.0]
        return list(lam) if isinstance(lam, (list, tuple)) else [float(lam)]
    # lambda_max: smallest lambda with all (penalized) coefs zero
    ybar = float(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12))
    xty = jnp.abs((X1 * w[:, None]).T @ (y - ybar))[:-1]  # exclude intercept
    lam_max = float(jnp.max(xty)) / (nobs * max(alpha, 1e-3))
    lmr = float(p["lambda_min_ratio"])
    if lmr <= 0:            # wire default -1 = auto (GLMParameters)
        lmr = 1e-4
    lam_min = lam_max * lmr
    n = int(p["nlambdas"])
    if n <= 0:              # wire default -1 = auto → 100-step path
        n = 100
    return list(np.exp(np.linspace(np.log(lam_max), np.log(lam_min), n)))


def _p_values_table(X1, y, w, coef, fam: Family, names, nobs: float,
                    off=None):
    """Wald inference rows (name, coefficient, std_error, z_value,
    p_value) — hex/glm GLMModel coefficients table with p-values.

    Fisher information = X'WX with the IRLS variance weights at the
    fitted coefficients; gaussian uses the t distribution with the
    moment-estimated dispersion, other families the normal (z) with
    dispersion 1 (binomial/poisson) or the Pearson estimate (gamma/
    tweedie), matching the reference's computePValues path."""
    eta = X1 @ coef if off is None else X1 @ coef + off
    mu = fam.linkinv(eta)
    name = fam.name
    # general GLM Fisher weight: (dmu/deta)^2 / Var(mu) — exact for every
    # family × link combination Family supports
    dmu = fam.dmu_deta(eta, mu)
    vw = dmu * dmu / jnp.maximum(fam.variance(mu), 1e-12)
    wi = w * vw
    info = (X1 * wi[:, None]).T @ X1
    info_h = np.asarray(info, dtype=np.float64)
    P = info_h.shape[0]
    try:
        cov = np.linalg.inv(info_h + 1e-10 * np.eye(P))
    except np.linalg.LinAlgError:
        cov = np.linalg.pinv(info_h)
    dof = max(nobs - P, 1.0)
    if name == "gaussian":
        resid = np.asarray(y - mu, dtype=np.float64)
        wh = np.asarray(w, dtype=np.float64)
        dispersion = float((wh * resid ** 2).sum() / dof)
    elif name in ("binomial", "poisson"):
        dispersion = 1.0
    else:   # gamma/tweedie: Pearson estimate over Var(mu)
        resid = np.asarray(y - mu, dtype=np.float64)
        var = np.maximum(np.asarray(fam.variance(mu), dtype=np.float64),
                         1e-12)
        wh = np.asarray(w, dtype=np.float64)
        dispersion = float((wh * resid ** 2 / var).sum() / dof)
    se = np.sqrt(np.maximum(np.diag(cov) * dispersion, 0.0))
    ch = np.asarray(coef, dtype=np.float64)
    z = np.where(se > 0, ch / np.maximum(se, 1e-300), np.inf)
    from scipy import stats as _st
    if name == "gaussian":
        pv = 2.0 * _st.t.sf(np.abs(z), df=dof)
    else:
        pv = 2.0 * _st.norm.sf(np.abs(z))
    return [{"name": nm, "coefficient": float(c), "std_error": float(s),
             "z_value": float(zz), "p_value": float(pp)}
            for nm, c, s, zz, pp in zip(names, ch, se, z, pv)]


def _finish(model: GLMModel, frame: Frame, validation_frame):
    if validation_frame is not None:
        model.validation_metrics = model.model_performance(validation_frame)


# ---- model-batched training (parallel/model_batch.py trainer) ----------


def fit_glm_batched(builder_cls, params_list: List[dict], frame: Frame,
                    y: Optional[str] = None,
                    x: Optional[Sequence[str]] = None,
                    validation_frame: Optional[Frame] = None) -> List[Model]:
    """Train a grid bucket's (alpha, lambda) product as ONE vmapped IRLS
    program (_irls_solve_batched): the design matrix, weights and
    response adapt once, per-combo l1/l2/objective-epsilon stack onto
    the vmapped axis, and the sequential walk's per-combo dispatch+
    readback round trips collapse into one per use_l1 partition (ADMM
    vs Cholesky inner solves are distinct compiled programs, exactly
    like the sequential path's use_l1 static flag).

    Raises parallel.model_batch.BatchIneligible for anything the
    vmapped solve cannot express — CV, lambda_search, constrained/
    L-BFGS solvers, multinomial/ordinal, p-values, interactions — and
    the caller falls back per-combo."""
    from h2o3_tpu.parallel.model_batch import BATCHABLE_KNOBS, BatchIneligible

    builders = [builder_cls(**p) for p in params_list]
    M = len(builders)
    b0 = builders[0]
    p0 = b0.params
    batchable = BATCHABLE_KNOBS["glm"] | {"lambda_"}
    for b in builders[1:]:
        for k, v in b.params.items():
            if k not in batchable and v != p0.get(k):
                raise BatchIneligible(f"structural param '{k}' varies")
    lams, alphas = [], []
    for b in builders:
        p = b.params
        if int(p.get("nfolds") or 0) >= 2 or p.get("fold_column"):
            raise BatchIneligible("cross-validation")
        if p.get("lambda_search"):
            raise BatchIneligible("lambda_search (warm-started path)")
        if p.get("compute_p_values"):
            raise BatchIneligible("compute_p_values")
        if p.get("beta_constraints") is not None or p.get("non_negative"):
            raise BatchIneligible("constrained solve (projected COD)")
        if p.get("interactions"):
            raise BatchIneligible("interaction expansion")
        if str(p.get("solver") or "auto").lower() not in ("auto", "irlsm"):
            raise BatchIneligible(f"solver {p.get('solver')}")
        if float(p.get("max_runtime_secs") or 0.0) > 0:
            raise BatchIneligible("per-model runtime cap")
        lam = p.get("lambda_")
        if isinstance(lam, (list, tuple)):
            if len(lam) > 1:
                raise BatchIneligible("multi-lambda combo")
            lam = lam[0] if lam else 0.0
        lams.append(float(lam or 0.0))
        alphas.append(float(p["alpha"] if p["alpha"] is not None else 0.5))

    mesh = get_mesh()
    x = b0.resolve_x(frame, x, y)
    category = infer_category(frame, y)
    if category == ModelCategory.MULTINOMIAL:
        raise BatchIneligible("multinomial")
    fam_name = b0._resolve_family(category)
    if fam_name in ("multinomial", "ordinal"):
        raise BatchIneligible(f"family {fam_name}")
    fam = Family(fam_name, float(p0["tweedie_power"]), p0["link"],
                 theta=float(p0.get("theta") or 1e-5))

    # ---- shared preamble (identical to the sequential _fit) ----------
    di = build_datainfo(frame, x, standardize=bool(p0["standardize"]),
                        use_all_factor_levels=bool(
                            p0["use_all_factor_levels"]),
                        missing_values_handling=p0["missing_values_handling"])
    ones = jnp.ones((di.X.shape[0], 1), jnp.float32)
    X1 = jax.device_put(jnp.concatenate([di.X, ones], axis=1),
                        row_sharding(mesh))
    w = frame.valid_weights()
    if p0.get("weights_column"):
        wc = frame.col(p0["weights_column"]).numeric_view()
        w = w * jnp.where(jnp.isnan(wc), 0.0, wc)
    off = None
    if p0.get("offset_column") and p0["offset_column"] in frame:
        ov = frame.col(p0["offset_column"]).numeric_view()
        off = jnp.where(jnp.isnan(ov), 0.0, ov).astype(jnp.float32)
    off_or0 = off if off is not None else \
        jnp.zeros((X1.shape[0],), jnp.float32)
    rc = frame.col(y)
    cmus, csds = coef_stats(di)
    output_base = {"category": category, "response": y, "names": list(x),
                   "coef_names": di.coef_names, "domain": rc.domain,
                   "coef_means": cmus.tolist(), "coef_sds": csds.tolist(),
                   "standardized": bool(p0["standardize"]),
                   "nclasses": rc.cardinality if rc.is_categorical else 1}
    if category == ModelCategory.BINOMIAL:
        yraw = adapt_domain(rc, rc.domain)
        yv = np.pad(np.maximum(yraw, 0).astype(np.float32),
                    (0, X1.shape[0] - frame.nrows))
        wna = np.pad((yraw >= 0).astype(np.float32),
                     (0, X1.shape[0] - frame.nrows))
        w = w * jnp.asarray(wna)
    else:
        yn = rc.to_numpy()
        wna = np.pad((~np.isnan(yn)).astype(np.float32),
                     (0, X1.shape[0] - frame.nrows))
        w = w * jnp.asarray(wna)
        yv = np.pad(np.nan_to_num(yn).astype(np.float32),
                    (0, X1.shape[0] - frame.nrows))
    y_dev = put_sharded(yv, row_sharding(mesh))

    # ---- one vmapped solve per use_l1 partition ----------------------
    l1_all = np.array([lams[m] * alphas[m] for m in range(M)], np.float32)
    l2_all = np.array([lams[m] * (1.0 - alphas[m]) for m in range(M)],
                      np.float32)
    oe_all = np.array([b._objective_eps() for b in builders], np.float32)
    coef0 = jnp.zeros((X1.shape[1],), jnp.float32)
    coefs = np.zeros((M, X1.shape[1]), np.float32)
    from h2o3_tpu import telemetry
    from h2o3_tpu.telemetry import stepprof
    for use_l1 in (False, True):
        # sequential parity: _fit_irlsm picks ADMM iff l1 > 0
        idx = np.where((l1_all > 0) == use_l1)[0]
        if idx.size == 0:
            continue
        _st0 = time.time()
        stepprof.chunk_begin()
        with telemetry.span("glm.solve_batched", solver="irlsm",
                            width=int(idx.size)):
            out = _irls_solve_batched(
                X1, coef0, y_dev, w, off_or0,
                jnp.asarray(l1_all[idx]), jnp.asarray(l2_all[idx]),
                jnp.float32(p0["beta_epsilon"]),
                jnp.int32(p0["max_iterations"]), fam.name, fam.link,
                jnp.float32(fam.p), jnp.float32(fam.theta),
                jnp.asarray(oe_all[idx]), use_l1=use_l1)
            stepprof.compute_done(out)
        telemetry.histogram("train_chunk_seconds",
                            algo="glm").observe(time.time() - _st0)
        telemetry.counter("train_iterations_total", algo="glm").inc(
            int(idx.size) * int(p0["max_iterations"]))
        stepprof.chunk_end(width=int(idx.size))
        coefs[idx] = np.asarray(out)

    # ---- per-model unstack into ordinary Model objects ---------------
    models: List[Model] = []
    t_done = time.time()
    for m in range(M):
        output = dict(output_base)
        output["lambda_best"] = lams[m]
        model = GLMModel(builders[m].params, output, coefs[m], fam,
                         stats_of(di), list(x))
        mu = fam.linkinv(X1 @ jnp.asarray(coefs[m], jnp.float32) + off_or0)
        if category == ModelCategory.BINOMIAL:
            model.training_metrics = mm.binomial_metrics(mu, y_dev, w)
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        else:
            model.training_metrics = mm.regression_metrics(
                mu, y_dev, w,
                deviance_fn=lambda a, b: fam.deviance(a, b))
        _finish(model, frame, validation_frame)
        model.output["run_time"] = time.time() - t_done
        models.append(model)
    return models
