"""Model metrics — the hex.ModelMetrics* family.

Reference: one ModelMetrics class per problem type filled by incremental
MetricBuilders inside scoring MRTasks (h2o-core/src/main/java/hex/
ModelMetrics*.java); exact AUC from a 400-bin score histogram
(hex/AUC2.java:24, NBINS=400). Here the same shape: one device pass
builds weighted histograms/sums (psum over the mesh), host finishes the
scalar math.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.ops.segments import segment_sum
from h2o3_tpu.parallel.mesh import get_mesh

AUC_NBINS = 400  # hex/AUC2.java:24

# metric sums always run true-f32 matmuls: a single one-hot matmul per
# pass, so the 6-pass TPU emulation is cheap here — and served metrics
# must hit the reference pyunits' 1e-5 equality bars (bf16x3 residue
# was the round-2 pyunit_weights_gbm "10x bug" that was really 2e-5)
_PREC = jax.lax.Precision.HIGHEST

# Every metric runs ONE jitted device pass (the MetricBuilder-inside-
# MRTask single sweep) and finishes scalars on host — un-jitted
# shard_maps would re-lower per call, which dominates wall time on a
# remote-attached chip.


@partial(jax.jit, static_argnames=("mesh",))
def _binomial_pass(p, y, w, *, mesh):
    pc = jnp.clip(p, 1e-7, 1 - 1e-7)
    sums = segment_sum(
        jnp.zeros_like(y, jnp.int32),
        jnp.stack([w,
                   w * (p - y) ** 2,
                   -w * (y * jnp.log(pc) + (1 - y) * jnp.log(1 - pc)),
                   w * y], axis=1),
        n_nodes=1, mesh=mesh, precision=_PREC)
    bins = jnp.clip((pc * AUC_NBINS).astype(jnp.int32), 0, AUC_NBINS - 1)
    hist = segment_sum(bins, jnp.stack([w * y, w * (1.0 - y)], axis=1),
                       n_nodes=AUC_NBINS, mesh=mesh, precision=_PREC)
    return sums[0], hist


def _auc_from_hist(pos: np.ndarray, neg: np.ndarray) -> Dict[str, float]:
    """AUC + AUCPR + max-F1 threshold from the bin histograms
    (hex/AUC2.java compute path)."""
    # sweep thresholds from high to low: cumulative TP/FP
    tp = np.cumsum(pos[::-1])[::-1]
    fp = np.cumsum(neg[::-1])[::-1]
    P, N = pos.sum(), neg.sum()
    if P == 0 or N == 0:
        return {"auc": 0.5, "pr_auc": 0.0, "max_f1": 0.0,
                "max_f1_threshold": 0.5, "gini": 0.0}
    tpr = np.concatenate([tp / P, [0.0]])
    fpr = np.concatenate([fp / N, [0.0]])
    auc = float(np.trapezoid(tpr[::-1], fpr[::-1]))
    prec = tp / np.maximum(tp + fp, 1e-12)
    rec = tp / P
    order = np.argsort(rec)
    pr_auc = float(np.trapezoid(np.concatenate([[prec[order][0]], prec[order]]),
                                np.concatenate([[0.0], rec[order]])))
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
    k = int(np.argmax(f1))
    return {"auc": auc, "pr_auc": pr_auc, "max_f1": float(f1[k]),
            "max_f1_threshold": float(k / AUC_NBINS), "gini": 2 * auc - 1}


class ModelMetrics:
    """Base: shared scalar fields (hex/ModelMetrics.java)."""

    def __init__(self, kind: str, nobs: int, mse: float, **extra):
        self.kind = kind
        self.nobs = nobs
        self.mse = mse
        self.rmse = float(np.sqrt(mse))
        self.extra = extra

    def to_dict(self) -> dict:
        d = {"model_category": self.kind, "nobs": self.nobs,
             "MSE": self.mse, "RMSE": self.rmse}
        d.update(self.extra)
        return d

    def __getitem__(self, k):
        return self.to_dict()[k]

    def __repr__(self):
        items = ", ".join(f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
                          for k, v in self.to_dict().items() if not isinstance(v, (list, dict)))
        return f"<ModelMetrics {items}>"


def binomial_metrics(p, y, w=None, mesh=None) -> ModelMetrics:
    """hex/ModelMetricsBinomial.java: AUC/logloss/Brier from one pass.

    p: P(class 1) [N]; y: 0/1 labels; w: weights (0 on padding rows).
    """
    mesh = mesh or get_mesh()
    p = jnp.asarray(p, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    w = jnp.ones_like(p) if w is None else jnp.asarray(w, jnp.float32)
    sums, hist = _binomial_pass(p, y, w, mesh=mesh)
    tot, sse, ll, pos = (float(x) for x in np.asarray(sums))
    hist = np.asarray(hist)
    pos_h, neg_h = hist[:, 0], hist[:, 1]
    roc = _auc_from_hist(pos_h, neg_h)
    t = roc["max_f1_threshold"]
    # confusion at max-F1 threshold (reference default criterion)
    idx = int(t * AUC_NBINS)
    tp = pos_h[idx:].sum(); fp = neg_h[idx:].sum()
    fn = pos_h[:idx].sum(); tn = neg_h[:idx].sum()
    err0 = fp / max(fp + tn, 1e-12)
    err1 = fn / max(fn + tp, 1e-12)
    mm = ModelMetrics(
        "Binomial", int(tot), sse / max(tot, 1e-12),
        logloss=ll / max(tot, 1e-12),
        AUC=roc["auc"], pr_auc=roc["pr_auc"], Gini=roc["gini"],
        max_f1=roc["max_f1"], max_f1_threshold=t,
        mean_per_class_error=float((err0 + err1) / 2),
        confusion_matrix=[[float(tn), float(fp)], [float(fn), float(tp)]],
        positive_fraction=pos / max(tot, 1e-12))
    # keep the 400-bin score histogram for the REST thresholds table
    # (hex/AUC2 serves per-threshold rows to the client)
    mm.hist = (pos_h, neg_h)
    return mm


@partial(jax.jit, static_argnames=("mesh",))
def _multinomial_pass(probs, y, w, *, mesh):
    K = probs.shape[1]
    py = jnp.clip(jnp.take_along_axis(probs, y[:, None], axis=1)[:, 0],
                  1e-7, 1.0)
    pred = jnp.argmax(probs, axis=1).astype(jnp.int32)
    onehot_err = (pred != y).astype(jnp.float32)
    sse = jnp.sum((probs - (jnp.arange(K)[None, :] == y[:, None])) ** 2,
                  axis=1)
    sums = segment_sum(
        jnp.zeros_like(y), jnp.stack([w, -w * jnp.log(py), w * onehot_err,
                                      w * sse], axis=1),
        n_nodes=1, mesh=mesh, precision=_PREC)
    cm = segment_sum((y * K + pred).astype(jnp.int32), w[:, None],
                     n_nodes=K * K, mesh=mesh, precision=_PREC)
    return sums[0], cm


@partial(jax.jit, static_argnames=("mesh",))
def _multinomial_score_hists(probs, y, w, *, mesh):
    """[K, K, AUC_NBINS] — weight of rows with TRUE class j landing in
    score bin b of class-k probability. One structure serves both
    one-vs-rest (pos = H[k,k], neg = Σ_{j≠k} H[k,j]) and one-vs-one
    (pos = H[i,i], neg = H[i,j]) AUCs — hex/MultinomialAUC.java."""
    K = probs.shape[1]
    out = []
    for k in range(K):
        b = jnp.clip((probs[:, k] * AUC_NBINS).astype(jnp.int32),
                     0, AUC_NBINS - 1)
        hk = segment_sum((y * AUC_NBINS + b).astype(jnp.int32), w[:, None],
                         n_nodes=K * AUC_NBINS, mesh=mesh, precision=_PREC)
        out.append(hk.reshape(K, AUC_NBINS))
    return jnp.stack(out)                    # [K(prob), K(true), B]


def multinomial_metrics(probs, y, w=None, mesh=None,
                        domain: Optional[List[str]] = None) -> ModelMetrics:
    """hex/ModelMetricsMultinomial.java: logloss, per-class error, CM."""
    mesh = mesh or get_mesh()
    K = probs.shape[1]
    y = jnp.asarray(y, jnp.int32)
    w = jnp.ones(probs.shape[0], jnp.float32) if w is None else jnp.asarray(w, jnp.float32)
    sums, cm = _multinomial_pass(probs, y, w, mesh=mesh)
    tot, ll, err, sse_t = (float(x) for x in np.asarray(sums))
    cm = np.asarray(cm).reshape(K, K)
    row = cm.sum(axis=1)
    per_class_err = np.where(row > 0, 1.0 - np.diag(cm) / np.maximum(row, 1e-12), 0.0)
    extra = {}
    if 2 <= K <= 30:
        # one-vs-rest + one-vs-one AUC/PR-AUC tables (PUBDEV-7269,
        # hex/MultinomialAUC.java; capped K bounds the K² histogram set)
        H = np.asarray(_multinomial_score_hists(probs, y, w, mesh=mesh),
                       np.float64)
        dom = domain or [f"class_{i}" for i in range(K)]
        frac = row / max(row.sum(), 1e-12)
        auc_rows, pr_rows = [], []
        ovr_auc, ovr_pr = np.zeros(K), np.zeros(K)
        for k in range(K):
            pos = H[k, k]
            neg = H[k].sum(axis=0) - pos
            r = _auc_from_hist(pos, neg)
            ovr_auc[k], ovr_pr[k] = r["auc"], r["pr_auc"]
            auc_rows.append([f"{dom[k]} vs Rest", dom[k], "",
                             float(r["auc"])])
            pr_rows.append([f"{dom[k]} vs Rest", dom[k], "",
                            float(r["pr_auc"])])
        auc_rows.append(["Macro OVR", "", "", float(ovr_auc.mean())])
        auc_rows.append(["Weighted OVR", "", "",
                         float((ovr_auc * frac).sum())])
        pr_rows.append(["Macro OVR", "", "", float(ovr_pr.mean())])
        pr_rows.append(["Weighted OVR", "", "",
                        float((ovr_pr * frac).sum())])
        ovo_auc, ovo_pr, ovo_w = [], [], []
        for i in range(K):
            for j in range(i + 1, K):
                # symmetric pairwise AUC: average of i-scored and
                # j-scored directions (PairwiseAUC semantics)
                ri = _auc_from_hist(H[i, i], H[i, j])
                rj = _auc_from_hist(H[j, j], H[j, i])
                a = 0.5 * (ri["auc"] + rj["auc"])
                pr = 0.5 * (ri["pr_auc"] + rj["pr_auc"])
                ovo_auc.append(a)
                ovo_pr.append(pr)
                ovo_w.append(frac[i] + frac[j])
                auc_rows.append([f"{dom[i]} vs {dom[j]}", dom[i], dom[j],
                                 float(a)])
                pr_rows.append([f"{dom[i]} vs {dom[j]}", dom[i], dom[j],
                                float(pr)])
        ow = np.asarray(ovo_w) / max(sum(ovo_w), 1e-12)
        auc_rows.append(["Macro OVO", "", "", float(np.mean(ovo_auc))])
        auc_rows.append(["Weighted OVO", "", "",
                         float((np.asarray(ovo_auc) * ow).sum())])
        pr_rows.append(["Macro OVO", "", "", float(np.mean(ovo_pr))])
        pr_rows.append(["Weighted OVO", "", "",
                        float((np.asarray(ovo_pr) * ow).sum())])
        extra = {"multinomial_auc_rows": auc_rows,
                 "multinomial_aucpr_rows": pr_rows,
                 # scalar AUC/PR = weighted OVR (the reference's
                 # default MultinomialAucType when computed)
                 "AUC": float((ovr_auc * frac).sum()),
                 "pr_auc": float((ovr_pr * frac).sum())}
    return ModelMetrics(
        "Multinomial", int(tot), sse_t / max(tot, 1e-12),
        logloss=ll / max(tot, 1e-12),
        mean_per_class_error=float(per_class_err[row > 0].mean()) if (row > 0).any() else 0.0,
        error_rate=err / max(tot, 1e-12),
        confusion_matrix=cm.tolist(),
        domain=domain, **extra)


@partial(jax.jit, static_argnames=("mesh",))
def _regression_pass(pred, y, w, dev, *, mesh):
    ok_log = (y > -1) & (pred > -1)
    rmsle_term = jnp.where(ok_log,
                           (jnp.log1p(jnp.maximum(pred, -1 + 1e-12))
                            - jnp.log1p(jnp.maximum(y, -1 + 1e-12))) ** 2, 0.0)
    sums = segment_sum(
        jnp.zeros(y.shape[0], jnp.int32),
        jnp.stack([w, w * (y - pred) ** 2, w * jnp.abs(y - pred),
                   w * rmsle_term, w * y, w * y * y, w * dev], axis=1),
        n_nodes=1, mesh=mesh, precision=_PREC)
    return sums[0]


def regression_metrics(pred, y, w=None, mesh=None,
                       deviance_fn=None) -> ModelMetrics:
    """hex/ModelMetricsRegression.java: MSE/MAE/RMSLE/deviance/R2."""
    mesh = mesh or get_mesh()
    pred = jnp.asarray(pred, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    w = jnp.ones_like(y) if w is None else jnp.asarray(w, jnp.float32)
    # deviance_fn is a fresh lambda per call — evaluate it outside the
    # jitted pass so the pass's trace cache never misses
    dev = deviance_fn(y, pred) if deviance_fn is not None else (y - pred) ** 2
    sums = _regression_pass(pred, y, w, jnp.asarray(dev, jnp.float32),
                            mesh=mesh)
    tot, sse, sae, sle, sy, syy, sdev = (float(x) for x in np.asarray(sums))
    mse = sse / max(tot, 1e-12)
    var_y = syy / max(tot, 1e-12) - (sy / max(tot, 1e-12)) ** 2
    return ModelMetrics(
        "Regression", int(tot), mse,
        mae=sae / max(tot, 1e-12),
        rmsle=float(np.sqrt(sle / max(tot, 1e-12))),
        mean_residual_deviance=sdev / max(tot, 1e-12),
        r2=1.0 - mse / max(var_y, 1e-12))
