"""RuleFit — rules from tree ensembles + sparse linear model.

Reference: hex/rulefit/RuleFit.java:36 (~1.6K LoC) — trains tree models
at depths min_rule_length..max_rule_length, decomposes every path
root→leaf into a rule (conjunction of splits), builds a 0/1 rule matrix
plus winsorized linear terms, and fits an L1 GLM over it; output is the
rule importance table (RuleFitModel "rule_importance").

TPU redesign: rules are NOT evaluated per-condition — each tree is
routed once on device (the same static-depth routing loop as scoring,
models/tree.py), giving final leaf ids [N]; a rule's membership is
``lo <= nid < hi`` for the leaf-range its (possibly shallow) node covers
in the complete tree. The rule matrix assembles from T routed columns,
and the sparse GLM reuses the einsum-Gram IRLS/ADMM machinery.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.binning import rebin_for_scoring
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory, infer_category
from h2o3_tpu.models.tree import row_feature_values
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.rulefit")


def _route_nids(tree, bins, B: int):
    """Final leaf id per row for one tree (predict_tree sans leaf gather)."""
    from h2o3_tpu.models.tree import _level_goleft
    N = bins.shape[0]
    D = tree.feat.shape[0]
    nid = jnp.zeros((N,), jnp.int32)
    for d in range(D):
        nid = _level_goleft(tree.feat[d], tree.thresh[d], tree.na_left[d],
                            tree.is_split[d], tree.cat_split[d],
                            tree.left_words[d], nid, bins, B)
    return nid


def _extract_rules(forest, tree_idx: int, D: int) -> List[dict]:
    """Walk one complete tree (host) → rules with leaf-id ranges.

    Conds are (feat, thresh, na_left, side, binset): binset is None for
    numeric range splits, else the frozenset of bin ids going left
    (categorical subset split)."""
    feat = np.asarray(forest.feat[tree_idx])
    thresh = np.asarray(forest.thresh[tree_idx])
    na_left = np.asarray(forest.na_left[tree_idx])
    is_split = np.asarray(forest.is_split[tree_idx])
    cat_split = np.asarray(forest.cat_split[tree_idx])
    left_words = np.asarray(forest.left_words[tree_idx])
    rules: List[dict] = []

    def _binset(d, idx):
        if not bool(cat_split[d, idx]):
            return None
        words = left_words[d, idx]
        return frozenset(
            int(32 * k + b) for k in range(words.shape[0])
            for b in range(32) if (int(words[k]) >> b) & 1)

    def walk(d, idx, conds):
        if d == D or not is_split[d, idx]:
            if conds:
                span = 2 ** (D - d)
                rules.append({"tree": tree_idx, "conds": list(conds),
                              "lo": idx * span, "hi": (idx + 1) * span})
            return
        f, t, nal = int(feat[d, idx]), int(thresh[d, idx]), bool(na_left[d, idx])
        bs = _binset(d, idx)
        walk(d + 1, 2 * idx, conds + [(f, t, nal, "left", bs)])
        walk(d + 1, 2 * idx + 1, conds + [(f, t, nal, "right", bs)])

    walk(0, 0, [])
    return rules


def _rule_language(rule: dict, bm) -> str:
    """Human-readable rule string (reference Rule.languageRule)."""
    edges = np.asarray(bm.edges)
    parts = []
    for f, t, nal, side, binset in rule["conds"]:
        name = bm.names[f]
        if bm.is_cat[f]:
            dom = bm.domains[f] or []
            card = max(len(dom), 1)
            nbf = int(np.asarray(bm.nbins)[f])
            div = -(-card // nbf) if card > nbf else 1
            if binset is not None:
                levels = [dom[i] for i in range(len(dom))
                          if (i // div) in binset]
            else:
                levels = [dom[i] for i in range(len(dom))
                          if (i // div) <= t]
            s = (f"{name} in {{{', '.join(levels)}}}" if side == "left"
                 else f"{name} not in {{{', '.join(levels)}}}")
        else:
            v = float(edges[f, t]) if t < edges.shape[1] else float("inf")
            s = f"{name} < {v:.6g}" if side == "left" else f"{name} >= {v:.6g}"
        if (side == "left") == nal:
            s += " or NA"
        parts.append(s)
    return " & ".join(parts)


class RuleFitModel(Model):
    algo = "rulefit"

    def __init__(self, params, output, glm_model, tree_models: List,
                 rules: List[dict], linear_cols: List[str],
                 winsor: Dict[str, tuple]):
        super().__init__(params, output)
        self.glm_model = glm_model
        self.tree_models = tree_models   # per-depth GBMModels (forest + bm)
        self.rules = rules               # each: tree-model idx, tree, lo/hi
        self.linear_cols = linear_cols
        self.winsor = winsor

    def _feature_frame(self, frame: Frame) -> Frame:
        cols: Dict[str, np.ndarray] = {}
        ri = 0
        for mi, tm in enumerate(self.tree_models):
            bm = rebin_for_scoring(tm.bm, frame)
            B = bm.nbins_total
            D = tm.forest.feat.shape[1]
            my_rules = [r for r in self.rules if r["model"] == mi]
            by_tree: Dict[int, List[dict]] = {}
            for r in my_rules:
                by_tree.setdefault(r["tree"], []).append(r)
            for t, rl in sorted(by_tree.items()):
                tree = type(tm.forest)(*(a[t] for a in tm.forest))
                nid = np.asarray(_route_nids(tree, bm.bins, B))[: frame.nrows]
                for r in rl:
                    cols[r["name"]] = ((nid >= r["lo"]) & (nid < r["hi"])
                                       ).astype(np.float64)
        for n in self.linear_cols:
            v = frame.col(n).to_numpy()
            lo, hi = self.winsor[n]
            cols[f"linear.{n}"] = np.clip(v, lo, hi)
        return Frame.from_numpy(cols)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        return self.glm_model._score_raw(self._feature_frame(frame))

    def model_performance(self, frame: Frame):
        ff = self._feature_frame(frame)
        y = self.output["response"]
        ff.add_column(frame.col(y))
        return self.glm_model.model_performance(ff)

    @property
    def rule_importance(self) -> List[dict]:
        return self.output["rule_importance"]


class RuleFitEstimator(ModelBuilder):
    """h2o-py H2ORuleFitEstimator surface
    (h2o-py/h2o/estimators/rulefit.py)."""

    algo = "rulefit"

    DEFAULTS = dict(
        seed=-1, algorithm="auto", min_rule_length=3, max_rule_length=3,
        max_num_rules=-1, model_type="rules_and_linear",
        rule_generation_ntrees=50, distribution="auto",
        sample_rate=0.8, nfolds=0, fold_assignment="auto",
        weights_column=None, fold_column=None, ignored_columns=None,
        lambda_=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        if "Lambda" in params:
            params["lambda_"] = params.pop("Lambda")
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown RuleFit params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        from h2o3_tpu.models.gbm import GBMEstimator
        from h2o3_tpu.models.drf import DRFEstimator
        from h2o3_tpu.models.glm import GLMEstimator
        p = self.params
        category = infer_category(frame, y)
        if category == ModelCategory.MULTINOMIAL:
            raise ValueError("RuleFit: multinomial not supported yet")
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xBEEF
        model_type = str(p["model_type"])
        use_rules = "rules" in model_type
        use_linear = "linear" in model_type

        depths = list(range(int(p["min_rule_length"]),
                            int(p["max_rule_length"]) + 1))
        ntrees_each = max(1, int(p["rule_generation_ntrees"]) // max(len(depths), 1))
        algo = str(p["algorithm"]).lower()
        TreeEst = DRFEstimator if algo == "drf" else GBMEstimator

        tree_models, rules = [], []
        cols: Dict[str, np.ndarray] = {}
        if use_rules:
            for di, depth in enumerate(depths):
                kw = dict(ntrees=ntrees_each, max_depth=depth, seed=seed + di,
                          sample_rate=float(p["sample_rate"]))
                if TreeEst is GBMEstimator:
                    kw["learn_rate"] = 0.1
                tm = TreeEst(**kw).train(frame, y=y, x=list(x))
                tree_models.append(tm)
                K = tm.output.get("nclasses", 1)
                forest = tm.forest
                T = forest.feat.shape[0]
                D = forest.feat.shape[1]
                B = tm.bm.nbins_total
                # binomial GBM trains 1 tree/iter; trees stack plainly
                for t in range(T):
                    tree = type(forest)(*(a[t] for a in forest))
                    nid = np.asarray(_route_nids(tree, tm.bm.bins, B))
                    for r in _extract_rules(forest, t, D):
                        r["model"] = di
                        r["name"] = f"M{di}T{t}N{r['lo']}"
                        r["lang"] = _rule_language(r, tm.bm)
                        mask = ((nid >= r["lo"]) & (nid < r["hi"])
                                )[: frame.nrows].astype(np.float64)
                        support = mask.mean()
                        if 0.0 < support < 1.0:
                            r["support"] = float(support)
                            rules.append(r)
                            cols[r["name"]] = mask
                job.update(0.5 / len(depths), f"rules depth {depth}")

        linear_cols: List[str] = []
        winsor: Dict[str, tuple] = {}
        if use_linear:
            for n in x:
                c = frame.col(n)
                if c.is_categorical or c.type == "string":
                    continue
                v = c.to_numpy()
                lo, hi = np.nanquantile(v, [0.025, 0.975])
                winsor[n] = (float(lo), float(hi))
                linear_cols.append(n)
                cols[f"linear.{n}"] = np.clip(v, lo, hi)

        if not cols:
            raise ValueError("RuleFit produced no features (no rules/linear)")
        ff = Frame.from_numpy(cols)
        ff.add_column(frame.col(y))

        lam = p["lambda_"]
        glm = GLMEstimator(
            family="binomial" if category == ModelCategory.BINOMIAL else "gaussian",
            alpha=1.0,
            lambda_=lam if lam is not None else None,
            lambda_search=lam is None, nlambdas=20,
            standardize=True,
            weights_column=p.get("weights_column"))
        gm = glm.train(ff, y=y, x=[n for n in ff.names if n != y])
        job.update(0.4, "glm fit")

        # rank rules by |coef|; enforce max_num_rules by zeroing the tail
        coefs = gm.coefficients
        max_rules = int(p["max_num_rules"])
        imp = []
        for r in rules:
            c = coefs.get(r["name"], 0.0)
            imp.append({"rule": r["lang"], "coefficient": float(c),
                        "support": r["support"], "name": r["name"]})
        for n in linear_cols:
            c = coefs.get(f"linear.{n}", 0.0)
            imp.append({"rule": f"linear({n})", "coefficient": float(c),
                        "support": 1.0, "name": f"linear.{n}"})
        imp.sort(key=lambda d: -abs(d["coefficient"]))
        if max_rules > 0:
            kill = {d["name"] for d in imp[max_rules:]}
            gm.coef = np.array(gm.coef)   # may be a read-only device view
            names = gm.output["coef_names"]
            for i, nm in enumerate(names):
                if nm in kill:
                    gm.coef[i] = 0.0
            imp = imp[:max_rules]
        imp = [d for d in imp if abs(d["coefficient"]) > 1e-12]

        output = {"category": category, "response": y, "names": list(x),
                  "domain": frame.col(y).domain,
                  "nclasses": frame.col(y).cardinality
                  if frame.col(y).is_categorical else 1,
                  "rule_importance": imp,
                  "n_rules": len(rules),
                  "default_threshold": gm.output.get("default_threshold", 0.5)}
        model = RuleFitModel(p, output, gm, tree_models,
                             [r for r in rules], linear_cols, winsor)
        model.training_metrics = gm.training_metrics
        if validation_frame is not None:
            model.validation_metrics = model.model_performance(validation_frame)
        return model
