"""PCA + SVD — dimensionality reduction via the distributed Gram path.

Reference: hex/pca/PCA.java:41 (pca_method GramSVD default: MRTask Gram
then local SVD; Power / Randomized / GLRM alternatives) and
hex/svd/SVD.java (distributed power iteration / randomized subspace).

TPU redesign: the Gram X'X is one einsum + psum over the row-sharded
design matrix (ops/gram.py); the [P,P] eigendecomposition runs on a
single chip (P is feature-space width — modest in H2O's tabular regime).
Randomized SVD (Halko et al.) keeps everything as tall-matmuls on the
MXU: Y = X Ω → QR → B = Qᵀ X → small SVD, one pass over the data axis.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.datainfo import build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model import Model, ModelBuilder, ModelCategory
from h2o3_tpu.ops.gram import gram
from h2o3_tpu.parallel.mesh import get_mesh


def _gram_eig(X, w, mesh):
    """X'WX eigen-decomposition (GramSVD): returns (eigvals desc, eigvecs)."""
    z = jnp.zeros(X.shape[0], jnp.float32)
    xtx, _, wsum = gram(X, w, z, mesh=mesh)
    cov = xtx / jnp.maximum(wsum - 1.0, 1.0)
    evals, evecs = jnp.linalg.eigh(cov)        # ascending
    return evals[::-1], evecs[:, ::-1], wsum


@partial(jax.jit, static_argnames=("k", "iters"))
def _randomized_range(X, k: int, iters: int, key):
    """Randomized subspace: Q [N, k] orthonormal range of X (Halko)."""
    P = X.shape[1]
    omega = jax.random.normal(key, (P, k), jnp.float32)
    Y = X @ omega
    Q, _ = jnp.linalg.qr(Y)
    for _ in range(iters):
        Z = X.T @ Q          # [P, k] — psum over data axis by XLA
        Q, _ = jnp.linalg.qr(X @ Z)
    return Q


class PCAModel(Model):
    algo = "pca"

    def __init__(self, params, output, eigvecs, di_stats, features,
                 transform: str, use_all_levels: bool):
        super().__init__(params, output)
        self.eigvecs = eigvecs          # [P, k]
        self.di_stats = di_stats
        self.features = features
        self.transform = transform
        self.use_all_levels = use_all_levels

    def _design(self, frame: Frame):
        return build_datainfo(frame, self.features,
                              standardize=(self.transform == "standardize"),
                              use_all_factor_levels=self.use_all_levels,
                              stats_override=self.di_stats)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        di = self._design(frame)
        scores = np.asarray(di.X @ self.eigvecs)[: frame.nrows]
        return {f"PC{i + 1}": scores[:, i] for i in range(scores.shape[1])}

    def model_performance(self, frame: Frame):
        return self.training_metrics


class PCAEstimator(ModelBuilder):
    """h2o-py H2OPrincipalComponentAnalysisEstimator-compatible surface."""

    algo = "pca"
    supervised = False

    DEFAULTS = dict(
        k=1, transform="standardize", pca_method="GramSVD",
        max_iterations=20, seed=-1, use_all_factor_levels=False,
        compute_metrics=True, impute_missing=True, ignored_columns=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown PCA params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        transform = str(p["transform"]).lower()
        di = build_datainfo(frame, x, standardize=(transform == "standardize"),
                            use_all_factor_levels=bool(p["use_all_factor_levels"]))
        w = frame.valid_weights()
        k = min(int(p["k"]), di.P)
        method = str(p["pca_method"]).lower()

        if method in ("gramsvd", "power", "glrm"):
            evals, evecs, wsum = _gram_eig(di.X, w, mesh)
            evals = np.maximum(np.asarray(evals), 0.0)
            V = np.asarray(evecs)[:, :k]
            sdev = np.sqrt(evals)
        else:  # randomized
            seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0x9CA
            Q = _randomized_range(di.X * w[:, None], k + 4,
                                  int(p["max_iterations"]),
                                  jax.random.PRNGKey(seed))
            B = Q.T @ di.X                         # [k+4, P]
            _, s, Vt = jnp.linalg.svd(B, full_matrices=False)
            V = np.asarray(Vt.T)[:, :k]
            n_eff = float(jnp.sum(w))
            sdev = np.asarray(s) / np.sqrt(max(n_eff - 1.0, 1.0))
            evals = sdev ** 2
        job.update(1.0, "decomposition done")

        tot = float(evals.sum()) or 1.0
        prop = evals[:k] / tot
        output = {"category": ModelCategory.DIMREDUCTION, "response": None,
                  "names": list(x), "domain": None,
                  "std_deviation": sdev[:k].tolist(),
                  "eigenvectors": V.tolist(),
                  "coef_names": di.coef_names,
                  "pct_variance": prop.tolist(),
                  "cum_pct_variance": np.cumsum(prop).tolist()}
        model = PCAModel(p, output, jnp.asarray(V), stats_of(di), list(x),
                         transform, bool(p["use_all_factor_levels"]))
        model.training_metrics = ModelMetrics(
            "PCA", frame.nrows, 0.0,
            pct_variance_explained=float(np.cumsum(prop)[-1]))
        return model


class SVDModel(Model):
    algo = "svd"

    def __init__(self, params, output, V, di_stats, features, transform,
                 use_all_levels: bool):
        super().__init__(params, output)
        self.V = V
        self.di_stats = di_stats
        self.features = features
        self.transform = transform
        self.use_all_levels = use_all_levels

    def _design(self, frame: Frame):
        return build_datainfo(frame, self.features,
                              standardize=(self.transform == "standardize"),
                              use_all_factor_levels=self.use_all_levels,
                              stats_override=self.di_stats)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        di = self._design(frame)
        sv = np.asarray(self.output["d"], np.float32)
        proj = np.asarray(di.X @ self.V)[: frame.nrows]
        u = proj / np.maximum(sv[None, :], 1e-12)
        return {f"u{i + 1}": u[:, i] for i in range(u.shape[1])}

    def model_performance(self, frame: Frame):
        return self.training_metrics


class SVDEstimator(ModelBuilder):
    """h2o-py H2OSingularValueDecompositionEstimator-compatible surface."""

    algo = "svd"
    supervised = False

    DEFAULTS = dict(
        nv=1, transform="none", svd_method="GramSVD", max_iterations=20,
        seed=-1, use_all_factor_levels=True, ignored_columns=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown SVD params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        transform = str(p["transform"]).lower()
        di = build_datainfo(frame, x, standardize=(transform == "standardize"),
                            use_all_factor_levels=bool(p["use_all_factor_levels"]))
        w = frame.valid_weights()
        k = min(int(p["nv"]), di.P)
        # X'X eigen → right singular vectors; σ = sqrt(λ) (unscaled Gram)
        z = jnp.zeros(di.X.shape[0], jnp.float32)
        xtx, _, _ = gram(di.X, w, z, mesh=mesh)
        evals, evecs = jnp.linalg.eigh(xtx)
        evals = np.maximum(np.asarray(evals)[::-1], 0.0)
        V = np.asarray(evecs)[:, ::-1][:, :k]
        d = np.sqrt(evals[:k])
        job.update(1.0, "svd done")
        output = {"category": ModelCategory.DIMREDUCTION, "response": None,
                  "names": list(x), "domain": None,
                  "d": d.tolist(), "v": V.tolist(),
                  "coef_names": di.coef_names}
        model = SVDModel(p, output, jnp.asarray(V), stats_of(di), list(x),
                         transform, bool(p["use_all_factor_levels"]))
        model.training_metrics = ModelMetrics("SVD", frame.nrows, 0.0)
        return model
