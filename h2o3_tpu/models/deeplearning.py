"""DeepLearning — multilayer perceptron, data-parallel over the mesh.

Reference: hex/deeplearning/DeepLearning.java:35 + DeepLearningTask.java:17
(fprop/bprop per row, HOGWILD! lock-free SGD per node, periodic cross-node
model averaging, DeepLearningTask.java:62,125-135,164-176), Neurons.java:21
(Rectifier/Tanh/Maxout ± dropout), adadelta/nesterov updates
(DeepLearningModelInfo), autoencoder mode.

TPU redesign: HOGWILD row-at-a-time SGD is a CPU idiom. Here one jitted
`_train_step` runs a minibatch fprop/bprop as batched matmuls (MXU) with
rows sharded over the 'data' axis; the gradient psum XLA inserts IS the
reference's model averaging — every step, not every pass, which strictly
dominates it (SURVEY §2.4 item 3). Adadelta (rho/epsilon), Nesterov
momentum with rate annealing, L1/L2, input/hidden dropout, and the
UniformAdaptive initializer match the reference's semantics.
"""

from __future__ import annotations

import time

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.core import recovery as _recovery
from h2o3_tpu.core.watchdog import maybe_fail
from h2o3_tpu.frame.datainfo import build_datainfo, stats_of
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as mm
from h2o3_tpu.models.model import (EarlyStopper, Model, ModelBuilder,
                                   ModelCategory, adapt_domain,
                                   checkpoint_error, infer_category,
                                   resolve_checkpoint_model,
                                   validate_checkpoint_params)
from h2o3_tpu.parallel.mesh import get_mesh, row_sharding, shard_rows
from h2o3_tpu.telemetry import observed_jit

ACTS = {
    "rectifier": jax.nn.relu,
    "tanh": jnp.tanh,
    "maxout": None,  # handled specially (pairs of units, max)
}


def _parse_activation(name: str):
    n = name.lower().replace("withdropout", "").replace("with_dropout", "")
    dropout = "dropout" in name.lower()
    return n, dropout


def _init_params(key, sizes: List[int], maxout: bool):
    """UniformAdaptive init: ±sqrt(6/(fan_in+fan_out)) (reference
    DeepLearningModelInfo.randomizeWeights)."""
    params = []
    for i in range(len(sizes) - 1):
        fin, fout = sizes[i], sizes[i + 1]
        mult = 2 if (maxout and i < len(sizes) - 2) else 1
        key, sub = jax.random.split(key)
        lim = np.sqrt(6.0 / (fin + fout))
        W = jax.random.uniform(sub, (fin, fout * mult), jnp.float32,
                               -lim, lim)
        params.append({"W": W, "b": jnp.zeros((fout * mult,), jnp.float32)})
    return params


def _forward(params, X, act: str, *, key=None, input_dropout=0.0,
             hidden_dropout=None, train=False, bf16=False):
    """fprop (Neurons.java fprop); returns final-layer linear output."""
    h = X
    if train and input_dropout > 0:
        key, sub = jax.random.split(key)
        keep = jax.random.bernoulli(sub, 1 - input_dropout, h.shape)
        h = h * keep / (1 - input_dropout)
    L = len(params)
    # bf16 (explicit flag, set only by the fused TRAINING step at
    # batch >= 16K): matmuls run at the v5e MXU's native bf16 rate with
    # f32 accumulation (f32 dots pay the bf16x3 triple pass). Scoring,
    # small fits, and the early-stopping loss evals stay f32 — metric
    # oracles and stopping_tolerance (1e-5 default) are asserted on the
    # f32 path.
    for i, layer in enumerate(params):
        if bf16:
            z = jax.lax.dot(h.astype(jnp.bfloat16),
                            layer["W"].astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32) \
                + layer["b"]
        else:
            z = h @ layer["W"] + layer["b"]
        if i == L - 1:
            return z
        if act == "maxout":
            z = z.reshape(z.shape[0], -1, 2).max(axis=2)
        elif act == "tanh":
            z = jnp.tanh(z)
        else:
            z = jax.nn.relu(z)
        if train and hidden_dropout and hidden_dropout[i] > 0:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1 - hidden_dropout[i], z.shape)
            z = z * keep / (1 - hidden_dropout[i])
        h = z
    return h


@partial(jax.jit, static_argnames=("act",))
def _forward_scoring(params, X, act: str):
    """Jitted inference forward — scoring paths must never run the
    layer loop eagerly (per-op dispatch through a remote-chip tunnel is
    100x the fused program cost)."""
    return _forward(params, X, act)


def _loss(params, X, y, w, key, *, act, category, input_dropout,
          hidden_dropout, l1, l2, nclasses, bf16=False):
    out = _forward(params, X, act, key=key, input_dropout=input_dropout,
                   hidden_dropout=hidden_dropout, train=True, bf16=bf16)
    if category == "softmax":
        logp = jax.nn.log_softmax(out, axis=1)
        nll = -jnp.take_along_axis(logp, y[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        data_loss = jnp.sum(w * nll)
    else:  # regression / autoencoder: quadratic loss
        err = out - (y if out.ndim == y.ndim else y[:, None])
        data_loss = 0.5 * jnp.sum(w[:, None] * err * err) / max(out.shape[1], 1)
    wsum = jnp.maximum(jnp.sum(w), 1e-12)
    reg = sum(l2 * jnp.sum(p["W"] ** 2) + l1 * jnp.sum(jnp.abs(p["W"]))
              for p in params)
    return data_loss / wsum + reg


def _train_step_impl(params, opt_state, lr, X, y, w, key, *, act, category,
                     input_dropout, hidden_dropout, l1, l2, nclasses,
                     adaptive, rho, epsilon, nesterov, mu_now=None,
                     bf16=False):
    """One minibatch step. XLA's gradient psum over the sharded batch is
    the cross-replica model averaging (DeepLearningTask.java:164-176).
    ``mu_now`` overrides the momentum carried in opt_state (the fused
    multi-step path computes the ramp per step on device)."""
    grads = jax.grad(_loss)(params, X, y, w, key, act=act, category=category,
                            input_dropout=input_dropout,
                            hidden_dropout=hidden_dropout, l1=l1, l2=l2,
                            nclasses=nclasses, bf16=bf16)
    def upd(p, g, s):
        # ADADELTA (reference adaptive_rate=True, rho/epsilon params)
        eg2 = rho * s["eg2"] + (1 - rho) * g * g
        dx = -jnp.sqrt(s["ex2"] + epsilon) / jnp.sqrt(eg2 + epsilon) * g
        ex2 = rho * s["ex2"] + (1 - rho) * dx * dx
        return p + dx, {"eg2": eg2, "ex2": ex2}

    new_params, new_state = [], []
    for p, g, s in zip(params, grads, opt_state):
        np_, ns_ = {}, {}
        for k in ("W", "b"):
            if adaptive:
                pk, sk = upd(p[k], g[k], s[k])
            else:
                # Nesterov momentum SGD (reference momentum_start/stable)
                mu = s[k]["mu"] if mu_now is None else mu_now
                v = mu * s[k]["v"] - lr * g[k]
                pk = (p[k] + mu * v - lr * g[k]) if nesterov else (p[k] + v)
                sk = {"v": v, "mu": mu}
            np_[k] = pk
            ns_[k] = sk
        new_params.append(np_)
        new_state.append(ns_)
    return new_params, new_state


_STEP_STATICS = ("act", "category", "input_dropout", "hidden_dropout",
                 "l1", "l2", "nclasses", "adaptive", "rho", "epsilon",
                 "nesterov", "bf16")

# jitted full-dataset loss for the early-stopping boundary — the eager
# _loss layer loop would re-dispatch per op through the chip tunnel
_loss_eval = partial(jax.jit, static_argnames=(
    "act", "category", "input_dropout", "hidden_dropout", "l1", "l2",
    "nclasses"))(_loss)


@observed_jit("dl.train_chunk")
@partial(jax.jit, static_argnames=_STEP_STATICS + (
    "nsteps", "batch", "n", "rate", "rate_annealing",
    "momentum_start", "momentum_stable", "momentum_ramp"))
def _train_steps_fused(params, opt_state, X, y, w, key, step0, start0,
                       limit, *,
                       nsteps, batch, n, rate, rate_annealing,
                       momentum_start, momentum_stable, momentum_ramp,
                       **step_kwargs):
    """``nsteps`` minibatch steps as one compiled scan — batch indices
    drawn on device, lr/momentum schedules computed per step. Removes
    the per-step host round trip (the dominant cost on a remote chip),
    the HOGWILD-free analogue of the reference's per-node inner loop
    (hex/deeplearning/DeepLearningTask.java).

    ``nsteps`` is the STATIC chunk size and ``limit`` the TRACED count
    of effective steps: iterations past the limit keep params frozen
    (masked update). One compiled program therefore serves every chunk
    of every epoch count at a given shape — the DL analogue of the tree
    DEPTH_BUCKETS; the remainder chunk (e.g. 153 of a 200-chunk) no
    longer compiles its own program (round-4 bench lost ~7 minutes of
    its warmup budget to exactly that)."""

    from h2o3_tpu.parallel.mesh import row_sharding

    def body(carry, i):
        params, opt_state, key = carry
        key, kstep = jax.random.split(key)
        step = step0 + i
        # CONTIGUOUS cyclic slice, not a random gather: random row
        # gathers from a GB-scale HBM array run at ~3GB/s on v5e (the
        # measured 1M-samples/s ceiling); sequential slices stream at
        # full bandwidth. Matches the reference's default pass order
        # (shuffle_training_data=false, DeepLearningTask row walk).
        # start0 is host-computed (exact int; step0*batch would overflow
        # int32 on long fits); modulo n, with dynamic_slice clamping the
        # epoch-boundary start so tail rows still train.
        start = (start0 + i.astype(jnp.int32) * batch) % max(n, 1)
        Xb = jax.lax.dynamic_slice_in_dim(X, start, batch, axis=0)
        yb = jax.lax.dynamic_slice_in_dim(y, start, batch, axis=0)
        wb = jax.lax.dynamic_slice_in_dim(w, start, batch, axis=0)
        # the sliced batch must stay row-sharded: without the constraint
        # GSPMD may replicate it and the gradient psum over the 'data'
        # axis would average a replicated batch
        Xb = jax.lax.with_sharding_constraint(Xb, row_sharding())
        yb = jax.lax.with_sharding_constraint(yb, row_sharding())
        wb = jax.lax.with_sharding_constraint(wb, row_sharding())
        lr = jnp.float32(rate) / (1.0 + rate_annealing * step * batch)
        ramp = jnp.minimum(1.0, step * batch / max(momentum_ramp, 1.0))
        mu_now = jnp.float32(momentum_start
                             + (momentum_stable - momentum_start) * ramp)
        new_p, new_s = _train_step_impl(
            params, opt_state, lr, Xb, yb, wb, kstep,
            mu_now=mu_now, **step_kwargs)
        eff = i < limit
        params = jax.tree_util.tree_map(
            lambda a, b: jnp.where(eff, a, b), new_p, params)
        opt_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(eff, a, b), new_s, opt_state)
        return (params, opt_state, key), None

    (params, opt_state, key), _ = jax.lax.scan(
        body, (params, opt_state, key),
        jnp.arange(nsteps, dtype=jnp.float32))
    return params, opt_state, key


_DESIGN_MEMO = None      # (model, frame, key, DataInfo) — one slot


class DeepLearningModel(Model):
    algo = "deeplearning"

    def __init__(self, params, output, net_params, di_stats, features, act,
                 standardize, resp_stats=None):
        super().__init__(params, output)
        self.net = net_params
        self.di_stats = di_stats
        self.features = features
        self.act = act
        self.standardize = standardize
        self.resp_stats = resp_stats   # (mean, sigma) for regression target

    def _design(self, frame: Frame):
        # single-slot memo (module-level, NOT per model): _fit scores
        # training_metrics on the frame it just expanded, and
        # bench/AutoML score the training frame again right after —
        # rebuilding a 784-column design costs seconds on a remote
        # chip. One global slot bounds pinned device memory to one
        # design no matter how many models the leaderboard holds.
        # Keyed by (model, frame) object identity + frame key; rapids
        # mutations always produce NEW Frame objects.
        global _DESIGN_MEMO
        memo = _DESIGN_MEMO
        if memo is not None and memo[0] is self and memo[1] is frame \
                and memo[2] == frame.key:
            return memo[3]
        di = build_datainfo(frame, self.features,
                            standardize=self.standardize,
                            use_all_factor_levels=bool(
                                self.params.get("use_all_factor_levels")),
                            stats_override=self.di_stats)
        _DESIGN_MEMO = (self, frame, frame.key, di)
        return di

    def _raw_out(self, frame: Frame):
        di = self._design(frame)
        return _forward_scoring(self.net, di.X, self.act)

    def _score_raw(self, frame: Frame) -> Dict[str, np.ndarray]:
        n = frame.nrows
        if self.params.get("autoencoder"):
            di = self._design(frame)
            out = _forward_scoring(self.net, di.X, self.act)
            mse = np.asarray(jnp.mean((out - di.X) ** 2, axis=1))[:n]
            return {"reconstruction_error": mse}
        # the model's ONE compiled scoring program — the same
        # executable the serving tier dispatches, so row-payload
        # predictions match bit-for-bit (Model._serve_jit)
        di = self._design(frame)
        return self._serve_finish(np.asarray(self._serve_jit()(di.X)), n)

    def _serve_dev(self, X):
        """Device half of the serving fast path (serving/engine.py jits
        this per row bucket): EXACTLY the device math of ``_score_raw``
        on a prepared design matrix (``_design(frame).X``). Autoencoders
        take the engine's eager fallback (their host tail needs the
        design matrix itself)."""
        out = _forward_scoring(self.net, X, self.act)
        if self.output["category"] in (ModelCategory.BINOMIAL,
                                       ModelCategory.MULTINOMIAL):
            return jax.nn.softmax(out, axis=1)
        return out

    def _serve_finish(self, fetched: np.ndarray, n: int) -> Dict[str, np.ndarray]:
        """Host half of the serving fast path: the exact host tail of
        ``_score_raw`` applied to the fetched device output (the
        regression de-standardization deliberately stays host-side —
        ``_score_raw`` does it in numpy, and moving a f32-array ×
        python-float product onto the device would risk a ULP drift)."""
        cat = self.output["category"]
        if cat == ModelCategory.BINOMIAL:
            p = fetched[:n]
            t = self.output.get("default_threshold", 0.5)
            return {"predict": (p[:, 1] >= t).astype(np.int32),
                    "p0": p[:, 0], "p1": p[:, 1]}
        if cat == ModelCategory.MULTINOMIAL:
            p = fetched[:n]
            o = {"predict": p.argmax(axis=1).astype(np.int32)}
            for k in range(p.shape[1]):
                o[f"p{k}"] = p[:, k]
            return o
        mu, sd = self.resp_stats
        return {"predict": fetched[:n, 0] * sd + mu}

    def anomaly(self, frame: Frame) -> Frame:
        """Autoencoder per-row reconstruction MSE (reference
        DeepLearningModel.scoreAutoEncoder)."""
        assert self.params.get("autoencoder")
        return Frame.from_numpy(self._score_raw(frame))

    def model_performance(self, frame: Frame, mask_weights=None):
        """``mask_weights``: optional row mask multiplied into the
        weights — the score_training_samples subsample path (the
        reference scores training metrics on a 10K sample by default,
        DeepLearningModel._score_training_samples=10000)."""
        y = self.output["response"]
        w = frame.valid_weights()
        if mask_weights is not None:
            w = w * jnp.asarray(np.asarray(mask_weights, np.float32))
        cat = self.output["category"]
        if self.params.get("autoencoder"):
            di = self._design(frame)
            out = _forward_scoring(self.net, di.X, self.act)
            mse = float(jnp.sum(w * jnp.mean((out - di.X) ** 2, axis=1))
                        / jnp.maximum(jnp.sum(w), 1e-12))
            return mm.ModelMetrics("AutoEncoder", int(jnp.sum(w)), mse)
        out = self._raw_out(frame)
        if cat in (ModelCategory.BINOMIAL, ModelCategory.MULTINOMIAL):
            yv = adapt_domain(frame.col(y), self.output["domain"])
            yv = np.pad(yv, (0, out.shape[0] - frame.nrows),
                        constant_values=-1)
            w = w * jnp.asarray((yv >= 0).astype(np.float32))
            yv = np.maximum(yv, 0)
            p = jax.nn.softmax(out, axis=1)
            if cat == ModelCategory.BINOMIAL:
                return mm.binomial_metrics(p[:, 1],
                                           jnp.asarray(yv.astype(np.float32)), w)
            return mm.multinomial_metrics(p, jnp.asarray(yv), w,
                                          domain=self.output["domain"])
        mu, sd = self.resp_stats
        pred = out[:, 0] * sd + mu
        yv = frame.col(y).numeric_view()
        w = w * jnp.where(jnp.isnan(yv), 0.0, 1.0)
        yv = jnp.where(jnp.isnan(yv), 0.0, yv)
        return mm.regression_metrics(pred, yv, w)


class DeepLearningEstimator(ModelBuilder):
    """h2o-py H2ODeepLearningEstimator-compatible surface."""

    algo = "deeplearning"

    DEFAULTS = dict(
        hidden=(200, 200), epochs=10.0, activation="Rectifier",
        adaptive_rate=True, rho=0.99, epsilon=1e-8,
        rate=0.005, rate_annealing=1e-6, rate_decay=1.0,
        momentum_start=0.0, momentum_ramp=1e6, momentum_stable=0.0,
        nesterov_accelerated_gradient=True,
        input_dropout_ratio=0.0, hidden_dropout_ratios=None,
        l1=0.0, l2=0.0, loss="auto", distribution="auto",
        standardize=True, mini_batch_size=1, seed=-1,
        autoencoder=False, export_weights_and_biases=False,
        nfolds=0, weights_column=None,
        fold_column=None, fold_assignment="auto", ignored_columns=None,
        stopping_rounds=5, stopping_metric="auto", stopping_tolerance=0.0,
        score_interval=5.0, train_samples_per_iteration=-2,
        score_training_samples=10000, score_validation_samples=0,
        use_all_factor_levels=False, max_w2=3.4e38, reproducible=False,
        checkpoint=None,
    )

    def __init__(self, **params):
        merged = dict(self.DEFAULTS)
        unknown = set(params) - set(merged)
        if unknown:
            raise ValueError(f"unknown DeepLearning params: {sorted(unknown)}")
        merged.update(params)
        super().__init__(**merged)

    def _fit(self, frame: Frame, x: Sequence[str], y: Optional[str],
             job, validation_frame: Optional[Frame] = None) -> Model:
        p = self.params
        mesh = get_mesh()
        auto_enc = bool(p["autoencoder"])
        category = (None if auto_enc else infer_category(frame, y))
        act, act_dropout = _parse_activation(str(p["activation"]))
        di = build_datainfo(frame, x, standardize=bool(p["standardize"]),
                            use_all_factor_levels=bool(p["use_all_factor_levels"]))
        w = frame.valid_weights()
        if p.get("weights_column"):
            wc = frame.col(p["weights_column"]).numeric_view()
            w = w * jnp.where(jnp.isnan(wc), 0.0, wc)

        N = di.X.shape[0]
        n = frame.nrows
        resp_stats = None
        if auto_enc:
            y_dev = di.X
            out_dim = di.P
            cat_mode = "mse"
        elif category == ModelCategory.REGRESSION:
            yv = frame.col(y).numeric_view()
            w = w * jnp.where(jnp.isnan(yv), 0.0, 1.0)
            yhost = np.nan_to_num(np.asarray(yv))
            wn = np.asarray(w)
            mu = float((yhost * wn).sum() / max(wn.sum(), 1e-12))
            sd = float(np.sqrt(np.maximum(
                ((yhost - mu) ** 2 * wn).sum() / max(wn.sum(), 1e-12), 1e-12)))
            resp_stats = (mu, sd)
            y_dev = jnp.asarray((yhost - mu) / sd)[:, None]
            out_dim = 1
            cat_mode = "mse"
        else:
            rc = frame.col(y)
            codes = _fetch_np(rc.data)[:n].astype(np.int32)
            na = _fetch_np(rc.na_mask)[:n]
            w = w * jnp.asarray(np.pad((~na).astype(np.float32), (0, N - n)))
            codes[na] = 0
            y_dev = jax.device_put(np.pad(codes, (0, N - n)),
                                   row_sharding(mesh))
            out_dim = rc.cardinality
            cat_mode = "softmax"

        hidden = [int(h) for h in p["hidden"]]
        sizes = [di.P] + hidden + [out_dim]
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xD1
        key = jax.random.PRNGKey(seed)
        key, kinit = jax.random.split(key)
        done0 = 0
        prior_opt = prior_key = None
        if p.get("checkpoint") is not None:
            # checkpoint restart (DeepLearningModelInfo semantics):
            # ``epochs`` names the new TOTAL and training CONTINUES from
            # the donor's step count, the optimizer state is restored so
            # ADADELTA accumulators / momentum do not cold-start, and
            # the minibatch PRNG stream resumes where the donor stopped
            prior = resolve_checkpoint_model(
                "deeplearning", p["checkpoint"], DeepLearningModel)
            shapes = [tuple(np.asarray(l["W"]).shape) for l in prior.net]
            want = [(sizes[i], sizes[i + 1] * (2 if act == "maxout"
                                               and i < len(sizes) - 2 else 1))
                    for i in range(len(sizes) - 1)]
            if shapes != want:
                raise checkpoint_error(
                    "deeplearning", "hidden",
                    "Field _hidden cannot be modified if checkpoint is "
                    "provided (hidden layout cannot change across "
                    "checkpoint restart)")
            validate_checkpoint_params(
                "deeplearning", prior.params, p,
                ("activation", "standardize", "adaptive_rate",
                 "use_all_factor_levels", "autoencoder"))
            prior_epochs = float(prior.params.get("epochs", 0.0))
            if float(p["epochs"]) <= prior_epochs:
                raise checkpoint_error(
                    "deeplearning", "epochs",
                    f"If checkpoint is provided, epochs ({p['epochs']}) "
                    "must be higher than the checkpoint model's epochs "
                    f"({prior_epochs})")
            params_net = [{"W": jnp.asarray(l["W"]), "b": jnp.asarray(l["b"])}
                          for l in prior.net]
            done0 = int(getattr(prior, "_steps_trained", 0) or 0)
            prior_opt = getattr(prior, "_opt_state", None)
            prior_key = getattr(prior, "_prng_key", None)
        else:
            params_net = _init_params(kinit, sizes, act == "maxout")

        hd = p["hidden_dropout_ratios"]
        if hd is None:
            hd = tuple([0.5] * len(hidden)) if act_dropout else tuple([0.0] * len(hidden))
        else:
            hd = tuple(float(v) for v in hd)
        in_drop = float(p["input_dropout_ratio"])

        adaptive = bool(p["adaptive_rate"])
        if adaptive:
            opt_state = [{k: {"eg2": jnp.zeros_like(l[k]),
                              "ex2": jnp.zeros_like(l[k])} for k in ("W", "b")}
                         for l in params_net]
        else:
            opt_state = [{k: {"v": jnp.zeros_like(l[k]),
                              "mu": jnp.float32(p["momentum_start"])}
                          for k in ("W", "b")}
                         for l in params_net]
        if prior_opt is not None:
            # optimizer state continues across the restart (adaptive_rate
            # is validated non-modifiable and layer shapes match)
            opt_state = jax.tree_util.tree_map(jnp.asarray, prior_opt)
        if prior_key is not None:
            key = jnp.asarray(prior_key)

        batch = int(p["mini_batch_size"])
        if batch <= 1:
            # TPU minibatch default: scale with data up to 16K — the
            # fused step is overhead-bound below that (measured
            # 0.08ms/step at 1024 vs 0.36ms at 8192 on v5e; per-step
            # dispatch ~6ms dominates at 4096 on 1M-row fits), and
            # ADADELTA's per-parameter rates keep convergence stable.
            # Power-of-two so the MXU tiles cleanly. The 256 floor is
            # clamped to the PADDED row count: the fused step slices
            # `batch` rows with dynamic_slice_in_dim, which requires
            # slice size <= array dim — without the clamp any fit on a
            # frame below ~224 rows fails at trace time.
            batch = min(16384, max(256, n // 64), N)
            # small fits get at least ~16 optimizer steps per epoch:
            # ADADELTA ramps its per-parameter rates from ex2=0, so a
            # 1500-row fit at the 256 floor ran only ~3 steps/epoch and
            # never left the warmup regime (the reference's HOGWILD
            # loop updates per ROW). Only fits under ~4096 rows shrink;
            # the 32 floor keeps the fused step off degenerate slices.
            batch = min(batch, max(32, n // 16))
            batch = 1 << (batch.bit_length() - 1)
        ndata = mesh.shape["data"]
        batch = ((batch + ndata - 1) // ndata) * ndata
        epochs = float(p["epochs"])
        total_steps = max(1, int(epochs * n / batch))
        stopper = EarlyStopper(int(p["stopping_rounds"]),
                               float(p["stopping_tolerance"]) or 1e-5)

        Xh = di.X   # already device, row-sharded
        step_kwargs = dict(bf16=batch >= 16384,
                           act=act, category=cat_mode, input_dropout=in_drop,
                           hidden_dropout=hd, l1=float(p["l1"]),
                           l2=float(p["l2"]), nclasses=out_dim,
                           adaptive=adaptive, rho=float(p["rho"]),
                           epsilon=float(p["epsilon"]),
                           nesterov=bool(p["nesterov_accelerated_gradient"]))
        scoring_history = []
        sched = dict(nsteps=0, batch=batch, n=n,
                     rate=float(p["rate"]),
                     rate_annealing=float(p["rate_annealing"]),
                     momentum_start=float(p["momentum_start"]),
                     momentum_stable=float(p["momentum_stable"]),
                     momentum_ramp=float(p["momentum_ramp"]))
        # fused multi-step chunks: score/cancel boundaries between
        # chunks. The chunk size is the STATIC program; short final
        # chunks ride the same program with a traced ``limit``, and the
        # size is FIXED (200, or 25 for tiny fits) so epoch-count
        # variants — AutoML candidates, a bench warmup vs its timed
        # run — share one compile. Early stopping therefore scores at
        # chunk boundaries (the reference's ScoreKeeper likewise scores
        # on an interval, not per iteration).
        chunk = 200 if total_steps >= 25 else 25
        sched["nsteps"] = chunk
        # full-dataset loss evals keep the OLD total//10 cadence (a
        # long fit must not pay a full-data pass every 200 steps); the
        # eval itself is the jitted program, never the eager layer loop
        score_stride = max(chunk, -(-total_steps // 10))
        next_score = score_stride
        # checkpoint= continuation starts at the donor's step count (the
        # lr/momentum schedules read the GLOBAL step, so annealing
        # continues rather than restarting)
        done = min(done0, total_steps)
        # in-fit checkpointer (core/recovery.py): epoch-boundary partial
        # state — net, optimizer state, PRNG key, early-stop + scoring
        # history — so a killed fit resumes bit-identically
        fc = None
        if getattr(self, "_cv_fold_mask", None) is None:
            fc = _recovery.fit_checkpointer(
                "deeplearning", p, y, x, frame.nrows,
                default_every=max(chunk, int(round(n / max(batch, 1)))))
            if fc is not None:
                _loaded = fc.load()
                if _loaded is not None:
                    _st = _loaded[1]
                    done = int(_st["done"])
                    params_net = jax.tree_util.tree_map(
                        jnp.asarray, _st["net"])
                    opt_state = jax.tree_util.tree_map(
                        jnp.asarray, _st["opt"])
                    key = jnp.asarray(_st["key"])
                    next_score = _st["next_score"]
                    stopper.history = list(_st["stop_hist"])
                    scoring_history = list(_st["scoring_history"])
        from h2o3_tpu import telemetry
        from h2o3_tpu.telemetry import stepprof
        while done < total_steps:
            k = min(chunk, total_steps - done)
            _ct0 = time.time()
            stepprof.chunk_begin()
            with telemetry.span("deeplearning.chunk", steps=k):
                params_net, opt_state, key = _train_steps_fused(
                    params_net, opt_state, Xh, y_dev, w, key,
                    jnp.float32(done),
                    jnp.int32((done * batch) % max(n, 1)),
                    jnp.float32(k), **sched, **step_kwargs)
                stepprof.compute_done((params_net, opt_state))
            telemetry.histogram("train_chunk_seconds",
                                algo="deeplearning").observe(
                time.time() - _ct0)
            telemetry.counter("train_iterations_total",
                              algo="deeplearning").inc(k)
            stepprof.chunk_end(steps=k)
            done += k
            job.update(k / total_steps, f"step {done}/{total_steps}")
            if stopper.enabled and (done >= next_score
                                    or done >= total_steps):
                next_score += score_stride
                key, sub = jax.random.split(key)
                lv = float(_loss_eval(
                    params_net, Xh, y_dev, w, sub, act=act,
                    category=cat_mode, input_dropout=0.0,
                    hidden_dropout=tuple([0.0] * len(hidden)),
                    l1=0.0, l2=0.0, nclasses=out_dim))
                scoring_history.append({"step": done, "loss": lv})
                if stopper.should_stop(lv):
                    break
            if fc is not None:
                _d = done
                fc.maybe_save(done, lambda: {
                    "done": _d,
                    "net": _recovery.snapshot_host(params_net),
                    "opt": _recovery.snapshot_host(opt_state),
                    "key": _recovery.snapshot_host(key),
                    "next_score": next_score,
                    "stop_hist": list(stopper.history),
                    "scoring_history": list(scoring_history)})
            maybe_fail("fit_chunk")
            maybe_fail("device_oom")
        if fc is not None:
            fc.clear()

        rc = None if (auto_enc or y is None) else frame.col(y)
        output = {"category": category or "AutoEncoder", "response": y,
                  "names": list(x),
                  "nclasses": (rc.cardinality if rc is not None and
                               rc.is_categorical else 1),
                  "domain": rc.domain if rc is not None else None,
                  "scoring_history": scoring_history,
                  "hidden": hidden, "activation": p["activation"]}
        model = DeepLearningModel(p, output, params_net, stats_of(di),
                                  list(x), act, bool(p["standardize"]),
                                  resp_stats)
        # continuation state for checkpoint= restarts (host-lowered so a
        # pickled model restarts on any mesh): optimizer accumulators,
        # global step count, and the minibatch PRNG position
        model._opt_state = jax.tree_util.tree_map(np.asarray, opt_state)
        model._steps_trained = int(done)
        model._prng_key = np.asarray(key)
        # training_metrics below re-scores `frame`: hand it the design
        # we already expanded instead of rebuilding it
        global _DESIGN_MEMO
        _DESIGN_MEMO = (model, frame, frame.key, di)
        if p.get("export_weights_and_biases"):
            # per-layer weight/bias frames in the DKV
            # (DeepLearningModelInfo export; client model.weights(i) /
            # .biases(i) fetch them by key)
            wkeys, bkeys = [], []
            for li, layer in enumerate(params_net):
                Wh = np.asarray(layer["W"], np.float64)
                wf = Frame.from_numpy(
                    {f"C{j + 1}": Wh[j] for j in range(Wh.shape[0])},
                    key=f"{model.key}_weights_{li}")
                bf = Frame.from_numpy(
                    {"C1": np.asarray(layer["b"], np.float64).ravel()},
                    key=f"{model.key}_biases_{li}")
                wkeys.append(wf.key)
                bkeys.append(bf.key)
            model.output["weights_keys"] = wkeys
            model.output["biases_keys"] = bkeys
        nscore = int(p.get("score_training_samples") or 0)
        score_mask = None
        if nscore and frame.nrows > nscore:
            # reference default: training metrics on a 10K sample
            rs = np.random.RandomState(
                (int(p["seed"]) if int(p["seed"]) >= 0 else 0xD1) & 0xFFFF)
            mw = np.zeros(frame.nrows_padded, np.float32)
            # randint draw, not choice(replace=False): the latter
            # materializes an O(n) permutation on the controller
            idx = np.unique(rs.randint(0, frame.nrows, 2 * nscore))[:nscore]
            mw[idx] = 1.0
            score_mask = mw
        model.training_metrics = model.model_performance(
            frame, mask_weights=score_mask)
        if category == ModelCategory.BINOMIAL:
            model.output["default_threshold"] = \
                model.training_metrics["max_f1_threshold"]
        if validation_frame is not None:
            nv = int(p.get("score_validation_samples") or 0)
            vmask = None
            if nv and validation_frame.nrows > nv:
                rs = np.random.RandomState(0xD2)
                vm = np.zeros(validation_frame.nrows_padded, np.float32)
                vidx = np.unique(rs.randint(0, validation_frame.nrows,
                                            2 * nv))[:nv]
                vm[vidx] = 1.0
                vmask = vm
            model.validation_metrics = model.model_performance(
                validation_frame, mask_weights=vmask)
        return model
