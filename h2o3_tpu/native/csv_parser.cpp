// Native CSV tokenizer — the water/parser hot loop, reimplemented for the
// TPU host runtime.
//
// Reference behavior being reproduced (not copied — the reference is Java):
//   - water/parser/CsvParser.java: per-byte tokenizer with quote handling
//   - water/parser/ParseDataset.java:253: chunk-parallel parse, each worker
//     tokenizes its byte range starting at the first line break past its
//     offset (cross-chunk line stitching)
//   - water/parser/ParseDataset.java:356-440: per-worker categorical
//     interning followed by global domain unification + code renumbering
//   - water/parser/ParseSetup.java: type guessing (numeric unless some
//     non-missing field fails numeric parse)
//
// Two passes over the buffer: pass 1 infers column types + row count
// (no allocation per field), pass 2 fills typed columns. Threads own
// contiguous row blocks; categorical levels intern into per-thread maps
// merged into one sorted global domain (sorted to match the Python
// fallback's pandas.factorize(sort=True) ordering).
//
// C ABI (ctypes-consumed; see native/__init__.py):
//   csv_parse(data, len, sep, header, nthreads) -> handle
//   csv_nrows/csv_ncols/csv_colname/csv_coltype
//   csv_numeric (double out, NaN=NA) / csv_codes (int32 out, -1=NA)
//   csv_card/csv_level, csv_free

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Field { const char* p; long n; };

static inline bool is_na_token(const char* p, long n) {
  if (n == 0) return true;
  if (n == 2 && (memcmp(p, "NA", 2) == 0 || memcmp(p, "na", 2) == 0))
    return true;
  if (n == 3 && (memcmp(p, "nan", 3) == 0 || memcmp(p, "NaN", 3) == 0 ||
                 memcmp(p, "NAN", 3) == 0)) return true;
  if (n == 4 && (memcmp(p, "null", 4) == 0 || memcmp(p, "NULL", 4) == 0))
    return true;
  return false;
}

static bool parse_double_slow(const char* p, long n, double* out) {
  // strtod needs NUL-termination; fields are short, copy to stack
  char buf[64];
  if (n <= 0 || n >= 63) return false;
  memcpy(buf, p, n);
  buf[n] = 0;
  char* end = nullptr;
  double v = strtod(buf, &end);
  while (end && *end == ' ') end++;
  if (end != buf + n) return false;
  *out = v;
  return true;
}

static const double kPow10[19] = {
  1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10, 1e11,
  1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18};

static inline bool parse_double(const char* p, long n, double* out) {
  // fast path for the overwhelmingly common [+-]ddd[.ddd] form — strtod
  // plus the stack copy costs ~2x the whole tokenize loop on a 1-core
  // host; exponents/hex/inf fall back to strtod
  if (n <= 0) return false;
  const char* start = p;
  const char* e = p + n;
  bool neg = false;
  if (*p == '-' || *p == '+') { neg = (*p == '-'); p++; }
  if (p == e) return false;
  unsigned long long ip = 0;
  int digits = 0;
  while (p < e && *p >= '0' && *p <= '9') {
    ip = ip * 10u + (unsigned)(*p - '0');
    p++; digits++;
  }
  long frac_digits = 0;
  if (p < e && *p == '.') {
    p++;
    while (p < e && *p >= '0' && *p <= '9') {
      ip = ip * 10u + (unsigned)(*p - '0');
      p++; digits++; frac_digits++;
    }
  }
  // 15-digit cutoff: the integer fits a double exactly and the single
  // divide rounds once, matching correctly-rounded strtod; 16-17 digit
  // values would round twice (integer conversion + divide) and can be
  // 1 ulp off, so they take the slow path
  if (p != e || digits == 0 || digits > 15)
    return parse_double_slow(start, n, out);
  double v = (double)ip;
  if (frac_digits) v /= kPow10[frac_digits];
  *out = neg ? -v : v;
  return true;
}

// Advance over one line from `p` (< limit), invoking cb(field_idx, ptr, len)
// per field. Returns pointer past the line terminator. Handles quoted
// fields with "" escapes; embedded newlines inside quotes are honored.
// dispatch table shared by every scan_line call (one core, one sep per
// parse): building a 256-entry table per LINE dominated short-row files
struct SpecialTable {
  bool special[256] = {};
  explicit SpecialTable(char sep) {
    special[(unsigned char)sep] = special['\n'] = special['\r'] =
        special['"'] = true;
  }
};

// cb(col, ptr, len, quoted): `quoted` distinguishes a quoted empty field
// ("" = empty STRING token, CsvParser.java addStrCol path) from a bare
// empty field (missing, addInvalidCol path)
template <typename F>
static const char* scan_line(const char* p, const char* limit, char sep,
                             const bool* special, F&& cb) {
  int col = 0;
  const char* fstart = p;
  bool quoted = false;
  const char* qstart = nullptr;
  std::string unq;  // only used when a quoted field has "" escapes
  bool has_esc = false;

  while (p < limit) {
    if (!quoted) {
      while (p < limit && !special[(unsigned char)*p]) p++;
      if (p >= limit) break;
    }
    char c = *p;
    if (quoted) {
      if (c == '"') {
        if (p + 1 < limit && p[1] == '"') { has_esc = true; p += 2; continue; }
        quoted = false;
      }
      p++;
      continue;
    }
    if (c == '"' && p == fstart) { quoted = true; qstart = p + 1; p++; continue; }
    if (c == sep || c == '\n' || c == '\r') {
      const char* fp = fstart;
      long fn = p - fstart;
      if (qstart) {  // strip quotes
        fp = qstart;
        fn = (p - 1) - qstart;           // closing quote
        if (fn < 0) fn = 0;
        if (has_esc) {                   // collapse "" -> "
          unq.clear();
          for (long i = 0; i < fn; i++) {
            unq.push_back(fp[i]);
            if (fp[i] == '"' && i + 1 < fn && fp[i + 1] == '"') i++;
          }
          fp = unq.data();
          fn = (long)unq.size();
        }
      }
      cb(col++, fp, fn, qstart != nullptr);
      if (c == sep) { p++; fstart = p; qstart = nullptr; has_esc = false; continue; }
      // line end
      if (c == '\r' && p + 1 < limit && p[1] == '\n') p++;
      return p + 1;
    }
    p++;
  }
  // final line without terminator (same quote/escape handling as above)
  const char* fp = qstart ? qstart : fstart;
  long fn = qstart ? (p - 1) - qstart : p - fstart;
  if (fn < 0) fn = 0;
  if (qstart && has_esc) {
    unq.clear();
    for (long i = 0; i < fn; i++) {
      unq.push_back(fp[i]);
      if (fp[i] == '"' && i + 1 < fn && fp[i + 1] == '"') i++;
    }
    fp = unq.data();
    fn = (long)unq.size();
  }
  cb(col++, fp, fn, qstart != nullptr);
  return p;
}

// first line start at/after `off` (0 stays 0); only safe for bodies with
// no '"' at all — quoted bodies go through next_record_start below
static const char* next_line_start(const char* base, const char* limit,
                                   long off) {
  if (off <= 0) return base;
  const char* p = base + off;
  while (p < limit && *p != '\n') p++;
  return p < limit ? p + 1 : limit;
}

// quote-parity-aware record start: first newline at/after `off` whose
// running double-quote parity (seeded with the parity of [base, base+off))
// is even, i.e. outside any RFC4180-quoted field — so a quoted field with
// an embedded newline or separator never straddles a worker boundary.
// "" escapes toggle parity twice and cancel out.
static const char* next_record_start(const char* base, const char* limit,
                                     long off, long parity) {
  if (off <= 0) return base;
  const char* p = base + off;
  while (p < limit) {
    if (*p == '"') parity ^= 1;
    else if (*p == '\n' && (parity & 1) == 0) return p + 1;
    p++;
  }
  return limit;
}

struct ColData {
  std::string name;
  int type = 0;                     // 0 numeric, 1 categorical
  std::vector<double> nums;
  std::vector<int> codes;           // global codes after merge
  std::vector<std::string> domain;  // sorted global domain
};

// Open-addressing intern map keyed by raw bytes: the std::unordered_map
// path constructed a std::string (malloc) per FIELD, which dominated
// pass 2 on categorical columns. Probes compare bytes against the
// owned level strings; allocation happens only on a NEW level.
struct InternMap {
  std::vector<int> slots;            // level index + 1; 0 = empty
  std::vector<std::string> levels;
  size_t mask = 0;

  void init(size_t cap = 64) {
    size_t n = 64;
    while (n < cap * 2) n <<= 1;
    slots.assign(n, 0);
    mask = n - 1;
  }
  static inline uint64_t hash_bytes(const char* p, long n) {
    uint64_t h = 1469598103934665603ull;               // FNV-1a
    for (long i = 0; i < n; i++) { h ^= (unsigned char)p[i]; h *= 1099511628211ull; }
    return h;
  }
  void grow() {
    std::vector<int> old = std::move(slots);
    slots.assign(old.size() * 2, 0);
    mask = slots.size() - 1;
    for (int v : old) {
      if (!v) continue;
      const std::string& s = levels[(size_t)(v - 1)];
      size_t i = hash_bytes(s.data(), (long)s.size()) & mask;
      while (slots[i]) i = (i + 1) & mask;
      slots[i] = v;
    }
  }
  inline int intern(const char* p, long n) {
    if (slots.empty()) init();
    size_t i = hash_bytes(p, n) & mask;
    while (true) {
      int v = slots[i];
      if (!v) {
        int code = (int)levels.size();
        levels.emplace_back(p, (size_t)n);
        slots[i] = code + 1;
        if (levels.size() * 2 > slots.size()) grow();
        return code;
      }
      const std::string& s = levels[(size_t)(v - 1)];
      if ((long)s.size() == n && memcmp(s.data(), p, (size_t)n) == 0)
        return v - 1;
      i = (i + 1) & mask;
    }
  }
};

struct Parsed {
  long nrows = 0;
  std::vector<ColData> cols;
};

struct ThreadChunk {
  const char* begin;
  const char* end;
  long nrows = 0;                    // estimate in sampled mode
  // pass-2 storage
  std::vector<std::vector<double>> nums;           // [ncols][rows]
  std::vector<std::vector<int>> local_codes;       // [ncols][rows]
  std::vector<InternMap> interns;                  // per col
  std::vector<char> col_is_str;                    // pass-1 flags
  std::vector<char> col_has_num;                   // saw a numeric token
  std::vector<char> col_has_qempty;                // saw a quoted ""
};

}  // namespace

extern "C" {

void* csv_parse(const char* data, long len, char sep, int header,
                int nthreads) {
  auto* out = new Parsed();
  const char* limit = data + len;
  const char* body = data;
  SpecialTable st(sep);

  // header row
  std::vector<std::string> names;
  if (header) {
    body = scan_line(data, limit, sep, st.special,
                     [&](int, const char* p, long n, bool) {
      names.emplace_back(p, (size_t)n);
    });
  }
  if (body >= limit) {  // empty body
    for (auto& nm : names) {
      out->cols.emplace_back();
      out->cols.back().name = nm;
    }
    return out;
  }

  if (nthreads < 1) nthreads = 1;
  long blen = limit - body;
  const bool has_quote = memchr(body, '"', (size_t)blen) != nullptr;
  std::vector<ThreadChunk> chunks((size_t)nthreads);
  std::vector<const char*> starts((size_t)nthreads + 1);
  starts[0] = body;
  starts[(size_t)nthreads] = limit;
  if (has_quote) {
    // quote parity at each naive boundary = prefix quote count (mod 2)
    std::vector<long> qpfx((size_t)nthreads + 1, 0);
    for (int t = 0; t < nthreads; t++) {
      const char* s = body + blen * t / nthreads;
      const char* e = body + blen * (t + 1) / nthreads;
      long c = 0;
      while (s < e) {
        const char* hit = (const char*)memchr(s, '"', (size_t)(e - s));
        if (!hit) break;
        c++;
        s = hit + 1;
      }
      qpfx[(size_t)t + 1] = qpfx[(size_t)t] + c;
    }
    for (int t = 1; t < nthreads; t++)
      starts[(size_t)t] = next_record_start(body, limit, blen * t / nthreads,
                                            qpfx[(size_t)t] & 1);
  } else {
    for (int t = 1; t < nthreads; t++)
      starts[(size_t)t] = next_line_start(body, limit, blen * t / nthreads);
  }
  for (int t = 0; t < nthreads; t++) {
    chunks[t].begin = starts[(size_t)t];
    chunks[t].end = starts[(size_t)t + 1];
  }

  size_t ncols_guess = names.size();
  if (!ncols_guess) {
    // count fields of first line
    size_t c = 0;
    scan_line(body, limit, sep, st.special,
              [&](int, const char*, long, bool) { c++; });
    ncols_guess = c;
  }
  const size_t NC = ncols_guess;

  // ---- pass 1: type inference (+ row counts on the full-scan path).
  // Small files scan everything. Large files infer from SAMPLE windows
  // only — the reference's ParseSetup.guessSetup likewise guesses from
  // sample chunks, and a later non-numeric token in a numeric-guessed
  // column degrades to NA exactly as the reference's parse does. This
  // halves the big-file wall time (the full pass 1 re-parsed every
  // field once just to learn the types).
  // quoted bodies always get the exact full scan: the sample windows are
  // aligned with the quote-blind next_line_start and could open inside a
  // quoted field, mis-typing columns
  const long FULL_SCAN_LIMIT = 4 << 20;
  const bool sampled = blen > FULL_SCAN_LIMIT && !has_quote;
  std::vector<std::thread> pool;
  std::vector<char> is_str(NC, 0), has_num(NC, 0), has_qe(NC, 0);
  long total_rows = 0;
  double est_row_bytes = 64.0;

  if (!sampled) {
    for (int t = 0; t < nthreads; t++) {
      pool.emplace_back([&, t]() {
        ThreadChunk& ch = chunks[t];
        ch.col_is_str.assign(NC, 0);
        ch.col_has_num.assign(NC, 0);
        ch.col_has_qempty.assign(NC, 0);
        const char* p = ch.begin;
        while (p < ch.end) {
          if (*p == '\n') { p++; continue; }                    // blank line
          if (*p == '\r' && p + 1 < ch.end && p[1] == '\n') { p += 2; continue; }
          p = scan_line(p, limit, sep, st.special,
                        [&](int col, const char* fp, long fn, bool q) {
            if ((size_t)col >= NC) return;
            if (fn == 0) {
              if (q) ch.col_has_qempty[col] = 1;  // quoted "": string token
              return;
            }
            if (ch.col_is_str[col] || is_na_token(fp, fn)) return;
            double v;
            if (!parse_double(fp, fn, &v)) ch.col_is_str[col] = 1;
            else ch.col_has_num[col] = 1;
          });
          ch.nrows++;
        }
      });
    }
    for (auto& th : pool) th.join();
    pool.clear();
    for (auto& ch : chunks) {
      total_rows += ch.nrows;
      for (size_t j = 0; j < NC; j++) {
        is_str[j] |= ch.col_is_str[j];
        has_num[j] |= ch.col_has_num[j];
        has_qe[j] |= ch.col_has_qempty[j];
      }
    }
  } else {
    // 8 windows of 256KB spread across the body, aligned to line starts
    const int NW = 8;
    const long WIN = 256 << 10;
    long sampled_rows = 0, sampled_bytes = 0;
    for (int wi = 0; wi < NW; wi++) {
      const char* wbeg = next_line_start(body, limit,
                                         (blen - WIN) * wi / (NW - 1));
      const char* wend = wbeg + WIN < limit ? wbeg + WIN : limit;
      const char* p = wbeg;
      while (p < wend) {
        if (*p == '\n') { p++; continue; }
        if (*p == '\r' && p + 1 < wend && p[1] == '\n') { p += 2; continue; }
        const char* line0 = p;
        p = scan_line(p, limit, sep, st.special,
                      [&](int col, const char* fp, long fn, bool q) {
          if ((size_t)col >= NC) return;
          if (fn == 0) {
            if (q) has_qe[col] = 1;
            return;
          }
          if (is_str[col] || is_na_token(fp, fn)) return;
          double v;
          if (!parse_double(fp, fn, &v)) is_str[col] = 1;
          else has_num[col] = 1;
        });
        sampled_rows++;
        sampled_bytes += (long)(p - line0);
      }
    }
    if (sampled_rows > 0)
      est_row_bytes = (double)sampled_bytes / (double)sampled_rows;
    for (auto& ch : chunks)
      ch.nrows = (long)((double)(ch.end - ch.begin) / est_row_bytes) + 16;
  }
  // a column whose only non-missing tokens are quoted "" is a string
  // column with the {""} domain (PreviewParseWriter.guessType: all-same-
  // string domain → T_CAT); any numeric token keeps it numeric
  // (nnums >= nstrings tie goes numeric) and "" degrades to NA there
  for (size_t j = 0; j < NC; j++)
    if (!is_str[j] && has_qe[j] && !has_num[j]) is_str[j] = 1;

  // ---- pass 2: typed fill with per-thread interning ----
  for (int t = 0; t < nthreads; t++) {
    pool.emplace_back([&, t]() {
      ThreadChunk& ch = chunks[t];
      ch.nums.assign(NC, {});
      ch.local_codes.assign(NC, {});
      ch.interns.assign(NC, {});
      for (size_t j = 0; j < NC; j++) {
        if (is_str[j]) ch.local_codes[j].reserve((size_t)ch.nrows);
        else ch.nums[j].reserve((size_t)ch.nrows);
      }
      const char* p = ch.begin;
      long filled = 0;
      while (p < ch.end) {
        if (*p == '\n') { p++; continue; }                      // blank line
        if (*p == '\r' && p + 1 < ch.end && p[1] == '\n') { p += 2; continue; }
        long before = filled;
        p = scan_line(p, limit, sep, st.special,
                      [&](int col, const char* fp, long fn, bool q) {
          if ((size_t)col >= NC) return;
          if (is_str[col]) {
            // quoted "" is the empty STRING, bare empty is missing
            if (is_na_token(fp, fn) && !(fn == 0 && q)) {
              ch.local_codes[col].push_back(-1);
              return;
            }
            ch.local_codes[col].push_back(ch.interns[col].intern(fp, fn));
          } else {
            double v;
            if (is_na_token(fp, fn) || !parse_double(fp, fn, &v))
              v = NAN;
            ch.nums[col].push_back(v);
          }
        });
        filled = before + 1;
        // short rows: pad missing trailing fields with NA
        for (size_t j = 0; j < NC; j++) {
          size_t want = (size_t)filled;
          if (is_str[j]) while (ch.local_codes[j].size() < want)
            ch.local_codes[j].push_back(-1);
          else while (ch.nums[j].size() < want)
            ch.nums[j].push_back(NAN);
        }
      }
      ch.nrows = filled;              // exact count (sampled mode needs it)
    });
  }
  for (auto& th : pool) th.join();

  // ---- merge: global sorted domains + code remap (the ParseDataset
  //      domain-unification step) ----
  total_rows = 0;
  for (auto& ch : chunks) total_rows += ch.nrows;
  out->nrows = total_rows;
  out->cols.resize(NC);
  for (size_t j = 0; j < NC; j++) {
    ColData& cd = out->cols[j];
    cd.name = j < names.size() ? names[j] : ("C" + std::to_string(j + 1));
    cd.type = is_str[j] ? 1 : 0;
    if (!is_str[j]) {
      cd.nums.reserve((size_t)total_rows);
      for (auto& ch : chunks)
        cd.nums.insert(cd.nums.end(), ch.nums[j].begin(), ch.nums[j].end());
    } else {
      std::vector<std::string> all;
      for (auto& ch : chunks)
        all.insert(all.end(), ch.interns[j].levels.begin(),
                   ch.interns[j].levels.end());
      std::sort(all.begin(), all.end());
      all.erase(std::unique(all.begin(), all.end()), all.end());
      std::unordered_map<std::string, int> global;
      global.reserve(all.size() * 2);
      for (size_t k = 0; k < all.size(); k++) global[all[k]] = (int)k;
      cd.domain = std::move(all);
      cd.codes.reserve((size_t)total_rows);
      for (auto& ch : chunks) {
        std::vector<int> remap(ch.interns[j].levels.size());
        for (size_t k = 0; k < remap.size(); k++)
          remap[k] = global[ch.interns[j].levels[k]];
        for (int c : ch.local_codes[j])
          cd.codes.push_back(c < 0 ? -1 : remap[(size_t)c]);
      }
    }
  }
  return out;
}

long csv_nrows(void* h) { return ((Parsed*)h)->nrows; }
int csv_ncols(void* h) { return (int)((Parsed*)h)->cols.size(); }
const char* csv_colname(void* h, int j) {
  return ((Parsed*)h)->cols[(size_t)j].name.c_str();
}
int csv_coltype(void* h, int j) { return ((Parsed*)h)->cols[(size_t)j].type; }
void csv_numeric(void* h, int j, double* outp) {
  auto& v = ((Parsed*)h)->cols[(size_t)j].nums;
  memcpy(outp, v.data(), v.size() * sizeof(double));
}
void csv_codes(void* h, int j, int* outp) {
  auto& v = ((Parsed*)h)->cols[(size_t)j].codes;
  memcpy(outp, v.data(), v.size() * sizeof(int));
}
int csv_card(void* h, int j) {
  return (int)((Parsed*)h)->cols[(size_t)j].domain.size();
}
const char* csv_level(void* h, int j, int k) {
  return ((Parsed*)h)->cols[(size_t)j].domain[(size_t)k].c_str();
}
void csv_free(void* h) { delete (Parsed*)h; }

}  // extern "C"
