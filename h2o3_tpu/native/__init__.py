"""Native runtime components (C++), loaded via ctypes.

The reference's native layer is the XGBoost JNI bridge
(h2o-extensions/xgboost, SURVEY §2.3); ours is a small C++ library for
the host-side hot paths that JAX/XLA doesn't cover — currently the
chunk-parallel CSV tokenizer (csv_parser.cpp, the water/parser role).

The shared object is compiled on first use with g++ (cached next to the
source, keyed by source mtime); every consumer must degrade gracefully
when no toolchain is available (`load_csv_parser()` returns None).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "csv_parser.cpp")
_SO = os.path.join(_DIR, "_csv_parser.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            log.warning("native csv build failed: %s",
                        r.stderr.decode()[:500])
            return False
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native csv build unavailable: %s", e)
        return False


def load_csv_parser() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native tokenizer; None on failure."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                if not _build():
                    _lib_failed = True
                    return None
            lib = ctypes.CDLL(_SO)
            lib.csv_parse.restype = ctypes.c_void_p
            lib.csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                      ctypes.c_char, ctypes.c_int,
                                      ctypes.c_int]
            lib.csv_nrows.restype = ctypes.c_long
            lib.csv_nrows.argtypes = [ctypes.c_void_p]
            lib.csv_ncols.restype = ctypes.c_int
            lib.csv_ncols.argtypes = [ctypes.c_void_p]
            lib.csv_colname.restype = ctypes.c_char_p
            lib.csv_colname.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.csv_coltype.restype = ctypes.c_int
            lib.csv_coltype.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.csv_numeric.restype = None
            lib.csv_numeric.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_double)]
            lib.csv_codes.restype = None
            lib.csv_codes.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.POINTER(ctypes.c_int)]
            lib.csv_card.restype = ctypes.c_int
            lib.csv_card.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.csv_level.restype = ctypes.c_char_p
            lib.csv_level.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_int]
            lib.csv_free.restype = None
            lib.csv_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except OSError as e:
            log.warning("native csv load failed: %s", e)
            _lib_failed = True
    return _lib


def parse_csv_bytes(data: bytes, sep: str = ",", header: bool = True,
                    nthreads: Optional[int] = None, decode: bool = True):
    """Tokenize a CSV buffer natively.

    Returns (columns dict name→ndarray, domains dict name→levels) or
    None when the native library is unavailable. Numeric columns come
    back float64 with NaN NAs. Categorical columns: with decode=True,
    object arrays of level strings (None for NA); with decode=False,
    raw int32 code arrays (-1 = NA) to feed straight into
    Frame.from_numpy(domains=...) without re-interning — the fast path.
    """
    lib = load_csv_parser()
    if lib is None:
        return None
    if nthreads is None:
        nthreads = min(os.cpu_count() or 4, 16)
    h = lib.csv_parse(data, len(data), sep.encode()[:1], int(header),
                      int(nthreads))
    if not h:
        return None
    try:
        n = lib.csv_nrows(h)
        nc = lib.csv_ncols(h)
        cols: Dict[str, np.ndarray] = {}
        domains: Dict[str, list] = {}
        for j in range(nc):
            name = lib.csv_colname(h, j).decode()
            if lib.csv_coltype(h, j) == 0:
                buf = np.empty(n, dtype=np.float64)
                lib.csv_numeric(h, j, buf.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_double)))
                cols[name] = buf
            else:
                codes = np.empty(n, dtype=np.int32)
                lib.csv_codes(h, j, codes.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int)))
                levels = [lib.csv_level(h, j, k).decode()
                          for k in range(lib.csv_card(h, j))]
                domains[name] = levels
                if decode:
                    vals = np.empty(n, dtype=object)
                    ok = codes >= 0
                    lv = np.asarray(levels, dtype=object)
                    vals[ok] = lv[codes[ok]]
                    vals[~ok] = None
                    cols[name] = vals
                else:
                    cols[name] = codes
        return cols, domains
    finally:
        lib.csv_free(h)
