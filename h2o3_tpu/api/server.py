"""REST API server — the water.api surface.

Reference: water/api/RequestServer.java:56 (route tree, dispatch at
:371-388), versioned Schema wire contract (water/api/Schema.java),
handlers per endpoint (CloudHandler, ParseHandler, ModelBuilderHandler,
JobsHandler, FramesHandler, RapidsHandler, ...). The reference serves
/3/* (stable) and /99/* (experimental: Rapids, AutoML); clients poll
GET /3/Jobs/{key} for async work.

This server keeps the same URI shapes and JSON field names that h2o-py
relies on (h2o-py/h2o/backend/connection.py), implemented on Python's
threading HTTP server — the web tier is control-plane only; all data
stays in device HBM, responses carry keys + small previews.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.core import cloud as cloud_mod
from h2o3_tpu.core.job import Job, list_jobs
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import all_algos, get_builder
from h2o3_tpu.models.model import Model
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.api")

ROUTES: List[Tuple[str, re.Pattern, Callable]] = []


def route(method: str, pattern: str):
    rx = re.compile("^" + pattern + "$")

    def deco(fn):
        ROUTES.append((method, rx, fn))
        return fn
    return deco


def _coerce(v: str) -> Any:
    """Form-value → python (the Schema fillFromParms coercion)."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    if s.lower() in ("null", "none", ""):
        return None
    if s.startswith("[") or s.startswith("{"):
        try:
            return json.loads(s.replace("'", '"'))
        except json.JSONDecodeError:
            pass
    try:
        f = float(s)
        return int(f) if f == int(f) and "." not in s and "e" not in s.lower() else f
    except ValueError:
        return s


def _frame_json(fr: Frame, rows: int = 10) -> dict:
    """Frame preview schema (water/api/schemas3/FrameV3)."""
    cols = []
    for n in fr.names:
        c = fr.col(n)
        preview = c.to_numpy()[:rows]
        if c.is_categorical and c.domain:
            dom = np.array(c.domain + [None], dtype=object)
            codes = np.asarray(c.data)[: min(rows, fr.nrows)].astype(np.int64)
            na = np.asarray(c.na_mask)[: min(rows, fr.nrows)]
            preview = dom[np.where(na, len(c.domain), codes)]
        cols.append({
            "label": n, "type": c.type,
            "domain": c.domain,
            "data": [None if (isinstance(x, float) and np.isnan(x)) else
                     (x.item() if isinstance(x, np.generic) else x)
                     for x in preview],
        })
    return {"frame_id": {"name": fr.key}, "rows": fr.nrows,
            "num_columns": fr.ncols, "column_names": fr.names,
            "columns": cols}


# ------------------------------------------------------------- handlers


@route("GET", "/3/Cloud")
def _cloud(params, body):
    info = cloud_mod.cluster_info()
    return {"version": info["version"], "cloud_name": info["cloud_name"],
            "cloud_size": info["cloud_size"],
            "cloud_healthy": info["cloud_healthy"],
            "consensus": True, "locked": True,
            "nodes": [{"h2o": d, "healthy": True}
                      for d in info["devices"]]}


@route("GET", "/3/Ping")
def _ping(params, body):
    return {"status": "running"}


@route("GET", "/3/Cleaner")
def _cleaner_status(params, body):
    """Spill/restore counters + HBM pressure (the Cleaner observability
    the reference exposes via water meters)."""
    from h2o3_tpu.core.cleaner import cleaner
    return cleaner.status()


@route("GET", "/3/About")
def _about(params, body):
    info = cloud_mod.cluster_info()
    return {"entries": [{"name": "Build version", "value": info["version"]},
                        {"name": "Backend", "value": info["platform"]}]}


@route("POST", "/3/ImportFiles")
def _import_files(params, body):
    path = params.get("path")
    return {"files": [path], "destination_frames": [path], "fails": [],
            "dels": []}


@route("POST", "/3/ParseSetup")
def _parse_setup(params, body):
    from h2o3_tpu.io.parser import parse_setup
    src = params.get("source_frames")
    if isinstance(src, list):
        src = src[0]
    src = str(src).strip('[]"')
    setup = parse_setup(src)
    return {"source_frames": [{"name": src}],
            "destination_frame": src.split("/")[-1] + ".hex",
            "column_names": setup["columns"],
            "column_types": [setup["types"][c] for c in setup["columns"]],
            "separator": ord(setup["separator"]),
            "check_header": 1 if setup["header"] else 0,
            "number_columns": len(setup["columns"])}


@route("POST", "/3/Parse")
def _parse(params, body):
    from h2o3_tpu.io.parser import import_file
    src = params.get("source_frames")
    if isinstance(src, list):
        src = src[0]
    src = str(src).strip('[]"')
    dest = params.get("destination_frame") or None
    job = Job(f"parse {src}", dest=dest)

    def _run(j):
        fr = import_file(src, destination_frame=dest)
        j.update(1.0, "parsed")
        return fr

    job.start(_run, background=True)
    return {"job": job.to_dict()}


@route("GET", "/3/Frames")
def _frames(params, body):
    out = []
    for k in DKV.keys():
        v = DKV.get(k)
        if isinstance(v, Frame):
            out.append({"frame_id": {"name": k}, "rows": v.nrows,
                        "num_columns": v.ncols})
    return {"frames": out}


@route("GET", r"/3/Frames/(?P<fid>[^/]+)/summary")
def _frame_summary(params, body, fid=None):
    fr = DKV.get(fid)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    summ = fr.summary()
    j = _frame_json(fr)
    for c in j["columns"]:
        s = summ.get(c["label"], {})
        c.update({k: (None if v is None or (isinstance(v, float) and np.isnan(v)) else v)
                  for k, v in s.items() if k in
                  ("min", "max", "mean", "sigma", "na_count", "zeros",
                   "cardinality", "type")})
    return {"frames": [j]}


@route("GET", r"/3/Frames/(?P<fid>[^/]+)")
def _frame_one(params, body, fid=None):
    fr = DKV.get(fid)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    rows = int(params.get("row_count") or 10)
    return {"frames": [_frame_json(fr, rows=rows)]}


@route("DELETE", r"/3/Frames/(?P<fid>[^/]+)")
def _frame_del(params, body, fid=None):
    DKV.remove(fid)
    return {}


@route("DELETE", r"/3/DKV/(?P<key>[^/]+)")
def _dkv_del(params, body, key=None):
    DKV.remove(key)
    return {}


@route("GET", "/3/ModelBuilders")
def _builders(params, body):
    out = {}
    for algo in all_algos():
        cls = get_builder(algo)
        defaults = getattr(cls, "DEFAULTS", {})
        out[algo] = {"algo": algo, "algo_full_name": cls.__name__,
                     "parameters": [
                         {"name": k, "default_value": defaults.get(k),
                          "type": type(defaults.get(k)).__name__}
                         for k in sorted(cls.accepted_params())]}
    return {"model_builders": out}


@route("POST", r"/3/ModelBuilders/(?P<algo>[^/]+)")
def _train(params, body, algo=None):
    cls = get_builder(algo)
    p = {k: _coerce(v) for k, v in params.items()}
    frame_key = p.pop("training_frame", None)
    y = p.pop("response_column", None)
    valid_key = p.pop("validation_frame", None)
    model_id = p.pop("model_id", None)
    ignored = p.pop("ignored_columns", None)
    fr = DKV.get(str(frame_key))
    if not isinstance(fr, Frame):
        raise KeyError(f"training_frame {frame_key} not found")
    vf = DKV.get(str(valid_key)) if valid_key else None
    known = cls.accepted_params()
    builder_params = {k: v for k, v in p.items() if k in known}
    if ignored is not None:
        builder_params["ignored_columns"] = ignored
    builder = cls(**builder_params)
    # the one ModelBuilder.train lifecycle (CV dispatch, run_time, logs)
    job = builder.train(fr, y=y, validation_frame=vf, background=True,
                        dest_key=model_id)
    return {"job": job.to_dict()}


@route("GET", r"/3/Jobs/(?P<key>[^/]+)")
def _job(params, body, key=None):
    j = DKV.get(key)
    if not isinstance(j, Job):
        raise KeyError(f"job {key} not found")
    d = j.to_dict()
    # h2o-py expects job.status in {CREATED,RUNNING,DONE,FAILED,CANCELLED}
    if j.status == "DONE" and j.result is not None and \
            isinstance(j.result, Model):
        d["dest"] = {"name": j.result.key, "type": "Key<Model>"}
    return {"jobs": [d]}


@route("POST", r"/3/Jobs/(?P<key>[^/]+)/cancel")
def _job_cancel(params, body, key=None):
    j = DKV.get(key)
    if isinstance(j, Job):
        j.cancel()
    return {}


@route("GET", "/3/Jobs")
def _jobs(params, body):
    return {"jobs": list_jobs()}


@route("GET", "/3/Models")
def _models(params, body):
    out = []
    for k in DKV.keys():
        v = DKV.get(k)
        if isinstance(v, Model):
            out.append(v.to_dict())
    return {"models": out}


@route("GET", r"/3/Models/(?P<mid>[^/]+)")
def _model_one(params, body, mid=None):
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    return {"models": [m.to_dict()]}


@route("DELETE", r"/3/Models/(?P<mid>[^/]+)")
def _model_del(params, body, mid=None):
    DKV.remove(mid)
    return {}


@route("POST", r"/3/Predictions/models/(?P<mid>[^/]+)/frames/(?P<fid>[^/]+)")
def _predict(params, body, mid=None, fid=None):
    m = DKV.get(mid)
    fr = DKV.get(fid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    dest = params.get("predictions_frame") or f"predictions_{mid}_{fid}"
    def _flag(name):
        return str(params.get(name, "")).lower() in ("1", "true", "yes")
    for flag, meth in (("leaf_node_assignment", "predict_leaf_node_assignment"),
                       ("predict_contributions", "predict_contributions")):
        if _flag(flag):
            fn = getattr(m, meth, None)
            if fn is None:
                raise ValueError(f"{flag} is not supported for "
                                 f"algo '{m.algo}'")
            preds = fn(fr)
            break
    else:
        preds = m.predict(fr)
    DKV.remove(preds.key)
    preds.key = str(dest)
    DKV.put(preds.key, preds)
    return {"predictions_frame": {"name": preds.key},
            "model_metrics": [{}]}


@route("GET", r"/3/Models/(?P<mid>[^/]+)/mojo")
def _model_mojo(params, body, mid=None):
    """Stream the MOJO zip (h2o-py download_mojo GET endpoint)."""
    from h2o3_tpu.genmodel.export import mojo_artifacts
    from h2o3_tpu.genmodel.mojo import mojo_bytes
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    return {"__bytes__": mojo_bytes(*mojo_artifacts(m)),
            "__ctype__": "application/zip"}


@route("GET", r"/3/Models\.java/(?P<mid>[^/]+)")
def _model_pojo(params, body, mid=None):
    """Generated-source scorer download (water/api Models.java POJO
    endpoint shape; a stdlib-Python module here)."""
    from h2o3_tpu.genmodel.pojo import pojo_source
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    src = pojo_source(m, modname=str(mid))
    return {"__bytes__": src.encode(),
            "__ctype__": "text/plain; charset=utf-8"}


@route("POST", r"/3/ModelMetrics/models/(?P<mid>[^/]+)/frames/(?P<fid>[^/]+)")
def _model_metrics(params, body, mid=None, fid=None):
    """Score a frame and return its metrics (water/api/ModelMetricsHandler
    — the model_performance(test_data) wire call)."""
    m = DKV.get(mid)
    fr = DKV.get(fid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    mm_ = m.model_performance(fr)
    d = mm_.to_dict() if hasattr(mm_, "to_dict") else dict(mm_ or {})
    return {"model_metrics": [d]}


@route("POST", "/3/PartialDependence")
def _pdp(params, body):
    """water/api/PartialDependenceHandler: grid sweep per feature."""
    m = DKV.get(str(params.get("model_id")))
    fr = DKV.get(str(params.get("frame_id")))
    if not isinstance(m, Model):
        raise KeyError(f"model {params.get('model_id')} not found")
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {params.get('frame_id')} not found")
    cols = _coerce(params.get("cols") or "[]")
    if isinstance(cols, str):
        cols = [cols]
    nbins = int(params.get("nbins") or 20)
    from h2o3_tpu.ml.explain import partial_dependence
    return {"partial_dependence_data": partial_dependence(
        m, fr, cols or m.output.get("names", []), nbins=nbins)}


@route("POST", "/99/Rapids")
def _rapids_ep(params, body):
    from h2o3_tpu.rapids import rapids
    expr = params.get("ast") or ""
    try:
        val = rapids(expr)
    except Exception as e:
        return {"error": str(e)}
    if isinstance(val, Frame):
        return {"key": {"name": val.key},
                "frame": _frame_json(val, rows=5)}
    if isinstance(val, (int, float)):
        return {"scalar": float(val)}
    return {"string": str(val)}


@route("POST", "/99/AutoMLBuilder")
def _automl(params, body):
    from h2o3_tpu.automl import H2OAutoML
    p = {k: _coerce(v) for k, v in params.items()}
    # h2o-py ships nested specs (h2o-py/h2o/automl/_estimator.py):
    # build_control{project_name,nfolds,stopping_criteria{...}},
    # input_spec{training_frame,response_column}, build_models{*_algos}
    ctl = p.get("build_control") or {}
    if isinstance(ctl, str):
        ctl = json.loads(ctl)
    crit = ctl.get("stopping_criteria") or {}
    inp = p.get("input_spec") or {}
    if isinstance(inp, str):
        inp = json.loads(inp)
    bm = p.get("build_models") or {}
    if isinstance(bm, str):
        bm = json.loads(bm)
    frame_key = inp.get("training_frame") or p.get("training_frame")
    y = inp.get("response_column") or p.get("response_column")
    if isinstance(y, dict):
        y = y.get("column_name")
    fr = DKV.get(str(frame_key))
    ignored = inp.get("ignored_columns")
    x_cols = ([n for n in fr.names if n not in set(ignored) and n != y]
              if ignored and isinstance(fr, Frame) else None)
    aml = H2OAutoML(
        max_models=int(crit.get("max_models") or p.get("max_models") or 0),
        max_runtime_secs=float(crit.get("max_runtime_secs")
                               or p.get("max_runtime_secs") or 3600),
        seed=int(crit.get("seed") or p.get("seed") or -1),
        nfolds=int(next(v for v in (ctl.get("nfolds"), p.get("nfolds"), 5)
                        if v is not None)),
        include_algos=bm.get("include_algos"),
        exclude_algos=bm.get("exclude_algos"),
        project_name=ctl.get("project_name") or p.get("project_name"))
    job = Job("automl", dest=aml.project_name)

    def _run(j):
        aml.train(y=y, training_frame=fr, x=x_cols)
        j.update(1.0, "done")
        DKV.put(f"leaderboard_{aml.project_name}_result", aml)
        return aml

    job.start(_run, background=True)
    return {"job": job.to_dict(), "project_name": aml.project_name}


@route("GET", r"/99/Leaderboards/(?P<project>[^/]+)")
def _leaderboard(params, body, project=None):
    aml = DKV.get(f"leaderboard_{project}_result")
    if aml is None:
        raise KeyError(f"automl project {project} not found")
    return {"project_name": project,
            "models": [m.key for m in aml.leaderboard.sorted_models()],
            "leaderboard_table": aml.leaderboard.as_table()}


@route("GET", r"/flow(/index\.html)?/?")
def _flow(params, body, **_):
    """The Flow notebook UI (h2o-web role) — served from the node at
    /flow/index.html like the reference."""
    from h2o3_tpu.api.flow import FLOW_HTML
    return {"__html__": FLOW_HTML}


@route("GET", "/")
def _index(params, body):
    """Minimal landing page (the h2o-web Flow-serving role: the node
    itself answers a browser with a live cluster view)."""
    info = cloud_mod.cluster_info()
    frames = sum(1 for k in DKV.keys() if isinstance(DKV.get(k), Frame))
    models = sum(1 for k in DKV.keys() if isinstance(DKV.get(k), Model))
    html = f"""<!doctype html><html><head><title>h2o3-tpu</title></head>
<body style="font-family:monospace">
<h2>h2o3-tpu cloud '{info["cloud_name"]}'</h2>
<p>{info["cloud_size"]} device(s) on {info["platform"]} —
healthy: {info["cloud_healthy"]}</p>
<p>{frames} frame(s), {models} model(s),
{len(all_algos())} algorithms registered</p>
<p><a href="/flow/index.html"><b>Open Flow (notebook UI)</b></a></p>
<p>REST: <a href="/3/Cloud">/3/Cloud</a> ·
<a href="/3/Frames">/3/Frames</a> ·
<a href="/3/Models">/3/Models</a> ·
<a href="/3/ModelBuilders">/3/ModelBuilders</a> ·
<a href="/3/Jobs">/3/Jobs</a> ·
<a href="/3/Timeline">/3/Timeline</a> ·
<a href="/3/SelfBench">/3/SelfBench</a></p>
</body></html>"""
    return {"__html__": html}


@route("GET", "/3/WaterMeterCpuTicks")
def _water_meter(params, body):
    """Per-core cpu tick counters (water/util/WaterMeterCpuTicks.java).
    Wire layout per LinuxProcFileReader: [user+nice, system, other(io),
    idle]."""
    ticks = []
    try:
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("cpu") and line[3].isdigit():
                    p = line.split()   # cpuN user nice system idle iowait…
                    ticks.append([int(p[1]) + int(p[2]), int(p[3]),
                                  int(p[5]), int(p[4])])
    except OSError:
        pass
    return {"cpu_ticks": ticks}


@route("GET", "/3/Timeline")
def _timeline(params, body):
    from h2o3_tpu.utils.timeline import snapshot
    return {"events": snapshot(last=params.get("last"))}


@route("GET", "/3/JStack")
def _jstack(params, body):
    """Thread stack dump (water/api/JStackHandler role)."""
    import sys
    import traceback
    frames = sys._current_frames()
    threads = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        out.append({"thread": threads.get(tid, str(tid)),
                    "stack": traceback.format_stack(frame)})
    return {"traces": out}


@route("GET", "/3/SelfBench")
def _selfbench(params, body):
    """Node capability probes (water/init/{Linpack,MemoryBandwidth,
    NetworkBench} role)."""
    from h2o3_tpu.core.selfcheck import run_self_bench
    return run_self_bench()


@route("GET", "/3/Logs/download")
def _logs(params, body):
    return {"log": ""}


@route("POST", "/3/Shutdown")
def _shutdown(params, body):
    threading.Thread(target=lambda: _SERVER and _SERVER.shutdown(),
                     daemon=True).start()
    return {}


# ------------------------------------------------------------- plumbing


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # route to our logger
        log.debug("http: " + fmt, *args)

    def _dispatch(self, method: str):
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        params: Dict[str, str] = {
            k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        body = raw.decode("utf-8", "replace")
        ctype = self.headers.get("Content-Type", "")
        if "json" in ctype and body:
            try:
                params.update(json.loads(body))
            except json.JSONDecodeError:
                pass
        elif body:
            params.update({k: v[0]
                           for k, v in urllib.parse.parse_qs(body).items()})
        from h2o3_tpu.utils.timeline import record as _tl_record
        _tl_record("rest", f"{method} {path}")
        for m, rx, fn in ROUTES:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                try:
                    out = fn(params, body, **match.groupdict())
                    code = 200
                except KeyError as e:
                    out = {"__meta": {"schema_type": "H2OError"},
                           "error_url": path, "msg": str(e),
                           "exception_msg": str(e)}
                    code = 404
                except Exception as e:   # noqa: BLE001 - request boundary
                    log.exception("handler error on %s %s", method, path)
                    out = {"__meta": {"schema_type": "H2OError"},
                           "error_url": path, "msg": str(e),
                           "exception_msg": str(e)}
                    code = 500
                if isinstance(out, dict) and "__bytes__" in out:
                    payload = out["__bytes__"]
                    ctype = out.get("__ctype__", "application/octet-stream")
                elif isinstance(out, dict) and "__html__" in out:
                    payload = out["__html__"].encode()
                    ctype = "text/html; charset=utf-8"
                else:
                    payload = json.dumps(out, default=_json_default).encode()
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return
        self.send_response(404)
        payload = json.dumps({"msg": f"no route {method} {path}"}).encode()
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


def _json_default(o):
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, float) and np.isnan(o):
        return None
    return str(o)


_SERVER: Optional[ThreadingHTTPServer] = None
_THREAD: Optional[threading.Thread] = None


def start_server(port: int = 54321, background: bool = True) -> int:
    """Start the REST server (water.api.RequestServer.start).

    Returns the bound port (0 picks an ephemeral port)."""
    global _SERVER, _THREAD
    _SERVER = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    actual = _SERVER.server_address[1]
    log.info("REST server on http://127.0.0.1:%d (/3, /99)", actual)
    if background:
        _THREAD = threading.Thread(target=_SERVER.serve_forever, daemon=True)
        _THREAD.start()
    else:
        _SERVER.serve_forever()
    return actual


def stop_server():
    global _SERVER
    if _SERVER is not None:
        _SERVER.shutdown()
        _SERVER = None
