"""REST API server — the water.api surface.

Reference: water/api/RequestServer.java:56 (route tree, dispatch at
:371-388), versioned Schema wire contract (water/api/Schema.java),
handlers per endpoint (CloudHandler, ParseHandler, ModelBuilderHandler,
JobsHandler, FramesHandler, RapidsHandler, ...). The reference serves
/3/* (stable) and /99/* (experimental: Rapids, AutoML); clients poll
GET /3/Jobs/{key} for async work.

This server keeps the same URI shapes and JSON field names that h2o-py
relies on (h2o-py/h2o/backend/connection.py), implemented on Python's
threading HTTP server — the web tier is control-plane only; all data
stays in device HBM, responses carry keys + small previews.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.core import cloud as cloud_mod
from h2o3_tpu.core import request_ctx
from h2o3_tpu.core.job import Job, list_jobs
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import all_algos, get_builder
from h2o3_tpu.models.model import Model
from h2o3_tpu.core.durability import DataLostError
from h2o3_tpu.serving.batcher import BatcherDraining, QueueSaturated
from h2o3_tpu.serving.fleet import FleetUnavailable
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.api")
_RMALL_COUNT = 0   # remove_all calls since boot (jit-cache clear cadence)

ROUTES: List[Tuple[str, re.Pattern, Callable]] = []


def route(method: str, pattern: str):
    rx = re.compile("^" + pattern + "$")

    def deco(fn):
        ROUTES.append((method, rx, fn))
        return fn
    return deco


def _register_metadata_routes():
    from h2o3_tpu.api import metadata
    metadata.register(route)


def _coerce(v: str) -> Any:
    """Form-value → python (the Schema fillFromParms coercion)."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    if s.lower() in ("null", "none", ""):
        return None
    if s.startswith("[") or s.startswith("{"):
        try:
            return json.loads(s.replace("'", '"'))
        except json.JSONDecodeError:
            pass
    try:
        f = float(s)
        return int(f) if f == int(f) and "." not in s and "e" not in s.lower() else f
    except ValueError:
        return s


def _unquote(s):
    """Strip the client-side quoted() wrapper (h2o-py sends frame ids and
    type names wrapped in literal double quotes)."""
    if isinstance(s, str) and len(s) >= 2 and s[0] == s[-1] and s[0] in "\"'":
        return s[1:-1]
    return s


_WIRE_TYPES = {"numeric": "real", "categorical": "enum",
               "time": "time", "string": "string", "uuid": "uuid"}


def _col_json(fr: Frame, name: str, row_offset: int, rows: int,
              summ: Optional[dict] = None) -> dict:
    """ColV3 wire shape (water/api/schemas3/FrameV3.java ColV3).

    The real h2o-py pops __meta / domain_cardinality / string_data
    unconditionally (h2o-py/h2o/expr.py:381-385), so those keys are
    mandatory."""
    c = fr.col(name)
    lo, hi = row_offset, min(row_offset + rows, fr.nrows)
    wire_type = _WIRE_TYPES.get(c.type, c.type)
    data, string_data, domain = None, None, None
    if c.type in ("string", "uuid"):
        vals = c.host_view()[lo:hi]
        string_data = [None if v is None else str(v) for v in vals]
        data = []
    elif c.is_categorical:
        domain = list(c.domain or [])
        # cached host view (prefetch_host batched the fetch): f64 codes
        # with NaN at NA. NA cells ride as JSON NaN (json.dumps
        # allow_nan): the client probes math.isnan(cell) before
        # indexing the domain (h2o-py/h2o/expr.py:416 _tabulate)
        codes = c.host_view()[lo:hi]
        data = [float("nan") if np.isnan(v) else int(v) for v in codes]
    else:
        vals = np.asarray(c.host_view()[lo:hi], np.float64)
        if wire_type == "real" and vals.size and \
                np.all(np.isnan(vals) | (vals == np.round(vals))) and \
                np.nanmax(np.abs(vals), initial=0) < 2**53:
            wire_type = "int"
        data = [float("nan") if np.isnan(v) else
                (int(v) if wire_type in ("int", "time") else float(v))
                for v in vals]
    try:
        s = (summ if summ is not None else fr.summary()).get(name, {})
    except Exception:
        s = {}
    mean = s.get("mean")
    sigma = s.get("sigma")
    mins = [s.get("min")] if s.get("min") is not None else []
    maxs = [s.get("max")] if s.get("max") is not None else []
    return {
        "__meta": {"schema_version": 3, "schema_name": "ColV3",
                   "schema_type": "Vec"},
        "label": name, "type": wire_type,
        "missing_count": int(s.get("na_count", 0) or 0),
        "zero_count": int(s.get("zero_count", 0) or 0),
        "positive_infinity_count": 0, "negative_infinity_count": 0,
        "mins": [None if (isinstance(v, float) and np.isnan(v)) else v
                 for v in mins],
        "maxs": [None if (isinstance(v, float) and np.isnan(v)) else v
                 for v in maxs],
        "mean": None if mean is None or (isinstance(mean, float)
                                         and np.isnan(mean)) else mean,
        "sigma": None if sigma is None or (isinstance(sigma, float)
                                           and np.isnan(sigma)) else sigma,
        "persist_type": "HBM", "precision": -1,
        "domain": domain,
        "domain_cardinality": len(domain) if domain else 0,
        "data": data, "string_data": string_data,
        "histogram_bins": None, "histogram_base": 0,
        "histogram_stride": 0, "percentiles": None,
    }


def _frame_json(fr: Frame, rows: int = 10, row_offset: int = 0) -> dict:
    """FrameV3 wire shape (water/api/schemas3/FrameV3.java)."""
    rows = min(rows, fr.nrows)
    # one batched host fetch for every column's preview data — a
    # 1000-column frame (pyunit_create_frame) otherwise pays a blocking
    # tunnel round trip per column
    from h2o3_tpu.frame.column import prefetch_host
    prefetch_host([fr.col(n) for n in fr.names])
    try:
        summ = fr.summary()
    except Exception:
        summ = {}
    cols = [_col_json(fr, n, row_offset, rows, summ) for n in fr.names]
    return {"__meta": {"schema_version": 3, "schema_name": "FrameV3",
                       "schema_type": "Frame"},
            "frame_id": {"name": fr.key, "type": "Key<Frame>",
                         "URL": f"/3/Frames/{fr.key}"},
            "byte_size": 0, "is_text": False,
            "row_offset": row_offset, "row_count": rows,
            "column_offset": 0, "column_count": fr.ncols,
            "full_column_count": fr.ncols, "total_column_count": fr.ncols,
            "checksum": 0,
            "rows": fr.nrows, "num_columns": fr.ncols,
            "default_percentiles": [0.001, 0.01, 0.1, 0.25, 0.333, 0.5,
                                    0.667, 0.75, 0.9, 0.99, 0.999],
            "column_names": fr.names,
            "columns": cols, "compatible_models": [],
            "chunk_summary": None, "distribution_summary": None}


# ------------------------------------------------------------- handlers


def _local_sched_snapshot(pidx) -> dict:
    """This node's live scheduler counters for /3/Cloud — only for the
    serving process itself; peers without a published ``sched`` field
    (snapshot predates the scheduler) show ``{}``."""
    try:
        import jax
        if int(pidx) != jax.process_index():
            return {}
        from h2o3_tpu.parallel import scheduler
        return scheduler.snapshot()
    except Exception:   # noqa: BLE001 - occupancy is best-effort
        return {}


@route("GET", "/3/Cloud")
def _cloud(params, body):
    """Cluster status (water/api/CloudHandler, schemas3/CloudV3.java).

    ``healthy``/``last_ping`` per node come from the heartbeat monitor
    (core/heartbeat.py) when it runs — the HeartBeatThread → CloudV3
    wiring of the reference — and degrade to the formation-time verdict
    when it does not (single-process cloud, monitor off)."""
    import os
    info = cloud_mod.cluster_info()
    hb = info.get("heartbeat", {})
    peers = hb.get("peers", {})
    now = int(__import__("time").time() * 1000)
    mesh_devs = list(cloud_mod.mesh_mod.get_mesh().devices.flat)
    # published identity + per-node load from the cluster fan-in
    # snapshots (telemetry/cluster.py) — replaces the old default-0
    # process_index attribute guess; single-process clouds still get
    # their own (live) summary
    owner_map, summaries = {}, {}
    try:
        from h2o3_tpu.telemetry import cluster as _cluster
        col = _cluster.collect()
        owner_map = _cluster.device_owner_map(col)
        summaries = _cluster.node_summaries(col)
    except Exception:   # noqa: BLE001 - summaries are best-effort
        pass
    from h2o3_tpu.telemetry import roofline as _roofline
    peaks = _roofline.device_peaks()
    # this node's memory truth (core/memgov.py) — the fallback when a
    # peer's published snapshot predates the hbm field or is absent
    from h2o3_tpu.core.memgov import governor as _governor
    _governor.refresh_gauges()
    local_hbm = _governor.snapshot()
    nodes = []
    for i, d in enumerate(info["devices"]):
        # device i belongs to a process: published identity first, the
        # device's own process_index attribute as the fallback
        pidx = owner_map.get(
            d, getattr(mesh_devs[i], "process_index", 0))
        pst = peers.get(str(pidx))
        healthy = bool(pst["healthy"]) if pst else info["cloud_healthy"]
        last_ping = (int(pst["last_seen"] * 1000) if pst else now)
        summ = summaries.get(int(pidx), {})
        hbm = summ.get("hbm") or {}
        if not hbm:
            hbm = {"budget": local_hbm["budget_bytes"],
                   "in_use": local_hbm["bytes_in_use"],
                   "free": local_hbm["free_bytes"],
                   "spilled": local_hbm["spilled_bytes"]}
        nodes.append({
            "h2o": d, "ip_port": f"127.0.0.1:{54321 + i}",
            "healthy": healthy and not summ.get("stale", False),
            "last_ping": last_ping,
            "pid": summ.get("pid", os.getpid()),
            "num_cpus": os.cpu_count(),
            "cpus_allowed": os.cpu_count(), "nthreads": os.cpu_count(),
            "sys_load": 0.0, "my_cpu_pct": 0, "sys_cpu_pct": 0,
            # real memory truth from the governor: free/max against the
            # HBM budget, swap = bytes the Cleaner holds on ice
            "mem_value_size": hbm.get("in_use", 0), "pojo_mem": 0,
            "free_mem": hbm.get("free", 0),
            "max_mem": hbm.get("budget", 0),
            "swap_mem": hbm.get("spilled", 0),
            "num_keys": len(list(DKV.keys())),
            "free_disk": 0, "max_disk": 0, "rpcs_active": 0,
            "fjthrds": [], "fjqueue": [], "tcps_active": 0,
            "open_fds": -1,
            "gflops": peaks["flops"] / 1e9,
            "mem_bw": peaks["hbm_bytes_per_s"],
            "process_index": int(pidx),
            "metrics_summary": {
                "jobs_inflight": summ.get("jobs_inflight", 0),
                "last_publish_age_s": summ.get("last_publish_age_s", 0.0),
                "peak_hbm": summ.get("peak_hbm", 0),
                "stale": summ.get("stale", False),
            },
            # work-scheduler occupancy (parallel/scheduler.py): leases
            # this host currently holds plus lifetime item counters —
            # peers via their published snapshot, this node live
            "sched": summ.get("sched") or _local_sched_snapshot(pidx),
        })
    return {"__meta": {"schema_version": 3, "schema_name": "CloudV3",
                       "schema_type": "Iced"},
            "version": info["version"], "branch_name": "tpu-native",
            "last_commit_hash": "", "describe": "h2o3-tpu",
            "compiled_by": "h2o3-tpu", "compiled_on": "",
            "build_number": "0", "build_age": "0 days",
            "build_too_old": False, "node_idx": 0,
            "cloud_name": info["cloud_name"],
            "cloud_size": info["cloud_size"],
            "cloud_uptime_millis": info["cloud_uptime_ms"],
            "cloud_internal_timezone": "UTC",
            "datafile_parser_timezone": "UTC",
            "cloud_healthy": info["cloud_healthy"],
            "bad_nodes": sum(1 for n in nodes if not n["healthy"]),
            "consensus": info["cloud_healthy"],
            "locked": True, "is_client": False,
            "heartbeat": hb,
            "nodes": nodes, "internal_security_enabled": False,
            "web_ip": "127.0.0.1"}


@route("GET", "/3/Ping")
def _ping(params, body):
    return {"status": "running"}


_SESSIONS: set = set()


@route("POST", "/4/sessions")
def _new_session(params, body):
    """Issue a Rapids session id (water/api/InitIDHandler)."""
    import uuid
    sid = "_sid_" + uuid.uuid4().hex[:12]
    _SESSIONS.add(sid)
    return {"__meta": {"schema_version": 4, "schema_name": "SessionIdV4",
                       "schema_type": "Iced"},
            "session_key": sid}


@route("POST", "/3/InitID")
def _init_id(params, body):
    import uuid
    sid = "_sid_" + uuid.uuid4().hex[:12]
    _SESSIONS.add(sid)
    return {"__meta": {"schema_version": 3, "schema_name": "InitIDV3",
                       "schema_type": "Iced"},
            "session_key": sid}


@route("DELETE", r"/4/sessions/(?P<sid>[^/]+)")
def _end_session(params, body, sid=None):
    _SESSIONS.discard(sid)
    return {"session_key": sid}


@route("GET", "/3/Capabilities")
def _capabilities(params, body):
    caps = [{"name": n} for n in
            ("AutoML", "Algos", "TargetEncoder", "TPU")]
    return {"capabilities": caps}


@route("GET", "/3/Capabilities/Core")
def _capabilities_core(params, body):
    return {"capabilities": [{"name": "TPU"}, {"name": "Algos"}]}


@route("GET", "/3/Capabilities/API")
def _capabilities_api(params, body):
    return {"capabilities": [{"name": "AutoML"},
                             {"name": "TargetEncoder"}]}


@route("GET", "/3/Cleaner")
def _cleaner_status(params, body):
    """Spill/restore counters + HBM pressure (the Cleaner observability
    the reference exposes via water meters)."""
    from h2o3_tpu.core.cleaner import cleaner
    return cleaner.status()


@route("GET", "/3/About")
def _about(params, body):
    info = cloud_mod.cluster_info()
    return {"entries": [{"name": "Build version", "value": info["version"]},
                        {"name": "Backend", "value": info["platform"]}]}


def _wire_list(src) -> List[str]:
    """Decode h2o-py's stringify_list wire format: '[a,b]' where items
    may or may not be individually double-quoted (shared_utils.py:171 —
    bare for paths, quoted() for frame ids)."""
    if isinstance(src, list):
        items = src
    else:
        s = str(src).strip()
        if s.startswith("[") and s.endswith("]"):
            s = s[1:-1]
        items = s.split(",") if s else []
    out = []
    for it in items:
        if isinstance(it, dict):
            it = it.get("name")
        out.append(_unquote(str(it).strip()))
    return out


def _wire_nested_list(src):
    """Decode stringify_list of a list-of-lists — the na_strings wire
    format: '[["NA","x"],[],[""]]' with each item quoted() by the client
    (h2o-py/h2o/h2o.py:925 builds it, shared_utils.py:171 stringifies).
    Returns a list of per-column string lists, or None if unparseable.
    A flat list (h2o-py list-form semantics: same tokens for EVERY
    column) returns [tokens] and the caller broadcasts; null/None per
    column means 'no NA strings for that column'."""
    def _norm(lst):
        if not isinstance(lst, list):
            return None
        if all(x is None or isinstance(x, str) for x in lst) and \
                not any(isinstance(x, list) for x in lst):
            flat = [_unquote(x) for x in lst if isinstance(x, str)]
            return [flat] if flat else None
        out = []
        for inner in lst:
            if inner is None:
                out.append([])
            elif isinstance(inner, list):
                out.append([_unquote(str(x)) for x in inner
                            if x is not None])
            else:
                out.append([_unquote(str(inner))])
        return out
    if isinstance(src, list):
        return _norm(src)
    s = str(src).strip()
    try:
        import json as _json
        parsed = _json.loads(s)
        if isinstance(parsed, list):
            return _norm(parsed)
    except ValueError:
        pass                      # stringify_list fallback below
    if not (s.startswith("[") and s.endswith("]")):
        return None
    s, out, i, n = s[1:-1], [], 0, len(s) - 2
    while i < n:
        if s[i] != "[":
            i += 1
            continue
        j, inq = i + 1, False
        while j < n and (inq or s[j] != "]"):
            if s[j] == '"':
                inq = not inq
            j += 1
        inner = s[i + 1:j]
        items, cur, inq = [], [], False
        for ch in inner:
            if ch == '"':
                inq = not inq
                cur.append(ch)
            elif ch == "," and not inq:
                items.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
        if cur or items:
            items.append("".join(cur))
        out.append([_unquote(t.strip()) for t in items])
        i = j + 1
    return out


def _wire_map(s: str) -> dict:
    """Decode stringify_dict_as_map output: near-JSON where bare words
    (enum/string values like bernoulli) arrive unquoted
    (h2o-py/h2o/utils/shared_utils.py:167)."""
    s = s.replace("'", '"')
    # python-repr literals (h2o-py stringifies dicts with repr(): the
    # kmeans-grid pyunit ships standardize: [True, False]) must become
    # JSON booleans, NOT get caught by the bare-identifier quoting below
    # — a wire "False" string breaks expect_model_param's coercion
    # quote guards confine the rewrite to BARE literals — a quoted
    # string value that happens to be "True"/"None" must survive intact
    s = re.sub(r'(?<!")\bTrue\b(?!")', "true", s)
    s = re.sub(r'(?<!")\bFalse\b(?!")', "false", s)
    s = re.sub(r'(?<!")\bNone\b(?!")', "null", s)
    # quote bare identifiers that aren't JSON literals
    s = re.sub(
        r'(?<![\w"])(?!true\b|false\b|null\b)'
        r'([A-Za-z_][A-Za-z0-9_.\-]*)(?!["\w])(?=\s*[,\]\}])',
        r'"\1"', s)
    return json.loads(s)


def _src_list(params) -> List[str]:
    """source_frames / paths param → clean list of path strings."""
    src = params.get("source_frames") or params.get("paths") or \
        params.get("path")
    return _wire_list(src)


@route("POST", "/3/ImportFiles")
def _import_files(params, body):
    path = _unquote(params.get("path"))
    import os
    if not os.path.exists(path) and not any(c in path for c in "*?["):
        return {"files": [], "destination_frames": [], "fails": [path],
                "dels": []}
    return {"files": [path], "destination_frames": [path], "fails": [],
            "dels": []}


@route("POST", "/3/ImportFilesMulti")
def _import_files_multi(params, body):
    """Multi-path import (water/api/ImportFilesHandler) — the real
    h2o-py always goes through this (h2o-py/h2o/h2o.py:336)."""
    import os
    paths = _src_list(params)
    files, fails = [], []
    for p in paths:
        if os.path.exists(p) or any(c in p for c in "*?["):
            files.append(p)
        else:
            fails.append(p)
    return {"files": files, "destination_frames": files, "fails": fails,
            "dels": []}


# ParseSetupV3 column-type enum names (water/parser/ParseSetup)
_SETUP_TYPES = {"numeric": "Numeric", "categorical": "Enum",
                "string": "String", "time": "Time"}
_SETUP_TYPES_BACK = {"numeric": "numeric", "enum": "categorical",
                     "factor": "categorical", "categorical": "categorical",
                     "string": "string", "time": "time", "int": "numeric",
                     "real": "numeric", "float": "numeric",
                     "uuid": "string"}


@route("POST", "/3/ParseSetup")
def _parse_setup(params, body):
    from h2o3_tpu.io.parser import parse_setup
    srcs = _src_list(params)
    ch = params.get("check_header")
    hint = None
    if ch is not None:
        ch = int(float(ch))
        hint = True if ch == 1 else (False if ch == -1 else None)
    setup = parse_setup(srcs[0], header=hint)
    dest = srcs[0].split("/")[-1]
    for ext in (".zip", ".gz", ".csv", ".parquet", ".pq", ".xlsx",
                ".arff", ".svm", ".svmlight"):
        if dest.endswith(ext):
            dest = dest[: -len(ext)]
    return {"__meta": {"schema_version": 3, "schema_name": "ParseSetupV3",
                       "schema_type": "ParseSetup"},
            "source_frames": [{"name": s} for s in srcs],
            "destination_frame": dest + ".hex",
            "parse_type": "CSV",
            "column_names": setup["columns"],
            "column_types": [_SETUP_TYPES.get(setup["types"][c], "Numeric")
                             for c in setup["columns"]],
            "na_strings": None,
            "warnings": [],
            "separator": ord(setup["separator"]),
            "single_quotes": False,
            "check_header": 1 if setup["header"] else 0,
            "number_columns": len(setup["columns"]),
            "chunk_size": 1 << 22,
            # how the ingest pipeline would run: chunk-parallel vs
            # sequential vs arrow-columnar, worker count, window size
            "parse_plan": _chunk_plan(srcs),
            "total_filtered_column_count": len(setup["columns"])}


def _chunk_plan(srcs):
    from h2o3_tpu.io.chunking import parse_plan
    try:
        return parse_plan(srcs)
    except Exception:            # plan reporting must never fail a parse
        return None


@route("POST", "/3/Parse")
def _parse(params, body):
    from h2o3_tpu.io.parser import import_file
    srcs = _src_list(params)
    dest = _unquote(params.get("destination_frame")) or None
    names = _wire_list(params["column_names"]) \
        if params.get("column_names") else None
    types = _wire_list(params["column_types"]) \
        if params.get("column_types") else None
    col_types = None
    if types and names:
        # type names arrive in either ParseSetup casing ("Enum") or the
        # client's lowercase coltype vocabulary ("enum"); unknowns are
        # left to the parser's own guess rather than forced numeric
        col_types = {}
        for n, t in zip(names, types):
            mapped = _SETUP_TYPES_BACK.get(str(t).lower())
            if mapped:
                col_types[n] = mapped
    # na_strings: column-indexed list of lists (water/parser/ParseSetup
    # naStrings contract — tokens matched BEFORE type inference).
    # Passed POSITIONALLY: keying by the client's column_names breaks
    # when those rename the file's own header columns.
    na_map = None
    if params.get("na_strings"):
        nested = _wire_nested_list(params["na_strings"])
        if nested and any(nested):
            if len(nested) == 1 and names and len(names) > 1:
                # flat-list form: the same tokens apply to every column
                nested = nested * len(names)
            na_map = [lst or None for lst in nested]
    job = Job(f"parse {srcs[0]}", dest=dest)

    ch = params.get("check_header")
    header = None
    if ch is not None:
        ch = int(float(ch))
        header = True if ch == 1 else (False if ch == -1 else None)

    def _run(j):
        if len(srcs) == 1:
            fr = import_file(srcs[0], destination_frame=dest,
                             col_types=col_types, header=header,
                             na_strings=na_map)
            if names and len(names) == fr.ncols and \
                    list(names) != list(fr.names):
                fr.rename_columns(list(names))
        else:
            import pandas as pd
            parts = []
            for s in srcs:
                part = import_file(s, col_types=col_types, header=header,
                                   na_strings=na_map)
                parts.append(part.to_pandas())
                DKV.remove(part.key)     # intermediate per-file frames
            fr = Frame.from_pandas(pd.concat(parts, ignore_index=True),
                                   key=dest)
            DKV.put(fr.key, fr)
        j.update(1.0, "parsed")
        return fr

    job.start(_run, background=True)
    return {"job": job.to_dict(), "parse_plan": _chunk_plan(srcs)}


@route("GET", "/3/Frames")
def _frames(params, body):
    out = []
    for k in DKV.keys():
        # get_raw: listing must NOT materialize lazy/spilled stubs — a
        # catalog poll would otherwise parse every lazy import and
        # un-evict everything the Cleaner just spilled
        v = DKV.get_raw(k)
        if isinstance(v, Frame):
            out.append({"frame_id": {"name": k}, "rows": v.nrows,
                        "num_columns": v.ncols})
        elif getattr(v, "_is_lazy_stub", False):
            out.append({"frame_id": {"name": k},
                        "rows": getattr(v, "nrows", None) or 0,
                        "num_columns": len(getattr(v, "names", []) or [])})
    return {"frames": out}


@route("GET", r"/3/Frames/(?P<fid>[^/]+)/summary")
def _frame_summary(params, body, fid=None):
    fr = DKV.get(fid)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    summ = fr.summary()
    j = _frame_json(fr)
    for c in j["columns"]:
        s = summ.get(c["label"], {})
        c.update({k: (None if v is None or (isinstance(v, float) and np.isnan(v)) else v)
                  for k, v in s.items() if k in
                  ("min", "max", "mean", "sigma", "na_count", "zero_count",
                   "cardinality", "type")})
    return {"frames": [j]}


@route("GET", "/3/DownloadDataset")
def _download_dataset(params, body):
    """Frame → CSV stream (water/api/DownloadDataHandler) — h2o-py's
    as_data_frame()/frame download path."""
    fid = _unquote(params.get("frame_id"))
    fr = DKV.get(fid)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    import io
    buf = io.StringIO()
    fr.to_pandas().to_csv(buf, index=False)
    data = buf.getvalue().encode()
    return {"__bytes__": data, "__ctype__": "text/csv",
            "__headers__": {
                "Content-Disposition":
                    f'attachment; filename="{fid}.csv"'}}


@route("GET", r"/3/Frames/(?P<fid>[^/]+)/light")
def _frame_light(params, body, fid=None):
    return _frame_one(params, body, fid=fid)


@route("GET", r"/3/Frames/(?P<fid>[^/]+)")
def _frame_one(params, body, fid=None):
    fr = DKV.get(fid)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    rows = int(float(params.get("row_count") or 10))
    if rows < 0:
        rows = fr.nrows
    offset = int(float(params.get("row_offset") or 0))
    j = _frame_json(fr, rows=rows, row_offset=offset)
    # provenance surface (ISSUE 18): source paths + parse plan, derived
    # op chains, mirror status — what the durability layer would replay
    # to re-materialize this frame after a peer loss
    from h2o3_tpu.core import durability as _durability
    j["lineage"] = _durability.lineage_of(fr)
    return {"frames": [j]}


@route("DELETE", r"/3/Frames/(?P<fid>[^/]+)")
def _frame_del(params, body, fid=None):
    DKV.remove(fid)
    return {}


@route("DELETE", "/3/DKV")
def _dkv_del_all(params, body):
    """h2o.remove_all(): clear every key except retained models/frames;
    a retained MODEL also keeps its training/validation frames
    (water/api/RemoveAllHandler → DKVManager.retain model→frame)."""
    retained = set(_wire_list(params.get("retained_keys") or []))
    from h2o3_tpu.models.model import Model as _Model
    for k in list(retained):
        v = DKV.get_raw(k)
        if isinstance(v, _Model):
            for fk in (v.output.get("training_frame"),
                       v.output.get("validation_frame")):
                if fk:
                    retained.add(str(fk))
    for k in list(DKV.keys()):
        if k not in retained:
            DKV.remove(k)
    # release dropped device buffers NOW: deferred GC lets HBM pile up
    # across many remove_all cycles (the conformance suite exhausted the
    # chip after ~60 pyunits without this)
    import gc
    gc.collect()
    # compiled executables pin HBM too (program binaries + baked
    # constants live on chip, and jit caches keep them forever): drop
    # the caches when the device nears full — or, where the plugin
    # reports no memory stats (axon returns None), every 15th clear;
    # the conformance tail ResourceExhausted around remove_all #55
    # without this, and a periodic recompile beats a dead suite
    try:
        import jax
        global _RMALL_COUNT
        _RMALL_COUNT += 1
        st = jax.devices()[0].memory_stats() or {}
        used = int(st.get("bytes_in_use", 0) or 0)
        cap = int(st.get("bytes_limit", 0) or 0)
        if (cap and used > 0.8 * cap) or \
                (not cap and _RMALL_COUNT % 10 == 0):
            from h2o3_tpu.core.job import free_device_memory
            free_device_memory(f"remove_all #{_RMALL_COUNT}, HBM "
                               f"{used / 1e9:.1f}/{cap / 1e9:.1f} GB")
    except Exception:
        pass
    return {}


@route("DELETE", r"/3/DKV/(?P<key>[^/]+)")
def _dkv_del(params, body, key=None):
    DKV.remove(key)
    return {}


@route("POST", "/3/LogAndEcho")
def _log_and_echo(params, body):
    log.info("client: %s", params.get("message") or "")
    return {"message": params.get("message") or ""}


@route("GET", "/3/ModelBuilders")
def _builders(params, body):
    out = {}
    for algo in all_algos():
        cls = get_builder(algo)
        defaults = getattr(cls, "DEFAULTS", {})
        out[algo] = {"algo": algo, "algo_full_name": cls.__name__,
                     "parameters": [
                         {"name": k, "default_value": defaults.get(k),
                          "type": type(defaults.get(k)).__name__}
                         for k in sorted(cls.accepted_params())]}
    return {"model_builders": out}


@route("POST", r"/3/ModelBuilders/(?P<algo>[^/]+)")
def _train(params, body, algo=None):
    cls = get_builder(algo)
    p = {k: _coerce(v) for k, v in params.items()}
    frame_key = p.pop("training_frame", None)
    y = p.pop("response_column", None)
    valid_key = p.pop("validation_frame", None)
    model_id = p.pop("model_id", None)
    ignored = p.pop("ignored_columns", None)
    fr = DKV.get(str(frame_key))
    if not isinstance(fr, Frame):
        raise KeyError(f"training_frame {frame_key} not found")
    vf = DKV.get(str(valid_key)) if valid_key else None
    known = cls.accepted_params()
    builder_params = {k: v for k, v in p.items() if k in known}
    if ignored is not None:
        builder_params["ignored_columns"] = ignored
    builder = cls(**builder_params)
    # the one ModelBuilder.train lifecycle (CV dispatch, run_time, logs)
    job = builder.train(fr, y=y, validation_frame=vf, background=True,
                        dest_key=model_id)
    # ModelBuilderSchema shape: job + validation messages
    # (h2o-py/h2o/estimators/estimator_base.py:190 reads "messages")
    return {"__meta": {"schema_version": 3,
                       "schema_name": "ModelBuilderSchema",
                       "schema_type": "ModelBuilder"},
            "job": job.to_dict(), "messages": [], "error_count": 0}


@route("GET", r"/3/Jobs/(?P<key>[^/]+)")
def _job(params, body, key=None):
    j = DKV.get(key)
    if not isinstance(j, Job):
        raise KeyError(f"job {key} not found")
    d = j.to_dict()
    # h2o-py expects job.status in {CREATED,RUNNING,DONE,FAILED,CANCELLED}
    if j.status == "DONE" and j.result is not None and \
            isinstance(j.result, Model):
        d["dest"] = {"name": j.result.key, "type": "Key<Model>"}
    return {"jobs": [d]}


@route("POST", r"/3/Jobs/(?P<key>[^/]+)/cancel")
def _job_cancel(params, body, key=None):
    j = DKV.get(key)
    if isinstance(j, Job):
        j.cancel()
    return {}


@route("GET", "/3/Jobs")
def _jobs(params, body):
    """Job list (water/api/JobsHandler). ``?cluster=1`` on a
    multi-process cloud merges every peer's job list from the telemetry
    fan-in (telemetry/cluster.py) — each entry stamped with its owning
    ``node`` (job keys are process-local counters, so same-key entries
    on different nodes are distinct jobs, never deduped)."""
    if _cluster_requested(params):
        from h2o3_tpu.telemetry import cluster
        return cluster.merged_jobs()
    return {"jobs": list_jobs()}


@route("GET", "/3/Models")
def _models(params, body):
    out = []
    for k in DKV.keys():
        v = DKV.get(k)
        if isinstance(v, Model):
            out.append(v.to_dict())
    return {"models": out}


@route("GET", r"/3/Models/(?P<mid>[^/]+)")
def _model_one(params, body, mid=None):
    from h2o3_tpu.api.model_schema import model_to_v3
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    return {"models": [model_to_v3(m)]}


@route("GET", r"/3/Models/(?P<mid>[^/]+)/profile")
def _model_profile(params, body, mid=None):
    """Per-fit step profile (telemetry/stepprof.py): phase totals,
    per-chunk ring, collective-wait share. ``?cluster=1`` merges every
    host's profile of a pod-global fit into the skew/straggler verdict
    (pod_step_skew_ratio / pod_straggler_host)."""
    from h2o3_tpu.telemetry import stepprof
    out = stepprof.profile_for(mid)       # KeyError -> 404
    if _cluster_requested(params):
        out["cluster"] = stepprof.cluster_profile(mid)
    return out


@route("DELETE", r"/3/Models/(?P<mid>[^/]+)")
def _model_del(params, body, mid=None):
    DKV.remove(mid)
    return {}


@route("POST", r"/3/Predictions/models/(?P<mid>[^/]+)/frames/(?P<fid>[^/]+)")
def _predict(params, body, mid=None, fid=None):
    m = DKV.get(mid)
    fr = DKV.get(fid)
    if not isinstance(m, Model):
        # bulk predicts route through the fleet too (ISSUE 17): a model
        # this node never trained can still be answered here — proxy or
        # 307 to a healthy replica, or install the published binary
        from h2o3_tpu.serving import fleet
        hop = str(params.pop("_fleet_hop", "")).lower() in ("1", "true")
        plan = fleet.plan_route(mid, have_local=False, hop=hop)
        bulk_path = (f"/3/Predictions/models/"
                     f"{urllib.parse.quote(str(mid), safe='')}/frames/"
                     f"{urllib.parse.quote(str(fid), safe='')}")
        if plan.decision == "redirect":
            return {"__redirect__": fleet.redirect_url(plan, bulk_path)}
        if plan.decision == "proxy":
            payload = {k: v for k, v in params.items()
                       if not str(k).startswith("_")}
            res = fleet.proxy_predict(
                plan, bulk_path, payload, mid,
                local_fallback=fleet.published(mid) is not None)
            if res is not fleet.SERVE_LOCALLY:
                return res
        if plan.decision == "none":
            raise KeyError(f"model {mid} not found")
        m = fleet.install_published(mid)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    dest = params.get("predictions_frame") or f"predictions_{mid}_{fid}"
    def _flag(name):
        return str(params.get(name, "")).lower() in ("1", "true", "yes")
    for flag, meth in (("leaf_node_assignment", "predict_leaf_node_assignment"),
                       ("predict_staged_proba", "staged_predict_proba"),
                       ("feature_frequencies", "feature_frequencies"),
                       ("predict_contributions", "predict_contributions")):
        if _flag(flag):
            fn = getattr(m, meth, None)
            if fn is None:
                raise ValueError(f"{flag} is not supported for "
                                 f"algo '{m.algo}'")
            preds = fn(fr)
            break
    else:
        preds = m.predict(fr)
    DKV.remove(preds.key)
    preds.key = str(dest)
    DKV.put(preds.key, preds)
    # scoring computes metrics when the response is present (the
    # reference's BigScore fills a MetricBuilder during predict; the
    # client's multinomial confusion_matrix(data=...) reads
    # model_metrics[0].cm from THIS response)
    metrics_list = [{}]
    try:
        resp = m.output.get("response")
        if resp and resp in fr:
            from h2o3_tpu.api.model_schema import metrics_v3
            metrics_list = [metrics_v3(m.model_performance(fr), m,
                                       frame_key=fr.key)]
    except Exception:
        pass
    return {"predictions_frame": {"name": preds.key},
            "model_metrics": metrics_list}


@route("POST", r"/4/Predictions/models/(?P<mid>[^/]+)/frames/(?P<fid>[^/]+)")
def _predict_async(params, body, mid=None, fid=None):
    """Async bulk scoring (water/api/ModelMetricsHandler.predictAsync —
    returns a bare JobV3; the real h2o-py polls it then fetches
    job.dest as the predictions frame)."""
    m = DKV.get(mid)
    fr = DKV.get(fid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    dest = f"prediction_{mid}_on_{fid}"
    job = Job(f"predict {mid}", dest=dest)

    def _run(j):
        # chunked BigScore: cancel_point at every chunk boundary, so a
        # cancelled or deadline-expired bulk predict frees its worker
        # within one chunk like training does (models/model.py)
        preds = m.predict_in_chunks(fr, job=j)
        DKV.remove(preds.key)
        preds.key = dest
        DKV.put(dest, preds)
        j.update(1.0, "scored")
        return preds

    job.start(_run, background=True)
    return job.to_dict()


@route("POST", r"/3/Predictions/models/(?P<mid>[^/]+)")
def _predict_rows(params, body, mid=None):
    """Row-payload predict fast path (README §Serving): inline JSON
    rows — no DKV frame round trip — scored through the serving tier's
    compiled-scorer cache and continuous micro-batcher, bit-identical
    to ``Model.predict`` on the same rows. Body:
    ``{"rows": [{"col": value, ...}, ...]}``; missing keys are NAs.

    Fleet-routed (ISSUE 17): the request resolves against the replica
    registry — heartbeat-dead peers excluded, least-loaded healthy
    replica wins — and either serves locally, proxies (with hedged
    failover within the deadline budget), or 307-redirects.
    ``_fleet_hop=1`` marks an already-routed request (never re-routed)."""
    from h2o3_tpu.serving import fleet
    hop = str(params.pop("_fleet_hop", "")).lower() in ("1", "true")
    rows = params.get("rows")
    if isinstance(rows, str):
        try:
            rows = json.loads(rows)
        except json.JSONDecodeError as e:
            raise ValueError(f"malformed 'rows' JSON: {e}") from None
    if rows is None:
        raise ValueError("missing 'rows': POST a JSON body "
                         '{"rows": [{"col": value, ...}, ...]}')
    m = DKV.get(mid)
    have_local = isinstance(m, Model)
    plan = fleet.plan_route(mid, have_local=have_local, hop=hop)
    if plan.decision == "none":
        raise KeyError(f"model {mid} not found")
    if plan.decision == "redirect":
        return {"__redirect__": plan.url}
    if plan.decision == "proxy":
        res = fleet.proxy_predict(
            plan,
            f"/3/Predictions/models/"
            f"{urllib.parse.quote(str(mid), safe='')}",
            {"rows": rows}, mid,
            local_fallback=(have_local
                            or fleet.published(mid) is not None))
        if res is not fleet.SERVE_LOCALLY:
            return res
    if not have_local:
        # routed here (or every remote hop failed) without a local
        # copy: install + pre-warm from the published binary
        m = fleet.install_published(mid)
    from h2o3_tpu.serving import ServingUnsupported
    from h2o3_tpu.serving.engine import engine
    try:
        out, domains, meta = engine.score_rows(m, rows)
    except ServingUnsupported as e:
        raise ValueError(str(e)) from None
    preds = {}
    for name, arr in out.items():
        vals = arr.tolist()
        dom = domains.get(name)
        if dom is not None:
            # label the predict column with the training response
            # domain (what the predictions-frame download shows)
            vals = [dom[int(v)] if 0 <= int(v) < len(dom) else None
                    for v in vals]
        preds[name] = vals
    return {"model_id": mid, "rows_scored": len(rows),
            "predictions": preds, "batch": meta}


@route("GET", r"/3/Models/(?P<mid>[^/]+)/mojo")
def _model_mojo(params, body, mid=None):
    """Stream the MOJO zip (h2o-py download_mojo GET endpoint)."""
    from h2o3_tpu.genmodel.export import mojo_artifacts
    from h2o3_tpu.genmodel.mojo import mojo_bytes
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    return {"__bytes__": mojo_bytes(*mojo_artifacts(m)),
            "__ctype__": "application/zip"}


@route("GET", r"/3/Models\.java/(?P<mid>[^/]+)")
def _model_pojo(params, body, mid=None):
    """Generated-source scorer download (water/api Models.java POJO
    endpoint). gbm/drf/glm return compilable Java implementing
    hex.genmodel.GenModel.score0 (hex/genmodel/GenModel.java:363);
    other algos ship the stdlib-Python scorer module."""
    m = DKV.get(mid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    if getattr(m, "algo", None) in ("gbm", "drf", "glm"):
        from h2o3_tpu.genmodel.pojo_java import java_pojo_source
        src = java_pojo_source(m, class_name=str(mid))
        ctype = "text/x-java; charset=utf-8"
    else:
        from h2o3_tpu.genmodel.pojo import pojo_source
        src = pojo_source(m, modname=str(mid))
        ctype = "text/plain; charset=utf-8"
    return {"__bytes__": src.encode(), "__ctype__": ctype}


@route("POST", r"/3/ModelMetrics/models/(?P<mid>[^/]+)/frames/(?P<fid>[^/]+)")
def _model_metrics(params, body, mid=None, fid=None):
    """Score a frame and return its metrics (water/api/ModelMetricsHandler
    — the model_performance(test_data) wire call)."""
    m = DKV.get(mid)
    fr = DKV.get(fid)
    if not isinstance(m, Model):
        raise KeyError(f"model {mid} not found")
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {fid} not found")
    from h2o3_tpu.api.model_schema import metrics_v3
    mm_ = m.model_performance(fr)
    return {"model_metrics": [metrics_v3(mm_, m, frame_key=fid)]}


@route("POST", "/3/CreateFrame")
def _create_frame(params, body):
    """Synthetic frame generator (water/api/CreateFrameHandler →
    hex/createframe/): randomized numeric/categorical/integer/binary/
    time/string columns with missing values and optional response."""
    p = {k: _coerce(v) for k, v in params.items()}
    dest = _unquote(str(p.get("dest") or p.get("destination_frame")
                        or "createframe.hex"))
    rows = int(p.get("rows") or 100)
    cols_n = int(p.get("cols") or 10)
    seed = int(p.get("seed") or -1)
    r = np.random.RandomState(seed & 0x7FFFFFFF if seed >= 0 else None)
    cat_f = float(p.get("categorical_fraction") or 0.0)
    int_f = float(p.get("integer_fraction") or 0.0)
    bin_f = float(p.get("binary_fraction") or 0.0)
    time_f = float(p.get("time_fraction") or 0.0)
    str_f = float(p.get("string_fraction") or 0.0)
    miss_f = float(p.get("missing_fraction") or 0.0)
    factors = int(p.get("factors") or 100)
    real_range = float(p.get("real_range") or 100.0)
    int_range = int(p.get("integer_range") or 100)
    bin_ones = float(p.get("binary_ones_fraction") or 0.02)
    counts = {
        "cat": int(round(cols_n * cat_f)),
        "int": int(round(cols_n * int_f)),
        "bin": int(round(cols_n * bin_f)),
        "time": int(round(cols_n * time_f)),
        "str": int(round(cols_n * str_f)),
    }
    counts["real"] = max(cols_n - sum(counts.values()), 0)
    job = Job("create frame", dest=dest)

    def _run(j):
        arrays, cats, strs, times = {}, [], [], []
        ci = 0
        for kind, cnt in counts.items():
            for _ in range(cnt):
                name = f"C{ci + 1}"
                ci += 1
                if kind == "cat":
                    arrays[name] = np.array(
                        [f"c{ci}.l{v}" for v in
                         r.randint(0, max(factors, 1), rows)], object)
                    cats.append(name)
                elif kind == "int":
                    arrays[name] = r.randint(-int_range, int_range + 1,
                                             rows).astype(np.float64)
                elif kind == "bin":
                    arrays[name] = (r.rand(rows) < bin_ones
                                    ).astype(np.float64)
                elif kind == "time":
                    arrays[name] = r.randint(0, 2 ** 40,
                                             rows).astype(np.float64)
                    times.append(name)
                elif kind == "str":
                    arrays[name] = np.array(
                        [f"s{v}" for v in r.randint(0, 10 ** 6, rows)],
                        object)
                    strs.append(name)
                else:
                    arrays[name] = r.uniform(-real_range, real_range, rows)
        if miss_f > 0:
            for name, arr in arrays.items():
                mask = r.rand(rows) < miss_f
                if name in strs or name in cats:
                    a = arr.astype(object)
                    a[mask] = None
                    arrays[name] = a
                else:
                    arr[mask] = np.nan
        if str(p.get("has_response", "")).lower() in ("1", "true"):
            rf = int(p.get("response_factors") or 2)
            if rf <= 1:
                arrays["response"] = r.randn(rows)
            else:
                arrays["response"] = np.array(
                    [f"resp.l{v}" for v in r.randint(0, rf, rows)], object)
                cats.append("response")
        fr = Frame.from_numpy(arrays, categorical=cats, strings=strs,
                              times=times, key=dest)
        DKV.put(dest, fr)
        j.update(1.0)
        return fr

    job.start(_run, background=True)
    return {"job": job.to_dict()}


@route("POST", "/3/Interaction")
def _interaction_ep(params, body):
    """Categorical interaction features (water/api/InteractionHandler →
    hex/Interaction: pairwise or full combination of factor columns,
    capped at max_factors levels by occurrence)."""
    p = {k: _coerce(v) for k, v in params.items()}
    src = DKV.get(_unquote(str(p.get("source_frame"))))
    if not isinstance(src, Frame):
        raise KeyError(f"frame {p.get('source_frame')} not found")
    dest = _unquote(str(p.get("dest") or "interaction.hex"))
    factors = [_unquote(f) for f in _wire_list(p.get("factor_columns"))]
    pairwise = str(p.get("pairwise", "")).lower() in ("1", "true")
    max_factors = int(p.get("max_factors") or 100)
    min_occ = int(p.get("min_occurrence") or 1)
    job = Job("interaction", dest=dest)

    def _run(j):
        import itertools
        from h2o3_tpu.frame.column import T_CAT
        groups = (list(itertools.combinations(factors, 2)) if pairwise
                  else [tuple(factors)])
        arrays, cats, doms = {}, [], {}
        for grp in groups:
            name = "_".join(grp)
            codes = None
            labels = None
            for g in grp:
                c = src.col(g)
                cc = _fetch_np(c.data)[: src.nrows].astype(np.int64)
                cna = _fetch_np(c.na_mask)[: src.nrows]
                lab = np.array([c.domain[v] if 0 <= v < len(c.domain)
                                else "NA" for v in cc], object)
                lab[cna] = "NA"
                labels = lab if labels is None else \
                    np.char.add(np.char.add(labels.astype(str), "_"),
                                lab.astype(str))
            vals, cnts = np.unique(labels, return_counts=True)
            keep = vals[cnts >= min_occ]
            if len(keep) > max_factors:
                keep = vals[np.argsort(-cnts)][:max_factors]
            keep_set = set(keep.tolist())
            out = np.array([v if v in keep_set else "other"
                            for v in labels], object)
            arrays[name] = out
            cats.append(name)
        fr = Frame.from_numpy(arrays, categorical=cats, key=dest)
        DKV.put(dest, fr)
        j.update(1.0)
        return fr

    job.start(_run, background=True)
    return {"job": job.to_dict()}


@route("POST", "/3/MissingInserter")
def _missing_inserter(params, body):
    """Insert missing values into a frame in place
    (water/api/MissingInserterHandler)."""
    p = {k: _coerce(v) for k, v in params.items()}
    key = _unquote(str(p.get("dataset")))
    fr = DKV.get(key)
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {key} not found")
    frac = float(p.get("fraction") or 0.0)
    seed = int(p.get("seed") or -1)
    job = Job("insert missing", dest=key)

    def _run(j):
        r = np.random.RandomState(seed & 0x7FFFFFFF if seed >= 0 else None)
        arrays, cats, doms, strs = {}, [], {}, []
        for n in fr.names:
            c = fr.col(n)
            if c.type == "string":
                a = c.to_numpy().astype(object).copy()
                a[r.rand(fr.nrows) < frac] = None
                arrays[n] = a
                strs.append(n)
            elif c.is_categorical:
                codes = _fetch_np(c.data)[: fr.nrows].astype(np.int32)
                codes[_fetch_np(c.na_mask)[: fr.nrows]] = -1
                codes[r.rand(fr.nrows) < frac] = -1
                arrays[n] = codes
                cats.append(n)
                doms[n] = c.domain
            else:
                a = c.to_numpy()
                a[r.rand(fr.nrows) < frac] = np.nan
                arrays[n] = a
        new = Frame.from_numpy(arrays, categorical=cats, domains=doms,
                               strings=strs, key=key)
        DKV.put(key, new)
        j.update(1.0)
        return new

    job.start(_run, background=True)
    return job.to_dict()


@route("GET", r"/3/Typeahead/files")
def _typeahead(params, body):
    """File-path completion (water/api/TypeaheadHandler)."""
    import glob as _g
    import os
    src = _unquote(str(params.get("src") or ""))
    limit = int(float(params.get("limit") or 100))
    if os.path.isdir(src):
        pattern = os.path.join(src, "*")
    else:
        pattern = src + "*"
    matches = sorted(_g.glob(pattern))[:limit]
    return {"src": src, "limit": limit, "matches": matches}


@route("GET", "/3/NetworkTest")
def _network_test(params, body):
    """Collective micro-bench over the mesh (water/init/NetworkBench):
    times a small psum across devices — the ICI/DCN path."""
    import time as _t
    import jax
    import jax.numpy as jnp
    from h2o3_tpu.parallel.mesh import get_mesh
    from h2o3_tpu.ops.segments import segment_sum
    mesh = get_mesh()
    x = jnp.ones((8192,), jnp.float32)
    t0 = _t.time()
    s = segment_sum(jnp.zeros((8192,), jnp.int32), x[:, None],
                    n_nodes=1, mesh=mesh)
    float(jnp.sum(s))
    dt = _t.time() - t0
    return {"table": [{"op": "psum-32KB",
                       "devices": len(jax.devices()),
                       "seconds": round(dt, 5)}],
            "nodes": [str(d) for d in mesh.devices.flat]}


@route("POST", "/3/PartialDependence")
def _pdp(params, body):
    """water/api/PartialDependenceHandler: grid sweep per feature."""
    m = DKV.get(str(params.get("model_id")))
    fr = DKV.get(str(params.get("frame_id")))
    if not isinstance(m, Model):
        raise KeyError(f"model {params.get('model_id')} not found")
    if not isinstance(fr, Frame):
        raise KeyError(f"frame {params.get('frame_id')} not found")
    cols = _coerce(params.get("cols") or "[]")
    if isinstance(cols, str):
        cols = [cols]
    nbins = int(params.get("nbins") or 20)
    from h2o3_tpu.ml.explain import partial_dependence
    return {"partial_dependence_data": partial_dependence(
        m, fr, cols or m.output.get("names", []), nbins=nbins)}


@route("POST", "/99/Rapids")
def _rapids_ep(params, body):
    """Rapids eval (water/api/RapidsHandler). The real h2o-py reads
    key/num_rows/num_cols for frames, scalar, string, map_keys/frames
    (h2o-py/h2o/expr.py:116-128); errors must be H2OErrorV3."""
    from h2o3_tpu.rapids import rapids
    expr = params.get("ast") or ""
    try:
        val = rapids(expr)
    except Exception as e:   # noqa: BLE001
        # HBM pressure shows up as RESOURCE_EXHAUSTED (the axon plugin
        # reports no memory gauge): purge jit caches and retry once
        if "RESOURCE_EXHAUSTED" not in f"{e}":
            raise
        from h2o3_tpu.core.job import free_device_memory
        free_device_memory("rapids RESOURCE_EXHAUSTED retry")
        val = rapids(expr)
    if isinstance(val, Frame):
        return {"__meta": {"schema_version": 3,
                           "schema_name": "RapidsFrameV3",
                           "schema_type": "RapidsFrame"},
                "key": {"name": val.key},
                "num_rows": val.nrows, "num_cols": val.ncols,
                "frame": _frame_json(val, rows=5)}
    if isinstance(val, (bool, np.bool_)):
        return {"scalar": bool(val)}
    if isinstance(val, (int, float, np.generic)):
        return {"scalar": float(val)}
    if val is None:
        return {"scalar": None}
    if isinstance(val, (list, np.ndarray)):
        return {"scalar": [float(x) for x in np.asarray(val).ravel()]}
    return {"string": str(val)}


@route("POST", r"/99/Grid/(?P<algo>[^/]+)")
def _grid_build(params, body, algo=None):
    """Grid search build (water/api/GridSearchHandler +
    hex/grid/GridSearch.java:70). The real h2o-py posts
    hyper_parameters as a stringified map and polls the returned job
    (h2o-py/h2o/grid/grid_search.py:414)."""
    from h2o3_tpu.ml.grid import GridSearch
    cls = get_builder(algo)
    p = {k: _coerce(v) for k, v in params.items()}
    hyper = p.pop("hyper_parameters", None) or {}
    if isinstance(hyper, str):
        hyper = _wire_map(hyper)
    criteria = p.pop("search_criteria", None)
    if isinstance(criteria, str):
        criteria = _wire_map(criteria)
    frame_key = str(p.pop("training_frame", None))
    y = p.pop("response_column", None)
    valid_key = p.pop("validation_frame", None)
    grid_id = p.pop("grid_id", None)
    ignored = p.pop("ignored_columns", None)
    if isinstance(ignored, str):
        ignored = _wire_list(ignored)
    fr = DKV.get(frame_key)
    if not isinstance(fr, Frame):
        raise KeyError(f"training_frame {frame_key} not found")
    vf = DKV.get(str(valid_key)) if valid_key else None
    known = cls.accepted_params()
    fixed = {k: v for k, v in p.items() if k in known and k not in hyper}
    if ignored:
        fixed["ignored_columns"] = [_unquote(c) for c in ignored]
    gs = GridSearch(cls, hyper, search_criteria=criteria,
                    grid_id=grid_id, **fixed)
    job = Job(f"grid {algo}", dest=gs.grid_id)

    def _run(j):
        grid = gs.train(fr, y=y, validation_frame=vf)
        j.update(1.0, "grid done")
        return grid

    job.start(_run, background=True)
    return {"__meta": {"schema_version": 99,
                       "schema_name": "GridSearchSchema",
                       "schema_type": "GridSearch"},
            "job": job.to_dict(), "messages": [], "error_count": 0}


def _grid_json(grid, sort_by=None, decreasing=None):
    from h2o3_tpu.api.model_schema import twodim
    metric = sort_by or grid.sort_metric
    try:
        models = grid.sorted_models(metric)
    except Exception:
        models = list(grid.models)
    if decreasing is not None and str(decreasing).lower() == "true":
        models = models[::-1]
    hyper_names = sorted({k for m in models
                          for k in (m.output.get("grid_params") or {})})
    rows = []
    for m in models:
        gp = m.output.get("grid_params") or {}
        mm_ = m.default_metrics
        val = None
        if mm_ is not None:
            try:
                val = float(mm_[metric.upper()
                                if metric.lower() == "auc" else metric])
            except Exception:
                try:
                    val = float(mm_["MSE"])
                except Exception:
                    val = None
        rows.append([str(gp.get(h)) for h in hyper_names] +
                    [m.key, val])
    summary = twodim(
        "Hyper-Parameter Search Summary",
        hyper_names + ["model_ids", metric],
        ["string"] * len(hyper_names) + ["string", "float64"], rows)
    return {
        "__meta": {"schema_version": 99, "schema_name": "GridSchemaV99",
                   "schema_type": "Grid"},
        "grid_id": {"name": grid.grid_id, "type": "Key<Grid>"},
        "model_ids": [{"name": m.key, "type": "Key<Model>"}
                      for m in models],
        "hyper_names": hyper_names,
        "failure_details": [f["error"] for f in grid.failures],
        "failure_stack_traces": [f.get("stacktrace", f["error"])
                                 for f in grid.failures],
        "failed_params": [f["params"] for f in grid.failures],
        "warning_details": [],
        "export_checkpoints_dir": None,
        "summary_table": summary,
    }


@route("GET", r"/99/Grids/(?P<gid>[^/]+)")
def _grid_get(params, body, gid=None):
    from h2o3_tpu.ml.grid import Grid
    g = DKV.get(gid)
    if not isinstance(g, Grid):
        raise KeyError(f"grid {gid} not found")
    return _grid_json(g, sort_by=params.get("sort_by"),
                      decreasing=params.get("decreasing"))


@route("GET", "/99/Grids")
def _grids_list(params, body):
    from h2o3_tpu.ml.grid import Grid
    out = []
    for k in list(DKV.keys()):
        g = DKV.get_raw(k)
        if isinstance(g, Grid):
            out.append({"name": g.grid_id, "type": "Key<Grid>"})
    return {"grids": out}


@route("GET", r"/99/Models/(?P<mid>[^/]+)")
def _model_one_v99(params, body, mid=None):
    return _model_one(params, body, mid=mid)


@route("POST", "/99/AutoMLBuilder")
def _automl(params, body):
    from h2o3_tpu.automl import H2OAutoML
    p = {k: _coerce(v) for k, v in params.items()}
    # h2o-py ships nested specs (h2o-py/h2o/automl/_estimator.py):
    # build_control{project_name,nfolds,stopping_criteria{...}},
    # input_spec{training_frame,response_column}, build_models{*_algos}
    ctl = p.get("build_control") or {}
    if isinstance(ctl, str):
        ctl = json.loads(ctl)
    crit = ctl.get("stopping_criteria") or {}
    inp = p.get("input_spec") or {}
    if isinstance(inp, str):
        inp = json.loads(inp)
    bm = p.get("build_models") or {}
    if isinstance(bm, str):
        bm = json.loads(bm)
    frame_key = inp.get("training_frame") or p.get("training_frame")
    y = inp.get("response_column") or p.get("response_column")
    if isinstance(y, dict):
        y = y.get("column_name")
    fr = DKV.get(str(frame_key))
    ignored = inp.get("ignored_columns")
    x_cols = ([n for n in fr.names if n not in set(ignored) and n != y]
              if ignored and isinstance(fr, Frame) else None)
    aml = H2OAutoML(
        max_models=int(crit.get("max_models") or p.get("max_models") or 0),
        max_runtime_secs=float(crit.get("max_runtime_secs")
                               or p.get("max_runtime_secs") or 3600),
        seed=int(crit.get("seed") or p.get("seed") or -1),
        nfolds=int(next(v for v in (ctl.get("nfolds"), p.get("nfolds"), 5)
                        if v is not None)),
        include_algos=bm.get("include_algos"),
        exclude_algos=bm.get("exclude_algos"),
        project_name=ctl.get("project_name") or p.get("project_name"))
    job = Job("automl", dest=aml.project_name)

    def _run(j):
        aml.train(y=y, training_frame=fr, x=x_cols)
        j.update(1.0, "done")
        DKV.put(f"leaderboard_{aml.project_name}_result", aml)
        return aml

    job.start(_run, background=True)
    return {"job": job.to_dict(), "project_name": aml.project_name,
            "build_control": {"project_name": aml.project_name}}


def _automl_tables(aml):
    """leaderboard_table + event_log_table TwoDimTables the real h2o-py
    parses into H2OFrames (h2o-py/h2o/automl/_base.py:333 _fetch_state)."""
    from h2o3_tpu.api.model_schema import twodim
    rows = []
    tab = aml.leaderboard.as_table()
    metric_cols = [k for k in (tab[0].keys() if tab else [])
                   if k != "model_id"]
    if not metric_cols:
        # an empty leaderboard must still carry the metric columns: the
        # client slices fr[1:] off the parsed table
        # (h2o-py/h2o/automl/_base.py:328), which asserts on ncol == 1.
        # Column set follows the task's sort metric.
        sm = (getattr(aml.leaderboard, "sort_metric", None) or "auc").lower()
        if sm in ("auc", "logloss", "aucpr"):
            metric_cols = ["auc", "logloss", "aucpr",
                           "mean_per_class_error", "rmse", "mse"]
        elif sm == "mean_per_class_error":
            metric_cols = ["mean_per_class_error", "logloss", "rmse", "mse"]
        else:
            metric_cols = ["mean_residual_deviance", "rmse", "mse",
                           "mae", "rmsle"]
    for r in tab:
        rows.append([str(r.get("model_id"))] +
                    [r.get(k) for k in metric_cols])
    # col_types feed straight into H2OFrame(column_types=...), whose
    # vocabulary is "double"/"string" (h2o-py _fetch_table)
    lb_table = twodim(
        "Leaderboard", ["model_id"] + metric_cols,
        ["string"] + ["double"] * len(metric_cols), rows)
    ev_rows = [[str(e.get("timestamp", "")), "info",
                e.get("stage", ""), e.get("message", ""), "", ""]
               for e in getattr(aml, "event_log", [])]
    ev_table = twodim(
        "Event Log",
        ["timestamp", "level", "stage", "message", "name", "value"],
        ["string"] * 6, ev_rows)
    return lb_table, ev_table


@route("GET", r"/99/AutoML/(?P<project>[^/]+)")
def _automl_state(params, body, project=None):
    """AutoML state fetch (water/api + ai/h2o/automl AutoMLV99): the
    real client reads project_name, leaderboard.models,
    leaderboard_table and event_log_table."""
    aml = DKV.get(f"leaderboard_{project}_result")
    if aml is None:
        raise KeyError(f"automl project {project} not found")
    lb_table, ev_table = _automl_tables(aml)
    return {"project_name": aml.project_name,
            "leaderboard": {"models": [
                {"name": m.key, "type": "Key<Model>"}
                for m in aml.leaderboard.sorted_models()]},
            "leaderboard_table": lb_table,
            "event_log_table": ev_table,
            "training_info": {}}


@route("GET", r"/99/Leaderboards/(?P<project>[^/]+)")
def _leaderboard(params, body, project=None):
    aml = DKV.get(f"leaderboard_{project}_result")
    if aml is None:
        raise KeyError(f"automl project {project} not found")
    lb_table, _ = _automl_tables(aml)
    return {"project_name": project,
            "models": [{"name": m.key, "type": "Key<Model>"}
                       for m in aml.leaderboard.sorted_models()],
            "table": lb_table,
            "leaderboard_table": aml.leaderboard.as_table()}


@route("GET", r"/flow(/index\.html)?/?")
def _flow(params, body, **_):
    """The Flow notebook UI (h2o-web role) — served from the node at
    /flow/index.html like the reference."""
    from h2o3_tpu.api.flow import FLOW_HTML
    return {"__html__": FLOW_HTML}


@route("GET", "/")
def _index(params, body):
    """Minimal landing page (the h2o-web Flow-serving role: the node
    itself answers a browser with a live cluster view)."""
    info = cloud_mod.cluster_info()
    frames = sum(1 for k in DKV.keys()
                 if isinstance(DKV.get_raw(k), Frame)
                 or getattr(DKV.get_raw(k), "_is_lazy_stub", False))
    models = sum(1 for k in DKV.keys()
                 if isinstance(DKV.get_raw(k), Model))
    html = f"""<!doctype html><html><head><title>h2o3-tpu</title></head>
<body style="font-family:monospace">
<h2>h2o3-tpu cloud '{info["cloud_name"]}'</h2>
<p>{info["cloud_size"]} device(s) on {info["platform"]} —
healthy: {info["cloud_healthy"]}</p>
<p>{frames} frame(s), {models} model(s),
{len(all_algos())} algorithms registered</p>
<p><a href="/flow/index.html"><b>Open Flow (notebook UI)</b></a></p>
<p>REST: <a href="/3/Cloud">/3/Cloud</a> ·
<a href="/3/Frames">/3/Frames</a> ·
<a href="/3/Models">/3/Models</a> ·
<a href="/3/ModelBuilders">/3/ModelBuilders</a> ·
<a href="/3/Jobs">/3/Jobs</a> ·
<a href="/3/Timeline">/3/Timeline</a> ·
<a href="/3/Metrics">/3/Metrics</a> ·
<a href="/3/Trace">/3/Trace</a> ·
<a href="/3/Logs">/3/Logs</a> ·
<a href="/3/SelfBench">/3/SelfBench</a></p>
</body></html>"""
    return {"__html__": html}


def _cluster_requested(params) -> bool:
    """``?cluster=1`` opt-in, honored only on a multi-process cloud —
    with process_count()==1 every cluster view IS the local view
    (bit-identical by construction, asserted in tier-1)."""
    if str(params.get("cluster") or "").lower() not in ("1", "true",
                                                        "yes"):
        return False
    try:
        import jax
        return jax.process_count() > 1
    except Exception:   # noqa: BLE001 - no backend → local view
        return False


@route("GET", "/3/Metrics")
def _metrics(params, body):
    """Runtime telemetry snapshot (h2o3_tpu/telemetry): registry
    counters/gauges/histograms + recent spans. ``?format=prometheus``
    returns text exposition 0.0.4 for a scraping agent; the JSON shape
    additionally carries the span ring and per-span-name aggregate.
    ``?cluster=1`` on a multi-process cloud merges every peer's fan-in
    snapshot (telemetry/cluster.py): counters summed across nodes,
    gauges/histograms per-node with a ``node=`` label, peers past their
    publish window served stale-but-labeled (``stale_nodes``)."""
    from h2o3_tpu import telemetry
    # refresh the slo_* burn-rate gauges so every scrape carries the
    # current objective health (telemetry/slo.py — best-effort: the
    # scrape must survive a broken rule)
    try:
        from h2o3_tpu.telemetry import slo as _slo
        _slo.evaluate()
    except Exception:   # noqa: BLE001 - scrape over alerting
        pass
    fmt = str(params.get("format") or "").lower()
    if _cluster_requested(params):
        from h2o3_tpu.telemetry import cluster
        col = cluster.collect()
        if fmt in ("prometheus", "prom", "text"):
            return {"__bytes__": cluster.merged_prometheus(col).encode(),
                    "__ctype__":
                        "text/plain; version=0.0.4; charset=utf-8"}
        summaries = cluster.node_summaries(col)
        return {"metrics": cluster.merged_metrics(col),
                "spans": telemetry.spans_snapshot(50),
                "span_aggregate": telemetry.spans_aggregate(),
                "cluster": {
                    "process_count": col["process_count"],
                    "stale_nodes": col["stale_nodes"],
                    "nodes": [summaries[n] for n in sorted(summaries)],
                }}
    if fmt in ("prometheus", "prom", "text"):
        return {"__bytes__": telemetry.to_prometheus().encode(),
                "__ctype__": "text/plain; version=0.0.4; charset=utf-8"}
    try:
        nspans = int(float(params.get("spans") or 50))
    except (TypeError, ValueError):
        nspans = 50
    return {"metrics": telemetry.snapshot(),
            "spans": telemetry.spans_snapshot(nspans),
            "span_aggregate": telemetry.spans_aggregate()}


@route("GET", "/3/Alerts")
def _alerts(params, body):
    """SLO burn-rate evaluation (telemetry/slo.py): every declarative
    objective's state (healthy/burning/alert/recovery), 5m/1h burn
    rates, and the currently-firing alerts. ``?cluster=1`` on a
    multi-process cloud merges every peer's published alert view
    (telemetry/cluster.py fan-in), each entry stamped with its
    ``node``."""
    from h2o3_tpu.telemetry import slo
    if _cluster_requested(params):
        from h2o3_tpu.telemetry import cluster
        return cluster.merged_alerts()
    return slo.evaluate()


@route("GET", "/3/WaterMeterCpuTicks")
def _water_meter(params, body):
    """Per-core cpu tick counters (water/util/WaterMeterCpuTicks.java).
    Wire layout per LinuxProcFileReader: [user+nice, system, other(io),
    idle]."""
    ticks = []
    try:
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("cpu") and line[3].isdigit():
                    p = line.split()   # cpuN user nice system idle iowait…
                    ticks.append([int(p[1]) + int(p[2]), int(p[3]),
                                  int(p[5]), int(p[4])])
    except OSError:
        pass
    if not ticks:
        # no /proc (macOS, sandboxes): synthesize one pseudo-core from
        # the process's own rusage so the endpoint still reports REAL
        # collected data instead of an empty stub
        import os as _os
        t = _os.times()
        hz = 100.0
        ticks = [[int(t.user * hz), int(t.system * hz), 0,
                  int(max(t.elapsed - t.user - t.system, 0) * hz)]]
    return {"cpu_ticks": ticks}


@route("GET", "/3/Timeline")
def _timeline(params, body):
    from h2o3_tpu.utils.timeline import snapshot
    return {"events": snapshot(last=params.get("last"))}


@route("GET", "/3/JStack")
def _jstack(params, body):
    """Thread stack dump (water/api/JStackHandler role)."""
    import sys
    import traceback
    frames = sys._current_frames()
    threads = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        out.append({"thread": threads.get(tid, str(tid)),
                    "stack": traceback.format_stack(frame)})
    return {"traces": out}


@route("GET", "/3/Profiler")
def _profiler(params, body):
    """Statistical CPU profile (water/api/ProfilerHandler): sample every
    thread's Python stack `depth` times at short intervals and count
    identical stacks — the reference aggregates JVM stack samples the
    same way."""
    import sys
    import time as _t
    import traceback
    depth = int(float(params.get("depth") or 10))
    counts: Dict[str, int] = {}
    for _ in range(max(1, min(depth, 100))):
        for tid, frame in sys._current_frames().items():
            sig = "".join(traceback.format_stack(frame)[-6:])
            counts[sig] = counts.get(sig, 0) + 1
        _t.sleep(0.01)
    nodes = [{"entries": [
        {"stacktrace": sig, "count": cnt}
        for sig, cnt in sorted(counts.items(), key=lambda kv: -kv[1])[:30]
    ]}]
    # span-level profile rides along: where the RUNTIME's structured
    # phases (jobs, fits, chunks, parses) actually spent wall time —
    # complements the raw stack samples the same way the reference's
    # Timeline complements its Profiler
    from h2o3_tpu import telemetry
    return {"nodes": nodes, "depth": depth,
            "spans": telemetry.spans_aggregate()}


@route("GET", "/3/SelfBench")
def _selfbench(params, body):
    """Node capability probes (water/init/{Linpack,MemoryBandwidth,
    NetworkBench} role)."""
    from h2o3_tpu.core.selfcheck import run_self_bench
    return run_self_bench()


@route("GET", "/3/Logs")
def _logs(params, body):
    """Recent log lines (water/api/LogsHandler role) from the structured
    pipeline's ring buffers: ``?level=ERROR`` selects a per-level ring,
    ``?last=N`` bounds the tail. ``?cluster=1`` on a multi-process
    cloud merges every peer's published tail, timestamp-ordered, each
    line prefixed with its node id."""
    from h2o3_tpu.utils.log import level_counts, log_buffer, log_file_path
    level = params.get("level")
    try:
        last = int(float(params.get("last") or 0)) or None
    except (TypeError, ValueError):
        last = None
    if _cluster_requested(params):
        from h2o3_tpu.telemetry import cluster
        merged = cluster.merged_logs(level=level, last=last)
        return {"log": "\n".join(merged["lines"]),
                "lines": merged["lines"],
                "level": (level or "ALL").upper(),
                "level_counts": level_counts(),
                "file": log_file_path() or "",
                "cluster": {"process_count": merged["process_count"],
                            "stale_nodes": merged["stale_nodes"]}}
    lines = log_buffer(level=level, last=last)
    return {"log": "\n".join(lines),
            "lines": lines,
            "level": (level or "ALL").upper(),
            "level_counts": level_counts(),
            "file": log_file_path() or ""}


@route("GET", "/3/Logs/download")
def _logs_download(params, body):
    """The whole log as a text attachment (h2o.download_all_logs role).
    Serves the rotating file sink when H2O3TPU_LOG_DIR is active,
    otherwise the in-memory ring — never again the empty stub."""
    from h2o3_tpu.utils.log import log_buffer, log_file_path
    path = log_file_path()
    data = None
    if path:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            data = None
    if data is None:
        data = ("\n".join(log_buffer()) + "\n").encode()
    return {"__bytes__": data, "__ctype__": "text/plain; charset=utf-8",
            "__headers__": {
                "Content-Disposition":
                    'attachment; filename="h2o3tpu.log"'}}


@route("GET", r"/3/Jobs/(?P<key>[^/]+)/trace")
def _job_trace(params, body, key=None):
    """One job's flight-recorder capsule as Chrome trace-event JSON —
    load it in https://ui.perfetto.dev (telemetry/trace_export.py)."""
    from h2o3_tpu.telemetry import flight_recorder, trace_export
    cap = flight_recorder.get_capsule(key)
    if cap is None:
        raise KeyError(
            f"no telemetry capsule for job {key} (cancelled capsules "
            f"are swept; completed ones are retained for the last "
            f"{flight_recorder.keep_count()} jobs — "
            f"H2O3TPU_FLIGHT_RECORDER_KEEP)")
    return trace_export.capsule_trace(cap)


@route("GET", r"/3/Jobs/(?P<key>[^/]+)/telemetry")
def _job_telemetry(params, body, key=None):
    """The raw capsule (spans/events/compiles/logs/metric deltas)."""
    from h2o3_tpu.telemetry import flight_recorder
    cap = flight_recorder.get_capsule(key)
    if cap is None:
        raise KeyError(f"no telemetry capsule for job {key}")
    return cap.to_dict()


@route("GET", "/3/Trace")
def _process_trace(params, body):
    """The whole process ring (spans + timeline + compiles) as Chrome
    trace JSON — the zoomed-out view when no single job is suspect.
    ``?cluster=1`` on a multi-process cloud merges every peer's
    published ring tails into ONE trace with ``pid`` = process_index,
    so Perfetto renders one track group per host.
    ``?trace_id=`` instead stitches ONE request's spans — from every
    host that published them — into a single causal trace (cross-
    process parent links, not pid-grouped tracks): the distributed-
    tracing read side (ISSUE 16)."""
    from h2o3_tpu.telemetry import trace_export
    trace_id = params.get("trace_id")
    if trace_id:
        from h2o3_tpu.telemetry import cluster
        return cluster.stitched_trace(trace_id)
    if _cluster_requested(params):
        from h2o3_tpu.telemetry import cluster
        return cluster.merged_trace()
    try:
        nspans = int(float(params.get("spans") or 2048))
        nevents = int(float(params.get("events") or 2048))
    except (TypeError, ValueError):
        nspans, nevents = 2048, 2048
    return trace_export.process_trace(last_spans=nspans,
                                      last_events=nevents)


@route("POST", "/3/Profiler/capture")
def _profiler_capture(params, body):
    """Bounded jax.profiler window (the /3/JProfile analogue): captures
    a TensorBoard-loadable device trace for ``duration_ms`` (capped at
    10s) into ``log_dir``. Degrades gracefully — a backend that cannot
    profile answers with supported=false, not a 500."""
    import os
    import tempfile
    try:
        dur_ms = float(params.get("duration_ms") or 1000.0)
    except (TypeError, ValueError):
        raise ValueError(
            f"malformed duration_ms {params.get('duration_ms')!r}")
    dur_s = min(max(dur_ms, 1.0), 10_000.0) / 1000.0
    log_dir = _unquote(str(params.get("log_dir") or "")) or \
        tempfile.mkdtemp(prefix="h2o3tpu_jprofile_")
    started = False
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        started = True
        time.sleep(dur_s)
    except Exception as e:   # noqa: BLE001 - degrade, don't 500
        return {"supported": False, "error": str(e)[:500],
                "log_dir": log_dir if started else None}
    finally:
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:   # noqa: BLE001
                pass
    files = []
    for root, _dirs, names in os.walk(log_dir):
        files.extend(os.path.join(root, n) for n in names)
    return {"supported": True, "log_dir": log_dir,
            "duration_ms": dur_s * 1000.0, "files": sorted(files)[:100]}


@route("POST", "/3/CloudCheckpoint")
def _cloud_checkpoint(params, body):
    """Whole-cloud checkpoint (ISSUE 18): quiesce RUNNING jobs
    (bounded), persist the DKV — frames as device-independent blocks,
    models as device-lowered binaries — under ``dir``, manifest written
    last. ``init(restore_dir=<dir>)`` reforms the cloud bit-identically
    (core/durability.py)."""
    d = params.get("dir") or params.get("directory") or \
        (body.get("dir") if isinstance(body, dict) else None)
    if not d:
        raise ValueError("CloudCheckpoint requires a 'dir' parameter")
    quiesce_s = float(params.get("quiesce_s") or 30.0)
    from h2o3_tpu.core import durability as _durability
    return _durability.cloud_checkpoint(str(d), quiesce_s=quiesce_s)


@route("POST", "/3/Shutdown")
def _shutdown(params, body):
    threading.Thread(target=lambda: _SERVER and _SERVER.shutdown(),
                     daemon=True).start()
    return {}


# ------------------------------------------------------------- plumbing


class AdmissionGate:
    """Bounded in-flight request gate (the reference's bounded Jetty
    pool role, water/api/RequestServer): at most ``max_inflight``
    requests execute handlers concurrently; up to ``queue_depth`` more
    wait for a slot (bounded by ``queue_wait_s`` or their own request
    deadline, whichever is sooner); everything past that fails fast
    with 503 + Retry-After so overload degrades into clean client
    retries instead of an unbounded handler-thread pile-up."""

    def __init__(self, max_inflight: int, queue_depth: int,
                 queue_wait_s: float):
        self.max_inflight = max(1, int(max_inflight))
        self.queue_depth = max(0, int(queue_depth))
        self.queue_wait_s = float(queue_wait_s)
        self._inflight = 0
        self._waiting = 0
        self._cond = threading.Condition()

    def enter(self, deadline: Optional[float] = None) -> bool:
        """True = admitted (caller MUST pair with leave()); False =
        saturated, answer 503."""
        from h2o3_tpu import telemetry
        gauge = telemetry.gauge("rest_inflight_requests")
        with self._cond:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                gauge.set(self._inflight)
                return True
            if self._waiting >= self.queue_depth:
                return False
            t_q = time.monotonic()
            limit = t_q + self.queue_wait_s
            if deadline is not None:
                limit = min(limit, deadline)
            self._waiting += 1
            try:
                while self._inflight >= self.max_inflight:
                    left = limit - time.monotonic()
                    if left <= 0:
                        return False
                    self._cond.wait(left)
                self._inflight += 1
                gauge.set(self._inflight)
                return True
            finally:
                self._waiting -= 1
                # queue-wait leg of the RED surface: how long admitted
                # AND timed-out requests sat waiting for a slot
                telemetry.histogram("rest_queue_wait_seconds").observe(
                    time.monotonic() - t_q)

    def leave(self) -> None:
        from h2o3_tpu import telemetry
        with self._cond:
            self._inflight -= 1
            telemetry.gauge("rest_inflight_requests").set(self._inflight)
            self._cond.notify()


def _gate_from_config() -> AdmissionGate:
    """Build the gate from config.ARGS with H2O3TPU_REST_* env overrides
    on top (same pattern as watchdog.policy_from_config: servers booted
    without init() still honor the knobs)."""
    import os
    from h2o3_tpu.core import config as _cfg
    env = os.environ.get
    a = _cfg.ARGS
    return AdmissionGate(
        max_inflight=int(env("H2O3TPU_REST_MAX_INFLIGHT",
                             a.rest_max_inflight)),
        queue_depth=int(env("H2O3TPU_REST_QUEUE_DEPTH",
                            a.rest_queue_depth)),
        queue_wait_s=float(env("H2O3TPU_REST_QUEUE_WAIT_S",
                               a.rest_queue_wait_s)))


def _max_body_bytes() -> int:
    import os
    from h2o3_tpu.core import config as _cfg
    mb = int(os.environ.get("H2O3TPU_REST_MAX_BODY_MB",
                            _cfg.ARGS.rest_max_body_mb))
    return mb << 20


# health checks, the metrics scrape, and job polling/cancel must keep
# answering while the gate rejects work — an overloaded node that stops
# ping/poll responses looks dead to every client and orchestrator
_EXEMPT_PREFIXES = ("/3/Ping", "/3/Metrics", "/3/Jobs")


def _admission_exempt(path: str) -> bool:
    return any(path == p or path.startswith(p + "/")
               for p in _EXEMPT_PREFIXES)


_UPLOAD_CHUNK = 1 << 20      # /3/PostFile disk-streaming block


def _job_key_of(out) -> Optional[str]:
    """Job key inside a handler response: ModelBuilderSchema-style
    {"job": JobV3} or a bare JobV3 at the root."""
    if not isinstance(out, dict):
        return None
    jd = out.get("job")
    if isinstance(jd, dict) and isinstance(jd.get("key"), dict):
        return jd["key"].get("name")
    meta = out.get("__meta")
    if isinstance(meta, dict) and meta.get("schema_name") == "JobV3" \
            and isinstance(out.get("key"), dict):
        return out["key"].get("name")
    return None


def _await_job_deadline(out, deadline: float, path: str):
    """A deadlined request that spawned a background job blocks until
    the job finishes or the deadline passes. Expiry cancels the job —
    the cooperative checks (Job.update / map_reduce cancel_point) stop
    it at the next chunk boundary, the job ends CANCELLED, and the
    client gets 408 instead of a leaked RUNNING job."""
    jk = _job_key_of(out)
    if not jk:
        return out, 200
    from h2o3_tpu import telemetry
    j = DKV.get(jk)
    while isinstance(j, Job) and j.status in ("CREATED", "RUNNING"):
        if time.monotonic() >= deadline:
            j.cancel()
            j.join(5.0)      # grace: one chunk boundary away
            telemetry.counter("request_deadline_exceeded_total").inc()
            err = _error_json(path, request_ctx.DeadlineExceeded(
                f"request deadline exceeded; job {jk} cancelled"), 408)
            err["values"] = {"job": jk,
                            "job_status": getattr(j, "status", "?")}
            return err, 408
        time.sleep(0.02)
        j = DKV.get(jk)
    if isinstance(j, Job):
        # finished inside the deadline: refresh the snapshot the client
        # sees (it was RUNNING when the handler returned)
        if isinstance(out.get("job"), dict):
            out["job"] = j.to_dict()
        elif _job_key_of(out) == jk and out.get("__meta", {}).get(
                "schema_name") == "JobV3":
            out = j.to_dict()
    return out, 200


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # route to our logger
        log.debug("http: " + fmt, *args)

    def _dispatch(self, method: str):
        try:
            self._dispatch_inner(method)
        except (BrokenPipeError, ConnectionResetError) as e:
            # the client hung up mid-request/mid-response — a normal
            # event under load, not a handler crash worth a traceback
            from h2o3_tpu import telemetry
            telemetry.counter("rest_client_disconnects_total").inc()
            log.info("client disconnected on %s %s: %r",
                     method, self.path, e)
            self.close_connection = True

    _DRAIN_CAP = 8 << 20

    def _drain(self, length: int) -> bool:
        """Consume a modest unread request body so an early error
        response can be read reliably and the connection stays usable;
        oversized bodies are left unread (the caller then closes the
        connection instead of swallowing gigabytes)."""
        if length > self._DRAIN_CAP:
            return False
        left = length
        while left > 0:
            chunk = self.rfile.read(min(_UPLOAD_CHUNK, left))
            if not chunk:
                break
            left -= len(chunk)
        return True

    def _respond(self, code: int, out, extra_headers: Optional[dict] = None,
                 close: bool = False):
        if isinstance(out, dict) and "__bytes__" in out:
            payload = out["__bytes__"]
            ctype = out.get("__ctype__", "application/octet-stream")
            extra_headers = {**(out.get("__headers__") or {}),
                             **(extra_headers or {})}
        elif isinstance(out, dict) and "__html__" in out:
            payload = out["__html__"].encode()
            ctype = "text/html; charset=utf-8"
        else:
            payload = json.dumps(_json_sanitize(out),
                                 default=_json_default).encode()
            ctype = "application/json"
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        tc = getattr(self, "_trace_ctx", None)
        if tc is not None:
            # every response names its trace — the client's handle into
            # GET /3/Trace?trace_id= (ISSUE 16)
            self.send_header("X-H2O-Trace-Id", tc.trace_id)
        for hk, hv in (extra_headers or {}).items():
            self.send_header(hk, hv)
        if close:
            # the body was not (fully) read: the connection cannot be
            # reused — the leftover bytes would parse as a new request
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        self.wfile.write(payload)

    def _dispatch_inner(self, method: str):
        from h2o3_tpu import telemetry
        from h2o3_tpu.telemetry import trace_context
        parsed = urllib.parse.urlparse(self.path)
        path = parsed.path
        params: Dict[str, str] = {
            k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}

        # -- distributed trace ingress (traceparent header) ------------
        # an incoming W3C-style traceparent joins the client's trace
        # (malformed → fresh trace, never a 4xx: tracing is telemetry);
        # _respond echoes the id as X-H2O-Trace-Id on EVERY response
        tc = trace_context.parse_traceparent(
            self.headers.get("traceparent"))
        self._trace_ctx = tc if tc is not None \
            else trace_context.new_context()

        # -- request deadline (?_timeout_ms= / X-H2O-Deadline-Ms) ------
        deadline = None
        tmo = params.pop("_timeout_ms", None)
        if tmo is None:
            tmo = self.headers.get("X-H2O-Deadline-Ms")
        if tmo is not None:
            try:
                tmo_ms = float(tmo)
            except (TypeError, ValueError):
                return self._respond(400, _error_json(path, ValueError(
                    f"malformed deadline {tmo!r} "
                    f"(expected milliseconds)"), 400))
            if tmo_ms > 0:
                deadline = time.monotonic() + tmo_ms / 1000.0

        # -- Content-Length must be a clean non-negative integer -------
        raw_len = self.headers.get("Content-Length")
        try:
            length = int(raw_len) if raw_len else 0
            if length < 0:
                raise ValueError(raw_len)
        except (TypeError, ValueError):
            telemetry.counter("rest_rejected_total",
                              reason="bad_content_length").inc()
            return self._respond(400, _error_json(path, ValueError(
                f"malformed Content-Length: {raw_len!r}"), 400),
                close=True)

        # -- admission control (exempt: ping/metrics/job polling) ------
        exempt = _admission_exempt(path)
        if not exempt and not _GATE.enter(deadline=deadline):
            telemetry.counter("rest_rejected_total",
                              reason="saturated").inc()
            drained = self._drain(length)
            return self._respond(503, _error_json(path, RuntimeError(
                f"server saturated ({_GATE.max_inflight} in flight, "
                f"{_GATE.queue_depth} queued); retry later"), 503),
                extra_headers={"Retry-After": "1"}, close=not drained)
        try:
            self._handle(method, path, params, length, deadline)
        finally:
            if not exempt:
                _GATE.leave()

    def _post_file(self, path: str, length: int):
        """Raw file-body upload (h2o-py sends the file bytes as the
        request body, h2o-py/h2o/backend/connection.py:473) — streamed
        to disk in 1 MiB blocks so a multi-GB upload never buffers in
        handler memory."""
        import tempfile
        first = self.rfile.read(min(length, _UPLOAD_CHUNK)) \
            if length else b""
        # the client sends no filename: sniff the container format so
        # the extension-dispatching parser picks the right reader
        if first[:4] == b"PK\x03\x04":
            suffix = ".zip"
        elif first[:2] == b"\x1f\x8b":
            suffix = ".csv.gz"
        elif first[:4] == b"PAR1":
            suffix = ".parquet"
        else:
            suffix = ".csv"
        fd, tmp = tempfile.mkstemp(prefix="h2o3tpu_upload_",
                                   suffix=suffix)
        total = len(first)
        with open(fd, "wb") as f:
            f.write(first)
            while total < length:
                chunk = self.rfile.read(min(_UPLOAD_CHUNK,
                                            length - total))
                if not chunk:
                    break
                f.write(chunk)
                total += len(chunk)
        self._respond(200, {"destination_frame": tmp,
                            "total_bytes": total})

    def _handle(self, method: str, path: str, params: Dict[str, str],
                length: int, deadline: Optional[float]):
        from h2o3_tpu import telemetry
        if path.startswith("/3/PostFile"):
            return self._post_file(path, length)
        max_body = _max_body_bytes()
        if length > max_body:
            telemetry.counter("rest_rejected_total",
                              reason="body_too_large").inc()
            drained = self._drain(length)
            return self._respond(413, _error_json(path, ValueError(
                f"request body of {length} bytes exceeds the "
                f"{max_body >> 20} MB cap (H2O3TPU_REST_MAX_BODY_MB); "
                f"use /3/PostFile for large uploads"), 413),
                close=not drained)
        raw = self.rfile.read(length) if length else b""
        body = raw.decode("utf-8", "replace")
        ctype = self.headers.get("Content-Type", "")
        if "json" in ctype and body:
            try:
                params.update(json.loads(body))
            except json.JSONDecodeError as e:
                # a body the client MARKED as JSON but that does not
                # parse must fail loudly — silently ignoring it ran
                # handlers with half the intended parameters
                return self._respond(400, _error_json(path, ValueError(
                    f"malformed JSON body: {e}"), 400))
        elif body:
            params.update({k: v[0]
                           for k, v in urllib.parse.parse_qs(body).items()})
        # h2o-py style clients ship every parameter form-encoded in the
        # body: honor a _timeout_ms that arrived there too (query-string
        # and header deadlines were already parsed pre-admission)
        tmo = params.pop("_timeout_ms", None)
        if tmo is not None and deadline is None:
            try:
                tmo_ms = float(tmo)
            except (TypeError, ValueError):
                return self._respond(400, _error_json(path, ValueError(
                    f"malformed deadline {tmo!r} "
                    f"(expected milliseconds)"), 400))
            if tmo_ms > 0:
                deadline = time.monotonic() + tmo_ms / 1000.0
        from h2o3_tpu.utils.timeline import record as _tl_record
        for m, rx, fn in ROUTES:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                # endpoint label = the route PATTERN (bounded
                # cardinality), not the raw path with its keys
                endpoint = rx.pattern.strip("^$")
                telemetry.counter("rest_requests_total", method=method,
                                  endpoint=endpoint).inc()
                t_req = time.monotonic()
                retry_after = "1"
                redirect_loc = None
                try:
                    # the deadline and trace context ride contextvars:
                    # any Job the handler creates captures both
                    # (core/job.py), the cooperative checks enforce the
                    # deadline at chunk boundaries, and every span the
                    # handler opens is stamped with the request's trace
                    from h2o3_tpu.telemetry import trace_context
                    with request_ctx.deadline_scope(deadline), \
                            trace_context.trace_scope(
                                getattr(self, "_trace_ctx", None)), \
                            telemetry.span("rest", method=method,
                                           endpoint=endpoint):
                        # recorded INSIDE the span so the Timeline event
                        # carries this request's span id
                        _tl_record("rest", f"{method} {path}")
                        out = fn(params, body, **match.groupdict())
                    code = 200
                except request_ctx.DeadlineExceeded as e:
                    out = _error_json(path, e, 408)
                    code = 408
                except KeyError as e:
                    out = _error_json(path, e, 404)
                    code = 404
                except ValueError as e:
                    # user-input errors → 412 + H2OErrorV3, which the
                    # real h2o-py maps to H2OResponseError
                    # (EnvironmentError) — raw 500s become
                    # H2OServerError and break every pyunit that
                    # asserts on invalid parameters
                    # (water/api/RequestServer.java:371 error path).
                    # Logged with traceback: an internal bug surfacing
                    # as ValueError must stay diagnosable server-side.
                    log.warning("412 on %s %s: %s", method, path, e,
                                exc_info=True)
                    out = _error_json(path, e, 412)
                    code = 412
                except QueueSaturated as e:
                    # per-model predict queue full: the AdmissionGate
                    # overload contract applied to the scoring queue
                    telemetry.counter("rest_rejected_total",
                                      reason="predict_queue_full").inc()
                    out = _error_json(path, e, 503)
                    code = 503
                except BatcherDraining as e:
                    # serving tier shutting down: queued/new predicts
                    # fail fast 503 instead of hanging on a closing
                    # dispatcher (ISSUE 17 graceful drain)
                    telemetry.counter("rest_rejected_total",
                                      reason="draining").inc()
                    out = _error_json(path, e, 503)
                    code = 503
                except DataLostError as e:
                    # a frame proven unrecoverable (peer death, no
                    # mirror or replayable lineage): 410 Gone in
                    # H2OErrorV3 shape — typed and terminal, a retry
                    # cannot bring the data back (core/durability.py)
                    telemetry.counter("rest_rejected_total",
                                      reason="data_lost").inc()
                    out = _error_json(path, e, 410)
                    code = 410
                except FleetUnavailable as e:
                    # every replica unhealthy: explicit degradation —
                    # 503 + Retry-After in H2OErrorV3 shape, never a
                    # hang (serving/fleet.py routing contract)
                    telemetry.counter("rest_rejected_total",
                                      reason="fleet_unavailable").inc()
                    retry_after = str(max(
                        1, int(round(e.retry_after_s))))
                    out = _error_json(path, e, 503)
                    code = 503
                except Exception as e:   # noqa: BLE001 - request boundary
                    log.exception("handler error on %s %s", method, path)
                    out = _error_json(path, e, 500)
                    code = 500
                if code == 200 and isinstance(out, dict) \
                        and "__redirect__" in out:
                    # fleet 307: same-method redirect at the chosen
                    # replica (serving/fleet.py routing contract)
                    redirect_loc = out["__redirect__"]
                    out = {"location": redirect_loc}
                    code = 307
                if code == 200 and deadline is not None:
                    out, code = _await_job_deadline(out, deadline, path)
                # RED per-route latency: the duration leg next to the
                # rest_requests_total rate leg (route = bounded pattern,
                # status = final HTTP code incl. the 408 deadline path)
                telemetry.histogram("rest_request_seconds",
                                    route=endpoint,
                                    status=str(code)).observe(
                    time.monotonic() - t_req)
                extra = None
                if code == 503:
                    extra = {"Retry-After": retry_after}
                elif code == 307:
                    extra = {"Location": redirect_loc}
                return self._respond(code, out, extra_headers=extra)
        _tl_record("rest", f"{method} {path}", status=404)
        telemetry.counter("rest_requests_total", method=method,
                          endpoint="(no_route)").inc()
        self._respond(404, {"msg": f"no route {method} {path}"})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


_GATE = _gate_from_config()


def _error_json(path: str, e: Exception, status: int) -> dict:
    """H2OErrorV3 wire shape (water/api/schemas3/H2OErrorV3.java) — the
    real h2o-py turns this into an H2OResponseError with .msg etc."""
    import time
    import traceback
    return {"__meta": {"schema_version": 3, "schema_name": "H2OErrorV3",
                       "schema_type": "H2OError"},
            "timestamp": int(time.time() * 1000),
            "error_url": path, "msg": str(e),
            "dev_msg": str(e), "http_status": status, "values": {},
            "exception_type": type(e).__name__,
            "exception_msg": str(e),
            "stacktrace": traceback.format_exc().splitlines()[-10:]}


def _json_default(o):
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, float) and np.isnan(o):
        return None
    return str(o)


def _nan_str_list(vals):
    """ColV3 data cells: NaN→"NaN", ±inf→"Infinity"/"-Infinity"
    (AutoBuffer JSON_NAN/JSON_POS_INF strings)."""
    out = []
    for v in vals:
        if isinstance(v, np.generic):
            v = v.item()
        if isinstance(v, float):
            if np.isnan(v):
                v = "NaN"
            elif np.isinf(v):
                v = "Infinity" if v > 0 else "-Infinity"
        out.append(v)
    return out


def _json_sanitize(o):
    """Strict-JSON cleanup: NaN/Infinity become null everywhere EXCEPT
    ColV3 ``data`` arrays — there NA cells ride as the STRING "NaN",
    exactly the reference wire (AutoBuffer.putJSON8d emits the quoted
    JSON_NAN string, water/AutoBuffer.java:2006); h2o-py decodes
    'x == "NaN"' back to float nan (h2o-py/h2o/expr.py:392) before
    probing math.isnan (expr.py:416)."""
    if isinstance(o, dict):
        meta = o.get("__meta")
        if isinstance(meta, dict) and meta.get("schema_name") == "ColV3":
            return {k: (_nan_str_list(o[k]) if k == "data" and o[k]
                        else _json_sanitize(v))
                    for k, v in o.items()}
        return {k: _json_sanitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_json_sanitize(v) for v in o]
    if isinstance(o, np.generic):
        o = o.item()
    if isinstance(o, float) and (np.isnan(o) or np.isinf(o)):
        return None
    return o


_SERVER: Optional[ThreadingHTTPServer] = None
_THREAD: Optional[threading.Thread] = None


def start_server(port: int = 54321, background: bool = True) -> int:
    """Start the REST server (water.api.RequestServer.start).

    Returns the bound port (0 picks an ephemeral port)."""
    global _SERVER, _THREAD, _GATE
    # rebuild the admission gate at boot: init() rebinds config.ARGS and
    # H2O3TPU_REST_* env knobs set after import must take effect
    _GATE = _gate_from_config()
    _SERVER = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    actual = _SERVER.server_address[1]
    log.info("REST server on http://127.0.0.1:%d (/3, /99)", actual)
    # publish this node's REST edge in the fleet registry: peers route
    # predictions here by ACTUAL bound port (ephemeral binds included)
    try:
        from h2o3_tpu.serving import fleet
        fleet.set_local_endpoint(actual)
    except Exception as e:   # noqa: BLE001 - registry is best-effort
        log.debug("fleet endpoint publish failed: %s", e)
    if background:
        _THREAD = threading.Thread(target=_SERVER.serve_forever, daemon=True)
        _THREAD.start()
    else:
        _SERVER.serve_forever()
    return actual


def stop_server():
    global _SERVER
    try:
        from h2o3_tpu.serving import fleet
        fleet.clear_local_endpoint()
    except Exception:        # noqa: BLE001
        pass
    if _SERVER is not None:
        _SERVER.shutdown()
        _SERVER = None


# schema-metadata endpoints live in api/metadata.py; register them into the
# same ROUTES table at import time
_register_metadata_routes()
