"""ModelSchemaV3 / ModelMetrics*V3 wire producers.

Reference: water/api/schemas3/ModelSchemaV3.java (model_id/parameters/
output), hex/schemas/*ModelV3, ModelMetrics*V3 (one schema per problem
type), TwoDimTableV3 (column-major data), and the thresholds table AUC2
serves (hex/AUC2.java). The real h2o-py builds its model objects straight
from this JSON (h2o-py/h2o/estimators/estimator_base.py:357
_resolve_model; metrics objects via h2o/model/metrics/__init__.py:18
make_metrics keyed on __meta.schema_name), so field names here ARE the
contract.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from h2o3_tpu.models.model import Model


def twodim(name: str, col_names: List[str], col_types: List[str],
           rows: List[list], description: str = "",
           col_formats: Optional[List[str]] = None,
           row_headers: Optional[List[str]] = None) -> dict:
    """TwoDimTableV3: data is COLUMN-major on the wire
    (water/api/schemas3/TwoDimTableV3.java; h2o-py transposes it back in
    H2OTwoDimTable._parse_values). ``row_headers`` prepends the
    reference's unnamed row-header column — clients index cell_values
    positionally, so its presence must match the reference table."""
    fmts = col_formats or ["%s" if t == "string" else "%f"
                           for t in col_types]
    if row_headers is not None:
        col_names = [""] + list(col_names)
        col_types = ["string"] + list(col_types)
        fmts = ["%s"] + list(fmts)
        rows = [[str(h)] + list(r) for h, r in zip(row_headers, rows)]
    ncol = len(col_names)
    data = [[_clean(r[j]) for r in rows] for j in range(ncol)]
    return {
        "__meta": {"schema_version": 3, "schema_name": "TwoDimTableV3",
                   "schema_type": "TwoDimTable"},
        "name": name, "description": description,
        "columns": [{"__meta": {"schema_name": "ColumnSpecsBase"},
                     "name": n, "type": t, "format": f, "description": n}
                    for n, t, f in zip(col_names, col_types, fmts)],
        "rowcount": len(rows),
        "data": data,
    }


def _clean(v):
    if v is None:
        return None
    if isinstance(v, (np.generic,)):
        v = v.item()
    if isinstance(v, float) and (np.isnan(v) or np.isinf(v)):
        return None
    return v


# ---------------------------------------------------------------- binomial


def _binomial_tables(mm) -> dict:
    """thresholds_and_metric_scores + max_criteria_and_metric_scores from
    the 400-bin histogram (hex/AUC2.java column layout — index 11..14
    must be tns/fns/fps/tps, h2o-py/h2o/model/metrics/binomial.py:760)."""
    hist = getattr(mm, "hist", None)
    if hist is None:
        return {}
    pos, neg = (np.asarray(h, np.float64) for h in hist)
    nb = len(pos)
    used = np.nonzero((pos > 0) | (neg > 0))[0][::-1]   # high→low threshold
    if len(used) == 0:
        return {}
    P, N = pos.sum(), neg.sum()
    tp_c = np.cumsum(pos[::-1])[::-1]
    fp_c = np.cumsum(neg[::-1])[::-1]
    cols = ["threshold", "f1", "f2", "f0point5", "accuracy", "precision",
            "recall", "specificity", "absolute_mcc",
            "min_per_class_accuracy", "mean_per_class_accuracy",
            "tns", "fns", "fps", "tps", "tnr", "fnr", "fpr", "tpr", "idx"]
    rows = []
    for i, b in enumerate(used):
        tps, fps = tp_c[b], fp_c[b]
        fns, tns = P - tps, N - fps
        prec = tps / max(tps + fps, 1e-12)
        rec = tps / max(P, 1e-12)
        spec = tns / max(N, 1e-12)
        acc = (tps + tns) / max(P + N, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        f2 = 5 * prec * rec / max(4 * prec + rec, 1e-12)
        f05 = 1.25 * prec * rec / max(0.25 * prec + rec, 1e-12)
        denom = np.sqrt(max((tps + fps) * (tps + fns)
                            * (tns + fps) * (tns + fns), 1e-12))
        mcc = abs((tps * tns - fps * fns) / denom)
        mpca = min(rec, spec)
        rows.append([b / nb, f1, f2, f05, acc, prec, rec, spec, mcc,
                     mpca, (rec + spec) / 2,
                     tns, fns, fps, tps,
                     spec, fns / max(P, 1e-12), fps / max(N, 1e-12), rec,
                     i])
    arr = np.array([r[:-1] for r in rows], np.float64)
    crit_rows = []
    # (metric name, column index, maximize?) — reference criteria order
    for label, ci in (("max f1", 1), ("max f2", 2), ("max f0point5", 3),
                      ("max accuracy", 4), ("max precision", 5),
                      ("max recall", 6), ("max specificity", 7),
                      ("max absolute_mcc", 8),
                      ("max min_per_class_accuracy", 9),
                      ("max mean_per_class_accuracy", 10),
                      ("max tns", 11), ("max fns", 12), ("max fps", 13),
                      ("max tps", 14), ("max tnr", 15), ("max fnr", 16),
                      ("max fpr", 17), ("max tpr", 18)):
        k = int(np.argmax(arr[:, ci]))
        crit_rows.append([label, float(arr[k, 0]), float(arr[k, ci]), k])
    types = ["float64"] * 19 + ["int32"]
    return {
        "thresholds_and_metric_scores": twodim(
            "Metrics for Thresholds", cols, types, rows,
            "Binomial metrics as a function of classification thresholds"),
        "max_criteria_and_metric_scores": twodim(
            "Maximum Metrics", ["metric", "threshold", "value", "idx"],
            ["string", "float64", "float64", "int32"], crit_rows,
            "Maximum metrics at their respective thresholds"),
    }


# ---------------------------------------------------------------- metrics


_METRIC_SCHEMA = {
    "Binomial": ("ModelMetricsBinomialV3", "ModelMetricsBinomial"),
    "Multinomial": ("ModelMetricsMultinomialV3", "ModelMetricsMultinomial"),
    "Regression": ("ModelMetricsRegressionV3", "ModelMetricsRegression"),
    "Clustering": ("ModelMetricsClusteringV3", "ModelMetricsClustering"),
    "AnomalyDetection": ("ModelMetricsAnomalyV3", "ModelMetricsAnomaly"),
    "DimReduction": ("ModelMetricsPCAV3", "ModelMetricsPCA"),
    "Ordinal": ("ModelMetricsOrdinalV3", "ModelMetricsOrdinal"),
}


def metrics_v3(mm, model: Model, frame_key: str = "",
               domain: Optional[List[str]] = None) -> Optional[dict]:
    """One ModelMetrics*V3 payload."""
    if mm is None:
        return None
    d = mm.to_dict() if hasattr(mm, "to_dict") else dict(mm)
    kind = d.get("model_category") or d.get("kind") or "Regression"
    schema, stype = _METRIC_SCHEMA.get(
        kind, ("ModelMetricsRegressionV3", "ModelMetricsRegression"))
    if model.algo in ("glm", "gam") and kind in ("Binomial", "Regression",
                                                 "Multinomial"):
        schema = schema.replace("V3", "GLMV3")
        stype = stype + "GLM"
    out = {
        "__meta": {"schema_version": 3, "schema_name": schema,
                   "schema_type": stype},
        "model": {"name": model.key, "type": "Key<Model>"},
        "model_category": kind,
        "frame": {"name": frame_key, "type": "Key<Frame>"},
        "description": None,
        "scoring_time": 0,
        "MSE": _clean(d.get("MSE")), "RMSE": _clean(d.get("RMSE")),
        "nobs": int(d.get("nobs") or 0),
        "custom_metric_name": d.get("custom_metric_name"),
        "custom_metric_value": _clean(d.get("custom")),
    }
    dom = domain or d.get("domain") or model.output.get("domain")
    if kind == "Binomial":
        out.update({
            "AUC": _clean(d.get("AUC")), "pr_auc": _clean(d.get("pr_auc")),
            "Gini": _clean(d.get("Gini")),
            "logloss": _clean(d.get("logloss")),
            "mean_per_class_error": _clean(d.get("mean_per_class_error")),
            "domain": dom,
            "gains_lift_table": None,
            # present-but-None when no score histogram exists (e.g. DRF
            # with sample_rate=1.0 → no OOB rows): the client reads
            # these keys unconditionally (pyunit_no_oob_prostateRF)
            "thresholds_and_metric_scores": None,
            "max_criteria_and_metric_scores": None,
        })
        out.update(_binomial_tables(mm))
    elif kind == "Multinomial":
        cm = d.get("confusion_matrix")
        cm_table = None
        if cm is not None and dom:
            k = len(cm)
            names = list(dom) + ["Error", "Rate"]
            rows = []
            for i in range(k):
                rowsum = float(np.sum(cm[i]))
                err = 1.0 - (cm[i][i] / rowsum if rowsum else 0.0)
                wrong = int(rowsum - cm[i][i])
                rows.append(list(np.asarray(cm[i], np.float64)) +
                            [err, f"{wrong:,} / {int(rowsum):,}"])
            tot = float(np.sum(cm))
            diag = float(np.trace(np.asarray(cm)))
            rows.append([float(np.sum(np.asarray(cm)[:, j]))
                         for j in range(k)] +
                        [1.0 - diag / max(tot, 1e-12),
                         f"{int(tot - diag):,} / {int(tot):,}"])
            cm_table = twodim(
                "Confusion Matrix", names,
                ["float64"] * k + ["float64", "string"], rows,
                "Row labels: Actual class; Column labels: Predicted class")
        out.update({
            "logloss": _clean(d.get("logloss")),
            "mean_per_class_error": _clean(d.get("mean_per_class_error")),
            # multinomial AUC/AUCPR exist as fields the client probes
            # unconditionally (metrics_base.py:126); None = "not computed"
            "AUC": _clean(d.get("AUC")), "pr_auc": _clean(d.get("pr_auc")),
            "multinomial_auc_table": _multinomial_auc_table(
                d.get("multinomial_auc_rows"), "AUC"),
            "multinomial_aucpr_table": _multinomial_auc_table(
                d.get("multinomial_aucpr_rows"), "auc_pr"),
            "cm": {"__meta": {"schema_version": 3,
                              "schema_name": "ConfusionMatrixV3",
                              "schema_type": "ConfusionMatrix"},
                   "table": cm_table} if cm_table else None,
            "hit_ratio_table": None,
            "domain": dom,
        })
    elif kind == "Regression":
        out.update({
            "mae": _clean(d.get("mae")),
            "rmsle": _clean(d.get("rmsle")),
            "r2": _clean(d.get("r2")),
            "mean_residual_deviance": _clean(d.get("mean_residual_deviance")),
        })
        if model.algo in ("glm", "gam"):
            out.update({
                "null_deviance": _clean(d.get("null_deviance")),
                "residual_deviance": _clean(d.get("residual_deviance")),
                "AIC": _clean(d.get("AIC") or d.get("aic")),
                "null_degrees_of_freedom": d.get("null_degrees_of_freedom"),
                "residual_degrees_of_freedom":
                    d.get("residual_degrees_of_freedom"),
            })
    elif kind == "Clustering":
        cs = d.get("centroid_stats")
        cs_table = None
        if isinstance(cs, dict) and cs.get("size") is not None:
            sizes = cs["size"]
            wss = cs.get("within_cluster_sum_of_squares",
                         [None] * len(sizes))
            rows = [[i + 1, float(sizes[i]),
                     _clean(wss[i]) if i < len(wss) else None]
                    for i in range(len(sizes))]
            cs_table = twodim(
                "Centroid Statistics",
                ["centroid", "size", "within_cluster_sum_of_squares"],
                ["int32", "float64", "float64"], rows,
                row_headers=[str(i + 1) for i in range(len(rows))])
        out.update({
            "tot_withinss": _clean(d.get("tot_withinss")),
            "totss": _clean(d.get("totss")),
            "betweenss": _clean(d.get("betweenss")),
            "centroid_stats": cs_table,
        })
    if model.algo in ("glm", "gam") and kind == "Binomial":
        out.update({
            "null_deviance": _clean(d.get("null_deviance")),
            "residual_deviance": _clean(d.get("residual_deviance")),
            "AIC": _clean(d.get("AIC") or d.get("aic")),
            "null_degrees_of_freedom": d.get("null_degrees_of_freedom"),
            "residual_degrees_of_freedom":
                d.get("residual_degrees_of_freedom"),
        })
    # pass through anything scalar we haven't mapped (harmless extras)
    for k, v in d.items():
        if k not in out and isinstance(v, (int, float, str, type(None))):
            out[k] = _clean(v)
    return out


# ------------------------------------------------------------------ model


_CATEGORY_WIRE = {"AnomalyDetection": "AnomalyDetection"}


def _params_v3(model: Model) -> List[dict]:
    from h2o3_tpu.models import get_builder
    try:
        cls = get_builder(model.algo)
        defaults = dict(getattr(cls, "DEFAULTS", {}))
    except Exception:
        defaults = {}
    hidden = set()
    try:
        hidden = set(getattr(cls, "SCHEMA_HIDDEN_PARAMS", ()))
    except Exception:
        pass
    names = sorted((set(defaults) | set(model.params)) - hidden)
    # wire spellings differ from our internal python-safe names
    wire_names = {"lambda_": "lambda",
                  "tweedie_power": "tweedie_variance_power"}
    out = [
        # pseudo-parameters every reference schema carries; clients
        # rebuild estimators from this list (pyunit_parametersKmeans
        # deletes these names explicitly)
        {"__meta": {"schema_version": 3,
                    "schema_name": "ModelParameterSchemaV3",
                    "schema_type": "Iced"},
         "name": nm, "label": nm, "help": nm, "required": False,
         "type": "Key", "default_value": None,
         "actual_value": av_, "input_value": av_,
         "level": "critical", "values": [], "gridable": False,
         "is_member_of_frames": [], "is_mutually_exclusive_with": []}
        for nm, av_ in (
            ("model_id", {"name": model.key, "type": "Key<Model>"}),
            ("training_frame",
             {"name": str(model.output.get("training_frame") or ""),
              "type": "Key<Frame>"}),
            ("validation_frame", None),
            ("max_runtime_secs", 0.0),
        ) + ((("response_column", model.output.get("response")),)
             if model.output.get("response") else ())
        if nm not in defaults and nm not in model.params]
    for n in names:
        dv = defaults.get(n)
        av = model.params.get(n, dv)
        if n == "checkpoint" and av is not None and not isinstance(av, str):
            # a donor passed as a Model object serializes as its key
            # (the wire type is Key<Model>, h2o-py sends the key string)
            av = getattr(av, "key", str(av))
        # numpy scalars (e.g. np.bool_ from grid hyper expansion) must
        # become native JSON types, not str() — a wire "False" breaks
        # pyunit expect_model_param's float(actual) coercion
        if isinstance(av, np.generic):
            av = av.item()
        if isinstance(dv, np.generic):
            dv = dv.item()
        if not isinstance(av, (int, float, str, bool, list, type(None))):
            av = str(av)
        if not isinstance(dv, (int, float, str, bool, list, type(None))):
            dv = str(dv)
        wn = wire_names.get(n, n)
        out.append({
            "__meta": {"schema_version": 3,
                       "schema_name": "ModelParameterSchemaV3",
                       "schema_type": "Iced"},
            "name": wn, "label": wn, "help": wn, "required": False,
            "type": type(av).__name__ if av is not None else "string",
            "default_value": dv, "actual_value": av,
            "input_value": av,
            "level": "critical", "values": [], "gridable": True,
            "is_member_of_frames": [], "is_mutually_exclusive_with": [],
        })
    return out


def _multinomial_auc_table(rows, metric: str) -> Optional[dict]:
    """hex/MultinomialAUC.java getTable wire twin: row headers
    '<class> vs Rest' / 'Macro OVR' / '<a> vs <b>' / 'Weighted OVO',
    columns [First class domain, Second class domain, <metric>]."""
    if not rows:
        return None
    return twodim(
        f"Multinomial {metric} values",
        ["First class domain", "Second class domain", metric],
        ["string", "string", "double"],
        [[r[1], r[2], r[3]] for r in rows],
        f"Multinomial {metric} values",
        row_headers=[r[0] for r in rows])


def _varimp_table(model: Model) -> Optional[dict]:
    vi = model.output.get("varimp")
    if not vi:
        return None
    # stored as [(name, relative)] or dicts
    rows = []
    if isinstance(vi[0], dict):
        pairs = [(v["variable"], float(v["relative_importance"]))
                 for v in vi]
    else:   # tuples (name, relative[, scaled, pct]) — extras recomputed
        pairs = [(str(t[0]), float(t[1])) for t in vi]
    total = sum(p[1] for p in pairs) or 1.0
    mx = max((p[1] for p in pairs), default=1.0) or 1.0
    for name, rel in sorted(pairs, key=lambda p: -p[1]):
        rows.append([name, rel, rel / mx, rel / total])
    return twodim("Variable Importances",
                  ["variable", "relative_importance", "scaled_importance",
                   "percentage"],
                  ["string", "float64", "float64", "float64"], rows)


def _history_table(model: Model) -> Optional[dict]:
    hist = model.output.get("scoring_history")
    if not hist:
        return None
    keys = list(hist[0].keys())
    rows = [[_clean(h.get(k)) for k in keys] for h in hist]
    types = ["string" if isinstance(rows[0][i], str) else "float64"
             for i in range(len(keys))]
    return twodim("Scoring History", keys, types, rows)


def model_to_v3(model: Model) -> dict:
    """Full ModelSchemaV3 payload for GET /3/Models/{id}."""
    out_src = model.output
    category = out_src.get("category") or "Regression"
    names = list(out_src.get("names") or [])
    response = out_src.get("response")
    domain = out_src.get("domain")
    col_names = names + ([response] if response else [])
    domains: List[Optional[List[str]]] = [None] * len(names) + \
        ([list(domain)] if response and domain else
         ([None] if response else []))
    output = {
        "__meta": {"schema_version": 3,
                   "schema_name": "ModelOutputSchemaV3",
                   "schema_type": "ModelOutput"},
        "model_category": _CATEGORY_WIRE.get(category, category),
        "names": col_names,
        "column_types": [],
        "domains": domains,
        "response_column_name": response,
        "status": "DONE",
        "start_time": 0, "end_time": 0,
        "run_time": int(out_src.get("run_time_ms") or 0),
        "default_threshold": _clean(out_src.get("default_threshold")),
        "training_metrics": metrics_v3(model.training_metrics, model),
        "validation_metrics": metrics_v3(model.validation_metrics, model),
        "cross_validation_metrics":
            metrics_v3(model.cross_validation_metrics, model),
        "cross_validation_metrics_summary": (
            twodim("Cross-Validation Metrics Summary",
                   ["mean", "sd"] + [
                       f"cv_{i + 1}_valid" for i in range(
                           int(out_src.get("cv_summary_nfolds") or 0))],
                   ["float64"] * (2 + int(out_src.get("cv_summary_nfolds")
                                          or 0)),
                   [r[1:] for r in out_src["cv_summary_rows"]],
                   row_headers=[r[0] for r in out_src["cv_summary_rows"]])
            if out_src.get("cv_summary_rows") else None),
        "cross_validation_models":
            [{"name": k, "type": "Key<Model>"} for k in
             out_src.get("cv_model_keys", [])] or None,
        "cross_validation_predictions":
            [{"name": k, "type": "Key<Frame>"} for k in
             (out_src.get("cv_predictions_keys") or [])] or None,
        "cross_validation_holdout_predictions_frame_id":
            ({"name": out_src["cv_holdout_frame_key"],
              "type": "Key<Frame>"}
             if out_src.get("cv_holdout_frame_key") else None),
        "cross_validation_fold_assignment_frame_id":
            ({"name": out_src["cv_fold_assignment_key"],
              "type": "Key<Frame>"}
             if out_src.get("cv_fold_assignment_key") else None),
        "scoring_history": _history_table(model),
        "variable_importances": _varimp_table(model),
        "model_summary": None,
        "help": {},
    }
    # DeepLearning export_weights_and_biases: frame key refs the client
    # fetches via output.weights[i].URL (h2o-py/h2o/model/model_base.py:340)
    if out_src.get("weights_keys"):
        output["weights"] = [
            {"name": k, "type": "Key<Frame>", "URL": f"/3/Frames/{k}"}
            for k in out_src["weights_keys"]]
        output["biases"] = [
            {"name": k, "type": "Key<Frame>", "URL": f"/3/Frames/{k}"}
            for k in out_src.get("biases_keys", [])]

    # GLM/GAM: coefficients_table with raw + standardized coefficients
    # (hex/glm GLMModel output; client coef()/coef_norm() read it,
    # h2o-py/h2o/model/model_base.py:685)
    if model.algo in ("glm", "gam") and getattr(model, "coef", None) \
            is not None and out_src.get("coef_names") is not None \
            and getattr(model, "coef_multinomial", None) is None \
            and out_src.get("family") != "ordinal":
        names = list(out_src["coef_names"]) + ["Intercept"]
        coefs = np.asarray(model.coef, np.float64)
        mus = np.asarray(out_src.get("coef_means") or
                         [0.0] * (len(names) - 1), np.float64)
        sds = np.asarray(out_src.get("coef_sds") or
                         [1.0] * (len(names) - 1), np.float64)
        if out_src.get("standardized"):
            from h2o3_tpu.models.glm import destandardize_coefs
            std_c = coefs.copy()
            raw = destandardize_coefs(coefs, mus, sds)
        else:
            raw = coefs.copy()
            std_c = coefs.copy()
            std_c[:-1] = raw[:-1] * sds
            std_c[-1] = raw[-1] + float(np.sum(raw[:-1] * mus))
        rows = [[nm, float(rc_), float(sc_)]
                for nm, rc_, sc_ in zip(names, raw, std_c)]
        rows = [rows[-1]] + rows[:-1]     # Intercept first (reference order)
        output["coefficients_table"] = twodim(
            "Coefficients",
            ["names", "coefficients", "standardized_coefficients"],
            ["string", "float64", "float64"], rows,
            "glm coefficients")
        if output.get("variable_importances") is None:
            # GLM varimp = |standardized coefficient| (hex/glm GLMModel
            # standardized-coefficient-magnitudes table)
            mags = sorted(zip(names[:-1], np.abs(std_c[:-1])),
                          key=lambda t: -t[1])
            mx = max((m for _, m in mags), default=1.0) or 1.0
            tot = sum(m for _, m in mags) or 1.0
            output["variable_importances"] = twodim(
                "Standardized Coefficient Magnitudes",
                ["variable", "relative_importance", "scaled_importance",
                 "percentage"],
                ["string", "float64", "float64", "float64"],
                [[nm, float(m), float(m / mx), float(m / tot)]
                 for nm, m in mags])

    # multinomial GLM coefficient tables: indexed-class headers plus the
    # class-named twin (GLMModel output coefficients_table and
    # coefficients_table_multinomials_with_class_names — PUBDEV-6062)
    if model.algo in ("glm", "gam") and \
            getattr(model, "coef_multinomial", None) is not None and \
            out_src.get("coef_names") is not None and \
            out_src.get("family") != "ordinal" and \
            output.get("coefficients_table") is None:
        B = np.asarray(model.coef_multinomial, np.float64)   # [P+1, K]
        names_m = list(out_src["coef_names"]) + ["Intercept"]
        K = B.shape[1]
        mus = np.asarray(out_src.get("coef_means") or
                         [0.0] * (len(names_m) - 1), np.float64)
        sds = np.asarray(out_src.get("coef_sds") or
                         [1.0] * (len(names_m) - 1), np.float64)
        if out_src.get("standardized"):
            from h2o3_tpu.models.glm import destandardize_coefs
            std_B = B
            raw_B = np.stack([destandardize_coefs(B[:, k], mus, sds)
                              for k in range(K)], axis=1)
        else:
            raw_B = B
            std_B = np.empty_like(B)
            std_B[:-1] = raw_B[:-1] * sds[:, None]
            std_B[-1] = raw_B[-1] + raw_B[:-1].T @ mus
        rows = [[nm] + [float(v) for v in raw_B[i]]
                + [float(v) for v in std_B[i]]
                for i, nm in enumerate(names_m)]
        rows = [rows[-1]] + rows[:-1]    # Intercept first
        dom = list(out_src.get("domain") or [str(k) for k in range(K)])
        types_m = ["string"] + ["float64"] * (2 * K)
        output["coefficients_table"] = twodim(
            "Coefficients",
            ["names"] + [f"coefs_class_{k}" for k in range(K)]
            + [f"std_coefs_class_{k}" for k in range(K)],
            types_m, rows, "glm multinomial coefficients")
        output["coefficients_table_multinomials_with_class_names"] = twodim(
            "Coefficients",
            ["names"] + [f"coefs_class_{d}" for d in dom]
            + [f"std_coefs_class_{d}" for d in dom],
            types_m, rows, "glm multinomial coefficients")

    # multinomial GLM varimp: mean |standardized coef| across classes
    if model.algo in ("glm", "gam") and \
            getattr(model, "coef_multinomial", None) is not None and \
            out_src.get("coef_names") is not None and \
            output.get("variable_importances") is None:
        B = np.asarray(model.coef_multinomial, np.float64)
        names_m = list(out_src["coef_names"])
        mags = sorted(zip(names_m, np.abs(B[:-1, :]).mean(axis=1)),
                      key=lambda t: -t[1])
        mx = max((m for _, m in mags), default=1.0) or 1.0
        tot = sum(m for _, m in mags) or 1.0
        output["variable_importances"] = twodim(
            "Standardized Coefficient Magnitudes",
            ["variable", "relative_importance", "scaled_importance",
             "percentage"],
            ["string", "float64", "float64", "float64"],
            [[nm, float(m), float(m / mx), float(m / tot)]
             for nm, m in mags])

    # KMeans: centers tables (client centers()/centers_std() read
    # output.centers.cell_values, h2o-py/h2o/model/models/clustering.py:233)
    if model.algo == "kmeans" and out_src.get("centers") is not None:
        cvals = out_src["centers"]
        rows = [[float(v) for v in c] for c in cvals]
        width = len(rows[0]) if rows else 0
        cand = list(out_src.get("coef_names") or [])
        if len(cand) != width:
            cand = list(out_src.get("names") or [])[:width]
        rh = [str(i + 1) for i in range(len(rows))]
        output["centers"] = twodim(
            "Cluster means", cand, ["float64"] * len(cand), rows,
            row_headers=rh)
        if out_src.get("centers_std") is not None:
            rows_s = [[float(v) for v in c]
                      for c in out_src["centers_std"]]
            output["centers_std"] = twodim(
                "Standardized cluster means", cand,
                ["float64"] * len(cand), rows_s, row_headers=rh)

    # algo-specific output extras (GLM coefficients, KMeans centers, ...)
    for k, v in out_src.items():
        if k in ("category", "names", "response", "domain", "varimp",
                 "scoring_history", "cv_model_keys"):
            continue
        if isinstance(v, (int, float, str, bool, type(None))):
            output.setdefault(k, _clean(v))
        elif isinstance(v, (list, tuple)) and (
                not v or isinstance(v[0], (int, float, str, type(None)))):
            output.setdefault(k, [_clean(x) for x in v])
    return {
        "__meta": {"schema_version": 3, "schema_name": "ModelSchemaV3",
                   "schema_type": "Model"},
        "model_id": {"name": model.key, "type": "Key<Model>",
                     "URL": f"/3/Models/{model.key}"},
        "algo": model.algo,
        "algo_full_name": model.algo.upper(),
        "response_column_name": response,
        "treatment_column_name": model.params.get("treatment_column"),
        "have_pojo": hasattr(model, "download_pojo"),
        "have_mojo": hasattr(model, "download_mojo"),
        "timestamp": 0,
        "data_frame": {"name": str(out_src.get("training_frame") or ""),
                       "type": "Key<Frame>"},
        "parameters": _params_v3(model),
        "output": output,
    }
