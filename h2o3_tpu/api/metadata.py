"""Schema metadata endpoints — the SchemaServer surface.

Reference: water/api/SchemaServer.java (schema registry),
water/api/MetadataHandler.java (/3/Metadata/endpoints, /3/Metadata/schemas),
water/api/schemas3/CloudV3.java (field list served to H2OCluster).

The real h2o-py client cannot even connect without these: on connect it
calls define_classes_from_schema for H2OCluster / H2OErrorV3 /
H2OModelBuilderErrorV3, each of which GETs /3/Metadata/schemas/{name} and
turns the returned field list into python properties
(h2o-py/h2o/schemas/schema.py:28, h2o-py/h2o/backend/connection.py:679).
Serving the right field names IS the wire contract.
"""

from __future__ import annotations

from typing import Dict, List


def _fields(*names: str, schema: Dict[str, str] | None = None) -> List[dict]:
    """Field descriptors: name + is_schema flag + help text."""
    schema = schema or {}
    return [{"name": n, "is_schema": n in schema,
             "schema_name": schema.get(n),
             "type": "Iced", "help": n.replace("_", " ")}
            for n in names]


# Field lists mirror the reference schema classes (water/api/schemas3/*.java)
# — names only; the client builds properties from them.
SCHEMAS: Dict[str, List[dict]] = {
    "CloudV3": _fields(
        "version", "branch_name", "last_commit_hash", "describe",
        "compiled_by", "compiled_on", "build_number", "build_age",
        "build_too_old", "node_idx", "cloud_name", "cloud_size",
        "cloud_uptime_millis", "cloud_internal_timezone",
        "datafile_parser_timezone", "cloud_healthy", "bad_nodes",
        "consensus", "locked", "is_client", "nodes",
        "internal_security_enabled", "web_ip",
        schema={"nodes": "NodeV3"}),
    "H2OErrorV3": _fields(
        "timestamp", "error_url", "msg", "dev_msg", "http_status",
        "values", "exception_type", "exception_msg", "stacktrace"),
    "H2OModelBuilderErrorV3": _fields(
        "timestamp", "error_url", "msg", "dev_msg", "http_status",
        "values", "exception_type", "exception_msg", "stacktrace",
        "parameters", "messages", "error_count",
        schema={"parameters": "ModelParametersSchemaV3"}),
    "NodeV3": _fields(
        "h2o", "ip_port", "healthy", "last_ping", "pid", "num_cpus",
        "cpus_allowed", "nthreads", "sys_load", "my_cpu_pct",
        "sys_cpu_pct", "mem_value_size", "pojo_mem", "free_mem",
        "max_mem", "swap_mem", "num_keys", "free_disk", "max_disk",
        "rpcs_active", "fjthrds", "fjqueue", "tcps_active", "open_fds",
        "gflops", "mem_bw"),
    "TwoDimTableV3": _fields(
        "name", "description", "columns", "rowcount", "data"),
    "FrameV3": _fields(
        "frame_id", "byte_size", "is_text", "row_offset", "row_count",
        "column_offset", "column_count", "full_column_count",
        "total_column_count", "checksum", "rows", "num_columns",
        "default_percentiles", "columns", "compatible_models",
        "chunk_summary", "distribution_summary",
        schema={"frame_id": "FrameKeyV3"}),
    "JobV3": _fields(
        "key", "description", "status", "progress", "progress_msg",
        "start_time", "msec", "dest", "warnings", "exception",
        "stacktrace", "ready_for_view",
        schema={"key": "JobKeyV3", "dest": "KeyV3"}),
    "ModelSchemaV3": _fields(
        "model_id", "algo", "algo_full_name", "parameters", "output",
        "compatible_frames", "have_pojo", "have_mojo", "timestamp",
        schema={"model_id": "ModelKeyV3"}),
    "RapidsSchemaV3": _fields("ast", "session_id", "id"),
    "InitIDV3": _fields("session_key"),
}


def register(route):
    """Attach handlers onto the server's route table (called by server.py
    at import time so ROUTES stays a single registry)."""

    @route("GET", r"/3/Metadata/schemas/(?P<name>[^/]+)")
    def _schema_meta(params, body, name=None):
        fields = SCHEMAS.get(name)
        if fields is None:
            # Unknown schemas yield an empty field list rather than a 404:
            # the client treats absent fields as "property not available".
            fields = []
        return {
            "__meta": {"schema_version": 3, "schema_name": "MetadataV3",
                       "schema_type": "Metadata"},
            "schemas": [{"name": name, "superclass": "Schema",
                         "version": 3, "type": "Iced",
                         "fields": fields, "markdown": ""}],
            "routes": [],
        }

    @route("GET", "/3/Metadata/schemas")
    def _schemas_all(params, body):
        return {
            "__meta": {"schema_version": 3, "schema_name": "MetadataV3",
                       "schema_type": "Metadata"},
            "schemas": [{"name": n, "superclass": "Schema", "version": 3,
                         "type": "Iced", "fields": f, "markdown": ""}
                        for n, f in SCHEMAS.items()],
            "routes": [],
        }

    @route("GET", "/3/Metadata/endpoints")
    def _endpoints(params, body):
        from h2o3_tpu.api.server import ROUTES
        routes = []
        for method, rx, fn in ROUTES:
            pat = rx.pattern.strip("^$")
            routes.append({
                "http_method": method,
                "url_pattern": pat,
                "summary": (fn.__doc__ or "").strip().split("\n")[0],
                "api_name": fn.__name__.strip("_"),
                "input_schema": "Iced", "output_schema": "Iced",
                "path_params": rx.groupindex and list(rx.groupindex) or [],
            })
        return {
            "__meta": {"schema_version": 3, "schema_name": "MetadataV3",
                       "schema_type": "Metadata"},
            "schemas": [], "routes": routes,
        }
