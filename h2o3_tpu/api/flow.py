"""Flow — the in-node notebook UI (h2o-web's role, compressed).

Reference: h2o-web/ serves the CoffeeScript Flow notebook from the node
itself at /flow/index.html; cells run "routines" that call the REST API
(importFiles, parse, buildModel, predict, getFrames, ...) and render
results as tables.

Here: one dependency-free HTML/JS page with the same shape — notebook of
cells, each cell an editable REST call (method, path, params) created
from assist buttons or by hand, executed against this server's /3 and
/99 endpoints, results rendered as tables where the payload is tabular
(frames preview, leaderboard, jobs) and as JSON otherwise. Notebooks
save/load as .flow JSON (localStorage + file download), mirroring
Flow's notebook files.
"""

FLOW_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>Flow — h2o3-tpu</title>
<style>
 body { font-family: -apple-system, 'Segoe UI', sans-serif; margin: 0;
        background: #f4f5f7; }
 #top { background: #1b2330; color: #ffd24d; padding: 10px 16px;
        display: flex; align-items: center; gap: 16px; }
 #top h1 { font-size: 16px; margin: 0; }
 #top .sub { color: #9aa7bd; font-size: 12px; }
 #assist { padding: 8px 16px; display: flex; flex-wrap: wrap; gap: 6px; }
 #assist button, .cellbar button {
   background: #fff; border: 1px solid #c8cdd6; border-radius: 4px;
   padding: 4px 10px; cursor: pointer; font-size: 12px; }
 #assist button:hover, .cellbar button:hover { background: #eef3ff; }
 #cells { padding: 0 16px 40px; }
 .cell { background: #fff; border: 1px solid #d9dde3; border-radius: 6px;
         margin: 10px 0; }
 .cell.running { border-color: #ffd24d; }
 .cellbar { display: flex; gap: 6px; padding: 6px 8px;
            border-bottom: 1px solid #eee; align-items: center; }
 .cellbar .label { font-size: 11px; color: #667; margin-right: auto; }
 .cell textarea { width: calc(100% - 20px); margin: 8px 10px;
                  font-family: ui-monospace, monospace; font-size: 12px;
                  border: 1px solid #e3e6ea; border-radius: 4px;
                  padding: 6px; min-height: 54px; box-sizing: border-box; }
 .out { margin: 0 10px 10px; font-size: 12px; overflow-x: auto; }
 .out pre { background: #0e1420; color: #c9e3ff; padding: 8px;
            border-radius: 4px; max-height: 320px; overflow: auto; }
 .out table { border-collapse: collapse; }
 .out th, .out td { border: 1px solid #d5dae2; padding: 3px 8px;
                    font-size: 12px; }
 .out th { background: #eef1f5; }
 .err { color: #b00020; }
</style>
</head>
<body>
<div id="top">
 <h1>Flow</h1>
 <span class="sub" id="cloudinfo">connecting…</span>
 <span style="margin-left:auto"></span>
 <button onclick="saveFlow()">Save .flow</button>
 <button onclick="document.getElementById('loadfile').click()">Load</button>
 <input type="file" id="loadfile" style="display:none"
        onchange="loadFlowFile(this.files[0])">
</div>
<div id="assist">
 <button onclick="addCell('POST /3/ImportFiles\n{\"path\": \"/path/to/data.csv\"}')">importFiles</button>
 <button onclick="addCell('POST /3/Parse\n{\"source_frames\": \"/path/to/data.csv\"}')">parse</button>
 <button onclick="addCell('GET /3/Frames\n{}')">getFrames</button>
 <button onclick="addCell('GET /3/Models\n{}')">getModels</button>
 <button onclick="addCell('POST /3/ModelBuilders/gbm\n{\"training_frame\": \"FRAME_KEY\", \"response_column\": \"y\", \"ntrees\": 20}')">buildModel</button>
 <button onclick="addCell('POST /3/Predictions/models/MODEL/frames/FRAME\n{}')">predict</button>
 <button onclick="addCell('POST /99/Rapids\n{\"ast\": \"(+ 1 2)\"}')">rapids</button>
 <button onclick="addCell('POST /99/AutoMLBuilder\n{\"input_spec\": {\"training_frame\": \"FRAME_KEY\", \"response_column\": \"y\"}, \"build_control\": {\"stopping_criteria\": {\"max_models\": 4}}}')">runAutoML</button>
 <button onclick="addCell('GET /3/Jobs\n{}')">getJobs</button>
 <button onclick="addCell('GET /3/Cloud\n{}')">getCloud</button>
</div>
<div id="cells"></div>
<script>
let CELLS = [];

function el(tag, attrs, html) {
  const e = document.createElement(tag);
  for (const k in (attrs || {})) e.setAttribute(k, attrs[k]);
  if (html !== undefined) e.innerHTML = html;
  return e;
}

function addCell(text, outHtml) {
  const cell = el('div', {class: 'cell'});
  const bar = el('div', {class: 'cellbar'});
  const label = el('span', {class: 'label'}, 'cell ' + (CELLS.length + 1));
  const run = el('button', {}, '&#9654; Run');
  const del = el('button', {}, '&#10005;');
  const ta = el('textarea');
  ta.value = text || 'GET /3/Cloud\n{}';
  ta.addEventListener('keydown', ev => {
    if ((ev.ctrlKey || ev.metaKey) && ev.key === 'Enter') runCell(cell, ta, out);
  });
  const out = el('div', {class: 'out'});
  if (outHtml) out.innerHTML = outHtml;
  run.onclick = () => runCell(cell, ta, out);
  del.onclick = () => { cell.remove(); CELLS = CELLS.filter(c => c !== cell); };
  bar.append(label, run, del);
  cell.append(bar, ta, out);
  document.getElementById('cells').append(cell);
  CELLS.push(cell);
  ta.focus();
  return cell;
}

function parseCell(text) {
  const nl = text.indexOf('\n');
  const head = (nl < 0 ? text : text.slice(0, nl)).trim().split(/\s+/);
  const body = nl < 0 ? '{}' : text.slice(nl + 1).trim() || '{}';
  return {method: head[0].toUpperCase(), path: head[1],
          params: JSON.parse(body)};
}

async function call(method, path, params) {
  let url = path, opts = {method};
  const enc = o => Object.entries(o).map(([k, v]) =>
    encodeURIComponent(k) + '=' + encodeURIComponent(
      typeof v === 'object' ? JSON.stringify(v) : v)).join('&');
  if (method === 'GET') {
    if (Object.keys(params).length) url += '?' + enc(params);
  } else {
    opts.headers = {'Content-Type': 'application/x-www-form-urlencoded'};
    opts.body = enc(params);
  }
  const r = await fetch(url, opts);
  return r.json();
}

function esc(v) {
  return String(v).replace(/&/g, '&amp;').replace(/</g, '&lt;')
    .replace(/>/g, '&gt;').replace(/"/g, '&quot;');
}

function tableHTML(cols, rows) {
  let h = '<table><tr>' + cols.map(c => '<th>' + esc(c) + '</th>').join('') + '</tr>';
  for (const row of rows)
    h += '<tr>' + row.map(v => '<td>' + (v === null ? '' : esc(v)) + '</td>').join('') + '</tr>';
  return h + '</table>';
}

function render(out, data) {
  // tabular shapes: frame preview, leaderboard, jobs
  try {
    if (data.frames && data.frames[0] && data.frames[0].columns) {
      const f = data.frames[0];
      const cols = f.columns.map(c => c.label);
      const n = Math.min(10, (f.columns[0].data || []).length);
      const rows = [];
      for (let i = 0; i < n; i++) rows.push(f.columns.map(c => c.data[i]));
      out.innerHTML = '<p>' + esc(f.frame_id.name) + ': ' + f.rows +
        ' rows × ' + f.num_columns + ' cols</p>' + tableHTML(cols, rows);
      return;
    }
    if (data.leaderboard_table) {
      const t = data.leaderboard_table;
      out.innerHTML = tableHTML(t.columns || Object.keys(t[0] || {}),
        (t.data || t).map(r => Array.isArray(r) ? r : Object.values(r)));
      return;
    }
    if (data.jobs) {
      out.innerHTML = tableHTML(['key', 'description', 'status', 'progress'],
        data.jobs.map(j => [j.key ? (j.key.name || j.key) : '', j.description,
                            j.status, j.progress]));
      return;
    }
  } catch (e) { /* fall through to JSON */ }
  out.innerHTML = '<pre>' + esc(JSON.stringify(data, null, 1)) + '</pre>';
}

async function runCell(cell, ta, out) {
  cell.classList.add('running');
  out.innerHTML = '<pre>…</pre>';
  try {
    const {method, path, params} = parseCell(ta.value);
    let data = await call(method, path, params);
    // auto-poll async jobs (the Flow progress bar role)
    let jobKey = data.job && data.job.key && (data.job.key.name || data.job.key);
    while (jobKey) {
      const j = (await call('GET', '/3/Jobs/' + jobKey, {})).jobs[0];
      out.innerHTML = '<pre>job ' + esc(jobKey) + ': ' + esc(j.status) +
        ' ' + Math.round((j.progress || 0) * 100) + '%</pre>';
      if (j.status === 'DONE') { data = j; break; }
      if (j.status === 'FAILED' || j.status === 'CANCELLED') {
        data = j; break; }
      await new Promise(r => setTimeout(r, 500));
    }
    render(out, data);
  } catch (e) {
    out.innerHTML = '<pre class="err">' + e + '</pre>';
  }
  cell.classList.remove('running');
}

function saveFlow() {
  const doc = {version: 1, cells: CELLS.map(c =>
    ({input: c.querySelector('textarea').value}))};
  const blob = new Blob([JSON.stringify(doc, null, 1)],
                       {type: 'application/json'});
  const a = el('a', {download: 'notebook.flow',
                     href: URL.createObjectURL(blob)});
  a.click();
  localStorage.setItem('h2o3tpu_flow', JSON.stringify(doc));
}

function loadFlowFile(f) {
  if (!f) return;
  f.text().then(t => {
    document.getElementById('cells').innerHTML = '';
    CELLS = [];
    for (const c of JSON.parse(t).cells) addCell(c.input);
  });
}

(async () => {
  try {
    const c = await call('GET', '/3/Cloud', {});
    document.getElementById('cloudinfo').textContent =
      c.cloud_name + ' — ' + c.cloud_size + ' node(s), healthy: ' +
      c.cloud_healthy;
  } catch (e) {
    document.getElementById('cloudinfo').textContent = 'cloud unreachable';
  }
  const saved = localStorage.getItem('h2o3tpu_flow');
  if (saved) {
    for (const c of JSON.parse(saved).cells) addCell(c.input);
  } else {
    addCell('GET /3/Cloud\n{}');
  }
})();
</script>
</body>
</html>
"""
