"""User-defined functions — custom distributions and custom metrics.

Reference: water/udf/CFunc.java:1 — users upload a function artifact
(POJO/Jython source) into the DKV and pass a "lang:key" reference as
``custom_distribution_func`` / ``custom_metric_func``
(hex/DistributionFactory CustomDistribution + water/udf/CFuncRef).

TPU twin: the artifact is a Python object registered in the controller
object store under "python:<key>". A custom DISTRIBUTION supplies
jnp-traceable callables, so the boosting loop compiles it straight into
the fused scan program — same speed as a built-in loss:

    class AsymmetricLoss:
        def link(self): return "identity"
        def gradient(self, y, f): return jnp.where(f > y, 2.0, -1.0)
        # optional: hessian(y, f), deviance(y, f), init(mean)

    ref = h2o3_tpu.upload_custom_distribution(AsymmetricLoss())
    GBMEstimator(distribution="custom", custom_distribution_func=ref)

A custom METRIC is a host callable ``fn(y, preds_dict, w) -> float``
(the CMetricFunc map/reduce collapse)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from h2o3_tpu.core.kv import DKV, make_key

_PREFIX = "python:"


def upload_custom_distribution(obj: Any, key: Optional[str] = None) -> str:
    """Register a custom-distribution object; returns its CFunc ref.

    ``obj`` must provide ``gradient(y, f)`` (jnp-traceable). Optional:
    ``link() -> str`` (identity/log/logit, default identity),
    ``hessian(y, f)`` (default 1), ``deviance(y, f)``,
    ``init(mean) -> float``.
    """
    if isinstance(obj, type):
        obj = obj()
    if not callable(getattr(obj, "gradient", None)):
        raise ValueError("custom distribution must define gradient(y, f)")
    key = key or make_key("udf_dist")
    DKV.put(key, obj)
    return _PREFIX + key


def upload_custom_metric(fn: Callable, key: Optional[str] = None) -> str:
    """Register a custom metric fn(y, preds, w) -> float; returns ref."""
    if not callable(fn):
        raise ValueError("custom metric must be callable")
    key = key or make_key("udf_metric")
    DKV.put(key, fn)
    return _PREFIX + key


def resolve_udf(ref: Any) -> Any:
    """'python:key' → the registered object; callables pass through."""
    if callable(ref) and not isinstance(ref, str):
        return ref
    if isinstance(ref, str):
        key = ref[len(_PREFIX):] if ref.startswith(_PREFIX) else ref
        obj = DKV.get(key.strip('"'))
        if obj is None:
            raise ValueError(f"no uploaded UDF under '{ref}'")
        return obj
    raise ValueError(f"cannot resolve UDF reference {ref!r}")
