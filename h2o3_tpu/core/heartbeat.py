"""Cloud heartbeat — peer-health monitoring and fail-fast degradation.

Reference: water/HeartBeatThread.java:16 pings every node each second;
water/Paxos.java ejects nodes that miss their beat from the committed
cloud, and every MRTask blocked on a dead node fails instead of hanging
forever. The TPU-native hazard is worse: a collective (psum) issued
against a mesh with a dead peer never returns — there is no RPC timeout
inside XLA — so every frame_reduce would hang the worker thread.

This module runs the HeartBeatThread analogue:

- **Single-process cloud** (one controller, local devices): each round
  is a tiny psum over the mesh — the same dispatch path every
  frame_reduce takes — bounded by the watchdog's thread-timeout prober
  (``bounded_call``). A wedged backend turns the round into a miss
  instead of a hang.
- **Multi-process cloud** (jax.distributed): rounds ride the
  coordination-service key-value store (the control plane that formed
  the cloud), NOT device collectives — two Python threads issuing
  collectives in different orders across processes can deadlock the
  mesh, which is exactly the failure this thread must detect, so the
  monitor stays out-of-band like the reference's heartbeat UDP channel
  vs. compute TCP split. Each process publishes ``hb/<pid> = now`` every
  round and reads every peer's last beat back: genuine per-peer
  last-seen tracking.

Misses accumulate per round; ``miss_budget`` consecutive misses (or a
peer's beat going stale past ``interval * miss_budget``) flips the cloud
unhealthy. The flag is checked at every chunk boundary
(parallel/map_reduce.py, Job.update via request_ctx.cancel_point) so
in-flight jobs fail within one heartbeat interval with a classified
:class:`CloudUnhealthyError` — infra-class, so job-level retries and
grid/AutoML ``recovery_dir`` snapshots compose with it — rather than
blocking on a collective that will never complete.

Telemetry: ``heartbeat_rounds_total``, ``heartbeat_misses_total{peer=}``,
``cloud_peers_healthy`` gauge (README §Cloud formation).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Any, Dict, List, Optional

from h2o3_tpu.core import config as _config
from h2o3_tpu.core import watchdog
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.heartbeat")

KV_PREFIX = "h2o3tpu/hb/"


class CloudUnhealthyError(Exception):
    """The cloud missed its heartbeat budget; collectives can no longer
    be trusted to complete. The message carries an INFRA_SIGNS token so
    ``watchdog.is_infra_error`` classifies it retryable — job-level
    retries and recovery_dir snapshot/resume compose with it."""

    def __init__(self, reason: str, site: str = ""):
        at = f" at {site}" if site else ""
        super().__init__(f"UNAVAILABLE: cloud unhealthy{at} — {reason}")
        self.reason = reason
        self.site = site


class HeartbeatMonitor:
    """Background peer-health thread (one per process, like the
    reference's one HeartBeatThread per node)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.interval_s = 1.0
        self.miss_budget = 3
        self.timeout_s = 5.0
        self.rounds = 0
        self.consecutive_misses = 0
        # pid -> {"last_seen": wall-clock ts of last agreement/beat,
        #         "healthy": bool}
        self.peers: Dict[int, Dict[str, Any]] = {}
        # fast-path flag read lock-free at every chunk boundary
        self._unhealthy_reason: Optional[str] = None
        self._psum_fn = None            # cached per-mesh agreement fn
        self._psum_mesh = None
        # captured ONCE at start(): jax.process_count()/process_index()
        # can re-enter (and block on) backend initialization, which must
        # never happen from the monitor thread mid-round
        self._nproc = 1
        self._pid = 0

    # -------------------------------------------------------- lifecycle
    def start(self, interval_s: Optional[float] = None,
              miss_budget: Optional[int] = None,
              timeout_s: Optional[float] = None,
              thread: bool = True) -> None:
        """Launch the monitor (idempotent). Defaults from core/config.py
        (H2O3TPU_HEARTBEAT_{INTERVAL_S,MISS_BUDGET,TIMEOUT_S}).
        ``thread=False`` configures peers/knobs but leaves rounds to the
        caller — deterministic tests and the bench cloud leg drive
        ``round()`` synchronously."""
        args = _config.ARGS
        with self._lock:
            self.interval_s = float(interval_s
                                    if interval_s is not None
                                    else args.heartbeat_interval_s)
            self.miss_budget = int(miss_budget
                                   if miss_budget is not None
                                   else args.heartbeat_miss_budget)
            self.timeout_s = float(timeout_s
                                   if timeout_s is not None
                                   else args.heartbeat_timeout_s
                                   ) or self.interval_s
            if self._thread is not None:
                return
            self._stop.clear()
            self._unhealthy_reason = None
            self.consecutive_misses = 0
            now = time.time()
            import jax
            self._nproc = jax.process_count()
            self._pid = jax.process_index()
            self.peers = {p: {"last_seen": now, "healthy": True}
                          for p in range(self._nproc)}
            if thread:
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="cloud-heartbeat")
                self._thread.start()
        log.info("heartbeat up: interval=%.2fs miss_budget=%d timeout=%.2fs",
                 self.interval_s, self.miss_budget, self.timeout_s)

    def stop(self) -> None:
        """Stop and reset so a re-formed cloud starts clean."""
        with self._lock:
            t, self._thread = self._thread, None
        self._stop.set()
        if t is not None:
            t.join(timeout=max(self.timeout_s, 2.0) + 1.0)
        with self._lock:
            self._unhealthy_reason = None
            self.consecutive_misses = 0
            self.peers = {}
            self._psum_fn = None
            self._psum_mesh = None

    @property
    def running(self) -> bool:
        return self._thread is not None

    # ---------------------------------------------------------- status
    def healthy(self) -> bool:
        return self._unhealthy_reason is None

    def reason(self) -> Optional[str]:
        return self._unhealthy_reason

    def mark_unhealthy(self, reason: str) -> None:
        """Flip the cloud unhealthy (round-miss budget exhausted, or a
        test/operator decision). Chunk boundaries observe it on their
        next dispatch."""
        from h2o3_tpu import telemetry
        first = self._unhealthy_reason is None
        self._unhealthy_reason = reason
        with self._lock:
            for st in self.peers.values():
                st["healthy"] = False
            telemetry.gauge("cloud_peers_healthy").set(0)
        if first:
            log.error("cloud UNHEALTHY: %s", reason)

    def mark_healthy(self) -> None:
        """Clear the unhealthy flag and per-peer health. ``last_seen``
        is deliberately NOT touched: it tracks actual observed beats
        (kv rounds) or completed agreements (psum rounds) — refreshing
        it here would mask a dead peer's staleness behind every
        successful round."""
        from h2o3_tpu import telemetry
        was = self._unhealthy_reason
        self._unhealthy_reason = None
        with self._lock:
            self.consecutive_misses = 0
            for st in self.peers.values():
                st["healthy"] = True
            telemetry.gauge("cloud_peers_healthy").set(len(self.peers))
        if was is not None:
            log.warning("cloud healthy again (was: %s)", was)

    def status(self) -> dict:
        """Peer-health block for cluster_info() / GET /3/Cloud."""
        with self._lock:
            peers = {str(p): dict(st) for p, st in self.peers.items()}
        return {
            "running": self.running,
            "healthy": self.healthy(),
            "reason": self._unhealthy_reason,
            "interval_s": self.interval_s,
            "miss_budget": self.miss_budget,
            "rounds": self.rounds,
            "consecutive_misses": self.consecutive_misses,
            "peers": peers,
        }

    # ---------------------------------------------------------- rounds
    def _loop(self) -> None:
        # first round fires immediately so a freshly formed cloud gets
        # a last_seen baseline before any job dispatches
        while True:
            try:
                self.round()
            except Exception as e:      # noqa: BLE001 - never kill the loop
                log.warning("heartbeat round error (uncounted): %s", e)
            if self._stop.wait(self.interval_s):
                return

    def round(self) -> bool:
        """One heartbeat round; returns True on agreement. Public so
        tests and the bench cloud leg can drive rounds synchronously."""
        from h2o3_tpu import telemetry
        telemetry.counter("heartbeat_rounds_total").inc()
        with self._lock:
            self.rounds += 1
        try:
            watchdog.maybe_fail("heartbeat")
            if self._nproc > 1:
                stale = watchdog.bounded_call(
                    self._kv_round, self.timeout_s, name="heartbeat-kv")
            else:
                watchdog.bounded_call(
                    self._psum_round, self.timeout_s, name="heartbeat-psum")
                stale = []
        except Exception as e:          # noqa: BLE001 - classified as a miss
            self._miss(list(self.peers), f"{type(e).__name__}: {e}")
            return False
        if stale:
            self._miss(stale, f"peer beat stale: {stale}")
            return False
        self.mark_healthy()
        return True

    def _miss(self, peer_ids, why: str) -> None:
        from h2o3_tpu import telemetry
        with self._lock:
            self.consecutive_misses += 1
            misses = self.consecutive_misses
            for p in peer_ids:
                telemetry.counter("heartbeat_misses_total",
                                  peer=str(p)).inc()
                if p in self.peers:
                    self.peers[p]["healthy"] = False
            telemetry.gauge("cloud_peers_healthy").set(
                sum(1 for st in self.peers.values() if st["healthy"]))
        log.warning("heartbeat miss %d/%d: %s", misses, self.miss_budget,
                    why)
        if misses >= self.miss_budget:
            self.mark_unhealthy(
                f"{misses} consecutive heartbeat misses ({why})")

    # agreement checks ------------------------------------------------
    def _psum_round(self) -> None:
        """Single-controller agreement: a tiny psum over the mesh — the
        exact dispatch path frame_reduce takes, so a backend that would
        hang the next chunk hangs (and times out) here first."""
        import jax
        import numpy as np
        from h2o3_tpu.parallel import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
        if self._psum_fn is None or self._psum_mesh is not mesh:
            import functools
            from jax.sharding import PartitionSpec as P
            from h2o3_tpu.parallel.mesh import DATA_AXIS, shard_map

            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(DATA_AXIS), out_specs=P(),
                               check_vma=False)
            def _agree(x):
                return jax.lax.psum(x.sum(), DATA_AXIS)

            self._psum_fn = jax.jit(_agree)
            self._psum_mesh = mesh
        d = mesh.shape[mesh_mod.DATA_AXIS]
        x = jax.device_put(np.ones((d,), dtype=np.float32),
                           mesh_mod.row_sharding(mesh))
        total = float(self._psum_fn(x))
        if total != float(d):
            raise RuntimeError(
                f"INTERNAL: heartbeat psum corrupt ({total} != {d})")
        # a completed psum IS an all-peer agreement: everyone's beat
        now = time.time()
        with self._lock:
            for st in self.peers.values():
                st["last_seen"] = now

    def _kv_round(self):
        """Multi-process agreement over the coordination-service KV
        store: publish our beat, read every peer's. Returns the list of
        process ids whose beat is stale past interval*miss_budget."""
        from jax._src import distributed
        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "UNAVAILABLE: no coordination-service client")
        now = time.time()
        client.key_value_set(f"{KV_PREFIX}{self._pid}", repr(now),
                             allow_overwrite=True)
        # cluster-telemetry snapshot piggybacks on the beat cadence —
        # same out-of-band rule (KV write, never a device collective),
        # same bounded_call window; its own interval rate-limits it and
        # a publish failure never counts as a heartbeat miss
        try:
            from h2o3_tpu.telemetry import cluster
            cluster.maybe_publish()
        except Exception as e:      # noqa: BLE001 - publish is best-effort
            log.debug("cluster telemetry publish skipped: %s", e)
        # fleet re-warm piggybacks here too: when a replica's last host
        # dies, the least-loaded healthy peer adopts the published model
        # (rate-limited inside maybe_adopt; install runs off-thread)
        try:
            from h2o3_tpu.serving import fleet
            fleet.maybe_adopt()
        except Exception as e:      # noqa: BLE001 - adopt is best-effort
            log.debug("fleet adopt check skipped: %s", e)
        # frame recovery supervisor piggybacks last: once a peer's beat
        # is declared stale, the least-loaded survivor rebuilds the
        # dead peer's registered frames from mirror-or-lineage and
        # re-homes them (rate-limited inside maybe_rebuild; KV-only —
        # never a device collective)
        try:
            from h2o3_tpu.core import durability
            durability.maybe_rebuild_async()
        except Exception as e:      # noqa: BLE001 - rebuild best-effort
            log.debug("durability rebuild check skipped: %s", e)
        beats = {}
        for key, val in client.key_value_dir_get(KV_PREFIX):
            try:
                beats[int(key.rsplit("/", 1)[-1])] = float(val)
            except ValueError:
                continue
        stale_after = self.interval_s * self.miss_budget
        stale = []
        with self._lock:
            for p in self.peers:
                ts = beats.get(p)
                if ts is not None:
                    self.peers[p]["last_seen"] = max(
                        self.peers[p]["last_seen"], ts)
                # a peer that has not beaten recently is suspect; our
                # own beat was just written so never stales here
                if now - self.peers[p]["last_seen"] > stale_after:
                    stale.append(p)
        return stale


monitor = HeartbeatMonitor()

# chunk boundaries inside this scope skip the cloud-unhealthy fail-fast:
# scheduled work items (parallel/scheduler.py) train purely on LOCAL
# devices, so a dead peer cannot wedge them — failing them fast would
# abandon exactly the work that can still finish and serve the
# reassignment of the dead peer's items
_LOCAL_WORK: contextvars.ContextVar[bool] = \
    contextvars.ContextVar("h2o3tpu_local_work", default=False)


@contextlib.contextmanager
def local_work_scope():
    """Mark this thread's work as local-device-only: ``check_healthy``
    becomes a no-op so an unhealthy cloud (a dead peer) does not kill
    fits that issue no cross-process collectives. Cancel/deadline checks
    in Job.update still apply."""
    token = _LOCAL_WORK.set(True)
    try:
        yield
    finally:
        _LOCAL_WORK.reset(token)


def dead_peers() -> List[int]:
    """Process ids whose beat is stale past ``interval * miss_budget``.

    Deliberately based on ``last_seen`` staleness, not the per-peer
    ``healthy`` flag — ``mark_unhealthy`` flips every peer's flag, so
    staleness is the only signal that distinguishes the actually-dead
    peer from the bystanders (the scheduler's reassignment trigger)."""
    now = time.time()
    stale_after = monitor.interval_s * monitor.miss_budget
    with monitor._lock:
        return [p for p, st in monitor.peers.items()
                if p != monitor._pid
                and now - st["last_seen"] > stale_after]


def healthy_peers() -> List[int]:
    """Process ids (self included) whose beat is fresh — the complement
    of :func:`dead_peers` over the known peer set. The fleet router uses
    this to build its candidate pool before consulting load."""
    now = time.time()
    stale_after = monitor.interval_s * monitor.miss_budget
    with monitor._lock:
        fresh = [p for p, st in monitor.peers.items()
                 if p == monitor._pid
                 or now - st["last_seen"] <= stale_after]
        if monitor._pid not in fresh:
            fresh.append(monitor._pid)   # single-process / monitor off
        return sorted(fresh)


def check_healthy(site: str = "") -> None:
    """Fail-fast checkpoint — called at chunk boundaries alongside
    cancel_point. Raises CloudUnhealthyError once the monitor has
    declared the cloud unhealthy, so a job dies within one heartbeat
    interval instead of hanging on the next collective."""
    reason = monitor._unhealthy_reason
    if reason is not None:
        if _LOCAL_WORK.get():
            return                     # local-only work: peers irrelevant
        from h2o3_tpu import telemetry
        telemetry.counter("cloud_unhealthy_failfast_total").inc()
        raise CloudUnhealthyError(reason, site=site)
