"""Durable data plane — frame lineage, mirrored shards, peer-loss
rebuild, and whole-cloud checkpoint/restore (ISSUE 18).

Every robustness layer before this one protects COMPUTE (fit
checkpoints, the OOM ladder, lease reassignment, serving failover); the
data plane was still a single point of loss — a SIGKILLed peer took its
homed DKV frames with it forever, and a cloud could not be snapshotted
or reformed with its state intact. This module closes that gap with
three legs:

- **Lineage**: every ingested Frame records its provenance (source
  paths + parse plan + content digest, riding the bit-identical ingest
  contract) and derived frames record their op chain, so a lost frame
  is re-materializable deterministically. Surfaced on
  ``GET /3/Frames/{id}`` as the ``lineage`` block.
- **Mirroring** (``H2O3TPU_DATA_DURABILITY=off|lineage|mirror``):
  mirror mode write-through-persists each frame's device-independent
  blocks (``io/persist.frame_to_bytes``) generation-suffixed like the
  ice files — to shared disk (default) or chunked parts-before-meta
  over the coordination-service KV (``H2O3TPU_DUR_TRANSPORT=kv``, the
  scheduler/fleet blob ordering: a half-written blob is never
  observed). A frame REGISTRY over the KV names which peer homes what,
  so survivors can walk a dead peer's keys without its memory.
  Mirrored bytes are governor-accounted (``core/memgov.py``) and
  published as ``frames_mirrored_bytes``.
- **Recovery supervisor + cloud restore**: ``maybe_rebuild`` piggybacks
  on the heartbeat round (the ``fleet.maybe_adopt`` pattern). When the
  heartbeat declares a peer dead, the least-loaded survivor walks the
  lost peer's registered keys, rebuilds each frame from
  mirror-or-lineage, re-homes it (registry entry moves), and counts
  ``frame_rebuilds_total{source=}``; affected fits resume from their
  traveling ``.fitsnap`` snapshots instead of failing. Unrecoverable
  keys land in the LOST set and fail jobs with a typed
  :class:`DataLostError` (REST: 410 in H2OErrorV3 shape) — never a
  hang. ``cloud_checkpoint``/``cloud_restore`` (REST:
  ``POST /3/CloudCheckpoint``; ``init(restore_dir=)``) quiesce jobs,
  persist the whole DKV (frames as blocks, models as device-lowered
  binaries, manifest written LAST), and reform a cloud bit-identically
  — the rolling-restart / disaster-recovery story.

The registry/rebuild decision core (:class:`DurabilityBoard`) is a
pure, jax-free state machine on the RunBoard model: the bench
``_stub_durability`` leg and the unit tests drive it with no backend
in the process.

Metrics (README §Observability): ``frames_mirrored_bytes``,
``frame_rebuilds_total{source}``, ``frame_rebuild_seconds``,
``cloud_restore_seconds``, ``frames_under_replicated``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.durability")

KV_PREFIX = "h2o3tpu/dur/"
_B64_CHUNK = 131072              # base64 chars per KV part (bounded values)
FRAME_SUFFIX = ".framesnap"

_REBUILD_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0)

_MODES = ("off", "lineage", "mirror")


class DataLostError(RuntimeError):
    """A frame (or the blocks backing it) is gone and neither a mirror
    nor deterministic lineage can bring it back. Typed and terminal:
    jobs touching the key fail fast with this (REST: 410 Gone in
    H2OErrorV3 shape) instead of hanging on data that will never
    reappear. NOT an infra error — retrying cannot help."""

    def __init__(self, key: str, detail: str = ""):
        super().__init__(
            f"DATA_LOST: frame '{key}' is unrecoverable"
            + (f" ({detail})" if detail else ""))
        self.key = key


# never worth a retry: the data is gone, not the infrastructure
try:
    from h2o3_tpu.core import watchdog as _watchdog
    if DataLostError not in _watchdog.NON_RETRYABLE:
        _watchdog.NON_RETRYABLE.append(DataLostError)
except Exception:            # noqa: BLE001 - classifier is optional
    pass


def mode() -> str:
    """The durability knob, env-at-call-time: ``off`` (default — a
    fully ungated zero-overhead no-op), ``lineage`` (provenance
    recording only; lost frames re-materialize from source), or
    ``mirror`` (write-through block persistence + lineage)."""
    m = os.environ.get("H2O3TPU_DATA_DURABILITY", "off").strip().lower()
    return m if m in _MODES else "off"


def _rebuild_interval_s() -> float:
    try:
        return float(os.environ.get("H2O3TPU_DUR_REBUILD_S", 2.0))
    except (TypeError, ValueError):
        return 2.0


def mirror_dir() -> str:
    """Shared mirror directory (disk transport): ``H2O3TPU_DUR_DIR``,
    else ``<ice>/mirror`` — generation-suffixed ``.framesnap`` files,
    published atomically (write-tmp + rename) by the file driver."""
    d = os.environ.get("H2O3TPU_DUR_DIR")
    if d:
        return d
    ice = os.environ.get(
        "H2O3_TPU_ICE_DIR",
        os.path.join(tempfile.gettempdir(), "h2o3_tpu_ice"))
    return os.path.join(ice, "mirror")


def _transport() -> str:
    t = os.environ.get("H2O3TPU_DUR_TRANSPORT", "disk").strip().lower()
    return t if t in ("disk", "kv") else "disk"


# ----------------------------------------------------- KV transport

class _LocalKV:
    """In-process stand-in for the coordination-service KV client so
    single-process clouds (and jax-free tests) run the SAME registry
    code — local-only, identical semantics (the fleet shim pattern)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, str] = {}

    def key_value_set(self, key, val, allow_overwrite=True):
        with self._lock:
            self._store[key] = val

    def key_value_dir_get(self, prefix):
        with self._lock:
            return [(k, v) for k, v in self._store.items()
                    if k.startswith(prefix)]

    def key_value_delete(self, key):
        # coordination-service directory semantics: the exact key plus
        # the subtree under ``key/`` — never bare-prefix matches, which
        # would take ``reg/0/iris_test`` down with ``reg/0/iris``
        sub = key if key.endswith("/") else key + "/"
        with self._lock:
            self._store.pop(key, None)
            for k in [k for k in self._store if k.startswith(sub)]:
                del self._store[k]

    def blocking_key_value_get(self, key, timeout_ms):
        with self._lock:
            if key not in self._store:
                raise KeyError(key)
            return self._store[key]


_local_kv = _LocalKV()


def _kv():
    try:
        from jax._src import distributed
        client = distributed.global_state.client
        if client is not None:
            return client
    except Exception:        # noqa: BLE001 - no jax / no distributed
        pass
    return _local_kv


def _encode(data: bytes) -> str:
    import base64
    import zlib
    return base64.b64encode(zlib.compress(data, 6)).decode("ascii")


def _decode(text: str) -> bytes:
    import base64
    import zlib
    return zlib.decompress(base64.b64decode(text.encode("ascii")))


def _self_pid() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:        # noqa: BLE001 - jax-free callers are pid 0
        return 0


# ------------------------------------------------------ module state

_lock = threading.RLock()
_mirrored: Dict[str, Dict[str, Any]] = {}    # key -> local mirror info
_gens: Dict[str, int] = {}                   # key -> next generation
_registered: set = set()                     # keys this pid published
_lost: set = set()                           # keys proven unrecoverable
_last_rebuild = 0.0
_suspend = threading.local()                 # transient-frame guard


@contextlib.contextmanager
def suspended():
    """Suspend durability hooks on this thread — transient frames
    (``row_slice`` chunk views, scheduler local copies) are scored and
    dropped, never homed, so mirroring them is pure overhead."""
    prev = getattr(_suspend, "on", False)
    _suspend.on = True
    try:
        yield
    finally:
        _suspend.on = prev


def _is_suspended() -> bool:
    return bool(getattr(_suspend, "on", False))


# ---------------------------------------------------------- lineage

def frame_digest(frame) -> str:
    """Canonical content digest of a frame — names, types, domains, and
    the exact host-f64 column bytes + NA masks. Stable across meshes
    and processes (the bit-identity the mirror/restore contracts assert
    against), unlike hashing an npz container whose zip metadata embeds
    timestamps."""
    import numpy as np
    h = hashlib.sha256()
    h.update(json.dumps({"names": list(frame.names),
                         "types": frame.types(),
                         "nrows": frame.nrows}, sort_keys=True).encode())
    for name in frame.names:
        c = frame.col(name)
        if c.domain is not None:
            h.update(json.dumps(list(c.domain)).encode())
        if c.type == "string":
            for s in c.strings[: c.nrows]:
                h.update(b"\x00" if s is None else str(s).encode())
        else:
            from h2o3_tpu.parallel.mesh import fetch_replicated
            h.update(np.ascontiguousarray(
                fetch_replicated(c.data)[: c.nrows]).tobytes())
            h.update(np.ascontiguousarray(
                fetch_replicated(c.na_mask)[: c.nrows]).tobytes())
    return h.hexdigest()


def file_digest(path: str) -> str:
    """Streamed sha256 of a source file (the format digest lineage
    records next to the parse plan)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def record_source(frame, paths: List[str], parse_kwargs: Dict,
                  parse_plan: Optional[Dict] = None) -> None:
    """Stamp ingest provenance on a frame: re-running ``import_file``
    with these paths + kwargs reproduces the frame bit-identically (the
    chunk-parallel ingest determinism contract)."""
    if mode() == "off":
        return
    lin = {"kind": "source", "paths": [str(p) for p in paths],
           "parse_kwargs": {k: v for k, v in (parse_kwargs or {}).items()
                            if v is not None}}
    if parse_plan:
        lin["parse_plan"] = parse_plan
    try:
        lin["format_digest"] = [file_digest(p) for p in lin["paths"]
                                if os.path.exists(p)]
    except OSError:
        pass
    frame._lineage = lin


def record_derived(frame, op: str, parent, params: Dict) -> None:
    """Stamp a derived frame with its op chain: parent key + lineage,
    plus this op and its params — deterministic ops replay top-down."""
    if mode() == "off":
        return
    chain = []
    plin = getattr(parent, "_lineage", None)
    if plin:
        chain = list(plin.get("ops") or [])
    chain.append({"op": op, "params": params})
    frame._lineage = {"kind": "derived", "parent": parent.key,
                      "root": (plin or {}).get("kind", "upload"),
                      "ops": chain,
                      "parent_lineage": plin}


def lineage_of(frame) -> Dict:
    """The lineage block ``GET /3/Frames/{id}`` surfaces. Frames with
    no recorded provenance are ``upload`` (REST/from_numpy ingest —
    mirror is their only durability leg)."""
    lin = getattr(frame, "_lineage", None)
    if lin:
        out = dict(lin)
    elif getattr(frame, "_source_paths", None):
        out = {"kind": "source",
               "paths": list(frame._source_paths),
               "parse_kwargs": dict(getattr(frame, "_source_kwargs",
                                            None) or {})}
    else:
        out = {"kind": "upload"}
    out["rebuildable_from_lineage"] = out["kind"] == "source" or (
        out["kind"] == "derived" and out.get("root") == "source")
    with _lock:
        out["mirrored"] = frame.key in _mirrored
    return out


def rebuild_from_lineage(key: str, lineage: Dict):
    """Deterministically re-materialize a lost frame from its recorded
    provenance. Source frames re-import; derived chains replay their
    ops over the re-imported root. Raises :class:`DataLostError` when
    the chain is not replayable (upload roots, missing source files)."""
    lin = lineage or {}
    if lin.get("kind") == "derived":
        root_lin = lin.get("parent_lineage")
        if lin.get("root") != "source" or not root_lin:
            raise DataLostError(key, "derived from an upload frame with "
                                     "no mirror")
        if not lin.get("ops"):
            raise DataLostError(key, "derived lineage with no op chain")
        from h2o3_tpu.core.kv import DKV
        parent_key = lin["parent"]
        # Replay over the DKV-resident parent when it is alive — a
        # sorted maybe_rebuild walk recovers 'train' before
        # 'train_sub', and re-importing + removing it here would
        # destroy the just-recovered frame (mirror, registry row and
        # all). Re-import only a genuinely absent parent, and under
        # suspended() so the temporary (and every replay intermediate)
        # never registers/mirrors and its removal has no side effects.
        base_is_temp = parent_key not in DKV
        with suspended():
            base = (rebuild_from_lineage(parent_key, root_lin)
                    if base_is_temp else DKV.get(parent_key))
            fr = base
            for step in lin["ops"]:
                nxt = _replay_op(fr, step)
                if fr is not base:
                    DKV.remove(fr.key)       # replay intermediate
                fr = nxt
            if fr.key != key:
                DKV.remove(fr.key)
                fr.key = key
                DKV.put(key, fr)
            if base_is_temp and base.key != key:
                DKV.remove(base.key)
        # the suspended re-key skipped the write-through hook: re-stamp
        # the recorded lineage and register the final frame so it
        # regains mirror + registry coverage on its new home
        fr._lineage = dict(lin)
        on_frame_put(fr)
        return fr
    if lin.get("kind") != "source":
        raise DataLostError(key, "no mirror and no source lineage "
                                 "(upload frames need mirror mode)")
    paths = lin.get("paths") or []
    for p in paths:
        if not os.path.exists(p):
            raise DataLostError(key, f"source file missing: {p}")
    from h2o3_tpu.io.parser import import_file
    kw = dict(lin.get("parse_kwargs") or {})
    kw.pop("destination_frame", None)
    return import_file(paths[0], destination_frame=key, **kw)


def _replay_op(fr, step: Dict):
    op, params = step.get("op"), step.get("params") or {}
    if op == "select":
        return fr[params["columns"]]
    if op == "drop":
        return fr.drop(params["columns"])
    raise DataLostError(fr.key, f"unreplayable derived op '{op}'")


# --------------------------------------------------------- mirroring

def _fname(key: str, gen: int) -> str:
    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in key)
    return f"{safe}_g{gen}{FRAME_SUFFIX}"


def on_frame_put(frame) -> None:
    """Write-through hook (Frame.__init__ → DKV.put): register the
    frame's home in the KV registry and, in mirror mode, persist its
    device-independent blocks. The ``off`` fast path never reaches
    here — callers gate on the env knob directly."""
    m = mode()
    if m == "off" or _is_suspended():
        return
    key = frame.key
    entry: Dict[str, Any] = {"pid": _self_pid(), "ts": time.time(),
                             "nrows": frame.nrows, "ncols": frame.ncols}
    lin = getattr(frame, "_lineage", None)
    if lin is None and getattr(frame, "_source_paths", None):
        lin = {"kind": "source", "paths": list(frame._source_paths),
               "parse_kwargs": dict(getattr(frame, "_source_kwargs",
                                            None) or {})}
    if lin:
        entry["lineage"] = lin
    if m == "mirror":
        try:
            info = _mirror_blocks(frame)
            entry.update(info)
        except Exception as e:   # noqa: BLE001 - mirror is best-effort
            log.warning("mirror write-through failed for %s: %s", key, e)
    _publish_registry(key, entry)
    with _lock:
        _registered.add(key)
        was_lost = key in _lost
        _lost.discard(key)
    if was_lost:             # re-registered: the loss verdict is void
        _clear_lost_marker(key)
    # materialize the under-replication gauge from the first tracked
    # frame on — a scrape must see the healthy 0, not an absent series
    try:
        from h2o3_tpu import telemetry
        telemetry.gauge("frames_under_replicated")
    except Exception:        # noqa: BLE001 - gauges are best-effort
        pass


def _mirror_blocks(frame) -> Dict[str, Any]:
    """Persist one frame's blocks, generation-suffixed, returning the
    registry fields naming where the mirror lives."""
    from h2o3_tpu.io.persist import frame_to_bytes, persist_manager
    data = frame_to_bytes(frame)
    digest = frame_digest(frame)
    with _lock:
        gen = _gens.get(frame.key, 0) + 1
        _gens[frame.key] = gen
    info: Dict[str, Any] = {"gen": gen, "nbytes": len(data),
                            "digest": digest}
    if _transport() == "kv":
        client = _kv()
        b64 = _encode(data)
        prefix = f"{KV_PREFIX}blob/{frame.key}/g{gen}/"
        nparts = (len(b64) + _B64_CHUNK - 1) // _B64_CHUNK if b64 else 0
        # parts BEFORE meta: a reader that sees the meta sees every part
        for j in range(nparts):
            client.key_value_set(
                f"{prefix}p{j}",
                b64[j * _B64_CHUNK:(j + 1) * _B64_CHUNK],
                allow_overwrite=True)
        client.key_value_set(
            f"{prefix}meta",
            json.dumps({"parts": nparts, "nbytes": len(data),
                        "digest": digest}),
            allow_overwrite=True)
        info["where"] = "kv"
    else:
        path = os.path.join(mirror_dir(), _fname(frame.key, gen))
        persist_manager.write(path, data)    # atomic tmp + rename
        info["where"] = "disk"
        info["uri"] = path
    _drop_mirror(frame.key, keep_gen=gen)
    with _lock:
        _mirrored[frame.key] = info
    _account(len(data))
    return info


def fetch_mirror(entry: Dict[str, Any]) -> bytes:
    """Pull a mirrored frame's bytes named by its registry entry."""
    if entry.get("where") == "kv":
        client = _kv()
        prefix = (f"{KV_PREFIX}blob/{entry['key']}/"
                  f"g{entry.get('gen', 1)}/")
        meta = json.loads(client.blocking_key_value_get(
            f"{prefix}meta", 10_000))
        parts = [client.blocking_key_value_get(f"{prefix}p{j}", 10_000)
                 for j in range(int(meta.get("parts", 0)))]
        return _decode("".join(parts))
    from h2o3_tpu.io.persist import persist_manager
    return persist_manager.read(entry["uri"])


def _drop_mirror(key: str, keep_gen: Optional[int] = None) -> None:
    """Delete this key's mirror blobs (all generations but
    ``keep_gen``) and release their accounting."""
    with _lock:
        info = _mirrored.get(key)
        if info is not None and info.get("gen") != keep_gen:
            _mirrored.pop(key, None)
        else:
            info = None
    if info is None:
        return
    _account(-int(info.get("nbytes", 0)))
    try:
        if info.get("where") == "kv":
            _kv().key_value_delete(
                f"{KV_PREFIX}blob/{key}/g{info['gen']}/")
        elif info.get("uri"):
            from h2o3_tpu.io.persist import persist_manager
            persist_manager.delete(info["uri"])
    except Exception:        # noqa: BLE001 - init-time sweep catches it
        pass


def on_remove(key: str, value=None) -> None:
    """DKV.remove hook: a deliberately deleted frame takes its mirror,
    registry row, and LOST marker with it. Keys this process never
    registered (transient row_slice views) cost one set lookup — no
    KV round-trip."""
    if mode() == "off":
        return
    with _lock:
        registered = key in _registered
        _registered.discard(key)
        # deliberate removal of a known-lost key retires the
        # cluster-wide verdict too; plain transient keys keep the
        # documented no-KV-round-trip fast path
        was_lost = key in _lost
        _lost.discard(key)
    if was_lost:
        _clear_lost_marker(key)
        # retire the loss record too: the dead peer's ``lost: true``
        # registry row would otherwise resurrect the verdict on the
        # next supervisor round
        ent = registry().get(key)
        if ent is not None and ent.get("lost"):
            try:
                _kv().key_value_delete(
                    f"{KV_PREFIX}reg/{ent['pid']}/{key}")
            except Exception:    # noqa: BLE001
                pass
    if not registered:
        return
    _drop_mirror(key)
    try:
        _kv().key_value_delete(f"{KV_PREFIX}reg/{_self_pid()}/{key}")
    except Exception:        # noqa: BLE001 - registry is best-effort
        pass


def _account(delta: int) -> None:
    """Governor-accounted mirror bytes → ``frames_mirrored_bytes``."""
    try:
        from h2o3_tpu.core import memgov
        memgov.governor.account_mirror(delta)
    except Exception:        # noqa: BLE001 - accounting best-effort
        pass


def mirrored_bytes() -> int:
    with _lock:
        return sum(int(i.get("nbytes", 0)) for i in _mirrored.values())


# ---------------------------------------------------------- registry

def _publish_registry(key: str, entry: Dict[str, Any]) -> None:
    try:
        _kv().key_value_set(f"{KV_PREFIX}reg/{entry['pid']}/{key}",
                            json.dumps(entry), allow_overwrite=True)
    except Exception as e:   # noqa: BLE001 - registry write best-effort
        log.debug("durability registry publish failed: %s", e)


def registry(pid: Optional[int] = None,
             strict: bool = False) -> Dict[str, Dict]:
    """key -> entry for one peer's registered frames (every peer when
    ``pid`` is None; entries carry their ``key`` and ``pid``). A KV
    transport failure yields the empty view — except under ``strict``,
    where it re-raises so callers can tell "unreadable" from "empty"
    (the debris sweep must not treat a flaky KV as zero live blobs)."""
    out: Dict[str, Dict] = {}
    prefix = (f"{KV_PREFIX}reg/{pid}/" if pid is not None
              else f"{KV_PREFIX}reg/")
    try:
        items = _kv().key_value_dir_get(prefix)
    except Exception:        # noqa: BLE001 - KV down: empty view
        if strict:
            raise
        return out
    for k, v in items:
        try:
            d = json.loads(v)
            tail = k[len(f"{KV_PREFIX}reg/"):]
            owner, fk = tail.split("/", 1)
            d.setdefault("pid", int(owner))
            d["key"] = fk
            out[fk] = d
        except (ValueError, KeyError, TypeError):
            continue
    return out


def _lost_marker_key(key: str) -> str:
    return f"{KV_PREFIX}lost/{key}"


def _publish_lost(key: str, detail: str = "") -> None:
    """A rebuild proved the key unrecoverable: record it locally AND
    publish a ``lost/`` marker through the KV, so every peer's
    ``check_lost`` sees the same terminal verdict — not a silent
    ``DKV.get(...) is None`` on the peers that never ran the rebuild."""
    with _lock:
        _lost.add(key)
    try:
        _kv().key_value_set(_lost_marker_key(key),
                            json.dumps({"ts": time.time(),
                                        "detail": detail}),
                            allow_overwrite=True)
    except Exception:        # noqa: BLE001 - marker is best-effort
        pass


def _clear_lost_marker(key: str) -> None:
    try:
        _kv().key_value_delete(_lost_marker_key(key))
    except Exception:        # noqa: BLE001
        pass


def _kv_lost(key: str) -> bool:
    """Cluster-wide lost check against the published ``lost/`` markers
    (exact-key match — the dir scan may return sibling keys sharing the
    prefix)."""
    want = _lost_marker_key(key)
    try:
        return any(k == want for k, _ in _kv().key_value_dir_get(want))
    except Exception:        # noqa: BLE001 - KV down: unknown, not lost
        return False


def lost_keys() -> List[str]:
    out = set()
    plen = len(f"{KV_PREFIX}lost/")
    try:
        for k, _ in _kv().key_value_dir_get(f"{KV_PREFIX}lost/"):
            out.add(k[plen:])
    except Exception:        # noqa: BLE001 - KV down: local view only
        pass
    with _lock:
        out |= _lost
    return sorted(out)


def check_lost(key: str) -> None:
    """Raise :class:`DataLostError` when a key is proven gone — the
    fail-fast jobs and REST handlers call before touching a frame.
    Consults the local LOST set first, then the cluster-wide ``lost/``
    markers (cached locally on a hit)."""
    with _lock:
        gone = key in _lost
    if not gone and _kv_lost(key):
        with _lock:
            _lost.add(key)
        gone = True
    if gone:
        raise DataLostError(key, "peer died; no mirror or replayable "
                                 "lineage survived")


# ------------------------------------------------- rebuild supervisor

_rebuild_thread: Optional[threading.Thread] = None


def maybe_rebuild_async() -> None:
    """The heartbeat-round entry point: rebuilds run on their own
    daemon thread because ``_kv_round`` executes under the watchdog's
    bounded-call window — an inline rebuild (frame IO + a compile)
    would trip the bound and count as a heartbeat miss."""
    global _rebuild_thread
    if mode() == "off":
        return
    try:
        from h2o3_tpu.core import heartbeat
        if not heartbeat.dead_peers():
            return
    except Exception:        # noqa: BLE001 - monitor off: nothing dead
        return
    with _lock:
        if _rebuild_thread is not None and _rebuild_thread.is_alive():
            return
        t = threading.Thread(target=maybe_rebuild, daemon=True,
                             name="durability-rebuild")
        _rebuild_thread = t
    t.start()


def maybe_rebuild(now: Optional[float] = None) -> int:
    """Heartbeat-piggybacked recovery supervisor: when a peer is dead,
    the least-loaded survivor rebuilds each of its registered frames
    from mirror-or-lineage, re-homes the key, and publishes the
    rebuild in ``frame_rebuilds_total{source=}``. Rate-limited
    (``H2O3TPU_DUR_REBUILD_S``); returns how many frames this peer
    rebuilt this round."""
    global _last_rebuild
    if mode() == "off":
        return 0
    now = time.monotonic() if now is None else now
    with _lock:
        if now - _last_rebuild < _rebuild_interval_s():
            return 0
        _last_rebuild = now
    try:
        from h2o3_tpu.core import heartbeat
        dead = set(heartbeat.dead_peers())
    except Exception:        # noqa: BLE001 - monitor off: nothing dead
        dead = set()
    _refresh_gauges(dead)
    if not dead:
        return 0
    self_pid = _self_pid()
    loads = _peer_loads()
    rebuilt = 0
    for dpid in sorted(dead):
        for key, entry in sorted(registry(dpid).items()):
            target = _pick_target(dead, loads)
            if target != self_pid:
                continue         # another survivor owns this rebuild
            if entry.get("lost"):
                with _lock:      # terminal verdict from an earlier round
                    _lost.add(key)
                continue
            ok = rebuild_frame(key, entry)
            with _lock:
                now_lost = key in _lost
            if now_lost:
                # keep the dead peer's row as the loss record —
                # rewritten with a ``lost`` marker so later rounds skip
                # it but frames_under_replicated (the
                # data_durability_floor SLO input) still counts it
                _mark_lost_row(dpid, key, entry)
            else:
                try:
                    _kv().key_value_delete(
                        f"{KV_PREFIX}reg/{dpid}/{key}")
                except Exception:    # noqa: BLE001
                    pass
            if ok:
                rebuilt += 1
    _refresh_gauges(dead)
    return rebuilt


def _mark_lost_row(dpid: int, key: str, entry: Dict[str, Any]) -> None:
    """Rewrite a dead peer's registry row with ``lost: true`` — the
    permanent loss record (the ``lost/`` marker itself was published by
    :func:`rebuild_frame`)."""
    try:
        e = dict(entry)
        e["lost"] = True
        _kv().key_value_set(f"{KV_PREFIX}reg/{dpid}/{key}",
                            json.dumps(e), allow_overwrite=True)
    except Exception:        # noqa: BLE001 - registry is best-effort
        pass


def _peer_loads() -> Dict[int, float]:
    try:
        from h2o3_tpu.serving import fleet
        return fleet.peer_loads()
    except Exception:        # noqa: BLE001 - loads unknown: pick by pid
        return {}


def _pick_target(dead: set, loads: Dict[int, float]) -> int:
    """Least-loaded surviving peer (pid tiebreak) — the rebuild's new
    home. Every survivor computes the same answer from the shared
    heartbeat + telemetry views, so exactly one peer claims each key."""
    try:
        from h2o3_tpu.core import heartbeat
        alive = [p for p in heartbeat.healthy_peers() if p not in dead]
    except Exception:        # noqa: BLE001
        alive = [_self_pid()]
    if not alive:
        return _self_pid()
    return min(alive, key=lambda p: (loads.get(p, 0.0), p))


def rebuild_frame(key: str, entry: Dict[str, Any]) -> bool:
    """Rebuild ONE lost frame locally: mirror first (bit-identical
    blocks), lineage second (deterministic re-ingest). On success the
    frame lands in this process's DKV and re-registers here (the
    write-through hook re-homes + re-mirrors it). Unrecoverable keys
    join the LOST set; jobs touching them get :class:`DataLostError`."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.core.kv import DKV
    if key in DKV:
        return False             # already homed here (or rebuilt)
    from h2o3_tpu.parallel import mesh as mesh_mod
    t0 = time.monotonic()
    source = None
    err: Optional[BaseException] = None
    entry = dict(entry)
    entry.setdefault("key", key)
    from h2o3_tpu.core import heartbeat
    # rebuild under the LOCAL mesh: the global mesh still spans the
    # dead peer's devices, and device_put against non-addressable
    # shards would hang — the exact topology scheduled work items use,
    # so the rebuilt frame bit-matches a local single-process ingest.
    # local_work_scope: the cloud IS unhealthy while we recover from
    # the death that made it so — the health gate must not kill the
    # recovery (a lineage replay runs parse jobs with chunk boundaries)
    with heartbeat.local_work_scope(), mesh_mod.local_mesh_scope():
        if entry.get("gen"):
            try:
                from h2o3_tpu.io.persist import frame_from_bytes
                data = fetch_mirror(entry)
                fr = frame_from_bytes(data, key=key)
                want = entry.get("digest")
                if want and frame_digest(fr) != want:
                    DKV.remove(key)
                    raise IOError(f"mirror digest mismatch for {key}")
                source = "mirror"
            except Exception as e:  # noqa: BLE001 - fall to lineage
                err = e
                log.warning("mirror rebuild of %s failed: %s", key, e)
        if source is None:
            try:
                rebuild_from_lineage(key, entry.get("lineage") or {})
                source = "lineage"
            except DataLostError as e:
                err = e
            except Exception as e:  # noqa: BLE001 - replay failed
                err = e
    if source is None:
        _publish_lost(key, str(err) if err else "no mirror or lineage")
        log.error("frame %s is LOST (no rebuildable mirror/lineage): %s",
                  key, err)
        return False
    dt = time.monotonic() - t0
    telemetry.counter("frame_rebuilds_total", source=source).inc()
    telemetry.histogram("frame_rebuild_seconds",
                        buckets=_REBUILD_BUCKETS).observe(dt)
    log.info("rebuilt frame %s from %s in %.3fs (re-homed on pid %d)",
             key, source, dt, _self_pid())
    return True


def _refresh_gauges(dead: set) -> None:
    """``frames_under_replicated``: registered frames whose home is
    dead and which no survivor has rebuilt yet — the
    ``data_durability_floor`` SLO rule's input."""
    try:
        from h2o3_tpu import telemetry
        from h2o3_tpu.core.kv import DKV
        under = 0
        for key, entry in registry().items():
            if int(entry.get("pid", -1)) in dead and key not in DKV:
                under += 1
        telemetry.gauge("frames_under_replicated").set(under)
        # frames_mirrored_bytes publishes from the governor's ledger
        # (memgov.refresh_gauges) — one writer per gauge
    except Exception:        # noqa: BLE001 - gauges are best-effort
        pass


# -------------------------------------------- pure decision core

class DurabilityBoard:
    """The registry/rebuild state machine, pure and jax-free (the
    RunBoard model): key -> home pid + what legs can bring it back.
    The bench ``_stub_durability`` leg and the unit tests drive the
    same decisions the live supervisor makes over the KV registry."""

    def __init__(self, procs: List[int]):
        self.procs = list(procs)
        self._dead: set = set()
        # key -> {"pid", "gen", "mirrored", "lineage"}
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._lost: set = set()

    def register(self, key: str, pid: int, gen: int = 1,
                 mirrored: bool = False, lineage: bool = False) -> None:
        if pid not in self.procs or pid in self._dead:
            raise ValueError(f"pid {pid} cannot home {key}")
        self._entries[key] = {"pid": pid, "gen": gen,
                              "mirrored": bool(mirrored),
                              "lineage": bool(lineage)}
        self._lost.discard(key)

    def remove(self, key: str) -> None:
        self._entries.pop(key, None)
        self._lost.discard(key)

    def alive(self) -> List[int]:
        return [p for p in self.procs if p not in self._dead]

    def home(self, key: str) -> Optional[int]:
        e = self._entries.get(key)
        return None if e is None else e["pid"]

    def on_dead(self, pid: int,
                loads: Optional[Dict[int, float]] = None
                ) -> List[Tuple[str, int, str]]:
        """A peer died: plan every rebuild — ``(key, new_home,
        source)`` with mirror preferred over lineage, each key homed on
        the least-loaded survivor. Keys with neither leg join the LOST
        set. Idempotent per pid."""
        if pid in self._dead or pid not in self.procs:
            return []
        self._dead.add(pid)
        loads = loads or {}
        alive = self.alive()
        plan: List[Tuple[str, int, str]] = []
        for key in sorted(self._entries):
            e = self._entries[key]
            if e["pid"] != pid:
                continue
            if not alive or not (e["mirrored"] or e["lineage"]):
                self._lost.add(key)
                continue
            target = min(alive, key=lambda p: (loads.get(p, 0.0), p))
            src = "mirror" if e["mirrored"] else "lineage"
            plan.append((key, target, src))
        return plan

    def on_rebuilt(self, key: str, pid: int) -> None:
        e = self._entries.get(key)
        if e is None or pid in self._dead:
            raise ValueError(f"bad rebuild ack for {key} on {pid}")
        e["pid"] = pid
        e["gen"] += 1

    def lost(self) -> List[str]:
        return sorted(self._lost)

    def under_replicated(self) -> List[str]:
        return sorted(k for k, e in self._entries.items()
                      if e["pid"] in self._dead and k not in self._lost)

    def complete(self) -> bool:
        return not self.under_replicated()


# -------------------------------------- whole-cloud checkpoint/restore

CLOUD_MAGIC = "h2o3tpu-cloud-v1"


def _quiesce_jobs(timeout_s: float) -> List[str]:
    """Wait (bounded) for RUNNING jobs to finish before snapshotting —
    a checkpoint taken mid-mutation would capture torn state. Returns
    job keys still running at the deadline (reported, not cancelled)."""
    from h2o3_tpu.core.kv import DKV
    deadline = time.monotonic() + max(0.0, timeout_s)
    while True:
        running = []
        for k in list(DKV.keys()):
            v = DKV.get_raw(k)
            if getattr(v, "status", None) == "RUNNING" and \
                    hasattr(v, "join"):
                running.append(k)
        if not running or time.monotonic() >= deadline:
            return running
        time.sleep(0.05)


def cloud_checkpoint(directory: str, quiesce_s: float = 30.0) -> Dict:
    """Persist the whole DKV — frames as device-independent blocks,
    models as device-lowered binaries — under ``directory``, manifest
    written LAST (the parts-before-meta ordering: a manifest that
    exists names only fully written artifacts). Returns the manifest."""
    from h2o3_tpu.core.kv import DKV
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.io.persist import (frame_to_bytes, model_to_bytes,
                                     persist_manager)
    from h2o3_tpu.models.model import Model
    t0 = time.monotonic()
    still_running = _quiesce_jobs(quiesce_s)
    manifest: Dict[str, Any] = {
        "magic": CLOUD_MAGIC, "ts": time.time(),
        "frames": [], "models": [], "skipped": [],
        "jobs_still_running": still_running}
    os.makedirs(directory, exist_ok=True)
    for idx, key in enumerate(sorted(DKV.keys())):
        v = DKV.get_raw(key)
        if getattr(v, "_is_lazy_stub", False):
            v = DKV.get(key)     # checkpoint materializes spilled frames
        if isinstance(v, Frame):
            fname = f"frame_{idx:04d}{FRAME_SUFFIX}"
            data = frame_to_bytes(v)
            persist_manager.write(os.path.join(directory, fname), data)
            manifest["frames"].append(
                {"key": key, "file": fname, "nbytes": len(data),
                 "digest": frame_digest(v),
                 "lineage": getattr(v, "_lineage", None)})
        elif isinstance(v, Model):
            fname = f"model_{idx:04d}.bin"
            data = model_to_bytes(v)
            persist_manager.write(os.path.join(directory, fname), data)
            manifest["models"].append(
                {"key": key, "file": fname, "nbytes": len(data),
                 "algo": getattr(v, "algo", "?"),
                 "digest": hashlib.sha256(data).hexdigest()})
        else:
            manifest["skipped"].append(key)
    persist_manager.write(os.path.join(directory, "manifest.json"),
                          json.dumps(manifest, indent=1).encode())
    manifest["seconds"] = round(time.monotonic() - t0, 4)
    log.info("cloud checkpoint: %d frame(s), %d model(s) -> %s (%.2fs)",
             len(manifest["frames"]), len(manifest["models"]),
             directory, manifest["seconds"])
    return manifest


def cloud_restore(directory: str) -> Dict:
    """Reform a cloud's DKV from a :func:`cloud_checkpoint` directory —
    frames land bit-identically (digest-verified), models re-register.
    The ``init(restore_dir=)`` / disaster-recovery entry point."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.io.persist import (frame_from_bytes, model_from_bytes,
                                     persist_manager)
    t0 = time.monotonic()
    mpath = os.path.join(directory, "manifest.json")
    if not os.path.exists(mpath):
        raise IOError(f"no cloud checkpoint manifest at {mpath}")
    manifest = json.loads(persist_manager.read(mpath).decode())
    if manifest.get("magic") != CLOUD_MAGIC:
        raise IOError(f"{mpath} is not an h2o3-tpu cloud checkpoint")
    restored = {"frames": 0, "models": 0}
    for ent in manifest.get("frames", []):
        data = persist_manager.read(os.path.join(directory, ent["file"]))
        fr = frame_from_bytes(data, key=ent["key"])
        if ent.get("lineage"):
            fr._lineage = ent["lineage"]
        want = ent.get("digest")
        if want:
            got = frame_digest(fr)
            if got != want:
                raise IOError(
                    f"restore of frame {ent['key']} is not bit-identical"
                    f" (digest {got[:12]} != {want[:12]})")
        restored["frames"] += 1
    for ent in manifest.get("models", []):
        model_from_bytes(persist_manager.read(
            os.path.join(directory, ent["file"])))
        restored["models"] += 1
    dt = time.monotonic() - t0
    try:
        telemetry.histogram("cloud_restore_seconds").observe(dt)
    except Exception:        # noqa: BLE001 - gauges are best-effort
        pass
    restored["seconds"] = round(dt, 4)
    log.info("cloud restore: %d frame(s), %d model(s) <- %s (%.2fs)",
             restored["frames"], restored["models"], directory, dt)
    return restored


# ------------------------------------------------ lifecycle + sweeps

def sweep_local_keys(client=None, pid: Optional[int] = None) -> None:
    """Delete THIS process's registry subtree + its mirror blobs from
    the coordination KV — the per-process half of the
    ``core/cloud._sweep_coordination_keys`` contract (``shutdown()``
    clears this process's registry keys)."""
    client = client if client is not None else _kv()
    pid = _self_pid() if pid is None else pid
    try:
        client.key_value_delete(f"{KV_PREFIX}reg/{pid}/")
    except Exception:        # noqa: BLE001
        pass
    with _lock:
        keys = list(_mirrored)
    for k in keys:
        _drop_mirror(k)


def sweep_keys() -> None:
    """Delete the ENTIRE durability subtree (init-time, after the
    roll-call barrier — the scheduler/fleet precedent): a re-formed
    cloud must never rebuild a previous incarnation's frames."""
    try:
        _kv().key_value_delete(KV_PREFIX)
    except Exception:        # noqa: BLE001
        pass


def sweep_debris() -> int:
    """Delete orphaned mirror artifacts: ``*.framesnap.tmp`` files a
    kill left mid-write, and ``*.framesnap`` blobs no live registry
    entry (any peer's) references — the conftest leak-check sweep,
    mirroring the fitsnap.tmp and spill-npz sweeps. Returns entries
    removed."""
    d = mirror_dir()
    if not os.path.isdir(d):
        return 0
    try:
        reg = registry(strict=True)
    except Exception:        # noqa: BLE001 - KV unreachable
        # blob liveness is unknowable without the registry: a sweep now
        # would delete other live peers' mirrors out from under the
        # rebuild path — only the always-safe half-written .tmp debris
        # goes
        reg = None
    with _lock:
        live = {_fname(k, i.get("gen", 1)) for k, i in _mirrored.items()}
    for ent in (reg or {}).values():
        if ent.get("uri"):
            live.add(os.path.basename(ent["uri"]))
    removed = 0
    for f in list(os.listdir(d)):
        p = os.path.join(d, f)
        orphan_tmp = f.endswith(FRAME_SUFFIX + ".tmp")
        orphan_blob = (reg is not None and f.endswith(FRAME_SUFFIX)
                       and f not in live)
        if orphan_tmp or orphan_blob:
            try:
                os.remove(p)
                removed += 1
            except OSError:
                pass
    try:
        if not os.listdir(d):
            os.rmdir(d)
    except OSError:
        pass
    return removed


def reset() -> None:
    """Test/shutdown hook: forget all local durability state (and the
    in-process KV shim)."""
    global _last_rebuild
    sweep_local_keys()
    with _lock:
        _mirrored.clear()
        _gens.clear()
        _registered.clear()
        _lost.clear()
        _last_rebuild = 0.0
    _local_kv._store.clear()
    _account(0)


def stats() -> Dict:
    with _lock:
        return {"mode": mode(), "mirrored": sorted(_mirrored),
                "mirrored_bytes": mirrored_bytes(),
                "lost": sorted(_lost),
                "registry": sorted(registry())}
