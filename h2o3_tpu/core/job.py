"""Job system — async job tracking with progress and cancellation.

Reference: water/Job.java:24 (start/update/progress, lines 206-225) and the
REST polling loop (client polls GET /3/Jobs/{id}). Jobs here run either
inline (fast path: device compute is async anyway, the Python 'job' merely
brackets it) or on a worker thread for long trainings so the REST server
stays responsive — the analogue of launching the ModelBuilder Driver on the
F/J pool (hex/ModelBuilder.java:234).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Optional

from h2o3_tpu.core import heartbeat as heartbeat_mod
from h2o3_tpu.core import request_ctx, watchdog
from h2o3_tpu.core.kv import DKV, make_key
from h2o3_tpu.core.scope import Scope
from h2o3_tpu.core.watchdog import is_infra_error  # noqa: F401 - re-export
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.job")

CREATED, RUNNING, DONE, FAILED, CANCELLED = (
    "CREATED", "RUNNING", "DONE", "FAILED", "CANCELLED")

# classification + retry policy live in core/watchdog.py (shared with
# bench.py and the probe); kept as an alias for existing importers
_INFRA_SIGNS = watchdog.INFRA_SIGNS


def free_device_memory(reason: str = "") -> None:
    """Best-effort HBM pressure release: drop jit executable caches and
    collect dropped buffers (the water/Cleaner.java role for a device
    whose backend reports no memory stats)."""
    import gc
    try:
        import jax
        jax.clear_caches()
    except Exception:
        pass
    gc.collect()
    log.info("freed device caches%s", f" ({reason})" if reason else "")


class JobCancelledException(Exception):
    pass


# cancellation is a user decision, never a retryable infra blip
watchdog.NON_RETRYABLE.append(JobCancelledException)


class Job:
    """One unit of trackable async work (reference water/Job.java:24)."""

    def __init__(self, description: str, work: float = 1.0, dest: Optional[str] = None):
        self.key = make_key("job")
        self.description = description
        self.dest = dest                      # key of the result object
        self.status = CREATED
        self.exception: Optional[str] = None
        self._work = max(work, 1e-9)
        self._worked = 0.0
        self._msg = ""
        self.start_time = 0.0
        self.end_time = 0.0
        self._cancel_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.result: Any = None
        # run exactly once when the job ends, whatever the status —
        # the memory governor parks its reservation release here
        # (core/memgov.py; retries re-enter fn, so the work itself
        # cannot host end-of-job cleanup)
        self._finalizers: list = []
        # request deadline (absolute monotonic) captured at SUBMISSION
        # time from the request context (api/server.py installs it for
        # ?_timeout_ms= / X-H2O-Deadline-Ms requests); background jobs
        # run on a fresh thread whose context would not inherit it, so
        # Job.start re-installs it via request_ctx.job_scope
        self.deadline: Optional[float] = request_ctx.current_deadline()
        # distributed trace context captured at SUBMISSION time, same
        # discipline as the deadline above: re-parented under the
        # submitting thread's active span (the REST request span) so
        # the job's root span stitches causally under the request that
        # created it, then re-installed on the worker thread by
        # job_scope (telemetry/trace_context.py)
        from h2o3_tpu.telemetry import spans as _spans
        from h2o3_tpu.telemetry import trace_context as _trace
        tc = _trace.current()
        self.trace = tc.child(_spans.current_span_id()
                              or tc.parent_id) if tc is not None else None
        self.trace_id: Optional[str] = tc.trace_id if tc else None
        DKV.put(self.key, self)

    # -- lifecycle (Job.start / Job.update, water/Job.java:206-225) ------
    def start(self, fn: Callable[["Job"], Any], background: bool = False) -> "Job":
        self.status = RUNNING
        self.start_time = time.time()
        from h2o3_tpu import telemetry
        from h2o3_tpu.telemetry import flight_recorder
        from h2o3_tpu.utils.timeline import record as _tl
        _tl("job", f"start {self.description}", key=self.key)
        telemetry.counter("jobs_started_total").inc()
        # live in-flight count: the per-node load summary GET /3/Cloud
        # and the cluster fan-in snapshots report (telemetry/cluster.py)
        telemetry.gauge("jobs_inflight").add(1)

        # the flight-recorder handle crosses the _run → _body closure
        # boundary via this cell (attach must run on the WORKER thread —
        # a background thread's context is fresh, so the contextvar set
        # in start()'s thread would never reach the work)
        rec_cell = []

        def _body():
            # every key the work creates is tracked in a job-local Scope:
            # a cancelled/expired job must release its partial keys
            # (water/Scope.java exit-on-abort role) instead of leaking
            # half-built models/frames into the DKV; DONE and FAILED
            # jobs keep theirs (pollers read FAILED results' state)
            sc = Scope()
            sc.__enter__()
            cloud_lost = False
            try:
                # the telemetry capsule key is DKV.put INSIDE this
                # Scope: a cancelled job's capsule is swept with its
                # partial keys (telemetry/flight_recorder.py)
                if rec_cell:
                    flight_recorder.publish(rec_cell[0])
                # bounded retries for infra-class errors only, under the
                # shared watchdog policy (backoff + jitter, attempts from
                # core/config.py). Supervisor contract: when the failed
                # work left an in-fit snapshot (core/recovery.py
                # FitCheckpointer), re-entering the fit resumes from it
                # instead of round 0 — the builder consults the same
                # checkpointer on entry; otherwise the work restarts
                # from scratch (model builds are idempotent).
                policy = watchdog.policy_from_config()
                attempt = 0
                while True:
                    attempt += 1
                    try:
                        watchdog.maybe_fail("job")
                        self.result = fn(self)
                        break
                    except Exception as e:  # noqa: BLE001
                        if (attempt >= policy.max_attempts
                                or not is_infra_error(e)
                                or self._cancel_requested.is_set()):
                            raise
                        if (isinstance(e, heartbeat_mod.CloudUnhealthyError)
                                and not heartbeat_mod.monitor.healthy()):
                            # fail-fast contract: retrying against a
                            # cloud that is STILL unhealthy just burns
                            # the backoff budget — recovery_dir
                            # snapshot/resume is the comeback path
                            raise
                        delay = policy.delay(attempt)
                        # consult the in-fit checkpointer: a surviving
                        # snapshot means the retry RE-ENTERS the fit at
                        # its last persisted boundary (bit-identical
                        # continuation) instead of restarting at round 0
                        from h2o3_tpu.core import recovery as _recovery
                        snap = _recovery.thread_fit_snapshot()
                        if snap is not None:
                            log.warning(
                                "job %s: infra error; supervisor will "
                                "resume the %s fit from its snapshot "
                                "(unit %d) in %.1fs (attempt %d/%d): %s",
                                self.key, snap[2], snap[1], delay,
                                attempt, policy.max_attempts, e)
                            _tl("job",
                                f"infra-resume {self.description}",
                                key=self.key, unit=snap[1],
                                error=str(e)[:200])
                        else:
                            log.warning(
                                "job %s: retrying after infra error "
                                "in %.1fs (attempt %d/%d): %s",
                                self.key, delay, attempt,
                                policy.max_attempts, e)
                            _tl("job", f"infra-retry {self.description}",
                                key=self.key, error=str(e)[:200])
                            self._worked = 0.0
                        telemetry.counter("infra_retries_total",
                                          site="job").inc()
                        if "RESOURCE_EXHAUSTED" in f"{e}":
                            # OOM escalation ladder (README §Memory
                            # governance): rung 1 purges the jit
                            # executable caches; rung 2 (repeat OOM)
                            # governor-evicts cold frames plus the
                            # per-frame device_matrix/bin caches; the
                            # snapshot consult above is rung 3 — the
                            # retry RESUMES from the checkpoint rather
                            # than restarting at round 0
                            free_device_memory("RESOURCE_EXHAUSTED retry")
                            telemetry.counter("oom_recoveries_total",
                                              stage="purge_jit").inc()
                            if attempt >= 2:
                                from h2o3_tpu.core.memgov import governor
                                freed = governor.evict_for_oom()
                                telemetry.counter("oom_recoveries_total",
                                                  stage="evict").inc()
                                log.warning(
                                    "job %s: repeat OOM — evicted cold "
                                    "frames + %.1f MB of device caches",
                                    self.key, freed / 1e6)
                            if snap is not None:
                                telemetry.counter("oom_recoveries_total",
                                                  stage="resume").inc()
                        policy.sleep(delay)
                if self.dest and self.result is not None:
                    DKV.put(self.dest, self.result)
                self.status = DONE
                _tl("job", f"done {self.description}", key=self.key)
            except JobCancelledException:
                self.status = CANCELLED
                _tl("job", f"cancelled {self.description}", key=self.key)
            except request_ctx.DeadlineExceeded as e:
                # an expired request deadline is a cancellation, not a
                # failure: the REST tier answers 408 and the job must
                # end CANCELLED, never linger RUNNING (ISSUE 3 contract)
                self.status = CANCELLED
                self._msg = "deadline exceeded"
                _tl("job", f"deadline-cancelled {self.description}",
                    key=self.key, error=str(e)[:200])
            except Exception as e:  # noqa: BLE001 - job boundary
                # exception BEFORE status: pollers react to FAILED by
                # reading .exception, which must already be set
                self.exception = "".join(
                    traceback.format_exception(type(e), e, e.__traceback__))
                self.status = FAILED
                # a cloud-unhealthy failure sweeps its partial keys like
                # a cancellation: the half-built model came off a
                # degraded mesh and must not linger in the DKV (resume
                # comes from recovery_dir snapshots, not these keys)
                cloud_lost = isinstance(e, heartbeat_mod.CloudUnhealthyError)
                _tl("job", f"failed {self.description}", key=self.key,
                    error=str(e)[:200])
                log.error("job %s failed: %s", self.key, e)
                if not background:
                    raise
            finally:
                self.end_time = time.time()
                if self.status != CANCELLED and not cloud_lost:
                    sc.keep(*sc._tracked)
                sc.__exit__(None, None, None)

        def _run():
            # the job is the ROOT telemetry span: everything the work
            # does (fit spans, boost chunks, compiles) nests under it —
            # background jobs run on their own thread, whose fresh
            # contextvar context makes this a root span automatically.
            # The flight recorder attaches FIRST so the root job span
            # itself lands in the capsule when it closes.
            handle = flight_recorder.attach(self.key, self.description)
            if handle is not None:
                rec_cell.append(handle)
            try:
                # job_scope makes this job + its captured deadline
                # visible to cancel_point() checks at chunk boundaries
                # (parallel/map_reduce.py) no matter how deep the work
                # nests — background threads start with a fresh
                # contextvar context, so this re-install is what carries
                # the request deadline across the thread hop
                with request_ctx.job_scope(self, deadline=self.deadline,
                                           trace=self.trace), \
                        telemetry.span("job", key=self.key,
                                       desc=self.description):
                    _body()
            finally:
                for fin in self._finalizers:
                    try:
                        fin()
                    except Exception:   # noqa: BLE001 - best-effort
                        pass
                flight_recorder.detach(handle, status=self.status)
                telemetry.gauge("jobs_inflight").add(-1)
                telemetry.counter("jobs_completed_total",
                                  status=self.status).inc()
                telemetry.histogram("job_duration_seconds").observe(
                    (self.end_time or time.time()) - self.start_time)

        if background:
            self._thread = threading.Thread(target=_run, daemon=True, name=self.key)
            self._thread.start()
        else:
            _run()
        return self

    def update(self, units: float, msg: str = "") -> None:
        self._worked = min(self._work, self._worked + units)
        if msg:
            self._msg = msg
        if self._cancel_requested.is_set():
            raise JobCancelledException(self.key)
        if self.deadline is not None and time.monotonic() >= self.deadline:
            from h2o3_tpu import telemetry
            telemetry.counter("request_deadline_exceeded_total").inc()
            raise request_ctx.DeadlineExceeded(
                f"job {self.key}: request deadline exceeded "
                f"(observed at progress update)")
        heartbeat_mod.check_healthy("job.update")

    @property
    def progress(self) -> float:
        if self.status == DONE:
            return 1.0
        return self._worked / self._work

    @property
    def progress_msg(self) -> str:
        return self._msg

    def add_finalizer(self, fn: Callable[[], None]) -> None:
        """Register end-of-job cleanup (runs once in the worker's
        finally, after DONE/FAILED/CANCELLED is settled)."""
        self._finalizers.append(fn)

    def cancel(self) -> None:
        self._cancel_requested.set()

    def cancel_requested(self) -> bool:
        """Polled at chunk boundaries (request_ctx.cancel_point — the
        water/Job.java stop_requested() analogue)."""
        return self._cancel_requested.is_set()

    def join(self, timeout: Optional[float] = None) -> "Job":
        if self._thread is not None:
            self._thread.join(timeout)
        return self

    @property
    def run_time(self) -> float:
        end = self.end_time or time.time()
        return end - self.start_time if self.start_time else 0.0

    def to_dict(self) -> dict:
        """JobV3 wire shape (water/api/schemas3/JobV3.java) — the real
        h2o-py H2OJob reads key.name, dest.name, status, progress,
        auto_recoverable, warnings (h2o-py/h2o/job.py:36-56)."""
        dest_type = "Key<Keyed>"
        if self.dest:
            from h2o3_tpu.models.model import Model
            if isinstance(DKV.get_raw(self.dest), Model):
                dest_type = "Key<Model>"
        return {
            "__meta": {"schema_version": 3, "schema_name": "JobV3",
                       "schema_type": "Job"},
            "key": {"name": self.key, "type": "Key<Job>",
                    "URL": f"/3/Jobs/{self.key}"},
            "description": self.description,
            "status": self.status,
            "progress": self.progress,
            "progress_msg": self._msg,
            "start_time": int(self.start_time * 1000),
            "msec": int(self.run_time * 1000),
            "dest": {"name": self.dest or "", "type": dest_type},
            "exception": self.exception,
            "stacktrace": self.exception,
            # the whole job's cross-host trace is one
            # GET /3/Trace?trace_id= fetch away (ISSUE 16)
            "trace_id": self.trace_id,
            "warnings": [],
            "auto_recoverable": False,
            "ready_for_view": True,
            "run_time_ms": int(self.run_time * 1000),
        }


def list_jobs() -> list:
    out = []
    for k in DKV.keys("job_"):
        # the key can be removed between keys() and get() (remove_all
        # from another handler thread) — skip dead keys instead of
        # AttributeError'ing on None
        j = DKV.get(k)
        if isinstance(j, Job):
            out.append(j.to_dict())
    return out
