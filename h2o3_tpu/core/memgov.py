"""MemoryGovernor — HBM as a governed resource.

Reference: the reference platform budgets its heap centrally
(water/MemoryManager.java: MEM_MAX and the CAN_ALLOC gate) and lets the
Cleaner thread swap cold Values to ice against that budget
(water/Cleaner.java:85-162). The TPU port had only the raw mechanics:
an LRU spiller with no budget source of truth, an OOM "recovery" that
purged the jit cache and restarted from round 0, and a ``/3/Cloud``
reporting ``free_mem: 0``.

This module is the single budget truth plus the policies around it:

- **Budget resolution** (``device_limit_bytes`` / ``budget_bytes``):
  device ``bytes_limit`` when the backend reports it, else the
  ``H2O3TPU_HBM_BUDGET_MB`` knob (deterministic and testable on CPU,
  where ``memory_stats()`` is empty), else the tracked sum of resident
  frame/cache bytes. ``ops/merge.py``'s out-size cap and
  ``core/cleaner.py``'s ``pressure()`` both route through here.
- **Predictive admission** (``admit_fit`` / ``reserve``): before a fit
  dispatches, its device footprint is estimated from the input frame
  bytes plus the roofline byte estimators (telemetry/roofline.py); a
  fit that would overshoot first spills cold frames via the Cleaner and
  only then is rejected pre-dispatch with an actionable error naming
  projected vs available bytes. Concurrent fits hold reservations in a
  ledger so two individually-admissible fits cannot jointly overshoot —
  bounded wait for a release, then reject (the AdmissionGate contract
  of api/server.py, applied to bytes instead of request slots).
- **OOM eviction** (``evict_for_oom``): the job supervisor's
  RESOURCE_EXHAUSTED escalation ladder (core/job.py) calls in here to
  drop the per-frame ``device_matrix``/``bin_frame`` caches — device
  residents that were previously pinned for the process lifetime — and
  spill every cold frame, before resuming the fit from its checkpoint.
- **Memory truth** (``snapshot`` / ``refresh_gauges``): the
  ``hbm_bytes_in_use`` / ``hbm_budget_bytes`` / ``frames_spilled_bytes``
  gauges, and the governor-backed ``free_mem``/``max_mem``/``swap_mem``
  of GET /3/Cloud.

Telemetry: the gauges above plus ``frame_spills_total``,
``frame_restores_total``, ``fit_admission_rejections_total{reason}``,
``oom_recoveries_total{stage}`` (README §Memory governance).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from h2o3_tpu.core import config as _config
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.memgov")

# assumed HBM when an accelerator plugin exports no memory stats and no
# budget knob is set (the old private fallback of ops/merge.py, now the
# one shared constant)
DEFAULT_DEVICE_HBM_BYTES = 16 << 30


class MemoryBudgetExceeded(ValueError):
    """Pre-dispatch admission rejection — deliberately a ValueError so
    the watchdog never retries it and the REST tier maps it to 412 with
    the H2OErrorV3 shape (api/server.py error mapping). The message
    names projected vs available bytes so the client can act (free
    frames, raise H2O3TPU_HBM_BUDGET_MB, or shrink the fit)."""

    def __init__(self, msg: str, projected: int = 0, available: int = 0,
                 budget: int = 0):
        super().__init__(msg)
        self.projected = int(projected)
        self.available = int(available)
        self.budget = int(budget)


class Reservation:
    """One fit's entry in the admission ledger."""

    __slots__ = ("owner", "nbytes", "ts", "released")

    def __init__(self, owner: str, nbytes: int):
        self.owner = owner
        self.nbytes = int(nbytes)
        self.ts = time.monotonic()
        self.released = False

    def __repr__(self):
        return f"<Reservation {self.owner} {self.nbytes / 1e6:.1f}MB>"


# auxiliary device-cache registry: subsystems holding device bytes
# OUTSIDE the DKV frame caches (e.g. the serving tier's compiled-scorer
# cache) register here so the eviction ladders can reclaim them the
# same way they drop frame device caches
_AUX_LOCK = threading.Lock()
_AUX_CACHES: Dict[str, tuple] = {}   # name -> (nbytes_fn, evict_fn)


def register_aux_cache(name: str, nbytes_fn, evict_fn) -> None:
    """Register an auxiliary device cache with the governor.

    ``nbytes_fn() -> int`` reports the cache's current device bytes;
    ``evict_fn(exclude=None) -> int`` drops it and returns bytes freed.
    Idempotent by name (re-registration replaces the hooks)."""
    with _AUX_LOCK:
        _AUX_CACHES[name] = (nbytes_fn, evict_fn)


def aux_cache_bytes() -> int:
    """Device bytes held by registered auxiliary caches."""
    total = 0
    with _AUX_LOCK:
        hooks = list(_AUX_CACHES.values())
    for nbytes_fn, _ in hooks:
        try:
            total += int(nbytes_fn() or 0)
        except Exception:   # noqa: BLE001 - accounting is best-effort
            pass
    return total


def _evict_aux_caches(exclude: Optional[set] = None) -> int:
    freed = 0
    with _AUX_LOCK:
        hooks = list(_AUX_CACHES.items())
    for name, (_, evict_fn) in hooks:
        try:
            freed += int(evict_fn(exclude=exclude) or 0)
        except Exception as e:   # noqa: BLE001 - one bad hook must not
            log.warning("aux cache '%s' eviction failed: %s", name, e)
    return freed


def _frame_cache_nbytes(fr) -> int:
    """Device bytes pinned by a frame's derived caches: the stacked
    ``device_matrix`` arrays and the ``bin_frame`` BinnedMatrix results
    (frame/frame.py, frame/binning.py)."""
    total = 0
    for m in list(getattr(fr, "_matrix_cache", {}).values()):
        total += int(getattr(m, "nbytes", 0) or 0)
    for bm in list(getattr(fr, "_bin_cache", {}).values()):
        for attr in ("bins", "edges"):
            a = getattr(bm, attr, None)
            total += int(getattr(a, "nbytes", 0) or 0)
        for t in list(getattr(bm, "_tile_cache", {}).values()):
            total += int(getattr(t, "nbytes", 0) or 0)
    return total


def estimate_fit_bytes(algo: str, params: Optional[Dict], frame, x,
                       validation_frame=None) -> int:
    """Projected device footprint of one fit: the resident input frames,
    the stacked f32 design matrix the builders materialize, and one
    algo-native unit's worth of the roofline streamed-bytes estimate
    (one tree / one IRLS iteration / one epoch — the transient working
    set alive between chunk boundaries)."""
    from h2o3_tpu.core.cleaner import _frame_nbytes
    est = _frame_nbytes(frame)
    if validation_frame is not None and validation_frame is not frame:
        est += _frame_nbytes(validation_frame)
    feats = max(len(x or []), 1)
    npad = int(getattr(frame, "nrows_padded", None)
               or getattr(frame, "nrows", 0) or 0)
    est += npad * feats * 4
    try:
        from h2o3_tpu.telemetry import roofline
        cost = roofline.analytic_fit_cost(algo, params or {}, None,
                                          frame, x)
    except Exception:   # noqa: BLE001 - estimate must never block a fit
        cost = None
    if cost:
        d = cost.get("detail", {})
        units = float(d.get("trees") or d.get("iterations") or 0.0)
        if not units:
            # DL details carry samples; one epoch = nrows samples
            samples = float(d.get("samples", 0.0) or 0.0)
            rows = float(getattr(frame, "nrows", 0) or 1)
            units = samples / rows if samples else 1.0
        est += int(float(cost.get("bytes", 0.0)) / max(units, 1.0))
    return int(est)


class MemoryGovernor:
    """Process-wide HBM budget arbiter (singleton ``governor``)."""

    def __init__(self):
        self._cond = threading.Condition(threading.Lock())
        self._reservations: List[Reservation] = []
        self._spilled_bytes = 0      # live bytes on ice (npz spills)
        self._mirror_bytes = 0       # live mirror blobs (durability)

    # -- budget truth --------------------------------------------------
    def device_limit_bytes(self) -> int:
        """The hard budget: device ``bytes_limit`` when the backend
        reports one, else the ``H2O3TPU_HBM_BUDGET_MB`` knob in bytes;
        0 = no limit known (ungoverned)."""
        from h2o3_tpu.core.cleaner import device_memory_stats
        stats = device_memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
        # env read at call time (policy_from_config pattern): tests and
        # bench children set the knob without rebuilding config.ARGS
        mb = os.environ.get("H2O3TPU_HBM_BUDGET_MB")
        if mb is None:
            mb = getattr(_config.ARGS, "hbm_budget_mb", 0)
        try:
            return int(float(mb)) << 20
        except (TypeError, ValueError):
            return 0

    def governed(self) -> bool:
        return self.device_limit_bytes() > 0 and self._mode() != "off"

    def budget_bytes(self) -> int:
        """The effective budget every surface reports: the hard limit,
        or (nothing known) the tracked resident bytes themselves."""
        return self.device_limit_bytes() or self.resident_bytes()

    def _mode(self) -> str:
        return str(os.environ.get("H2O3TPU_MEMGOV",
                                  getattr(_config.ARGS, "memgov", "auto"))
                   ).lower()

    def _wait_s(self) -> float:
        env = os.environ.get("H2O3TPU_MEMGOV_WAIT_S")
        if env is not None:
            return float(env)
        return float(getattr(_config.ARGS, "memgov_wait_s", 5.0))

    # -- accounting ----------------------------------------------------
    def resident_bytes(self) -> int:
        """Tracked device bytes: every in-memory DKV frame's columns
        plus its derived device caches (stubs on ice count zero)."""
        from h2o3_tpu.core.cleaner import _frame_nbytes
        from h2o3_tpu.core.kv import DKV
        from h2o3_tpu.frame.frame import Frame
        total = 0
        for key in list(DKV.keys()):
            v = DKV.get_raw(key)
            if isinstance(v, Frame):
                total += _frame_nbytes(v) + _frame_cache_nbytes(v)
            del v
        return total

    def bytes_in_use(self) -> int:
        """Device bytes_in_use when the backend reports it, else the
        tracked resident bytes."""
        from h2o3_tpu.core.cleaner import device_memory_stats
        stats = device_memory_stats()
        if stats:
            return int(stats.get("bytes_in_use", 0))
        return self.resident_bytes()

    def pressure(self) -> float:
        """Fraction of the budget in use; 0 when ungoverned (no limit
        to be under pressure against)."""
        limit = self.device_limit_bytes()
        if not limit:
            return 0.0
        return self.bytes_in_use() / limit

    def spilled_bytes(self) -> int:
        with self._cond:
            return self._spilled_bytes

    def note_spill(self, nbytes: int) -> None:
        """A frame went to ice (Cleaner npz spill)."""
        with self._cond:
            self._spilled_bytes += max(int(nbytes), 0)
        self.refresh_gauges()

    def note_unspill(self, nbytes: int) -> None:
        """An ice copy was reclaimed (restore won / key removed /
        stub clobbered by a newer put)."""
        with self._cond:
            self._spilled_bytes = max(
                self._spilled_bytes - max(int(nbytes), 0), 0)
            self._cond.notify_all()
        self.refresh_gauges()

    def mirror_bytes(self) -> int:
        with self._cond:
            return self._mirror_bytes

    def account_mirror(self, delta: int) -> None:
        """Durability mirror blobs flow through the governor's ledger
        like spills do (core/durability.py write-through), so
        ``frames_mirrored_bytes`` publishes from the same memory-truth
        surface as the other byte gauges."""
        with self._cond:
            self._mirror_bytes = max(self._mirror_bytes + int(delta), 0)
        self.refresh_gauges()

    def reserved_bytes(self) -> int:
        with self._cond:
            return sum(r.nbytes for r in self._reservations)

    # -- eviction ------------------------------------------------------
    def evict_frame_caches(self, exclude: Optional[set] = None) -> int:
        """Drop every frame's device_matrix/bin_frame caches (previously
        pinned for the process lifetime) plus any registered auxiliary
        device caches (compiled scorers etc.); returns bytes released."""
        from h2o3_tpu.core.kv import DKV
        from h2o3_tpu.frame.frame import Frame
        freed = 0
        for key in list(DKV.keys()):
            if exclude and key in exclude:
                continue
            v = DKV.get_raw(key)
            if isinstance(v, Frame):
                freed += v.drop_device_caches()
            del v
        freed += _evict_aux_caches(exclude=exclude)
        if freed:
            log.info("evicted %.1f MB of frame device caches", freed / 1e6)
        return freed

    def evict_for_admission(self, needed: int,
                            exclude: Optional[set] = None) -> int:
        """Spill cold frames until ``needed`` bytes fit under the budget
        (or nothing cold remains); returns frames spilled."""
        from h2o3_tpu.core.cleaner import cleaner
        limit = self.device_limit_bytes()
        freed = 0
        while self.bytes_in_use() + self.reserved_bytes() + needed > limit:
            spilled = cleaner.spill_coldest(1, exclude=exclude)
            if not spilled:
                break
            freed += 1
        return freed

    def evict_for_oom(self, exclude: Optional[set] = None) -> int:
        """The heavy rung of the OOM ladder: drop every derived device
        cache AND spill every cold frame. Returns cache bytes freed."""
        from h2o3_tpu.core.cleaner import cleaner
        freed = self.evict_frame_caches(exclude=exclude)
        cleaner.spill_coldest(n=1 << 30, exclude=exclude)
        self.refresh_gauges()
        return freed

    # -- admission -----------------------------------------------------
    def reserve(self, owner: str, nbytes: int,
                exclude: Optional[set] = None,
                timeout_s: Optional[float] = None) -> Reservation:
        """Admit ``nbytes`` of projected footprint or raise
        ``MemoryBudgetExceeded``. Spills cold frames first; when other
        jobs' reservations are what blocks admission, waits (bounded)
        for a release before rejecting."""
        from h2o3_tpu import telemetry
        rsv = Reservation(owner, nbytes)
        if not self.governed():
            with self._cond:
                self._reservations.append(rsv)
            return rsv
        limit = self.device_limit_bytes()
        deadline = time.monotonic() + (self._wait_s()
                                       if timeout_s is None else timeout_s)
        while True:
            in_use = self.bytes_in_use()
            reserved = self.reserved_bytes()
            if in_use + reserved + nbytes <= limit:
                with self._cond:
                    self._reservations.append(rsv)
                self.refresh_gauges()
                return rsv
            # rung 1: make room by spilling cold frames
            self.evict_for_admission(nbytes, exclude=exclude)
            in_use = self.bytes_in_use()
            if in_use + self.reserved_bytes() + nbytes <= limit:
                continue
            # rung 2: the blocker is other fits' reservations — wait
            # (bounded) for one to release, AdmissionGate-style
            if self.reserved_bytes() > 0 and time.monotonic() < deadline:
                with self._cond:
                    self._cond.wait(timeout=min(
                        0.25, max(deadline - time.monotonic(), 0.01)))
                continue
            reason = "contention" if self.reserved_bytes() > 0 else "budget"
            available = max(limit - in_use - self.reserved_bytes(), 0)
            telemetry.counter("fit_admission_rejections_total",
                              reason=reason).inc()
            log.warning("admission rejected for %s: projected %d B > "
                        "available %d B (budget %d B, reason=%s)",
                        owner, nbytes, available, limit, reason)
            raise MemoryBudgetExceeded(
                f"fit '{owner}' rejected before dispatch: projected "
                f"device footprint {nbytes} bytes exceeds available "
                f"HBM {available} bytes (budget {limit} bytes, "
                f"{in_use} in use, {self.reserved_bytes()} reserved by "
                f"concurrent fits; reason={reason}). Free or delete "
                f"frames, raise H2O3TPU_HBM_BUDGET_MB, or shrink the "
                f"fit.", projected=nbytes, available=available,
                budget=limit)

    def admit_replica(self, model_key: str, nbytes: int) -> Reservation:
        """Serving-replica admission (ISSUE 17): reserve the replica's
        projected device bytes NOW or raise ``MemoryBudgetExceeded`` —
        no bounded wait, because a fleet peer that cannot take the
        replica must DECLINE registration immediately so the registry
        offers it to the next healthy peer instead of queueing behind
        fits. The reservation lives as long as the replica; the fleet
        releases it on deregistration/eviction."""
        return self.reserve(f"replica:{model_key}", nbytes,
                            timeout_s=0.0)

    def release(self, rsv: Optional[Reservation]) -> None:
        if rsv is None or rsv.released:
            return
        with self._cond:
            rsv.released = True
            try:
                self._reservations.remove(rsv)
            except ValueError:
                pass
            self._cond.notify_all()
        self.refresh_gauges()

    def admit_fit(self, algo: str, params: Optional[Dict], frame, x,
                  validation_frame=None) -> Reservation:
        """ModelBuilder.train's pre-dispatch hook: estimate → reserve
        (spill / bounded wait / reject)."""
        projected = estimate_fit_bytes(algo, params, frame, x,
                                       validation_frame)
        exclude = {getattr(frame, "key", None),
                   getattr(validation_frame, "key", None)} - {None}
        return self.reserve(f"{algo}:{getattr(frame, 'key', '?')}",
                            projected, exclude=exclude)

    # -- surfacing -----------------------------------------------------
    def snapshot(self) -> Dict:
        limit = self.device_limit_bytes()
        in_use = self.bytes_in_use()
        budget = limit or in_use
        return {"budget_bytes": budget,
                "limit_bytes": limit,
                "bytes_in_use": in_use,
                "free_bytes": max(budget - in_use, 0),
                "spilled_bytes": self.spilled_bytes(),
                "aux_cache_bytes": aux_cache_bytes(),
                "reserved_bytes": self.reserved_bytes(),
                "reservations": len(self._reservations),
                "governed": self.governed()}

    def refresh_gauges(self) -> None:
        """Publish the memory truth into the metrics registry (and
        therefore flight-recorder capsules + /3/Cloud fan-in)."""
        try:
            from h2o3_tpu import telemetry
            telemetry.gauge("hbm_budget_bytes").set(self.budget_bytes())
            telemetry.gauge("hbm_bytes_in_use").set(self.bytes_in_use())
            telemetry.gauge("frames_spilled_bytes").set(
                self.spilled_bytes())
            telemetry.gauge("frames_mirrored_bytes").set(
                self.mirror_bytes())
        except Exception:   # noqa: BLE001 - gauges are best-effort
            pass

    def reset(self) -> None:
        """Shutdown/test hook: drop all ledger state."""
        with self._cond:
            self._reservations.clear()
            self._spilled_bytes = 0
            self._mirror_bytes = 0
            self._cond.notify_all()


governor = MemoryGovernor()
