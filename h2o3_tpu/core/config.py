"""Runtime configuration (analogue of water.H2O.OptArgs, reference
h2o-core/src/main/java/water/H2O.java:209,296-355).

The reference parses a flat CLI flag struct plus ``sys.ai.h2o.*`` system
properties (H2O.java:1321-1334). Here: a flat dataclass overridable from
``init()`` kwargs and ``H2O3TPU_*`` environment variables.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass
class Config:
    name: str = "h2o3-tpu"           # cloud name (-name)
    port: int = 54321                 # REST port (-port)
    log_level: str = "INFO"           # -log_level
    nthreads: int = 0                 # 0 = all (host-side thread pools)
    # mesh shape: data axis size; 0 = all visible devices
    data_axis: int = 0
    # optional second axis for model-parallel Gram/GLM (SURVEY §2.4 item 6)
    model_axis: int = 1
    backend: Optional[str] = None     # None = jax default; 'cpu' forces host
    # chunked-compute block size (rows per scan step in map/reduce kernels);
    # analogue of the reference's chunk target (water/fvec/Vec.java chunk
    # sizing), chosen for MXU tiling: multiple of 8*128.
    block_rows: int = 32768
    # default number of histogram bins (reference nbins, hex/tree/DHistogram.java)
    nbins: int = 64
    ice_root: str = "/tmp/h2o3_tpu"   # spill/checkpoint dir (-ice_root)
    # -- fault tolerance (core/watchdog.py shared retry policy) --------
    # total attempts for infra-class errors (1 = no retry); the analogue
    # of the reference's sys.ai.h2o.* retry properties
    infra_max_attempts: int = 3
    infra_backoff_base_s: float = 0.5   # first retry delay (doubles)
    infra_backoff_max_s: float = 30.0   # backoff ceiling
    # backend liveness probe deadline; 0 = unbounded (probe_backend)
    probe_timeout_s: float = 60.0
    # -- in-fit checkpointing (core/recovery.py FitCheckpointer) -------
    # directory for periodic mid-fit snapshots (GBM tree chunks, GLM
    # lambda iterations, DL epoch boundaries); "" = off. Grid/AutoML
    # recovery_dir= overrides this per combo via fit_checkpoint_scope
    fit_checkpoint_dir: str = ""
    # snapshot cadence in algo-native units (GBM trees / DL steps /
    # GLM lambdas); 0 = per-algo default (GBM 25 trees, DL one epoch,
    # GLM every lambda)
    fit_checkpoint_every: int = 0
    # -- cloud formation + peer health (core/cloud.py, core/heartbeat.py)
    # coordinator-connect bound for jax.distributed.initialize AND the
    # post-init roll-call barrier; the analogue of the reference's
    # stall_till_cloudsize timeout (water/H2O.java waitForCloudSize)
    cloud_timeout_s: float = 120.0
    # seconds between heartbeat rounds (HeartBeatThread pings every
    # second in the reference, water/HeartBeatThread.java:16)
    heartbeat_interval_s: float = 1.0
    # consecutive missed rounds before the cloud is declared unhealthy
    # (Paxos ejects after HeartBeatThread.TIMEOUT misses)
    heartbeat_miss_budget: int = 3
    # per-round deadline for the agreement check; 0 = use the interval
    heartbeat_timeout_s: float = 5.0
    # peer-health monitor: "auto" (default) runs it for multi-process
    # clouds where a dead peer would hang every collective; "on" forces
    # it for single-process clouds too (rounds become tiny bounded
    # psums); "off" disables it entirely
    heartbeat: str = "auto"
    # -- request hardening (api/server.py admission gate + bounds) -----
    # max requests executing handlers concurrently; the analogue of the
    # reference's bounded Jetty thread pool (water/api/RequestServer)
    rest_max_inflight: int = 64
    # requests allowed to WAIT for a slot once saturated; anything past
    # inflight+queue fails fast with 503 + Retry-After
    rest_queue_depth: int = 16
    # longest a queued request waits for a slot before 503
    rest_queue_wait_s: float = 10.0
    # Content-Length cap for buffered bodies (MB); /3/PostFile streams
    # to disk in chunks and is exempt
    rest_max_body_mb: int = 256
    # -- observability (telemetry/flight_recorder.py + utils/log.py) ---
    # rotating per-process log file directory; "" = stream+ring only
    log_dir: str = ""
    # completed-job telemetry capsules retained in the DKV (newest
    # first); cancelled jobs' capsules are swept with their Scope
    flight_recorder_keep: int = 32
    # -- cluster telemetry fan-in (telemetry/cluster.py) ---------------
    # per-peer metric/trace/log snapshots over the coordination-service
    # KV store: "auto" (default) publishes on multi-process clouds only,
    # "on" forces, "off" disables — the ?cluster=1 views then degrade to
    # the local process
    cluster_metrics: str = "auto"
    # seconds between snapshot publishes (piggybacked on the heartbeat
    # beat cadence — a publish never outpaces the beat)
    cluster_metrics_interval_s: float = 5.0
    # a peer whose newest snapshot is older than this is reported in
    # stale_nodes (its last data still serves, labeled stale)
    cluster_metrics_stale_s: float = 15.0
    # -- roofline accounting (telemetry/roofline.py) -------------------
    # per-fit FLOP/byte accounting against device peaks: "auto" =
    # analytic estimates everywhere + Compiled.cost_analysis() totals on
    # TPU backends; "analytic" / "cost" force one path; "off" disables
    roofline: str = "auto"
    # -- model batching (parallel/model_batch.py) ----------------------
    # grid/AutoML combos sharing one compiled program train as a single
    # vmapped batch: "auto" (default) batches eligible buckets of >= 2
    # combos; "off"/"0" forces the sequential per-combo walk
    batch_models: str = "auto"
    # -- HBM memory governor (core/memgov.py) --------------------------
    # deterministic HBM budget in MB when the backend reports no
    # bytes_limit (CPU tests, plugins exporting no memory stats);
    # 0 = no explicit budget (the governor only observes)
    hbm_budget_mb: int = 0
    # bounded wait for concurrent fits' reservations to release before
    # a pre-dispatch admission rejection (the AdmissionGate contract
    # applied to bytes instead of request slots)
    memgov_wait_s: float = 5.0
    # "auto" (default) = enforce admission whenever a budget source
    # exists; "off" = observe only, never reject
    memgov: str = "auto"
    # -- chunk-parallel ingest (io/chunking.py + io/stream.py) ---------
    # tokenizer workers for the chunk-parallel parse pipeline: 0 = one
    # per host core (the reference's MultiFileParseTask fans chunks to
    # the local FJ pool), 1 = the exact sequential fallback path
    parse_workers: int = 0
    # byte-window size fed to each tokenizer worker, in MB (the FileVec
    # chunk-size analogue for the parse plane)
    parse_chunk_mb: int = 64
    # -- low-latency scoring tier (serving/, README §Serving) ----------
    # row cap for one coalesced predict dispatch; also the ceiling of
    # the power-of-two row buckets the compiled scorer cache keys on
    score_batch_max_rows: int = 4096
    # how long the per-model dispatcher waits to coalesce concurrent
    # predict requests into one padded device dispatch
    score_batch_wait_ms: float = 2.0
    # bounded per-model predict queue; a full queue answers 503 +
    # Retry-After (the AdmissionGate overload contract on the scoring
    # queue)
    score_batch_queue_depth: int = 256
    # -- cluster work scheduler (parallel/scheduler.py) ----------------
    # fan independent fits (grid combos, AutoML steps, CV folds) across
    # cloud processes over the coordination-service KV: "auto" (default)
    # schedules on multi-process clouds only, "on" forces the code path
    # (single process = everything leases to process 0), "off" keeps
    # every fit on the coordinator
    scheduler: str = "auto"
    # seconds between KV polls in the worker lease loop and the
    # coordinator's completion wait (cheap control-plane reads)
    scheduler_poll_s: float = 0.2
    # a leased item whose owner's heartbeat goes stale past
    # interval * miss_budget is reassigned after this extra grace
    scheduler_reassign_grace_s: float = 0.0
    # hard wall on one scheduled run's completion wait; 0 = no deadline
    # (budget enforcement lives in grid/AutoML, not the scheduler)
    scheduler_timeout_s: float = 0.0
    # -- pod-global sharded training (parallel/mesh.py, frame/frame.py)
    # host-partitioned frame placement for data-parallel fits across the
    # whole pod: "auto"/"on" let partitioned ingest home each process's
    # row shards locally (ONE fit spans every host); "off" devolves
    # partitioned ingest to the legacy fully-replicated layout. The
    # single-process path is bit-identical in every mode.
    global_fit: str = "auto"
    # -- training-step profiler (telemetry/stepprof.py) ----------------
    # per-chunk phase timing (host/compute/collective/checkpoint) woven
    # through every fit: "auto"/"on" profile every fit (registry op +
    # one device sync per chunk — <2% on bench chunks), "off" disables
    # the weave entirely
    stepprof: str = "auto"
    # bounded per-fit ring of chunk records kept for /profile + capsule
    stepprof_ring: int = 128
    # -- performance kernels (ops/pallas/) -----------------------------
    # fused Pallas tree kernels (histogram+split+partition per level):
    # "auto" = Pallas on TPU backends, XLA elsewhere; "off" = always the
    # XLA path; "interpret" = force the kernels through the Pallas
    # interpreter (CPU parity testing). The XLA path remains the
    # always-available fallback (ops/pallas.decide)
    pallas: str = "auto"

    # fields that parse as int from the environment (annotations are
    # strings under `from __future__ import annotations`, so resolve
    # by hand)
    _INT_FIELDS = frozenset({"port", "nthreads", "data_axis", "model_axis",
                             "block_rows", "nbins", "infra_max_attempts",
                             "rest_max_inflight", "rest_queue_depth",
                             "rest_max_body_mb", "flight_recorder_keep",
                             "heartbeat_miss_budget",
                             "fit_checkpoint_every", "hbm_budget_mb",
                             "parse_workers", "parse_chunk_mb",
                             "score_batch_max_rows",
                             "score_batch_queue_depth",
                             "stepprof_ring"})
    _FLOAT_FIELDS = frozenset({"infra_backoff_base_s", "infra_backoff_max_s",
                               "probe_timeout_s", "rest_queue_wait_s",
                               "cloud_timeout_s", "heartbeat_interval_s",
                               "heartbeat_timeout_s",
                               "cluster_metrics_interval_s",
                               "cluster_metrics_stale_s",
                               "memgov_wait_s", "score_batch_wait_ms",
                               "scheduler_poll_s",
                               "scheduler_reassign_grace_s",
                               "scheduler_timeout_s"})

    @staticmethod
    def from_env(**overrides) -> "Config":
        cfg = Config()
        for f in dataclasses.fields(Config):
            env = os.environ.get("H2O3TPU_" + f.name.upper())
            if env is not None:
                if f.name in Config._INT_FIELDS:
                    val = int(env)
                elif f.name in Config._FLOAT_FIELDS:
                    val = float(env)
                else:
                    val = env
                setattr(cfg, f.name, val)
        for k, v in overrides.items():
            if v is not None and hasattr(cfg, k):
                setattr(cfg, k, v)
        return cfg


# process-wide config singleton (reference: static H2O.ARGS)
ARGS = Config()
