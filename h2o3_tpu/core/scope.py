"""Scope — per-call key lifetime tracking (water/Scope.java:22).

The reference brackets work units with Scope.enter()/exit(): every key
created inside the scope is tracked and deleted on exit unless
explicitly untracked (kept). Here the same contract as a context
manager; the DKV reports new keys via a put-listener so tracking is
automatic, like the reference's Scope.track hooks inside Vec/Frame
constructors.

    with Scope() as s:
        fr = Frame.from_numpy(...)     # auto-tracked
        model = est.train(fr, y=...)   # auto-tracked
        s.keep(model.key)              # survives the scope
    # fr is gone from the DKV, model remains
"""

from __future__ import annotations

import threading
from typing import List, Set

from h2o3_tpu.core.kv import DKV

_local = threading.local()


def _stack() -> List["Scope"]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def track(key: str) -> None:
    """Called by DKV.put for every new key (Scope.track role)."""
    st = _stack()
    if st:
        st[-1]._tracked.add(key)


class Scope:
    def __init__(self):
        self._tracked: Set[str] = set()
        self._kept: Set[str] = set()

    def keep(self, *keys: str) -> None:
        """Exclude keys from cleanup (Scope.untrack)."""
        self._kept.update(keys)

    def __enter__(self) -> "Scope":
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> bool:
        _stack().pop()
        for k in self._tracked - self._kept:
            DKV.remove(k)
        # keys kept in a nested scope still belong to the outer scope
        st = _stack()
        if st:
            st[-1]._tracked.update(self._kept)
        return False
