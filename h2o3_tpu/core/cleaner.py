"""Cleaner — LRU spill of cold frames from HBM to the ice directory.

Reference: water/Cleaner.java:12 (spill logic lines 85-162): a background
thread watches heap pressure and swaps the least-recently-used Values to
disk ("ice"); DKV.get transparently reloads them.

TPU-land redesign: the scarce resource is device HBM, and the only large
DKV residents are Frames (models hold comparatively small forests /
coefficient blocks). The Cleaner ranks frames by last DKV access, spills
the coldest to ``hex://spill/`` (the node ice dir, io/persist.py) as
compressed npz, and swaps a `SpilledFrame` stub into the DKV; `DKV.get`
restores stubs on touch. Pressure is read from the accelerator's own
`memory_stats()` (bytes_in_use / bytes_limit) when the backend exposes
it, else from the sum of tracked frame nbytes against a configured
budget.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.cleaner")


class SpilledFrame:
    """DKV stub for a frame currently living on ice (Value swapped to
    disk, water/Value.java isPersisted role)."""

    _is_lazy_stub = True

    def __init__(self, key: str, uri: str, nrows: int, names: List[str],
                 nbytes: int):
        self.key = key
        self.uri = uri
        self.nrows = nrows
        self.names = names
        self.nbytes = nbytes

        self._on_ice = False    # set True once memgov counts the spill

    def restore(self):
        from h2o3_tpu.io.persist import load_frame
        fr = load_frame(self.uri, key=self.key)
        log.info("restored %s from %s", self.key, self.uri)
        return fr

    def discard(self) -> None:
        """Best-effort removal of the ice file (restore won / key
        removed / stub clobbered by a newer put) so spills don't
        accumulate on disk. Idempotent: the governor's bytes-on-ice
        accounting is settled exactly once per stub."""
        from h2o3_tpu.io.persist import persist_manager
        if self._on_ice:
            self._on_ice = False
            from h2o3_tpu.core.memgov import governor
            governor.note_unspill(self.nbytes)
        try:
            persist_manager.delete(self.uri)
        except Exception:
            pass

    def __repr__(self):
        return f"<SpilledFrame {self.key} @ {self.uri}>"


def _frame_nbytes(fr) -> int:
    total = 0
    for n in fr.names:
        c = fr.col(n)
        if c.data is not None:
            total += c.data.nbytes + (c.na_mask.nbytes
                                      if c.na_mask is not None else 0)
    return total


def device_memory_stats() -> Optional[dict]:
    """bytes_in_use / bytes_limit of device 0, when the backend reports
    them (TPU runtimes do; CPU returns None)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats or "bytes_in_use" not in stats:
        return None
    return {"bytes_in_use": int(stats["bytes_in_use"]),
            "bytes_limit": int(stats.get("bytes_limit", 0))}


class Cleaner:
    """LRU frame spiller (the Cleaner thread, water/Cleaner.java)."""

    def __init__(self, threshold: float = 0.85,
                 ice_prefix: str = "hex://spill"):
        self.threshold = threshold
        self.ice_prefix = ice_prefix
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._gen = itertools.count()   # per-spill uri generation
        self.spilled_count = 0
        self.restored_count = 0

    # -- policy --------------------------------------------------------
    def pressure(self) -> float:
        """Fraction of the HBM budget in use, from the governor's
        single budget truth (core/memgov.py): device stats when the
        backend reports them, the H2O3TPU_HBM_BUDGET_MB knob against
        tracked frame/cache bytes otherwise; 0 when ungoverned."""
        from h2o3_tpu.core.memgov import governor
        return governor.pressure()

    def _lru_frames(self):
        """(atime, key) for every in-memory DKV frame, coldest first.

        Deliberately does NOT keep a reference to the Frame: holding one
        across the spill loop would pin every frame's device buffers for
        the whole scan, so pressure() could never drop mid-loop and one
        step would spill the entire DKV (hot frames included)."""
        from h2o3_tpu.core.kv import DKV
        from h2o3_tpu.frame.frame import Frame
        out = []
        for key in list(DKV.keys()):
            v = DKV.get_raw(key)
            if isinstance(v, Frame):
                out.append((DKV.atime(key), key))
            del v
        out.sort(key=lambda t: t[0])
        return out

    # -- mechanics -----------------------------------------------------
    def spill(self, key: str) -> Optional[SpilledFrame]:
        """Swap one frame to ice and stub it in the DKV.

        Returns None if the key changed or vanished while the frame was
        being written to ice (the stub must never clobber a newer put —
        compare-and-swap like the reference's home-node arbitration)."""
        from h2o3_tpu.core.kv import DKV
        from h2o3_tpu.io.persist import persist_manager, save_frame
        fr = DKV.get_raw(key)
        if fr is None or getattr(fr, "_is_lazy_stub", False):
            return fr
        # frames parsed from a file and never mutated evict straight
        # back to a FileBackedFrame stub — the source IS the ice copy
        # (water/fvec/FileVec.java role), no npz write needed
        src = getattr(fr, "_source_paths", None)
        if src:
            from h2o3_tpu.io.lazy import FileBackedFrame
            stub = FileBackedFrame(key, src[0], src, list(fr.names),
                                   fr.nrows, _frame_nbytes(fr),
                                   getattr(fr, "_source_kwargs", None))
            if not DKV.replace_if(key, fr, stub):
                return None
            self.spilled_count += 1
            from h2o3_tpu import telemetry
            telemetry.counter("frame_spills_total").inc()
            log.info("evicted %s back to source %s", key, src[0])
            return stub
        from urllib.parse import quote
        # keys come from user-supplied destination_frame strings: encode
        # so '..'/'/' cannot escape the ice directory. The uri carries a
        # monotonic generation so every SpilledFrame owns its file
        # exclusively: a reader's post-restore discard of an OLD stub
        # must never unlink the ice a newer stub of the same key points
        # at (that interleaving both tore concurrent restores and lost
        # the only surviving copy of the frame)
        uri = (f"{self.ice_prefix}/{quote(key, safe='')}"
               f".g{next(self._gen)}.npz")
        save_frame(fr, uri)
        stub = SpilledFrame(key, uri, fr.nrows, list(fr.names),
                            _frame_nbytes(fr))
        if not DKV.replace_if(key, fr, stub):
            # concurrent put/remove won — discard the stale spill file
            try:
                persist_manager.delete(uri)
            except Exception:
                pass
            return None
        self.spilled_count += 1
        stub._on_ice = True
        from h2o3_tpu import telemetry
        from h2o3_tpu.core.memgov import governor
        telemetry.counter("frame_spills_total").inc()
        governor.note_spill(stub.nbytes)
        log.info("spilled %s (%.1f MB) to %s", key,
                 stub.nbytes / 1e6, uri)
        return stub

    def spill_coldest(self, n: int = 1, exclude: Optional[set] = None
                      ) -> List[str]:
        """Spill the n least-recently-used frames; returns spilled keys."""
        exclude = exclude or set()
        done: List[str] = []
        for _, key in self._lru_frames():
            if key in exclude:
                continue
            if self.spill(key) is not None:
                done.append(key)
            if len(done) >= n:
                break
        return done

    def step(self) -> List[str]:
        """One pressure check: spill coldest frames while above the
        threshold (Cleaner.java main loop body). One LRU scan per step —
        stubs drop out of _lru_frames on the next scan anyway."""
        spilled: List[str] = []
        if self.pressure() <= self.threshold:
            return spilled
        for _, key in self._lru_frames():
            if self.spill(key) is not None:
                spilled.append(key)
            if self.pressure() <= self.threshold:
                break
        return spilled

    # -- thread --------------------------------------------------------
    def start(self, interval: float = 5.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.step()
                except Exception as e:      # never kill the process
                    log.warning("cleaner step failed: %s", e)

        self._thread = threading.Thread(target=loop, name="Cleaner",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def status(self) -> dict:
        from h2o3_tpu.core.memgov import governor
        stats = device_memory_stats() or {}
        return {"pressure": self.pressure(),
                "threshold": self.threshold,
                "spilled": self.spilled_count,
                "restored": self.restored_count,
                **stats,
                "governor": governor.snapshot()}


cleaner = Cleaner()
