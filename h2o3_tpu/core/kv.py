"""DKV — the distributed key/value store, shrunk to what a TPU mesh needs.

Reference: water/DKV.java, water/Key.java:44, water/Value.java:39. The
reference implements a MESI-like cached K/V with home-node arbitration
because every JVM owns a slice of the heap. On a TPU mesh the data plane
(jax.Arrays) already lives sharded in HBM and is addressed by Python
references; what remains of the DKV is a process-local metadata/object
store on the controller holding Frames, Models, Jobs, Grids — exactly the
objects the reference keeps globally addressable for its REST layer.

Multi-host note: under ``jax.distributed`` every host runs the same
program, so a plain dict per process is coherent by SPMD construction —
the reference's invalidate/ack machinery (water/RPC.java:17-46) has no
equivalent work to do.
"""

from __future__ import annotations

import os
import threading
import itertools
from typing import Any, Dict, Iterator, Optional

_counter = itertools.count()


def make_key(prefix: str) -> str:
    """Unique key (reference Key.make, water/Key.java:44)."""
    return f"{prefix}_{next(_counter):04d}"


class _DKV:
    def __init__(self) -> None:
        self._store: Dict[str, Any] = {}
        self._atime: Dict[str, float] = {}
        self._lock = threading.RLock()

    def put(self, key: str, value: Any) -> str:
        import time
        with self._lock:
            old = self._store.get(key)
            new = key not in self._store
            self._store[key] = value
            self._atime[key] = time.monotonic()
        if old is not None and old is not value \
                and getattr(old, "_is_lazy_stub", False):
            # a newer put clobbered a stub still on ice: reclaim the
            # orphaned spill file (and its bytes-on-ice accounting)
            # instead of leaking it until process exit
            old.discard()
        if new:
            # per-call lifetime tracking (water/Scope.track role)
            from h2o3_tpu.core.scope import track
            track(key)
        return key

    def get(self, key: str) -> Optional[Any]:
        import time
        with self._lock:
            v = self._store.get(key)
            if v is not None:
                self._atime[key] = time.monotonic()
        # transparent un-spill (Value swap-in, water/Value.java role);
        # outside the lock: restore does file IO + device_put. Lazy
        # stubs (SpilledFrame on ice, FileBackedFrame on its source
        # file) share the restore/discard duck type.
        from h2o3_tpu.core.cleaner import cleaner
        while v is not None and getattr(v, "_is_lazy_stub", False):
            try:
                fr = v.restore()
            except Exception:
                # a concurrent restore/put may have won and reclaimed
                # the ice file mid-read — only propagate when the store
                # still holds THIS stub (the ice is genuinely bad)
                with self._lock:
                    cur = self._store.get(key)
                if cur is v:
                    raise
                v = cur
                continue
            cleaner.restored_count += 1
            from h2o3_tpu import telemetry
            telemetry.counter("frame_restores_total").inc()
            with self._lock:
                # restore() paths end in Frame.__init__, which re-puts
                # the key itself — so the store already holds `fr` (the
                # common case), or a concurrent writer's newer value
                cur = self._store.get(key)
                if cur is v:
                    self._store[key] = fr
                    cur = fr
            if cur is fr:
                v.discard()     # our restore won: reclaim the ice file
                return fr
            v = cur             # retry until we hold a live value
        if v is None and \
                os.environ.get("H2O3TPU_DATA_DURABILITY", "off") != "off":
            # a key proven unrecoverable (peer died, no mirror or
            # replayable lineage) fails typed here — the data-access
            # chokepoint — instead of surfacing as a hang or a late
            # AttributeError somewhere in a fit
            from h2o3_tpu.core import durability
            durability.check_lost(key)
        return v

    def get_raw(self, key: str) -> Optional[Any]:
        """Fetch without un-spilling or touching the access clock
        (Cleaner internals only)."""
        with self._lock:
            return self._store.get(key)

    def replace_if(self, key: str, expect: Any, value: Any) -> bool:
        """Compare-and-swap: store value only if the key still holds
        ``expect`` (water/Atomic home-node CAS role)."""
        with self._lock:
            if self._store.get(key) is not expect:
                return False
            self._store[key] = value
            return True

    def atime(self, key: str) -> float:
        with self._lock:
            return self._atime.get(key, 0.0)

    def remove(self, key: str) -> None:
        with self._lock:
            v = self._store.pop(key, None)
            self._atime.pop(key, None)
        if v is not None and getattr(v, "_is_lazy_stub", False):
            v.discard()     # drop the orphaned ice file with the key
        # durability write-through (ISSUE 18): a deliberately removed
        # frame takes its mirror blob + registry row with it — and a
        # key with NO value may still carry a LOST verdict to retire,
        # so the hook runs even for absent keys. One env read when the
        # knob is off — the zero-overhead contract.
        if os.environ.get("H2O3TPU_DATA_DURABILITY", "off") != "off":
            from h2o3_tpu.core import durability
            durability.on_remove(key, v)

    def keys(self, prefix: str = "") -> Iterator[str]:
        with self._lock:
            return iter([k for k in self._store if k.startswith(prefix)])

    def clear(self) -> None:
        """Test helper — analogue of water/runner/CleanAllKeysTask.java."""
        with self._lock:
            self._store.clear()
            self._atime.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store


DKV = _DKV()
