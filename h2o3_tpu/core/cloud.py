"""Cloud bootstrap — process/mesh startup, the ``h2o.init()`` analogue.

Reference call stack (SURVEY §3.1): h2o.init (h2o-py/h2o/h2o.py:138) →
water.H2O.main (water/H2O.java:2328) → NetworkInit → Paxos heartbeat
consensus (water/Paxos.java:40) → CLOUD committed. TPU-native: membership
is either a single process over local devices or ``jax.distributed``
across hosts (its coordinator barrier replaces the heartbeat quorum); the
"cloud" object is a ``jax.sharding.Mesh``. Cloud shape locks at first use
just like Paxos._cloudLocked (water/Paxos.java:32) because the mesh is
baked into compiled programs.

Hardening (ISSUE 7): ``jax.distributed.initialize`` runs under the
shared watchdog RetryPolicy with a bounded coordinator-connect timeout
(``H2O3TPU_CLOUD_TIMEOUT_S``); a post-init roll call over the
coordination-service KV store names the process ids that went missing
when formation is partial; ``core/heartbeat.py`` watches peer health for
the life of the cloud; ``shutdown()`` tears all of it down — heartbeat,
cleaner, mesh, distributed client — so a later ``init()`` reforms
cleanly instead of attaching to stale state.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax

from h2o3_tpu.core import config as _config
from h2o3_tpu.core import heartbeat as heartbeat_mod
from h2o3_tpu.core import watchdog
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.utils.log import get_logger
from h2o3_tpu.version import __version__

log = get_logger("h2o3_tpu.cloud")

_STARTED = False
_CLOUD_START_MS = 0        # wall-clock ms at init() (cloud_uptime_ms base)
_DISTRIBUTED = False       # this process ran jax.distributed.initialize

BOOT_KV_PREFIX = "h2o3tpu/boot/"


def _cloud_timeout_s(cfg) -> float:
    return float(os.environ.get("H2O3TPU_CLOUD_TIMEOUT_S",
                                cfg.cloud_timeout_s))


def _distributed_init(coordinator_address: str, num_processes: int,
                      process_id: int, timeout_s: float) -> None:
    """One jax.distributed.initialize attempt, retryable: a failed
    attempt tears the half-open client down so the next one starts
    clean (initialize raises on double-init)."""
    global _DISTRIBUTED
    watchdog.maybe_fail("cloud_init")
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=max(int(timeout_s), 1))
        _DISTRIBUTED = True
    except Exception as e:
        log.warning("cloud formation attempt failed (coordinator=%s "
                    "process %s/%s): %s", coordinator_address, process_id,
                    num_processes, e)
        try:
            jax.distributed.shutdown()
        except Exception:   # noqa: BLE001 - nothing half-open to close
            pass
        raise


def _roll_call(num_processes: int, process_id: int,
               timeout_s: float) -> None:
    """Post-init agreement: every process publishes its id and waits at
    a barrier. When a peer dies between connect and first use, THIS is
    where the hole gets a name — the diagnostic lists exactly which
    process ids never reported, instead of the first collective
    hanging."""
    from jax._src import distributed
    client = distributed.global_state.client
    if client is None:      # single-process init path
        return
    client.key_value_set(f"{BOOT_KV_PREFIX}{process_id}",
                         f"{os.uname().nodename}:{os.getpid()}",
                         allow_overwrite=True)
    try:
        client.wait_at_barrier("h2o3tpu_boot_rollcall",
                               max(int(timeout_s * 1000), 1000))
    except Exception as e:
        seen = set()
        try:
            for key, _val in client.key_value_dir_get(BOOT_KV_PREFIX):
                seen.add(int(key.rsplit("/", 1)[-1]))
        except Exception:   # noqa: BLE001 - diagnostics are best-effort
            pass
        missing = sorted(set(range(num_processes)) - seen)
        raise RuntimeError(
            f"UNAVAILABLE: partial cloud formation — expected "
            f"{num_processes} processes, missing ids {missing or '?'} "
            f"after {timeout_s:.0f}s roll call ({e})") from e


def init(backend: Optional[str] = None,
         data_axis: Optional[int] = None,
         model_axis: Optional[int] = None,
         coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         restore_dir: Optional[str] = None,
         **kwargs) -> dict:
    """Start (or attach to) the cloud. Analogue of h2o.init (h2o.py:49,138).

    Single-host: builds the mesh over local devices. Multi-host: pass
    ``coordinator_address``/``num_processes``/``process_id`` and every host
    calls this with the same arguments — ``jax.distributed.initialize`` is
    the clouding protocol (replaces multicast/flatfile discovery,
    water/init/NetworkInit.java:62-174), retried under the shared
    watchdog policy and bounded by ``H2O3TPU_CLOUD_TIMEOUT_S``.

    ``restore_dir`` reforms the cloud's DKV from a ``cloud_checkpoint``
    directory (POST /3/CloudCheckpoint) — frames land bit-identically
    (digest-verified) and models re-register (core/durability.py, the
    rolling-restart / disaster-recovery path).
    """
    global _STARTED, _CLOUD_START_MS
    if (_STARTED and backend is None and coordinator_address is None
            and data_axis is None and model_axis is None
            and num_processes is None and process_id is None
            and restore_dir is None and not kwargs):
        # cloud already formed and no explicit backend/mesh re-shape
        # requested: attach, don't reform (h2o.init attaches to a
        # running cluster; silently re-detecting devices here could
        # swap the session's mesh to a different backend mid-flight)
        return cluster_info()
    cfg = _config.Config.from_env(backend=backend, data_axis=data_axis,
                                  model_axis=model_axis, **kwargs)
    _config.ARGS = cfg

    # rebuild the logging pipeline: level/dir/format knobs set between
    # import and init() (H2O3TPU_LOG_*, init(log_level=...)) must take
    # effect — utils/log.py configure() is idempotent
    from h2o3_tpu.utils import log as _log
    _log.configure(level=cfg.log_level,
                   log_dir=cfg.log_dir or None)

    # persistent XLA compilation cache: repeated sessions (tests, bench,
    # conformance servers) skip recompiling identical programs — this
    # both cuts cold-start time and shrinks the exposure to the CPU
    # backend's flaky-compile crashes observed in long processes
    try:
        cache_dir = os.environ.get("H2O3TPU_XLA_CACHE",
                                   "/tmp/h2o3tpu_xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:           # noqa: BLE001 — cache is optional
        log.warning("persistent XLA cache unavailable: %s", e)

    if coordinator_address is not None and not _STARTED:
        timeout_s = _cloud_timeout_s(cfg)
        # the CPU backend only speaks cross-process collectives through
        # gloo; the flag must be set BEFORE the first backend client is
        # created or the psum tree dies with "Multiprocess computations
        # aren't implemented on the CPU backend" — the standing
        # multiprocess-CPU failure this PR retires
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception as e:       # noqa: BLE001 — TPU-only jaxes
            log.warning("cpu collectives unavailable (multi-process CPU "
                        "meshes will not form): %s", e)
        watchdog.retry_call(
            lambda: _distributed_init(coordinator_address,
                                      int(num_processes),
                                      int(process_id), timeout_s),
            site="cloud_init")
        _roll_call(int(num_processes), int(process_id), timeout_s)
        # reformed-cloud hygiene for the work scheduler: swept at INIT,
        # where the roll-call barrier proves no process is mid-run —
        # never at shutdown, where processes arrive at different times
        # and a sweep wedges a peer still reading its last run
        if int(process_id) == 0:
            from h2o3_tpu.parallel import scheduler as _scheduler_mod
            _scheduler_mod.sweep_keys()
            # same reasoning for the serving-fleet registry: stale
            # replica/endpoint entries and published binaries from the
            # previous incarnation are swept once the roll-call barrier
            # proves nobody is still routing against them
            from h2o3_tpu.serving import fleet as _fleet_mod
            _fleet_mod.sweep_keys()
            # and the durability registry/blob subtree: a reformed
            # cloud must never rebuild the previous incarnation's
            # frames from its ghost registry entries
            from h2o3_tpu.core import durability as _durability_mod
            _durability_mod.sweep_keys()
        # stamp this process's cloud identity on every log record and
        # flight-recorder capsule (utils/log.py ContextFilter) so merged
        # cluster views stay attributable — set here, NOT read from
        # jax.process_index() inside the logging hot path
        _log.set_node(int(process_id))

    devices = jax.devices(cfg.backend) if cfg.backend else jax.devices()
    m = mesh_mod.make_mesh(devices, cfg.data_axis, cfg.model_axis)
    mesh_mod.set_global_mesh(m)
    _STARTED = True
    _CLOUD_START_MS = int(time.time() * 1000)
    # peer health: always for multi-process clouds (a dead peer hangs
    # every collective — someone must notice), opt-in for single-process
    hb = (cfg.heartbeat or "auto").lower()
    if hb == "on" or (hb == "auto" and jax.process_count() > 1):
        heartbeat_mod.monitor.start()
    info = cluster_info()
    log.info("cloud up: %s", info)
    # Cleaner thread (water/Cleaner.java): opt-in — spilling mid-test
    # would make timings nondeterministic, so default off like the
    # reference's -cleaner flag family
    if os.environ.get("H2O3_TPU_SPILL") == "1":
        from h2o3_tpu.core.cleaner import cleaner
        cleaner.start()
        log.info("cleaner started (threshold %.0f%%)",
                 cleaner.threshold * 100)
    if restore_dir:
        from h2o3_tpu.core import durability as _durability_mod
        restored = _durability_mod.cloud_restore(restore_dir)
        info["restored"] = restored
    return info


def cluster_info() -> dict:
    """GET /3/Cloud shape (water/api/CloudHandler.java)."""
    m = mesh_mod.get_mesh()
    devs = list(m.devices.flat)
    hb = heartbeat_mod.monitor.status()
    now_ms = int(time.time() * 1000)
    return {
        "version": __version__,
        "cloud_name": _config.ARGS.name,
        "cloud_size": len(devs),
        # hardcoded True until ISSUE 7: now the heartbeat monitor's
        # verdict (trivially healthy when the monitor is off)
        "cloud_healthy": hb["healthy"],
        "mesh_shape": dict(m.shape),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "devices": [str(d) for d in devs],
        "platform": devs[0].platform if devs else "none",
        "build_age_sec": 0,
        "cloud_uptime_ms": (now_ms - _CLOUD_START_MS
                            if _STARTED and _CLOUD_START_MS else 0),
        "heartbeat": hb,
        # cluster work scheduler (parallel/scheduler.py): this host's
        # lease/throughput view; GET /3/Cloud?cluster=1 merges peers'
        "scheduler": _scheduler_snapshot(),
    }


def _scheduler_snapshot() -> dict:
    from h2o3_tpu.parallel import scheduler
    return scheduler.snapshot()


def _sweep_coordination_keys() -> None:
    """Delete THIS process's heartbeat/bootstrap/telemetry entries from
    the coordination-service KV store. Runs during shutdown, before the
    distributed client disconnects: a reformed cloud (shutdown → init)
    must never read the previous incarnation's ghost beats or stale
    metric snapshots."""
    try:
        from jax._src import distributed
        client = distributed.global_state.client
    except Exception:       # noqa: BLE001 - no distributed runtime
        return
    if client is None:
        return
    pidx = heartbeat_mod.monitor._pid
    try:
        pidx = jax.process_index()
    except Exception:       # noqa: BLE001 - keep the monitor's capture
        pass
    from h2o3_tpu.telemetry import cluster
    for prefix in (heartbeat_mod.KV_PREFIX, BOOT_KV_PREFIX,
                   cluster.KV_PREFIX):
        try:
            client.key_value_delete(f"{prefix}{pidx}")
        except Exception:   # noqa: BLE001 - absent key / service down
            pass
    try:
        # fleet endpoint + replica rows are per-process too; published
        # model binaries stay (a lagging peer may still be installing
        # one) and are garbage-collected by the init-time prefix sweep
        from h2o3_tpu.serving import fleet as _fleet_mod
        _fleet_mod.sweep_local_keys(client, pidx)
    except Exception:       # noqa: BLE001 - fleet tier is optional
        pass
    try:
        # partitioned-ingest metadata this process published (codec
        # facts, off-mode gather blobs) — per-exchange keys are dead
        # the moment the frame exists, but a reformed cloud reuses
        # exchange counters from zero and must never read ghosts
        from h2o3_tpu.frame import partition as _partition_mod
        _partition_mod.sweep_local_keys(client)
    except Exception:       # noqa: BLE001 - ingest tier is optional
        pass
    try:
        # durability registry rows + mirror blobs this process homes:
        # a clean shutdown is not a peer death — survivors must not
        # "rebuild" frames the operator deliberately took down
        from h2o3_tpu.core import durability as _durability_mod
        _durability_mod.sweep_local_keys(client, pidx)
    except Exception:       # noqa: BLE001 - durability tier is optional
        pass
    # scheduler run subtrees are NOT swept here: processes reach
    # shutdown at different times, and deleting h2o3tpu/sched/ while a
    # lagging peer still polls its last run's done manifest wedges that
    # peer forever. Old runs are garbage-collected run-over-run instead
    # (scheduler.run deletes the run-before-last, which every process
    # has provably finished installing), and the subtree dies with the
    # coordination service itself.


def shutdown() -> None:
    """Drop all state (reference: POST /3/Shutdown).

    Tears down everything ``init()`` built — heartbeat and cleaner
    threads, this process's coordination-KV entries (beats, roll-call
    marker, telemetry snapshot), the DKV, the global mesh, and the
    jax.distributed client — so a subsequent ``init()`` reforms the
    cloud instead of attaching to stale state."""
    global _STARTED, _CLOUD_START_MS, _DISTRIBUTED
    try:
        # fleet drain FIRST, while the heartbeat still marks us healthy:
        # deregister local replicas and pull the REST endpoint so peers
        # stop routing predictions here before anything else tears down
        from h2o3_tpu.serving import fleet as _fleet_mod
        _fleet_mod.drain()
    except Exception:       # noqa: BLE001 - fleet tier is optional
        pass
    heartbeat_mod.monitor.stop()
    try:
        from h2o3_tpu.core.cleaner import cleaner
        cleaner.stop()
    except Exception:       # noqa: BLE001 - cleaner is optional
        pass
    _sweep_coordination_keys()
    try:
        # orphaned FitCheckpointer tmp files / partial snapshot dirs
        # (a kill mid-write leaves *.tmp debris; completed .fitsnap
        # snapshots are resumable state and stay)
        from h2o3_tpu.core import recovery as _recovery
        _recovery.sweep_fit_checkpoints()
    except Exception:       # noqa: BLE001 - sweep is best-effort
        pass
    try:
        # clear this process's durability state (registry keys, mirror
        # blobs, framesnap.tmp debris) — the ISSUE 18 shutdown contract
        from h2o3_tpu.core import durability as _durability_mod
        _durability_mod.reset()
        _durability_mod.sweep_debris()
    except Exception:       # noqa: BLE001 - durability is optional
        pass
    try:
        # the admission ledger and bytes-on-ice accounting die with the
        # cloud: a reformed cloud must not inherit ghost reservations
        from h2o3_tpu.core.memgov import governor
        governor.reset()
    except Exception:       # noqa: BLE001 - governor is optional
        pass
    DKV.clear()
    mesh_mod.set_global_mesh(None)
    if _DISTRIBUTED:
        try:
            jax.distributed.shutdown()
        except Exception as e:   # noqa: BLE001 - already down is fine
            log.warning("jax.distributed shutdown: %s", e)
        _DISTRIBUTED = False
    _STARTED = False
    _CLOUD_START_MS = 0
