"""Cloud bootstrap — process/mesh startup, the ``h2o.init()`` analogue.

Reference call stack (SURVEY §3.1): h2o.init (h2o-py/h2o/h2o.py:138) →
water.H2O.main (water/H2O.java:2328) → NetworkInit → Paxos heartbeat
consensus (water/Paxos.java:40) → CLOUD committed. TPU-native: membership
is either a single process over local devices or ``jax.distributed``
across hosts (its coordinator barrier replaces the heartbeat quorum); the
"cloud" object is a ``jax.sharding.Mesh``. Cloud shape locks at first use
just like Paxos._cloudLocked (water/Paxos.java:32) because the mesh is
baked into compiled programs.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax

from h2o3_tpu.core import config as _config
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.parallel import mesh as mesh_mod
from h2o3_tpu.utils.log import get_logger
from h2o3_tpu.version import __version__

log = get_logger("h2o3_tpu.cloud")

_STARTED = False


def init(backend: Optional[str] = None,
         data_axis: Optional[int] = None,
         model_axis: Optional[int] = None,
         coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         **kwargs) -> dict:
    """Start (or attach to) the cloud. Analogue of h2o.init (h2o.py:49,138).

    Single-host: builds the mesh over local devices. Multi-host: pass
    ``coordinator_address``/``num_processes``/``process_id`` and every host
    calls this with the same arguments — ``jax.distributed.initialize`` is
    the clouding protocol (replaces multicast/flatfile discovery,
    water/init/NetworkInit.java:62-174).
    """
    global _STARTED
    if (_STARTED and backend is None and coordinator_address is None
            and data_axis is None and model_axis is None
            and num_processes is None and process_id is None
            and not kwargs):
        # cloud already formed and no explicit backend/mesh re-shape
        # requested: attach, don't reform (h2o.init attaches to a
        # running cluster; silently re-detecting devices here could
        # swap the session's mesh to a different backend mid-flight)
        return cluster_info()
    cfg = _config.Config.from_env(backend=backend, data_axis=data_axis,
                                  model_axis=model_axis, **kwargs)
    _config.ARGS = cfg

    # rebuild the logging pipeline: level/dir/format knobs set between
    # import and init() (H2O3TPU_LOG_*, init(log_level=...)) must take
    # effect — utils/log.py configure() is idempotent
    from h2o3_tpu.utils import log as _log
    _log.configure(level=cfg.log_level,
                   log_dir=cfg.log_dir or None)

    # persistent XLA compilation cache: repeated sessions (tests, bench,
    # conformance servers) skip recompiling identical programs — this
    # both cuts cold-start time and shrinks the exposure to the CPU
    # backend's flaky-compile crashes observed in long processes
    try:
        cache_dir = os.environ.get("H2O3TPU_XLA_CACHE",
                                   "/tmp/h2o3tpu_xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:           # noqa: BLE001 — cache is optional
        log.warning("persistent XLA cache unavailable: %s", e)

    if coordinator_address is not None and not _STARTED:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    devices = jax.devices(cfg.backend) if cfg.backend else jax.devices()
    m = mesh_mod.make_mesh(devices, cfg.data_axis, cfg.model_axis)
    mesh_mod.set_global_mesh(m)
    _STARTED = True
    info = cluster_info()
    log.info("cloud up: %s", info)
    # Cleaner thread (water/Cleaner.java): opt-in — spilling mid-test
    # would make timings nondeterministic, so default off like the
    # reference's -cleaner flag family
    if os.environ.get("H2O3_TPU_SPILL") == "1":
        from h2o3_tpu.core.cleaner import cleaner
        cleaner.start()
        log.info("cleaner started (threshold %.0f%%)",
                 cleaner.threshold * 100)
    return info


def cluster_info() -> dict:
    """GET /3/Cloud shape (water/api/CloudHandler.java)."""
    m = mesh_mod.get_mesh()
    devs = list(m.devices.flat)
    return {
        "version": __version__,
        "cloud_name": _config.ARGS.name,
        "cloud_size": len(devs),
        "cloud_healthy": True,
        "mesh_shape": dict(m.shape),
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "devices": [str(d) for d in devs],
        "platform": devs[0].platform if devs else "none",
        "build_age_sec": 0,
        "cloud_uptime_ms": int(time.time() * 1000),
    }


def shutdown() -> None:
    """Drop all state (reference: POST /3/Shutdown)."""
    global _STARTED
    DKV.clear()
    _STARTED = False
