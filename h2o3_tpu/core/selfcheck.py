"""Startup self-benchmarks — device capability probes.

Reference: water/init/{Linpack,MemoryBandwidth,NetworkBench}.java — at
boot every node measures GFLOPS, memory bandwidth, and network
throughput so cluster health pages can flag slow nodes. TPU-native
probes: MXU matmul GFLOPS (Linpack role), HBM read bandwidth
(MemoryBandwidth role), host↔device transfer (NetworkBench role — the
PCIe/tunnel link is the analogous bottleneck path), and a mesh psum
round-trip when more than one device is attached.
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np


def run_self_bench(sizes: Dict[str, int] | None = None) -> Dict[str, float]:
    import jax
    import jax.numpy as jnp

    sizes = sizes or {}
    M = int(sizes.get("matmul", 4096))
    V = int(sizes.get("membw", 64 * 1024 * 1024))   # elements (f32)
    T = int(sizes.get("transfer", 16 * 1024 * 1024))

    out: Dict[str, float] = {"device": str(jax.devices()[0]),
                             "backend": jax.default_backend()}

    # Linpack role: f32 and bf16 matmul GFLOPS
    for dtype, name in ((jnp.float32, "matmul_f32_gflops"),
                        (jnp.bfloat16, "matmul_bf16_gflops")):
        a = jnp.ones((M, M), dtype)
        b = jnp.ones((M, M), dtype)
        f = jax.jit(lambda x, y: (x @ y).sum())
        float(f(a, b))                    # compile + warm
        t0 = time.time()
        reps = 5
        for _ in range(reps):
            s = f(a, b)
        float(s)
        dt = (time.time() - t0) / reps
        out[name] = round(2 * M ** 3 / dt / 1e9, 1)

    # MemoryBandwidth role: big-vector reduce (reads V*4 bytes)
    v = jnp.ones((V,), jnp.float32)
    g = jax.jit(lambda x: x.sum())
    float(g(v))
    t0 = time.time()
    reps = 10
    for _ in range(reps):
        s = g(v)
    float(s)
    dt = (time.time() - t0) / reps
    out["hbm_read_gbps"] = round(V * 4 / dt / 1e9, 1)

    # NetworkBench role: host→device and device→host throughput
    host = np.ones((T,), np.float32)
    t0 = time.time()
    dev = jax.device_put(host)
    dev.block_until_ready()
    out["h2d_gbps"] = round(T * 4 / (time.time() - t0) / 1e9, 2)
    t0 = time.time()
    _ = np.asarray(dev)
    out["d2h_gbps"] = round(T * 4 / (time.time() - t0) / 1e9, 2)

    # mesh collective probe (reduce-tree role) when a mesh exists
    try:
        from h2o3_tpu.parallel.mesh import DATA_AXIS, get_mesh
        from jax.sharding import PartitionSpec as P
        mesh = get_mesh()
        if mesh.shape[DATA_AXIS] > 1:
            import functools
            from h2o3_tpu.parallel.mesh import shard_map

            @jax.jit
            @functools.partial(shard_map, mesh=mesh, in_specs=P(DATA_AXIS),
                               out_specs=P(), check_vma=False)
            def _ps(x):
                return jax.lax.psum(x, DATA_AXIS)

            x = jnp.ones((mesh.shape[DATA_AXIS] * 1024,), jnp.float32)
            float(_ps(x).sum())
            t0 = time.time()
            for _ in range(10):
                s = _ps(x)
            float(s.sum())
            out["psum_us"] = round((time.time() - t0) / 10 * 1e6, 1)
    except Exception:
        pass
    return out
