"""Backend watchdog — liveness probe, shared infra-retry policy, fault
injection.

Reference: the reference platform treats node death as a first-class
event (water/HeartBeatThread.java:1 pings every node each second and
ejects corpses from the cloud; hex/faulttolerance/Recovery.java resumes
the work they dropped). The TPU analogue of a dead node is a wedged or
restarting worker process behind the tunnel: ``jax.devices()`` hangs or
every dispatch dies with INTERNAL/UNAVAILABLE. Round 5 lost the whole
bench scoreboard to exactly that — the first ``device_put`` hit a
corpse and every ad-hoc retry hit it again.

This module centralizes what used to be scattered one-shot retries
(core/job.py, bench.py):

- ``probe_backend()``    — cheap liveness check: ``jax.devices()`` plus a
  tiny ``device_put`` round-trip, optionally bounded by a thread-timeout
  (a hung transfer must not hang the prober).
- ``RetryPolicy``        — bounded exponential backoff with jitter;
  defaults come from ``core/config.py`` (``H2O3TPU_INFRA_*`` env knobs).
- ``retry_call()``       — run a callable under the policy, retrying only
  classified infra errors.
- ``is_infra_error()``   — the single classifier for retryable
  infra-class failures (moved here from core/job.py, which re-exports).
- ``bounded_call()``     — run a callable on a daemon thread with a hard
  deadline (the thread-timeout prober; a hung device transfer or
  collective must never hang the caller). Used by ``probe_backend`` and
  the cloud heartbeat (core/heartbeat.py).
- fault injection        — ``inject_fault()`` / ``H2O3TPU_FAULTS`` plant
  classified failures at named sites (``probe``, ``job``,
  ``frame_reduce``, ``frame_map``, ``heartbeat``, ``cloud_init``,
  ``fit_chunk`` — the GBM/GLM/DL training-loop host boundaries where
  the FitCheckpointer snapshots — and ``device_oom``, the same
  boundaries raising RESOURCE_EXHAUSTED so the OOM escalation ladder
  of core/job.py runs deterministically) so every retry/degradation
  path runs in tier-1 CPU tests instead of waiting for a real TPU
  crash.

Telemetry: ``backend_probes_total``, ``backend_probe_failures_total``,
``infra_retries_total{site=}`` (README §Fault tolerance).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from h2o3_tpu.core import config as _config
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.watchdog")

# transient infra failures of the tunneled chip / compile service —
# distinct from user errors and worth bounded retries. RESOURCE_EXHAUSTED
# is retryable because callers purge the jit executable cache first (see
# core/job.py free_device_memory): the cache pins HBM and the axon plugin
# reports no memory stats, so pressure shows up as this error. "Gloo" is
# the CPU cross-process collective transport: a peer dying mid-collective
# surfaces as FAILED_PRECONDITION "Gloo collective ... Connection closed
# by peer", which is cloud infrastructure, never user code.
INFRA_SIGNS = ("remote_compile", "INTERNAL:", "UNAVAILABLE:",
               "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED", "Gloo")

# exception types never worth a retry, regardless of message. Modules
# that define their own (e.g. core/job.py JobCancelledException) append
# to this at import so the classifier needs no circular import.
NON_RETRYABLE: List[type] = [ValueError, TypeError, KeyError]


def is_infra_error(e: BaseException) -> bool:
    """True for retryable infra-class errors (XlaRuntimeError INTERNAL /
    remote_compile / UNAVAILABLE), False for user/programming errors."""
    if isinstance(e, tuple(NON_RETRYABLE)):
        return False
    msg = f"{type(e).__name__}: {e}"
    return any(s in msg for s in INFRA_SIGNS)


# ------------------------------------------------------------ fault injection


class InjectedFault(Exception):
    """Planted by the fault-injection hooks; message carries an
    INFRA_SIGNS token so it classifies as retryable."""


_faults_lock = threading.Lock()
# site -> {"left": remaining failures, "sign": message token}
_faults: Dict[str, Dict[str, Any]] = {}
_fired: Dict[str, int] = {}       # site -> injected-failure count (tests)


def _state_path() -> Optional[str]:
    """Optional cross-process fault budget: when H2O3TPU_FAULT_STATE
    names a directory, consumed counts persist there so N injected
    failures span N fresh subprocesses (a per-process counter would
    reset with every child and the site could never recover)."""
    return os.environ.get("H2O3TPU_FAULT_STATE") or None


def _parse_env_faults() -> None:
    """H2O3TPU_FAULTS="site:count[:SIGN],site2:count" — planted once at
    first use; programmatic inject_fault() overrides."""
    spec = os.environ.get("H2O3TPU_FAULTS", "")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        site = bits[0]
        with _faults_lock:
            if site in _faults:
                continue
        count = int(bits[1]) if len(bits) > 1 and bits[1] else 1
        sign = bits[2] if len(bits) > 2 and bits[2] else None
        inject_fault(site, times=count, sign=sign)


_env_parsed = False


def inject_fault(site: str, times: int = 1,
                 sign: Optional[str] = None) -> None:
    """Plant `times` classified failures at a named site. ``sign``
    defaults per site: ``device_oom`` faults as RESOURCE_EXHAUSTED (so
    the job supervisor's OOM escalation ladder runs), everything else
    as UNAVAILABLE."""
    if sign is None:
        sign = "RESOURCE_EXHAUSTED" if site == "device_oom" \
            else "UNAVAILABLE"
    with _faults_lock:
        _faults[site] = {"left": int(times), "sign": sign}


def clear_faults() -> None:
    with _faults_lock:
        _faults.clear()
        _fired.clear()


def fired(site: str) -> int:
    """How many injected failures a site has raised (test assertion)."""
    with _faults_lock:
        return _fired.get(site, 0)


def _consume_shared(site: str, budget: int) -> bool:
    """Cross-process consumption: bump <state>/<site>.count under an
    exclusive lockfile; True while consumed < budget (i.e. still fail)."""
    d = _state_path()
    path = os.path.join(d, f"fault_{site}.count")
    os.makedirs(d, exist_ok=True)
    lock = path + ".lock"
    for _ in range(200):                      # ~2s worst case
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            break
        except FileExistsError:
            time.sleep(0.01)
    try:
        consumed = 0
        if os.path.exists(path):
            with open(path) as f:
                consumed = int(f.read().strip() or 0)
        if consumed >= budget:
            return False
        with open(path, "w") as f:
            f.write(str(consumed + 1))
        return True
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


def maybe_fail(site: str) -> None:
    """Injection hook — called at the top of every guarded site
    (probe / job / frame_reduce / frame_map). No-op unless a fault is
    planted there."""
    global _env_parsed
    if not _env_parsed:
        _env_parsed = True
        _parse_env_faults()
    with _faults_lock:
        f = _faults.get(site)
        if f is None or f["left"] <= 0:
            return
        shared = _state_path() is not None
        if not shared:
            f["left"] -= 1
        budget = int(f["left"])
        sign = f["sign"]
    if shared and not _consume_shared(site, budget):
        return
    with _faults_lock:
        _fired[site] = _fired.get(site, 0) + 1
    raise InjectedFault(f"{sign}: injected fault at site '{site}'")


# ------------------------------------------------------------- retry policy


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with jitter.

    ``max_attempts`` counts total tries (1 = no retry). Delay before
    retry k (k starting at 1) is ``base * 2**(k-1)`` clamped to ``max``,
    then multiplied by a uniform jitter in ``[1-jitter, 1+jitter]`` so a
    fleet of retriers cannot thundering-herd a recovering worker."""
    max_attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    jitter: float = 0.25
    sleep: Callable[[float], None] = time.sleep
    rng: random.Random = field(default_factory=random.Random)

    def delay(self, retry_index: int) -> float:
        d = min(self.base_delay_s * (2.0 ** max(retry_index - 1, 0)),
                self.max_delay_s)
        if self.jitter > 0:
            d *= self.rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)
        return max(d, 0.0)


def policy_from_config(**overrides) -> RetryPolicy:
    """The shared policy, from core/config.py. Reads config.ARGS at call
    time (init() rebinds the singleton), with H2O3TPU_INFRA_* env
    overrides applied on top so processes that never call init() — the
    bench parent, probe children — still honor the knobs."""
    args = _config.ARGS
    env = os.environ.get
    kw = dict(
        max_attempts=int(env("H2O3TPU_INFRA_MAX_ATTEMPTS",
                             args.infra_max_attempts)),
        base_delay_s=float(env("H2O3TPU_INFRA_BACKOFF_BASE_S",
                               args.infra_backoff_base_s)),
        max_delay_s=float(env("H2O3TPU_INFRA_BACKOFF_MAX_S",
                              args.infra_backoff_max_s)))
    kw.update(overrides)
    return RetryPolicy(**kw)


def retry_call(fn: Callable[[], Any], policy: Optional[RetryPolicy] = None,
               site: str = "call",
               on_retry: Optional[Callable[[BaseException, int], None]] = None):
    """Run ``fn`` under the retry policy; only infra-class errors are
    retried, anything else propagates immediately."""
    from h2o3_tpu import telemetry
    policy = policy or policy_from_config()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if attempt >= policy.max_attempts or not is_infra_error(e):
                raise
            telemetry.counter("infra_retries_total", site=site).inc()
            d = policy.delay(attempt)
            log.warning("%s: infra error (attempt %d/%d), retrying in "
                        "%.1fs: %s", site, attempt, policy.max_attempts,
                        d, e)
            if on_retry is not None:
                on_retry(e, attempt)
            policy.sleep(d)


# ------------------------------------------------------------ liveness probe


def bounded_call(fn: Callable[[], Any], timeout_s: float,
                 name: str = "bounded-call") -> Any:
    """Run ``fn`` on a daemon thread with a hard deadline.

    A wedged worker accepts a transfer/collective and never completes
    it; the sync is the part that hangs. On deadline the worker thread
    is abandoned (it dies with the process — for a dead backend that is
    imminent anyway) and a classified DEADLINE_EXCEEDED error is raised
    so retry/degradation layers treat it as infra-class."""
    done = threading.Event()
    box: Dict[str, Any] = {}

    def _runner():
        try:
            box["val"] = fn()
        except BaseException as e:  # noqa: BLE001 - reraised below
            box["err"] = e
        finally:
            done.set()

    t = threading.Thread(target=_runner, daemon=True, name=name)
    t.start()
    if not done.wait(timeout_s):
        raise TimeoutError(
            f"DEADLINE_EXCEEDED: {name} hung > {timeout_s}s")
    if "err" in box:
        raise box["err"]
    return box.get("val")


def _probe_once() -> None:
    maybe_fail("probe")
    import jax
    import numpy as np
    devs = jax.devices()
    if not devs:
        raise RuntimeError("UNAVAILABLE: backend reports no devices")
    # tiny round-trip: host -> HBM -> compute -> host. A wedged worker
    # accepts the transfer but never completes it; the float() sync is
    # the part that hangs, which is why probe_backend bounds it.
    x = jax.device_put(np.arange(8.0, dtype=np.float32), devs[0])
    total = float(x.sum())
    if total != 28.0:
        raise RuntimeError(f"INTERNAL: probe round-trip corrupt ({total})")


def probe_backend(timeout_s: Optional[float] = None) -> float:
    """Liveness probe; returns round-trip seconds. Raises a classified
    infra error when the backend is dead, corrupt, or slower than
    ``timeout_s`` (default ARGS.probe_timeout_s; 0/None = unbounded)."""
    from h2o3_tpu import telemetry
    if timeout_s is None:
        timeout_s = float(getattr(_config.ARGS, "probe_timeout_s",
                                  0.0)) or None
    t0 = time.time()
    try:
        if timeout_s:
            bounded_call(_probe_once, timeout_s, name="backend-probe")
        else:
            _probe_once()
    except BaseException:
        telemetry.counter("backend_probe_failures_total").inc()
        raise
    telemetry.counter("backend_probes_total").inc()
    return time.time() - t0


def probe_with_retry(policy: Optional[RetryPolicy] = None,
                     timeout_s: Optional[float] = None) -> float:
    """Probe under the shared retry policy (bench pre-flight)."""
    return retry_call(lambda: probe_backend(timeout_s),
                      policy=policy, site="probe")
