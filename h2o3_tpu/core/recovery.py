"""Recovery — crash-survivable snapshot/resume state for long walks
AND for the fit in flight.

Reference: hex/faulttolerance/Recovery.java:21-45 — when a Grid or
AutoML run is started with a recovery directory, every trained model
and the walk state are persisted there so a fresh cluster can pick the
work up after a node dies. Here the same contract backs both
ml/grid.py (per-combo snapshots, resume_grid) and automl
(per-step snapshots, resume_automl in automl/__init__.py).

On-disk layout under ``recovery_dir``::

    <state name>.json      walk state (atomic: tmp + rename)
    <model key>.bin        one binary snapshot per trained model
    <step id>/             nested Recovery of a grid step (AutoML)
    fit_state/             in-fit snapshots of the combo in flight

State writes are atomic (write-to-tmp + ``os.rename``) so a SIGKILL
mid-write leaves the previous consistent snapshot, never a torn file.
Model snapshots go through io/persist.py (device-independent pickle),
so a run killed on an 8-device mesh resumes fine on one device.

**In-fit checkpointing** (:class:`FitCheckpointer`): the walk layer
above snapshots *between* models; a SIGKILL mid-fit still threw away
every boosting round already paid for. GBM (every K trees at the
`_boost_scan` host boundary), GLM (lambda-path outer iterations) and
DeepLearning (epoch boundaries) call the checkpointer to atomically
persist device-independent partial state — including the PRNG key
chain, early-stop history and scoring history — so a resumed fit is
**bit-identical** to an uninterrupted one (the DrJAX-style replayable
state-capture discipline, arxiv 2403.07128; Orbax-style async
snapshotting per SNIPPETS.md costs <1% of step time — ours is bounded
by the `fit_checkpoint_seconds` histogram and the bench.py
``checkpoint`` leg).

A corrupt/truncated snapshot is *quarantined* (renamed ``*.corrupt``,
``snapshot_load_failures_total`` incremented) and the fit restarts
cleanly — never a crash, never a silent wrong model.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.recovery")


def quarantine_snapshot(path: str, err: BaseException) -> Optional[str]:
    """Move an unreadable snapshot aside as ``<path>.corrupt`` (never
    crash, never silently reuse it) and count the failure. Returns the
    quarantine path, or None when even the rename failed."""
    from h2o3_tpu import telemetry
    telemetry.counter("snapshot_load_failures_total").inc()
    dest = path + ".corrupt"
    n = 0
    while os.path.exists(dest):            # keep every corpse for forensics
        n += 1
        dest = f"{path}.corrupt.{n}"
    try:
        os.rename(path, dest)
    except OSError as re:
        log.warning("recovery: could not quarantine %s: %s", path, re)
        return None
    log.warning("recovery: quarantined corrupt snapshot %s -> %s (%s)",
                path, os.path.basename(dest), err)
    return dest


class Recovery:
    """One recovery directory: model snapshots + an atomic state file."""

    def __init__(self, recovery_dir: str, state_name: str = "state"):
        self.dir = recovery_dir
        self.state_name = state_name
        os.makedirs(recovery_dir, exist_ok=True)

    # ------------------------------------------------------------ state
    @property
    def state_path(self) -> str:
        return os.path.join(self.dir, f"{self.state_name}.json")

    def write_state(self, state: dict) -> None:
        """Atomic state snapshot: a kill mid-write must leave the prior
        consistent state, not a torn JSON (Recovery.java writes the
        recovery state via the persist layer for the same reason)."""
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, self.state_path)

    def read_state(self) -> Optional[dict]:
        if not os.path.exists(self.state_path):
            return None
        with open(self.state_path) as f:
            return json.load(f)

    def has_state(self) -> bool:
        return os.path.exists(self.state_path)

    # ------------------------------------------------------------ models
    def save_model(self, model) -> str:
        """Snapshot one trained model; returns its file name."""
        from h2o3_tpu.io.persist import save_model
        fname = f"{model.key}.bin"
        save_model(model, os.path.join(self.dir, fname))
        return fname

    def load_models(self, files: List[str]) -> List:
        from h2o3_tpu.io.persist import load_model
        out = []
        for f in files:
            path = os.path.join(self.dir, f)
            try:
                out.append(load_model(path))
            except FileNotFoundError as e:
                log.warning("recovery: missing snapshot %s: %s", path, e)
            except Exception as e:  # noqa: BLE001 - a torn tail snapshot
                # (killed mid-save_model) costs one model, not the
                # resume; the corpse is quarantined so a later resume
                # cannot trip over it again
                quarantine_snapshot(path, e)
        return out

    def sub(self, name: str) -> "Recovery":
        """Nested recovery dir (one per AutoML grid step)."""
        return Recovery(os.path.join(self.dir, name),
                        state_name=self.state_name)


def ensure_json_safe(params: Dict, what: str) -> None:
    """Fail fast (before any model trains) when walk params cannot be
    serialized into the recovery state."""
    for k, v in params.items():
        try:
            json.dumps(v)
        except TypeError:
            raise ValueError(
                f"{what} requires JSON-serializable params; "
                f"'{k}'={type(v).__name__} is not") from None


# ===================================================================
# In-fit checkpointing (FitCheckpointer)
# ===================================================================

FIT_SNAPSHOT_VERSION = 1
FIT_SUFFIX = ".fitsnap"

# directory override for the current fit — ml/grid.py and
# automl/executor.py point it INSIDE their recovery_dir so a
# SIGKILL-mid-combo resumes inside the combo; models/model.py captures
# it on the caller thread and re-installs it on the job worker thread
_fit_dir_var: contextvars.ContextVar = contextvars.ContextVar(
    "h2o3tpu_fit_ckpt_dir", default=None)

_fit_lock = threading.Lock()
# every directory a checkpointer ever touched in this process — the
# shutdown()/conftest sweep walks these for orphaned tmp files
_fit_dirs_used: set = set()
# last snapshot THIS thread wrote/loaded: the job supervisor
# (core/job.py) consults it on an infra retry to log/decide
# resume-vs-restart without reaching into builder internals
_thread_state = threading.local()

# post-save observer for the current context — the cluster work
# scheduler (parallel/scheduler.py) installs a hook that republishes
# every written snapshot to the coordination-service KV so a reassigned
# work item's new owner can resume the fit mid-flight
_post_save_var: contextvars.ContextVar = contextvars.ContextVar(
    "h2o3tpu_fit_post_save", default=None)


@contextlib.contextmanager
def post_save_scope(hook: Callable[[str, bytes], None]):
    """Call ``hook(path, blob)`` after every ``FitCheckpointer.save``
    in this context (exceptions in the hook never fail the fit)."""
    tok = _post_save_var.set(hook)
    try:
        yield
    finally:
        _post_save_var.reset(tok)


def fit_checkpoint_dir() -> Optional[str]:
    """Resolved in-fit snapshot directory: the contextvar scope wins
    (grid/AutoML recovery composition), then ``H2O3TPU_FIT_CHECKPOINT_DIR``,
    then ``Config.fit_checkpoint_dir``. None/empty = checkpointing off."""
    d = _fit_dir_var.get()
    if d:
        return d
    d = os.environ.get("H2O3TPU_FIT_CHECKPOINT_DIR")
    if d:
        return d
    from h2o3_tpu.core.config import ARGS
    return getattr(ARGS, "fit_checkpoint_dir", "") or None


@contextlib.contextmanager
def fit_checkpoint_scope(directory: Optional[str]):
    """Scope the fit-checkpoint directory for the current context
    (passing None is a transparent no-op that keeps env/config
    resolution intact)."""
    tok = _fit_dir_var.set(directory)
    try:
        yield
    finally:
        _fit_dir_var.reset(tok)


def fit_checkpoint_every(default: int) -> int:
    """Snapshot cadence in algo-native units (GBM: trees, DL: minibatch
    steps, GLM: lambda-path iterations). ``H2O3TPU_FIT_CHECKPOINT_EVERY``
    / ``Config.fit_checkpoint_every`` override the caller's default."""
    env = os.environ.get("H2O3TPU_FIT_CHECKPOINT_EVERY")
    if env:
        return max(1, int(env))
    from h2o3_tpu.core.config import ARGS
    v = int(getattr(ARGS, "fit_checkpoint_every", 0) or 0)
    return v if v > 0 else max(1, int(default))


def _fit_fingerprint(algo: str, params: Dict, y, x, nrows: int) -> str:
    """Stable cross-process identity of one fit: the resumed process
    must find the snapshot the dead one wrote, so the file name derives
    from (algo, params, response, predictors, row count) — never from a
    per-process model/job key."""
    import hashlib
    canon = {}
    for k, v in params.items():
        if k == "checkpoint" and v is not None:
            v = getattr(v, "key", v)       # Model object → its key
        canon[k] = repr(v)
    payload = json.dumps(
        {"algo": algo, "y": y, "x": list(x) if x else None,
         "nrows": int(nrows), "params": canon}, sort_keys=True)
    return hashlib.blake2b(payload.encode(), digest_size=10).hexdigest()


def snapshot_host(x):
    """Device-independent host snapshot of (possibly cross-process
    sharded) fit state — what every ``FitCheckpointer.maybe_save``
    state_fn must use for device arrays. ``np.asarray`` raises on a
    row-sharded array of a multi-process cloud (it spans non-addressable
    devices); this lowers through the same ladder as model persistence
    (io/persist.py): fully-addressable → device_get, cross-process
    replicated → read the local replica, cross-process sharded →
    allgather to the GLOBAL array, so a reformed cloud of any size can
    re-shard the snapshot and resume. On multi-process clouds the
    allgather is an SPMD collective: every process must call at the
    same program point (the shared snapshot cadence guarantees it)."""
    import jax
    import numpy as np

    def _snap(v):
        if isinstance(v, jax.Array) and not v.is_fully_addressable:
            if v.sharding.is_fully_replicated:
                return np.asarray(v.addressable_shards[0].data)
            from h2o3_tpu.parallel.mesh import fetch_replicated
            return np.asarray(fetch_replicated(v))
        return np.asarray(v)
    return jax.tree_util.tree_map(_snap, x)


def fit_checkpointer(algo: str, params: Dict, y, x, nrows: int,
                     default_every: int) -> Optional["FitCheckpointer"]:
    """The builder-facing entry point: returns a checkpointer when
    in-fit snapshotting is enabled for this context, else None."""
    d = fit_checkpoint_dir()
    if not d:
        return None
    fp = _fit_fingerprint(algo, params, y, x, nrows)
    return FitCheckpointer(
        os.path.join(d, f"{algo}_{fp}{FIT_SUFFIX}"), algo,
        fit_checkpoint_every(default_every))


class FitCheckpointer:
    """Periodic, atomic, device-independent snapshots of one fit's
    partial state, written at host boundaries the training loops
    already cross (GBM tree chunks, DL step chunks, GLM lambdas).

    The on-disk artifact is one pickle (version + algo + unit + state)
    published via write-to-tmp + ``os.replace`` so a SIGKILL mid-write
    leaves the previous consistent snapshot. ``load()`` quarantines
    anything unreadable and returns None — a corrupt snapshot costs the
    resume, never correctness."""

    def __init__(self, path: str, algo: str, every: int):
        self.path = path
        self.algo = algo
        self.every = max(1, int(every))
        self._last_unit = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with _fit_lock:
            _fit_dirs_used.add(os.path.dirname(path) or ".")

    # -- write ---------------------------------------------------------
    def due(self, unit: int) -> bool:
        return unit - self._last_unit >= self.every

    def save(self, unit: int, state: Dict[str, Any]) -> None:
        from h2o3_tpu import telemetry
        from h2o3_tpu.telemetry import stepprof
        t0 = time.time()
        # an active fit profile charges the snapshot write to its
        # "checkpoint" phase (IO time is neither compute nor host prep)
        with stepprof.phase("checkpoint"):
            blob = pickle.dumps({"version": FIT_SNAPSHOT_VERSION,
                                 "algo": self.algo, "unit": int(unit),
                                 "state": state}, protocol=4)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        self._last_unit = int(unit)
        _thread_state.last = (self.path, int(unit), self.algo)
        hook = _post_save_var.get()
        if hook is not None:
            try:
                hook(self.path, blob)
            except Exception as e:   # noqa: BLE001 - observer only
                log.warning("fit checkpoint post-save hook failed: %s", e)
        telemetry.counter("fit_checkpoints_written_total",
                          algo=self.algo).inc()
        telemetry.histogram("fit_checkpoint_seconds").observe(
            time.time() - t0)
        # test hook (SIGKILL-mid-fit tests): widen the crash window so
        # the killer deterministically lands between a snapshot and the
        # next chunk — analogous to the watchdog fault-injection knobs
        hold = float(os.environ.get("H2O3TPU_FIT_CHECKPOINT_HOLD_S",
                                    "0") or 0)
        if hold > 0:
            time.sleep(hold)

    def maybe_save(self, unit: int,
                   state_fn: Callable[[], Dict[str, Any]]) -> bool:
        """Snapshot when the cadence is due; ``state_fn`` defers the
        (host-sync) state capture so off-cadence boundaries cost one
        integer compare."""
        if not self.due(unit):
            return False
        self.save(unit, state_fn())
        return True

    # -- read ----------------------------------------------------------
    def load(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """(unit, state) of the last snapshot, or None. Counts
        ``fit_resumes_total{algo}`` on success; quarantines on any
        failure (bit-flip, truncation, version drift)."""
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                payload = pickle.load(f)
            if payload.get("version") != FIT_SNAPSHOT_VERSION:
                raise ValueError(
                    f"fit snapshot version {payload.get('version')} != "
                    f"{FIT_SNAPSHOT_VERSION}")
            if payload.get("algo") != self.algo:
                raise ValueError(
                    f"fit snapshot algo {payload.get('algo')!r} != "
                    f"{self.algo!r}")
            unit = int(payload["unit"])
            state = payload["state"]
        except Exception as e:  # noqa: BLE001 - quarantine boundary
            quarantine_snapshot(self.path, e)
            return None
        self._last_unit = unit
        _thread_state.last = (self.path, unit, self.algo)
        from h2o3_tpu import telemetry
        telemetry.counter("fit_resumes_total", algo=self.algo).inc()
        log.info("fit resume: %s from snapshot unit %d (%s)",
                 self.algo, unit, self.path)
        return unit, state

    def clear(self) -> None:
        """Remove the snapshot once the fit completed — a finished model
        must never resume."""
        for pp in (self.path, self.path + ".tmp"):
            try:
                os.remove(pp)
            except OSError:
                pass
        _thread_state.last = None


def thread_fit_snapshot() -> Optional[Tuple[str, int, str]]:
    """(path, unit, algo) of the last in-fit snapshot this thread wrote
    or loaded, if it still exists on disk — the job supervisor's
    resume-vs-restart probe (core/job.py retry loop)."""
    t = getattr(_thread_state, "last", None)
    if t and os.path.exists(t[0]):
        return t
    return None


def clear_fit_snapshots(directory: str) -> int:
    """Remove every fit snapshot (and tmp debris) under ``directory``;
    rmdir it when empty. Grid/AutoML call this when their walk
    completes — unconsumed snapshots (e.g. a combo that got batch-
    trained on resume) must not leak."""
    removed = 0
    if not os.path.isdir(directory):
        return 0
    for f in list(os.listdir(directory)):
        if FIT_SUFFIX in f:
            try:
                os.remove(os.path.join(directory, f))
                removed += 1
            except OSError:
                pass
    try:
        if not os.listdir(directory):
            os.rmdir(directory)
    except OSError:
        pass
    with _fit_lock:
        _fit_dirs_used.discard(directory)
    return removed


def sweep_fit_checkpoints(extra_dir: Optional[str] = None) -> int:
    """Sweep ORPHANED in-fit checkpoint debris: ``*.tmp`` files a kill
    left behind and partial (now-empty) snapshot directories. Completed
    ``*.fitsnap`` snapshots are intentional resumable state and stay.
    Called by ``shutdown()`` and the conftest leak check (extends the
    PR 2 sweep). Returns how many entries were removed."""
    with _fit_lock:
        dirs = set(_fit_dirs_used)
    if extra_dir:
        dirs.add(extra_dir)
    env_d = os.environ.get("H2O3TPU_FIT_CHECKPOINT_DIR")
    if env_d:
        dirs.add(env_d)
    removed = 0
    for d in dirs:
        if not os.path.isdir(d):
            with _fit_lock:
                _fit_dirs_used.discard(d)
            continue
        for f in list(os.listdir(d)):
            if f.endswith(FIT_SUFFIX + ".tmp"):
                try:
                    os.remove(os.path.join(d, f))
                    removed += 1
                except OSError:
                    pass
        try:
            if not os.listdir(d):
                os.rmdir(d)
                removed += 1
                with _fit_lock:
                    _fit_dirs_used.discard(d)
        except OSError:
            pass
    try:
        # mirror-blob debris rides the same sweep cadence: orphaned
        # *.framesnap.tmp from a kill mid-write plus unregistered
        # *.framesnap blobs (core/durability.py, ISSUE 18)
        from h2o3_tpu.core import durability as _durability
        removed += _durability.sweep_debris()
    except Exception:       # noqa: BLE001 - durability is optional
        pass
    return removed
