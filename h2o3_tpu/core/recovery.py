"""Recovery — crash-survivable snapshot/resume state for long walks.

Reference: hex/faulttolerance/Recovery.java:21-45 — when a Grid or
AutoML run is started with a recovery directory, every trained model
and the walk state are persisted there so a fresh cluster can pick the
work up after a node dies. Here the same contract backs both
ml/grid.py (per-combo snapshots, resume_grid) and automl
(per-step snapshots, resume_automl in automl/__init__.py).

On-disk layout under ``recovery_dir``::

    <state name>.json      walk state (atomic: tmp + rename)
    <model key>.bin        one binary snapshot per trained model
    <step id>/             nested Recovery of a grid step (AutoML)

State writes are atomic (write-to-tmp + ``os.rename``) so a SIGKILL
mid-write leaves the previous consistent snapshot, never a torn file.
Model snapshots go through io/persist.py (device-independent pickle),
so a run killed on an 8-device mesh resumes fine on one device.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.recovery")


class Recovery:
    """One recovery directory: model snapshots + an atomic state file."""

    def __init__(self, recovery_dir: str, state_name: str = "state"):
        self.dir = recovery_dir
        self.state_name = state_name
        os.makedirs(recovery_dir, exist_ok=True)

    # ------------------------------------------------------------ state
    @property
    def state_path(self) -> str:
        return os.path.join(self.dir, f"{self.state_name}.json")

    def write_state(self, state: dict) -> None:
        """Atomic state snapshot: a kill mid-write must leave the prior
        consistent state, not a torn JSON (Recovery.java writes the
        recovery state via the persist layer for the same reason)."""
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, self.state_path)

    def read_state(self) -> Optional[dict]:
        if not os.path.exists(self.state_path):
            return None
        with open(self.state_path) as f:
            return json.load(f)

    def has_state(self) -> bool:
        return os.path.exists(self.state_path)

    # ------------------------------------------------------------ models
    def save_model(self, model) -> str:
        """Snapshot one trained model; returns its file name."""
        from h2o3_tpu.io.persist import save_model
        fname = f"{model.key}.bin"
        save_model(model, os.path.join(self.dir, fname))
        return fname

    def load_models(self, files: List[str]) -> List:
        from h2o3_tpu.io.persist import load_model
        out = []
        for f in files:
            path = os.path.join(self.dir, f)
            try:
                out.append(load_model(path))
            except Exception as e:  # noqa: BLE001 - a torn tail snapshot
                # (killed mid-save_model) costs one model, not the resume
                log.warning("recovery: skipping unreadable snapshot %s: %s",
                            path, e)
        return out

    def sub(self, name: str) -> "Recovery":
        """Nested recovery dir (one per AutoML grid step)."""
        return Recovery(os.path.join(self.dir, name),
                        state_name=self.state_name)


def ensure_json_safe(params: Dict, what: str) -> None:
    """Fail fast (before any model trains) when walk params cannot be
    serialized into the recovery state."""
    for k, v in params.items():
        try:
            json.dumps(v)
        except TypeError:
            raise ValueError(
                f"{what} requires JSON-serializable params; "
                f"'{k}'={type(v).__name__} is not") from None
