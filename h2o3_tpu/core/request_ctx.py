"""Request lifecycle context — deadlines and cooperative cancellation.

Reference: water/api/RequestServer.java serves every request on a
bounded Jetty pool and water/Job.java:stop_requested() is polled at
chunk boundaries inside MRTask loops, so a cancelled or expired request
frees its F/J workers within one chunk. Here the same contract rides on
``contextvars``: the REST tier (api/server.py) installs a request
deadline, ``Job.start`` captures it and re-installs it (plus the job
itself) on the worker thread, and the map/reduce layer
(parallel/map_reduce.py) calls :func:`cancel_point` at every dispatch —
the chunk boundary of this runtime. A DrJAX-style scan only yields
between dispatches, so this is exactly where an expired request can be
observed without preempting compiled code.

Deadlines are ABSOLUTE ``time.monotonic()`` instants (never wall clock:
NTP steps must not expire requests).
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator, Optional

from h2o3_tpu.core import heartbeat, watchdog


class DeadlineExceeded(Exception):
    """The request's deadline expired; the work was cancelled
    cooperatively. Maps to HTTP 408 at the REST boundary and to a
    CANCELLED job in core/job.py."""


# a deadline expiry is a client decision, never a retryable infra blip
# (and the name must NOT contain the watchdog's "DEADLINE_EXCEEDED"
# infra token, which marks the backend's own RPC timeouts)
watchdog.NON_RETRYABLE.append(DeadlineExceeded)

_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "h2o3tpu_request_deadline", default=None)
_JOB: contextvars.ContextVar[Optional[object]] = contextvars.ContextVar(
    "h2o3tpu_current_job", default=None)


def current_deadline() -> Optional[float]:
    """The active absolute monotonic deadline, or None."""
    return _DEADLINE.get()


def current_job():
    """The Job whose work is running on this thread, or None."""
    return _JOB.get()


def remaining_s() -> Optional[float]:
    """Seconds until the active deadline (negative = expired); None when
    no deadline is set."""
    dl = _DEADLINE.get()
    return None if dl is None else dl - time.monotonic()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[float]) -> Iterator[None]:
    """Install an absolute monotonic deadline for the duration of the
    block (None = explicitly clear any inherited deadline)."""
    tok = _DEADLINE.set(deadline)
    try:
        yield
    finally:
        _DEADLINE.reset(tok)


@contextlib.contextmanager
def job_scope(job, deadline: Optional[float] = None,
              trace=None) -> Iterator[None]:
    """Install ``job`` (and its captured deadline and trace context) as
    the thread's current work unit — Job.start wraps the worker body in
    this so cancel_point() deep inside map/reduce loops can observe the
    job + deadline, and so the job's spans stay stitched to the
    originating request's trace across the thread hop
    (telemetry/trace_context.py)."""
    tok_j = _JOB.set(job)
    tok_d = _DEADLINE.set(deadline)
    tok_t = None
    if trace is not None:
        from h2o3_tpu.telemetry import trace_context
        tok_t = trace_context.install(trace)
    try:
        yield
    finally:
        if tok_t is not None:
            from h2o3_tpu.telemetry import trace_context
            trace_context.uninstall(tok_t)
        _DEADLINE.reset(tok_d)
        _JOB.reset(tok_j)


def check_deadline(site: str = "") -> None:
    """Raise DeadlineExceeded if the active deadline has passed."""
    dl = _DEADLINE.get()
    if dl is not None and time.monotonic() >= dl:
        from h2o3_tpu import telemetry
        telemetry.counter("request_deadline_exceeded_total").inc()
        raise DeadlineExceeded(
            f"request deadline exceeded"
            f"{f' at {site}' if site else ''} "
            f"({time.monotonic() - dl:.3f}s past)")


def cancel_point(site: str = "") -> None:
    """Cooperative cancellation checkpoint — call at chunk boundaries.

    Observes (1) a cancel() on the current job, (2) the request
    deadline, and (3) cloud health (core/heartbeat.py), raising
    JobCancelledException / DeadlineExceeded / CloudUnhealthyError so
    the job layer frees the worker within one chunk
    (water/Job.java stop_requested() polling contract) — for an
    unhealthy cloud that means failing fast HERE instead of blocking on
    a collective a dead peer will never join."""
    job = _JOB.get()
    if job is not None and job.cancel_requested():
        from h2o3_tpu.core.job import JobCancelledException
        raise JobCancelledException(getattr(job, "key", "job"))
    check_deadline(site)
    heartbeat.check_healthy(site)
