"""Cluster work scheduler — fan independent fits across pod hosts.

Reference: H2O's design point that any node can drive a job and
independent model builds run wherever capacity exists (water/Job.java +
hex/ModelBuilder distributed dispatch); DrJAX (arxiv 2403.07128) shows
the same coordinator-plus-workers MapReduce decomposition over a JAX
mesh. Here the independent units are grid-search combos, AutoML steps
and CV fold models.

Execution model
---------------
The cloud is SPMD: every process runs the same driver program (the
tests/mp_worker.py contract), so ``run()`` is entered by every process
at the same program point with the same arguments. Work items therefore
never serialize their work DESCRIPTION — each process already holds the
closures; only three things ride the coordination-service KV store (the
same out-of-band channel as the heartbeat and cluster telemetry, NEVER
a device collective):

- ``ctl/assign/<pid>`` — the coordinator-owned lease table: item index
  → generation. Publication IS the lease (the KV store has no CAS, so a
  competitive-pull queue cannot be made race-free; a coordinator-push
  assignment can).
- ``rmeta/`` + ``rblob/`` — the executing host's device-independent
  result bytes (io/persist ``_DeviceLoweringPickler`` payloads),
  chunked + base64 like telemetry/cluster.py snapshots. Metas live in
  their own subtree so the coordinator's poll (``key_value_dir_get``)
  never drags blob parts over the wire.
- ``smeta/`` + ``sblob/`` — the item's traveling PR 9 fit snapshots:
  every ``FitCheckpointer.save`` under a scheduled item republishes the
  blob, and a reassigned item's new owner restores them into its local
  fit dir BEFORE training, so the fingerprint-addressed resume
  (core/recovery.py ``_fit_fingerprint`` is cross-process stable) picks
  up mid-fit.

Items execute on a LOCAL device mesh (parallel/mesh.local_mesh_scope)
against the host copy of the frame (frame.local_copy), so a scheduled
fit issues no cross-process collectives — a dead peer cannot wedge it,
which is why the whole run sits inside heartbeat.local_work_scope().
A lease whose owner's heartbeat goes stale past interval*miss_budget is
reassigned with a bumped generation; stale-generation results are
ignored. The coordinator freezes the authoritative result set in the
``ctl/done`` manifest so every process installs EXACTLY the same
results in the same order (the SPMD walk after the run must agree
bit-for-bit).

Determinism contract: item identity, ordering and assignment derive
from the item LIST (content), never from placement; per-item PRNG state
rides in the params (canonical combo key → same seed resolution
everywhere), and local frames rebuild through the same from_numpy
narrowing/padding a single-process ingest runs — so scheduler-on output
is bit-identical to the scheduler-off single-process run.
"""

from __future__ import annotations

import base64
import contextlib
import hashlib
import itertools
import json
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from h2o3_tpu.core import config as _config
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.scheduler")

KV_PREFIX = "h2o3tpu/sched/"
_B64_CHUNK = 131072          # base64 chars per KV part (bounded values)
_BLOB_TIMEOUT_MS = 120_000   # blocking fetch bound for published blobs

_RUN_SEQ = itertools.count()  # SPMD-deterministic: every process enters
#                               run() at the same program points
_PAST_RUNS: List[str] = []    # coordinator's GC ring of run subtrees

# process-local observability block (telemetry/cluster.py sched block +
# cluster_info() node leases)
_lock = threading.Lock()
_STATE = {"runs": 0, "leases_held": 0, "items_done": 0,
          "items_reassigned": 0}

# process-global nesting guard: work executing INSIDE a scheduled item
# runs on one host only, so any nested scheduler.run() would violate
# the SPMD entry contract (the other processes never reach it) and
# deadlock — nested scheduling degrades to local execution instead.
# A global (not a contextvar) because builder.train may hop threads.
_IN_ITEM_DEPTH = 0


class ScheduledFailure:
    """A work item whose execution raised on its owner — travels in
    place of a result so the consuming walk re-raises the SAME error
    (grid failure recording stays bit-compatible with the sequential
    walk, which would have hit the identical deterministic error)."""

    def __init__(self, error: str):
        self.error = str(error)

    def __repr__(self) -> str:
        return f"<ScheduledFailure {self.error!r}>"


# ------------------------------------------------------------------ gating

def mode() -> str:
    return str(getattr(_config.ARGS, "scheduler", "auto") or "auto").lower()


def in_item() -> bool:
    """True while this process is executing a scheduled work item."""
    return _IN_ITEM_DEPTH > 0


def active() -> bool:
    """Scheduler gate: H2O3TPU_SCHEDULER=auto|on|off; auto = on for
    multi-process clouds only. Always False inside a scheduled item
    (nested fan-out would break the SPMD run() entry contract)."""
    if in_item():
        return False
    m = mode()
    if m in ("off", "0", "false"):
        return False
    if m in ("on", "1", "true"):
        return True
    try:
        import jax
        return jax.process_count() > 1
    except Exception:        # noqa: BLE001 - no backend → nothing to fan
        return False


def snapshot() -> dict:
    """Per-host scheduler observability block (cluster telemetry +
    GET /3/Cloud node leases)."""
    with _lock:
        return dict(_STATE)


def leases_held() -> int:
    with _lock:
        return int(_STATE["leases_held"])


def _set_leases(n: int) -> None:
    from h2o3_tpu import telemetry
    with _lock:
        _STATE["leases_held"] = int(n)
    telemetry.gauge("sched_leases_held").set(int(n))


# ------------------------------------------------------------------ KV I/O

def _kv():
    """The coordination-service KV client, or None off-cloud (the same
    control plane heartbeat._kv_round rides)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:        # noqa: BLE001 - no jax / no distributed
        return None


def _encode(data: bytes) -> str:
    return base64.b64encode(zlib.compress(data, 6)).decode("ascii")


def _decode(text: str) -> bytes:
    return zlib.decompress(base64.b64decode(text.encode("ascii")))


def _dir(client, prefix: str) -> Dict[str, str]:
    """Snapshot a KV subtree as {full key: value}; {} when absent."""
    try:
        return dict(client.key_value_dir_get(prefix))
    except Exception:        # noqa: BLE001 - nothing published yet
        return {}


def _publish(client, meta_key: str, blob_prefix: str,
             data: Optional[bytes], meta: Dict[str, Any]) -> None:
    """Chunked blob publish: parts first, meta LAST (pollers watch the
    meta subtree, so a half-written blob is never observed)."""
    b64 = _encode(data) if data is not None else ""
    nparts = (len(b64) + _B64_CHUNK - 1) // _B64_CHUNK if b64 else 0
    for j in range(nparts):
        client.key_value_set(f"{blob_prefix}p{j}",
                             b64[j * _B64_CHUNK:(j + 1) * _B64_CHUNK],
                             allow_overwrite=True)
    client.key_value_set(meta_key, json.dumps({**meta, "parts": nparts}),
                         allow_overwrite=True)


def _fetch_parts(client, blob_prefix: str, nparts: int,
                 timeout_ms: int = _BLOB_TIMEOUT_MS) -> Optional[bytes]:
    """Fetch + decode a published blob. Parts are written before their
    meta, so once a meta is visible every part is a bounded wait."""
    if nparts <= 0:
        return b""
    parts = []
    for j in range(nparts):
        try:
            parts.append(client.blocking_key_value_get(
                f"{blob_prefix}p{j}", timeout_ms))
        except Exception:    # noqa: BLE001 - lost part: caller decides
            return None
    try:
        return _decode("".join(parts))
    except Exception:        # noqa: BLE001 - corrupt transport
        return None


# ------------------------------------------------------------------ board

class RunBoard:
    """Pure lease/complete/reassign state machine — one scheduled run's
    truth, owned by the coordinator. Deliberately jax- and KV-free so
    the bench ``_stub_sched`` leg and unit tests drive it dry.

    Invariants:
    - every item always has exactly one owner (assignment IS the lease);
    - generations only grow, and only via reassignment;
    - a result is accepted only at the item's CURRENT generation
      (stale results from a slow-but-alive ex-owner are ignored);
    - reassignment targets rotate round-robin over the alive hosts.
    """

    def __init__(self, n_items: int, procs: List[int], offset: int = 0):
        if n_items <= 0:
            raise ValueError("RunBoard needs >= 1 item")
        if not procs:
            raise ValueError("RunBoard needs >= 1 process")
        self.n_items = int(n_items)
        self.procs = list(procs)
        self.dead: set = set()
        # idx -> (owner pid, generation)
        self.leases: Dict[int, tuple] = {
            i: (self.procs[(i + offset) % len(self.procs)], 1)
            for i in range(self.n_items)}
        # idx -> (pid, gen) of the ACCEPTED result
        self.results: Dict[int, tuple] = {}
        self._rr = 0

    # -- views ---------------------------------------------------------
    def owner(self, idx: int) -> int:
        return self.leases[idx][0]

    def generation(self, idx: int) -> int:
        return self.leases[idx][1]

    def assignments(self, pid: int) -> Dict[int, int]:
        """{item idx: generation} currently leased to ``pid``."""
        return {i: g for i, (p, g) in self.leases.items() if p == pid}

    def pending(self) -> List[int]:
        return [i for i in range(self.n_items) if i not in self.results]

    def held(self, pid: int) -> List[int]:
        """Leases held = assigned and not yet resulted (queue-drain
        visibility for GET /3/Cloud)."""
        return [i for i, (p, _) in self.leases.items()
                if p == pid and i not in self.results]

    def complete(self) -> bool:
        return len(self.results) == self.n_items

    def alive(self) -> List[int]:
        return [p for p in self.procs if p not in self.dead]

    # -- transitions ---------------------------------------------------
    def on_result(self, idx: int, pid: int, gen: int) -> bool:
        """Accept a published result iff it matches the item's current
        lease generation; stale generations are dropped."""
        if idx in self.results:
            return False
        owner, cur = self.leases[idx]
        if gen != cur:
            return False
        self.results[idx] = (pid, gen)
        return True

    def on_dead(self, pid: int) -> List[tuple]:
        """Reassign every unresulted lease the dead host held; returns
        [(idx, new_pid, new_gen)]. Idempotent per host."""
        if pid in self.dead:
            return []
        self.dead.add(pid)
        alive = self.alive()
        if not alive:
            raise RuntimeError("no alive hosts left to reassign to")
        moved = []
        for idx in sorted(self.held(pid)):
            new = alive[self._rr % len(alive)]
            self._rr += 1
            gen = self.leases[idx][1] + 1
            self.leases[idx] = (new, gen)
            moved.append((idx, new, gen))
        return moved


# ------------------------------------------------------------------ run

def _restore_snapshots(client, R: str, idx: int, fit_dir: str) -> int:
    """Write an item's traveling fit snapshots into the local fit dir —
    the reassigned owner's mid-fit resume input. Returns count."""
    metas = _dir(client, f"{R}smeta/{idx}/")
    n = 0
    os.makedirs(fit_dir, exist_ok=True)
    for key, raw in sorted(metas.items()):
        try:
            meta = json.loads(raw)
        except ValueError:
            continue
        tag = key.rsplit("/", 1)[-1]
        blob = _fetch_parts(client, f"{R}sblob/{idx}/{tag}/",
                            int(meta.get("parts", 0)))
        name = os.path.basename(str(meta.get("name", "")))
        if blob is None or not name:
            continue
        path = os.path.join(fit_dir, name)
        tmp = path + ".travel"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        n += 1
    if n:
        log.info("sched item %d: restored %d traveling fit snapshot(s) "
                 "into %s", idx, n, fit_dir)
    return n


@contextlib.contextmanager
def _noop_ctx():
    yield


def _lease_payload(assignments: Dict[int, int],
                   traceparent: Optional[str]) -> str:
    """Serialize a lease record for ``ctl/assign/<pid>``. With a
    coordinator traceparent the record wraps to ``{"items": ...,
    "traceparent": ...}`` so the holder's item spans stitch under the
    coordinator's sched.run span; without one it stays the legacy bare
    ``{idx: gen}`` dict."""
    if not traceparent:
        return json.dumps(assignments)
    return json.dumps({"items": assignments, "traceparent": traceparent})


def _parse_lease(raw: Optional[str]) -> Tuple[Dict[int, int],
                                              Optional[str]]:
    """Inverse of :func:`_lease_payload`; accepts both shapes."""
    if not raw:
        return {}, None
    d = json.loads(raw)
    if isinstance(d.get("items"), dict):
        return ({int(k): int(v) for k, v in d["items"].items()},
                d.get("traceparent") or None)
    return {int(k): int(v) for k, v in d.items()}, None


def _execute_one(idx: int, gen: int, execute: Callable[[int], bytes],
                 client, R: str, fit_dir: Optional[str],
                 pid: int) -> Dict[str, Any]:
    """Run one work item locally; returns the result record (the caller
    publishes it). Snapshot-travel hooks + fit scope wrap the
    execution; all exceptions become ok=False results (the consuming
    walk decides failure semantics, exactly like the sequential walk's
    try/except)."""
    from h2o3_tpu import telemetry
    from h2o3_tpu.core import recovery as _recovery

    def _publish_snap(path: str, blob: bytes) -> None:
        if client is None:
            return
        name = os.path.basename(path)
        tag = hashlib.blake2b(name.encode(), digest_size=6).hexdigest()
        _publish(client, f"{R}smeta/{idx}/{tag}",
                 f"{R}sblob/{idx}/{tag}/", blob, {"name": name})

    if client is not None and gen > 1 and fit_dir:
        _restore_snapshots(client, R, idx, fit_dir)
    t0 = time.time()
    ok, err, data = True, "", None
    global _IN_ITEM_DEPTH
    with telemetry.span("sched.item", item=idx, gen=gen, host=pid):
        with (_recovery.fit_checkpoint_scope(fit_dir)
              if fit_dir else _noop_ctx()), \
                _recovery.post_save_scope(_publish_snap):
            _IN_ITEM_DEPTH += 1
            try:
                data = execute(idx)
            except Exception as e:   # noqa: BLE001 - travels as failure
                ok, err = False, str(e) or type(e).__name__
                log.warning("sched item %d failed on host %d: %s",
                            idx, pid, e)
            finally:
                _IN_ITEM_DEPTH -= 1
    telemetry.histogram("sched_item_seconds").observe(time.time() - t0)
    telemetry.counter("sched_items_completed_total",
                      host=str(pid)).inc()
    with _lock:
        _STATE["items_done"] += 1
    return {"gen": gen, "pid": pid, "ok": ok, "error": err, "data": data}


def _run_inline(n_items: int, execute: Callable[[int], bytes],
                fit_dir: Optional[str]) -> Dict[int, dict]:
    """Degenerate run: single process or no coordination client — every
    item leases to this host, executes in order. Exercises the same
    item-execution path (local mesh, local frame, fit scope) so
    H2O3TPU_SCHEDULER=on tests the plumbing on one process."""
    out = {}
    for idx in range(n_items):
        _set_leases(n_items - idx)
        r = _execute_one(idx, 1, execute, None, "", fit_dir, 0)
        out[idx] = {"ok": r["ok"], "error": r["error"], "data": r["data"]}
    _set_leases(0)
    return out


def run(tag: str, n_items: int, execute: Callable[[int], bytes], *,
        job=None, fit_dir: Optional[str] = None,
        deadline: Optional[float] = None) -> Dict[int, dict]:
    """Schedule ``n_items`` independent work items across the cloud.

    SPMD entry point: EVERY process calls run() with identical
    arguments at the same program point. Returns {item idx →
    {"ok", "error", "data"(bytes)}} — identical on every process (the
    ``ctl/done`` manifest freezes the authoritative result set). Items
    missing from the dict were cancelled by the deadline; the caller's
    walk handles them exactly like budget-stopped sequential work.

    ``execute(idx) -> bytes`` must be a pure-local computation (local
    mesh + host frame copies) — it runs on whichever host holds the
    item's lease.
    """
    from h2o3_tpu import telemetry
    from h2o3_tpu.core import heartbeat as _hb

    args = _config.ARGS
    seq = next(_RUN_SEQ)
    with _lock:
        _STATE["runs"] += 1
    telemetry.counter("sched_runs_total",
                      kind=tag.split(":", 1)[0]).inc()
    telemetry.counter("sched_items_total").inc(n_items)

    client = _kv()
    try:
        import jax
        pid, nproc = jax.process_index(), jax.process_count()
    except Exception:        # noqa: BLE001 - no backend
        pid, nproc = 0, 1
    if client is None or nproc <= 1:
        with _hb.local_work_scope(), \
                telemetry.span("sched.run", tag=tag, items=n_items,
                               hosts=1):
            return _run_inline(n_items, execute, fit_dir)

    digest = hashlib.blake2b(
        f"{tag}:{n_items}".encode(), digest_size=5).hexdigest()
    run_id = f"{seq:04d}-{digest}"
    R = f"{KV_PREFIX}{run_id}/"
    poll_s = float(getattr(args, "scheduler_poll_s", 0.2) or 0.2)
    grace = float(getattr(args, "scheduler_reassign_grace_s", 0.0) or 0.0)
    wall = float(getattr(args, "scheduler_timeout_s", 0.0) or 0.0)
    hard_deadline = deadline
    if wall > 0:
        hard_deadline = min(deadline or float("inf"), time.time() + wall)

    coordinator = pid == 0
    board: Optional[RunBoard] = None
    suspects: Dict[int, float] = {}     # dead-candidate pid -> first seen
    my_done: Dict[int, int] = {}        # idx -> gen executed locally
    manifest: Optional[dict] = None
    log_every = max(1, int(5.0 / poll_s))
    tick = 0
    run_tp: Optional[str] = None
    from h2o3_tpu.telemetry import spans as _spans
    from h2o3_tpu.telemetry import trace_context as _trace
    with _hb.local_work_scope(), \
            telemetry.span("sched.run", tag=tag, run=run_id,
                           items=n_items, hosts=nproc):
        if coordinator:
            # the coordinator's traceparent rides every lease record:
            # a leased item executes under the COORDINATOR's causality,
            # so a remote host's sched.item spans parent under this
            # sched.run span in the stitched GET /3/Trace?trace_id=
            run_tp = _trace.format_traceparent(
                parent_id=_spans.current_span_id())
            # garbage-collect the run-before-last: a process entering
            # run seq N has provably finished INSTALLING run N-1
            # (install gates its return), so only the immediately-
            # previous subtree can still have readers — anything older
            # is safe to delete
            with _lock:
                _PAST_RUNS.append(R)
                stale = _PAST_RUNS[:-2]
                del _PAST_RUNS[:-2]
            for old in stale:
                try:
                    client.key_value_delete(old)
                except Exception:   # noqa: BLE001 - best-effort hygiene
                    pass
            # hosts already heartbeat-dead at run start never get
            # leases; run-sequence rotation spreads successive small
            # runs (AutoML single-model steps) across different hosts
            dead0 = set(_hb.dead_peers())
            procs = [p for p in range(nproc) if p not in dead0 or p == 0]
            board = RunBoard(n_items, procs, offset=seq % len(procs))
            for p in procs:
                client.key_value_set(
                    f"{R}ctl/assign/{p}",
                    _lease_payload(board.assignments(p), run_tp),
                    allow_overwrite=True)
            counts = {p: len(board.assignments(p)) for p in procs}
            log.info("sched run %s (%s): %d items over hosts %s", run_id,
                     tag, n_items, counts)
            if job is not None:
                job.update(0.0, f"sched {run_id}: {n_items} items "
                                f"across hosts {counts}")
        while True:
            # -- lease intake + local execution (every process) --------
            ctl = _dir(client, f"{R}ctl/")
            done_raw = ctl.get(f"{R}ctl/done")
            if done_raw and not coordinator:
                manifest = json.loads(done_raw)
                _set_leases(0)
                break
            raw = ctl.get(f"{R}ctl/assign/{pid}")
            items, lease_tp = _parse_lease(raw)
            lease_tc = _trace.parse_traceparent(lease_tp) \
                if lease_tp else None
            todo = sorted((i, g) for i, g in items.items()
                          if my_done.get(i) != g)
            for n_left, (idx, gen) in enumerate(todo):
                _set_leases(len(todo) - n_left)
                if lease_tc is not None:
                    # execute under the LEASE's causality: detach from
                    # the local polling loop's span stack so sched.item
                    # roots under the coordinator's sched.run
                    with _trace.trace_scope(lease_tc), _spans.detach():
                        r = _execute_one(idx, gen, execute, client, R,
                                         fit_dir, pid)
                else:
                    r = _execute_one(idx, gen, execute, client, R,
                                     fit_dir, pid)
                data = r.pop("data")
                _publish(client, f"{R}rmeta/{idx}/{r['gen']}",
                         f"{R}rblob/{idx}/{r['gen']}/", data, r)
                my_done[idx] = gen
            _set_leases(0)

            if coordinator:
                # -- result intake (one cheap subtree poll) ------------
                rmeta = _dir(client, f"{R}rmeta/")
                for idx in board.pending():
                    gen = board.generation(idx)
                    v = rmeta.get(f"{R}rmeta/{idx}/{gen}")
                    if v:
                        meta = json.loads(v)
                        board.on_result(idx, int(meta["pid"]),
                                        int(meta["gen"]))
                # -- dead-peer reassignment ----------------------------
                now = time.time()
                for d in _hb.dead_peers():
                    if d in board.dead or d not in board.procs:
                        continue
                    first = suspects.setdefault(d, now)
                    if now - first < grace:
                        continue
                    moved = board.on_dead(d)
                    if moved:
                        telemetry.counter(
                            "sched_items_reassigned_total").inc(
                                len(moved))
                        with _lock:
                            _STATE["items_reassigned"] += len(moved)
                        log.warning(
                            "sched run %s: host %d heartbeat-dead, "
                            "reassigned items %s", run_id, d,
                            [(i, p) for i, p, _ in moved])
                        # re-home the dead peer's frames BEFORE its
                        # items re-run: a reassigned item whose input
                        # frame died with its host either rebuilds
                        # (mirror/lineage) or fails typed with
                        # DataLostError — never hangs on absent data
                        try:
                            from h2o3_tpu.core import durability
                            durability.maybe_rebuild()
                        except Exception as e:  # noqa: BLE001
                            log.debug("durability rebuild skipped: %s",
                                      e)
                        for p in board.alive():
                            client.key_value_set(
                                f"{R}ctl/assign/{p}",
                                _lease_payload(board.assignments(p),
                                               run_tp),
                                allow_overwrite=True)
                done_n = len(board.results)
                if job is not None and tick % log_every == 0:
                    held = {p: len(board.held(p)) for p in board.alive()}
                    job.update(0.0, f"sched {run_id}: {done_n}/"
                                    f"{n_items} done, leases {held}")
                expired = (hard_deadline is not None
                           and time.time() > hard_deadline)
                if board.complete() or expired:
                    manifest = {"results": {
                        str(i): g for i, (_, g) in
                        sorted(board.results.items())}}
                    if expired and not board.complete():
                        manifest["cancelled"] = True
                        log.warning(
                            "sched run %s: deadline hit with %d/%d "
                            "items", run_id, done_n, n_items)
                    client.key_value_set(f"{R}ctl/done",
                                         json.dumps(manifest),
                                         allow_overwrite=True)
                    break
            elif hard_deadline is not None and \
                    time.time() > hard_deadline + 60.0:
                # coordinator never published done (it died): the
                # driver is gone, return what we have
                log.error("sched run %s: no done manifest past "
                          "deadline; abandoning", run_id)
                manifest = {"results": {}}
                break
            tick += 1
            time.sleep(poll_s)

        # -- install phase: identical on every process -----------------
        out: Dict[int, dict] = {}
        for sidx, gen in sorted(manifest.get("results", {}).items(),
                                key=lambda kv: int(kv[0])):
            idx = int(sidx)
            # the manifest only lists accepted results, whose meta was
            # published before acceptance — a bounded wait, not a poll
            meta = json.loads(client.blocking_key_value_get(
                f"{R}rmeta/{idx}/{int(gen)}", _BLOB_TIMEOUT_MS))
            blob = None
            if meta.get("ok"):
                blob = _fetch_parts(client, f"{R}rblob/{idx}/{gen}/",
                                    int(meta.get("parts", 0)))
                if blob is None:
                    raise RuntimeError(
                        f"UNAVAILABLE: sched run {run_id} result {idx} "
                        "blob never became readable")
            out[idx] = {"ok": bool(meta.get("ok")),
                        "error": str(meta.get("error") or ""),
                        "data": blob}
    return out


# ---------------------------------------------------------------- helpers

def lower_to_bytes(obj) -> bytes:
    """Device-independent pickle (io/persist _DeviceLoweringPickler) —
    the result-payload encoder every scheduled producer uses."""
    import io as _io
    import pickle
    from h2o3_tpu.io.persist import _DeviceLoweringPickler
    buf = _io.BytesIO()
    _DeviceLoweringPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
    return buf.getvalue()


def from_bytes(data: bytes):
    import pickle
    return pickle.loads(data)


def detach_model(m):
    """Drop a freshly-trained model (and its CV submodels) from the
    trainer's local DKV — every process re-installs from the
    round-tripped result bytes so DKV state is identical cloud-wide."""
    from h2o3_tpu.core.kv import DKV
    for cm in getattr(m, "_cv_models", None) or []:
        DKV.remove(cm.key)
    DKV.remove(m.key)
    return m


def install_model(m):
    """Install a round-tripped model under a fresh process-local key
    (model keys are process-local counters, never part of the parity
    contract); CV submodels re-key relative to it like ml/cv.py does."""
    from h2o3_tpu.core.kv import DKV, make_key
    new_key = make_key(f"model_{m.algo}")
    for j, cm in enumerate(getattr(m, "_cv_models", None) or []):
        cm.key = f"{new_key}_cv_{j + 1}"
        DKV.put(cm.key, cm)
    m.key = new_key
    DKV.put(new_key, m)
    return m


def sweep_keys() -> None:
    """Delete every scheduler KV key (cloud shutdown sweep — a re-formed
    cloud must not observe a previous run's leases)."""
    client = _kv()
    if client is None:
        return
    try:
        client.key_value_delete(KV_PREFIX)
    except Exception:        # noqa: BLE001 - best-effort sweep
        pass
