"""Device-mesh management — the TPU-native replacement for H2O "clouding".

Reference: the cloud is N symmetric JVMs agreeing on membership via
heartbeat gossip (water/Paxos.java:27, water/HeartBeatThread.java:16) and
reducing over a binary node tree (water/MRTask.java:716-756). TPU-native:
membership is ``jax.distributed`` (control plane), the node tree is a
``jax.sharding.Mesh`` and every reduce is an XLA collective over ICI/DCN.

Axes:
- ``data``  — row-sharding axis; the analogue of H2O's chunk-to-node hash
  distribution (water/fvec/Vec.java chunk homing). All MRTask-style work
  shards rows over it and reduces with ``psum``.
- ``model`` — reserved width-sharding axis (wide Gram matrices for GLM with
  huge one-hot spaces; SURVEY §2.4 item 6). Size 1 on small meshes.

Multi-slice pods map as mesh shape (dcn_slices, ici_chips_per_slice)
flattened into ('data','model'); shardings are laid out so psum rides ICI
first (innermost axis varies fastest across a slice).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

if hasattr(jax, "shard_map"):                       # jax >= 0.6
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        """Old-jax adapter: jax.experimental.shard_map spells the VMA
        check flag ``check_rep``; everything else is call-compatible."""
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

DATA_AXIS = "data"
MODEL_AXIS = "model"

_GLOBAL_MESH: Optional[Mesh] = None


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              data_axis: int = 0, model_axis: int = 1) -> Mesh:
    """Build the (data, model) mesh. data_axis=0 ⇒ use all devices."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if data_axis <= 0:
        data_axis = n // model_axis
    assert data_axis * model_axis <= n, (
        f"mesh {data_axis}x{model_axis} needs more than {n} devices")
    dev = np.array(devices[: data_axis * model_axis]).reshape(
        data_axis, model_axis)
    return Mesh(dev, (DATA_AXIS, MODEL_AXIS))


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    """Install the process mesh; ``None`` resets so the next
    ``get_mesh()`` (or ``init()``) rebuilds from current devices —
    cloud.shutdown() must not leave a stale mesh behind."""
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


# per-thread mesh override (parallel/scheduler.py worker loops): scheduled
# work items train against a mesh over the process's LOCAL devices so a fit
# never issues a cross-process collective — a dead peer then cannot wedge
# it, and a single local device matches the single-process reference mesh
# bit-for-bit (the scheduler's determinism contract)
_MESH_OVERRIDE: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("h2o3tpu_mesh_override", default=None)


def get_mesh() -> Mesh:
    """The process mesh (analogue of the static H2O.CLOUD, water/H2O.java)."""
    global _GLOBAL_MESH
    override = _MESH_OVERRIDE.get()
    if override is not None:
        return override
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = make_mesh()
    return _GLOBAL_MESH


@contextlib.contextmanager
def local_mesh_scope(model_axis: int = 1):
    """Route every ``get_mesh()`` in this thread to a mesh over
    ``jax.local_devices()`` — the execution context for scheduled work
    items (each host trains its leased combos on its own chips while the
    global mesh stays reserved for collective-plane work)."""
    mesh = make_mesh(jax.local_devices(), model_axis=model_axis)
    token = _MESH_OVERRIDE.set(mesh)
    try:
        yield mesh
    finally:
        _MESH_OVERRIDE.reset(token)


def data_size(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return mesh.shape[DATA_AXIS]


def row_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Rows sharded over 'data', everything else replicated."""
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P())


def padded_rows(n: int, mesh: Optional[Mesh] = None, block: int = 1) -> int:
    """Rows padded so every data-shard holds an equal, block-aligned count,
    then rounded up to a shape BUCKET: at most 16 distinct padded sizes
    per power of two (≤6.25% padding waste).

    The alignment is the analogue of H2O chunk alignment
    (water/fvec/Vec.java ESPC layout); the bucketing is pure XLA
    economics — every distinct row count is a fresh compilation, and
    workflows like k-fold CV produce many near-identical sizes
    (n·(k-1)/k for k=2..10) that would otherwise each pay the 20-40s
    trace+compile. Padding rows carry weight 0 so reductions ignore
    them; all math paths already mask by weight.
    """
    d = data_size(mesh) * max(block, 1)
    aligned = ((n + d - 1) // d) * d
    if aligned <= 4 * d:
        return aligned
    # small frames: 4 buckets/octave (≤25% padding waste, trivial compute
    # at this scale) — k-fold CV on a small frame otherwise compiles a
    # fresh program per fold size; large frames: 16/octave (≤6.25%)
    shift = 3 if aligned < 65536 else 5
    q = 1 << (max(aligned.bit_length() - shift, 0))
    bucket = ((aligned + q - 1) // q) * q
    # keep mesh/block alignment after bucketing
    return ((bucket + d - 1) // d) * d


def global_fit_mode() -> str:
    """The ``H2O3TPU_GLOBAL_FIT`` knob: ``auto`` (default) | ``on`` |
    ``off``. Gates host-partitioned frame placement (each process homes
    only its own row shards) vs the legacy fully-replicated ingest where
    every process holds the complete host copy. ``auto`` and ``on`` are
    equivalent today (partitioned placement whenever the caller uses the
    partitioned ingest surface); ``off`` devolves partitioned ingest to
    the legacy replicated layout. The single-process path is bit-identical
    in every mode — partitioning one process's rows is the identity."""
    mode = os.environ.get("H2O3TPU_GLOBAL_FIT")
    if not mode:
        from h2o3_tpu.core.config import ARGS
        mode = getattr(ARGS, "global_fit", "auto") or "auto"
    mode = str(mode).lower()
    return mode if mode in ("auto", "on", "off") else "auto"


def global_fit_enabled() -> bool:
    """True when frames may keep host-partitioned device data."""
    return global_fit_mode() != "off"


def partition_bounds(npad: int, mesh: Optional[Mesh] = None) -> Tuple[int, int]:
    """This process's contiguous padded row range ``[lo, hi)`` under
    ``row_sharding(mesh)`` — the shard-homing contract: global row *i*
    lives on the process whose bounds contain it (the analogue of
    water/fvec/Vec.java chunk homing, ESPC layout). Raises if this
    process's addressable shards do not tile one contiguous interval
    (never the case for the process-major device order jax builds)."""
    mesh = mesh or get_mesh()
    sh = row_sharding(mesh)
    spans = set()
    for idx in sh.addressable_devices_indices_map((int(npad),)).values():
        s = idx[0]
        spans.add((s.start or 0, int(npad) if s.stop is None else s.stop))
    spans = sorted(spans)
    lo, hi = spans[0][0], spans[0][0]
    for start, stop in spans:
        if start > hi:
            raise ValueError(
                f"non-contiguous local row shards {spans} — partitioned "
                "ingest requires process-major device order")
        hi = max(hi, stop)
    return lo, hi


def owned_rows(nrows: int, mesh: Optional[Mesh] = None, block: int = 1,
               pad_to: Optional[int] = None) -> Tuple[int, int]:
    """The logical (unpadded) row range ``[lo, hi)`` this process must
    supply to a partitioned ingest of an ``nrows``-row frame — what a
    multi-host reader asks before loading its slice of the source (the
    PR 12 ingest chunk-boundary contract, io/chunking.py). Clipped to
    ``nrows``: a process whose shards are pure mesh padding gets an
    empty range."""
    npad = padded_rows(nrows, mesh, block)
    if pad_to is not None:
        npad = max(npad, int(pad_to))
    lo, hi = partition_bounds(npad, mesh)
    return min(lo, nrows), min(hi, nrows)


def put_partitioned(local_block, sharding, global_shape):
    """Assemble a global row-sharded array from ONLY this process's rows.

    ``local_block`` is the padded local slab covering this process's
    ``partition_bounds`` range; no process ever materializes (or ships)
    another process's rows — the host-partitioned complement of
    ``put_sharded``'s replicated-ingest contract. Single process: the
    slab IS the full array, so this degenerates to device_put (bit-
    identical to put_sharded)."""
    import numpy as _np
    local_block = _np.asarray(local_block)
    global_shape = tuple(int(s) for s in global_shape)
    if getattr(sharding, "is_fully_addressable", True):
        assert local_block.shape[0] == global_shape[0], (
            f"single-process slab {local_block.shape} != {global_shape}")
        return jax.device_put(local_block, sharding)
    imap = sharding.addressable_devices_indices_map(global_shape)
    lo = min((idx[0].start or 0) for idx in imap.values())
    shards = []
    for dev, idx in imap.items():
        s = idx[0]
        start = (s.start or 0) - lo
        stop = (global_shape[0] if s.stop is None else s.stop) - lo
        shards.append(jax.device_put(local_block[start:stop], dev))
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, shards)


def put_sharded(host_array, sharding):
    """Place a host array onto a (possibly multi-process) sharding.

    Single process: plain device_put. Multi-process (jax.distributed
    cloud — the @CloudSize(n) tier): every process holds the SAME full
    host array (deterministic ingest), so each contributes its
    addressable shards via make_array_from_callback — the analogue of
    chunks parsing on their home nodes (water/parser/ParseDataset).
    When each process holds ONLY its own rows, use ``put_partitioned``
    (the H2O3TPU_GLOBAL_FIT host-partitioned ingest path)."""
    import numpy as _np
    import time as _time
    from h2o3_tpu.telemetry import stepprof as _sp
    _t0 = _time.perf_counter()
    try:
        if getattr(sharding, "is_fully_addressable", True):
            return jax.device_put(host_array, sharding)
        if isinstance(host_array, jax.Array):
            # already a global device array: reshard (device-to-device),
            # never pull through the host
            if host_array.sharding == sharding:
                return host_array
            return jax.device_put(host_array, sharding)
        host_array = _np.asarray(host_array)
        return jax.make_array_from_callback(
            host_array.shape, sharding, lambda idx: host_array[idx])
    finally:
        # wall-clock annotation on an active fit profile (stepprof
        # marks are NOT part of the phase partition — they say where
        # host time went, they don't re-charge it)
        _sp.mark("put_sharded_seconds", _time.perf_counter() - _t0)


FETCH_CALLS = 0      # observability: device→host fetches (tests assert
#                      device pipelines never materialize on controller)


def fetch_replicated(x):
    """Device→host fetch that works on cross-process sharded arrays.

    Single process: device_get. Multi-process: allgather the shards so
    every host sees the full array (water/MRTask postGlobal view)."""
    global FETCH_CALLS
    FETCH_CALLS += 1
    import time as _time
    from h2o3_tpu.telemetry import stepprof as _sp
    _t0 = _time.perf_counter()
    try:
        leaves = jax.tree_util.tree_leaves(x)
        if all(getattr(getattr(v, "sharding", None),
                       "is_fully_addressable", True) for v in leaves):
            return jax.device_get(x)
        from jax.experimental import multihost_utils
        return jax.device_get(multihost_utils.process_allgather(
            x, tiled=True))
    finally:
        _sp.mark("fetch_replicated_seconds",
                 _time.perf_counter() - _t0)


def shard_rows(x, mesh: Optional[Mesh] = None, block: int = 1,
               fill: float = 0.0):
    """Pad axis-0 to a shardable length and place with row_sharding.

    Placement goes through put_sharded: on a multi-process cloud a raw
    device_put onto a non-addressable sharding pays a cross-process
    assert_equal broadcast per call (and on CPU without collectives it
    simply fails — the old multiprocess-CPU standing failure)."""
    mesh = mesh or get_mesh()
    n = x.shape[0]
    npad = padded_rows(n, mesh, block)
    if npad != n:
        pad_widths = [(0, npad - n)] + [(0, 0)] * (x.ndim - 1)
        x = np.pad(np.asarray(x), pad_widths, constant_values=fill)
    return put_sharded(x, row_sharding(mesh))


def valid_mask(n: int, npad: int, mesh: Optional[Mesh] = None):
    """float32 1/0 mask marking real rows among padded."""
    m = np.zeros((npad,), dtype=np.float32)
    m[:n] = 1.0
    return put_sharded(m, row_sharding(mesh))
