"""Model batching — vmap hyperparameter combos into ONE compiled program.

`hex.grid`'s analogue (ml/grid.py) and the AutoML executor train combos
sequentially: a 50-combo grid pays 50 dispatch/readback round trips
while the mesh idles between models — the "driver-bound outer loop"
DrJAX (PAPERS.md) eliminates by expressing the whole sweep as one
compiled MapReduce program, and the batched-learner layout GPU
tree-boosting systems use. The per-model hot loops are already fused
(GBM `_boost_scan_jit`, GLM `_irls_solve`) and already carry their
numeric knobs as TRACED values (gbm `_knobs_of`), so the missing layer
is exactly this module: group combos into SHAPE BUCKETS (same
structural/static knobs → same compiled program), stack their numeric
knobs and PRNG keys, and train the whole bucket as one jitted
``vmap``-over-knobs program.

Eligibility is knob-driven: ``BATCHABLE_KNOBS[algo]`` lists the hyper
parameters that may vary WITHIN a bucket (they ride on the vmapped
axis); any other varying knob is structural and splits buckets. A
bucket the per-algo trainer cannot vmap raises ``BatchIneligible`` and
the caller (ml/grid.py) falls back to the sequential per-combo path,
so grid semantics, early stopping, recovery snapshots and leaderboard
order are always preserved.

Knob: ``H2O3TPU_BATCH_MODELS`` = ``auto`` (default, batch eligible
buckets of >= 2 combos) | ``off``/``0`` (always sequential).

Telemetry (stable names, README §Batched training):
``batched_train_batches_total{algo}``, ``batched_train_width{algo}``
(histogram), ``grid_models_total{algo,path}``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.model_batch")


class BatchIneligible(Exception):
    """The combo set cannot be trained as one vmapped program; the
    caller must fall back to the sequential per-combo path."""


# hyper parameters that may vary WITHIN one shape bucket — each rides as
# a traced value (or a PRNG key) on the vmapped model axis of the
# compiled program. Anything else that varies is structural: it would
# change the compiled program (static jit key, tree shapes, solver
# family) and therefore keys the bucket instead.
BATCHABLE_KNOBS: Dict[str, frozenset] = {
    # gbm: _knobs_of() already hoists these out of the static jit key;
    # max_depth batches WITHIN a compile bucket (tree.py DEPTH_BUCKETS —
    # the program compiles at the bucket depth with a traced limit)
    "gbm": frozenset({"learn_rate", "sample_rate",
                      "col_sample_rate_per_tree", "min_rows",
                      "min_split_improvement", "reg_lambda", "seed",
                      "max_depth"}),
    # glm: the (alpha, lambda) product enters _irls_solve as traced
    # l1/l2 scalars; every other knob changes the solve family/design
    "glm": frozenset({"alpha", "lambda_", "Lambda", "lambda"}),
}


def mode() -> str:
    """Resolved ``H2O3TPU_BATCH_MODELS`` value (env wins over config)."""
    v = os.environ.get("H2O3TPU_BATCH_MODELS")
    if v is None:
        from h2o3_tpu.core.config import ARGS
        v = getattr(ARGS, "batch_models", "auto")
    return str(v).strip().lower() or "auto"


def enabled() -> bool:
    return mode() not in ("0", "off", "false", "no")


def row_bucket(n: int, max_rows: int) -> int:
    """Serving face of the shape-bucket planner: the padded row count a
    predict batch of ``n`` logical rows compiles at.

    Powers of two from 8 up to the first power of two >= ``max_rows``
    (the ``H2O3TPU_SCORE_BATCH_MAX_ROWS`` cap), so a storm of
    variably-sized micro-batches converges on a handful of compiled
    programs per model instead of one trace per distinct row count —
    the same geometric-bucket argument as DEPTH_BUCKETS in the tree
    layer. Always a multiple of the 8-row mesh block, so
    ``Frame.from_numpy(pad_to=bucket)`` pads to exactly the bucket.
    """
    n = max(int(n), 1)
    cap = max(int(max_rows), 1)
    b = 8
    while b < n and b < cap:
        b <<= 1
    return b


def _canon(v):
    """Hashable canonical form of a hyper value (JSON round trips lists)."""
    if isinstance(v, (list, tuple)):
        return tuple(_canon(x) for x in v)
    return v


def combo_key(combo: dict) -> tuple:
    """Canonical identity of a combo — sorted items with list values
    tupled, so resume filtering is one set lookup per combo instead of
    the O(n·m) dict-equality scan (ml/grid.py recovery path)."""
    return tuple(sorted((k, _canon(v)) for k, v in combo.items()))


def bucket_key(algo: str, combo: dict) -> tuple:
    """Structural signature of a combo: the non-batchable knob values
    (plus, for gbm, the compile DEPTH BUCKET of max_depth). Combos with
    equal bucket keys share one compiled program."""
    batchable = BATCHABLE_KNOBS.get(algo, frozenset())
    items: List[Tuple] = []
    for k in sorted(combo):
        if k in batchable:
            if algo == "gbm" and k == "max_depth":
                from h2o3_tpu.models.tree import bucket_depth
                items.append(("max_depth#bucket",
                              bucket_depth(int(combo[k]))))
            continue
        items.append((k, _canon(combo[k])))
    return tuple(items)


@dataclasses.dataclass
class Bucket:
    key: tuple
    indices: List[int]           # positions in the walk-ordered combo list

    @property
    def width(self) -> int:
        return len(self.indices)


def plan_buckets(algo: str, combos: Sequence[dict]) -> List[Bucket]:
    """Group walk-ordered combos into shape buckets (first-occurrence
    order; indices stay ascending so the caller can restore walk order
    after batch training)."""
    by_key: Dict[tuple, Bucket] = {}
    order: List[Bucket] = []
    for i, c in enumerate(combos):
        k = bucket_key(algo, c)
        b = by_key.get(k)
        if b is None:
            b = Bucket(key=k, indices=[])
            by_key[k] = b
            order.append(b)
        b.indices.append(i)
    return order


def _trainer_for(algo: str):
    """Per-algo batched trainer (lazy import — no cycles, and the
    planner above stays importable without a backend)."""
    if algo == "gbm":
        from h2o3_tpu.models.gbm import fit_gbm_batched
        return fit_gbm_batched
    if algo == "glm":
        from h2o3_tpu.models.glm import fit_glm_batched
        return fit_glm_batched
    return None


def train_bucket(builder_cls, fixed: dict, combos: Sequence[dict], frame,
                 y: Optional[str] = None, x=None,
                 validation_frame=None) -> List:
    """Train one shape bucket as a single vmapped program; returns one
    Model per combo, in combo order. Raises ``BatchIneligible`` when the
    algo has no batched trainer or the shared params cannot be vmapped
    (the caller falls back per-combo)."""
    algo = builder_cls.algo
    trainer = _trainer_for(algo)
    if trainer is None:
        raise BatchIneligible(f"no batched trainer for algo '{algo}'")
    params_list = [{**fixed, **c} for c in combos]
    if any(p.get("checkpoint") is not None for p in params_list):
        # a checkpointed combo extends a donor model's forest/weights —
        # per-model structural state the vmapped program cannot express;
        # the caller's sequential per-combo walk handles it
        raise BatchIneligible("checkpoint restart (per-combo fallback)")
    import time as _time
    from h2o3_tpu import telemetry
    t0 = _time.time()
    with telemetry.span("model_batch.train", algo=algo,
                        width=len(params_list)):
        models = trainer(builder_cls, params_list, frame, y=y, x=x,
                         validation_frame=validation_frame)
    telemetry.counter("batched_train_batches_total", algo=algo).inc()
    telemetry.histogram(
        "batched_train_width",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
        algo=algo).observe(float(len(models)))
    log.info("batched %s bucket: %d models in %.2fs", algo, len(models),
             _time.time() - t0)
    return models
