"""frame_map_reduce — the MRTask analogue, as one primitive.

Reference: water/MRTask.java:69 — serialize task to all nodes, split node
range as a binary tree (remote_compute, MRTask.java:716-756), split local
chunks over Fork/Join, ``map(Chunk...)`` per chunk, ``reduce`` pairwise up
both trees (MRTask.java:891). All of that machinery — RPC, ack/ackack,
F/J priorities — exists to make one thing safe: a distributed map + an
all-reduce.

TPU-native: ``shard_map`` over the 'data' mesh axis runs ``map_fn`` on each
row-shard; ``jax.lax.psum`` over the axis IS the reduce tree (XLA emits the
ICI ring/tree). Elementwise (map-only) tasks skip the psum and keep outputs
row-sharded. Local chunking (the F/J level) is either left to XLA fusion or
done with ``lax.scan`` over row blocks inside the shard when the map needs
bounded memory (see ops/histogram.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
from h2o3_tpu.parallel.mesh import shard_map
from jax.sharding import PartitionSpec as P

from h2o3_tpu import telemetry
from h2o3_tpu.core import request_ctx, watchdog
from h2o3_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, get_mesh


def _charge_reduce_payload(out, mesh) -> None:
    """MRTask telemetry: the reduce payload is the pytree the psum tree
    carries — the analogue of the reference's ack/ackack wire volume.
    Sizes come from avals (no device sync). A psum ring moves
    ~2·(n-1)/n of the payload over EACH of its n links, so the total
    collective estimate is 2·(n-1)·payload along the data axis.

    On a multi-host mesh the data-axis ring mixes link classes: a link
    whose endpoints share a process rides ICI (intra-host), one that
    crosses processes rides DCN. The counter is labeled by that scope —
    ``collective_bytes_total{scope=host|pod}`` — so the roofline/MFU
    gauges (fed the combined total via add_collective_bytes) and the
    DCN-bandwidth view stay honest when ONE fit spans the pod."""
    try:
        payload = sum(getattr(leaf, "nbytes", 0) or 0
                      for leaf in jax.tree_util.tree_leaves(out))
    except Exception:   # noqa: BLE001 - accounting must never fail the task
        return
    telemetry.histogram("frame_reduce_payload_bytes",
                        buckets=telemetry.BYTES_BUCKETS).observe(payload)
    n = mesh.shape[DATA_AXIS]
    est = 2.0 * max(n - 1, 0) * payload
    pod = 0.0
    if n > 1:
        try:
            # every model column rings over the same process layout —
            # classify the first column's n links (uniform traffic each)
            col = mesh.devices.reshape(mesh.shape[DATA_AXIS], -1)[:, 0]
            cross = sum(
                1 for i in range(n)
                if getattr(col[i], "process_index", 0)
                != getattr(col[(i + 1) % n], "process_index", 0))
            pod = est * cross / n
        except Exception:   # noqa: BLE001 - accounting must never fail
            pod = 0.0
    telemetry.counter("collective_bytes_total", scope="host").inc(est - pod)
    telemetry.counter("collective_bytes_total", scope="pod").inc(pod)
    telemetry.add_collective_bytes(est)


def frame_reduce(map_fn: Callable[..., Any], *arrays, mesh=None) -> Any:
    """All-reduce of ``map_fn`` applied per row-shard.

    ``map_fn(*local_arrays) -> pytree of stats``; every leaf is summed over
    the data axis. Equivalent of MRTask.doAll + reduce (water/MRTask.java).
    """
    mesh = mesh or get_mesh()
    # fault-injection site: a dispatch onto a wedged/restarted worker
    # dies here with INTERNAL/UNAVAILABLE — tier-1 tests plant that
    # failure (watchdog.inject_fault) to exercise the job-level retries
    watchdog.maybe_fail("frame_reduce")
    # chunk boundary: the one place a cancelled/expired request — or an
    # unhealthy cloud (core/heartbeat.py) — can be observed without
    # preempting compiled code (a scan only yields between dispatches).
    # A cancel or deadline frees this worker within one chunk; a
    # heartbeat-declared dead peer fails the job HERE with
    # CloudUnhealthyError instead of hanging forever inside the psum
    request_ctx.cancel_point("frame_reduce")
    telemetry.counter("frame_reduce_total").inc()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=tuple(P(DATA_AXIS) for _ in arrays),
        out_specs=P(),
        check_vma=False)
    def _task(*local):
        stats = map_fn(*local)
        return jax.tree_util.tree_map(
            lambda s: jax.lax.psum(s, DATA_AXIS), stats)

    from h2o3_tpu.telemetry import stepprof
    _t0 = stepprof.t_mark()
    with telemetry.span("mr.frame_reduce"):
        out = _task(*arrays)
    # charge the reduce wait to an active fit profile's collective
    # phase — this is where a fast host waits on a straggler's psum
    stepprof.collective_done(out, _t0)
    _charge_reduce_payload(out, mesh)
    return out


def frame_map(map_fn: Callable[..., Any], *arrays, mesh=None) -> Any:
    """Elementwise over rows; output stays row-sharded (map-only MRTask)."""
    mesh = mesh or get_mesh()
    watchdog.maybe_fail("frame_map")
    request_ctx.cancel_point("frame_map")
    telemetry.counter("frame_map_total").inc()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=tuple(P(DATA_AXIS) for _ in arrays),
        out_specs=P(DATA_AXIS),
        check_vma=False)
    def _task(*local):
        return map_fn(*local)

    with telemetry.span("mr.frame_map"):
        return _task(*arrays)
