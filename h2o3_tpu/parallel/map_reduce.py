"""frame_map_reduce — the MRTask analogue, as one primitive.

Reference: water/MRTask.java:69 — serialize task to all nodes, split node
range as a binary tree (remote_compute, MRTask.java:716-756), split local
chunks over Fork/Join, ``map(Chunk...)`` per chunk, ``reduce`` pairwise up
both trees (MRTask.java:891). All of that machinery — RPC, ack/ackack,
F/J priorities — exists to make one thing safe: a distributed map + an
all-reduce.

TPU-native: ``shard_map`` over the 'data' mesh axis runs ``map_fn`` on each
row-shard; ``jax.lax.psum`` over the axis IS the reduce tree (XLA emits the
ICI ring/tree). Elementwise (map-only) tasks skip the psum and keep outputs
row-sharded. Local chunking (the F/J level) is either left to XLA fusion or
done with ``lax.scan`` over row blocks inside the shard when the map needs
bounded memory (see ops/histogram.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, get_mesh


def frame_reduce(map_fn: Callable[..., Any], *arrays, mesh=None) -> Any:
    """All-reduce of ``map_fn`` applied per row-shard.

    ``map_fn(*local_arrays) -> pytree of stats``; every leaf is summed over
    the data axis. Equivalent of MRTask.doAll + reduce (water/MRTask.java).
    """
    mesh = mesh or get_mesh()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=tuple(P(DATA_AXIS) for _ in arrays),
        out_specs=P(),
        check_vma=False)
    def _task(*local):
        stats = map_fn(*local)
        return jax.tree_util.tree_map(
            lambda s: jax.lax.psum(s, DATA_AXIS), stats)

    return _task(*arrays)


def frame_map(map_fn: Callable[..., Any], *arrays, mesh=None) -> Any:
    """Elementwise over rows; output stays row-sharded (map-only MRTask)."""
    mesh = mesh or get_mesh()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=tuple(P(DATA_AXIS) for _ in arrays),
        out_specs=P(DATA_AXIS),
        check_vma=False)
    def _task(*local):
        return map_fn(*local)

    return _task(*arrays)
