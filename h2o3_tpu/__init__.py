"""h2o3_tpu — a TPU-native, JAX/XLA-based reimplementation of the H2O-3
distributed ML platform (reference: sashashura/h2o-3).

The reference is a JVM cluster: Frame/Vec/Chunk columnar store + MRTask
map/reduce (h2o-core/src/main/java/water/MRTask.java) + hex.* algorithms.
Here the same capabilities are rebuilt TPU-first:

- Frame        = dict of dtype-narrowed device arrays sharded over a
                 ``jax.sharding.Mesh`` 'data' axis (replaces water.fvec).
- map/reduce   = ``shard_map`` + ``psum`` over ICI (replaces the MRTask
                 node tree + Fork/Join, water/MRTask.java:716-756).
- algorithms   = jitted JAX programs (histogram GBM/DRF on the MXU, GLM via
                 einsum Gram + Cholesky, DeepLearning as an MLP, ...).
- REST surface = the /3 and /99 JSON API kept compatible in spirit with
                 water.api.RequestServer so h2o-py-style clients can drive it.

Public API mirrors the h2o-py module surface (h2o-py/h2o/h2o.py):
``init``, ``import_file``, ``H2OFrame``-like ``Frame``, estimator classes.
"""

try:
    # pandas >= 3.0 backs str columns with pyarrow; libarrow segfaults
    # under this image's threading profile (observed: handler threads in
    # the REST server dying inside libarrow.so during frame ops). Python
    # string storage sidesteps the native library entirely — string work
    # is host-side control plane here, never the hot path.
    import pandas as _pd
    _pd.set_option("mode.string_storage", "python")
except Exception:
    pass

from h2o3_tpu.version import __version__
from h2o3_tpu.core.cloud import init, cluster_info, shutdown
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.io.parser import (export_file, import_file, parse_raw,
                                upload_numpy)
from h2o3_tpu.io.sql import import_sql_select, import_sql_table
from h2o3_tpu.io.persist import (load_frame, load_model, persist_manager,
                                 save_frame, save_model)
from h2o3_tpu.core.kv import DKV
from h2o3_tpu.core.memgov import MemoryBudgetExceeded
from h2o3_tpu.core.scope import Scope
from h2o3_tpu.core.udf import (upload_custom_distribution,
                               upload_custom_metric)

__all__ = [
    "__version__",
    "upload_custom_distribution",
    "upload_custom_metric",
    "init",
    "cluster_info",
    "shutdown",
    "Frame",
    "import_file",
    "export_file",
    "import_sql_select",
    "import_sql_table",
    "parse_raw",
    "upload_numpy",
    "DKV",
    "MemoryBudgetExceeded",
    "save_frame",
    "load_frame",
    "save_model",
    "load_model",
    "persist_manager",
]
