"""Rapids — the Lisp-like dataframe expression language.

Reference: water/rapids/Rapids.java:27 (parser), water/rapids/Env.java
(scopes + Val types Frame/Num/Str/Seq), ~100 primitives under
water/rapids/ast/prims/{mungers,math,matrix,reducers,operators,...}.
h2o-py builds these expression strings client-side (h2o-py/h2o/expr.py)
and ships them to POST /99/Rapids; this module is the server-side
interpreter.

Execution is eager: structural ops manipulate Column/Frame metadata;
group-by aggregates run as one segment_sum per aggregate over the mesh
(the AstGroup MRTask role). Host numpy carries the remaining munging ops
— they are metadata-scale, not the benchmark hot path, mirroring the
reference's driver-node finalization for merge/sort.

Grammar (Rapids.java:27-52):
  expr := '(' op expr* ')' | number | "string" | id | '[' elems ']'
"""

from __future__ import annotations

import math
import os as _os
import re as _re
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from h2o3_tpu.parallel.mesh import fetch_replicated as _fetch_np

from h2o3_tpu.core.kv import DKV
from h2o3_tpu.frame.column import Column, T_CAT, T_NUM, T_STR, T_UUID
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as mesh_mod

# ---------------------------------------------------------------- parser


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def peek(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1
        return self.s[self.i] if self.i < len(self.s) else ""

    def parse(self):
        c = self.peek()
        if c == "(":
            self.i += 1
            items = []
            while self.peek() not in (")", ""):
                items.append(self.parse())
            self.i += 1
            return items
        if c == "[":
            self.i += 1
            items = []
            while self.peek() not in ("]", ""):
                items.append(self.parse())
            self.i += 1
            return ("list", items)
        if c in ("'", '"'):
            quote = c
            self.i += 1
            out = []
            while self.i < len(self.s) and self.s[self.i] != quote:
                ch = self.s[self.i]
                if ch == "\\":
                    self.i += 1
                    ch = self.s[self.i]
                out.append(ch)
                self.i += 1
            self.i += 1
            return ("str", "".join(out))
        j = self.i
        while (j < len(self.s)
               and not self.s[j].isspace() and self.s[j] not in "()[]"):
            j += 1
        tok = self.s[self.i:j]
        self.i = j
        if tok in ("TRUE", "True", "true"):
            return ("num", 1.0)
        if tok in ("FALSE", "False", "false"):
            return ("num", 0.0)
        try:
            return ("num", float(tok))
        except ValueError:
            pass
        # 'lo:cnt[:step]' range inside number lists (AstNumList range
        # syntax: cnt elements starting at lo, stride step; cnt may be
        # 'nan' = through the end — h2o-py serializes Python slices this
        # way, h2o-py/h2o/expr.py _arg_to_expr)
        m = _re.fullmatch(
            r"(-?\d+(?:\.\d+)?):(nan|-?\d+(?:\.\d+)?)(?::(-?\d+))?", tok)
        if m:
            return ("range", float(m.group(1)), float(m.group(2)),
                    int(m.group(3) or 1))
        return ("id", tok)


def parse(expr: str):
    return _Parser(expr).parse()




# ---------------------------------------------------------------- session


class Session:
    """Rapids session: tmp-frame scope (water/rapids/Session.java)."""

    def __init__(self):
        self.tmp: Dict[str, Any] = {}

    def lookup(self, name: str):
        if name in self.tmp:
            return self.tmp[name]
        v = DKV.get(name)
        if v is None:
            raise KeyError(f"Rapids: unknown id '{name}'")
        return v

    def assign(self, name: str, val):
        self.tmp[name] = val
        if isinstance(val, Frame):
            DKV.put(name, val)

    def rm(self, name: str):
        self.tmp.pop(name, None)
        DKV.remove(name)


# --------------------------------------------------------- value helpers


def _as_frame(v) -> Frame:
    if isinstance(v, Frame):
        return v
    if isinstance(v, (int, float)):
        return Frame.from_numpy({"C1": np.array([float(v)])})
    raise TypeError(f"expected frame, got {type(v)}")


def _col_np(frame: Frame, name: str) -> np.ndarray:
    return frame.col(name).to_numpy()


def _cat_codes(frame: Frame, name: str) -> np.ndarray:
    c = frame.col(name)
    codes = _fetch_np(c.data)[: frame.nrows].astype(np.int32).copy()
    codes[_fetch_np(c.na_mask)[: frame.nrows]] = -1
    return codes


def _rebuild(frame: Frame, arrays: Dict[str, np.ndarray],
             keep_domains: bool = True) -> Frame:
    cats, doms = [], {}
    for n in arrays:
        if keep_domains and n in frame and frame.col(n).is_categorical \
                and arrays[n].dtype.kind not in "OUS":
            cats.append(n)
            doms[n] = frame.col(n).domain
        elif arrays[n].dtype == object:
            cats.append(n)
    return Frame.from_numpy(arrays, categorical=cats, domains=doms)


def _take_rows(f: Frame, idx: np.ndarray) -> Frame:
    arrays, cats, doms = {}, [], {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            arrays[n] = _cat_codes(f, n)[idx]
            cats.append(n)
            doms[n] = c.domain
        elif c.type == "string":
            arrays[n] = c.to_numpy()[idx]
        else:
            arrays[n] = _col_np(f, n)[idx]
    return Frame.from_numpy(arrays, categorical=cats, domains=doms)


def _broadcast2(l, r):
    if isinstance(l, Frame) and isinstance(r, Frame):
        if l.ncols == 1 and r.ncols > 1:
            a = _col_np(l, l.names[0])
            return {n: (a, _col_np(r, n)) for n in r.names}
        if r.ncols == 1 and l.ncols > 1:
            b = _col_np(r, r.names[0])
            return {n: (_col_np(l, n), b) for n in l.names}
        assert l.ncols == r.ncols, "ncols mismatch"
        return {n: (_col_np(l, n), _col_np(r, m))
                for n, m in zip(l.names, r.names)}
    if isinstance(l, Frame):
        return {n: (_col_np(l, n), r) for n in l.names}
    if isinstance(r, Frame):
        return {n: (l, _col_np(r, n)) for n in r.names}
    return {"C1": (l, r)}


# ------------------------------------------------- device elementwise
#
# Elementwise prims on frames at or above this row count run on the
# device mesh instead of fetching to the controller (the reference runs
# every prim as an MRTask — water/rapids/ast/prims/mungers/AstGroup.java
# pattern; at 116M rows a controller fetch per op is the difference
# between an in-HBM pipeline and shipping the frame over the wire).
# Below the threshold the exact host-float64 path runs: reference
# pyunits assert f64-exact results that f32 device math can miss.
_DEV_MIN_ROWS = int(_os.environ.get("H2O3TPU_RAPIDS_DEVICE_ROWS", "1000000"))

DEV_OPS = 0      # observability: prims served by the device path (tests
#                  assert scale ops don't silently fall back to host)


def _dev_hit():
    global DEV_OPS
    DEV_OPS += 1
    from h2o3_tpu import telemetry
    telemetry.counter("rapids_device_ops_total").inc()

# dtypes safe in the f32 device path: values exact in a 24-bit mantissa.
# int32/time columns can exceed 2^24 (epoch millis certainly do) and
# stay on the host f64 path; cat codes are always < 2^24.
_DEV_SAFE_DTYPES = ("int8", "int16", "float32", "bfloat16", "uint8")


def _dev_col_ok(c: Column) -> bool:
    if c.type == T_CAT:
        return True
    if c.type != T_NUM or c.data is None:
        return False
    return str(c.data.dtype) in _DEV_SAFE_DTYPES


def _dev_eligible(*vals) -> bool:
    """True when every Frame operand is large, same-shape, and device-safe."""
    frames = [v for v in vals if isinstance(v, Frame)]
    if not frames or any(f.nrows < _DEV_MIN_ROWS for f in frames):
        return False
    if len({f.nrows for f in frames}) > 1:
        return False
    shapes = set()
    for f in frames:
        for n in f.names:
            c = f.col(n)
            if not _dev_col_ok(c):
                return False
            shapes.add(int(c.data.shape[0]))
    return len(shapes) == 1


import functools as _functools


def _kernel_view(d, m):
    """NaN-injected f32 view — same NA encoding as the host f64 path, so
    every ufunc reproduces host semantics (NaN propagation in arithmetic,
    False comparisons on NA) on device. Trace-time helper: only ever
    called inside the jitted kernels below."""
    import jax.numpy as jnp
    return jnp.where(m, jnp.nan, d.astype(jnp.float32))


def _kernel_seal(out, nrows):
    """(data, mask) result pair: NA where NaN, plus the padding tail —
    comparisons map NaN-injected padding back to 0.0 (NaN < x is False),
    which would otherwise read as valid rows."""
    import jax.numpy as jnp
    out = jnp.asarray(out, jnp.float32)
    pad = jnp.arange(out.shape[0], dtype=jnp.int32) >= nrows
    return out, jnp.isnan(out) | pad


@_functools.lru_cache(maxsize=None)
def _binop_kernel(name: str, kind: str):
    """ONE jitted program per (op, operand-kind): the whole
    view→op→seal chain fuses, so each prim costs one compile per shape
    instead of ~5 eager sub-op compiles (the 10M-row scale test was
    compile-bound, not compute-bound)."""
    import jax
    op = _jnp_binops()[0][name]
    if kind == "ff":
        def k(da, ma, db, mb, nrows):
            return _kernel_seal(op(_kernel_view(da, ma),
                                   _kernel_view(db, mb)), nrows)
    elif kind == "fs":
        def k(da, ma, s, nrows):
            return _kernel_seal(op(_kernel_view(da, ma), s), nrows)
    else:
        def k(s, db, mb, nrows):
            return _kernel_seal(op(s, _kernel_view(db, mb)), nrows)
    return jax.jit(k)


@_functools.lru_cache(maxsize=None)
def _unop_kernel(name: str):
    import jax
    op = _jnp_binops()[1][name]

    def k(d, m, nrows):
        return _kernel_seal(op(_kernel_view(d, m)), nrows)
    return jax.jit(k)


@_functools.lru_cache(maxsize=None)
def _isna_kernel():
    import jax
    import jax.numpy as jnp

    def k(m, nrows):
        pad = jnp.arange(m.shape[0], dtype=jnp.int32) >= nrows
        return m.astype(jnp.float32), pad
    return jax.jit(k)


@_functools.lru_cache(maxsize=None)
def _ifelse_kernel(ykind: str, nkind: str):
    """kinds: 'f' frame (data+mask args) or 's' numeric scalar."""
    import jax
    import jax.numpy as jnp

    def k(td, tm, *rest):
        i = 0
        tv = _kernel_view(td, tm)
        if ykind == "f":
            yv = _kernel_view(rest[0], rest[1]); i = 2
        else:
            yv = rest[0]; i = 1
        if nkind == "f":
            nv = _kernel_view(rest[i], rest[i + 1]); i += 2
        else:
            nv = rest[i]; i += 1
        nrows = rest[i]
        o = jnp.where(jnp.nan_to_num(tv) != 0, yv, nv)
        o = jnp.where(jnp.isnan(tv), jnp.nan, o)
        return _kernel_seal(o, nrows)
    return jax.jit(k)


@_functools.lru_cache(maxsize=None)
def _reduce_kernel(name: str):
    import jax
    import jax.numpy as jnp

    def k(d, m, nrows):
        logical = jnp.arange(d.shape[0], dtype=jnp.int32) < nrows
        valid = logical & ~m
        x = d.astype(jnp.float32)
        # counts stay int32: an f32 ones-sum saturates at 2^24 rows,
        # understating the mean denominator on 100M-row frames
        n_na = jnp.sum((m & logical).astype(jnp.int32))
        cnt = jnp.sum(valid.astype(jnp.int32))
        if name in ("sum", "mean"):
            part = jnp.sum(jnp.where(valid, x, 0.0))
        elif name == "min":
            part = jnp.min(jnp.where(valid, x, jnp.inf))
        else:
            part = jnp.max(jnp.where(valid, x, -jnp.inf))
        return part, cnt, n_na
    return jax.jit(k)


def _dev_frame(nrows: int, outs: Dict[str, Any]) -> Frame:
    """Frame from (data, mask) device result pairs."""
    _dev_hit()
    cols = [Column(name=n, type=T_NUM, data=d, na_mask=m, nrows=nrows)
            for n, (d, m) in outs.items()]
    return Frame(cols, nrows)


def _jnp_binops():
    """name → jnp callable. Built lazily (jax import cost) and cached.
    numpy ufuncs applied to jax arrays materialize to HOST numpy (no
    __array_ufunc__ dispatch), so the device path needs its own table."""
    global _JNP_BINOPS, _JNP_UNOPS
    if _JNP_BINOPS is not None:
        return _JNP_BINOPS, _JNP_UNOPS
    import jax.numpy as jnp
    from jax import lax

    def _f32(x):
        return x.astype(jnp.float32)

    _JNP_BINOPS = {
        "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
        "/": jnp.divide, "^": jnp.power, "%": jnp.mod, "%%": jnp.mod,
        "intDiv": jnp.floor_divide, "%/%": jnp.floor_divide,
        "==": lambda a, b: _f32(jnp.equal(a, b)),
        "!=": lambda a, b: _f32(jnp.not_equal(a, b)),
        "<": lambda a, b: _f32(jnp.less(a, b)),
        "<=": lambda a, b: _f32(jnp.less_equal(a, b)),
        ">": lambda a, b: _f32(jnp.greater(a, b)),
        ">=": lambda a, b: _f32(jnp.greater_equal(a, b)),
        "&": lambda a, b: _f32((a != 0) & (b != 0)),
        "|": lambda a, b: _f32((a != 0) | (b != 0)),
    }
    _JNP_UNOPS = {
        "abs": jnp.abs, "ceiling": jnp.ceil, "floor": jnp.floor,
        "trunc": jnp.trunc, "exp": jnp.exp, "log": jnp.log,
        "log10": jnp.log10, "log1p": jnp.log1p, "log2": jnp.log2,
        "sqrt": jnp.sqrt, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
        "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
        "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
        "sign": jnp.sign,
        "not": lambda a: _f32(a == 0), "!": lambda a: _f32(a == 0),
        "cumsum": jnp.cumsum, "cumprod": jnp.cumprod,
        "cummax": lax.cummax, "cummin": lax.cummin,
    }
    return _JNP_BINOPS, _JNP_UNOPS


_JNP_BINOPS = None
_JNP_UNOPS = None


def _dev_binop(name, l, r):
    """Device path for frame⊗frame / frame⊗scalar elementwise binops.
    Returns None when ineligible (caller falls back to host f64)."""
    if name not in _jnp_binops()[0] or not _dev_eligible(l, r):
        return None
    outs = {}
    if isinstance(l, Frame) and isinstance(r, Frame):
        k = _binop_kernel(name, "ff")
        if l.ncols == 1 and r.ncols > 1:
            cl = l.col(l.names[0])
            for n in r.names:
                cr = r.col(n)
                outs[n] = k(cl.data, cl.na_mask, cr.data, cr.na_mask,
                            l.nrows)
        elif r.ncols == 1 and l.ncols > 1:
            cr = r.col(r.names[0])
            for n in l.names:
                cl = l.col(n)
                outs[n] = k(cl.data, cl.na_mask, cr.data, cr.na_mask,
                            l.nrows)
        elif l.ncols == r.ncols:
            for n, m in zip(l.names, r.names):
                cl, cr = l.col(n), r.col(m)
                outs[n] = k(cl.data, cl.na_mask, cr.data, cr.na_mask,
                            l.nrows)
        else:
            return None
    elif isinstance(l, Frame):
        k = _binop_kernel(name, "fs")
        for n in l.names:
            cl = l.col(n)
            outs[n] = k(cl.data, cl.na_mask, float(r), l.nrows)
    else:
        k = _binop_kernel(name, "sf")
        for n in r.names:
            cr = r.col(n)
            outs[n] = k(float(l), cr.data, cr.na_mask, r.nrows)
    base = l if isinstance(l, Frame) else r
    return _dev_frame(base.nrows, outs)


def _dev_unop(name, v: Frame):
    if name not in _jnp_binops()[1] or not isinstance(v, Frame) \
            or not _dev_eligible(v):
        return None
    k = _unop_kernel(name)
    outs = {}
    for n in v.names:
        c = v.col(n)
        outs[n] = k(c.data, c.na_mask, v.nrows)
    return _dev_frame(v.nrows, outs)


# ---------------------------------------------------------------- prims

PRIMS: Dict[str, Callable] = {}


def prim(*names):
    def deco(fn):
        for n in names:
            PRIMS[n] = fn
        return fn
    return deco


def _cmp_str(fr: Frame, s: str, negate: bool) -> Frame:
    """Categorical/string column vs string literal — the wire form of
    ``fr['g'] == 'x'``; matches against the domain, NA rows → NA."""
    out = {}
    for n in fr.names:
        c = fr.col(n)
        if c.is_categorical:
            try:
                code = (c.domain or []).index(s)
            except ValueError:
                code = -2
            codes = _cat_codes(fr, n).astype(np.float64)
            eq = (codes == code).astype(np.float64)
            eq[codes < 0] = np.nan
        elif c.type == "string":
            eq = np.array([np.nan if v is None else float(v == s)
                           for v in c.to_numpy()])
        else:
            eq = np.zeros(fr.nrows)   # numeric vs string: never equal
        out[n] = (1.0 - eq) if negate else eq
    return _rebuild(fr, out, keep_domains=False)


def _binop(op, name: str = ""):
    def fn(env, l, r):
        l, r = env.ev(l), env.ev(r)
        if name in ("==", "!=") and (isinstance(l, str) or isinstance(r, str)):
            fr = l if isinstance(l, Frame) else r
            s = r if isinstance(r, str) else l
            if isinstance(fr, Frame) and isinstance(s, str):
                return _cmp_str(fr, s, negate=(name == "!="))
            return float((l == r) if name == "==" else (l != r))
        if not isinstance(l, Frame) and not isinstance(r, Frame):
            return float(op(l, r))
        dv = _dev_binop(name, l, r)
        if dv is not None:
            return dv
        pairs = _broadcast2(l, r)
        out = {}
        for n, (a, b) in pairs.items():
            # equality against literals is exact in f64: columns carry
            # a seeded float64 host view (frame/column.py host cache),
            # so `5.1 in fr` compares the original parsed values
            with np.errstate(all="ignore"):
                out[n] = np.asarray(
                    op(np.asarray(a, np.float64), np.asarray(b, np.float64)),
                    np.float64)
        return _rebuild(l if isinstance(l, Frame) else r, out,
                        keep_domains=False)
    return fn


for _name, _op in [("+", np.add), ("-", np.subtract), ("*", np.multiply),
                   ("/", np.divide), ("^", np.power), ("%", np.mod),
                   ("%%", np.mod),
                   ("==", lambda a, b: np.equal(a, b).astype(float)),
                   ("!=", lambda a, b: np.not_equal(a, b).astype(float)),
                   ("<", lambda a, b: np.less(a, b).astype(float)),
                   ("<=", lambda a, b: np.less_equal(a, b).astype(float)),
                   (">", lambda a, b: np.greater(a, b).astype(float)),
                   (">=", lambda a, b: np.greater_equal(a, b).astype(float)),
                   ("&", lambda a, b: ((a != 0) & (b != 0)).astype(float)),
                   ("|", lambda a, b: ((a != 0) | (b != 0)).astype(float)),
                   ("intDiv", np.floor_divide), ("%/%", np.floor_divide)]:
    PRIMS[_name] = _binop(_op, _name)


def _unop(op, name: str = ""):
    def fn(env, x):
        v = env.ev(x)
        if not isinstance(v, Frame):
            return float(op(v))
        dv = _dev_unop(name, v)
        if dv is not None:
            return dv
        with np.errstate(all="ignore"):
            out = {n: np.asarray(op(_col_np(v, n).astype(np.float64)))
                   for n in v.names}
        return _rebuild(v, out, keep_domains=False)
    return fn


for _name, _op in [("abs", np.abs), ("ceiling", np.ceil), ("floor", np.floor),
                   ("trunc", np.trunc), ("exp", np.exp), ("log", np.log),
                   ("log10", np.log10), ("log1p", np.log1p), ("log2", np.log2),
                   ("sqrt", np.sqrt), ("sin", np.sin), ("cos", np.cos),
                   ("tan", np.tan), ("asin", np.arcsin), ("acos", np.arccos),
                   ("atan", np.arctan), ("sinh", np.sinh), ("cosh", np.cosh),
                   ("tanh", np.tanh), ("sign", np.sign),
                   ("not", lambda a: np.asarray(a == 0, float)),
                   ("!", lambda a: np.asarray(a == 0, float)),
                   ("lgamma", np.vectorize(math.lgamma)),
                   ("gamma", np.vectorize(math.gamma)),
                   ]:
    PRIMS[_name] = _unop(_op, _name)


@prim("is.na")
def _is_na(env, x):
    """AstIsNa — per-cell 0/1; string columns test None (the numeric
    _unop path would try float('oneteen'))."""
    v = env.ev(x)
    if not isinstance(v, Frame):
        if isinstance(v, str):
            return 0.0            # a string scalar is a value, not NA
        try:
            return float(np.isnan(float(v)))
        except (TypeError, ValueError):
            return 1.0 if v is None else 0.0
    if _dev_eligible(v):
        # the NA answer is the mask itself — no values ever leave HBM
        _dev_hit()
        k = _isna_kernel()
        cols = []
        for n in v.names:
            c = v.col(n)
            d, m = k(c.na_mask, v.nrows)
            cols.append(Column(name=f"isNA({n})", type=T_NUM,
                               data=d, na_mask=m, nrows=v.nrows))
        return Frame(cols, v.nrows)
    out = {}
    for n in v.names:
        c = v.col(n)
        if c.type in ("string", "uuid"):
            flags = np.asarray([1.0 if s is None else 0.0
                                for s in c.to_numpy()])
        elif c.is_categorical:
            flags = (_cat_codes(v, n) < 0).astype(np.float64)
        else:
            flags = np.isnan(_col_np(v, n)).astype(np.float64)
        out[f"isNA({n})"] = flags         # AstIsNa output naming
    return Frame.from_numpy(out)


@prim("round")
def _round(env, x, digits=("num", 0)):
    v, d = env.ev(x), int(env.ev(digits))
    if not isinstance(v, Frame):
        return float(np.round(v, d))
    return _rebuild(v, {n: np.round(_col_np(v, n), d) for n in v.names},
                    keep_domains=False)


@prim("signif")
def _signif(env, x, digits=("num", 6)):
    v, d = env.ev(x), int(env.ev(digits))

    def sig(a):
        a = np.asarray(a, np.float64)
        with np.errstate(all="ignore"):
            mag = 10.0 ** (d - 1 - np.floor(np.log10(np.abs(a))))
            out = np.round(a * mag) / mag
        return np.where(a == 0, 0.0, out)

    if not isinstance(v, Frame):
        return float(sig(v))
    return _rebuild(v, {n: sig(_col_np(v, n)) for n in v.names}, False)


# ---- reducers (ast/prims/reducers) ----------------------------------


def _dev_reduce(name: str, v: Frame, na_rm: bool):
    """Device-resident sum/min/max/mean over all columns: per-column
    scalar partials leave the device, never the rows (AstSumAxis-at-scale
    role). None → host fallback. f32 accumulation (XLA tree-reduces, so
    error ~log n · eps) — only taken above _DEV_MIN_ROWS where the exact
    client oracles of the small pyunits never go."""
    if name not in ("sum", "min", "max", "mean") or not _dev_eligible(v):
        return None
    _dev_hit()
    # per-column 0-d partials accumulate ON DEVICE (one jitted kernel
    # per reduce); ONE batched scalar fetch ends the reduce (three
    # float() syncs per column would pay ~100ms tunnel RTT each — the
    # cost this path exists to avoid)
    k = _reduce_kernel(name)
    parts, counts, n_nas = [], [], []
    for n in v.names:
        c = v.col(n)
        part, cnt, n_na = k(c.data, c.na_mask, v.nrows)
        parts.append(part)
        counts.append(cnt)
        n_nas.append(n_na)
    parts, counts, n_nas = _fetch_np((parts, counts, n_nas))
    if not na_rm and np.sum(n_nas) > 0:
        return float("nan")
    if name == "sum":
        return float(np.sum(parts))
    if name == "mean":
        tot = float(np.sum(counts))
        # all values NA with na.rm: the host path (np.nanmean) yields
        # NaN — a clamped denominator would silently return 0.0 here
        return float(np.sum(parts) / tot) if tot > 0 else float("nan")
    return float(np.min(parts) if name == "min" else np.max(parts))


def _reducer(np_fn, na_fn, name: str = ""):
    def fn(env, *args):
        vals = [env.ev(a) for a in args]
        na_rm = False
        if len(vals) > 1 and isinstance(vals[-1], (bool, float, int)):
            na_rm = bool(vals[-1])
            vals = vals[:-1]
        if len(vals) == 1 and isinstance(vals[0], Frame):
            dv = _dev_reduce(name, vals[0], na_rm)
            if dv is not None:
                return dv
        acc = []
        for v in vals:
            if isinstance(v, Frame):
                # f64 accumulation: the client recomputes oracles in
                # float64 over the same (f32-parsed) values, so an f32
                # running product/sum would diverge at ~1e-7 relative
                acc += [_col_np(v, n).astype(np.float64)
                        for n in v.names]
            else:
                acc.append(np.array([float(v)]))
        flat = np.concatenate(acc)
        return float(na_fn(flat) if na_rm else np_fn(flat))
    return fn


for _name, _f, _fna in [
        ("sum", np.sum, np.nansum), ("min", np.min, np.nanmin),
        ("max", np.max, np.nanmax), ("mean", np.mean, np.nanmean),
        ("median", np.median, np.nanmedian),
        ("sd", lambda a: np.std(a, ddof=1), lambda a: np.nanstd(a, ddof=1)),
        ("var", lambda a: np.var(a, ddof=1), lambda a: np.nanvar(a, ddof=1)),
        ("prod", np.prod, np.nanprod),
        ("any", lambda a: float(np.any(a != 0)),
         lambda a: float(np.any(a[~np.isnan(a)] != 0))),
        ("all", lambda a: float(np.all(a != 0)),
         lambda a: float(np.all(a[~np.isnan(a)] != 0)))]:
    PRIMS[_name] = _reducer(_f, _fna, _name)


# NA-skipping scalar rollups (AstNaRollupOp subclasses: sumNA/minNA/
# maxNA/prodNA — h2o-py sends these for skipna=True, its default)
for _name, _fna in [("sumNA", np.nansum), ("minNA", np.nanmin),
                    ("maxNA", np.nanmax), ("prodNA", np.nanprod)]:
    PRIMS[_name] = _reducer(_fna, _fna)


@prim("flatten")
def _flatten_prim(env, x):
    """1x1 frame → scalar Val (AstFlatten.java:16); anything else
    passes through unchanged — the client's _eager_scalar path."""
    v = env.ev(x)
    if not isinstance(v, Frame) or v.ncols != 1 or v.nrows != 1:
        return v
    c = v.col(v.names[0])
    if c.is_categorical:
        k = int(_cat_codes(v, v.names[0])[0])
        return "NA" if k < 0 else str((c.domain or [])[k])
    val = c.to_numpy()[0]
    if c.type in ("string", "uuid"):
        return "NA" if val is None else str(val)
    return float(val)


def _cumop(op, axis1_op, name: str = ""):
    def fn(env, x, axis=0):
        v = env.ev(x)
        ax = int(env.ev(axis)) if not isinstance(axis, (int, float)) \
            else int(axis)
        if ax == 0:
            # padding rows sit AFTER the logical rows, so a prefix scan
            # over the padded array is exact on the logical prefix
            dv = _dev_unop(name, v)
            if dv is not None:
                return dv
            return _rebuild(v, {n: op(_col_np(v, n)) for n in v.names},
                            False)
        # axis=1: accumulate across columns within each row (AstCumu)
        m = np.stack([_col_np(v, n) for n in v.names], axis=1)
        acc = axis1_op(m)
        return _rebuild(v, {n: acc[:, j]
                            for j, n in enumerate(v.names)}, False)
    return fn


for _name, _op, _op1 in [
        ("cumsum", np.cumsum, lambda m: np.cumsum(m, axis=1)),
        ("cumprod", np.cumprod, lambda m: np.cumprod(m, axis=1)),
        ("cummax", np.maximum.accumulate,
         lambda m: np.maximum.accumulate(m, axis=1)),
        ("cummin", np.minimum.accumulate,
         lambda m: np.minimum.accumulate(m, axis=1))]:
    PRIMS[_name] = _cumop(_op, _op1, _name)


# ---- structural (ast/prims/mungers) ---------------------------------


def _num_list_indices(sel, n: Optional[int] = None) -> Optional[List[int]]:
    """Flatten a numeric selector (num / range / list of those) to ints;
    None when the selector isn't purely numeric. ``n`` resolves
    open-ended ('lo:nan') ranges."""
    if isinstance(sel, tuple) and sel[0] == "num":
        return [int(sel[1])]
    if isinstance(sel, tuple) and sel[0] == "range":
        lo = int(sel[1])
        step = int(sel[3]) if len(sel) > 3 else 1
        if math.isnan(sel[2]):
            if n is None:
                raise ValueError("open range needs a bound")
            return list(range(lo, n, step))
        return list(range(lo, lo + int(sel[2]) * step, step))
    if isinstance(sel, tuple) and sel[0] == "list":
        out: List[int] = []
        for it in sel[1]:
            sub = _num_list_indices(it, n)
            if sub is None:
                return None
            out.extend(sub)
        return out
    if isinstance(sel, (int, float)):
        return [int(sel)]
    return None


def _is_empty_list(sel) -> bool:
    return isinstance(sel, tuple) and sel[0] == "list" and not sel[1]


def _resolve_cols(frame: Frame, sel) -> List[str]:
    nums = _num_list_indices(sel, frame.ncols)
    if nums is not None:
        # all-negative numeric selector = COMPLEMENT: h2o-py's pop/del
        # send -(i+1) meaning "every column except i"
        # (h2o-py/h2o/frame.py pop/drop wire format)
        if nums and all(v < 0 for v in nums):
            drop = {-(v) - 1 for v in nums}
            return [n for i, n in enumerate(frame.names) if i not in drop]
        return [frame.names[v] for v in nums]
    if isinstance(sel, tuple) and sel[0] == "list":
        out = []
        for it in sel[1]:
            out.extend(_resolve_cols(frame, it))
        return out
    if isinstance(sel, tuple) and sel[0] in ("str", "id"):
        return [sel[1]]
    if isinstance(sel, str):
        return [sel]
    raise ValueError(f"bad column selector {sel!r}")


@prim("cols", "cols_py")
def _cols(env, fr, sel):
    f = _as_frame(env.ev(fr))
    return f[_resolve_cols(f, sel)]


def _row_indices(f: Frame, sel, env) -> np.ndarray:
    nums = _num_list_indices(sel, f.nrows)
    if nums is not None:
        idx = np.asarray(nums, np.int64)
        if len(idx) and (idx < 0).all():
            # negative row list = complement (AstNumList semantics)
            drop = set((-idx - 1).tolist())
            return np.asarray([i for i in range(f.nrows) if i not in drop],
                              np.int64)
        return idx
    mask_fr = _as_frame(env.ev(sel))
    m = _col_np(mask_fr, mask_fr.names[0])
    return np.flatnonzero(np.nan_to_num(m) != 0)


@prim("rows")
def _rows(env, fr, sel):
    f = _as_frame(env.ev(fr))
    return _take_rows(f, _row_indices(f, sel, env))


@prim("append", "cbind")
def _append(env, *args):
    # (append fr value "name"): h2o-py's new-column assignment
    # fr["new"] = value (h2o-py/h2o/frame.py:2251) — value may be a
    # scalar (broadcast) or a 1-col frame; the string names the column
    if len(args) == 3 and isinstance(args[2], tuple) and args[2][0] == "str":
        base = _as_frame(env.ev(args[0]))
        val = env.ev(args[1])
        name = args[2][1]
        out_arrays, cats, doms = {}, [], {}
        for n in base.names:
            c = base.col(n)
            if c.is_categorical:
                out_arrays[n] = _cat_codes(base, n)
                cats.append(n)
                doms[n] = c.domain
            else:
                out_arrays[n] = _col_np(base, n)
        if isinstance(val, Frame):
            vc = val.col(val.names[0])
            if vc.is_categorical:
                out_arrays[name] = _cat_codes(val, val.names[0])
                cats.append(name)
                doms[name] = vc.domain
            else:
                out_arrays[name] = _col_np(val, val.names[0])
        elif isinstance(val, str):
            out_arrays[name] = np.zeros(base.nrows, np.int32)
            cats.append(name)
            doms[name] = [val]
        else:
            out_arrays[name] = np.full(base.nrows, float(val), np.float64)
        return Frame.from_numpy(out_arrays, categorical=cats, domains=doms)
    frames = [_as_frame(env.ev(a)) for a in args
              if not (isinstance(a, tuple) and a[0] == "str")]
    out_arrays, cats, doms = {}, [], {}
    seen = set()
    for f in frames:
        for n in f.names:
            # duplicate names take integer suffixes FROM ZERO:
            # Frame.uniquify (water/fvec/Frame.java:227) appends cnt++
            # per collision — colgroup → colgroup0, colgroup2 →
            # colgroup20 → colgroup21 when colgroup20 is taken
            nm, k = n, 0
            while nm in seen:
                nm = f"{n}{k}"
                k += 1
            seen.add(nm)
            c = f.col(n)
            if c.is_categorical:
                out_arrays[nm] = _cat_codes(f, n)
                cats.append(nm)
                doms[nm] = c.domain
            else:
                out_arrays[nm] = _col_np(f, n)
    return Frame.from_numpy(out_arrays, categorical=cats, domains=doms)


@prim("rbind")
def _rbind(env, *args):
    frames = [_as_frame(env.ev(a)) for a in args]
    base = frames[0]
    arrays, cats, doms = {}, [], {}
    for n in base.names:
        if base.col(n).is_categorical:
            dom: List[str] = []
            for f in frames:
                for lvl in (f.col(n).domain or []):
                    if lvl not in dom:
                        dom.append(lvl)
            parts = []
            for f in frames:
                lut = {lvl: i for i, lvl in enumerate(dom)}
                mapping = np.array(
                    [lut[lvl] for lvl in (f.col(n).domain or [])], np.int32)
                codes = _cat_codes(f, n)
                ok = codes >= 0
                if len(mapping):
                    codes[ok] = mapping[codes[ok]]
                parts.append(codes)
            arrays[n] = np.concatenate(parts)
            cats.append(n)
            doms[n] = dom
        else:
            arrays[n] = np.concatenate([_col_np(f, n) for f in frames])
    return Frame.from_numpy(arrays, categorical=cats, domains=doms)


@prim("nrow")
def _nrow(env, fr):
    return float(_as_frame(env.ev(fr)).nrows)


@prim("ncol")
def _ncol(env, fr):
    return float(_as_frame(env.ev(fr)).ncols)


@prim("colnames=")
def _colnames(env, fr, idxs, names):
    f = _as_frame(env.ev(fr))
    cols = _resolve_cols(f, idxs)
    new = ([n[1] for n in names[1]]
           if isinstance(names, tuple) and names[0] == "list" else [names[1]])
    ren = dict(zip(cols, new))
    out, cats, doms = {}, [], {}
    for n in f.names:
        nm = ren.get(n, n)
        c = f.col(n)
        if c.is_categorical:
            out[nm] = _cat_codes(f, n)
            cats.append(nm)
            doms[nm] = c.domain
        else:
            out[nm] = _col_np(f, n)
    return Frame.from_numpy(out, categorical=cats, domains=doms)


@prim("tmp=", "assign")
def _assign(env, name, expr, *rest):
    nm = name[1] if isinstance(name, tuple) else str(name)
    val = env.ev(expr)
    env.session.assign(nm, val)
    return val


@prim(":=")
def _rect_assign(env, dst, src, col_sel, row_sel):
    """Rectangle assign (water/rapids/ast/prims/assign/AstRectangleAssign
    role): h2o-py `fr[rows, col] = value` ships
    ``(:= <frame> <value> <col> <rows>)`` with '[]' = all rows/cols
    (h2o-py/h2o/frame.py:2242, expr.py _arg_to_expr None → '[]')."""
    f = _as_frame(env.ev(dst))
    cols = (f.names if _is_empty_list(col_sel)
            else _resolve_cols(f, col_sel))
    rows = (np.arange(f.nrows)
            if _is_empty_list(row_sel) or row_sel is None
            else _row_indices(f, row_sel, env))
    val = env.ev(src)

    arrays, cats, doms, strs = {}, [], {}, []
    for i, n in enumerate(f.names):
        c = f.col(n)
        if c.is_categorical:
            arr = _cat_codes(f, n).astype(np.float64)
            arr[arr < 0] = np.nan
            dom = list(c.domain or [])
        elif c.type == "string":
            arr = c.to_numpy().copy()
            dom = None
        else:
            arr = _col_np(f, n).copy()
            dom = None
        if n in cols:
            if isinstance(val, Frame):
                j = cols.index(n) if val.ncols > 1 else 0
                vc = val.col(val.names[j])
                if vc.is_categorical:
                    # NA codes are -1; as float they must become NaN
                    # BEFORE the domain remap or mp[-1] silently maps
                    # every NA row to the LAST level
                    v = _cat_codes(val, val.names[j]).astype(np.float64)
                    v[v < 0] = np.nan
                else:
                    v = vc.to_numpy()
                full = len(rows) == f.nrows
                if vc.type in ("string", "uuid"):
                    # string-typed source (AstRectangleAssign string
                    # path): a full-column replace converts the dest to
                    # T_STR; a partial assign into an enum interns the
                    # labels into the destination domain
                    v = np.asarray(v, dtype=object)
                    if full:
                        dom = None
                        arr = np.empty(f.nrows, dtype=object)
                    elif dom is not None:
                        lut = {lvl: k for k, lvl in enumerate(dom)}
                        vv = np.full(len(v), np.nan)
                        for k2, s in enumerate(v):
                            # non-strings (None, float NaN cells a
                            # numeric assign left in a T_STR column)
                            # stay NA, never become levels
                            if not isinstance(s, str):
                                continue
                            if s not in lut:
                                lut[s] = len(dom)
                                dom.append(s)
                            vv[k2] = lut[s]
                        v = vv
                    elif c.type != "string":
                        raise ValueError(
                            f"cannot assign string rows into numeric "
                            f"column '{n}'")
                elif full and vc.is_categorical and dom is None:
                    # whole-column replace with a factor: the column
                    # BECOMES categorical (fr["y"] = fr["y"].asfactor())
                    dom = list(vc.domain or [])
                    arr = np.full(f.nrows, np.nan)
                elif full and not vc.is_categorical and dom is not None \
                        and c.type != "string":
                    # whole-column replace with numeric: drops the factor
                    dom = None
                    arr = np.full(f.nrows, np.nan)
                if vc.is_categorical and dom is not None:
                    # remap source codes into the destination domain
                    lut = {lvl: k for k, lvl in enumerate(dom)}
                    src_dom = vc.domain or []
                    for lvl in src_dom:
                        if lvl not in lut:
                            lut[lvl] = len(dom)
                            dom.append(lvl)
                    mp = np.array([lut[lvl] for lvl in src_dom], np.float64)
                    ok = ~np.isnan(v)
                    v = v.copy()
                    v[ok] = mp[v[ok].astype(np.int64)]
                v = v[: f.nrows] if len(v) >= f.nrows else v
                arr[rows] = v[rows] if len(v) == f.nrows else v[: len(rows)]
            elif isinstance(val, str):
                if dom is not None:
                    if val not in dom:
                        dom.append(val)
                    arr[rows] = float(dom.index(val))
                elif c.type == "string":
                    arr[rows] = val
                else:
                    raise ValueError(
                        f"cannot assign string into numeric column '{n}'")
            else:
                arr[rows] = float(val)
        if dom is not None:
            na = np.isnan(arr)
            arr = np.where(na, -1, arr).astype(np.int32)
            arrays[n] = arr
            cats.append(n)
            doms[n] = dom
        else:
            arrays[n] = arr
            if arr.dtype == object:
                # string columns must stay T_STR — from_numpy would
                # otherwise re-intern the object array into an enum
                strs.append(n)
    out = Frame.from_numpy(arrays, categorical=cats, domains=doms,
                           strings=strs)
    # preserve column order
    return out[f.names]


@prim("rm")
def _rm(env, name):
    nm = name[1] if isinstance(name, tuple) else str(name)
    env.session.rm(nm)
    return 0.0


@prim("ifelse")
def _ifelse(env, test, yes, no):
    t, y, n = env.ev(test), env.ev(yes), env.ev(no)
    if isinstance(t, Frame) and _dev_eligible(t, y, n) \
            and not isinstance(y, str) and not isinstance(n, str):
        # string yes/no branches intern as categoricals — host path only
        tc = t.col(t.names[0])
        args = [tc.data, tc.na_mask]
        ykind = "f" if isinstance(y, Frame) else "s"
        nkind = "f" if isinstance(n, Frame) else "s"
        if ykind == "f":
            yc = y.col(y.names[0])
            args += [yc.data, yc.na_mask]
        else:
            args.append(float(y))
        if nkind == "f":
            nc = n.col(n.names[0])
            args += [nc.data, nc.na_mask]
        else:
            args.append(float(n))
        args.append(t.nrows)
        out = _ifelse_kernel(ykind, nkind)(*args)
        return _dev_frame(t.nrows, {"C1": out})
    tv = _col_np(t, t.names[0]) if isinstance(t, Frame) else t
    if not isinstance(tv, np.ndarray):
        return y if tv else n
    yv = _col_np(y, y.names[0]) if isinstance(y, Frame) else y
    nv = _col_np(n, n.names[0]) if isinstance(n, Frame) else n
    out = np.where(np.nan_to_num(tv) != 0, yv, nv)
    out = np.where(np.isnan(tv), np.nan, out)
    base = t if isinstance(t, Frame) else (y if isinstance(y, Frame) else n)
    return _rebuild(base, {"C1": out}, False)


@prim("as.factor", "as_factor")
def _as_factor(env, x):
    f = _as_frame(env.ev(x))
    out, cats, doms = {}, [], {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            out[n] = _cat_codes(f, n)
            cats.append(n)
            doms[n] = c.domain
        else:
            v = _col_np(f, n)
            uniq = np.unique(v[~np.isnan(v)])
            dom = [str(int(u)) if u == int(u) else str(u) for u in uniq]
            lut = {u: i for i, u in enumerate(uniq)}
            codes = np.array([lut[x_] if not np.isnan(x_) and x_ in lut else -1
                              for x_ in v], np.int32)
            out[n] = codes
            cats.append(n)
            doms[n] = dom
    return Frame.from_numpy(out, categorical=cats, domains=doms)


@prim("as.numeric", "as_numeric")
def _as_numeric(env, x):
    f = _as_frame(env.ev(x))
    out = {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            dom = c.domain or []
            try:
                dv = np.array([float(s) for s in dom])
            except ValueError:
                dv = np.arange(len(dom), dtype=np.float64)
            codes = _fetch_np(c.data)[: f.nrows].astype(np.int64)
            v = dv[codes] if len(dom) else codes.astype(np.float64)
            v = v.copy()
            v[_fetch_np(c.na_mask)[: f.nrows]] = np.nan
            out[n] = v
        else:
            out[n] = _col_np(f, n)
    return Frame.from_numpy(out)


@prim("as.character")
def _as_character(env, x):
    f = _as_frame(env.ev(x))
    out = {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            dom = np.array((c.domain or []) + [None], dtype=object)
            codes = _fetch_np(c.data)[: f.nrows].astype(np.int64)
            codes = np.where(_fetch_np(c.na_mask)[: f.nrows],
                             len(dom) - 1, codes)
            out[n] = dom[codes]
        else:
            out[n] = np.array([str(v) for v in _col_np(f, n)], dtype=object)
    # as.character yields STRING columns (AstAsCharacter → Vec.T_STR),
    # not a re-interned enum — isstring()/ischaracter() observe the type
    return Frame.from_numpy(out, strings=list(out))


@prim("unique")
def _unique(env, x, *rest):
    """AstUnique; optional include_nas flag appends one NA row when the
    column has missing values (h2o-py unique(include_nas=True))."""
    include_nas = any(bool(a[1] if isinstance(a, tuple) else env.ev(a))
                      for a in rest)
    f = _as_frame(env.ev(x))
    n = f.names[0]
    c = f.col(n)
    if c.is_categorical:
        codes = _cat_codes(f, n)
        u = np.unique(codes[codes >= 0]).astype(np.float64)
        if include_nas and (codes < 0).any():
            u = np.concatenate([u, [np.nan]])
        out = Frame.from_numpy({n: u}, categorical=[n],
                               domains={n: c.domain})
        return out
    v = _col_np(f, n)
    u = np.unique(v[~np.isnan(v)])
    if include_nas and np.isnan(v).any():
        u = np.concatenate([u, [np.nan]])
    out = Frame.from_numpy({n: u},
                           times=[n] if c.type == "time" else ())
    return out


def _table_values(fr, nm):
    c = fr.col(nm)
    if c.is_categorical:
        dom = np.asarray(list(c.domain or []), dtype=object)
        codes = _cat_codes(fr, nm)
        return np.asarray([dom[k] if k >= 0 else None for k in codes],
                          dtype=object)
    return _col_np(fr, nm)


@prim("table")
def _table(env, x, *rest):
    """AstTable: single-column counts, or a two-column cross tabulation
    — dense=True emits (v1, v2, Counts) rows, dense=False a wide
    cross-tab whose columns are the second variable's levels."""
    f = _as_frame(env.ev(x))
    f2, dense = None, True
    for a in rest:
        v = a[1] if isinstance(a, tuple) else env.ev(a)
        if isinstance(v, Frame):
            f2 = v
        elif isinstance(v, (bool, int, float)):
            dense = bool(v)
    if f2 is not None:
        pairs = ((f, f.names[0]), (f2, f2.names[0]))
    elif f.ncols == 2:
        pairs = ((f, f.names[0]), (f, f.names[1]))
    else:
        n = f.names[0]
        c = f.col(n)
        if c.is_categorical:
            codes = _cat_codes(f, n)
            cnt = np.bincount(codes[codes >= 0],
                              minlength=len(c.domain or []))
            return Frame.from_numpy(
                {n: np.arange(len(cnt), dtype=np.int32),
                 "Count": cnt.astype(np.float64)},
                categorical=[n], domains={n: c.domain})
        v = _col_np(f, n)
        u, cnt = np.unique(v[~np.isnan(v)], return_counts=True)
        return Frame.from_numpy({n: u, "Count": cnt.astype(np.float64)})

    (fr1, n1), (fr2, n2) = pairs
    a1, a2 = _table_values(fr1, n1), _table_values(fr2, n2)
    from collections import Counter
    cnt = Counter((v1, v2) for v1, v2 in zip(a1, a2)
                  if v1 is not None and v2 is not None
                  and not (isinstance(v1, float) and np.isnan(v1))
                  and not (isinstance(v2, float) and np.isnan(v2)))
    u1 = sorted({k[0] for k in cnt})
    u2 = sorted({k[1] for k in cnt})
    if n2 == n1:
        n2 = n2 + "2"
    if dense:
        rows = sorted(cnt.items())
        c1 = np.asarray([r[0][0] for r in rows], dtype=object)
        c2 = np.asarray([r[0][1] for r in rows], dtype=object)
        counts = np.asarray([r[1] for r in rows], np.float64)
        out = {}
        for nm, arr in ((n1, c1), (n2, c2)):
            if all(isinstance(v, (int, float, np.floating, np.integer))
                   for v in arr):
                out[nm] = arr.astype(np.float64)
            else:
                out[nm] = arr
        out["Counts"] = counts
        return Frame.from_numpy(out)
    # wide cross-tab: one row per u1 value, one column per u2 level
    out = {n1: (np.asarray(u1, np.float64)
                if all(isinstance(v, (int, float, np.floating,
                                      np.integer)) for v in u1)
                else np.asarray(u1, dtype=object))}
    for lvl in u2:
        out[str(lvl)] = np.asarray(
            [float(cnt.get((v1, lvl), 0)) for v1 in u1], np.float64)
    return Frame.from_numpy(out)


@prim("naCnt", "na_cnt")
def _na_cnt(env, fr):
    """Per-column NA counts (ast/prims/advmath AstNaCnt)."""
    f = _as_frame(env.ev(fr))
    out = []
    for n in f.names:
        c = f.col(n)
        if c.type == "string":
            out.append(int(sum(v is None for v in c.to_numpy())))
        else:
            out.append(int(_fetch_np(c.na_mask)[: f.nrows].sum()))
    return out


@prim("h2o.runif")
def _runif(env, fr, seed):
    f = _as_frame(env.ev(fr))
    s = int(env.ev(seed))
    rng = np.random.RandomState(s if s >= 0 else None)
    return Frame.from_numpy({"rnd": rng.rand(f.nrows)})


@prim("quantile")
def _quantile(env, fr, probs, method=("str", "interpolate"), *rest):
    from h2o3_tpu.frame.quantiles import column_quantiles
    f = _as_frame(env.ev(fr))
    plist = (probs[1] if isinstance(probs, tuple) and probs[0] == "list"
             else [probs])
    pr = [p[1] if isinstance(p, tuple) else float(p) for p in plist]
    meth = method[1] if isinstance(method, tuple) else str(method)
    out = {"Probs": np.asarray(pr, np.float64)}
    for n in f.names:
        c = f.col(n)
        if not c.is_categorical and c.type != "string":
            out[n + "Quantiles"] = column_quantiles(c, pr, combine_method=meth)
    return Frame.from_numpy(out)


@prim("sort")
def _sort(env, fr, cols_sel, *asc):
    f = _as_frame(env.ev(fr))
    names = _resolve_cols(f, cols_sel)
    # h2o-py encodes direction as +1 (asc) / -1 (desc), never 0
    # (h2o-py/h2o/frame.py sort(): ascendingI[index]=1 if ... else -1),
    # so bool() is wrong — bool(-1) is True. Sign is the contract.
    if asc and isinstance(asc[0], tuple) and asc[0][0] == "list":
        ascending = [float(a[1]) > 0 for a in asc[0][1]]
    else:
        ascending = [float(env.ev(a)) > 0 for a in asc]
    ascending = ascending or [True] * len(names)
    # device radix-order path (water/rapids/RadixOrder.java role): sort
    # permutation + column gathers stay on the mesh; the controller
    # never holds the data. Host lexsort remains the tiny-frame path.
    from h2o3_tpu.ops.sort import device_sort
    df = device_sort(f, names, ascending)
    if df is not None:
        return df
    keys = []
    for n, a in list(zip(names, ascending))[::-1]:
        c = f.col(n)
        v = (_cat_codes(f, n).astype(np.float64) if c.is_categorical
             else _col_np(f, n))
        keys.append(v if a else -v)
    order = np.lexsort(keys)
    return _take_rows(f, order)


_GB_AGGS = {"sum": "sum", "mean": "mean", "min": "min", "max": "max",
            "count": "count", "nrow": "count", "sd": "sd", "sdev": "sd",
            "var": "var", "sumSquares": "ss",
            "median": "median", "mode": "mode"}


@prim("GB", "group-by", "groupby")
def _groupby(env, fr, by_sel, *aggs):
    """(GB frame [by...] agg col na_handling ...) — AstGroup
    (ast/prims/mungers/AstGroup.java). Device path: dense group ids →
    one segment_sum per moment aggregate over the mesh."""
    import jax.numpy as jnp
    import pandas as pd
    from h2o3_tpu.ops.segments import segment_sum
    f = _as_frame(env.ev(fr))
    by = _resolve_cols(f, by_sel)
    key_cols = []
    for n in by:
        c = f.col(n)
        v = (_cat_codes(f, n).astype(np.int64) if c.is_categorical
             else _col_np(f, n))
        key_cols.append(v)
    kdf = pd.DataFrame({i: k for i, k in enumerate(key_cols)})
    gid, uniq = pd.factorize(pd.MultiIndex.from_frame(kdf), sort=True)
    G = len(uniq)
    out: Dict[str, np.ndarray] = {}
    cats, doms = [], {}
    for i, n in enumerate(by):
        c = f.col(n)
        vals = np.asarray([u[i] if isinstance(u, tuple) else u for u in uniq])
        if c.is_categorical:
            out[n] = vals.astype(np.int32)
            cats.append(n)
            doms[n] = c.domain
        else:
            out[n] = vals.astype(np.float64)
    gid_pad = np.zeros(f.nrows_padded, np.int32)
    gid_pad[: f.nrows] = gid
    gid_dev = jnp.asarray(gid_pad)
    valid = np.zeros(f.nrows_padded, np.float32)
    valid[: f.nrows] = 1.0
    valid_dev = jnp.asarray(valid)
    it = list(aggs)
    triplets = []
    while it:
        a = it.pop(0)
        aname = a[1] if isinstance(a, tuple) else str(a)
        col = it.pop(0) if it else None
        if it:
            it.pop(0)   # na-handling token (all/rm/ignore); NAs excluded
        triplets.append((aname.strip('"'), col))
    for aname, colsel in triplets:
        aname = _GB_AGGS.get(aname, aname)
        cname = _resolve_cols(f, colsel)[0] if colsel is not None else by[0]
        c = f.col(cname)
        label = f"{aname}_{cname}" if aname != "count" else "nrow"
        if aname in ("count", "sum", "mean", "var", "sd", "ss"):
            v = c.numeric_view()
            okv = ~jnp.isnan(v)
            w = valid_dev * okv.astype(jnp.float32)
            v0 = jnp.where(okv, v, 0.0)
            sums = segment_sum(gid_dev,
                               jnp.stack([w, w * v0, w * v0 * v0], axis=1),
                               n_nodes=G, mesh=mesh_mod.get_mesh())
            cnt = np.asarray(sums[:, 0], np.float64)
            s1 = np.asarray(sums[:, 1], np.float64)
            s2 = np.asarray(sums[:, 2], np.float64)
            if aname == "count":
                out[label] = cnt
            elif aname == "sum":
                out[label] = s1
            elif aname == "ss":
                out[label] = s2
            elif aname == "mean":
                out[label] = s1 / np.maximum(cnt, 1e-12)
            else:
                m = s1 / np.maximum(cnt, 1e-12)
                var = (s2 / np.maximum(cnt, 1e-12) - m * m) \
                    * cnt / np.maximum(cnt - 1, 1e-12)
                out[label] = (np.sqrt(np.maximum(var, 0))
                              if aname == "sd" else var)
        elif aname in ("min", "max", "median", "mode"):
            if aname == "mode":
                vv = _cat_codes(f, cname).astype(np.float64)
                vv[vv < 0] = np.nan
            else:
                vv = _col_np(f, cname)
            s = pd.Series(vv).groupby(gid)
            agg = (s.agg(lambda g: g.value_counts().idxmax())
                   if aname == "mode" else getattr(s, aname)())
            out[label] = agg.reindex(range(G)).to_numpy()
        else:
            raise ValueError(f"unknown group-by agg '{aname}'")
    return Frame.from_numpy(out, categorical=cats, domains=doms)


@prim("merge")
def _merge(env, l, r, all_left=("num", 0), all_right=("num", 0),
           by_x=None, by_y=None, method=None):
    """Equi-join (water/rapids/Merge.java + BinaryMerge.java roles).

    h2o-py always ships by_x/by_y as column-index lists (defaulting to
    all shared names, h2o-py/h2o/frame.py merge()). Large frames with
    same-named keys run fully on device (ops/merge.py sort-merge join);
    everything else — string keys, right/outer, renamed key pairs,
    tiny frames — takes the host hash join."""
    lf = _as_frame(env.ev(l))
    rf = _as_frame(env.ev(r))
    how = "inner"
    if int(env.ev(all_left)):
        how = "left"
    if int(env.ev(all_right)):
        how = "outer" if how == "left" else "right"
    shared = [n for n in lf.names if n in set(rf.names)]
    bx = by = shared
    if by_x is not None and isinstance(by_x, tuple) \
            and by_x[0] == "list" and by_x[1]:
        bx = _resolve_cols(lf, by_x)
        by = _resolve_cols(rf, by_y) if by_y is not None else bx
    if bx == by:
        from h2o3_tpu.ops.merge import device_merge
        dm = device_merge(lf, rf, bx, how)
        if dm is not None:
            return dm
    ldf = lf.to_pandas()
    rdf = rf.to_pandas()
    # NA keys never match (reference Merge.java / SQL semantics; pandas
    # would join NaN==NaN): drop NA-key rows from the non-preserved side
    if bx:
        if how in ("inner", "left"):
            rdf = rdf.dropna(subset=by)
        if how in ("inner", "right"):
            ldf = ldf.dropna(subset=bx)
    if how == "outer" and bx:
        # both sides preserved: join the non-NA-key rows, then append
        # each side's NA-key rows unmatched (pandas would pair NaN==NaN).
        # Appended slices must carry the SAME schema as the merge result:
        # colliding non-key columns take pandas' _x/_y suffixes and
        # renamed right keys fold under the left key names.
        import pandas as _pd
        lna = ldf[bx].isna().any(axis=1)
        rna = rdf[by].isna().any(axis=1)
        if bx == by:
            m = ldf[~lna].merge(rdf[~rna], how="outer", on=bx)
        else:
            m = ldf[~lna].merge(rdf[~rna], how="outer",
                                left_on=bx, right_on=by)
            m = m.drop(columns=[c for c in by if c not in bx and c in m])
        collide = {c for c in rdf.columns
                   if c not in by and c in set(ldf.columns) - set(bx)}
        l_tail = ldf[lna].rename(
            columns={c: c + "_x" for c in collide})
        r_tail = rdf[rna].rename(columns={**dict(zip(by, bx)),
                                          **{c: c + "_y" for c in collide}})
        r_tail = r_tail.loc[:, [c for c in r_tail.columns if c in m.columns]]
        m = _pd.concat([m, l_tail, r_tail], ignore_index=True)
        return Frame.from_pandas(m)
    if bx == by:
        m = ldf.merge(rdf, how=how, on=bx or None)
    else:
        # renamed key pairs: the reference keeps ONE key column under
        # the left frame's names (BinaryMerge result layout)
        m = ldf.merge(rdf, how=how, left_on=bx, right_on=by)
        m = m.drop(columns=[c for c in by if c not in bx and c in m])
    return Frame.from_pandas(m)


def _device_merge(lf: Frame, rf: Frame, how: str) -> Optional[Frame]:
    """Back-compat shim over ops/merge.py device_merge (joins on all
    shared column names, like the h2o-py default)."""
    from h2o3_tpu.ops.merge import device_merge
    shared = [n for n in lf.names if n in set(rf.names)]
    if not shared:
        return None
    return device_merge(lf, rf, shared, how)


@prim("na.omit")
def _na_omit(env, fr):
    f = _as_frame(env.ev(fr))
    keep = np.ones(f.nrows, bool)
    for n in f.names:
        keep &= ~_fetch_np(f.col(n).na_mask)[: f.nrows]
    return _take_rows(f, np.flatnonzero(keep))


@prim("h2o.impute", "impute")
def _impute(env, fr, col_idx, method=("str", "mean"), *rest):
    f = _as_frame(env.ev(fr))
    all_cols = (isinstance(col_idx, tuple) and col_idx[0] == "num"
                and col_idx[1] < 0)
    names = f.names if all_cols else _resolve_cols(f, col_idx)
    meth = method[1] if isinstance(method, tuple) else str(method)
    arrays, cats, doms = {}, [], {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            codes = _cat_codes(f, n)
            na = codes < 0
            if n in names and meth == "mode" and (~na).any():
                codes[na] = np.bincount(codes[~na]).argmax()
            arrays[n] = codes
            cats.append(n)
            doms[n] = c.domain
        else:
            v = _col_np(f, n).copy()
            if n in names and np.isnan(v).any():
                fill = (np.nanmean(v) if meth == "mean"
                        else np.nanmedian(v) if meth == "median" else np.nan)
                v[np.isnan(v)] = fill
            arrays[n] = v
    return Frame.from_numpy(arrays, categorical=cats, domains=doms)


@prim("scale")
def _scale(env, fr, center=("num", 1), scale_=("num", 1)):
    f = _as_frame(env.ev(fr))
    out = {}
    for n in f.names:
        v = _col_np(f, n)
        if int(env.ev(center)):
            v = v - np.nanmean(v)
        if int(env.ev(scale_)):
            sd = np.nanstd(v, ddof=1)
            v = v / (sd if sd > 0 else 1.0)
        out[n] = v
    return Frame.from_numpy(out)


# ---- string ops (ast/prims/string) ----------------------------------


def _strop(fn):
    def wrapper(env, x, *args):
        f = _as_frame(env.ev(x))
        extra = [a[1] if isinstance(a, tuple) else env.ev(a) for a in args]
        if f.nrows >= _DEV_MIN_ROWS and all(
                f.col(n).is_categorical and f.col(n).domain
                for n in f.names):
            # scale path: transform the DOMAIN on host (O(cardinality))
            # and remap codes on device via a LUT gather — the rows
            # never leave HBM (AstStrOp over CStrChunk becomes a
            # dictionary rewrite at TPU scale)
            import jax.numpy as jnp
            _dev_hit()
            cols = []
            for n in f.names:
                c = f.col(n)
                dom = [fn(s, *extra) for s in (c.domain or [])]
                uniq = sorted(set(dom))
                remap = {s: i for i, s in enumerate(uniq)}
                lut = np.array([remap[s] for s in dom], np.int32)
                codes = jnp.take(jnp.asarray(lut),
                                 c.data.astype(jnp.int32),
                                 mode="clip")
                cols.append(Column(name=n, type=T_CAT, data=codes,
                                   na_mask=c.na_mask, nrows=f.nrows,
                                   domain=uniq))
            return Frame(cols, f.nrows)
        out, cats, strs = {}, [], []
        for n in f.names:
            c = f.col(n)
            if c.is_categorical:
                # transformed labels re-intern: duplicates collapse.
                # '' stays a REAL level — AstSubstring keeps a {""}
                # domain server-side and h2o-py levels() filters ''
                # client-side (h2o-py/h2o/frame.py levels()).
                dom = [fn(s, *extra) for s in (c.domain or [])]
                codes = _fetch_np(c.data)[: f.nrows].astype(np.int64)
                codes = np.where(_fetch_np(c.na_mask)[: f.nrows],
                                 len(dom), codes)
                out[n] = np.array(dom + [None], dtype=object)[codes]
                cats.append(n)
            elif c.type == "string":
                out[n] = np.array([fn(s, *extra) if s is not None else None
                                   for s in c.to_numpy()], dtype=object)
                strs.append(n)   # string in, string out (AstStrOp)
            else:
                out[n] = c.to_numpy()
        return Frame.from_numpy(out, categorical=cats, strings=strs)
    return wrapper


PRIMS["tolower"] = _strop(lambda s, *a: s.lower())
PRIMS["toupper"] = _strop(lambda s, *a: s.upper())
PRIMS["trim"] = _strop(lambda s, *a: s.strip())
PRIMS["sub"] = _strop(
    lambda s, pat, rep, *a: _re.sub(str(pat), str(rep), s, count=1))
PRIMS["gsub"] = _strop(lambda s, pat, rep, *a: _re.sub(str(pat), str(rep), s))
PRIMS["replacefirst"] = PRIMS["sub"]
PRIMS["replaceall"] = PRIMS["gsub"]


@prim("nchar", "strlen")
def _nchar(env, x):
    """String length (AstStrLength, str()='strlen' — the op h2o-py
    nchar() actually sends; 'nchar' kept as a courtesy alias)."""
    f = _as_frame(env.ev(x))
    out = {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            dom = c.domain or []
            lens = np.array([float(len(s)) for s in dom] + [np.nan])
            codes = _fetch_np(c.data)[: f.nrows].astype(np.int64)
            codes = np.where(_fetch_np(c.na_mask)[: f.nrows], len(dom), codes)
            out[n] = lens[codes]
        elif c.type == "string":
            out[n] = np.array([float(len(s)) if s is not None else np.nan
                               for s in c.to_numpy()])
        else:
            out[n] = c.to_numpy()
    return Frame.from_numpy(out)


@prim("substring")
def _substring(env, x, start, end=("num", 1e9)):
    """AstSubstring: start clamps to 0; end sent as an empty AstNumList
    ([] — h2o-py substring(end_index=None)) means MAX; start >= end
    yields '' for every row (the reference's {\"\"} domain), so a
    negative end must NOT fall through to Python negative slicing."""
    s0 = int(env.ev(start))
    if isinstance(end, tuple) and end[0] == "list":
        e0 = int(1e9)                       # [] → Integer.MAX_VALUE
    else:
        ev = env.ev(end)
        e0 = int(1e9) if (isinstance(ev, float) and np.isnan(ev)) \
            else int(min(ev, 1e9))
    s0 = max(s0, 0)
    if e0 <= s0:
        return _strop(lambda s: "")(env, x)
    return _strop(lambda s: s[s0:e0])(env, x)


# ---------------------------------------------------------------- env


# ---- matching / introspection (ast/prims/{mungers,misc}) -------------

@prim("match")
def _match(env, x, table, nomatch=("num", float("nan")), *rest):
    """Value → 1-based index into ``table`` (AstMatch semantics)."""
    f = _as_frame(env.ev(x))
    tbl = env.ev(table)
    if isinstance(tbl, tuple) and tbl[0] == "list":
        tbl = [t[1] for t in tbl[1]]
    elif not isinstance(tbl, (list, np.ndarray)):
        tbl = [tbl]
    nm = env.ev(nomatch)
    lut = {str(v): i + 1 for i, v in enumerate(tbl)}
    out = {}
    for n in f.names:
        c = f.col(n)
        if c.is_categorical:
            dom_map = np.asarray([lut.get(lvl, np.nan)
                                  for lvl in (c.domain or [])] + [np.nan])
            codes = _cat_codes(f, n)
            vals = dom_map[np.where(codes < 0, len(dom_map) - 1, codes)]
        else:
            vals = np.asarray([lut.get(str(v), np.nan)
                               for v in c.to_numpy()])
        out[n] = np.where(np.isnan(vals), nm, vals)
    return _rebuild(f, out, keep_domains=False)


@prim("h2o.which")
def _which(env, x):
    """Row numbers (0-based) where the predicate column is non-zero;
    NA predicate rows are excluded (R which() semantics)."""
    f = _as_frame(env.ev(x))
    v = _col_np(f, f.names[0])
    hit = np.where(~np.isnan(v) & (v != 0))[0]
    return Frame.from_numpy({"which": hit.astype(np.float64)})


def _which_extreme(best_of):
    def fn(env, x, na_rm=("num", 1), axis=("num", 0)):
        """idxmax/idxmin (h2o-py frame.py): axis=0 → per-column max-row
        index (1-row frame); axis=1 → per-row argmax across columns.
        All-NaN slices yield NA instead of raising."""
        f = _as_frame(env.ev(x))
        ax = int(env.ev(axis))
        M = np.stack([_col_np(f, n) for n in f.names], axis=1)
        fill = -np.inf if best_of == "max" else np.inf
        Mf = np.where(np.isnan(M), fill, M)
        pick = np.argmax(Mf, axis=ax) if best_of == "max" \
            else np.argmin(Mf, axis=ax)
        all_na = np.isnan(M).all(axis=ax)
        out = np.where(all_na, np.nan, pick.astype(float))
        name = f"which.{best_of}"
        if ax == 0:
            return Frame.from_numpy({n: np.asarray([out[j]])
                                     for j, n in enumerate(f.names)})
        return Frame.from_numpy({name: out})
    return fn


PRIMS["which.max"] = PRIMS["which_max"] = _which_extreme("max")
PRIMS["which.min"] = PRIMS["which_min"] = _which_extreme("min")


@prim("levels")
def _levels(env, x):
    f = _as_frame(env.ev(x))
    dom = f.col(f.names[0]).domain or []
    return Frame.from_numpy({"levels": np.asarray(dom, dtype=object)},
                            categorical=["levels"])


@prim("nlevels")
def _nlevels(env, x):
    f = _as_frame(env.ev(x))
    return float(f.col(f.names[0]).cardinality)


def _per_column_flags(f, pred):
    """Per-column 0/1 list — h2o-py's isfactor()/isnumeric()/isstring()
    iterate the scalar result (h2o-py/h2o/frame.py:1820)."""
    return [float(pred(f.col(n))) for n in f.names]


@prim("is.factor")
def _is_factor(env, x):
    f = _as_frame(env.ev(x))
    return _per_column_flags(f, lambda c: c.is_categorical)


@prim("is.numeric")
def _is_numeric(env, x):
    f = _as_frame(env.ev(x))
    return _per_column_flags(f, lambda c: c.is_numeric)


@prim("is.character")
def _is_character(env, x):
    f = _as_frame(env.ev(x))
    return _per_column_flags(f, lambda c: c.type == "string")


@prim("anyfactor")
def _anyfactor(env, x):
    f = _as_frame(env.ev(x))
    return float(any(f.col(n).is_categorical for n in f.names))


@prim("any.na")
def _any_na(env, x):
    f = _as_frame(env.ev(x))
    for n in f.names:
        c = f.col(n)
        if c.type == "string":
            if any(v is None for v in c.to_numpy()):
                return 1.0
        elif bool(_fetch_np(c.na_mask)[: f.nrows].any()):
            return 1.0
    return 0.0


@prim("cor")
def _cor(env, x, y=None, use=("str", "everything"), *rest):
    """Pearson correlation (AstCorrelation). use='everything' propagates
    NaN; 'complete.obs'/'all.obs' drop NA rows first."""
    fx = _as_frame(env.ev(x))
    fy = _as_frame(env.ev(y)) if y is not None else fx
    mode = str(env.ev(use)).lower()
    a = np.stack([_col_np(fx, n) for n in fx.names], axis=1)
    b = np.stack([_col_np(fy, n) for n in fy.names], axis=1)
    if mode != "everything":
        ok = ~(np.isnan(a).any(axis=1) | np.isnan(b).any(axis=1))
        a, b = a[ok], b[ok]
    am = a - a.mean(axis=0)
    bm = b - b.mean(axis=0)
    cov = am.T @ bm / max(len(a) - 1, 1)
    sa = a.std(axis=0, ddof=1)
    sb = b.std(axis=0, ddof=1)
    cmat = cov / np.maximum(np.outer(sa, sb), 1e-300)
    if cmat.size == 1:
        return float(cmat[0, 0])
    return Frame.from_numpy({n: cmat[:, j] for j, n in enumerate(fy.names)})


@prim("skewness")
def _skewness(env, x, na_rm=("num", 1)):
    f = _as_frame(env.ev(x))
    v = _col_np(f, f.names[0])
    v = v[~np.isnan(v)]
    s = v.std(ddof=1)
    return float(((v - v.mean()) ** 3).mean() / max(s ** 3, 1e-300))


@prim("kurtosis")
def _kurtosis(env, x, na_rm=("num", 1)):
    f = _as_frame(env.ev(x))
    v = _col_np(f, f.names[0])
    v = v[~np.isnan(v)]
    s = v.std(ddof=1)
    return float(((v - v.mean()) ** 4).mean() / max(s ** 4, 1e-300))


def _str_values(f: Frame, name: str):
    """Column → list of Python strings (None for NA) for string prims."""
    c = f.col(name)
    if c.is_categorical:
        dom = np.asarray(c.domain or [], dtype=object)
        return [None if k < 0 or k >= len(dom) else dom[k]
                for k in _cat_codes(f, name)]
    return list(c.to_numpy())


@prim("strsplit")
def _strsplit(env, x, pattern):
    """Split a string/cat column → multi-column frame (AstStrSplit)."""
    f = _as_frame(env.ev(x))
    pat = env.ev(pattern)
    c = f.col(f.names[0])
    if c.is_categorical:
        dom = np.asarray(c.domain or [], dtype=object)
        codes = _cat_codes(f, f.names[0])
        vals = [None if k < 0 else dom[k] for k in codes]
    else:
        vals = list(c.to_numpy())
    def _split(v):
        if not isinstance(v, str):
            return []
        p = _re.split(pat, v)
        while p and p[-1] == "":   # Java String.split drops trailing empties
            p.pop()
        return p

    parts = [_split(v) for v in vals]
    width = max((len(p) for p in parts), default=1)
    out = {}
    for j in range(width):
        out[f"C{j + 1}"] = np.asarray(
            [p[j] if j < len(p) else None for p in parts], dtype=object)
    return Frame.from_numpy(out, categorical=list(out))


@prim("countmatches")
def _countmatches(env, x, patterns):
    f = _as_frame(env.ev(x))
    pats = env.ev(patterns)
    if isinstance(pats, tuple) and pats[0] == "list":
        pats = [p[1] for p in pats[1]]
    elif not isinstance(pats, list):
        pats = [pats]
    vals = _str_values(f, f.names[0])
    cnt = np.asarray([np.nan if not isinstance(v, str)
                      else float(sum(v.count(str(p)) for p in pats))
                      for v in vals])
    return Frame.from_numpy({f.names[0]: cnt})


@prim("entropy")
def _entropy(env, x):
    """Per-string Shannon entropy over characters (AstEntropy)."""
    f = _as_frame(env.ev(x))
    vals = _str_values(f, f.names[0])

    def ent(s):
        if not isinstance(s, str):
            return np.nan
        if not s:
            return 0.0           # AstEntropy: empty string = 0 bits
        _, cnt = np.unique(list(s), return_counts=True)
        p = cnt / cnt.sum()
        return float(-(p * np.log2(p)).sum())

    return Frame.from_numpy({f.names[0]: np.asarray([ent(v) for v in vals])})


@prim("difflag1")
def _difflag1(env, x):
    """First difference x[i] - x[i-1] (ast/prims/timeseries AstDiffLag1)."""
    f = _as_frame(env.ev(x))
    v = _col_np(f, f.names[0])
    out = np.empty_like(v)
    out[0] = np.nan
    out[1:] = v[1:] - v[:-1]
    return Frame.from_numpy({f.names[0]: out})


def _timeop(extract):
    def fn(env, x):
        f = _as_frame(env.ev(x))
        import datetime as _dt
        out = {}
        for n in f.names:
            ms = _col_np(f, n)
            vals = np.full(len(ms), np.nan)
            ok = ~np.isnan(ms)
            vals[ok] = [extract(_dt.datetime.fromtimestamp(
                m / 1000.0, _dt.timezone.utc)) for m in ms[ok]]
            out[n] = vals
        return _rebuild(f, out, keep_domains=False)
    return fn


PRIMS["year"] = _timeop(lambda d: d.year)
PRIMS["month"] = _timeop(lambda d: d.month)
PRIMS["day"] = _timeop(lambda d: d.day)
PRIMS["hour"] = _timeop(lambda d: d.hour)
PRIMS["minute"] = _timeop(lambda d: d.minute)
PRIMS["second"] = _timeop(lambda d: d.second)
PRIMS["dayOfWeek"] = _timeop(lambda d: d.weekday())
PRIMS["week"] = _timeop(lambda d: d.isocalendar()[1])


@prim("relevel")
def _relevel(env, x, level):
    """Move ``level`` to the front of the domain (AstRelevel)."""
    f = _as_frame(env.ev(x))
    lvl = str(env.ev(level))
    n = f.names[0]
    c = f.col(n)
    dom = list(c.domain or [])
    if lvl not in dom:
        raise ValueError(f"level '{lvl}' not in domain")
    new_dom = [lvl] + [d for d in dom if d != lvl]
    remap = np.asarray([new_dom.index(d) for d in dom])
    codes = _cat_codes(f, n)
    new_codes = np.where(codes < 0, -1, remap[np.maximum(codes, 0)])
    return Frame.from_numpy({n: new_codes.astype(np.int32)},
                            categorical=[n], domains={n: new_dom})


class Env:
    """Evaluation environment (water/rapids/Env.java)."""

    def __init__(self, session: Session):
        self.session = session

    def ev(self, node):
        if isinstance(node, tuple):
            tag, v = node
            if tag in ("num", "str"):
                return v
            if tag == "id":
                return self.session.lookup(v)
            if tag == "list":
                return node
            raise ValueError(f"bad node {node!r}")
        if isinstance(node, list):
            if not node:
                return None
            head = node[0]
            opname = head[1] if isinstance(head, tuple) else str(head)
            if opname not in PRIMS:
                raise ValueError(f"Rapids: unknown op '{opname}'")
            return PRIMS[opname](self, *node[1:])
        return node


_SESSION: Optional[Session] = None


def _default_session() -> Session:
    global _SESSION
    if _SESSION is None:
        _SESSION = Session()
    return _SESSION


def rapids(expr: str, session: Optional[Session] = None):
    """Parse + evaluate one Rapids expression (POST /99/Rapids)."""
    session = session or _default_session()
    return Env(session).ev(parse(expr))


# ------------------------------------------------------- extended prims
# (matrix, advmath, repeaters, filters, reshape — the remaining
# water/rapids/ast/prims families; wire names match the reference)

def _as_pylist(env, node):
    """('list', [...]) AST → python values; scalar → [scalar]."""
    if isinstance(node, tuple) and node[0] == "list":
        return [x[1] if isinstance(x, tuple) else x for x in node[1]]
    v = env.ev(node)
    return None if v is None else [v]


def _num_matrix(f: Frame) -> np.ndarray:
    # f64: matrix ops feed pyunit oracles computed in float64
    return np.stack([_col_np(f, n).astype(np.float64)
                     for n in f.names], axis=1)


@prim("t")
def _transpose(env, fr):
    """matrix/AstTranspose."""
    f = _as_frame(env.ev(fr))
    M = _num_matrix(f).T
    return Frame.from_numpy({f"C{i + 1}": M[:, i] for i in range(M.shape[1])})


@prim("x")
def _mmult(env, l, r):
    """matrix/AstMMult: frame-as-matrix product."""
    A = _num_matrix(_as_frame(env.ev(l)))
    B = _num_matrix(_as_frame(env.ev(r)))
    M = A @ B
    return Frame.from_numpy({f"C{i + 1}": M[:, i] for i in range(M.shape[1])})


@prim("hist")
def _hist(env, fr, breaks=("str", "sturges")):
    """advmath/AstHist: breaks/counts/mids frame (h2o-py frame.hist)."""
    f = _as_frame(env.ev(fr))
    v = _col_np(f, f.names[0])
    v = v[~np.isnan(v)]
    b = breaks[1] if isinstance(breaks, tuple) and breaks[0] in ("num", "str") \
        else breaks
    lst = _as_pylist(env, breaks) if isinstance(breaks, tuple) and \
        breaks[0] == "list" else None
    if lst is not None:
        edges = np.asarray(lst, np.float64)
    elif isinstance(b, (int, float)) and not isinstance(b, bool):
        edges = np.linspace(v.min(), v.max(), int(b) + 1) if v.size else \
            np.array([0.0, 1.0])
    else:   # sturges / rice / sqrt / doane / scott / fd
        rule = str(b).lower()
        n = max(v.size, 1)
        if rule == "rice":
            k = int(np.ceil(2 * n ** (1 / 3)))
        elif rule == "sqrt":
            k = int(np.ceil(np.sqrt(n)))
        else:   # sturges default
            k = int(np.ceil(np.log2(n))) + 1
        edges = np.linspace(v.min(), v.max(), max(k, 1) + 1) if v.size else \
            np.array([0.0, 1.0])
    counts, edges = np.histogram(v, bins=edges)
    widths = np.diff(edges)
    dens = counts / np.maximum(widths * max(v.size, 1), 1e-300)
    mids = 0.5 * (edges[:-1] + edges[1:])
    pad = lambda a: np.concatenate([[np.nan], a])
    return Frame.from_numpy({
        "breaks": edges.astype(np.float64),
        "counts": pad(counts.astype(np.float64)),
        "mids_true": pad(mids), "mids": pad(mids),
        "density": pad(dens)})


@prim("cut")
def _cut(env, fr, breaks, labels=None, include_lowest=("num", 0),
         right=("num", 1), dig_lab=("num", 3)):
    """mungers/AstCut: numeric → categorical by bin edges."""
    f = _as_frame(env.ev(fr))
    edges = np.asarray(_as_pylist(env, breaks), np.float64)
    labs = _as_pylist(env, labels) if labels is not None else None
    inc_low = bool(env.ev(include_lowest))
    rgt = bool(env.ev(right))
    dig = int(env.ev(dig_lab))
    v = _col_np(f, f.names[0])
    if labs:
        dom = [str(x) for x in labs]
    elif rgt:
        dom = [f"({round(edges[i], dig)}, {round(edges[i + 1], dig)}]"
               for i in range(len(edges) - 1)]
    else:
        dom = [f"[{round(edges[i], dig)}, {round(edges[i + 1], dig)})"
               for i in range(len(edges) - 1)]
    if rgt:
        codes = np.searchsorted(edges, v, side="left") - 1
        if inc_low:
            codes[v == edges[0]] = 0
    else:
        codes = np.searchsorted(edges, v, side="right") - 1
    codes = codes.astype(np.int32)
    bad = np.isnan(v) | (codes < 0) | (codes >= len(dom))
    codes[bad] = -1
    return Frame.from_numpy({f.names[0]: codes}, categorical=[f.names[0]],
                            domains={f.names[0]: dom})


@prim("h2o.fillna", "fillna")
def _fillna(env, fr, method=("str", "forward"), axis=("num", 0),
            maxlen=("num", 1)):
    """mungers/AstFillNA: directional NA fill with a run cap.

    Vectorized: last-valid-index propagation via maximum.accumulate +
    a run-length cap; column order is preserved; strings pass through.
    """
    f = _as_frame(env.ev(fr))
    meth = str(env.ev(method)).lower()
    ax = int(env.ev(axis))
    cap = int(env.ev(maxlen))
    forward = meth == "forward"

    def capped_fill(M):
        """Fill along axis 1 of a [n, m] float matrix."""
        if not forward:
            M = M[:, ::-1]
        valid = ~np.isnan(M)
        m = M.shape[1]
        idx = np.arange(m)[None, :]
        last = np.maximum.accumulate(np.where(valid, idx, -1), axis=1)
        rows = np.arange(M.shape[0])[:, None]
        src = M[rows, np.maximum(last, 0)]
        fill = ~valid & (last >= 0) & (idx - last <= cap)
        out = np.where(fill, src, M)
        return out[:, ::-1] if not forward else out

    out, cats, doms, strs = {}, [], {}, []
    if ax == 0:     # along rows, per column
        for n in f.names:
            c = f.col(n)
            if c.type == "string":
                out[n] = c.to_numpy()
                strs.append(n)
                continue
            v = (_cat_codes(f, n).astype(np.float64) if c.is_categorical
                 else _col_np(f, n))
            if c.is_categorical:
                v = np.where(v < 0, np.nan, v)
            v = capped_fill(v[None, :])[0]
            if c.is_categorical:
                out[n] = np.where(np.isnan(v), -1, v).astype(np.int32)
                cats.append(n)
                doms[n] = c.domain
            else:
                out[n] = v
    else:           # along columns, per row (numeric columns only)
        num_names = [n for n in f.names if not f.col(n).is_categorical
                     and f.col(n).type != "string"]
        M = (np.stack([_col_np(f, n) for n in num_names], axis=1)
             if num_names else None)
        if M is not None:
            M = capped_fill(M)
        for n in f.names:          # original order preserved
            c = f.col(n)
            if c.type == "string":
                out[n] = c.to_numpy()
                strs.append(n)
            elif c.is_categorical:
                out[n] = _cat_codes(f, n)
                cats.append(n)
                doms[n] = c.domain
            else:
                out[n] = M[:, num_names.index(n)]
    return Frame.from_numpy(out, categorical=cats, domains=doms,
                            strings=strs)


@prim("kfold_column")
def _kfold_column(env, fr, nfolds, seed=("num", -1)):
    """advmath/AstKFold: uniform random fold ids."""
    f = _as_frame(env.ev(fr))
    k = int(env.ev(nfolds))
    s = int(env.ev(seed))
    # seed==-1 means "draw a fresh random seed" in the reference, not a
    # fixed constant (AstKFold)
    r = np.random.RandomState(
        s if s >= 0 else np.random.SeedSequence().entropy % (2**32))
    return Frame.from_numpy(
        {"fold": r.randint(0, k, f.nrows).astype(np.float64)})


@prim("modulo_kfold_column")
def _modulo_kfold(env, fr, nfolds):
    f = _as_frame(env.ev(fr))
    k = int(env.ev(nfolds))
    return Frame.from_numpy(
        {"fold": (np.arange(f.nrows) % k).astype(np.float64)})


@prim("stratified_kfold_column")
def _strat_kfold(env, fr, nfolds, seed=("num", -1)):
    """advmath/AstStratifiedKFold: per-class round-robin after shuffle —
    every fold sees ~the same class distribution."""
    f = _as_frame(env.ev(fr))
    k = int(env.ev(nfolds))
    s = int(env.ev(seed))
    r = np.random.RandomState(
        s if s >= 0 else np.random.SeedSequence().entropy % (2**32))
    y = _cat_codes(f, f.names[0]) if f.col(f.names[0]).is_categorical \
        else _col_np(f, f.names[0])
    fold = np.zeros(f.nrows, np.float64)
    for cls in np.unique(y[~np.isnan(np.asarray(y, np.float64))]):
        idx = np.where(y == cls)[0]
        r.shuffle(idx)
        fold[idx] = np.arange(len(idx)) % k
    return Frame.from_numpy({"fold": fold})


@prim("h2o.random_stratified_split")
def _strat_split(env, fr, test_frac=("num", 0.25), seed=("num", -1)):
    """advmath/AstStratifiedSplit: per-class train/test tagging."""
    f = _as_frame(env.ev(fr))
    frac = float(env.ev(test_frac))
    s = int(env.ev(seed))
    r = np.random.RandomState(s if s >= 0 else 0x57A7)
    y = _cat_codes(f, f.names[0]) if f.col(f.names[0]).is_categorical \
        else _col_np(f, f.names[0])
    codes = np.zeros(f.nrows, np.int32)
    for cls in np.unique(y):
        idx = np.where(y == cls)[0]
        r.shuffle(idx)
        ntest = int(round(len(idx) * frac))
        codes[idx[:ntest]] = 1
    return Frame.from_numpy({"test_train_split": codes},
                            categorical=["test_train_split"],
                            domains={"test_train_split": ["train", "test"]})


@prim("seq_len")
def _seq_len(env, n):
    """repeaters/AstSeqLen: 1..n."""
    return Frame.from_numpy(
        {"C1": np.arange(1, int(env.ev(n)) + 1, dtype=np.float64)})


@prim("seq")
def _seq(env, fro, to, by=("num", 1)):
    a, b, st = float(env.ev(fro)), float(env.ev(to)), float(env.ev(by))
    # extend the stop by half a step IN the step direction so the
    # endpoint is included for both signs (R-style seq)
    return Frame.from_numpy(
        {"C1": np.arange(a, b + st / 2, st, dtype=np.float64)})


@prim("rep_len")
def _rep_len(env, x, length):
    """AstRepLen: single column → repeat ROWS to length; multi-column
    frame → repeat COLUMNS cyclically to length columns."""
    n = int(env.ev(length))
    v = env.ev(x)
    if not isinstance(v, Frame):
        return Frame.from_numpy({"C1": np.full(n, float(v))})
    if v.ncols == 1:
        # output vec is wrapped in an UNNAMED frame → default name C1
        # (AstRepLen.java:50 `new Frame(vec)`)
        nm = v.names[0]
        c = v.col(nm)
        if c.is_categorical:
            return Frame.from_numpy(
                {"C1": np.resize(_cat_codes(v, nm), n)},
                categorical=["C1"], domains={"C1": c.domain})
        return Frame.from_numpy(
            {"C1": np.resize(_col_np(v, nm), n).astype(np.float64)})
    out, cats, doms = {}, [], {}
    for i in range(n):
        src = v.names[i % v.ncols]
        nm = f"C{i + 1}"
        c = v.col(src)
        if c.is_categorical:
            out[nm] = _cat_codes(v, src)
            cats.append(nm)
            doms[nm] = c.domain
        else:
            out[nm] = _col_np(v, src)
    return Frame.from_numpy(out, categorical=cats, domains=doms)


@prim("distance")
def _distance(env, l, r, measure=("str", "l2")):
    """advmath/AstDistance: pairwise row distances [n_l x n_r]."""
    A = _num_matrix(_as_frame(env.ev(l)))
    B = _num_matrix(_as_frame(env.ev(r)))
    m = str(env.ev(measure)).lower()
    if m in ("l2", "euclidean"):
        D = np.sqrt(np.maximum(
            (A ** 2).sum(1)[:, None] + (B ** 2).sum(1)[None, :]
            - 2 * A @ B.T, 0.0))
    elif m == "l1":
        D = np.abs(A[:, None, :] - B[None, :, :]).sum(axis=2)
    elif m in ("cosine", "cosine_sq"):
        na = np.linalg.norm(A, axis=1)
        nb = np.linalg.norm(B, axis=1)
        C = (A @ B.T) / np.maximum(na[:, None] * nb[None, :], 1e-300)
        D = C ** 2 if m == "cosine_sq" else C
    else:
        raise ValueError(f"unknown distance measure '{m}'")
    return Frame.from_numpy({f"C{i + 1}": D[:, i] for i in range(D.shape[1])})


@prim("dropdup")
def _dropdup(env, fr, cols_sel, keep=("str", "first")):
    """filters/dropduplicates AstDropDuplicatesByColumns."""
    f = _as_frame(env.ev(fr))
    names = _resolve_cols(f, cols_sel)
    kp = str(env.ev(keep)).lower()

    def keycol(n):
        c = f.col(n)
        if c.is_categorical:
            return _cat_codes(f, n).astype(np.float64)
        if c.type == "string":
            # intern strings to codes so keys stay numeric (None -> nan)
            vals = c.to_numpy()
            lut = {}
            return np.array(
                [np.nan if v is None else lut.setdefault(v, len(lut))
                 for v in vals], np.float64)
        return _col_np(f, n)

    keyarr = np.stack([keycol(n) for n in names], axis=1)
    seen = {}
    order = range(f.nrows) if kp == "first" else range(f.nrows - 1, -1, -1)
    nan_mask = np.isnan(keyarr)
    key_vals = np.where(nan_mask, 0.0, keyarr)
    for i in order:
        # NaN != NaN, so carry the NA pattern separately to make
        # NA-keyed duplicates compare equal
        key = (tuple(key_vals[i].tolist()), tuple(nan_mask[i].tolist()))
        seen.setdefault(key, i)
    idx = np.array(sorted(seen.values()), dtype=np.int64)
    return _take_rows(f, idx)


@prim("grep")
def _grep(env, fr, regex, ignore_case=("num", 0), invert=("num", 0),
          output_logical=("num", 0)):
    """string/AstGrep: match rows of a string/categorical column."""
    f = _as_frame(env.ev(fr))
    pat = str(env.ev(regex))
    flags = _re.IGNORECASE if env.ev(ignore_case) else 0
    rx = _re.compile(pat, flags)
    c = f.col(f.names[0])
    if c.is_categorical:
        dom = c.domain or []
        dom_hit = np.array([bool(rx.search(s)) for s in dom])
        codes = _cat_codes(f, f.names[0])
        hit = np.where(codes >= 0, dom_hit[np.maximum(codes, 0)], False)
    else:
        hit = np.array([bool(rx.search(str(v))) if v is not None else False
                        for v in c.to_numpy()])
    if env.ev(invert):
        hit = ~hit
    if env.ev(output_logical):
        return Frame.from_numpy({"C1": hit.astype(np.float64)})
    return Frame.from_numpy(
        {"C1": np.where(hit)[0].astype(np.float64)})


def _strip_prim(side):
    def fn(env, fr, chars=("str", " ")):
        f = _as_frame(env.ev(fr))
        cs = str(env.ev(chars))
        out, cats, doms = {}, [], {}
        for n in f.names:
            c = f.col(n)
            if c.is_categorical:
                dom = [s.lstrip(cs) if side == "l" else s.rstrip(cs)
                       for s in (c.domain or [])]
                # re-intern: stripping may merge levels
                uniq = sorted(set(dom))
                remap = np.array([uniq.index(d) for d in dom], np.int32)
                codes = _cat_codes(f, n)
                out[n] = np.where(codes >= 0, remap[np.maximum(codes, 0)],
                                  -1).astype(np.int32)
                cats.append(n)
                doms[n] = uniq
            elif c.type == "string":
                out[n] = np.array(
                    [None if v is None else
                     (v.lstrip(cs) if side == "l" else v.rstrip(cs))
                     for v in c.to_numpy()], dtype=object)
            else:
                out[n] = _col_np(f, n)
        return Frame.from_numpy(out, categorical=cats, domains=doms)
    return fn


PRIMS["lstrip"] = _strip_prim("l")
PRIMS["rstrip"] = _strip_prim("r")


@prim("melt")
def _melt(env, fr, id_vars, value_vars=None, var_name=("str", "variable"),
          value_name=("str", "value"), skipna=("num", 0)):
    """mungers/AstMelt: wide → long."""
    f = _as_frame(env.ev(fr))
    ids = _resolve_cols(f, id_vars)
    vals = _resolve_cols(f, value_vars) if value_vars is not None and \
        not (isinstance(value_vars, tuple) and value_vars[1] is None) else \
        [n for n in f.names if n not in ids]
    vname = str(env.ev(var_name))
    vvalue = str(env.ev(value_name))
    skip = bool(env.ev(skipna))
    n = f.nrows
    id_cols = {k: [] for k in ids}
    var_codes, values = [], []
    id_data = {k: (_cat_codes(f, k) if f.col(k).is_categorical
                   else _col_np(f, k)) for k in ids}
    for vi, vn in enumerate(vals):
        col = _col_np(f, vn)
        keep = ~np.isnan(col) if skip else np.ones(n, bool)
        for k in ids:
            id_cols[k].append(np.asarray(id_data[k])[keep])
        var_codes.append(np.full(keep.sum(), vi, np.int32))
        values.append(col[keep])
    out, cats, doms = {}, [], {}
    for k in ids:
        merged = np.concatenate(id_cols[k])
        if f.col(k).is_categorical:
            out[k] = merged.astype(np.int32)
            cats.append(k)
            doms[k] = f.col(k).domain
        else:
            out[k] = merged.astype(np.float64)
    out[vname] = np.concatenate(var_codes)
    cats.append(vname)
    doms[vname] = list(vals)
    out[vvalue] = np.concatenate(values)
    return Frame.from_numpy(out, categorical=cats, domains=doms)


@prim("pivot")
def _pivot(env, fr, index, column, value):
    """mungers/AstPivot: long → wide (first value per cell)."""
    f = _as_frame(env.ev(fr))
    inames = _resolve_cols(f, index)
    cname = _resolve_cols(f, column)[0]
    vname = _resolve_cols(f, value)[0]
    iname = inames[0]
    icol_cat = f.col(iname).is_categorical
    ivals = _cat_codes(f, iname) if icol_cat else _col_np(f, iname)
    cc = f.col(cname)
    if cc.is_categorical:
        levels = list(cc.domain or [])
        ccode = _cat_codes(f, cname)
    else:
        raw = _col_np(f, cname)
        lv = np.unique(raw[~np.isnan(raw)])
        levels = [str(x) for x in lv]
        ccode = np.searchsorted(lv, raw)
    vvals = _col_np(f, vname)
    uniq = np.unique(np.asarray(ivals, np.float64))
    uniq = uniq[~np.isnan(uniq)]
    pos = {u: i for i, u in enumerate(uniq)}
    M = np.full((len(uniq), len(levels)), np.nan)
    for i in range(f.nrows):
        iv = float(ivals[i])
        if np.isnan(iv) or ccode[i] < 0 or ccode[i] >= len(levels):
            continue
        r_ = pos[iv]
        if np.isnan(M[r_, ccode[i]]):
            M[r_, ccode[i]] = vvals[i]
    out, cats, doms = {}, [], {}
    if icol_cat:
        out[iname] = uniq.astype(np.int32)
        cats.append(iname)
        doms[iname] = f.col(iname).domain
    else:
        out[iname] = uniq
    for j, lev in enumerate(levels):
        out[str(lev)] = M[:, j]
    return Frame.from_numpy(out, categorical=cats, domains=doms)
