"""Vendored R syntax validator — the `parse()` stand-in.

No R runtime exists in this image (VERDICT r1 item 9), so generated R
sources are validated with a real tokenizer + structural checks that
catch the error classes `R CMD check`'s parse step would: unterminated
strings, unbalanced delimiters (with string/comment stripping), operators
dangling at end-of-file, malformed `function(...)` headers, and `<-`
assignments without a left-hand side.
"""

from __future__ import annotations

import re
from typing import List, Tuple

_OPS = {"+", "-", "*", "/", "^", "<-", "<<-", "->", "=", "==", "!=",
        "<", ">", "<=", ">=", "&", "&&", "|", "||", "%%", "%/%", "%in%",
        "$", "@", "~", "?", ":", ","}


def tokenize_r(src: str) -> List[Tuple[str, str]]:
    """(kind, text) tokens; raises ValueError on lexical errors."""
    tokens: List[Tuple[str, str]] = []
    i, n = 0, len(src)
    while i < n:
        ch = src[i]
        if ch in " \t\r":
            i += 1
        elif ch == "\n":
            tokens.append(("newline", "\n"))
            i += 1
        elif ch == "#":
            while i < n and src[i] != "\n":
                i += 1
        elif ch in "\"'":
            q = ch
            j = i + 1
            while j < n and src[j] != q:
                j += 2 if src[j] == "\\" else 1
            if j >= n:
                raise ValueError(f"unterminated string at offset {i}")
            tokens.append(("string", src[i:j + 1]))
            i = j + 1
        elif ch == "`":
            j = src.find("`", i + 1)
            if j < 0:
                raise ValueError(f"unterminated backtick name at {i}")
            tokens.append(("name", src[i:j + 1]))
            i = j + 1
        elif ch.isdigit() or (ch == "." and i + 1 < n
                              and src[i + 1].isdigit()):
            m = re.match(r"[0-9.]+([eE][+-]?\d+)?L?i?", src[i:])
            tokens.append(("number", m.group(0)))
            i += m.end()
        elif ch.isalpha() or ch in "._":
            m = re.match(r"[A-Za-z._][A-Za-z0-9._]*", src[i:])
            tokens.append(("name", m.group(0)))
            i += m.end()
        elif ch == "%":
            j = src.find("%", i + 1)
            if j < 0:
                raise ValueError(f"unterminated %op% at {i}")
            tokens.append(("op", src[i:j + 1]))
            i = j + 1
        elif ch in "()[]{}":
            tokens.append(("bracket", ch))
            i += 1
        elif src[i:i + 3] in ("<<-",):
            tokens.append(("op", src[i:i + 3]))
            i += 3
        elif src[i:i + 2] in ("<-", "->", "==", "!=", "<=", ">=", "&&",
                              "||", "::"):
            tokens.append(("op", src[i:i + 2]))
            i += 2
        elif ch in "+-*/^<>=!&|~?:;,$@":
            tokens.append(("op", ch))
            i += 1
        else:
            raise ValueError(f"unexpected character {ch!r} at offset {i}")
    return tokens


def check_r_source(src: str) -> List[str]:
    """Structural validation; returns a list of error strings (empty =
    passes the parse-level checks)."""
    errors: List[str] = []
    try:
        tokens = tokenize_r(src)
    except ValueError as e:
        return [str(e)]

    # balanced delimiters with correct nesting
    stack: List[str] = []
    pairs = {")": "(", "]": "[", "}": "{"}
    for kind, text in tokens:
        if kind != "bracket":
            continue
        if text in "([{":
            stack.append(text)
        else:
            if not stack or stack[-1] != pairs[text]:
                errors.append(f"mismatched '{text}'")
                break
            stack.pop()
    if stack:
        errors.append(f"unclosed '{stack[-1]}'")

    code = [(k, t) for k, t in tokens if k != "newline"]
    # function headers: `function` must be followed by '('
    for j, (kind, text) in enumerate(code):
        if kind == "name" and text == "function":
            if j + 1 >= len(code) or code[j + 1][1] != "(":
                errors.append("`function` not followed by '('")
        if kind == "op" and text in ("<-", "<<-"):
            if j == 0 or code[j - 1][0] not in ("name", "string") \
                    and code[j - 1][1] not in (")", "]"):
                errors.append("assignment without assignable LHS")
    # dangling operator at EOF
    if code and code[-1][0] == "op" and code[-1][1] not in (";",):
        errors.append(f"dangling operator {code[-1][1]!r} at EOF")
    return errors
