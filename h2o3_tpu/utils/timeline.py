"""TimeLine — lock-free-ish ring buffer of runtime events.

Reference: water/TimeLine.java:22 — an Unsafe-based ring recording every
UDP/TCP packet cheaply, snapshotable cloud-wide via GET /3/Timeline
(water/init/TimelineSnapshot.java). The TPU runtime has no packet layer
to tap, so the recorded events are the runtime's own control-plane
moments: REST requests, job lifecycle, parse/train milestones, and
collective-heavy program dispatches. Recording must stay cheap enough
to leave on always (the reference's design constraint).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

_CAPACITY = 2048
_events: deque = deque(maxlen=_CAPACITY)
_lock = threading.Lock()
_seq = 0


def record(kind: str, what: str, **info) -> None:
    """Append one event (TimeLine.record_IOclose-style cheap append).

    Events recorded while a telemetry span is active carry its id, so
    the flat ring can be joined against the span tree (/3/Metrics)."""
    global _seq
    if "span_id" not in info:
        try:
            from h2o3_tpu.telemetry.spans import current_span_id
            sid = current_span_id()
            if sid is not None:
                info["span_id"] = sid
        except Exception:   # noqa: BLE001 - the ring must never fail
            pass
    ev = {"seq": 0, "ts_ms": int(time.time() * 1000),
          "kind": kind, "what": what, **info}
    with _lock:
        _seq += 1
        ev["seq"] = _seq
        _events.append(ev)
    try:
        from h2o3_tpu.telemetry import flight_recorder
        flight_recorder.record_event(ev)
    except Exception:   # noqa: BLE001 - the ring must never fail
        pass


def snapshot(last: Optional[int] = None) -> List[Dict]:
    """Consistent copy of the ring (TimelineSnapshot role)."""
    with _lock:
        evs = list(_events)
    try:
        n = int(last) if last is not None else 0
    except (TypeError, ValueError):
        n = 0
    if n > 0:
        evs = evs[-n:]
    return evs


def clear() -> None:
    with _lock:
        _events.clear()
