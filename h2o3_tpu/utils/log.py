"""Leveled logging (analogue of water.util.Log, reference
h2o-core/src/main/java/water/util/Log.java:24).

The reference keeps per-node rotating files via log4j; here a thin wrapper
over the stdlib so every subsystem logs through one place and the REST
``/3/Logs`` endpoint can replay the buffer.
"""

from __future__ import annotations

import logging
import collections

_BUFFER: collections.deque = collections.deque(maxlen=10000)


class _BufferHandler(logging.Handler):
    def emit(self, record: logging.LogRecord) -> None:
        _BUFFER.append(self.format(record))


_logger = logging.getLogger("h2o3_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s"))
    _logger.addHandler(_h)
    _b = _BufferHandler()
    _b.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s %(message)s"))
    _logger.addHandler(_b)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False


def get_logger(name: str = "h2o3_tpu") -> logging.Logger:
    return logging.getLogger(name)


def log_buffer() -> list:
    """Recent log lines — backs GET /3/Logs (water/api/LogsHandler.java)."""
    return list(_BUFFER)
