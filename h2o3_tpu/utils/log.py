"""Structured logging pipeline (analogue of water.util.Log, reference
h2o-core/src/main/java/water/util/Log.java:24).

The reference keeps per-node rotating log4j files and replays them over
``GET /3/Logs``. This is the same discipline for one controller
process, rebuilt as a pipeline every subsystem shares:

- **one root** — every logger is a child of ``h2o3_tpu`` (``get_logger``
  normalizes bare names to ``h2o3_tpu.<name>``), so the sinks below see
  every record exactly once;
- **context filter** — each record is stamped with the active telemetry
  ``span_id`` (telemetry/spans.py) and ``job_id``
  (core/request_ctx.py), tying log lines to the span tree and to the
  per-job flight recorder capsule;
- **ring sinks** — a combined ring plus per-level rings back
  ``GET /3/Logs``; the structured record dicts feed the flight
  recorder (telemetry/flight_recorder.py) so a job's capsule carries
  its own log lines;
- **file sink** — ``H2O3TPU_LOG_DIR`` enables a rotating per-process
  file (``h2o3tpu-<pid>.log``, the reference's per-node log-file
  discipline; size/backups via ``H2O3TPU_LOG_FILE_MB`` /
  ``H2O3TPU_LOG_FILE_BACKUPS``) that ``GET /3/Logs/download`` serves;
- **JSON lines** — ``H2O3TPU_LOG_JSON=1`` switches the stream and file
  sinks to one-JSON-object-per-line (``ts``, ``level``, ``logger``,
  ``msg``, ``span_id``, ``job_id``, ``thread``), scrape-ready.
"""

from __future__ import annotations

import collections
import json
import logging
import logging.handlers
import os
import threading
from typing import Dict, List, Optional

ROOT = "h2o3_tpu"

_RING_CAPACITY = 10000
_LEVEL_RING_CAPACITY = 2000

_BUFFER: collections.deque = collections.deque(maxlen=_RING_CAPACITY)
_LEVEL_BUFFERS: Dict[str, collections.deque] = {
    lvl: collections.deque(maxlen=_LEVEL_RING_CAPACITY)
    for lvl in ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")}
# structured twin of _BUFFER: (ts_ms, level, line, node) dicts — what a
# cluster-merged /3/Logs?cluster=1 tail sorts by (telemetry/cluster.py)
_RECORDS: collections.deque = collections.deque(maxlen=_RING_CAPACITY)
_setup_lock = threading.Lock()
_file_path: Optional[str] = None

# this process's cloud identity (jax process_index), stamped on every
# record so merged cluster views and shipped log files stay
# attributable. Set by core/cloud.py at init — NEVER read from
# jax.process_index() here: logging runs before (and during) backend
# bootstrap and must not re-enter it.
_NODE = 0


def set_node(node: int) -> None:
    global _NODE
    _NODE = int(node)


def current_node() -> int:
    return _NODE


class ContextFilter(logging.Filter):
    """Stamp every record with the active span/job ids — the join key
    between a flat log line, the span tree, and a job's capsule.
    Lazy imports: log.py is imported before telemetry on some paths and
    must never create a cycle; missing context is just empty."""

    def filter(self, record: logging.LogRecord) -> bool:
        span_id = job_id = ""
        try:
            from h2o3_tpu.telemetry.spans import current_span_id
            span_id = current_span_id() or ""
        except Exception:   # noqa: BLE001 - logging must never fail
            pass
        try:
            from h2o3_tpu.core.request_ctx import current_job
            job = current_job()
            job_id = getattr(job, "key", "") if job is not None else ""
        except Exception:   # noqa: BLE001
            pass
        record.span_id = span_id
        record.job_id = job_id
        record.node = _NODE
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line — the machine end of the pipeline."""

    def format(self, record: logging.LogRecord) -> str:
        d = {"ts": round(record.created, 3),
             "level": record.levelname,
             "logger": record.name,
             "msg": record.getMessage(),
             "span_id": getattr(record, "span_id", ""),
             "job_id": getattr(record, "job_id", ""),
             "node": getattr(record, "node", _NODE),
             "thread": record.threadName}
        if record.exc_info:
            d["exc"] = self.formatException(record.exc_info)
        return json.dumps(d)


class _TextFormatter(logging.Formatter):
    """Human format; the span/job stamp renders only when present."""

    def format(self, record: logging.LogRecord) -> str:
        sid = getattr(record, "span_id", "")
        jid = getattr(record, "job_id", "")
        ctx = " ".join(x for x in (sid, jid) if x)
        record.ctx = f" [{ctx}]" if ctx else ""
        return super().format(record)


class _RingHandler(logging.Handler):
    """Combined + per-level rings, plus the flight-recorder feed."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:   # noqa: BLE001
            line = record.getMessage()
        _BUFFER.append(line)
        buf = _LEVEL_BUFFERS.get(record.levelname)
        if buf is not None:
            buf.append(line)
        _RECORDS.append({"ts_ms": int(record.created * 1000),
                         "level": record.levelname,
                         "line": line,
                         "node": getattr(record, "node", _NODE)})
        try:
            from h2o3_tpu.telemetry import flight_recorder
            if flight_recorder.is_recording():
                flight_recorder.record_log({
                    "ts_ms": int(record.created * 1000),
                    "level": record.levelname,
                    "logger": record.name,
                    "msg": record.getMessage(),
                    "span_id": getattr(record, "span_id", ""),
                    "job_id": getattr(record, "job_id", ""),
                    "node": getattr(record, "node", _NODE),
                })
        except Exception:   # noqa: BLE001 - capture is best-effort
            pass


def _formatter(json_lines: bool) -> logging.Formatter:
    if json_lines:
        return JsonFormatter()
    return _TextFormatter(
        "%(asctime)s %(levelname).1s %(name)s%(ctx)s: %(message)s")


def configure(level: Optional[str] = None,
              log_dir: Optional[str] = None,
              json_lines: Optional[bool] = None) -> None:
    """(Re)build the pipeline on the ``h2o3_tpu`` root logger.

    Arguments default to the env knobs (``H2O3TPU_LOG_LEVEL``,
    ``H2O3TPU_LOG_DIR``, ``H2O3TPU_LOG_JSON``); safe to call again —
    ``init()`` re-runs it so knobs set after import take effect."""
    global _file_path
    if level is None:
        level = os.environ.get("H2O3TPU_LOG_LEVEL", "INFO")
    if log_dir is None:
        log_dir = os.environ.get("H2O3TPU_LOG_DIR", "")
    if json_lines is None:
        json_lines = os.environ.get("H2O3TPU_LOG_JSON", "0") == "1"
    with _setup_lock:
        root = logging.getLogger(ROOT)
        for h in list(root.handlers):
            root.removeHandler(h)
            try:
                if isinstance(h, logging.handlers.RotatingFileHandler):
                    h.close()
            except Exception:   # noqa: BLE001
                pass
        # the filter rides each HANDLER (a logger-level filter would
        # only see records logged directly on the root, not on
        # h2o3_tpu.* children — stdlib filter propagation rules)
        ctx_filter = ContextFilter()

        stream = logging.StreamHandler()
        stream.setFormatter(_formatter(json_lines))
        stream.addFilter(ctx_filter)
        root.addHandler(stream)

        ring = _RingHandler()
        ring.setFormatter(_formatter(json_lines))
        ring.addFilter(ctx_filter)
        root.addHandler(ring)

        _file_path = None
        if log_dir:
            try:
                os.makedirs(log_dir, exist_ok=True)
                max_mb = int(os.environ.get("H2O3TPU_LOG_FILE_MB", "64"))
                backups = int(os.environ.get("H2O3TPU_LOG_FILE_BACKUPS",
                                             "3"))
                path = os.path.join(log_dir, f"h2o3tpu-{os.getpid()}.log")
                fh = logging.handlers.RotatingFileHandler(
                    path, maxBytes=max_mb << 20, backupCount=backups)
                fh.setFormatter(_formatter(json_lines))
                fh.addFilter(ctx_filter)
                root.addHandler(fh)
                _file_path = path
            except OSError:
                root.warning("log dir %r unusable; file sink disabled",
                             log_dir)
        root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
        root.propagate = False


configure()


def get_logger(name: str = ROOT) -> logging.Logger:
    """Logger in the ``h2o3_tpu`` hierarchy. Bare names are normalized
    to ``h2o3_tpu.<name>`` children — a logger outside the configured
    root would bypass every sink above (the ``/3/Logs`` replay, the
    file, the flight recorder)."""
    if name != ROOT and not name.startswith(ROOT + "."):
        name = f"{ROOT}.{name}"
    return logging.getLogger(name)


def log_buffer(level: Optional[str] = None,
               last: Optional[int] = None) -> List[str]:
    """Recent log lines — backs GET /3/Logs (water/api/LogsHandler.java).
    ``level`` selects one per-level ring; default is the combined ring."""
    if level:
        buf = _LEVEL_BUFFERS.get(str(level).upper())
        lines = list(buf) if buf is not None else []
    else:
        lines = list(_BUFFER)
    if last is not None and last > 0:
        lines = lines[-last:]
    return lines


def log_records(last: Optional[int] = None) -> List[Dict]:
    """Structured recent records ({ts_ms, level, line, node}) — the
    timestamp-ordered feed a cluster-merged log tail is built from
    (telemetry/cluster.py publishes this ring's tail per peer)."""
    recs = list(_RECORDS)
    if last is not None and last > 0:
        recs = recs[-last:]
    return recs


def log_file_path() -> Optional[str]:
    """Rotating-file sink path (None when H2O3TPU_LOG_DIR is unset)."""
    return _file_path


def level_counts() -> Dict[str, int]:
    """Ring occupancy per level (the /3/Logs summary line)."""
    return {lvl: len(buf) for lvl, buf in _LEVEL_BUFFERS.items()}
