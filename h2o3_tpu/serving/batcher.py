"""Continuous micro-batching for the low-latency scoring tier (jax-free).

Concurrent REST predict calls enqueue parsed row payloads into a
bounded per-model queue; ONE dispatcher thread per model coalesces
whatever is waiting (up to ``H2O3TPU_SCORE_BATCH_WAIT_MS``, capped at
``H2O3TPU_SCORE_BATCH_MAX_ROWS`` rows) into a single padded device
dispatch and scatters per-request slices back. The accelerator
tree-traversal literature (Booster, arXiv 2011.02022) shows amortized
dispatch dominates per-row scoring — this is that amortization applied
to the REST tier.

Composes with the PR 3 request-hardening contract:
- the REST admission gate already bounds handler concurrency upstream;
  the queue bound here (``H2O3TPU_SCORE_BATCH_QUEUE_DEPTH``) is the
  per-model backpressure — a full queue raises :class:`QueueSaturated`
  which the REST tier maps to 503 + Retry-After;
- request deadlines ride on each :class:`PendingScore` — expired
  entries are failed with ``DeadlineExceeded`` (→ 408) before they
  waste a device dispatch, and the submitting thread waits with its
  own remaining budget;
- the dispatcher calls ``cancel_point`` between dispatches, so an
  unhealthy cloud fails queued predictions fast instead of blocking
  them on a device a dead peer owns.

This module is deliberately backend-free (stdlib + the engine-supplied
``dispatch_fn``): the bench ``_stub_serving`` leg drives the full
queue/coalesce/scatter state machine with no jax in the process.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from h2o3_tpu.core import config as _config
from h2o3_tpu.core import request_ctx
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.serving.batcher")


class QueueSaturated(RuntimeError):
    """The per-model predict queue is full — the REST tier answers 503
    with Retry-After (the AdmissionGate overload contract, applied to
    the scoring queue)."""


class BatcherDraining(RuntimeError):
    """The batcher is shutting down: new submissions and entries still
    queued at close() fail with THIS class so the REST tier can answer
    503 + Retry-After with ``rest_rejected_total{reason=draining}``
    instead of leaving futures hanging (ISSUE 17 graceful drain)."""


def batch_knobs() -> Dict[str, float]:
    """Resolved micro-batch knobs, env-at-call-time (the
    policy_from_config pattern: tests and bench children set
    ``H2O3TPU_SCORE_BATCH_*`` without rebuilding config.ARGS)."""
    env = os.environ.get
    a = _config.ARGS
    return {
        "max_rows": max(1, int(env("H2O3TPU_SCORE_BATCH_MAX_ROWS",
                                   a.score_batch_max_rows))),
        "wait_ms": max(0.0, float(env("H2O3TPU_SCORE_BATCH_WAIT_MS",
                                      a.score_batch_wait_ms))),
        "queue_depth": max(1, int(env("H2O3TPU_SCORE_BATCH_QUEUE_DEPTH",
                                      a.score_batch_queue_depth))),
    }


class PendingScore:
    """One request's seat in the micro-batch: parsed columns in, a
    per-request result slice (or error) out."""

    __slots__ = ("cols", "n", "deadline", "trace", "enqueue_t", "result",
                 "error", "meta", "_event")

    def __init__(self, cols: Dict, n: int,
                 deadline: Optional[float] = None, trace=None):
        self.cols = cols
        self.n = int(n)
        self.deadline = deadline          # absolute time.monotonic()
        self.trace = trace                # TraceContext of the submitter
        self.enqueue_t = time.monotonic()
        self.result = None
        self.error: Optional[BaseException] = None
        self.meta: Dict = {}
        self._event = threading.Event()

    def finish(self, result=None, error: Optional[BaseException] = None,
               **meta) -> None:
        self.result = result
        self.error = error
        self.meta.update(meta)
        self._event.set()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


class MicroBatcher:
    """Bounded queue + coalescing dispatcher for ONE model.

    ``dispatch_fn(batch)`` receives the coalesced ``PendingScore`` list
    and must ``finish()`` every entry (the engine scatters per-request
    slices); if it raises instead, every unfinished entry is failed
    with that error.
    """

    def __init__(self, name: str, dispatch_fn: Callable[[List[PendingScore]], None],
                 max_rows: Optional[int] = None,
                 wait_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 cancel_site: str = "serving.dispatch"):
        knobs = batch_knobs()
        self.name = name
        self.dispatch_fn = dispatch_fn
        self.max_rows = int(max_rows if max_rows is not None
                            else knobs["max_rows"])
        self.wait_ms = float(wait_ms if wait_ms is not None
                             else knobs["wait_ms"])
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else knobs["queue_depth"])
        self.cancel_site = cancel_site
        self.dispatches = 0
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=f"score-batch:{name}", daemon=True)
        self._thread.start()

    # -- producer side -------------------------------------------------
    def submit(self, pending: PendingScore) -> None:
        """Enqueue one request; raises :class:`QueueSaturated` when the
        bounded queue is full (→ 503 at the REST tier)."""
        with self._cond:
            if self._closed:
                raise BatcherDraining(
                    f"batcher {self.name} is draining; retry later")
            if len(self._q) >= self.queue_depth:
                raise QueueSaturated(
                    f"predict queue for {self.name} is full "
                    f"({self.queue_depth} waiting); retry later")
            self._q.append(pending)
            self._cond.notify()

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -- dispatcher side -----------------------------------------------
    def _collect(self) -> List[PendingScore]:
        """Block for the first request, then coalesce whatever arrives
        within ``wait_ms`` up to ``max_rows`` rows. An oversized single
        request rides alone (the engine windows it internally)."""
        with self._cond:
            while not self._q and not self._closed:
                self._cond.wait(0.25)
            if not self._q:
                return []
            batch = [self._q.popleft()]
        rows = batch[0].n
        limit = time.monotonic() + self.wait_ms / 1000.0
        while rows < self.max_rows:
            with self._cond:
                while self._q and rows + self._q[0].n <= self.max_rows:
                    p = self._q.popleft()
                    batch.append(p)
                    rows += p.n
                left = limit - time.monotonic()
                if left <= 0 or self._closed or rows >= self.max_rows:
                    break
                self._cond.wait(left)
        return batch

    @staticmethod
    def _local_work_scope():
        """Serving dispatch is process-local work (the engine scores on
        the local mesh; the fleet ROUTER owns dead-peer exclusion), so
        the heartbeat fail-fast must not kill local scoring when a PEER
        dies. Lazy + best-effort so the module stays backend-free for
        the stub bench leg."""
        try:
            from h2o3_tpu.core import heartbeat
            return heartbeat.local_work_scope()
        except Exception:    # noqa: BLE001 - stub/jax-free process
            import contextlib
            return contextlib.nullcontext()

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                if self._closed:
                    return
                continue
            # chunk-boundary cancellation: job cancel / deadline fails
            # queued predictions fast (no job context rides on the
            # dispatcher thread — per-request deadlines are checked
            # individually below). Runs as LOCAL work: a dead peer
            # degrades routing, never this host's own scoring.
            try:
                with self._local_work_scope():
                    request_ctx.cancel_point(self.cancel_site)
            except BaseException as e:   # noqa: BLE001 - fan the failure out
                for p in batch:
                    p.finish(error=e)
                continue
            now = time.monotonic()
            live = []
            for p in batch:
                if p.deadline is not None and now >= p.deadline:
                    p.finish(error=request_ctx.DeadlineExceeded(
                        f"request deadline expired in the predict queue "
                        f"({now - p.deadline:.3f}s past)"))
                else:
                    live.append(p)
            if not live:
                continue
            self.dispatches += 1
            try:
                with self._local_work_scope():
                    self.dispatch_fn(live)
            except BaseException as e:   # noqa: BLE001 - request boundary
                log.warning("micro-batch dispatch failed for %s: %s",
                            self.name, e, exc_info=True)
                for p in live:
                    if not p.done:
                        p.finish(error=e)

    def close(self, join: bool = True) -> None:
        """Graceful drain: stop accepting, let the dispatcher finish its
        in-flight batch (``join``), then fail anything still queued with
        :class:`BatcherDraining` — callers must never hang on a closed
        batcher, and the REST tier turns the drain into a clean 503."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if join:
            self._thread.join(timeout=2.0)
        with self._cond:
            drained = list(self._q)
            self._q.clear()
        for p in drained:
            p.finish(error=BatcherDraining(
                f"batcher {self.name} closed while request was queued; "
                f"retry later"))
