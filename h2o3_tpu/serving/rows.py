"""Row-payload parsing for the serving fast path (jax-free).

The row-payload endpoint (``POST /3/Predictions/models/{mid}``) carries
inline JSON rows — no DKV frame round trip. This module turns those
rows into host numpy columns ALREADY ADAPTED to the model's training
schema (the adaptTestForTrain role, hex/Model.java:1850, applied at
parse time): categorical values are mapped into the TRAINING domain
(unseen level / missing → -1 = NA) and numerics become float64 with
NaN NAs. Downstream the engine builds a transient padded Frame from
these columns, so the device sees exactly the bytes ``Model.predict``
would see on a client-built frame of the same rows — the foundation of
the bit-identity contract (README §Serving).

Deliberately import-safe without a backend: the bench stub leg
(``_stub_serving``) drives parsing + micro-batching with no jax in the
process.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class ServingUnsupported(Exception):
    """This model cannot take the compiled fast path (unknown algo,
    autoencoder, interactions, offset column...). The engine falls back
    to the eager ``_score_raw`` path on the same transient frame — the
    endpoint stays universal, only the compile cache is bypassed."""


# (name, training_domain_or_None) per input column, in model order
Schema = List[Tuple[str, Optional[List[str]]]]


def serving_schema(model) -> Schema:
    """The model's input schema: feature names + training categorical
    domains (None = numeric). Tree models carry it in their binning
    spec; GLM/DL in their DataInfo stats."""
    algo = getattr(model, "algo", "")
    if algo in ("gbm", "drf"):
        bm = model.bm
        return [(nm, (bm.domains[j] if bm.is_cat[j] else None))
                for j, nm in enumerate(bm.names)]
    if algo in ("glm", "deeplearning"):
        doms = list(model.di_stats.get("domains") or [])
        return [(nm, (doms[j] if j < len(doms) else None))
                for j, nm in enumerate(model.features)]
    raise ServingUnsupported(
        f"no serving schema for algo '{algo}' "
        f"(fast path supports gbm/drf/glm/deeplearning)")


def parse_rows(schema: Schema, rows: Sequence[dict]) -> Dict[str, np.ndarray]:
    """JSON rows → training-adapted host columns.

    Categorical: int32 codes in the TRAINING domain, -1 = NA (missing,
    null, or a level unseen at training time — the reference maps those
    to NA too). Numeric: float64, NaN = NA. Missing keys are NAs, never
    errors: a scoring client may legitimately omit sparse columns.
    """
    if not isinstance(rows, (list, tuple)) or not rows:
        raise ValueError("'rows' must be a non-empty JSON array of objects")
    for i, r in enumerate(rows):
        if not isinstance(r, dict):
            raise ValueError(
                f"row {i} is not a JSON object (got {type(r).__name__})")
    n = len(rows)
    cols: Dict[str, np.ndarray] = {}
    for name, dom in schema:
        if dom is not None:
            lut = {lvl: i for i, lvl in enumerate(dom)}
            out = np.full(n, -1, np.int32)
            for i, r in enumerate(rows):
                v = r.get(name)
                if v is None:
                    continue
                # training domains are interned as strings
                # (water/parser/Categorical.java) — coerce to match
                out[i] = lut.get(v if isinstance(v, str) else str(v), -1)
            cols[name] = out
        else:
            out = np.full(n, np.nan, np.float64)
            for i, r in enumerate(rows):
                v = r.get(name)
                if v is None or v == "":
                    continue
                try:
                    out[i] = float(v)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"row {i}: column '{name}' expects a number, "
                        f"got {v!r}") from None
            cols[name] = out
    return cols


def domains_of(schema: Schema) -> Dict[str, List[str]]:
    """{name: training_domain} for the categorical columns — the
    ``domains=`` argument of ``Frame.from_numpy`` (pre-interned integer
    codes, no re-factorize)."""
    return {name: dom for name, dom in schema if dom is not None}


def concat_columns(parts: Sequence[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
    """Stack per-request parsed columns into one batch (the micro-batch
    gather before the single padded device dispatch)."""
    if len(parts) == 1:
        return parts[0]
    names = list(parts[0])
    return {nm: np.concatenate([p[nm] for p in parts]) for nm in names}
