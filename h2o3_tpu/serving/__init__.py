"""Low-latency scoring tier (README §Serving).

``rows`` and ``batcher`` are jax-free and import eagerly (the bench
stub leg runs them with no backend in the process); the compiled-scorer
engine pulls in jax and loads lazily via :func:`get_engine`.
"""

from h2o3_tpu.serving.batcher import (BatcherDraining, MicroBatcher,
                                      PendingScore, QueueSaturated,
                                      batch_knobs)
from h2o3_tpu.serving.rows import (Schema, ServingUnsupported,
                                   concat_columns, domains_of,
                                   parse_rows, serving_schema)

__all__ = [
    "BatcherDraining", "MicroBatcher", "PendingScore", "QueueSaturated",
    "batch_knobs", "Schema", "ServingUnsupported", "concat_columns",
    "domains_of", "parse_rows", "serving_schema", "get_engine",
]


def get_engine():
    """The process-wide :class:`~h2o3_tpu.serving.engine.ScoringEngine`
    (lazy: importing it compiles nothing but does import jax)."""
    from h2o3_tpu.serving.engine import engine
    return engine
