"""Fleet serving resilience — replica registry + health-routed predicts.

H2O's core deployment property is node symmetry: ANY node answers REST
and scores any model. The PR 14 scoring tier broke that for multi-host
clouds — a model lived where it was trained, and that host was a single
point of failure for every ``POST /3/Predictions`` against it. This
module closes the gap (ISSUE 17):

- **Replica registry** over the coordination-service KV store (never a
  device collective — the same out-of-band rule the scheduler and the
  telemetry fan-in follow). A model's device-independent binary
  (``io/persist._DeviceLoweringPickler``) is published ONCE under
  ``h2o3tpu/fleet/bin/<model>/`` (chunked, parts-before-meta, the
  scheduler's blob transport ordering); any healthy peer can
  ``install_published`` it — unpickle, DKV.put, pre-warm into the
  ``ScoringEngine`` bucket cache — and register its warm replica under
  ``h2o3tpu/fleet/rep/<model>/<pid>``.
- **Governor-aware registration**: a replica reserves its projected
  device bytes through the PR 11 admission ledger
  (``memgov.admit_replica``); a peer over its HBM budget DECLINES
  instead of warming into an OOM. Scorer eviction deregisters the
  replica (routing stops sending here) and the heartbeat-piggybacked
  ``maybe_adopt`` re-warms it on the least-loaded healthy peer.
- **Health-routed predictions**: the REST tier resolves every predict
  against the registry — heartbeat staleness excludes dead peers
  BEFORE their requests fail, the PR 8 telemetry fan-in supplies the
  load signal (inflight jobs + predict queue depth + REST inflight),
  and the least-loaded healthy replica wins (with a local bias so a
  healthy local replica is never abandoned for a marginal win). The
  node either proxies (default) or 307-redirects
  (``H2O3TPU_FLEET_REDIRECT=1``); proxied predicts are idempotent, so
  a replica dying mid-request gets its call HEDGED to the next healthy
  replica within the request's deadline budget
  (``H2O3TPU_FLEET_MAX_HOPS``, per-hop ``H2O3TPU_FLEET_HOP_TIMEOUT_S``).
- **Explicit degradation**: all replicas unhealthy →
  :class:`FleetUnavailable` → 503 + Retry-After in H2OErrorV3 shape,
  never a hang; ``drain()`` (cloud shutdown) deregisters the local
  replicas FIRST, lets in-flight dispatches finish, and fails queued
  requests 503 immediately (``serving/batcher.BatcherDraining``).

Fault sites: ``replica_register`` (registration path) and
``replica_dispatch`` (the proxy hop), so every failover path runs
deterministically on CPU under ``core/watchdog.inject_fault``.

Metrics (README §Observability): ``fleet_replicas_healthy{model}``,
``predict_routed_total{decision}``, ``predict_failovers_total{reason}``,
``replica_warm_seconds``.

The module is deliberately jax-free at import: the routing/failover
state machine (:class:`ReplicaRouter`) runs on injected providers, so
the bench ``_stub_fleet`` leg and the router unit tests drive it with
no backend in the process. Single-process clouds (no coordination
client) degrade to an in-process KV shim — same code paths, local-only
registry.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from h2o3_tpu.core import request_ctx, watchdog
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.serving.fleet")

KV_PREFIX = "h2o3tpu/fleet/"
_B64_CHUNK = 131072              # base64 chars per KV part (bounded values)

_WARM_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class FleetUnavailable(RuntimeError):
    """No healthy replica can take this predict — the REST tier answers
    503 + Retry-After in H2OErrorV3 shape (explicit degradation, never
    a hang)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class RoutePlan:
    """One routing decision: ``local`` (serve here), ``install`` (pull
    the published binary, then serve here), ``proxy``/``redirect``
    (target pid + URL), or ``none`` (unknown model — caller 404s)."""

    __slots__ = ("decision", "pid", "url")

    def __init__(self, decision: str, pid: Optional[int] = None,
                 url: Optional[str] = None):
        self.decision = decision
        self.pid = pid
        self.url = url

    def __repr__(self):
        return f"<RoutePlan {self.decision} pid={self.pid}>"


# ------------------------------------------------------------- knobs


def _knob_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return float(default)


def fleet_knobs() -> Dict[str, float]:
    """Resolved routing knobs, env-at-call-time (the batch_knobs
    pattern: tests and bench children flip env without a re-init)."""
    return {
        "redirect": _knob_f("H2O3TPU_FLEET_REDIRECT", 0.0),
        "max_hops": max(1, int(_knob_f("H2O3TPU_FLEET_MAX_HOPS", 3))),
        "hop_timeout_s": _knob_f("H2O3TPU_FLEET_HOP_TIMEOUT_S", 10.0),
        "local_bias": _knob_f("H2O3TPU_FLEET_LOCAL_BIAS", 2.0),
        "retry_after_s": _knob_f("H2O3TPU_FLEET_RETRY_AFTER_S", 1.0),
        "load_ttl_s": _knob_f("H2O3TPU_FLEET_LOAD_TTL_S", 0.5),
        "adopt_s": _knob_f("H2O3TPU_FLEET_ADOPT_S", 2.0),
        "adopt_grace_s": _knob_f("H2O3TPU_FLEET_ADOPT_GRACE_S", 10.0),
    }


# ----------------------------------------------------- KV transport


class _LocalKV:
    """In-process stand-in for the coordination-service KV client:
    single-process clouds (and jax-free tests) run the SAME registry
    code against it — local-only, but identical semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, str] = {}

    def key_value_set(self, key, val, allow_overwrite=True):
        with self._lock:
            self._store[key] = val

    def key_value_dir_get(self, prefix):
        with self._lock:
            return [(k, v) for k, v in self._store.items()
                    if k.startswith(prefix)]

    def key_value_delete(self, key):
        with self._lock:
            for k in [k for k in self._store if k.startswith(key)]:
                del self._store[k]

    def blocking_key_value_get(self, key, timeout_ms):
        with self._lock:
            if key not in self._store:
                raise KeyError(key)
            return self._store[key]


_local_kv = _LocalKV()


def _kv():
    """The coordination-service client, or the in-process shim when the
    cloud is single-process / the distributed runtime is absent."""
    try:
        from jax._src import distributed
        client = distributed.global_state.client
        if client is not None:
            return client
    except Exception:        # noqa: BLE001 - no jax / no distributed
        pass
    return _local_kv


def _encode(data: bytes) -> str:
    import base64
    import zlib
    return base64.b64encode(zlib.compress(data, 6)).decode("ascii")


def _decode(text: str) -> bytes:
    import base64
    import zlib
    return zlib.decompress(base64.b64decode(text.encode("ascii")))


def _self_pid() -> int:
    from h2o3_tpu.telemetry.cluster import _identity
    return _identity()[0]


# ------------------------------------------------------ module state

_lock = threading.RLock()
_endpoint: Optional[Tuple[str, int]] = None      # this process's REST edge
_local_replicas: Dict[str, Dict[str, Any]] = {}  # model_key -> info
_reservations: Dict[str, Any] = {}               # model_key -> Reservation
_draining = False
_last_adopt = 0.0
_adopt_thread: Optional[threading.Thread] = None
_loads_cache: Dict[str, Any] = {"ts": 0.0, "loads": {}}


# -------------------------------------------------------- endpoints


def set_local_endpoint(port: int, host: str = "127.0.0.1") -> None:
    """Publish this process's REST edge (called by ``start_server``
    with the ACTUAL bound port — ``port=0`` ephemeral binds included),
    so peers can proxy/redirect predictions here."""
    global _endpoint, _draining
    with _lock:
        _endpoint = (host, int(port))
        _draining = False
    try:
        _kv().key_value_set(
            f"{KV_PREFIX}ep/{_self_pid()}",
            json.dumps({"host": host, "port": int(port),
                        "ts": time.time(), "ospid": os.getpid()}),
            allow_overwrite=True)
    except Exception as e:   # noqa: BLE001 - endpoint publish best-effort
        log.debug("fleet endpoint publish failed: %s", e)


def clear_local_endpoint() -> None:
    global _endpoint
    with _lock:
        _endpoint = None
    try:
        _kv().key_value_delete(f"{KV_PREFIX}ep/{_self_pid()}")
    except Exception:        # noqa: BLE001
        pass


def endpoints() -> Dict[int, Tuple[str, int]]:
    """pid -> (host, port) for every peer that published a REST edge."""
    out: Dict[int, Tuple[str, int]] = {}
    try:
        for key, val in _kv().key_value_dir_get(f"{KV_PREFIX}ep/"):
            try:
                pid = int(key.rsplit("/", 1)[-1])
                d = json.loads(val)
                out[pid] = (str(d["host"]), int(d["port"]))
            except (ValueError, KeyError, TypeError):
                continue
    except Exception:        # noqa: BLE001 - KV down: no remote edges
        pass
    return out


# ----------------------------------------------------- binary plane


def published(model_key: str) -> Optional[Dict]:
    """The published binary's meta (or None)."""
    try:
        for key, val in _kv().key_value_dir_get(
                f"{KV_PREFIX}bin/{model_key}/"):
            if key.endswith("/meta"):
                return json.loads(val)
    except Exception:        # noqa: BLE001
        pass
    return None


def publish(model) -> bool:
    """Publish the model's device-independent binary once (idempotent).

    Pickled with ``io/persist._DeviceLoweringPickler`` — every
    jax.Array lowers to numpy, so ANY peer (any backend) can install.
    Chunked parts are written before the meta (the scheduler's blob
    ordering: a half-written blob is never observed).

    SPMD contract: when the model holds cross-process sharded arrays
    (trained on the global mesh of a multi-process cloud), the lowering
    pickle allgathers them — EVERY process must call publish at the
    same program point, exactly like ``Model.predict``. Local-mesh and
    single-process models publish single-sided."""
    if published(model.key) is not None:
        return False
    import io as _io
    import pickle
    from h2o3_tpu.io.persist import _DeviceLoweringPickler
    buf = _io.BytesIO()
    _DeviceLoweringPickler(buf, protocol=pickle.HIGHEST_PROTOCOL
                           ).dump(model)
    b64 = _encode(buf.getvalue())
    client = _kv()
    prefix = f"{KV_PREFIX}bin/{model.key}/"
    nparts = (len(b64) + _B64_CHUNK - 1) // _B64_CHUNK if b64 else 0
    for j in range(nparts):
        client.key_value_set(f"{prefix}p{j}",
                             b64[j * _B64_CHUNK:(j + 1) * _B64_CHUNK],
                             allow_overwrite=True)
    client.key_value_set(
        f"{prefix}meta",
        json.dumps({"parts": nparts, "algo": model.algo,
                    "nbytes": len(buf.getvalue()), "ts": time.time()}),
        allow_overwrite=True)
    log.info("published fleet binary for %s (%d parts, %.1f KB)",
             model.key, nparts, len(buf.getvalue()) / 1e3)
    return True


def install_published(model_key: str):
    """Pull a published binary, land the model in the local DKV, and
    pre-warm it into the scoring engine + registry. Returns the model.
    Raises KeyError when nothing is published under that key."""
    meta = published(model_key)
    if meta is None:
        raise KeyError(f"model {model_key} not found")
    client = _kv()
    parts = []
    for j in range(int(meta.get("parts", 0))):
        parts.append(client.blocking_key_value_get(
            f"{KV_PREFIX}bin/{model_key}/p{j}", 10_000))
    import pickle
    model = pickle.loads(_decode("".join(parts)))
    from h2o3_tpu.core.kv import DKV
    DKV.put(model.key, model)
    register_local(model)
    return model


# -------------------------------------------------------- registry


def register_local(model) -> bool:
    """Register a warm local replica: governor admission first (a peer
    over its HBM reservation DECLINES — returns False), then warm the
    scoring engine, then announce the replica in the KV registry.
    Idempotent per model."""
    watchdog.maybe_fail("replica_register")
    from h2o3_tpu import telemetry
    with _lock:
        if _draining:
            return False
        if model.key in _local_replicas:
            return True
    from h2o3_tpu.serving.engine import _const_nbytes, engine
    nbytes = _const_nbytes(model)
    rsv = None
    try:
        from h2o3_tpu.core import memgov
        rsv = memgov.governor.admit_replica(model.key, nbytes)
    except ValueError as e:      # MemoryBudgetExceeded — decline
        log.warning("replica registration DECLINED for %s: %s",
                    model.key, e)
        return False
    t0 = time.monotonic()
    try:
        engine.register(model)
    except Exception:
        try:
            from h2o3_tpu.core import memgov
            memgov.governor.release(rsv)
        except Exception:    # noqa: BLE001
            pass
        raise
    warm_s = time.monotonic() - t0
    telemetry.histogram("replica_warm_seconds",
                        buckets=_WARM_BUCKETS).observe(warm_s)
    info = {"pid": _self_pid(), "algo": model.algo, "nbytes": nbytes,
            "warm_s": warm_s, "ts": time.time()}
    with _lock:
        _local_replicas[model.key] = info
        if rsv is not None:
            _reservations[model.key] = rsv
    try:
        _kv().key_value_set(f"{KV_PREFIX}rep/{model.key}/{info['pid']}",
                            json.dumps(info), allow_overwrite=True)
    except Exception as e:   # noqa: BLE001 - registry write best-effort
        log.debug("fleet replica announce failed: %s", e)
    _refresh_gauges(model.key)
    log.info("fleet replica registered: %s on pid %d (warm %.3fs)",
             model.key, info["pid"], warm_s)
    return True


def replicate(model) -> bool:
    """Publish the binary once + register a warm local replica — the
    one-call surface a trained model uses to join the fleet."""
    publish(model)
    return register_local(model)


def deregister_local(model_key: Optional[str] = None,
                     reason: str = "") -> None:
    """Remove local replica(s) from the registry (all when
    ``model_key`` is None) and release their governor reservations.
    Routing stops offering this peer immediately."""
    pid = _self_pid()
    with _lock:
        keys = ([model_key] if model_key is not None
                else list(_local_replicas))
        for k in keys:
            _local_replicas.pop(k, None)
    for k in keys:
        rsv = _reservations.pop(k, None)
        if rsv is not None:
            try:
                from h2o3_tpu.core import memgov
                memgov.governor.release(rsv)
            except Exception:    # noqa: BLE001
                pass
        try:
            _kv().key_value_delete(f"{KV_PREFIX}rep/{k}/{pid}")
        except Exception:        # noqa: BLE001
            pass
        _refresh_gauges(k)
    if keys:
        log.info("fleet deregistered %d replica(s) on pid %d%s",
                 len(keys), pid, f" ({reason})" if reason else "")


def on_scorers_evicted(model_keys: List[str]) -> None:
    """Engine eviction hook: an evicted scorer is no longer warm —
    deregister so routing stops here and ``maybe_adopt`` re-warms the
    replica on the least-loaded healthy peer."""
    with _lock:
        mine = [k for k in model_keys if k in _local_replicas]
    for k in mine:
        deregister_local(k, reason="scorer evicted")


def replicas(model_key: str) -> Dict[int, Dict]:
    """pid -> replica info for every registered replica of a model."""
    out: Dict[int, Dict] = {}
    try:
        for key, val in _kv().key_value_dir_get(
                f"{KV_PREFIX}rep/{model_key}/"):
            try:
                out[int(key.rsplit("/", 1)[-1])] = json.loads(val)
            except (ValueError, TypeError):
                continue
    except Exception:        # noqa: BLE001
        pass
    return out


def registered_models() -> List[str]:
    """Model keys with at least one registered replica (any peer)."""
    seen = set()
    try:
        for key, _val in _kv().key_value_dir_get(f"{KV_PREFIX}rep/"):
            # key = <prefix>rep/<model_key>/<pid>
            tail = key[len(f"{KV_PREFIX}rep/"):]
            mk = tail.rsplit("/", 1)[0]
            if mk:
                seen.add(mk)
    except Exception:        # noqa: BLE001
        pass
    return sorted(seen)


def published_models() -> List[str]:
    out = []
    try:
        for key, _val in _kv().key_value_dir_get(f"{KV_PREFIX}bin/"):
            if key.endswith("/meta"):
                out.append(key[len(f"{KV_PREFIX}bin/"):-len("/meta")])
    except Exception:        # noqa: BLE001
        pass
    return sorted(out)


# ---------------------------------------------------- health + load


def _dead_set() -> set:
    """Heartbeat's verdict: pids whose beat staleness exceeded the
    miss budget — excluded from routing BEFORE their requests fail."""
    try:
        from h2o3_tpu.core import heartbeat
        return set(heartbeat.dead_peers())
    except Exception:        # noqa: BLE001
        return set()


def local_load() -> float:
    """This process's live load: inflight jobs + predict queue depth +
    inflight REST handlers (the same composition peers publish)."""
    load = 0.0
    try:
        from h2o3_tpu.telemetry import REGISTRY
        load += float(REGISTRY.value("jobs_inflight"))
        load += float(REGISTRY.value("rest_inflight_requests"))
    except Exception:        # noqa: BLE001
        pass
    try:
        import sys
        eng = sys.modules.get("h2o3_tpu.serving.engine")
        if eng is not None:
            load += float(eng.engine.queue_depth())
    except Exception:        # noqa: BLE001
        pass
    return load


def peer_loads() -> Dict[int, float]:
    """pid -> load from the PR 8 telemetry fan-in ``serving`` block,
    TTL-cached (``H2O3TPU_FLEET_LOAD_TTL_S``); stale peers excluded.
    The local pid's entry is always live."""
    ttl = fleet_knobs()["load_ttl_s"]
    now = time.monotonic()
    with _lock:
        if now - _loads_cache["ts"] < ttl:
            loads = dict(_loads_cache["loads"])
            loads[_self_pid()] = local_load()
            return loads
    loads: Dict[int, float] = {}
    try:
        from h2o3_tpu.telemetry import cluster
        col = cluster.collect()
        stale = set(col["stale_nodes"])
        for n, snap in col["nodes"].items():
            if int(n) in stale:
                continue
            srv = snap.get("serving") or {}
            loads[int(n)] = (float(snap.get("jobs_inflight", 0) or 0)
                             + float(srv.get("queue_depth", 0) or 0)
                             + float(srv.get("rest_inflight", 0) or 0))
    except Exception:        # noqa: BLE001 - fan-in down: loads unknown
        loads = {}
    with _lock:
        _loads_cache["ts"] = now
        _loads_cache["loads"] = dict(loads)
    loads[_self_pid()] = local_load()
    return loads


# ---------------------------------------------------------- router


class ReplicaRouter:
    """The pure routing/failover state machine — providers injected so
    the bench ``_stub_fleet`` leg and unit tests drive it jax-free.

    ``replicas_fn(model_key) -> {pid: info}``;
    ``endpoints_fn() -> {pid: (host, port)}``;
    ``dead_fn() -> set of pids``; ``loads_fn() -> {pid: load}``;
    ``draining_fn() -> bool`` (is the LOCAL peer draining)."""

    def __init__(self, self_pid: int,
                 replicas_fn: Callable[[str], Dict[int, Dict]],
                 endpoints_fn: Callable[[], Dict[int, Tuple[str, int]]],
                 dead_fn: Callable[[], set],
                 loads_fn: Callable[[], Dict[int, float]],
                 draining_fn: Callable[[], bool] = lambda: False,
                 published_fn: Callable[[str], bool] = lambda _mk: False,
                 local_bias: Optional[float] = None):
        self.self_pid = self_pid
        self.replicas_fn = replicas_fn
        self.endpoints_fn = endpoints_fn
        self.dead_fn = dead_fn
        self.loads_fn = loads_fn
        self.draining_fn = draining_fn
        self.published_fn = published_fn
        self.local_bias = local_bias

    def _bias(self) -> float:
        return (self.local_bias if self.local_bias is not None
                else fleet_knobs()["local_bias"])

    def healthy_remote(self, model_key: str,
                       exclude: Optional[set] = None
                       ) -> Dict[int, Tuple[str, int]]:
        """Remote replicas that are routable NOW: registered, not
        heartbeat-dead, with a published REST edge."""
        dead = self.dead_fn()
        eps = self.endpoints_fn()
        out = {}
        for pid in self.replicas_fn(model_key):
            if pid == self.self_pid or pid in dead:
                continue
            if exclude and pid in exclude:
                continue
            ep = eps.get(pid)
            if ep is not None:
                out[pid] = ep
        return out

    def pick(self, model_key: str, exclude: Optional[set] = None
             ) -> Optional[Tuple[int, Tuple[str, int]]]:
        """The least-loaded healthy remote replica, or None."""
        cands = self.healthy_remote(model_key, exclude)
        if not cands:
            return None
        loads = self.loads_fn()
        pid = min(cands, key=lambda p: (loads.get(p, float("inf")), p))
        return pid, cands[pid]

    def plan(self, model_key: str, have_local: bool,
             hop: bool = False, redirect: Optional[bool] = None
             ) -> RoutePlan:
        """Resolve one predict. ``have_local``: the model object is in
        this process's DKV; ``hop``: the request already took one fleet
        hop (NEVER re-routed — loop prevention)."""
        local_ok = ((have_local or
                     self.self_pid in self.replicas_fn(model_key))
                    and not self.draining_fn())
        if hop:
            return RoutePlan("local" if local_ok else "install")
        best = self.pick(model_key)
        if local_ok:
            if best is not None:
                loads = self.loads_fn()
                remote_load = loads.get(best[0], float("inf"))
                if remote_load + self._bias() < loads.get(
                        self.self_pid, 0.0):
                    return self._remote_plan(model_key, best, redirect)
            return RoutePlan("local")
        if best is not None:
            return self._remote_plan(model_key, best, redirect)
        if have_local:
            # a draining local peer with no healthy remote still serves
            # (or 503s through the batcher's draining contract) rather
            # than 404ing a model it demonstrably holds
            return RoutePlan("local")
        if self.published_fn(model_key):
            return RoutePlan("install")
        return RoutePlan("none")

    def _remote_plan(self, model_key: str,
                     best: Tuple[int, Tuple[str, int]],
                     redirect: Optional[bool]) -> RoutePlan:
        pid, (host, port) = best
        if redirect is None:
            redirect = bool(fleet_knobs()["redirect"])
        url = (f"http://{host}:{port}/3/Predictions/models/"
               f"{urllib.parse.quote(model_key, safe='')}?_fleet_hop=1")
        return RoutePlan("redirect" if redirect else "proxy",
                         pid=pid, url=url)

    def hedged(self, model_key: str,
               attempt_fn: Callable[[int, Tuple[str, int]], Any],
               first: Optional[Tuple[int, Tuple[str, int]]] = None,
               deadline: Optional[float] = None,
               max_hops: Optional[int] = None,
               local_fallback: bool = False):
        """Run ``attempt_fn(pid, endpoint)`` against the best replica,
        hedging each infrastructure failure to the NEXT healthy replica
        within the deadline budget. Returns the first success, the
        :data:`SERVE_LOCALLY` sentinel when ``local_fallback`` and every
        remote failed, or raises :class:`FleetUnavailable`."""
        from h2o3_tpu import telemetry
        hops = max_hops if max_hops is not None \
            else int(fleet_knobs()["max_hops"])
        tried: set = set()
        target = first if first is not None else self.pick(model_key)
        last_err: Optional[BaseException] = None
        while target is not None and len(tried) < hops:
            pid, ep = target
            if deadline is not None and time.monotonic() >= deadline:
                raise request_ctx.DeadlineExceeded(
                    f"predict for {model_key} ran out of deadline "
                    f"budget after {len(tried)} fleet hop(s)")
            try:
                return attempt_fn(pid, ep)
            except (request_ctx.DeadlineExceeded, _Passthrough):
                raise
            except Exception as e:   # noqa: BLE001 - hedge the hop
                reason = _failure_reason(e)
                telemetry.counter("predict_failovers_total",
                                  reason=reason).inc()
                log.warning("fleet hop to pid %d failed (%s): %s — "
                            "hedging", pid, reason, e)
                last_err = e
                tried.add(pid)
                target = self.pick(model_key, exclude=tried)
        if local_fallback:
            return SERVE_LOCALLY
        raise FleetUnavailable(
            f"no healthy replica for {model_key}: "
            f"{len(tried)} hop(s) failed"
            + (f" (last: {last_err})" if last_err else ""),
            retry_after_s=fleet_knobs()["retry_after_s"])


# sentinel: every remote hop failed but the caller can score locally
SERVE_LOCALLY = object()


class _Passthrough(Exception):
    """Wraps a client-caused remote error (4xx) so the hedging loop
    re-raises the ORIGINAL instead of hedging a request that would fail
    identically everywhere."""

    def __init__(self, original: BaseException):
        super().__init__(str(original))
        self.original = original


def _failure_reason(e: BaseException) -> str:
    if isinstance(e, (socket.timeout, TimeoutError)):
        return "timeout"
    if isinstance(e, urllib.error.HTTPError):
        return "http_5xx" if e.code >= 500 else "not_found"
    if isinstance(e, urllib.error.URLError):
        if isinstance(getattr(e, "reason", None),
                      (socket.timeout, TimeoutError)):
            return "timeout"
        return "connection"
    if isinstance(e, (ConnectionError, OSError)):
        return "connection"
    return "error"


def router() -> ReplicaRouter:
    """The live router over the KV registry + heartbeat + telemetry
    fan-in providers."""
    return ReplicaRouter(
        self_pid=_self_pid(),
        replicas_fn=replicas,
        endpoints_fn=endpoints,
        dead_fn=_dead_set,
        loads_fn=peer_loads,
        draining_fn=lambda: _draining,
        published_fn=lambda mk: published(mk) is not None)


def redirect_url(plan: RoutePlan, path: str) -> str:
    """Location for a 307 at ``plan``'s replica (hop-marked so the
    peer never re-routes — loop prevention)."""
    eps = endpoints()
    if plan.pid not in eps:
        raise FleetUnavailable(
            f"replica pid {plan.pid} lost its REST edge",
            retry_after_s=fleet_knobs()["retry_after_s"])
    host, port = eps[plan.pid]
    return f"http://{host}:{port}{path}?_fleet_hop=1"


def plan_route(model_key: str, have_local: bool,
               hop: bool = False) -> RoutePlan:
    """REST entry: resolve a predict against the fleet, counting the
    decision in ``predict_routed_total{decision}``. Models with no
    fleet registration resolve ``local``/``none`` with no KV reads
    beyond the replica-dir lookup."""
    from h2o3_tpu import telemetry
    plan = router().plan(model_key, have_local, hop=hop)
    telemetry.counter("predict_routed_total",
                      decision=plan.decision).inc()
    return plan


def proxy_predict(plan: RoutePlan, path: str, payload: Dict,
                  model_key: str, deadline: Optional[float] = None,
                  local_fallback: bool = False):
    """Forward a predict to ``plan``'s replica with bounded, hedged
    failover. Returns the peer's decoded JSON response, or
    :data:`SERVE_LOCALLY` when every remote hop failed and the caller
    holds (or can install) the model."""
    knobs = fleet_knobs()
    if deadline is None:
        deadline = request_ctx.current_deadline()

    def _attempt(pid: int, ep: Tuple[str, int]):
        watchdog.maybe_fail("replica_dispatch")
        timeout = knobs["hop_timeout_s"]
        if deadline is not None:
            timeout = min(timeout,
                          max(deadline - time.monotonic(), 0.05))
        host, port = ep
        url = (f"http://{host}:{port}{path}"
               f"?_fleet_hop=1&_timeout_ms={int(timeout * 1000)}")
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                msg = json.loads(body).get("msg", "")
            except Exception:    # noqa: BLE001
                msg = body.decode("utf-8", "replace")[:200]
            if e.code == 408:
                raise _Passthrough(request_ctx.DeadlineExceeded(
                    f"replica pid {pid}: {msg}")) from None
            if e.code in (400, 412):
                # the request itself is bad — identical everywhere,
                # never hedge it
                raise _Passthrough(ValueError(msg)) from None
            raise

    from h2o3_tpu import telemetry
    first = None
    if plan.pid is not None:
        eps = endpoints()
        if plan.pid in eps:
            first = (plan.pid, eps[plan.pid])
    try:
        return router().hedged(model_key, _attempt, first=first,
                               deadline=deadline,
                               local_fallback=local_fallback)
    except _Passthrough as p:
        raise p.original
    finally:
        telemetry.gauge("fleet_replicas_healthy", model=model_key).set(
            len(router().healthy_remote(model_key))
            + (1 if model_key in _local_replicas else 0))


# --------------------------------------------------------- adoption


def maybe_adopt(now: Optional[float] = None) -> bool:
    """Heartbeat-piggybacked re-warm: when a registered model has NO
    healthy replica left (eviction, peer death), the least-loaded
    healthy peer pulls the published binary and re-warms it. Rate
    limited (``H2O3TPU_FLEET_ADOPT_S``); the install runs on a
    background thread so the heartbeat round stays bounded."""
    global _last_adopt, _adopt_thread
    if os.environ.get("H2O3TPU_FLEET_ADOPT", "1").lower() in ("0", "off"):
        return False
    now = time.monotonic() if now is None else now
    with _lock:
        if _draining or now - _last_adopt < fleet_knobs()["adopt_s"]:
            return False
        if _adopt_thread is not None and _adopt_thread.is_alive():
            return False
        _last_adopt = now
    orphans = _orphaned_models()
    if not orphans:
        return False

    def _adopt():
        for mk in orphans:
            try:
                log.info("fleet adopting orphaned replica %s", mk)
                install_published(mk)
            except Exception as e:   # noqa: BLE001 - next round retries
                log.warning("fleet adopt of %s failed: %s", mk, e)

    with _lock:
        _adopt_thread = threading.Thread(
            target=_adopt, name="fleet-adopt", daemon=True)
        _adopt_thread.start()
    return True


def _orphaned_models() -> List[str]:
    """Published models with zero healthy replicas, for which THIS peer
    is the least-loaded healthy candidate. A freshly published binary
    gets a grace window (``H2O3TPU_FLEET_ADOPT_GRACE_S``) before it
    counts as orphaned — its publisher is normally still warming the
    first replica, and adopting in that gap double-registers."""
    dead = _dead_set()
    self_pid = _self_pid()
    loads = peer_loads()
    grace = fleet_knobs()["adopt_grace_s"]
    out = []
    for mk in published_models():
        reps = replicas(mk)
        healthy = [p for p in reps if p not in dead]
        if healthy:
            continue
        meta = published(mk)
        if meta is None or time.time() - float(meta.get("ts", 0)) < grace:
            continue
        # candidates: peers with a live REST edge + self
        cands = {p for p in endpoints() if p not in dead}
        cands.add(self_pid)
        best = min(cands,
                   key=lambda p: (loads.get(p, float("inf")), p))
        if best == self_pid:
            out.append(mk)
    return out


# ------------------------------------------------ lifecycle + sweep


def drain() -> None:
    """Cloud-shutdown drain ordering (ISSUE 17): flip this peer out of
    routing, deregister its replicas and REST edge, then drain the
    scoring engine — in-flight dispatches finish, queued requests fail
    503 immediately. Called by ``core/cloud.shutdown`` BEFORE the
    heartbeat stops."""
    global _draining
    with _lock:
        _draining = True
    deregister_local(reason="draining")
    clear_local_endpoint()
    import sys
    eng = sys.modules.get("h2o3_tpu.serving.engine")
    if eng is not None:
        eng.engine.reset()


def sweep_local_keys(client=None, pid: Optional[int] = None) -> None:
    """Delete THIS process's fleet keys (endpoint + replica entries)
    from the coordination KV — the per-process half of the
    ``core/cloud._sweep_coordination_keys`` contract. Binary blobs are
    per-MODEL, not per-process: like the scheduler's run subtrees they
    are garbage-collected at the next init-time sweep, never at
    shutdown where a lagging peer may still be installing from them."""
    client = client if client is not None else _kv()
    pid = _self_pid() if pid is None else pid
    try:
        client.key_value_delete(f"{KV_PREFIX}ep/{pid}")
    except Exception:        # noqa: BLE001
        pass
    try:
        for key, _val in client.key_value_dir_get(f"{KV_PREFIX}rep/"):
            if key.endswith(f"/{pid}"):
                try:
                    client.key_value_delete(key)
                except Exception:    # noqa: BLE001
                    pass
    except Exception:        # noqa: BLE001
        pass


def sweep_keys() -> None:
    """Delete the ENTIRE fleet subtree (init-time, after the roll-call
    barrier proves no process is mid-install — the scheduler
    ``sweep_keys`` precedent): a re-formed cloud must never route to a
    previous incarnation's replicas or install its binaries."""
    try:
        _kv().key_value_delete(KV_PREFIX)
    except Exception:        # noqa: BLE001
        pass


def reset() -> None:
    """Test hook: forget all local fleet state + the in-process KV."""
    global _draining, _last_adopt, _endpoint
    deregister_local(reason="reset")
    with _lock:
        _local_replicas.clear()
        _reservations.clear()
        _draining = False
        _last_adopt = 0.0
        _endpoint = None
        _loads_cache["ts"] = 0.0
        _loads_cache["loads"] = {}
    _local_kv._store.clear()


def _refresh_gauges(model_key: str) -> None:
    try:
        from h2o3_tpu import telemetry
        dead = _dead_set()
        healthy = [p for p in replicas(model_key) if p not in dead]
        telemetry.gauge("fleet_replicas_healthy",
                        model=model_key).set(len(healthy))
    except Exception:        # noqa: BLE001 - gauges are best-effort
        pass


def stats() -> Dict:
    """Fleet block for the telemetry ``serving`` snapshot + tests."""
    with _lock:
        local = sorted(_local_replicas)
        ep = _endpoint
        draining = _draining
    return {"local_replicas": local,
            "endpoint": {"host": ep[0], "port": ep[1]} if ep else None,
            "draining": draining,
            "registered_models": registered_models()}
