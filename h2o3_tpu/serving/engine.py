"""ScoringEngine — the compiled-scorer cache behind the serving tier.

Training compiles once and streams millions of rows; serving inverts
the ratio: many small requests, each of which would pay a fresh XLA
trace on any new shape. The fix is the same full-program compilation
stance the rest of the runtime takes (arXiv 1810.09868): per model,
ONE jitted predict program per padded ROW BUCKET (powers of two up to
``H2O3TPU_SCORE_BATCH_MAX_ROWS`` — the serving face of the PR 4 shape
bucket planner, ``parallel/model_batch.row_bucket``), warmed at model
registration so the first request never pays a trace, with donated
input buffers on accelerator backends.

Bit-identity contract (asserted in tier-1, tests/test_serving.py): the
device half of each program is EXACTLY the device math of the model's
``_score_raw`` (``Model._serve_dev``), the host tail is EXACTLY its
host math (``Model._serve_finish``), and the shared post-processing
(threshold/argmax/calibrator/domains) is the same
``Model._finish_predict`` that ``Model.predict`` calls. Padding rows
never leak: every per-row op here is row-count-stable, and outputs are
sliced to logical rows before post-processing.

Eviction: the scorer cache registers with the PR 11 memory governor as
an auxiliary device cache (``core/memgov.register_aux_cache``) — the
OOM/admission ladders drop compiled scorers alongside
``Frame.drop_device_caches``, counted in
``scorer_cache_evictions_total``.

Metrics (README §Observability): ``predict_requests_total{algo}``,
``predict_batch_width``, ``predict_seconds{phase=queue|device|scatter}``,
``scorer_cache_{hits,misses,evictions}_total``, ``scorer_cache_bytes``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.core import request_ctx
from h2o3_tpu.serving import rows as rows_mod
from h2o3_tpu.serving.batcher import MicroBatcher, PendingScore, \
    QueueSaturated, batch_knobs
from h2o3_tpu.serving.rows import ServingUnsupported
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.serving")

_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
_WIDTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _serve_mesh_scope():
    """Serving is strictly process-local work: on a multi-process cloud
    it must run on THIS host's devices (the scheduler's local-mesh
    idiom), never the global mesh — a single-sided dispatch onto a
    cross-process sharding either fails or produces a result no one
    process can read — and under the heartbeat's local-work exemption,
    so a DEAD peer degrades fleet routing without killing this host's
    own scoring. Single-process: no-op."""
    import contextlib
    import jax
    stack = contextlib.ExitStack()
    if jax.process_count() > 1:
        from h2o3_tpu.core import heartbeat
        from h2o3_tpu.parallel import mesh as mesh_mod
        stack.enter_context(mesh_mod.local_mesh_scope())
        stack.enter_context(heartbeat.local_work_scope())
    return stack


def _const_nbytes(model) -> int:
    """Device bytes pinned by the model's own parameters (closure
    constants of its compiled scorers)."""
    import jax
    total = 0
    for attr in ("forest", "coef", "coef_multinomial", "net", "f0"):
        obj = getattr(model, attr, None)
        if obj is None:
            continue
        for leaf in jax.tree_util.tree_leaves(obj):
            total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


class CompiledScorer:
    """One model's seat in the scorer cache: its serving schema, the
    jitted device program (shared across row buckets — XLA keys the
    executable on the padded input shape), and the bucket bookkeeping
    the hit/miss metrics and byte accounting ride on."""

    def __init__(self, model):
        import jax
        self.model = model
        self.algo = model.algo
        self.schema = rows_mod.serving_schema(model)
        oc = model.params.get("offset_column")
        if oc and all(nm != oc for nm, _ in self.schema):
            # offset rides as a plain numeric input column; offset
            # models score through the eager fallback (see below), but
            # the payload schema must still accept the column
            self.schema.append((oc, None))
        self.domains = rows_mod.domains_of(self.schema)
        self.fallback_reason = self._fallback_reason()
        self.buckets: Dict[int, int] = {}    # padded rows -> input bytes
        self.serve = None
        self.prep: Optional[Callable] = None
        if self.fallback_reason is None:
            from h2o3_tpu.telemetry.compile_observer import observed_jit
            self.prep = self._prep_fn()
            if jax.default_backend() == "cpu":
                # SHARE the model's own compiled program
                # (Model._serve_jit — also what _score_raw runs):
                # bit-identity by construction, and predicts warm the
                # serving cache and vice versa
                base = model._serve_jit()
            else:
                # accelerator: a separate jit of the SAME traced fn
                # (identical HLO → identical numerics) with the input
                # buffer donated — serving inputs are transient, and
                # donation frees a bucket of HBM per dispatch
                base = jax.jit(model._serve_dev, donate_argnums=(0,))
            self.serve = observed_jit(f"serving.{self.algo}")(base)
        self.const_nbytes = _const_nbytes(model)

    def _fallback_reason(self) -> Optional[str]:
        m = self.model
        if not hasattr(m, "_serve_dev") or not hasattr(m, "_serve_finish"):
            return "no device scoring program"
        if m.params.get("offset_column"):
            return "offset_column"
        if m.algo == "deeplearning" and m.params.get("autoencoder"):
            return "autoencoder"
        return None

    def _prep_fn(self) -> Callable:
        """Frame → the device input of the jitted program (eager
        adaptTestForTrain half: training-edge binning / design
        expansion — itself shape-bucketed and jit-cached downstream)."""
        m = self.model
        if self.algo in ("gbm", "drf"):
            from h2o3_tpu.frame.binning import rebin_for_scoring
            return lambda fr: rebin_for_scoring(m.bm, fr).bins
        if self.algo == "glm":
            return m._design
        if self.algo == "deeplearning":
            return lambda fr: m._design(fr).X
        raise ServingUnsupported(f"no prep for algo '{self.algo}'")

    def nbytes(self) -> int:
        """Estimated device bytes this scorer pins: model constants +
        per-bucket input workspace (the executables themselves are
        untracked by jax; this is the accountable floor)."""
        return self.const_nbytes + sum(self.buckets.values())


class ScoringEngine:
    """Per-model compiled-scorer cache + continuous micro-batching
    (singleton ``engine``; README §Serving)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._scorers: Dict[str, CompiledScorer] = {}
        self._batchers: Dict[str, MicroBatcher] = {}
        self._memgov_registered = False

    # -- registration --------------------------------------------------
    def register(self, model) -> CompiledScorer:
        """Idempotent model registration: build the scorer, warm-compile
        the smallest row bucket (the first request must never pay a
        trace), and start the model's micro-batch dispatcher."""
        with self._lock:
            sc = self._scorers.get(model.key)
            if sc is not None and sc.model is model:
                return sc
        sc = CompiledScorer(model)       # may raise ServingUnsupported
        self._warm_up(model, sc)
        with self._lock:
            self._scorers[model.key] = sc
            if model.key not in self._batchers:
                self._batchers[model.key] = MicroBatcher(
                    model.key,
                    lambda batch, _mk=model.key: self._dispatch(_mk, batch))
            self._register_memgov()
        self._refresh_gauge()
        log.info("registered serving scorer for %s (%s%s)", model.key,
                 model.algo,
                 f", eager fallback: {sc.fallback_reason}"
                 if sc.fallback_reason else ", compiled")
        return sc

    def _warm_up(self, model, sc: CompiledScorer) -> None:
        """Score one all-NA row through the full prep+device+finish
        pipeline: compiles the smallest bucket's program AND the eager
        adaptation path (binning / design jits) at registration time."""
        from h2o3_tpu import telemetry
        t0 = time.monotonic()
        with telemetry.span("serving.warmup", algo=model.algo,
                            model=model.key):
            cols = rows_mod.parse_rows(sc.schema, [{}])
            self._score_cols(model, sc, cols, 1, warm=True)
        log.info("serving warm-up for %s took %.3fs", model.key,
                 time.monotonic() - t0)

    def _register_memgov(self) -> None:
        if self._memgov_registered:
            return
        from h2o3_tpu.core import memgov
        memgov.register_aux_cache("serving_scorers",
                                  self.cache_nbytes, self.evict)
        self._memgov_registered = True

    # -- public scoring ------------------------------------------------
    def score_rows(self, model, rows: List[dict],
                   deadline: Optional[float] = None,
                   wait_timeout_s: float = 300.0
                   ) -> Tuple[Dict[str, np.ndarray], Dict, Dict]:
        """The REST row-payload entry: parse → enqueue → coalesced
        device dispatch → this request's slice. Returns
        ``(columns, domains, meta)``. Raises :class:`QueueSaturated`
        (→ 503) on a full queue and ``DeadlineExceeded`` (→ 408) when
        the request deadline expires in the queue or in flight."""
        from h2o3_tpu import telemetry
        sc = self.register(model)
        telemetry.counter("predict_requests_total", algo=model.algo).inc()
        cols = rows_mod.parse_rows(sc.schema, rows)
        if deadline is None:
            deadline = request_ctx.current_deadline()
        # the submitter's trace rides its queue seat: the dispatcher
        # thread attributes retroactive queue/device/scatter sub-spans
        # back to each member request's OWN trace (parent = the span
        # submitting here, typically the rest ingress span)
        from h2o3_tpu.telemetry import spans as _spans
        from h2o3_tpu.telemetry import trace_context as _trace
        tc = _trace.current()
        trace = tc.child(_spans.current_span_id() or tc.parent_id) \
            if tc is not None else None
        pending = PendingScore(cols, len(rows), deadline=deadline,
                               trace=trace)
        self._batchers[model.key].submit(pending)
        timeout = wait_timeout_s
        if deadline is not None:
            timeout = max(deadline - time.monotonic(), 0.0) + 0.25
        if not pending.wait(timeout):
            raise request_ctx.DeadlineExceeded(
                f"predict for {model.key} did not complete within "
                f"{timeout:.1f}s")
        if pending.error is not None:
            raise pending.error
        out, domains = pending.result
        return out, domains, dict(pending.meta)

    def score_columns(self, model, cols: Dict[str, np.ndarray], n: int
                      ) -> Tuple[Dict[str, np.ndarray], Dict]:
        """Direct (batcher-bypassing) scoring of pre-parsed columns —
        the parity-test and warm-path surface."""
        sc = self.register(model)
        return self._score_cols(model, sc, cols, n)

    # -- dispatch ------------------------------------------------------
    def _dispatch(self, model_key: str, batch: List[PendingScore]) -> None:
        from h2o3_tpu import telemetry
        with self._lock:
            sc = self._scorers.get(model_key)
        if sc is None:
            for p in batch:
                p.finish(error=KeyError(
                    f"serving scorer for {model_key} was evicted"))
            return
        from h2o3_tpu.telemetry import spans as spans_mod
        traced = [p for p in batch if p.trace is not None]
        with telemetry.span("predict.dispatch", model=model_key,
                            requests=len(batch)) as dsp:
            if traced:
                # the coalesced dispatch is ONE device program serving
                # many traces — link them all on the dispatch span
                dsp.annotate(member_traces=sorted(
                    {p.trace.trace_id for p in traced}))
            now = time.monotonic()
            wall = time.time()
            q_hist = telemetry.histogram("predict_seconds",
                                         buckets=_LATENCY_BUCKETS,
                                         phase="queue")
            for p in batch:
                q_wait = now - p.enqueue_t
                q_hist.observe(q_wait)
            telemetry.histogram("predict_batch_width",
                                buckets=_WIDTH_BUCKETS).observe(
                float(len(batch)))
            cols = rows_mod.concat_columns([p.cols for p in batch])
            n = sum(p.n for p in batch)
            t_dev = time.monotonic()
            w_dev = time.time()
            out, domains = self._score_cols(sc.model, sc, cols, n)
            telemetry.histogram("predict_seconds",
                                buckets=_LATENCY_BUCKETS,
                                phase="device").observe(
                time.monotonic() - t_dev)
            t_sc = time.monotonic()
            w_sc = time.time()
            off = 0
            for p in batch:
                sl = {nm: arr[off:off + p.n] for nm, arr in out.items()}
                p.finish(result=(sl, domains), batch_requests=len(batch),
                         batch_rows=n)
                off += p.n
            telemetry.histogram("predict_seconds",
                                buckets=_LATENCY_BUCKETS,
                                phase="scatter").observe(
                time.monotonic() - t_sc)
            w_end = time.time()
            # retroactive per-member phase spans, each under its OWN
            # request's trace (parent = the submitting span): the
            # stitched trace shows every member's queue wait + its
            # share of the coalesced device/scatter work
            for p in traced:
                q_wait = max(now - p.enqueue_t, 0.0)
                spans_mod.record_finished(
                    "predict.queue", wall - q_wait, wall,
                    trace_id=p.trace.trace_id,
                    parent_id=p.trace.parent_id,
                    model=model_key, dispatch_span=dsp.id)
                spans_mod.record_finished(
                    "predict.device", w_dev, w_sc,
                    trace_id=p.trace.trace_id,
                    parent_id=p.trace.parent_id,
                    model=model_key, dispatch_span=dsp.id,
                    batch_requests=len(batch), batch_rows=n)
                spans_mod.record_finished(
                    "predict.scatter", w_sc, w_end,
                    trace_id=p.trace.trace_id,
                    parent_id=p.trace.parent_id,
                    model=model_key, dispatch_span=dsp.id)

    # -- the compiled pipeline -----------------------------------------
    def _score_cols(self, model, sc: CompiledScorer,
                    cols: Dict[str, np.ndarray], n: int,
                    warm: bool = False) -> Tuple[Dict, Dict]:
        """Score a batch of training-adapted host columns: window to the
        bucket cap, pad each window to its power-of-two row bucket, run
        the compiled program, reassemble, and apply the shared
        ``Model._finish_predict`` tail."""
        max_rows = int(batch_knobs()["max_rows"])
        parts = []
        with _serve_mesh_scope():
            for lo in range(0, n, max_rows):
                hi = min(lo + max_rows, n)
                win = cols if (lo == 0 and hi == n) else \
                    {nm: a[lo:hi] for nm, a in cols.items()}
                parts.append(
                    self._score_window(model, sc, win, hi - lo, warm))
            merged = parts[0] if len(parts) == 1 else {
                nm: np.concatenate([p[nm] for p in parts])
                for nm in parts[0]}
            return model._finish_predict(merged)

    def _score_window(self, model, sc: CompiledScorer,
                      cols: Dict[str, np.ndarray], n: int,
                      warm: bool) -> Dict[str, np.ndarray]:
        from h2o3_tpu import telemetry
        from h2o3_tpu.core.kv import DKV
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.parallel.model_batch import row_bucket
        bucket = row_bucket(n, int(batch_knobs()["max_rows"]))
        fr = Frame.from_numpy(cols, domains=sc.domains, pad_to=bucket)
        # transient scoring view — keep it out of the store (the
        # expand_interactions idiom, models/glm.py)
        DKV.remove(fr.key)
        try:
            if sc.fallback_reason is not None:
                if not warm:
                    telemetry.counter("scorer_cache_misses_total",
                                      algo=sc.algo, path="eager").inc()
                return model._score_raw(fr)
            x = sc.prep(fr)
            padded = int(fr.nrows_padded)
            hit = padded in sc.buckets
            if not warm:
                telemetry.counter(
                    "scorer_cache_hits_total" if hit
                    else "scorer_cache_misses_total",
                    algo=sc.algo, path="compiled").inc()
            if not hit:
                sc.buckets[padded] = int(getattr(x, "nbytes", 0) or 0)
                self._refresh_gauge()
            fetched = np.asarray(sc.serve(x))
            return model._serve_finish(fetched, n)
        finally:
            fr.drop_device_caches()

    # -- memory governance ---------------------------------------------
    def cache_nbytes(self) -> int:
        with self._lock:
            return sum(sc.nbytes() for sc in self._scorers.values())

    def evict(self, exclude: Optional[set] = None) -> int:
        """Drop compiled scorers (memgov eviction ladder hook); returns
        estimated bytes released. Batchers stay up — the next request
        re-registers and re-warms its model."""
        from h2o3_tpu import telemetry
        freed = 0
        evicted = []
        with self._lock:
            for key in list(self._scorers):
                if exclude and key in exclude:
                    continue
                sc = self._scorers.pop(key)
                freed += sc.nbytes()
                evicted.append(key)
                telemetry.counter("scorer_cache_evictions_total",
                                  algo=sc.algo).inc()
        if freed:
            log.info("evicted %d compiled scorers (%.1f MB est.)",
                     len(evicted), freed / 1e6)
        if evicted:
            # a replica whose scorer was evicted is no longer warm:
            # deregister it from the fleet registry so routing stops
            # sending here and the least-loaded healthy peer re-warms it
            # (serving/fleet.py maybe_adopt)
            try:
                from h2o3_tpu.serving import fleet
                fleet.on_scorers_evicted(evicted)
            except Exception:   # noqa: BLE001 - registry is best-effort
                pass
        self._refresh_gauge()
        return freed

    def _refresh_gauge(self) -> None:
        try:
            from h2o3_tpu import telemetry
            telemetry.gauge("scorer_cache_bytes").set(self.cache_nbytes())
        except Exception:   # noqa: BLE001 - gauges are best-effort
            pass

    # -- lifecycle -----------------------------------------------------
    def queue_depth(self, model_key: Optional[str] = None) -> int:
        """Pending predict requests (one model, or every batcher) — the
        per-peer load signal the fleet router and the telemetry fan-in
        serving block report."""
        with self._lock:
            if model_key is not None:
                b = self._batchers.get(model_key)
                return b.depth() if b is not None else 0
            return sum(b.depth() for b in self._batchers.values())

    def warm_models(self) -> List[str]:
        """Model keys with a warm compiled scorer in this process."""
        with self._lock:
            return sorted(self._scorers)

    def drain(self) -> None:
        """Graceful shutdown (ISSUE 17): deregister this process's
        replicas from the fleet registry FIRST (routing stops sending
        here), then close every batcher — the dispatcher thread joins,
        its in-flight batch finishes, and queued requests fail fast with
        :class:`BatcherDraining` (→ 503 + Retry-After) instead of
        hanging on abandoned futures."""
        try:
            from h2o3_tpu.serving import fleet
            fleet.deregister_local(reason="draining")
        except Exception:   # noqa: BLE001 - registry is best-effort
            pass
        self.reset()

    def stats(self) -> Dict:
        with self._lock:
            return {
                "models": {
                    k: {"algo": sc.algo,
                        "compiled": sc.fallback_reason is None,
                        "fallback_reason": sc.fallback_reason,
                        "buckets": sorted(sc.buckets),
                        "nbytes": sc.nbytes()}
                    for k, sc in self._scorers.items()},
                "cache_nbytes": self.cache_nbytes(),
            }

    def reset(self) -> None:
        """Test/shutdown hook: drop scorers and stop dispatchers."""
        with self._lock:
            batchers = list(self._batchers.values())
            self._batchers.clear()
            self._scorers.clear()
        for b in batchers:
            b.close()
        self._refresh_gauge()


# process-wide engine (the scorer cache is per-process, like the DKV)
engine = ScoringEngine()
