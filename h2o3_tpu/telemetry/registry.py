"""Process-wide metrics registry — counters, gauges, bucketed histograms.

Reference: the runtime-observability role of water/TimeLine.java plus
water/util/WaterMeter* — always-on, cheap enough to never turn off.
The reference exposes raw tick arrays per endpoint; here one registry
holds every runtime counter and the REST tier renders it as JSON or
Prometheus text exposition (GET /3/Metrics).

Metric identity is (name, sorted label items). Names are auto-prefixed
``h2o3tpu_`` so the exposition namespace never collides with a
co-located exporter; the names listed in README §Observability are a
stable surface.

Cost model: one dict lookup + one lock'd float add per op (~1µs). Every
op also bumps ``_OPS`` so tests can bound total telemetry overhead as
ops x per-op cost (the TimeLine "cheap enough to leave on" constraint,
asserted loosely in tests/test_telemetry.py).
"""

from __future__ import annotations

import bisect
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

PREFIX = "h2o3tpu_"

ENABLED = os.environ.get("H2O3TPU_TELEMETRY", "1") != "0"

# default duration buckets (seconds): sub-ms dispatches → multi-minute
# trainings; Prometheus-style cumulative le= bounds
SECONDS_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                   900.0)
# payload/collective sizes: 256B .. 16GB, x8 per step
BYTES_BUCKETS = tuple(256.0 * 8 ** i for i in range(9))


def _full_name(name: str) -> str:
    return name if name.startswith(PREFIX) else PREFIX + name


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        global _OPS
        with self._lock:
            self._value += n
        _OPS += 1

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        global _OPS
        with self._lock:
            self._value = float(v)
        _OPS += 1

    def set_max(self, v: float) -> None:
        """High-water update (device-memory peaks)."""
        global _OPS
        with self._lock:
            if v > self._value:
                self._value = float(v)
        _OPS += 1

    def add(self, v: float) -> None:
        global _OPS
        with self._lock:
            self._value += v
        _OPS += 1

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its bound; +Inf is implicit via count)."""

    __slots__ = ("name", "labels", "bounds", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name: str, labels: Dict[str, str],
                 buckets: Sequence[float] = SECONDS_BUCKETS):
        self.name = name
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * len(self.bounds)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        global _OPS
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            if i < len(self._counts):
                self._counts[i] += 1
            self._sum += v
            self._count += 1
        _OPS += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[int]:
        acc, out = 0, []
        with self._lock:
            counts = list(self._counts)
        for c in counts:
            acc += c
            out.append(acc)
        return out

    def counts_snapshot(self) -> Tuple[List[int], int]:
        """(per-bucket counts, total count) under one lock hold — the
        consistent view quantile math and the SLO engine need."""
        with self._lock:
            return list(self._counts), self._count

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile (the histogram_quantile
        estimator): linear interpolation inside the bucket the rank
        falls in, each bucket's lower edge being the previous bound (0
        for the first). Observations beyond the last bound live in the
        implicit +Inf bucket and clamp to the last finite bound — the
        same conservative answer PromQL gives. None with no
        observations."""
        counts, total = self.counts_snapshot()
        return _quantile_from_counts(self.bounds, counts, total, q)


def _quantile_from_counts(bounds: Sequence[float], counts: Sequence[int],
                          total: int, q: float) -> Optional[float]:
    if total <= 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        if c and seen + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * ((rank - seen) / c)
        seen += c
    return float(bounds[-1])   # rank fell in the +Inf overflow


def merged_quantile(hists: Sequence["Histogram"],
                    q: float) -> Optional[float]:
    """Quantile over the UNION of several histograms' observations.
    Only meaningful when every histogram shares one bucket grid —
    asserted, because silently merging mismatched grids produced
    garbage p99s (the predict_seconds{phase} audit)."""
    hists = [h for h in hists if h is not None]
    if not hists:
        return None
    bounds = hists[0].bounds
    for h in hists[1:]:
        if h.bounds != bounds:
            raise ValueError(
                f"merged_quantile over mismatched bucket grids: "
                f"{h.name}{h.labels} has {len(h.bounds)} bounds vs "
                f"{len(bounds)}")
    counts = [0] * len(bounds)
    total = 0
    for h in hists:
        cs, n = h.counts_snapshot()
        total += n
        for i, c in enumerate(cs):
            counts[i] += c
    return _quantile_from_counts(bounds, counts, total, q)


_OPS = 0   # total registry ops since boot (overhead accounting)


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[Tuple[str, tuple], object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, labels: Dict[str, str], **kw):
        name = _full_name(name)
        key = (name, _label_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, dict(labels), **kw)
                    self._metrics[key] = m
        return m

    def counter(self, name: str, /, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, /, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, /,
                  buckets: Sequence[float] = SECONDS_BUCKETS,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def value(self, name: str, /, **labels) -> float:
        """Current value of a counter/gauge (0.0 if never touched);
        for a histogram returns its observation count."""
        name = _full_name(name)
        with self._lock:
            m = self._metrics.get((name, _label_key(labels)))
        if m is None:
            return 0.0
        return float(m.count if isinstance(m, Histogram) else m.value)

    def find(self, name: str) -> List[object]:
        """Every metric object registered under ``name`` (any label
        set) — the SLO engine's read surface."""
        name = _full_name(name)
        with self._lock:
            items = list(self._metrics.items())
        return [m for (n, _), m in items if n == name]

    def total(self, name: str) -> float:
        """Sum of a counter across all label sets."""
        name = _full_name(name)
        tot = 0.0
        with self._lock:
            items = list(self._metrics.items())
        for (n, _), m in items:
            if n == name and isinstance(m, Counter):
                tot += m.value
        return tot

    def counter_totals(self) -> Dict[str, float]:
        """Counter totals folded over label sets — the cheap start/end
        delta snapshot the flight recorder takes per job."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, float] = {}
        for (name, _), m in items:
            if isinstance(m, Counter):
                out[name] = out.get(name, 0.0) + m.value
        return out

    def ops(self) -> int:
        return _OPS

    def reset(self) -> None:
        """Drop every metric (tests only — production metrics are
        cumulative-since-boot like the reference's tick counters)."""
        with self._lock:
            self._metrics.clear()

    def drop(self, name: str) -> None:
        """Remove every metric registered under ``name``, any label set
        (tests only — lets a subsystem reset just its own families)."""
        name = _full_name(name)
        with self._lock:
            for key in [k for k in self._metrics if k[0] == name]:
                del self._metrics[key]

    # -- exposition ---------------------------------------------------
    def snapshot(self) -> Dict[str, list]:
        """JSON shape: {counters: [...], gauges: [...], histograms: [...]},
        each entry {name, labels, value|...}."""
        # copy under the lock: a scrape racing first-touch metric
        # creation on another thread must never see the dict resize
        # mid-iteration (RuntimeError → 500 on /3/Metrics)
        with self._lock:
            items = list(self._metrics.items())
        counters, gauges, hists = [], [], []
        for (_, _), m in sorted(items, key=lambda kv: kv[0]):
            if isinstance(m, Counter):
                counters.append({"name": m.name, "labels": m.labels,
                                 "value": m.value})
            elif isinstance(m, Gauge):
                gauges.append({"name": m.name, "labels": m.labels,
                               "value": m.value})
            else:
                hists.append({"name": m.name, "labels": m.labels,
                              "count": m.count, "sum": m.sum,
                              "buckets": list(zip(m.bounds,
                                                  m.cumulative()))})
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        def _lbl(labels: Dict[str, str], extra: str = "") -> str:
            items = [f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())]
            if extra:
                items.append(extra)
            return "{" + ",".join(items) + "}" if items else ""

        def _esc(v) -> str:
            return str(v).replace("\\", r"\\").replace('"', r'\"') \
                         .replace("\n", r"\n")

        # same copy-under-lock discipline as snapshot(): the exposition
        # walk must not race first-touch creation
        with self._lock:
            metrics = list(self._metrics.values())
        by_name: Dict[str, List[object]] = {}
        for m in metrics:
            by_name.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for name in sorted(by_name):
            ms = by_name[name]
            kind = ("counter" if isinstance(ms[0], Counter) else
                    "gauge" if isinstance(ms[0], Gauge) else "histogram")
            lines.append(f"# TYPE {name} {kind}")
            for m in ms:
                if isinstance(m, (Counter, Gauge)):
                    lines.append(f"{m.name}{_lbl(m.labels)} {m.value:g}")
                else:
                    cum = m.cumulative()
                    for bound, c in zip(m.bounds, cum):
                        le = 'le="%g"' % bound
                        lines.append(f"{m.name}_bucket"
                                     f"{_lbl(m.labels, le)} {c}")
                    inf = 'le="+Inf"'
                    lines.append(f"{m.name}_bucket"
                                 f"{_lbl(m.labels, inf)} {m.count}")
                    lines.append(f"{m.name}_sum{_lbl(m.labels)} {m.sum:g}")
                    lines.append(f"{m.name}_count{_lbl(m.labels)} {m.count}")
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()

# module-level shorthands — the instrumentation call surface
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
