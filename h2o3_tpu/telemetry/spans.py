"""Hierarchical span tracer — where a distributed fit spends its time.

Reference: the reference answers "where did the time go" with the
TimeLine packet ring + /3/Profiler stack samples; the TPU runtime's
time sinks are instead structured phases (job → algo.fit → boost chunk
→ xla compile), so the primitive here is a nested span:

    with span("gbm.fit"):
        with span("gbm.chunk", trees=25):
            ...

Each span records wall time, the device-memory high-water mark at exit
(``device.memory_stats()['peak_bytes_in_use']``, best-effort — some
plugin backends report none), and any collective-byte estimates charged
to it by the dispatch layer (parallel/map_reduce.py). Nesting is
contextvar-based, so worker threads (background jobs) get their own
root spans for free. Finished spans land in a fixed ring (the TimeLine
capacity discipline) and feed ``span_seconds{name=}`` histograms in the
registry; ``GET /3/Metrics`` serves both views.

Timeline events recorded while a span is active carry its id
(utils/timeline.py), tying the flat event ring to the span tree.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

from h2o3_tpu.telemetry.registry import counter, histogram

_CAPACITY = 1024
_finished: deque = deque(maxlen=_CAPACITY)
_finished_lock = threading.Lock()
_ids = itertools.count(1)

_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("h2o3tpu_span", default=None)


class Span:
    __slots__ = ("id", "name", "parent_id", "trace_id", "start", "end",
                 "meta", "device_peak_bytes", "collective_bytes",
                 "_token", "_peak_base")

    def __init__(self, name: str, parent_id: Optional[str],
                 trace_id: Optional[str] = None, **meta):
        self.id = f"sp-{next(_ids):08d}"
        self.name = name
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.start = time.time()
        self.end = 0.0
        self.meta = meta
        self.device_peak_bytes = 0
        self.collective_bytes = 0.0
        self._token = None
        self._peak_base = 0

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def annotate(self, **meta) -> None:
        self.meta.update(meta)

    def to_dict(self) -> Dict:
        return {"id": self.id, "parent_id": self.parent_id,
                "trace_id": self.trace_id,
                "name": self.name,
                "start_ms": int(self.start * 1000),
                "duration_ms": round(self.duration * 1000, 3),
                "device_peak_bytes": self.device_peak_bytes,
                "collective_bytes": self.collective_bytes,
                "meta": {k: v for k, v in self.meta.items()}}


def _device_peak() -> int:
    """Device HBM high-water, 0 when the backend reports no stats (the
    axon plugin case — job.py documents that pressure then shows up as
    RESOURCE_EXHAUSTED, not as this gauge)."""
    try:
        import jax
        s = jax.devices()[0].memory_stats() or {}
        return int(s.get("peak_bytes_in_use", 0) or 0)
    except Exception:   # noqa: BLE001 - stats are strictly best-effort
        return 0


@contextmanager
def span(name: str, **meta):
    """Open a child of the current span (root if none) for the duration
    of the with-block. Exceptions propagate; the span still closes.

    ``device_peak_bytes`` is SPAN-RELATIVE: the process high-water mark
    is read at entry as a baseline, and the span reports how far the
    high-water ROSE while it was open. Best-effort semantics: the mark
    is process-wide and monotonic, so concurrent spans each get charged
    the shared rise, and a span that allocated under an earlier
    high-water reports 0 (pre-fix every span after the global peak
    reported the same global max). Backends without ``memory_stats``
    report 0 throughout."""
    from h2o3_tpu.telemetry import trace_context
    parent = _current.get()
    tc = trace_context.current()
    # cross-process/cross-thread stitch: a ROOT span (no in-process
    # parent) parents under the installed trace context's parent id —
    # the submitting request's span on the other side of the hop
    parent_id = parent.id if parent is not None \
        else (tc.parent_id if tc is not None else None)
    sp = Span(name, parent_id,
              trace_id=tc.trace_id if tc is not None else None, **meta)
    sp._peak_base = _device_peak()
    sp._token = _current.set(sp)
    try:
        yield sp
    finally:
        _current.reset(sp._token)
        sp.end = time.time()
        sp.device_peak_bytes = max(0, _device_peak() - sp._peak_base)
        if parent is not None:
            # charge child collective traffic up the tree so a root job
            # span totals its whole subtree
            parent.collective_bytes += sp.collective_bytes
        with _finished_lock:
            _finished.append(sp)
        counter("spans_total", name=name).inc()
        histogram("span_seconds", name=name).observe(sp.end - sp.start)
        # per-job flight recorder capture (one contextvar read when no
        # recorder is attached — telemetry/flight_recorder.py)
        try:
            from h2o3_tpu.telemetry import flight_recorder
            flight_recorder.record_span(sp)
        except Exception:   # noqa: BLE001 - capture is best-effort
            pass
        from h2o3_tpu.utils.timeline import record as _tl
        _tl("span", f"{name} {sp.duration * 1000:.1f}ms",
            span_id=sp.id, parent_id=sp.parent_id)


@contextmanager
def detach():
    """Detach from the in-process span stack for the with-block: the
    next span opened becomes a ROOT, parenting under the installed
    trace context (if any) instead of the local ancestor. A leased
    scheduler item executes under the LEASE's causality — the
    coordinator's sched.run — not the local polling loop's."""
    token = _current.set(None)
    try:
        yield
    finally:
        _current.reset(token)


def record_finished(name: str, start: float, end: float, *,
                    trace_id: Optional[str] = None,
                    parent_id: Optional[str] = None, **meta) -> Span:
    """Record a span whose interval was measured AFTER the fact — the
    serving batcher's queue/device/scatter phases are timed inside the
    coalesced dispatch, then attributed back to each member request's
    own trace. Skips the device-peak baseline (the interval is already
    closed) but otherwise lands in the same ring/metrics/flight
    recorder as a live span."""
    sp = Span(name, parent_id, trace_id=trace_id, **meta)
    sp.start = float(start)
    sp.end = float(end)
    with _finished_lock:
        _finished.append(sp)
    counter("spans_total", name=name).inc()
    histogram("span_seconds", name=name).observe(max(sp.end - sp.start,
                                                     0.0))
    try:
        from h2o3_tpu.telemetry import flight_recorder
        flight_recorder.record_span(sp)
    except Exception:   # noqa: BLE001 - capture is best-effort
        pass
    return sp


def current_span() -> Optional[Span]:
    return _current.get()


def current_span_id() -> Optional[str]:
    sp = _current.get()
    return sp.id if sp is not None else None


def add_collective_bytes(n: float) -> None:
    """Charge an estimated collective payload to the active span."""
    sp = _current.get()
    if sp is not None:
        sp.collective_bytes += n


def annotate(**meta) -> None:
    """Attach metadata to the active span (no-op without one)."""
    sp = _current.get()
    if sp is not None:
        sp.meta.update(meta)


def snapshot(last: int = 100) -> List[Dict]:
    """Most recent finished spans, oldest first."""
    with _finished_lock:
        evs = list(_finished)
    return [s.to_dict() for s in evs[-max(int(last), 0):]]


def aggregate() -> List[Dict]:
    """Per-name rollup of the finished ring (the /3/Profiler span view):
    count, total/mean wall ms, max device peak."""
    with _finished_lock:
        evs = list(_finished)
    agg: Dict[str, Dict] = {}
    for s in evs:
        a = agg.setdefault(s.name, {"name": s.name, "count": 0,
                                    "total_ms": 0.0,
                                    "device_peak_bytes": 0,
                                    "collective_bytes": 0.0})
        a["count"] += 1
        a["total_ms"] += s.duration * 1000
        a["device_peak_bytes"] = max(a["device_peak_bytes"],
                                     s.device_peak_bytes)
        a["collective_bytes"] += s.collective_bytes
    out = sorted(agg.values(), key=lambda a: -a["total_ms"])
    for a in out:
        a["total_ms"] = round(a["total_ms"], 3)
        a["mean_ms"] = round(a["total_ms"] / max(a["count"], 1), 3)
    return out


def clear() -> None:
    """Tests only."""
    with _finished_lock:
        _finished.clear()
