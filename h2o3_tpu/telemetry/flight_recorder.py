"""Flight recorder — a per-job capsule of everything telemetry saw.

PR 1 left the runtime with always-on aggregates (registry counters,
the span ring, the compile observer), but aggregates can't answer the
post-hoc question an operator actually asks: *what did THIS job do?*
The reference answers it with per-node log files plus the TimeLine
ring; here a job-scoped recorder rides the existing instrumentation:

- when a :class:`~h2o3_tpu.core.job.Job` starts, ``attach()`` installs
  a :class:`JobRecorder` on the worker thread's context;
- every span that closes on that context (telemetry/spans.py), every
  timeline event (utils/timeline.py), every XLA compile
  (telemetry/compile_observer.py) and every log record (utils/log.py)
  is *also* appended to the job's bounded :class:`JobTelemetry`
  capsule — the always-on ring/registry paths are untouched;
- the capsule lives in the DKV under ``<job_key>_telemetry``. It is
  DKV.put INSIDE the job's Scope, so a cancelled/expired job's capsule
  is swept with the rest of its partial keys (the water/Scope.java
  exit-on-abort contract); completed jobs keep theirs, bounded by a
  process-wide retention ring (``H2O3TPU_FLIGHT_RECORDER_KEEP`` newest
  capsules; older ones are evicted from the DKV).

``GET /3/Jobs/{key}/trace`` (api/server.py) renders a capsule as
Chrome trace-event JSON via telemetry/trace_export.py — the
DrJAX-style dispatch/compile timeline, loadable in Perfetto.

Capture is CONTEXT-scoped, not thread-scoped: nested foreground jobs
(grid → model builds) stack their recorders, so an inner model build
is captured by its own capsule AND its parent grid job's. Work a job
hands to unmanaged helper threads is best-effort invisible (same
limitation as thread-local Scope tracking).

Cost model: with no recorder attached, every hook is one contextvar
read of an empty tuple (~100ns) — the "cheap enough to leave on"
TimeLine constraint holds (tests/test_telemetry.py overhead bound runs
with the recorder enabled).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from h2o3_tpu.telemetry.registry import REGISTRY

ENABLED = os.environ.get("H2O3TPU_FLIGHT_RECORDER", "1") != "0"

# per-capsule bounds: a runaway job (million-chunk fit, log storm) must
# yield a truncated capsule, never an unbounded one — drops are counted
MAX_SPANS = 2048
MAX_EVENTS = 2048
MAX_COMPILES = 512
MAX_LOGS = 1024
MAX_STEP_PROFILES = 64

TELEMETRY_SUFFIX = "_telemetry"


def capsule_key(job_key: str) -> str:
    return f"{job_key}{TELEMETRY_SUFFIX}"


def keep_count() -> int:
    """Completed-job capsules retained in the DKV (newest first) —
    env ``H2O3TPU_FLIGHT_RECORDER_KEEP`` wins over config.ARGS, the
    watchdog/gate knob pattern."""
    env = os.environ.get("H2O3TPU_FLIGHT_RECORDER_KEEP")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    try:
        from h2o3_tpu.core import config as _cfg
        return max(0, int(_cfg.ARGS.flight_recorder_keep))
    except Exception:   # noqa: BLE001 - config not importable yet
        return 32


class JobTelemetry:
    """One job's bounded telemetry capsule (DKV value)."""

    __slots__ = ("job_key", "description", "start_ms", "end_ms", "status",
                 "spans", "events", "compiles", "logs", "step_profiles",
                 "metric_deltas", "dropped", "node", "slo_alerts",
                 "_counters0", "_lock")

    def __init__(self, job_key: str, description: str):
        self.job_key = job_key
        self.description = description
        # cloud identity: merged cluster views and single-file log
        # shipping must stay attributable to the producing process
        try:
            from h2o3_tpu.utils.log import current_node
            self.node = current_node()
        except Exception:   # noqa: BLE001
            self.node = 0
        self.start_ms = int(time.time() * 1000)
        self.end_ms = 0
        self.status = "RUNNING"
        self.spans: List[Dict] = []
        self.events: List[Dict] = []
        self.compiles: List[Dict] = []
        self.logs: List[Dict] = []
        self.step_profiles: List[Dict] = []
        self.metric_deltas: Dict[str, float] = {}
        self.dropped: Dict[str, int] = {}
        self.slo_alerts: List[Dict] = []
        self._counters0 = _counter_totals()
        self._lock = threading.Lock()

    # -- capture (hot path: one lock'd append) -------------------------
    def _add(self, bucket: List[Dict], cap: int, kind: str, item: Dict):
        with self._lock:
            if len(bucket) < cap:
                bucket.append(item)
            else:
                self.dropped[kind] = self.dropped.get(kind, 0) + 1

    def add_span(self, span_dict: Dict) -> None:
        self._add(self.spans, MAX_SPANS, "spans", span_dict)

    def add_event(self, event: Dict) -> None:
        self._add(self.events, MAX_EVENTS, "events", event)

    def add_compile(self, compile_event: Dict) -> None:
        self._add(self.compiles, MAX_COMPILES, "compiles", compile_event)

    def add_log(self, log_record: Dict) -> None:
        self._add(self.logs, MAX_LOGS, "logs", log_record)

    def add_step_profile(self, profile: Dict) -> None:
        self._add(self.step_profiles, MAX_STEP_PROFILES,
                  "step_profiles", profile)

    # -- lifecycle -----------------------------------------------------
    def finalize(self, status: str) -> None:
        self.end_ms = int(time.time() * 1000)
        self.status = status
        now = _counter_totals()
        self.metric_deltas = {
            name: round(now[name] - self._counters0.get(name, 0.0), 6)
            for name in now
            if now[name] != self._counters0.get(name, 0.0)}
        # SLO alerts firing as this job ended — a capsule pulled for a
        # slow job should say whether an objective was already burning
        try:
            from h2o3_tpu.telemetry import slo as _slo
            self.slo_alerts = _slo.active_alerts()
        except Exception:   # noqa: BLE001 - capture is best-effort
            self.slo_alerts = []

    def to_dict(self) -> Dict:
        with self._lock:
            return {
                "job_key": self.job_key,
                "description": self.description,
                "status": self.status,
                "node": self.node,
                "start_ms": self.start_ms,
                "end_ms": self.end_ms,
                "duration_ms": (self.end_ms - self.start_ms)
                if self.end_ms else None,
                "spans": list(self.spans),
                "events": list(self.events),
                "compiles": list(self.compiles),
                "logs": list(self.logs),
                # getattr: capsules restored from a pre-step-profile
                # checkpoint (core/checkpoint.py) lack the slot
                "step_profiles": list(getattr(self, "step_profiles",
                                              None) or []),
                "metric_deltas": dict(self.metric_deltas),
                "dropped": dict(self.dropped),
                "slo_alerts": list(self.slo_alerts),
            }


def _counter_totals() -> Dict[str, float]:
    """Counter totals by name (labels folded) — the start/end metric
    delta a capsule reports ("this job cost 3 compiles, 412 reduces")."""
    return REGISTRY.counter_totals()


# active recorders on THIS context, innermost last. A tuple (immutable)
# so readers never see a half-built list.
_ACTIVE: contextvars.ContextVar[Tuple[JobTelemetry, ...]] = \
    contextvars.ContextVar("h2o3tpu_flight_recorders", default=())

# completed-capsule retention ring (keys, oldest first)
_ring: deque = deque()
_ring_lock = threading.Lock()


class _Handle:
    __slots__ = ("capsule", "token", "published")

    def __init__(self, capsule: JobTelemetry, token):
        self.capsule = capsule
        self.token = token
        self.published = False


def attach(job_key: str, description: str = "") -> Optional[_Handle]:
    """Start recording the current context into a fresh capsule.

    Called by Job.start on the WORKER thread (a background thread's
    context is fresh, so the job really is the recording root there).
    Returns None when the recorder is disabled."""
    if not ENABLED:
        return None
    cap = JobTelemetry(job_key, description)
    token = _ACTIVE.set(_ACTIVE.get() + (cap,))
    return _Handle(cap, token)


def publish(handle: Optional[_Handle]) -> None:
    """DKV.put the capsule under ``<job_key>_telemetry`` — called from
    inside the job's Scope so the key is tracked and therefore swept
    when a cancelled job's scope unwinds."""
    if handle is None:
        return
    from h2o3_tpu.core.kv import DKV
    DKV.put(capsule_key(handle.capsule.job_key), handle.capsule)
    handle.published = True


def detach(handle: Optional[_Handle], status: str) -> None:
    """Stop recording, stamp the end state, and rotate retention: keep
    the newest ``H2O3TPU_FLIGHT_RECORDER_KEEP`` completed capsules, evict
    older ones from the DKV. A capsule whose key is already gone (the
    cancel sweep) is finalized but not resurrected."""
    if handle is None:
        return
    _ACTIVE.reset(handle.token)
    handle.capsule.finalize(status)
    if not handle.published:
        return
    from h2o3_tpu.core.kv import DKV
    key = capsule_key(handle.capsule.job_key)
    if key not in DKV:          # swept with the cancelled job's Scope
        return
    keep = keep_count()
    if keep == 0:
        DKV.remove(key)
        return
    with _ring_lock:
        _ring.append(key)
        while len(_ring) > keep:
            DKV.remove(_ring.popleft())


def get_capsule(job_key: str) -> Optional[JobTelemetry]:
    from h2o3_tpu.core.kv import DKV
    cap = DKV.get(capsule_key(job_key))
    return cap if isinstance(cap, JobTelemetry) else None


# ---------------------------------------------------------------- hooks
# Called from spans.py / timeline.py / compile_observer.py / log.py.
# With no recorder attached these cost one contextvar read.


def record_span(span) -> None:
    recs = _ACTIVE.get()
    if recs:
        d = span.to_dict()
        for cap in recs:
            cap.add_span(d)


def record_event(event: Dict) -> None:
    for cap in _ACTIVE.get():
        cap.add_event(event)


def record_compile(compile_event: Dict) -> None:
    for cap in _ACTIVE.get():
        cap.add_compile(compile_event)


def record_log(log_record: Dict) -> None:
    for cap in _ACTIVE.get():
        cap.add_log(log_record)


def record_step_profile(profile: Dict) -> None:
    """Per-fit step-profile block (telemetry/stepprof.py finish): the
    capsule answer to "where did THIS fit's wall clock go" — and, per
    fit, the MFU/phase record that the latest-wins ``model_fit_mfu``
    gauge cannot carry for concurrent same-algo fits."""
    for cap in _ACTIVE.get():
        cap.add_step_profile(profile)


def is_recording() -> bool:
    return bool(_ACTIVE.get())


def clear() -> None:
    """Tests only — drop the retention ring (not the DKV entries)."""
    with _ring_lock:
        _ring.clear()
