"""Compile observer — XLA compile storms made visible.

The recurring production failure mode of this runtime is not compute,
it is COMPILATION: every distinct padded shape is a fresh 20-40s XLA
trace+compile (ops/segments.py, frame/binning.py shape-bucket notes),
and a workload that misses the shape buckets silently spends its wall
time in the compiler. Two complementary probes:

1. ``install()`` hooks ``jax.monitoring`` duration events, so EVERY
   backend compile in the process increments
   ``xla_compile_total`` / ``xla_compile_seconds`` — no call-site
   changes needed, and compile time is charged to the active span.

2. ``observed_jit("name")`` decorates a jitted entry point and counts
   executable-cache hits vs fresh compiles per SHAPE-BUCKET (the
   argument signature XLA keys on), via the function's jit cache size
   before/after each call:
   ``jit_cache_{hit,miss}_total{fn=,shapes=}``. This is what tells an
   operator that e.g. k-fold CV is compiling per fold instead of
   hitting the padded_rows bucket.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List

from h2o3_tpu.telemetry import spans
from h2o3_tpu.telemetry.registry import counter, histogram

_installed = False
_install_lock = threading.Lock()

# recent compile events (end timestamp + duration) — the dedicated
# compile track in Chrome-trace exports (telemetry/trace_export.py)
_COMPILE_RING_CAPACITY = 512
_compile_ring: deque = deque(maxlen=_COMPILE_RING_CAPACITY)
_compile_ring_lock = threading.Lock()

# per observed fn: shape-signature interning with a cap, so label
# cardinality stays bounded even under pathological shape churn
_MAX_SHAPE_LABELS = 32
_shape_labels: Dict[str, set] = {}

# AOT replay sources for roofline accounting (telemetry/roofline.py):
# on each fresh compile the observed jit entry point's call signature is
# stashed as ABSTRACT shapes (jax.ShapeDtypeStruct — no device buffers
# retained), so Compiled.cost_analysis() can later be taken off a
# re-lowering of the exact executable the fit ran, without holding HBM.
_aot_sources: Dict[str, tuple] = {}
_aot_lock = threading.Lock()


def _abstractify(x):
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if isinstance(shape, tuple) and dtype is not None:
        import jax
        return jax.ShapeDtypeStruct(shape, dtype)
    return x


def _record_aot_source(name: str, jit_fn, args, kwargs) -> None:
    try:
        import jax
        aargs = jax.tree_util.tree_map(_abstractify, args)
        akwargs = {k: jax.tree_util.tree_map(_abstractify, v)
                   for k, v in kwargs.items()}
        with _aot_lock:
            _aot_sources[name] = (jit_fn, aargs, akwargs)
    except Exception:   # noqa: BLE001 - accounting must never break a fit
        pass


def aot_source(name: str):
    """(jit_fn, abstract_args, abstract_kwargs) of the most recent fresh
    compile of an observed entry point, or None."""
    with _aot_lock:
        return _aot_sources.get(name)


def aot_source_names():
    with _aot_lock:
        return sorted(_aot_sources)

_COMPILE_EVENTS = ("backend_compile_duration",      # jax >= 0.4.31
                   "backend_compile_time_sec")      # older spelling


def _on_duration(name: str, secs: float, **kw) -> None:
    if not name.endswith(_COMPILE_EVENTS):
        return
    counter("xla_compile_total").inc()
    histogram("xla_compile_seconds").observe(secs)
    sp = spans.current_span()
    ev = {"ts_ms": int(time.time() * 1000), "dur_s": round(secs, 6),
          "event": "xla_compile",
          "span_id": sp.id if sp is not None else None}
    with _compile_ring_lock:
        _compile_ring.append(ev)
    try:
        from h2o3_tpu.telemetry import flight_recorder
        flight_recorder.record_compile(ev)
    except Exception:   # noqa: BLE001 - capture is best-effort
        pass
    if sp is not None:
        sp.meta["xla_compiles"] = sp.meta.get("xla_compiles", 0) + 1
        sp.meta["xla_compile_s"] = round(
            sp.meta.get("xla_compile_s", 0.0) + secs, 3)


def compiles_snapshot(last: int = _COMPILE_RING_CAPACITY) -> List[Dict]:
    """Most recent compile events, oldest first."""
    with _compile_ring_lock:
        evs = list(_compile_ring)
    return evs[-max(int(last), 0):]


def install() -> None:
    """Register the jax.monitoring listener (idempotent, process-wide)."""
    global _installed
    with _install_lock:
        if _installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
            _installed = True
        except Exception:   # noqa: BLE001 - telemetry must never break init
            pass


def _sig_of(a) -> str:
    shape = getattr(a, "shape", None)
    if isinstance(shape, tuple):    # arrays only (Mesh.shape is a dict)
        return "x".join(map(str, shape)) or "0d"
    if isinstance(a, (list, tuple)) and a:      # pytree-of-arrays args
        inner = [_sig_of(v) for v in a[:8]]
        inner = [s for s in inner if s]
        return "[" + "|".join(inner) + "]" if inner else ""
    return ""


def _shape_sig(args, kwargs) -> str:
    """Compact shape-bucket signature of the array arguments — the part
    of the jit cache key an operator can act on (pick better buckets)."""
    parts = [s for s in (_sig_of(a) for a in args) if s]
    for k in sorted(kwargs):
        s = _sig_of(kwargs[k])
        if s:
            parts.append(f"{k}:{s}")
    return ",".join(parts) or "scalar"


def _bucket_label(fn_name: str, sig: str) -> str:
    seen = _shape_labels.setdefault(fn_name, set())
    if sig in seen:
        return sig
    if len(seen) >= _MAX_SHAPE_LABELS:
        return "overflow"
    seen.add(sig)
    return sig


def observed_jit(name: str) -> Callable:
    """Decorator for a ``jax.jit``-ed function: per-shape-bucket cache
    hit/miss accounting. Stack ABOVE the jit decorator:

        @observed_jit("gbm.boost_scan")
        @partial(jax.jit, static_argnames=(...))
        def _boost_scan_jit(...): ...
    """
    def deco(jit_fn):
        import functools

        @functools.wraps(jit_fn)
        def wrapper(*args, **kwargs):
            size_of = getattr(jit_fn, "_cache_size", None)
            if size_of is None:            # not a jit object: pass through
                return jit_fn(*args, **kwargs)
            before = size_of()
            out = jit_fn(*args, **kwargs)
            fresh = size_of() > before
            sig = _bucket_label(name, _shape_sig(args, kwargs))
            counter("jit_cache_miss_total" if fresh
                    else "jit_cache_hit_total", fn=name, shapes=sig).inc()
            if fresh:
                spans.annotate(fresh_compile=name)
                # miss-only: interning abstract shapes per call would tax
                # hot entry points (ops.segment_sum) for nothing new
                _record_aot_source(name, jit_fn, args, kwargs)
            return out
        return wrapper
    return deco
