"""Declarative SLOs + multi-window burn-rate alerting.

The registry (registry.py) accumulates since boot but nothing CONSUMES
it to judge health against objectives — an operator watching
``/3/Metrics`` has data, not answers. This module is the answer layer:
a small set of declarative SLO rules evaluated on demand from the
live registry with the standard multi-window burn-rate construction
(alert when the error budget burns faster than allowed over BOTH a
short 5m and a long 1h window; the long window confirms the burn is
real, the short window clears fast on recovery).

Burn rate = (observed error rate over a window) / (budgeted error
rate), where budget = ``1 - objective``. Rate > 1 means the budget is
burning faster than the objective allows. Registry counters are
cumulative-since-boot, so the engine keeps a bounded ring of
(timestamp, per-rule cumulative counts) samples — one per ``evaluate``
at >= 1s spacing — and window deltas come from the newest sample at or
before the window start (falling back to the oldest sample when the
process is younger than the window).

Per-rule state machine, transitions counted in
``slo_alert_transitions_total{slo,to}`` and recorded as ``slo``
timeline events (which flow into any recording flight-recorder
capsule):

    healthy -> burning   short-window burn exceeded, long not yet
    burning -> alert     long window confirms (both windows over)
    alert   -> recovery  short window back under budget
    recovery-> healthy   long window drained too

Surfaces: ``GET /3/Alerts`` (+ ``?cluster=1`` via telemetry/cluster.py
fan-in), ``slo_*`` gauges in the Prometheus scrape (refreshed on every
evaluate, which ``GET /3/Metrics`` triggers), and a final
``slo_alerts`` snapshot stamped into every flight-recorder capsule at
job end.

Default rules: predict p99 latency (``predict_seconds`` — all phases,
merged across ONE shared bucket grid), REST availability
(``rest_request_seconds{status}`` + ``rest_rejected_total``),
heartbeat health (``heartbeat_misses_total`` vs
``heartbeat_rounds_total``), and a fit-MFU floor
(``model_fit_mfu{algo}``, off by default). Everything here is
deliberately jax-free: bench.py's ``_stub_slo`` leg drives the full
state machine with a private registry and a fake clock.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from h2o3_tpu.telemetry.registry import (REGISTRY, MetricsRegistry,
                                         Counter, Histogram,
                                         merged_quantile)

SHORT_WINDOW_S = 300.0
LONG_WINDOW_S = 3600.0
_MIN_SAMPLE_SPACING_S = 1.0
_MAX_SAMPLES = 4096

# states a rule can be in; "alert" is the only one surfaced as firing
STATES = ("healthy", "burning", "alert", "recovery")


class RatioRule:
    """Burn-rate SLO over a cumulative (bad, total) pair."""

    kind = "ratio"

    def __init__(self, name: str, objective: float,
                 counts_fn: Callable[[MetricsRegistry],
                                     Tuple[float, float]],
                 detail_fn: Optional[
                     Callable[[MetricsRegistry], Dict]] = None,
                 description: str = ""):
        self.name = name
        self.objective = float(objective)
        self.counts_fn = counts_fn
        self.detail_fn = detail_fn
        self.description = description

    def counts(self, reg: MetricsRegistry) -> Tuple[float, float]:
        return self.counts_fn(reg)

    def detail(self, reg: MetricsRegistry) -> Dict:
        if self.detail_fn is None:
            return {}
        try:
            return self.detail_fn(reg)
        except Exception as e:   # noqa: BLE001 - detail is best-effort
            return {"detail_error": str(e)}


class GaugeRule:
    """Instant-predicate SLO (no windows): healthy <-> alert."""

    kind = "gauge"
    objective = None

    def __init__(self, name: str,
                 check_fn: Callable[[MetricsRegistry],
                                    Tuple[bool, Dict]],
                 description: str = ""):
        self.name = name
        self.check_fn = check_fn
        self.description = description

    def check(self, reg: MetricsRegistry) -> Tuple[bool, Dict]:
        return self.check_fn(reg)


# ------------------------------------------------------- default rules


def _predict_latency_threshold() -> float:
    try:
        return float(os.environ.get("H2O3TPU_SLO_PREDICT_P99_S", "0.5"))
    except ValueError:
        return 0.5


def _under_threshold(h: Histogram, thr: float) -> Tuple[int, int]:
    """(observations <= thr, total observations) for one histogram —
    the histogram-bucket latency SLI (observations past the last bound
    only appear in the total, i.e. count as bad)."""
    counts, total = h.counts_snapshot()
    cut = bisect.bisect_right(h.bounds, thr)
    return sum(counts[:cut]), total


def _predict_latency_counts(reg: MetricsRegistry) -> Tuple[float, float]:
    thr = _predict_latency_threshold()
    good = total = 0
    for h in reg.find("predict_seconds"):
        if isinstance(h, Histogram):
            g, t = _under_threshold(h, thr)
            good += g
            total += t
    return float(total - good), float(total)


def _predict_latency_detail(reg: MetricsRegistry) -> Dict:
    hists = [h for h in reg.find("predict_seconds")
             if isinstance(h, Histogram)]
    try:
        p99 = merged_quantile(hists, 0.99)
    except ValueError as e:      # mismatched grids: report, don't 500
        return {"threshold_seconds": _predict_latency_threshold(),
                "p99_seconds": None, "detail_error": str(e)}
    return {"threshold_seconds": _predict_latency_threshold(),
            "p99_seconds": p99}


def _rest_availability_counts(reg: MetricsRegistry) -> Tuple[float, float]:
    bad = total = 0.0
    for h in reg.find("rest_request_seconds"):
        if isinstance(h, Histogram):
            total += h.count
            if str(h.labels.get("status", "")).startswith("5"):
                bad += h.count
    # a rejected request never reached a handler: it is its own trial
    for c in reg.find("rest_rejected_total"):
        if isinstance(c, Counter):
            total += c.value
            bad += c.value
    return bad, total


def _heartbeat_counts(reg: MetricsRegistry) -> Tuple[float, float]:
    """Each agreement round is one trial; a round any peer missed is a
    bad trial (an approximation — misses are per peer — but the burn
    math only needs a rate that rises with degradation)."""
    bad = sum(c.value for c in reg.find("heartbeat_misses_total")
              if isinstance(c, Counter))
    total = sum(c.value for c in reg.find("heartbeat_rounds_total")
                if isinstance(c, Counter))
    return float(bad), float(max(total, bad))


def _fleet_routing_counts(reg: MetricsRegistry) -> Tuple[float, float]:
    """Each routed prediction is one trial; each failover hop is a bad
    trial (serving/fleet.py). Failovers CAN outnumber routes when every
    hop in a hedge chain fails, so clamp total like heartbeat does."""
    bad = sum(c.value for c in reg.find("predict_failovers_total")
              if isinstance(c, Counter))
    total = sum(c.value for c in reg.find("predict_routed_total")
                if isinstance(c, Counter))
    return float(bad), float(max(total, bad))


def _fleet_replicas_check(reg: MetricsRegistry) -> Tuple[bool, Dict]:
    """Every model the fleet registry tracks keeps at least one healthy
    replica; a model at zero is one heartbeat window from 503s."""
    vals = {str(g.labels.get("model", "?")): g.value
            for g in reg.find("fleet_replicas_healthy")}
    if not vals:
        return True, {"models": 0, "min_replicas": None}
    worst = min(vals, key=vals.get)
    return vals[worst] >= 1.0, {"models": len(vals),
                                "min_replicas": vals[worst],
                                "worst_model": worst}


def _data_durability_check(reg: MetricsRegistry) -> Tuple[bool, Dict]:
    """Every durability-registered frame keeps at least one live
    replica: ``frames_under_replicated`` counts frames whose home peer
    is heartbeat-dead and which no survivor has rebuilt yet
    (core/durability.py). Non-zero means the rebuild supervisor is
    behind — or the data is one more failure from gone."""
    under = max((g.value for g in reg.find("frames_under_replicated")),
                default=0.0)
    return under == 0.0, {"under_replicated": int(under)}


def _mfu_floor() -> float:
    try:
        return float(os.environ.get("H2O3TPU_SLO_MFU_FLOOR", "0"))
    except ValueError:
        return 0.0


def _mfu_check(reg: MetricsRegistry) -> Tuple[bool, Dict]:
    floor = _mfu_floor()
    vals = {str(g.labels.get("algo", "?")): g.value
            for g in reg.find("model_fit_mfu")}
    if floor <= 0.0 or not vals:
        return True, {"floor": floor,
                      "min_mfu": min(vals.values()) if vals else None}
    worst = min(vals, key=vals.get)
    return vals[worst] >= floor, {"floor": floor,
                                  "min_mfu": vals[worst],
                                  "worst_algo": worst}


def _step_regression_bound() -> float:
    try:
        return float(os.environ.get("H2O3TPU_SLO_STEP_REGRESSION",
                                    "1.25"))
    except ValueError:
        return 1.25


def _step_regression_check(reg: MetricsRegistry) -> Tuple[bool, Dict]:
    """Every ``fit_step_baseline_ratio{algo}`` gauge (current mean step
    time / stored best, telemetry/perfbase.py) stays under the bound —
    a ratio at 1.25 means this fit's step-time distribution degraded
    ≥25% against its persisted baseline."""
    bound = _step_regression_bound()
    vals = {str(g.labels.get("algo", "?")): g.value
            for g in reg.find("fit_step_baseline_ratio")}
    if bound <= 0.0 or not vals:
        return True, {"bound": bound,
                      "max_ratio": max(vals.values()) if vals else None}
    worst = max(vals, key=vals.get)
    return vals[worst] < bound, {"bound": bound,
                                 "max_ratio": vals[worst],
                                 "worst_algo": worst}


def default_rules() -> List[object]:
    return [
        RatioRule(
            "predict_p99_latency", objective=0.99,
            counts_fn=_predict_latency_counts,
            detail_fn=_predict_latency_detail,
            description="99% of predict phases complete within "
                        "H2O3TPU_SLO_PREDICT_P99_S (default 0.5s), "
                        "measured from predict_seconds"),
        RatioRule(
            "rest_availability", objective=0.999,
            counts_fn=_rest_availability_counts,
            description="99.9% of REST requests neither 5xx nor "
                        "rejected (rest_request_seconds{status} + "
                        "rest_rejected_total)"),
        RatioRule(
            "heartbeat_health", objective=0.9,
            counts_fn=_heartbeat_counts,
            description="90% of heartbeat agreement rounds miss-free "
                        "(heartbeat_misses_total / "
                        "heartbeat_rounds_total)"),
        GaugeRule(
            "fit_mfu_floor", check_fn=_mfu_check,
            description="every model_fit_mfu{algo} gauge stays above "
                        "H2O3TPU_SLO_MFU_FLOOR (0 disables)"),
        RatioRule(
            "fleet_routing_availability", objective=0.99,
            counts_fn=_fleet_routing_counts,
            description="99% of fleet-routed predictions land without "
                        "a failover hop (predict_failovers_total / "
                        "predict_routed_total)"),
        GaugeRule(
            "fleet_replica_floor", check_fn=_fleet_replicas_check,
            description="every fleet-registered model keeps at least "
                        "one healthy replica (fleet_replicas_healthy)"),
        GaugeRule(
            "data_durability_floor", check_fn=_data_durability_check,
            description="every durability-registered frame keeps at "
                        "least one live replica "
                        "(frames_under_replicated stays 0)"),
        GaugeRule(
            "fit_step_regression", check_fn=_step_regression_check,
            description="no fit's step time degrades past "
                        "H2O3TPU_SLO_STEP_REGRESSION (default 1.25 = "
                        "+25%) vs its stored perf baseline "
                        "(fit_step_baseline_ratio, telemetry/"
                        "perfbase.py)"),
    ]


# ------------------------------------------------------------- engine


class SLOEngine:
    def __init__(self, registry: MetricsRegistry = REGISTRY,
                 rules: Optional[List[object]] = None,
                 now: Callable[[], float] = time.monotonic,
                 burn_threshold: float = 1.0):
        self.registry = registry
        self.rules = list(rules) if rules is not None else default_rules()
        self._now = now
        self.burn_threshold = float(burn_threshold)
        self._samples: deque = deque(maxlen=_MAX_SAMPLES)
        self._state: Dict[str, str] = {r.name: "healthy"
                                       for r in self.rules}
        self._since: Dict[str, float] = {}
        self._lock = threading.Lock()

    # -- burn math -----------------------------------------------------
    def _baseline(self, now: float, window: float):
        """Newest sample at or before the window start (oldest sample
        when the history is younger than the window)."""
        base = None
        for ts, counts in self._samples:
            if ts <= now - window:
                base = (ts, counts)
            else:
                break
        if base is None and self._samples:
            base = self._samples[0]
        return base

    def _burn(self, rule, cur: Tuple[float, float], now: float,
              window: float) -> float:
        base = self._baseline(now, window)
        if base is None:
            return 0.0
        b0, t0 = base[1].get(rule.name, (0.0, 0.0))
        dbad, dtotal = cur[0] - b0, cur[1] - t0
        if dtotal <= 0:
            return 0.0
        err = min(max(dbad / dtotal, 0.0), 1.0)
        return err / max(1.0 - rule.objective, 1e-9)

    # -- state machine -------------------------------------------------
    def _step(self, name: str, short_over: bool, long_over: bool) -> str:
        s = self._state[name]
        if s == "healthy":
            if short_over and long_over:
                return "alert"
            if short_over:
                return "burning"
        elif s == "burning":
            if short_over and long_over:
                return "alert"
            if not short_over:
                return "healthy"
        elif s == "alert":
            if not short_over:
                return "healthy" if not long_over else "recovery"
        elif s == "recovery":
            if short_over:
                return "alert"
            if not long_over:
                return "healthy"
        return s

    def _transition(self, name: str, new: str, now: float) -> None:
        old = self._state[name]
        if new == old:
            return
        self._state[name] = new
        if new == "alert":
            self._since[name] = now
        elif new in ("healthy", "burning"):
            self._since.pop(name, None)
        self.registry.counter("slo_alert_transitions_total",
                              slo=name, to=new).inc()
        try:
            from h2o3_tpu.utils.timeline import record as _tl
            _tl("slo", f"{name}: {old} -> {new}", slo=name, state=new)
        except Exception:   # noqa: BLE001 - recording is best-effort
            pass

    # -- evaluation ----------------------------------------------------
    def evaluate(self) -> Dict:
        with self._lock:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> Dict:
        now = self._now()
        reg = self.registry
        rules_out: List[Dict] = []
        for r in self.rules:
            if r.kind == "ratio":
                cur = r.counts(reg)
                bs = self._burn(r, cur, now, SHORT_WINDOW_S)
                bl = self._burn(r, cur, now, LONG_WINDOW_S)
                self._transition(
                    r.name,
                    self._step(r.name, bs > self.burn_threshold,
                               bl > self.burn_threshold), now)
                reg.gauge("slo_burn_rate", slo=r.name,
                          window="5m").set(bs)
                reg.gauge("slo_burn_rate", slo=r.name,
                          window="1h").set(bl)
                entry = {"slo": r.name, "kind": r.kind,
                         "state": self._state[r.name],
                         "objective": r.objective,
                         "burn_5m": round(bs, 4), "burn_1h": round(bl, 4),
                         "bad": cur[0], "total": cur[1],
                         "description": r.description}
                entry.update(r.detail(reg))
            else:
                try:
                    ok, detail = r.check(reg)
                except Exception as e:   # noqa: BLE001 - never 500
                    ok, detail = True, {"check_error": str(e)}
                self._transition(r.name,
                                 "healthy" if ok else "alert", now)
                entry = {"slo": r.name, "kind": r.kind,
                         "state": self._state[r.name],
                         "description": r.description}
                entry.update(detail)
            reg.gauge("slo_alert_active", slo=r.name).set(
                1.0 if self._state[r.name] == "alert" else 0.0)
            if r.name in self._since:
                entry["since"] = round(self._since[r.name], 3)
            rules_out.append(entry)
        # sample AFTER computing burns: the current instant must not be
        # its own baseline
        if (not self._samples
                or now - self._samples[-1][0] >= _MIN_SAMPLE_SPACING_S):
            self._samples.append(
                (now, {r.name: r.counts(reg) for r in self.rules
                       if r.kind == "ratio"}))
        alerts = [e for e in rules_out
                  if e["state"] in ("alert", "recovery")]
        return {"now": round(now, 3),
                "burn_threshold": self.burn_threshold,
                "windows_s": [SHORT_WINDOW_S, LONG_WINDOW_S],
                "alerts": alerts, "rules": rules_out}

    def states(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._state)

    def active_alerts(self) -> List[Dict]:
        """Alerting/recovering rules WITHOUT re-evaluating — the
        side-effect-free snapshot flight-recorder capsules stamp at
        job end."""
        with self._lock:
            return [{"slo": n, "state": s,
                     "since": round(self._since[n], 3)
                     if n in self._since else None}
                    for n, s in self._state.items()
                    if s in ("alert", "recovery")]


# ------------------------------------------------- process-wide engine

_ENGINE: Optional[SLOEngine] = None
_ENGINE_LOCK = threading.Lock()


def engine() -> SLOEngine:
    global _ENGINE
    if _ENGINE is None:
        with _ENGINE_LOCK:
            if _ENGINE is None:
                _ENGINE = SLOEngine()
    return _ENGINE


def evaluate() -> Dict:
    """Evaluate the process-wide engine (the /3/Alerts + /3/Metrics
    refresh path)."""
    return engine().evaluate()


def active_alerts() -> List[Dict]:
    """No-side-effect alert snapshot; [] before the first evaluate."""
    if _ENGINE is None:
        return []
    return _ENGINE.active_alerts()
