"""Roofline accounting — per-fit FLOP/byte totals against device peaks.

The north star ("as fast as the hardware allows") and ROADMAP item 5
(DL at 0.14% MFU) need a measuring stick: raw rows/sec says nothing
about how far a fit sits from the chip. This module sizes every model
fit against the accelerator roofline the way DrJAX (arxiv 2403.07128)
sizes its MapReduce primitives against peak and the Julia-to-TPU
pipeline (arxiv 1810.09868) reports utilization per compiled program:

- :func:`device_peaks` detects peak FLOP/s and HBM bandwidth per
  backend (device_kind table for TPU generations, conservative
  estimates for cpu/gpu, ``H2O3TPU_PEAK_FLOPS`` /
  ``H2O3TPU_PEAK_HBM_GBPS`` overrides);
- per-fit work has two legs: **analytic** — closed-form per-algo
  estimates (GBM histogram matmuls, GLM IRLS Gram builds, DL dense
  fwd+bwd) — always drive the fit-level totals, and **cost_analysis**
  — ``Compiled.cost_analysis()`` taken off a re-lowering of the
  observed jit entry point's cached abstract call signature
  (telemetry/compile_observer.py ``aot_source``) — grounds them:
  XLA's numbers are per-device and count scan/while bodies ONCE, so
  they validate the analytic model per program unit (one histogram
  build, one DL step — tier-1 asserts 2x agreement) and ride fit
  records as diagnostics rather than being multiplied by guessed trip
  counts;
- :func:`record_model_fit` (hooked into the ``<algo>.fit`` span,
  models/model.py) emits ``model_fit_mfu{algo}`` and
  ``model_fit_hbm_util{algo}`` gauges, annotates the fit span (so the
  numbers land in flight-recorder capsules), and returns the record
  bench.py re-emits per config.

Mode knob ``H2O3TPU_ROOFLINE`` / ``Config.roofline``: ``auto``
(default) attaches cost_analysis diagnostics on TPU backends — where
re-lowering hits the persistent XLA cache and fits are large — and
skips them elsewhere; ``cost`` / ``analytic`` force; ``off`` disables
recording. MFU and HBM-utilization values are FRACTIONS (0..1) of the
AGGREGATE mesh peak (per-device peak x device count), not percent.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from h2o3_tpu.telemetry.registry import REGISTRY, counter, gauge
from h2o3_tpu.telemetry import spans as spans_mod

# ------------------------------------------------------------- peaks

# device_kind substring (lowercase) → (peak FLOP/s dense bf16/fp32 mix,
# HBM bytes/s). Public TPU spec numbers; matched longest-first.
_TPU_PEAKS: List[Tuple[str, float, float]] = [
    ("v6e", 918e12, 1640e9),       # Trillium
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),    # "TPU v5 lite" device_kind spelling
    ("v5litepod", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
]
# conservative single-socket estimates where the backend publishes no
# spec: utilization numbers stay comparable run-to-run, not absolute
_CPU_PEAK = (1.0e11, 2.0e10)       # ~100 GFLOP/s, ~20 GB/s
_GPU_PEAK = (1.0e13, 1.0e12)       # generic accelerator fallback

_peaks_lock = threading.Lock()
_peaks_cache: Optional[Dict] = None


def peaks_for(device_kind: str, platform: str = "") -> Dict:
    """Pure table lookup (no jax import) — also the bench stub path."""
    kind = (device_kind or "").lower()
    plat = (platform or "").lower()
    for sub, flops, bw in _TPU_PEAKS:
        if sub in kind:
            return {"flops": flops, "hbm_bytes_per_s": bw,
                    "device_kind": device_kind,
                    "source": f"tpu-spec:{sub}"}
    if "tpu" in kind or plat == "tpu":
        flops, bw = _TPU_PEAKS[0][1], _TPU_PEAKS[0][2]
        return {"flops": flops, "hbm_bytes_per_s": bw,
                "device_kind": device_kind, "source": "tpu-unknown"}
    if plat in ("gpu", "cuda", "rocm") or "gpu" in kind:
        return {"flops": _GPU_PEAK[0], "hbm_bytes_per_s": _GPU_PEAK[1],
                "device_kind": device_kind, "source": "gpu-estimate"}
    return {"flops": _CPU_PEAK[0], "hbm_bytes_per_s": _CPU_PEAK[1],
            "device_kind": device_kind or "cpu", "source": "cpu-estimate"}


def device_peaks(refresh: bool = False) -> Dict:
    """Detected PER-DEVICE peaks for the active backend plus the device
    count (fit totals are whole-mesh, so utilization divides by the
    aggregate), with ``H2O3TPU_PEAK_FLOPS`` / ``H2O3TPU_PEAK_HBM_GBPS``
    env overrides on top. Cached (the backend does not change
    mid-process)."""
    global _peaks_cache
    with _peaks_lock:
        if _peaks_cache is not None and not refresh:
            return dict(_peaks_cache)
    kind, plat, ndev = "", "", 1
    try:
        import jax
        d = jax.devices()[0]
        kind = getattr(d, "device_kind", "") or ""
        plat = getattr(d, "platform", "") or ""
        ndev = max(jax.device_count(), 1)
    except Exception:   # noqa: BLE001 - peaks must never break a fit
        pass
    p = peaks_for(kind, plat)
    p["devices"] = ndev
    env_f = os.environ.get("H2O3TPU_PEAK_FLOPS")
    env_b = os.environ.get("H2O3TPU_PEAK_HBM_GBPS")
    try:
        if env_f:
            p["flops"] = float(env_f)
            p["source"] = "env-override"
        if env_b:
            p["hbm_bytes_per_s"] = float(env_b) * 1e9
            p["source"] = "env-override"
    except ValueError:
        pass
    with _peaks_lock:
        _peaks_cache = dict(p)
    return p


# -------------------------------------------------------------- mode


def mode() -> str:
    """off | analytic | cost | auto — env wins over config (the
    watchdog/gate knob pattern)."""
    m = os.environ.get("H2O3TPU_ROOFLINE")
    if not m:
        try:
            from h2o3_tpu.core import config as _cfg
            m = _cfg.ARGS.roofline
        except Exception:   # noqa: BLE001 - config not importable yet
            m = "auto"
    m = (m or "auto").lower()
    return m if m in ("off", "analytic", "cost", "auto") else "auto"


def _use_cost() -> bool:
    m = mode()
    if m == "cost":
        return True
    if m == "auto":
        try:
            import jax
            return jax.default_backend() == "tpu"
        except Exception:   # noqa: BLE001
            return False
    return False


# -------------------------------------------- analytic fit estimates

# algo → family of analytic estimator + the observed jit entry points
# whose calls carry the fit's device work (compile_observer names)
_TREE_KERNELS = ("gbm.boost_scan", "gbm.boost_scan_scored",
                 "gbm.boost_scan_multi", "gbm.boost_scan_batched")
ALGO_KERNELS: Dict[str, Tuple[str, ...]] = {
    "gbm": _TREE_KERNELS, "drf": _TREE_KERNELS, "xgboost": _TREE_KERNELS,
    "glm": ("glm.irls_solve", "glm.irls_solve_batched"),
    "deeplearning": ("dl.train_chunk",),
}


def analytic_tree_cost(rows: int, features: int, trees: int, depth: int,
                       bins: int) -> Dict:
    """Histogram-build matmuls — the tree FLOPs that touch the MXU: per
    row per tree, levels 0..depth-1 contract [3·2^l, C] x [C, F·B]
    (ops/histogram.py _block_hist; same count bench.py's historical
    mfu_pct used). Bytes: each level re-streams the int8 binned matrix,
    the 3-stat payload, and the node-id vector."""
    flops = 2.0 * 3.0 * (2 ** depth - 1) * features * bins * rows * trees
    bytes_ = float(rows) * trees * depth * (features + 3 * 4 + 4)
    return {"flops": flops, "bytes": bytes_,
            "detail": {"rows": rows, "features": features, "trees": trees,
                       "depth": depth, "bins": bins}}


def analytic_glm_cost(rows: int, coefs: int, iterations: int,
                      solver: str = "irlsm") -> Dict:
    """IRLS is Gram-dominated (2·n·p² per iteration, ops/gram.py);
    L-BFGS/COD are matvec passes (~4·n·p). Bytes: the design matrix
    streams once per iteration (f32)."""
    s = (solver or "irlsm").lower()
    per_row = 2.0 * coefs * coefs if s in ("irlsm", "auto") else 4.0 * coefs
    return {"flops": per_row * rows * max(iterations, 1),
            "bytes": 4.0 * rows * coefs * max(iterations, 1),
            "detail": {"rows": rows, "coefs": coefs,
                       "iterations": iterations, "solver": s}}


def analytic_dl_cost(samples: float, layer_sizes) -> Dict:
    """Dense MLP fwd+bwd: 6 FLOPs per weight per sample (2 fwd + 4 bwd).
    Bytes: activations in/out per layer plus one weight read+write per
    sample-equivalent (optimizer state churn folded into the x3)."""
    sizes = [int(s) for s in layer_sizes]
    params = sum(a * b + b for a, b in zip(sizes[:-1], sizes[1:]))
    act = sum(sizes)
    return {"flops": 6.0 * params * max(samples, 1.0),
            "bytes": 4.0 * max(samples, 1.0) * (act + 3.0 * params /
                                                max(samples, 1.0)),
            "detail": {"samples": samples, "params": params,
                       "layers": sizes}}


def _nbins() -> int:
    try:
        from h2o3_tpu.core import config as _cfg
        return int(_cfg.ARGS.nbins) + 1      # +1: the NA bin
    except Exception:   # noqa: BLE001
        return 65


def analytic_fit_cost(algo: str, params: Dict, model, frame,
                      x) -> Optional[Dict]:
    """Closed-form fit-work estimate from the builder's own knobs — the
    always-available fallback when no cost_analysis source exists."""
    rows = int(getattr(frame, "nrows", 0) or 0)
    feats = max(len(x or []), 1)
    if rows <= 0:
        return None
    if algo in ("gbm", "drf", "xgboost"):
        out = getattr(model, "output", {}) or {}
        hist = out.get("scoring_history") or []
        trees = int(params.get("ntrees") or 50)
        if hist:
            try:
                trees = max(int(h.get("ntrees", 0)) for h in hist) or trees
            except Exception:   # noqa: BLE001
                pass
        depth = int(params.get("max_depth") or 6)
        return analytic_tree_cost(rows, feats, trees, depth, _nbins())
    if algo == "glm":
        out = getattr(model, "output", {}) or {}
        coefs = len(out.get("coef_names") or []) + 1 or feats + 1
        iters = int(params.get("max_iterations") or 50)
        return analytic_glm_cost(rows, coefs, iters,
                                 str(params.get("solver") or "irlsm"))
    if algo == "deeplearning":
        out = getattr(model, "output", {}) or {}
        hidden = [int(h) for h in (params.get("hidden") or [200, 200])]
        nclasses = len(out.get("domain") or []) or 1
        sizes = [feats] + hidden + [max(nclasses, 1)]
        samples = float(params.get("epochs") or 10.0) * rows
        return analytic_dl_cost(samples, sizes)
    return None


# --------------------------------------- cost_analysis (AOT replay)

_cost_cache: Dict[str, Optional[Dict]] = {}
_cost_lock = threading.Lock()


def kernel_cost(name: str, refresh: bool = False) -> Optional[Dict]:
    """``Compiled.cost_analysis()`` totals (flops, bytes accessed) for
    the observed jit entry point ``name``, replayed from the compile
    observer's cached abstract signature. The re-lowering compiles once
    per (name, newest shape bucket) and is cached here; on backends
    with the persistent XLA cache armed (core/cloud.py init) the XLA
    leg is a disk hit. Returns None when the entry point never compiled
    in this process or the backend reports no costs.

    Semantics — these are XLA's numbers, read them as such: costs are
    PER-DEVICE (a shard_map'd program reports one shard's work) and
    ``scan``/``while`` BODIES COUNT ONCE regardless of trip count. A
    loop-free program unit (one histogram build, one DL train step)
    therefore compares directly against its analytic estimate divided
    by the device count — tier-1 asserts 2x agreement on exactly those
    units — while scan-heavy fit programs (the 25-tree boost scan) are
    structurally undercounted, which is why fit-level MFU totals come
    from the analytic path (record_model_fit)."""
    from h2o3_tpu.telemetry import compile_observer
    src = compile_observer.aot_source(name)
    if src is None:
        return None
    key = name
    with _cost_lock:
        if not refresh and key in _cost_cache:
            c = _cost_cache[key]
            return dict(c) if c else None
    result: Optional[Dict] = None
    try:
        jit_fn, aargs, akwargs = src
        compiled = jit_fn.lower(*aargs, **akwargs).compile()
        ca = compiled.cost_analysis()
        entries = ca if isinstance(ca, (list, tuple)) else [ca]
        flops = sum(float(e.get("flops", 0.0) or 0.0)
                    for e in entries if isinstance(e, dict))
        bytes_ = sum(float(e.get("bytes accessed", 0.0) or 0.0)
                     for e in entries if isinstance(e, dict))
        if flops > 0 or bytes_ > 0:
            result = {"flops": flops, "bytes": bytes_, "kernel": name}
    except Exception:   # noqa: BLE001 - accounting must never break a fit
        result = None
    with _cost_lock:
        _cost_cache[key] = result
    return dict(result) if result else None


def _kernel_calls(algo: str) -> float:
    """Total calls of the algo's observed entry points so far (cache
    hits + misses). Deltas of this across a fit give the call count the
    cost_analysis totals scale by."""
    names = ALGO_KERNELS.get(algo, ())
    total = 0.0
    snap = REGISTRY.snapshot()["counters"]
    for c in snap:
        if c["name"] in ("h2o3tpu_jit_cache_hit_total",
                         "h2o3tpu_jit_cache_miss_total") and \
                c["labels"].get("fn") in names:
            total += c["value"]
    return total


def fit_probe(algo: str) -> Dict:
    """Snapshot taken at fit START (models/model.py) so record_model_fit
    can attribute kernel calls to this fit alone."""
    return {"algo": algo, "kernel_calls": _kernel_calls(algo)}


# ------------------------------------------------------------ record


def record_model_fit(builder, model, frame, x, seconds: float,
                     probe: Optional[Dict] = None) -> Optional[Dict]:
    """Compute this fit's FLOP/byte totals, emit the
    ``model_fit_mfu{algo}`` / ``model_fit_hbm_util{algo}`` gauges,
    annotate the active (fit) span so the numbers ride the flight
    recorder capsule, and return the record. Never raises."""
    try:
        if mode() == "off" or seconds <= 0:
            return None
        algo = getattr(builder, "algo", "?")
        est = analytic_fit_cost(algo, getattr(builder, "params", {}) or {},
                                model, frame, x)
        if est is None:
            return None
        flops, bytes_, source = est["flops"], est["bytes"], "analytic"
        # cost_analysis diagnostics ride along where the mode wants them
        # (per-device, loop-bodies-once — see kernel_cost); the fit
        # TOTAL stays analytic so scan trip counts are never faked
        kc = None
        calls = 0.0
        if probe is not None:
            calls = _kernel_calls(algo) - probe.get("kernel_calls", 0.0)
        if _use_cost():
            for name in ALGO_KERNELS.get(algo, ()):
                kc = kernel_cost(name)
                if kc is not None:
                    break
        peaks = device_peaks()
        agg_flops = peaks["flops"] * peaks.get("devices", 1)
        agg_bw = peaks["hbm_bytes_per_s"] * peaks.get("devices", 1)
        mfu = flops / (seconds * agg_flops) if agg_flops else 0.0
        hbm = bytes_ / (seconds * agg_bw) if agg_bw else 0.0
        rec = {"algo": algo, "seconds": round(seconds, 4),
               "flops": flops, "bytes": bytes_,
               "mfu": mfu, "hbm_util": hbm, "source": source,
               "kernel_calls": calls, "kernel_cost": kc,
               "peak_flops": peaks["flops"],
               "peak_hbm_bytes_per_s": peaks["hbm_bytes_per_s"],
               "devices": peaks.get("devices", 1),
               "device_kind": peaks["device_kind"]}
        gauge("model_fit_mfu", algo=algo).set(mfu)
        gauge("model_fit_hbm_util", algo=algo).set(hbm)
        counter("roofline_fits_total", algo=algo, source=source).inc()
        roofline_meta = {"flops": flops, "bytes": bytes_,
                         "source": source, "seconds": round(seconds, 4)}
        if kc is not None:
            roofline_meta["kernel_cost"] = kc
        # unrounded: a toy fit's MFU on a big mesh is legitimately tiny
        # and must survive into the capsule as nonzero
        spans_mod.annotate(mfu=mfu, hbm_util=hbm,
                           roofline=roofline_meta)
        # per-fit record on the MODEL: model_fit_mfu{algo} is a
        # latest-wins gauge, so concurrent fits of the same algo
        # (scheduler-spread grids) overwrite each other there — the
        # per-fit truth lives here and in the capsule, the gauge stays
        # "most recent fit" by contract (README §Observability)
        try:
            model.output["roofline"] = dict(rec)
        except Exception:   # noqa: BLE001 - accounting must never fail
            pass
        return rec
    except Exception:   # noqa: BLE001 - accounting must never fail a fit
        return None


def last_fit(algo: str) -> Dict:
    """Most recent fit's utilization gauges (bench.py per-config
    fields): {"mfu": fraction, "hbm_util": fraction}."""
    return {"mfu": float(REGISTRY.value("model_fit_mfu", algo=algo)),
            "hbm_util": float(REGISTRY.value("model_fit_hbm_util",
                                             algo=algo))}
