"""Runtime telemetry — metrics registry + hierarchical spans + compile
observer, exposed via ``GET /3/Metrics`` (api/server.py).

The reference ships observability as a design constraint (TimeLine,
WaterMeter, Profiler — PAPER.md §Timeline/Logs); this package is the
TPU runtime's equivalent for its OWN failure modes: XLA compile storms,
shape-bucket misses, and device-memory pressure. Always on, cheap
(registry op ≈ 1µs; see test_telemetry.py overhead bound).

Request hardening (api/server.py + core/request_ctx.py) reports
through the same registry: ``rest_inflight_requests`` (gauge),
``rest_rejected_total{reason=}``, ``request_deadline_exceeded_total``,
``rest_client_disconnects_total``; the RED duration legs are
``rest_request_seconds{route,status}`` and ``rest_queue_wait_seconds``.

Post-hoc, per-job debuggability rides the same instrumentation:
``flight_recorder`` captures each Job's span subtree, timeline events,
compiles, and log records into a bounded DKV capsule
(``<job_key>_telemetry``), and ``trace_export`` renders capsules or
the whole process ring as Perfetto-loadable Chrome trace JSON
(``GET /3/Jobs/{id}/trace``, ``GET /3/Trace``).

Surface (stable metric names — README §Observability):

    from h2o3_tpu import telemetry
    telemetry.counter("frame_reduce_total").inc()
    with telemetry.span("gbm.fit", trees=100):
        ...
    telemetry.snapshot() / telemetry.to_prometheus()
"""

from h2o3_tpu.telemetry.registry import (BYTES_BUCKETS, REGISTRY,
                                         SECONDS_BUCKETS, counter, gauge,
                                         histogram)
from h2o3_tpu.telemetry import flight_recorder
from h2o3_tpu.telemetry.spans import (add_collective_bytes, annotate,
                                      current_span, current_span_id, span)
from h2o3_tpu.telemetry.spans import snapshot as spans_snapshot
from h2o3_tpu.telemetry.spans import aggregate as spans_aggregate
from h2o3_tpu.telemetry.compile_observer import (compiles_snapshot, install,
                                                 observed_jit)
from h2o3_tpu.telemetry import trace_export
from h2o3_tpu.telemetry import trace_context
from h2o3_tpu.telemetry import slo
from h2o3_tpu.telemetry import cluster
from h2o3_tpu.telemetry import roofline
from h2o3_tpu.telemetry import stepprof
from h2o3_tpu.telemetry import perfbase

snapshot = REGISTRY.snapshot
to_prometheus = REGISTRY.to_prometheus

# the compile listener is process-wide and costs nothing when idle;
# importing telemetry anywhere arms it (core/job.py imports this, so
# every entry path — REST, python API, bench — is covered)
install()

__all__ = [
    "BYTES_BUCKETS", "SECONDS_BUCKETS", "REGISTRY",
    "counter", "gauge", "histogram",
    "span", "annotate", "current_span", "current_span_id",
    "add_collective_bytes", "spans_snapshot", "spans_aggregate",
    "install", "observed_jit", "snapshot", "to_prometheus",
    "compiles_snapshot", "flight_recorder", "trace_export",
    "trace_context", "slo", "cluster", "roofline", "stepprof",
    "perfbase",
]
