"""Chrome trace-event export — spans/timeline/compiles → Perfetto.

Converts telemetry into the Trace Event Format JSON that Chrome's
``about:tracing`` and https://ui.perfetto.dev load directly (the same
consumer-side workflow the reference gets from its Flow timeline, and
the dispatch/compile visibility DrJAX leans on):

- every finished span becomes a complete (``ph: "X"``) event; spans of
  one root tree share a ``tid`` so parent/child nesting renders as the
  usual flame stack (a child is temporally contained in its parent on
  the same track, and ``args.span_id``/``args.parent_id`` keep the
  exact tree recoverable);
- timeline moments (utils/timeline.py) become instant (``ph: "i"``)
  events, placed on their span's track when they carry a ``span_id``;
- XLA compiles get a dedicated track (``tid`` :data:`COMPILE_TID`) so
  a compile storm is visible as a solid bar even when it happens under
  many different spans.

Two entry points: :func:`capsule_trace` renders one job's flight
recorder capsule (``GET /3/Jobs/{key}/trace``), :func:`process_trace`
renders the whole process ring (``GET /3/Trace``, bench artifacts).

Timestamps are microseconds since the unix epoch (Perfetto normalizes
to the earliest event); durations are microseconds.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional

# reserved synthetic tracks (real span tracks count up from 1)
COMPILE_TID = 9001
TIMELINE_TID = 9000
STEPPROF_TID = 9002


def _span_tids(spans: List[Dict]):
    """Track id per span: every span of one root tree shares a tid, so
    Perfetto renders the tree as one flame stack. Spans whose parent
    fell off the ring start a tree of their own. Returns
    ``(span_id→tid, tid→root-name-label)``."""
    by_id = {s["id"]: s for s in spans}

    def root_of(s: Dict) -> Dict:
        seen = set()
        while s["parent_id"] in by_id and s["id"] not in seen:
            seen.add(s["id"])
            s = by_id[s["parent_id"]]
        return s

    tids: Dict[str, int] = {}
    root_tid: Dict[str, int] = {}
    labels: Dict[int, str] = {}
    # roots numbered by first-seen start time → stable track order
    for s in sorted(spans, key=lambda s: s["start_ms"]):
        r = root_of(s)
        if r["id"] not in root_tid:
            root_tid[r["id"]] = len(root_tid) + 1
            labels[root_tid[r["id"]]] = f"spans:{r['name']}"
        tids[s["id"]] = root_tid[r["id"]]
    return tids, labels


def _span_event(s: Dict, pid: int, tid: int) -> Dict:
    args = {"span_id": s["id"], "parent_id": s["parent_id"],
            "device_peak_bytes": s.get("device_peak_bytes", 0),
            "collective_bytes": s.get("collective_bytes", 0)}
    args.update(s.get("meta") or {})
    return {"name": s["name"], "cat": "span", "ph": "X",
            "ts": int(s["start_ms"] * 1000),
            "dur": max(int(round(s["duration_ms"] * 1000)), 1),
            "pid": pid, "tid": tid, "args": args}


def _instant_event(e: Dict, pid: int, tid: int) -> Dict:
    args = {k: v for k, v in e.items()
            if k not in ("kind", "what", "ts_ms", "seq")}
    return {"name": e.get("what", "?"), "cat": e.get("kind", "timeline"),
            "ph": "i", "ts": int(e.get("ts_ms", 0) * 1000), "s": "t",
            "pid": pid, "tid": tid, "args": args}


def _compile_event(c: Dict, pid: int) -> Dict:
    dur_us = max(int(round(c.get("dur_s", 0.0) * 1e6)), 1)
    return {"name": c.get("event", "xla_compile"), "cat": "compile",
            "ph": "X", "ts": int(c.get("ts_ms", 0) * 1000) - dur_us,
            "dur": dur_us, "pid": pid, "tid": COMPILE_TID,
            "args": {"seconds": c.get("dur_s", 0.0)}}


def _stepprof_events(profiles: Iterable[Dict], pid: int) -> List[Dict]:
    """Per-fit phase breakdown (telemetry/stepprof.py finish records in
    the capsule) → one stacked bar per fit on a dedicated track: the
    phases are a PARTITION of the fit's wall clock, so laying them
    end-to-end from the fit's start renders "where the time went" as
    contiguous phase-annotated segments."""
    out: List[Dict] = []
    for f in profiles:
        t = float(f.get("ts", 0.0)) * 1e6          # epoch seconds → us
        label = f.get("model_key") or f.get("algo", "fit")
        for ph, secs in (f.get("phases") or {}).items():
            dur_us = int(round(float(secs) * 1e6))
            if dur_us <= 0:
                continue
            out.append({"name": f"{label}:{ph}", "cat": "stepprof",
                        "ph": "X", "ts": int(t), "dur": max(dur_us, 1),
                        "pid": pid, "tid": STEPPROF_TID,
                        "args": {"phase": ph, "algo": f.get("algo"),
                                 "seconds": round(float(secs), 6),
                                 "chunks": f.get("chunks", 0),
                                 "collective_share":
                                     f.get("collective_share", 0.0),
                                 "mfu": f.get("mfu")}})
            t += dur_us
    return out


def _meta_event(pid: int, tid: Optional[int], name: str, label: str) -> Dict:
    return {"name": name, "cat": "__metadata", "ph": "M", "ts": 0,
            "pid": pid, "tid": tid if tid is not None else 0,
            "args": {"name": label}}


def _events_for(spans: Iterable[Dict], events: Iterable[Dict],
                compiles: Iterable[Dict], pid: int,
                process_name: str,
                step_profiles: Iterable[Dict] = ()) -> List[Dict]:
    """All trace events of ONE process/track-group (shared by the
    single-process build_trace and the multi-node cluster_trace)."""
    spans = list(spans)
    tids, tid_labels = _span_tids(spans)
    out: List[Dict] = [_meta_event(pid, None, "process_name", process_name)]
    for t in sorted(tid_labels):
        out.append(_meta_event(pid, t, "thread_name", tid_labels[t]))
    out.append(_meta_event(pid, TIMELINE_TID, "thread_name", "timeline"))
    out.append(_meta_event(pid, COMPILE_TID, "thread_name", "xla-compile"))
    for s in spans:
        out.append(_span_event(s, pid, tids[s["id"]]))
    for e in events:
        tid = tids.get(e.get("span_id"), TIMELINE_TID)
        out.append(_instant_event(e, pid, tid))
    for c in compiles:
        out.append(_compile_event(c, pid))
    sp = _stepprof_events(step_profiles, pid)
    if sp:
        out.append(_meta_event(pid, STEPPROF_TID, "thread_name",
                               "step-profile"))
        out.extend(sp)
    return out


def build_trace(spans: Iterable[Dict], events: Iterable[Dict] = (),
                compiles: Iterable[Dict] = (),
                process_name: str = "h2o3-tpu",
                extra: Optional[Dict] = None,
                step_profiles: Iterable[Dict] = ()) -> Dict:
    """Assemble Chrome trace JSON from already-snapshotted telemetry."""
    out = _events_for(spans, events, compiles, os.getpid(), process_name,
                      step_profiles=step_profiles)
    trace = {"traceEvents": out, "displayTimeUnit": "ms",
             "otherData": {"source": "h2o3_tpu.telemetry.trace_export"}}
    if extra:
        trace["otherData"].update(extra)
    return trace


def cluster_trace(nodes: Dict[int, Dict],
                  extra: Optional[Dict] = None) -> Dict:
    """Fuse per-peer ring tails into ONE Chrome trace: each node's
    events carry ``pid`` = its process_index, so Perfetto renders one
    track group per host (the telemetry/cluster.py ``?cluster=1``
    payload). ``nodes[n]`` = {"spans", "events", "compiles", "label"}."""
    out: List[Dict] = []
    for n in sorted(nodes):
        d = nodes[n]
        out.extend(_events_for(d.get("spans", ()), d.get("events", ()),
                               d.get("compiles", ()), int(n),
                               d.get("label", f"h2o3-tpu node {n}")))
    trace = {"traceEvents": out, "displayTimeUnit": "ms",
             "otherData": {"source": "h2o3_tpu.telemetry.trace_export"}}
    if extra:
        trace["otherData"].update(extra)
    return trace


def stitched_trace(trace_id: str, nodes: Dict[int, Dict],
                   extra: Optional[Dict] = None) -> Dict:
    """ONE request's causal trace across hosts (``GET
    /3/Trace?trace_id=``): each node's span ring is filtered to
    ``trace_id`` and the survivors merge into a SINGLE track group
    (``pid`` 1 — the trace is the unit, not the host), with tids
    assigned per causal tree ACROSS processes: a remote root whose
    ``parent_id`` names a span on another host (the traceparent
    propagated through a scheduler lease or job hop) joins that span's
    flame stack instead of starting a pid-grouped track of its own.

    Span ids are per-process counters, so merged ids are node-qualified
    (``n<node>:sp-…``); a ``parent_id`` is resolved to the node that
    owns it — same node first, else the unique other owner (the
    cross-process link), else left dangling as its own root. Each
    span's originating ``node`` rides its args."""
    node_spans: Dict[int, List[Dict]] = {}
    node_events: Dict[int, List[Dict]] = {}
    for n in nodes:
        d = nodes[n]
        node_spans[int(n)] = [s for s in d.get("spans", ())
                              if s.get("trace_id") == trace_id]
        node_events[int(n)] = list(d.get("events", ()))
    node_ids = {n: {s["id"] for s in ss} for n, ss in node_spans.items()}

    def qual(n: int, sid: Optional[str]) -> Optional[str]:
        if sid is None:
            return None
        if sid in node_ids[n]:
            return f"n{n}:{sid}"
        owners = [m for m, ids in node_ids.items() if sid in ids]
        if len(owners) == 1:
            return f"n{owners[0]}:{sid}"
        return sid      # unknown (off-ring) or ambiguous → dangles
    spans: List[Dict] = []
    for n, ss in sorted(node_spans.items()):
        for s in ss:
            s2 = dict(s)
            s2["id"] = f"n{n}:{s['id']}"
            s2["parent_id"] = qual(n, s.get("parent_id"))
            s2["meta"] = {**(s.get("meta") or {}), "node": n}
            spans.append(s2)
    tids, tid_labels = _span_tids(spans)
    pid = 1
    out: List[Dict] = [_meta_event(pid, None, "process_name",
                                   f"h2o3-tpu trace {trace_id}")]
    for t in sorted(tid_labels):
        out.append(_meta_event(pid, t, "thread_name", tid_labels[t]))
    for s in spans:
        out.append(_span_event(s, pid, tids[s["id"]]))
    # timeline instants only when they attribute to a span OF THIS
    # trace (events carry no trace id of their own)
    for n, evs in sorted(node_events.items()):
        for e in evs:
            tid = tids.get(qual(n, e.get("span_id")))
            if tid is not None:
                out.append(_instant_event({**e, "node": n}, pid, tid))
    trace = {"traceEvents": out, "displayTimeUnit": "ms",
             "otherData": {"source": "h2o3_tpu.telemetry.trace_export",
                           "trace_id": trace_id,
                           "span_count": len(spans),
                           "nodes": sorted(n for n, ss
                                           in node_spans.items() if ss)}}
    if extra:
        trace["otherData"].update(extra)
    return trace


def capsule_trace(capsule) -> Dict:
    """One job's flight-recorder capsule → Chrome trace JSON."""
    d = capsule.to_dict()
    return build_trace(
        d["spans"], d["events"], d["compiles"],
        process_name=f"h2o3-tpu job {d['job_key']}",
        extra={"job_key": d["job_key"], "description": d["description"],
               "status": d["status"], "metric_deltas": d["metric_deltas"],
               "dropped": d["dropped"]},
        step_profiles=d.get("step_profiles") or ())


def process_trace(last_spans: int = 2048, last_events: int = 2048,
                  last_compiles: int = 512) -> Dict:
    """The whole process ring (spans + timeline + compiles) → Chrome
    trace JSON; the ``GET /3/Trace`` and bench-artifact payload."""
    from h2o3_tpu.telemetry import compile_observer, spans as spans_mod
    from h2o3_tpu.utils import timeline
    return build_trace(
        spans_mod.snapshot(last_spans),
        timeline.snapshot(last_events),
        compile_observer.compiles_snapshot(last_compiles))


def write_trace(path: str, trace: Dict) -> str:
    """Write a trace JSON artifact (bench.py per-config capture)."""
    import json
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
