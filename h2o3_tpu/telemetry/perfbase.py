"""Perf-regression baselines — persisted step-time/MFU floors per fit
shape, and the gauge the ``fit_step_regression`` SLO rule watches.

BENCH_r01–r05 exist but nothing ever compared them; this module is the
in-process half of that guard (scripts/benchdiff.py is the offline
half). Every profiled fit (telemetry/stepprof.py finish) records its
mean step time under a baseline key

    (algo, shape-bucket, device_kind, pallas-mode)

— the same axes that change a compiled program's identity, so a
baseline never compares a 4K-row CPU fit against a 50M-row TPU one.
Baselines persist as one JSON file per key under
``<ice_root>/perf_baselines/`` (atomic tmp+rename, the recovery.py
snapshot idiom): ``best`` is the lowest mean step seconds ever seen,
``history`` a bounded tail of recent runs with their phase splits.

Each record sets ``fit_step_baseline_ratio{algo}`` = current/best;
the default SLO rule ``fit_step_regression`` (telemetry/slo.py) alerts
when any ratio reaches ``H2O3TPU_SLO_STEP_REGRESSION`` (default 1.25 —
a fit's step-time distribution degraded ≥25% vs its stored baseline).
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Dict, List, Optional

from h2o3_tpu.telemetry.registry import gauge

HISTORY_KEEP = 16


def baseline_dir() -> str:
    env = os.environ.get("H2O3TPU_PERF_BASELINE_DIR")
    if env:
        return env
    try:
        from h2o3_tpu.core.config import ARGS
        root = ARGS.ice_root
    except Exception:   # noqa: BLE001 - config not importable yet
        root = "/tmp/h2o3_tpu"
    return os.path.join(root, "perf_baselines")


def shape_bucket(nrows: int) -> str:
    """Power-of-two row bucket — the same coarse shape identity
    parallel/mesh.py padded_rows buckets compilation on."""
    n = max(int(nrows), 1)
    return f"r{1 << (n - 1).bit_length()}"


def _device_kind() -> str:
    try:
        from h2o3_tpu.telemetry import roofline
        return str(roofline.device_peaks().get("device_kind", "unknown"))
    except Exception:   # noqa: BLE001 - backend-free processes
        return "unknown"


def _pallas_mode() -> str:
    try:
        from h2o3_tpu.ops import pallas as pallas_policy
        return str(pallas_policy.knob_value())
    except Exception:   # noqa: BLE001
        return "auto"


def baseline_key(algo: str, nrows: int,
                 device_kind: Optional[str] = None,
                 pallas_mode: Optional[str] = None) -> str:
    raw = "_".join([str(algo), shape_bucket(nrows),
                    device_kind or _device_kind(),
                    pallas_mode or _pallas_mode()])
    return re.sub(r"[^A-Za-z0-9_.-]", "-", raw)


def _path(key: str) -> str:
    return os.path.join(baseline_dir(), key + ".json")


def load(key: str) -> Optional[Dict]:
    try:
        with open(_path(key)) as f:
            return json.load(f)
    except Exception:   # noqa: BLE001 - missing/corrupt = no baseline
        return None


def _store(key: str, doc: Dict) -> None:
    os.makedirs(baseline_dir(), exist_ok=True)
    tmp = _path(key) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, _path(key))


def record_fit(algo: str, nrows: int, profile: Dict,
               mfu: Optional[float] = None) -> Optional[float]:
    """Fold one completed fit profile (stepprof.finish) into its
    baseline; returns the step-time ratio vs the stored best (None when
    the fit has no chunks to average). Never raises."""
    try:
        chunks = int(profile.get("chunks") or 0)
        seconds = float(profile.get("seconds") or 0.0)
        if chunks <= 0 or seconds <= 0:
            return None
        step_s = seconds / chunks
        if not math.isfinite(step_s) or step_s <= 0:
            return None
        key = baseline_key(algo, nrows)
        doc = load(key) or {"key": key, "algo": algo,
                            "shape_bucket": shape_bucket(nrows),
                            "device_kind": _device_kind(),
                            "pallas": _pallas_mode(),
                            "unit": "seconds",
                            "best_step_seconds": step_s,
                            "history": []}
        best = float(doc.get("best_step_seconds") or step_s)
        ratio = step_s / max(best, 1e-12)
        entry = {"ts": time.time(), "step_seconds": round(step_s, 6),
                 "chunks": chunks,
                 "phases": dict(profile.get("phases") or {})}
        if mfu is not None:
            entry["mfu"] = float(mfu)
        doc["history"] = (doc.get("history") or [])[-(HISTORY_KEEP - 1):] \
            + [entry]
        doc["best_step_seconds"] = min(best, step_s)
        doc["last_step_seconds"] = round(step_s, 6)
        if mfu is not None:
            doc["best_mfu"] = max(float(doc.get("best_mfu") or 0.0),
                                  float(mfu))
        _store(key, doc)
        gauge("fit_step_baseline_ratio", algo=algo).set(ratio)
        return ratio
    except Exception:   # noqa: BLE001 - the guard must never fail a fit
        return None


def snapshot_metrics() -> List[Dict]:
    """Every stored baseline as a benchdiff-comparable metric line
    (``{"metric", "value", "unit", "phases"}``) — so
    ``scripts/benchdiff.py`` diffs a baseline dir against a BENCH_*.json
    or another baseline snapshot with one code path."""
    out: List[Dict] = []
    d = baseline_dir()
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        doc = load(name[:-len(".json")])
        if not doc:
            continue
        hist = doc.get("history") or []
        out.append({"metric": doc.get("key", name[:-len(".json")]),
                    "value": float(doc.get("last_step_seconds")
                                   or doc.get("best_step_seconds") or 0),
                    "unit": "seconds",
                    "best": float(doc.get("best_step_seconds") or 0),
                    "phases": dict((hist[-1].get("phases") or {})
                                   if hist else {})})
    return out
