"""Cluster telemetry fan-in — cross-process metric/trace/log snapshots.

PR 7 made the pod cloud genuinely multi-process, but the PR 1/5
telemetry stack stayed process-local: ``GET /3/Metrics`` on the
coordinator only ever showed process 0, so a dead-slow peer was
invisible exactly where the reference's CloudHandler/WaterMeter
contract promises whole-cloud visibility. This module is the fan-in:

- every peer periodically publishes a compact snapshot — registry
  counters/gauges/histograms, recent span/timeline/compile ring tails,
  a structured log tail, inflight-job and HBM-peak summaries — to the
  coordination-service KV store (``h2o3tpu/telemetry/<process_index>``,
  zlib+base64). Publishing piggybacks on the heartbeat beat cadence
  (core/heartbeat.py ``_kv_round``): same out-of-band-by-design rule —
  NEVER a device collective, which could deadlock training collectives
  across processes;
- the coordinator's REST tier merges them on demand (``?cluster=1`` on
  ``/3/Metrics`` / ``/3/Trace`` / ``/3/Logs``, api/server.py): counters
  summed across nodes, gauges/histograms per-node with a
  ``node=<process_index>`` label, traces fused into ONE Chrome trace
  with ``pid`` = process_index (one Perfetto track group per host),
  logs merged timestamp-ordered;
- degradation contract: a peer that misses its publish window serves
  its LAST snapshot, labeled in ``stale_nodes`` — never a block, never
  a 500. With ``process_count() == 1`` the ``?cluster=1`` views are
  exactly the local views (api/server.py short-circuits before calling
  in here).

Knobs: ``H2O3TPU_CLUSTER_METRICS`` (auto|on|off),
``H2O3TPU_CLUSTER_METRICS_INTERVAL_S`` (publish cadence, default 5),
``H2O3TPU_CLUSTER_METRICS_STALE_S`` (staleness threshold, default 15)
— env over core/config.py, the watchdog/gate pattern.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from h2o3_tpu.telemetry.registry import (BYTES_BUCKETS, REGISTRY, counter,
                                         gauge, histogram)

KV_PREFIX = "h2o3tpu/telemetry/"

# ring-tail caps per snapshot: the KV value must stay a bounded control
# -plane payload (compressed, typically a few KB), not a trace dump
MAX_SPANS = 192
MAX_EVENTS = 192
MAX_COMPILES = 96
MAX_LOGS = 160

_lock = threading.Lock()
_last_publish = 0.0
_seq = 0


# ------------------------------------------------------------- knobs


def _knob(env: str, attr: str, default):
    v = os.environ.get(env)
    if v is not None:
        try:
            return type(default)(v) if not isinstance(default, str) else v
        except ValueError:
            pass
    try:
        from h2o3_tpu.core import config as _cfg
        return getattr(_cfg.ARGS, attr)
    except Exception:   # noqa: BLE001 - config not importable yet
        return default


def enabled_mode() -> str:
    m = str(_knob("H2O3TPU_CLUSTER_METRICS", "cluster_metrics",
                  "auto")).lower()
    return m if m in ("auto", "on", "off") else "auto"


def interval_s() -> float:
    return float(_knob("H2O3TPU_CLUSTER_METRICS_INTERVAL_S",
                       "cluster_metrics_interval_s", 5.0))


def stale_s() -> float:
    return float(_knob("H2O3TPU_CLUSTER_METRICS_STALE_S",
                       "cluster_metrics_stale_s", 15.0))


# ----------------------------------------------------------- process


def _client():
    from jax._src import distributed
    return distributed.global_state.client


def _identity() -> Tuple[int, int]:
    """(process_index, process_count) WITHOUT re-entering backend init:
    the heartbeat monitor captured them at start; fall back to jax only
    when the monitor never ran (REST thread — backend already up)."""
    from h2o3_tpu.core import heartbeat
    mon = heartbeat.monitor
    if mon.peers:
        return mon._pid, max(mon._nproc, len(mon.peers))
    try:
        import jax
        return jax.process_index(), jax.process_count()
    except Exception:   # noqa: BLE001
        return 0, 1


# ----------------------------------------------------------- publish


def local_snapshot() -> Dict:
    """This process's publishable snapshot — also what the coordinator
    uses for ITSELF when merging (always live, never stale)."""
    from h2o3_tpu.telemetry import compile_observer
    from h2o3_tpu.telemetry import spans as spans_mod
    from h2o3_tpu.utils import log as log_mod
    from h2o3_tpu.utils import timeline
    node, _ = _identity()
    peak_hbm = 0
    try:
        import jax
        st = jax.local_devices()[0].memory_stats() or {}
        peak_hbm = int(st.get("peak_bytes_in_use", 0) or 0)
    except Exception:   # noqa: BLE001 - stats are best-effort
        pass
    devices = []
    try:
        import jax
        devices = [str(d) for d in jax.local_devices()]
    except Exception:   # noqa: BLE001
        pass
    return {
        "node": node,
        "ts": time.time(),
        "seq": _seq,
        "host": os.uname().nodename,
        "pid": os.getpid(),
        "devices": devices,
        "metrics": REGISTRY.snapshot(),
        "spans": spans_mod.snapshot(MAX_SPANS),
        "events": timeline.snapshot(MAX_EVENTS),
        "compiles": compile_observer.compiles_snapshot(MAX_COMPILES),
        "logs": log_mod.log_records(MAX_LOGS),
        "jobs_inflight": int(REGISTRY.value("jobs_inflight")),
        "peak_hbm": peak_hbm,
        "hbm": _hbm_snapshot(),
        "jobs": _jobs_snapshot(),
        "sched": _sched_snapshot(),
        "alerts": _alerts_snapshot(),
        "serving": _serving_snapshot(),
        "stepprof": _stepprof_snapshot(),
    }


MAX_JOBS = 64


def _jobs_snapshot() -> List[Dict]:
    """This node's job list (JobV3 dicts), newest first, bounded — the
    GET /3/Jobs?cluster=1 merge input."""
    try:
        from h2o3_tpu.core.job import list_jobs
        jobs = list_jobs()
        jobs.sort(key=lambda j: j.get("start_time", 0), reverse=True)
        return jobs[:MAX_JOBS]
    except Exception:   # noqa: BLE001 - snapshot is best-effort
        return []


def _stepprof_snapshot() -> Dict:
    """This node's training-step profiles (telemetry/stepprof.py):
    recent per-fit phase ledgers + inflight marks — the coordinator's
    input for pod skew/straggler verdicts (stepprof.cluster_profile)."""
    try:
        from h2o3_tpu.telemetry import stepprof
        return stepprof.snapshot()
    except Exception:   # noqa: BLE001 - snapshot is best-effort
        return {}


def _sched_snapshot() -> Dict:
    """This node's work-scheduler block (parallel/scheduler.py): leases
    held, items executed/reassigned — per-host queue-drain visibility."""
    try:
        from h2o3_tpu.parallel import scheduler
        return scheduler.snapshot()
    except Exception:   # noqa: BLE001 - snapshot is best-effort
        return {}


def _serving_snapshot() -> Dict:
    """This node's serving-tier load block — the fleet router's fan-in
    input (serving/fleet.py peer_loads): REST edge, predict queue depth
    and warm scorer set. Engine state is read via sys.modules so a node
    that never served stays jax-lazy."""
    import sys as _sys
    out: Dict = {"rest_port": None, "queue_depth": 0,
                 "rest_inflight": 0, "warm_models": []}
    try:
        out["rest_inflight"] = int(
            REGISTRY.value("rest_inflight_requests"))
    except Exception:   # noqa: BLE001 - gauge may not exist yet
        pass
    try:
        fleet = _sys.modules.get("h2o3_tpu.serving.fleet")
        if fleet is not None:
            ep = fleet.stats().get("endpoint")
            if ep:
                out["rest_port"] = int(ep["port"])
    except Exception:   # noqa: BLE001 - snapshot is best-effort
        pass
    try:
        eng_mod = _sys.modules.get("h2o3_tpu.serving.engine")
        if eng_mod is not None:
            out["queue_depth"] = int(eng_mod.engine.queue_depth())
            out["warm_models"] = list(eng_mod.engine.warm_models())
    except Exception:   # noqa: BLE001 - snapshot is best-effort
        pass
    return out


def _alerts_snapshot() -> Dict:
    """This node's SLO evaluation (telemetry/slo.py) — the
    GET /3/Alerts?cluster=1 merge input. Evaluating here keeps the
    published burn rates fresh at the publish cadence."""
    try:
        from h2o3_tpu.telemetry import slo
        out = slo.evaluate()
        # the fan-in only needs states + burns, not rule prose
        return {"alerts": out["alerts"], "rules": out["rules"]}
    except Exception:   # noqa: BLE001 - snapshot is best-effort
        return {}


def _hbm_snapshot() -> Dict:
    """This node's memory truth from the governor (core/memgov.py) —
    budget / in-use / bytes-on-ice, carried in the published snapshot
    so GET /3/Cloud reports real per-node free_mem/max_mem/swap_mem."""
    try:
        from h2o3_tpu.core.memgov import governor
        s = governor.snapshot()
        return {"budget": int(s["budget_bytes"]),
                "in_use": int(s["bytes_in_use"]),
                "free": int(s["free_bytes"]),
                "spilled": int(s["spilled_bytes"])}
    except Exception:   # noqa: BLE001 - stats are best-effort
        return {"budget": 0, "in_use": 0, "free": 0, "spilled": 0}


def _encode(snap: Dict) -> str:
    raw = json.dumps(snap, separators=(",", ":"), default=str).encode()
    return "z:" + base64.b64encode(zlib.compress(raw, 6)).decode("ascii")


def _decode(val: str) -> Optional[Dict]:
    try:
        if val.startswith("z:"):
            raw = zlib.decompress(base64.b64decode(val[2:]))
        else:
            raw = val.encode()
        return json.loads(raw)
    except Exception:   # noqa: BLE001 - a torn/garbled value is a miss
        return None


def publish(force: bool = False) -> bool:
    """Publish this process's snapshot to the coordination KV. Returns
    True on success; False when disabled, single-process, rate-limited,
    or the KV write failed (counted, never raised)."""
    global _last_publish, _seq
    if enabled_mode() == "off":
        return False
    node, nproc = _identity()
    if nproc <= 1 and enabled_mode() != "on":
        return False
    now = time.time()
    # snapshot AND KV write stay under the lock: concurrent publishers
    # (the heartbeat cadence racing a forced publish) must commit in
    # snapshot order, or a snapshot captured BEFORE a counter bump can
    # overwrite the forced post-bump publish and roll the cluster view
    # back behind live values until the next cadence tick
    with _lock:
        if not force and now - _last_publish < interval_s():
            return False
        _last_publish = now
        _seq += 1
        try:
            client = _client()
            if client is None:
                return False
            payload = _encode(local_snapshot())
            client.key_value_set(f"{KV_PREFIX}{node}", payload,
                                 allow_overwrite=True)
            counter("cluster_publish_total").inc()
            histogram("cluster_publish_bytes",
                      buckets=BYTES_BUCKETS).observe(len(payload))
            return True
        except Exception as e:   # noqa: BLE001 - publishing best-effort
            counter("cluster_publish_failures_total").inc()
            from h2o3_tpu.utils.log import get_logger
            get_logger("cluster").debug("snapshot publish failed: %s", e)
            return False


def maybe_publish() -> bool:
    """Rate-limited publish — the heartbeat piggyback entry point."""
    return publish(force=False)


def sweep_own_keys() -> None:
    """Delete this process's snapshot from the KV (cloud shutdown) so a
    reformed cloud never reads a previous incarnation's ghost data."""
    try:
        client = _client()
        if client is None:
            return
        node, _ = _identity()
        client.key_value_delete(f"{KV_PREFIX}{node}")
    except Exception:   # noqa: BLE001 - already gone / already down
        pass


# ----------------------------------------------------------- collect


def collect() -> Dict:
    """Read every peer's published snapshot. Returns
    ``{"nodes": {node: snapshot}, "ages": {node: seconds},
    "stale_nodes": [...], "process_count": N, "self": idx}``.
    The local node's entry is the LIVE snapshot (age 0). Peers past the
    staleness window — or that never published — land in stale_nodes;
    a KV read failure marks every peer stale rather than raising."""
    self_idx, nproc = _identity()
    now = time.time()
    nodes: Dict[int, Dict] = {self_idx: local_snapshot()}
    ages: Dict[int, float] = {self_idx: 0.0}
    stale: List[int] = []
    peer_ids = [p for p in range(nproc) if p != self_idx]
    if peer_ids:
        entries: Dict[int, Dict] = {}
        try:
            client = _client()
            if client is None:
                raise RuntimeError("no coordination-service client")
            for key, val in client.key_value_dir_get(KV_PREFIX):
                try:
                    n = int(key.rsplit("/", 1)[-1])
                except ValueError:
                    continue
                snap = _decode(val)
                if snap is not None:
                    entries[n] = snap
        except Exception:   # noqa: BLE001 - degrade to all-stale, no 500
            entries = {}
        # heartbeat's verdict folds in: a peer the monitor already
        # declared unhealthy is stale NOW, not after the window
        try:
            from h2o3_tpu.core import heartbeat
            hb_peers = heartbeat.monitor.peers
        except Exception:   # noqa: BLE001
            hb_peers = {}
        for p in peer_ids:
            snap = entries.get(p)
            if snap is None:
                stale.append(p)
                continue
            age = max(0.0, now - float(snap.get("ts", 0.0)))
            nodes[p] = snap
            ages[p] = age
            hb = hb_peers.get(p)
            if age > stale_s() or (hb is not None and not hb["healthy"]):
                stale.append(p)
    gauge("cluster_stale_nodes").set(len(stale))
    return {"nodes": nodes, "ages": ages, "stale_nodes": sorted(stale),
            "process_count": nproc, "self": self_idx}


def node_summaries(col: Optional[Dict] = None) -> Dict[int, Dict]:
    """Per-node operational summary for GET /3/Cloud: published
    identity, inflight jobs, last-publish age, peak HBM."""
    col = col or collect()
    out: Dict[int, Dict] = {}
    for n, snap in col["nodes"].items():
        out[int(n)] = {
            "node": int(n),
            "host": snap.get("host", ""),
            "pid": snap.get("pid", 0),
            "devices": snap.get("devices", []),
            "jobs_inflight": int(snap.get("jobs_inflight", 0) or 0),
            "last_publish_age_s": round(col["ages"].get(int(n), 0.0), 3),
            "peak_hbm": int(snap.get("peak_hbm", 0) or 0),
            "hbm": snap.get("hbm") or {},
            "sched": snap.get("sched") or {},
            "stale": int(n) in col["stale_nodes"],
        }
    return out


def device_owner_map(col: Optional[Dict] = None) -> Dict[str, int]:
    """str(device) → owning process_index, from published identity —
    replaces the default-0 ``process_index`` attribute guess on the
    /3/Cloud node blocks."""
    col = col or collect()
    out: Dict[str, int] = {}
    for n, snap in col["nodes"].items():
        for d in snap.get("devices", []) or []:
            out[str(d)] = int(n)
    return out


# ------------------------------------------------------------- merge


def _lkey(labels: Dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in (labels or {}).items()))


def merged_metrics(col: Optional[Dict] = None) -> Dict:
    """Fold per-node registry snapshots into the cluster view: counters
    SUMMED across nodes per (name, labels); gauges and histograms kept
    per-node with a ``node=<process_index>`` label (summing a gauge —
    or a histogram's bucket vector — across nodes would fabricate a
    distribution no single process observed)."""
    col = col or collect()
    csum: Dict[tuple, Dict] = {}
    gauges: List[Dict] = []
    hists: List[Dict] = []
    for n in sorted(col["nodes"]):
        m = col["nodes"][n].get("metrics") or {}
        for c in m.get("counters", []):
            key = (c["name"], _lkey(c.get("labels")))
            e = csum.get(key)
            if e is None:
                csum[key] = {"name": c["name"],
                             "labels": dict(c.get("labels") or {}),
                             "value": float(c.get("value", 0.0))}
            else:
                e["value"] += float(c.get("value", 0.0))
        for g in m.get("gauges", []):
            gauges.append({"name": g["name"],
                           "labels": {**(g.get("labels") or {}),
                                      "node": str(n)},
                           "value": g.get("value", 0.0)})
        for h in m.get("histograms", []):
            hists.append({"name": h["name"],
                          "labels": {**(h.get("labels") or {}),
                                     "node": str(n)},
                          "count": h.get("count", 0),
                          "sum": h.get("sum", 0.0),
                          "buckets": h.get("buckets", [])})
    counters = [csum[k] for k in sorted(csum)]
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def merged_prometheus(col: Optional[Dict] = None) -> str:
    """Cluster-merged Prometheus text exposition 0.0.4 — the same line
    grammar registry.to_prometheus emits, over merged_metrics()."""
    m = merged_metrics(col)

    def _esc(v) -> str:
        return str(v).replace("\\", r"\\").replace('"', r'\"') \
                     .replace("\n", r"\n")

    def _lbl(labels: Dict, extra: str = "") -> str:
        items = [f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())]
        if extra:
            items.append(extra)
        return "{" + ",".join(items) + "}" if items else ""

    by_name: Dict[str, List[Tuple[str, Dict]]] = {}
    for kind, entries in (("counter", m["counters"]),
                          ("gauge", m["gauges"]),
                          ("histogram", m["histograms"])):
        for e in entries:
            by_name.setdefault(e["name"], []).append((kind, e))
    lines: List[str] = []
    for name in sorted(by_name):
        kind = by_name[name][0][0]
        lines.append(f"# TYPE {name} {kind}")
        for _k, e in by_name[name]:
            if kind in ("counter", "gauge"):
                lines.append(f"{name}{_lbl(e['labels'])} {e['value']:g}")
            else:
                for bound, c in e.get("buckets", []):
                    le = 'le="%g"' % float(bound)
                    lines.append(f"{name}_bucket{_lbl(e['labels'], le)} "
                                 f"{int(c)}")
                inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{_lbl(e['labels'], inf)} "
                             f"{int(e['count'])}")
                lines.append(f"{name}_sum{_lbl(e['labels'])} "
                             f"{e['sum']:g}")
                lines.append(f"{name}_count{_lbl(e['labels'])} "
                             f"{int(e['count'])}")
    return "\n".join(lines) + "\n"


def merged_trace(col: Optional[Dict] = None) -> Dict:
    """Per-peer ring tails fused into ONE Chrome trace: ``pid`` =
    process_index, so Perfetto renders one track group per host
    (telemetry/trace_export.cluster_trace)."""
    from h2o3_tpu.telemetry import trace_export
    col = col or collect()
    nodes = {}
    for n in sorted(col["nodes"]):
        snap = col["nodes"][n]
        label = f"h2o3-tpu node {n}"
        host = snap.get("host")
        if host:
            label += f" ({host})"
        if int(n) in col["stale_nodes"]:
            label += " [stale]"
        nodes[int(n)] = {"spans": snap.get("spans", []),
                         "events": snap.get("events", []),
                         "compiles": snap.get("compiles", []),
                         "label": label}
    return trace_export.cluster_trace(
        nodes, extra={"cluster": True,
                      "process_count": col["process_count"],
                      "stale_nodes": col["stale_nodes"]})


def stitched_trace(trace_id: str, col: Optional[Dict] = None) -> Dict:
    """ONE request's causal trace across every host that published
    spans for it (``GET /3/Trace?trace_id=`` — trace_export
    .stitched_trace over the same fan-in snapshots merged_trace uses).
    On a single-process cloud this degrades to filtering the local
    ring."""
    from h2o3_tpu.telemetry import trace_export
    col = col or collect()
    nodes = {int(n): {"spans": snap.get("spans", []),
                      "events": snap.get("events", [])}
             for n, snap in col["nodes"].items()}
    return trace_export.stitched_trace(
        trace_id, nodes,
        extra={"process_count": col["process_count"],
               "stale_nodes": col["stale_nodes"]})


def merged_alerts(col: Optional[Dict] = None) -> Dict:
    """Cluster SLO view for GET /3/Alerts?cluster=1: every node's
    published evaluation, each alert/rule stamped with its ``node``.
    Objectives are evaluated per process (a burn on ANY host is a
    page), so entries merge side by side — never averaged."""
    col = col or collect()
    alerts: List[Dict] = []
    rules: List[Dict] = []
    for n in sorted(col["nodes"]):
        a = col["nodes"][n].get("alerts") or {}
        for e in a.get("alerts", []) or []:
            alerts.append({**e, "node": int(n)})
        for e in a.get("rules", []) or []:
            rules.append({**e, "node": int(n)})
    return {"alerts": alerts, "rules": rules,
            "stale_nodes": col["stale_nodes"],
            "process_count": col["process_count"]}


def merged_jobs(col: Optional[Dict] = None) -> Dict:
    """Cluster job view for GET /3/Jobs?cluster=1: every node's job
    list with a ``node`` id stamped on each entry, newest first. Job
    keys are process-local counters, so same-key entries on different
    nodes are different jobs (an SPMD driver job legitimately appears
    once per process — the per-host progress messages differ). Peers
    past the staleness window contribute their LAST list, labeled
    stale."""
    col = col or collect()
    jobs: List[Dict] = []
    for n in sorted(col["nodes"]):
        for j in col["nodes"][n].get("jobs", []) or []:
            jj = dict(j)
            jj["node"] = int(n)
            jobs.append(jj)
    jobs.sort(key=lambda j: j.get("start_time", 0), reverse=True)
    return {"jobs": jobs, "stale_nodes": col["stale_nodes"],
            "process_count": col["process_count"]}


def merged_logs(col: Optional[Dict] = None,
                level: Optional[str] = None,
                last: Optional[int] = None) -> Dict:
    """Merged, timestamp-ordered log tail with node ids."""
    col = col or collect()
    recs: List[Dict] = []
    for n in sorted(col["nodes"]):
        for r in col["nodes"][n].get("logs", []) or []:
            rr = dict(r)
            rr["node"] = int(rr.get("node", n))
            recs.append(rr)
    if level:
        lv = str(level).upper()
        recs = [r for r in recs if r.get("level") == lv]
    recs.sort(key=lambda r: (r.get("ts_ms", 0), r.get("node", 0)))
    if last is not None and last > 0:
        recs = recs[-last:]
    lines = [f"[node {r['node']}] {r.get('line', '')}" for r in recs]
    return {"records": recs, "lines": lines,
            "stale_nodes": col["stale_nodes"],
            "process_count": col["process_count"]}


def reset() -> None:
    """Tests only — clear the publish rate limiter."""
    global _last_publish, _seq
    with _lock:
        _last_publish = 0.0
        _seq = 0
