"""Request-scoped distributed trace context (W3C traceparent style).

The span tracer (spans.py) is process-local: it answers "where did the
time go" inside ONE process, but a request that fans out — REST handler
→ background job thread → scheduler leases on remote hosts → coalesced
predict dispatches — loses its identity at every hop. This module is
the identity that survives those hops: a (trace_id, parent span id,
sampled) triple carried in a contextvar BESIDE ``request_ctx``'s
deadline, following the exact same propagation discipline (captured at
ingress, re-installed across thread hops, serialized across process
boundaries).

Wire format: a ``traceparent`` header/string shaped like the W3C
recommendation, ``00-<32 hex trace id>-<parent id>-<2 hex flags>``.
The parent-id field is deliberately looser than W3C's 16-hex: spans.py
ids are ``sp-NNNNNNNN`` strings and the whole point of propagation is
that a remote child parents under the ORIGINATING span id, so the
parser accepts either form.

Propagation sites:
- REST ingress (api/server.py): incoming ``traceparent`` accepted (or
  a fresh context generated), echoed as ``X-H2O-Trace-Id`` on every
  response, installed around the handler.
- REST → job thread (core/job.py): the Job captures the context at
  ``__init__`` on the submitting thread and re-installs it in ``_run``
  on the worker thread, exactly like the request deadline.
- Scheduler leases (parallel/scheduler.py): the coordinator stamps its
  traceparent (parent = its ``sched.run`` span) into every
  ``ctl/assign/<pid>`` record so a remote host's ``sched.item`` spans
  parent under the coordinator's run.
- Serving batcher (serving/engine.py): each queued predict request
  carries its submitter's context so the coalesced dispatch can emit
  queue/device/scatter sub-spans under each member's OWN trace.

spans.py consumes the installed context: every span is stamped with
``trace_id``, and a ROOT span (no in-process parent) takes the
context's ``parent_id`` as its parent — that single rule is the
cross-process stitch ``GET /3/Trace?trace_id=`` renders.
"""

from __future__ import annotations

import contextvars
import re
import uuid
from contextlib import contextmanager
from typing import Optional

# trace id: 32 lowercase hex (uuid4().hex); parent: W3C 16-hex OR a
# spans.py "sp-NNNNNNNN" id OR the all-zero "none yet" placeholder
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-zA-Z._\-]{1,64})-"
    r"([0-9a-f]{2})$")
_NO_PARENT = "0" * 16


class TraceContext:
    __slots__ = ("trace_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, parent_id: Optional[str] = None,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.sampled = sampled

    def child(self, parent_id: Optional[str]) -> "TraceContext":
        """Same trace, re-parented — the hop primitive: capture the
        submitting side's active span id as the new parent."""
        return TraceContext(self.trace_id, parent_id, self.sampled)

    def to_traceparent(self,
                       parent_id: Optional[str] = None) -> str:
        pid = parent_id or self.parent_id or _NO_PARENT
        return f"00-{self.trace_id}-{pid}-" \
               f"{'01' if self.sampled else '00'}"

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "parent_id": self.parent_id,
                "sampled": self.sampled}

    def __repr__(self) -> str:   # pragma: no cover - debug aid
        return f"TraceContext({self.to_traceparent()})"


_CTX: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("h2o3tpu_trace", default=None)


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_context(sampled: bool = True) -> TraceContext:
    """Fresh root context — REST ingress with no ``traceparent``."""
    return TraceContext(new_trace_id(), None, sampled)


def current() -> Optional[TraceContext]:
    return _CTX.get()


def current_trace_id() -> Optional[str]:
    tc = _CTX.get()
    return tc.trace_id if tc is not None else None


@contextmanager
def trace_scope(ctx: Optional[TraceContext]):
    """Install ``ctx`` for the with-block (None uninstalls — a worker
    deliberately detaching from its submitter's trace)."""
    token = _CTX.set(ctx)
    try:
        yield ctx
    finally:
        _CTX.reset(token)


def install(ctx: Optional[TraceContext]):
    """Non-contextmanager install — returns the reset token. For hosts
    that manage several contextvars in one scope (request_ctx.job_scope
    carries job + deadline + trace across the worker-thread hop)."""
    return _CTX.set(ctx)


def uninstall(token) -> None:
    _CTX.reset(token)


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a traceparent string; malformed/absent → None (ingress
    then generates a fresh context — never a 4xx, tracing is telemetry
    not a contract)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    _version, trace_id, parent, flags = m.groups()
    if trace_id == "0" * 32:
        return None
    if parent == _NO_PARENT:
        parent = None
    try:
        sampled = bool(int(flags, 16) & 1)
    except ValueError:   # pragma: no cover - regex guarantees hex
        sampled = True
    return TraceContext(trace_id, parent, sampled)


def format_traceparent(ctx: Optional[TraceContext] = None,
                       parent_id: Optional[str] = None) -> Optional[str]:
    """Serialize the given (default: installed) context for a process
    hop, optionally re-parenting under ``parent_id`` (the sender's
    active span). None when no context is installed."""
    tc = ctx if ctx is not None else _CTX.get()
    if tc is None:
        return None
    return tc.to_traceparent(parent_id=parent_id)


def _reset() -> None:
    """Tests only — hard-clear the contextvar (conftest leak sweep)."""
    _CTX.set(None)
