"""Training-step profiler — per-chunk phase timing, pod skew, stragglers.

PR 19 shipped pod-global sharded training with an honest 0.54x 2-host
scaling number and nothing that says *why*: ``collective_bytes_total``
counts bytes but no instrument decomposes a training step into where
the wall clock went. This module is that instrument. Every fit carries
a bounded ring of per-chunk phase timings:

    host        python between dispatches — binning, stop checks,
                job.update, transfers, fault-injected delays
    compute     device dispatch → block_until_ready of the chunk's
                compiled scan/solve
    collective  timed psum / frame_reduce waits, plus the per-chunk
                barrier probe on a multi-process mesh (the wait a fast
                host spends on a straggler)
    checkpoint  in-fit snapshot writes (core/recovery.py)

The accounting is a PARTITION of the fit's wall clock: each charger
advances a single ``last_mark`` watermark, so phase sums never exceed
wall time and anything unattributed lands in ``host``.

Chunk loops weave three calls (models/gbm.py, glm.py, deeplearning.py):
``chunk_begin()`` (charges the inter-chunk host gap), ``compute_done()``
(blocks on the chunk outputs and charges compute), ``chunk_end()``
(barrier probe + ring record + ``model_fit_phase_seconds{algo,phase}``
observations on the shared SECONDS_BUCKETS grid, so cluster-merged
quantiles stay exact — telemetry/registry.merged_quantile).

Cross-host: ``snapshot()`` rides the PR 8 cluster fan-in
(telemetry/cluster.py local_snapshot "stepprof" block); the coordinator
calls ``cluster_profile(model_key)`` to merge per-host profiles of ONE
pod-global fit into skew/straggler verdicts — ``pod_step_skew_ratio``
and ``pod_straggler_host`` gauges plus per-host collective-wait shares.
Straggler identity needs no clock sync: a slow host shows up as large
SELF time (total − collective) on itself and as collective wait on
every fast host, because the barrier probe makes the wait observable.

Knobs: ``H2O3TPU_STEPPROF`` (auto|on|off; env over Config.stepprof),
``H2O3TPU_STEPPROF_RING`` (per-fit chunk-ring bound),
``H2O3TPU_STEPPROF_DELAY`` (test-only per-chunk sleep, charged to host
— the fault-injected "slow chunk"/straggler used by tier-1 and bench).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from h2o3_tpu.telemetry.registry import counter, gauge, histogram

PHASES = ("host", "compute", "collective", "checkpoint")

# completed profiles retained for GET /3/Models/{id}/profile
MAX_COMPLETED = 32
# completed fits published per cluster snapshot (newest first)
SNAPSHOT_FITS = 8
# ring entries shipped per published fit (full ring stays local)
SNAPSHOT_RING = 16


def _knob() -> str:
    env = os.environ.get("H2O3TPU_STEPPROF")
    if env:
        return str(env).lower()
    try:
        from h2o3_tpu.core.config import ARGS
        return str(getattr(ARGS, "stepprof", "auto") or "auto").lower()
    except Exception:   # noqa: BLE001 - config must never gate profiling
        return "auto"


def enabled() -> bool:
    """auto/on profile every fit; off disables the weave entirely."""
    return _knob() != "off"


def ring_size() -> int:
    env = os.environ.get("H2O3TPU_STEPPROF_RING")
    if env is not None:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    try:
        from h2o3_tpu.core.config import ARGS
        return max(1, int(getattr(ARGS, "stepprof_ring", 128)))
    except Exception:   # noqa: BLE001
        return 128


def _delay_s() -> float:
    try:
        return float(os.environ.get("H2O3TPU_STEPPROF_DELAY", "0") or 0)
    except ValueError:
        return 0.0


def _proc_index() -> int:
    try:
        from h2o3_tpu.telemetry.cluster import _identity
        return int(_identity()[0])
    except Exception:   # noqa: BLE001 - identity is best-effort
        return 0


class FitProfile:
    """One fit's phase ledger: bounded per-chunk ring + running totals.

    Single-writer by construction (the fit's worker thread); readers
    (cluster publish, REST) take shallow copies under the lock."""

    __slots__ = ("algo", "nrows", "proc", "t0_wall", "last_mark",
                 "totals", "marks", "ring", "chunks_total", "_cur",
                 "model_key", "seconds", "_token", "_lock")

    def __init__(self, algo: str, nrows: int = 0,
                 ring: Optional[int] = None):
        self.algo = algo
        self.nrows = int(nrows)
        self.proc = _proc_index()
        self.t0_wall = time.time()
        self.last_mark = time.perf_counter()
        self.totals = {p: 0.0 for p in PHASES}
        # wall-clock marks (NOT part of the phase partition): transfer
        # and fetch seconds/calls from the parallel/mesh.py weave
        self.marks: Dict[str, float] = {}
        self.ring: deque = deque(maxlen=ring or ring_size())
        self.chunks_total = 0
        self._cur: Optional[Dict] = None
        self.model_key: Optional[str] = None
        self.seconds = 0.0
        self._token = None
        self._lock = threading.Lock()

    def _charge(self, phase_name: str, dur: float) -> None:
        if dur <= 0.0:
            return
        with self._lock:
            self.totals[phase_name] = \
                self.totals.get(phase_name, 0.0) + dur
            if self._cur is not None:
                ph = self._cur["phases"]
                ph[phase_name] = ph.get(phase_name, 0.0) + dur

    def mark(self, name: str, dur: float) -> None:
        with self._lock:
            self.marks[name] = self.marks.get(name, 0.0) + dur

    def to_dict(self, ring_tail: Optional[int] = None) -> Dict:
        with self._lock:
            ring = list(self.ring)
        if ring_tail is not None:
            ring = ring[-ring_tail:]
        total = sum(self.totals.values())
        coll = self.totals.get("collective", 0.0)
        return {
            "algo": self.algo,
            "model_key": self.model_key,
            "proc": self.proc,
            "nrows": self.nrows,
            "ts": self.t0_wall,
            "seconds": round(self.seconds or total, 6),
            "chunks": self.chunks_total,
            "phases": {p: round(v, 6) for p, v in self.totals.items()},
            "marks": {k: round(v, 6) for k, v in self.marks.items()},
            "collective_share": round(coll / total, 6) if total > 0
            else 0.0,
            "ring": ring,
        }


# active profile on the fit's worker thread (models/model.py _run)
_PROFILE: contextvars.ContextVar[Optional[FitProfile]] = \
    contextvars.ContextVar("h2o3tpu_stepprof", default=None)

_reg_lock = threading.Lock()
# model_key -> completed profile dict, oldest first (REST lookups)
_completed: "OrderedDict[str, Dict]" = OrderedDict()
# live profiles visible to cross-thread readers (cluster publish)
_live: List[FitProfile] = []
# compiled barrier probes keyed by id(mesh)
_barriers: Dict[int, Any] = {}


def active() -> Optional[FitProfile]:
    return _PROFILE.get()


def reset() -> None:
    """Tests only — drop every registry, live profile, and this
    module's metric families (fits trained by OTHER test files in the
    same process would otherwise bleed into SLO-rule assertions)."""
    with _reg_lock:
        _completed.clear()
        del _live[:]
        _barriers.clear()
    try:
        from h2o3_tpu.telemetry.registry import REGISTRY
        for name in ("fit_step_baseline_ratio", "pod_step_skew_ratio",
                     "pod_straggler_host", "stepprof_fits_total",
                     "model_fit_phase_seconds"):
            REGISTRY.drop(name)
    except Exception:   # noqa: BLE001 - reset is best-effort
        pass


# ------------------------------------------------------------ lifecycle


def start(algo: str, nrows: int = 0) -> Optional[FitProfile]:
    """Attach a profile to the current context; None when disabled."""
    if not enabled():
        return None
    prof = FitProfile(algo, nrows=nrows)
    prof._token = _PROFILE.set(prof)
    with _reg_lock:
        _live.append(prof)
        while len(_live) > MAX_COMPLETED:
            _live.pop(0)
    return prof


def finish(prof: Optional[FitProfile], model_key: Optional[str] = None,
           seconds: Optional[float] = None,
           mfu: Optional[float] = None) -> Optional[Dict]:
    """Close the profile: flush the trailing host gap, register the
    completed record for REST/cluster readers, attach it to any active
    flight-recorder capsule, and feed the perf-regression baseline.
    Never raises — profiling must never fail a fit."""
    if prof is None:
        return None
    try:
        if prof._cur is not None:
            chunk_end()
        now = time.perf_counter()
        prof._charge("host", now - prof.last_mark)
        prof.last_mark = now
        prof.model_key = model_key
        prof.seconds = float(seconds) if seconds else \
            (time.time() - prof.t0_wall)
        # the caller's own wall measurement can bracket more tightly
        # than the charge watermark by sub-ms slack; published seconds
        # must cover the charged span or sum(phases) <= seconds breaks
        prof.seconds = max(prof.seconds, sum(prof.totals.values()))
        if prof._token is not None:
            try:
                _PROFILE.reset(prof._token)
            except ValueError:      # finished on a different context
                _PROFILE.set(None)
        d = prof.to_dict()
        if mfu is not None:
            d["mfu"] = float(mfu)
        with _reg_lock:
            if prof in _live:
                _live.remove(prof)
            if model_key:
                _completed[str(model_key)] = d
                while len(_completed) > MAX_COMPLETED:
                    _completed.popitem(last=False)
        counter("stepprof_fits_total", algo=prof.algo).inc()
        try:
            from h2o3_tpu.telemetry import flight_recorder
            flight_recorder.record_step_profile(
                {k: v for k, v in d.items() if k != "ring"})
        except Exception:   # noqa: BLE001 - capsule capture best-effort
            pass
        try:
            from h2o3_tpu.telemetry import perfbase
            perfbase.record_fit(prof.algo, prof.nrows, d, mfu=mfu)
        except Exception:   # noqa: BLE001 - guard must never fail a fit
            pass
        return d
    except Exception:   # noqa: BLE001 - profiling must never fail a fit
        return None


# ---------------------------------------------------------- chunk weave


def chunk_begin() -> None:
    """Open a chunk record; the host gap since the last charge (stop
    checks, job.update, binning between chunks) lands in THIS chunk."""
    prof = _PROFILE.get()
    if prof is None:
        return
    if prof._cur is not None:        # dangling (early-stop break)
        chunk_end()
    now = time.perf_counter()
    with prof._lock:
        prof._cur = {"phases": {p: 0.0 for p in PHASES}, "t0": now}
    prof._charge("host", now - prof.last_mark)
    prof.last_mark = now


def compute_done(out: Any = None) -> Any:
    """Block on the chunk's device outputs and charge the window since
    the last mark to ``compute``. With no active profile this is a
    no-op passthrough — dispatch overlap is untouched."""
    prof = _PROFILE.get()
    if prof is None:
        return out
    if out is not None:
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:   # noqa: BLE001 - host-only outputs are fine
            pass
    now = time.perf_counter()
    prof._charge("compute", now - prof.last_mark)
    prof.last_mark = now
    return out


def _mp_mesh():
    """The installed global mesh iff it spans >1 process (the only case
    the barrier probe can observe a straggler). jax-lazy via
    sys.modules so a backend-free process never triggers init."""
    m = sys.modules.get("h2o3_tpu.parallel.mesh")
    if m is None or getattr(m, "_GLOBAL_MESH", None) is None:
        return None
    try:
        mesh = m.get_mesh()     # honors local_mesh_scope overrides
        procs = {getattr(d, "process_index", 0)
                 for d in mesh.devices.flat}
        return mesh if len(procs) > 1 else None
    except Exception:   # noqa: BLE001 - probe is best-effort
        return None


def _barrier_probe(mesh) -> None:
    """Timed 1-element psum over the data axis: a fast host measures
    here the time it spends waiting for the slowest peer to reach the
    same chunk boundary. Compiled once per mesh."""
    import functools
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from h2o3_tpu.parallel import mesh as mesh_mod
    ent = _barriers.get(id(mesh))
    if ent is None:
        n = mesh.shape[mesh_mod.DATA_AXIS]

        @functools.partial(mesh_mod.shard_map, mesh=mesh,
                           in_specs=P(mesh_mod.DATA_AXIS), out_specs=P(),
                           check_vma=False)
        def _ps(x):
            return jax.lax.psum(x, mesh_mod.DATA_AXIS)

        arr = mesh_mod.put_sharded(np.ones((n,), np.float32),
                                   mesh_mod.row_sharding(mesh))
        ent = (jax.jit(_ps), arr)
        if len(_barriers) >= 4:      # stale-mesh backstop
            _barriers.clear()
        _barriers[id(mesh)] = ent
    fn, arr = ent
    jax.block_until_ready(fn(arr))


def chunk_end(**meta) -> None:
    """Close the chunk: test delay (host), barrier probe (collective),
    then record the ring entry and observe every phase into
    ``model_fit_phase_seconds{algo,phase}``."""
    prof = _PROFILE.get()
    if prof is None or prof._cur is None:
        return
    try:
        delay = _delay_s()
        if delay > 0:               # the fault-injected slow chunk
            time.sleep(delay)
        now = time.perf_counter()
        prof._charge("host", now - prof.last_mark)
        prof.last_mark = now
        mesh = _mp_mesh()
        if mesh is not None:
            try:
                _barrier_probe(mesh)
            except Exception:   # noqa: BLE001 - never fail the fit
                pass
            now = time.perf_counter()
            prof._charge("collective", now - prof.last_mark)
            prof.last_mark = now
    finally:
        with prof._lock:
            cur, prof._cur = prof._cur, None
        t_end = time.perf_counter()
        rec = {"dur": round(t_end - cur["t0"], 6),
               "phases": {p: round(v, 6)
                          for p, v in cur["phases"].items()}}
        rec.update(meta)
        with prof._lock:
            prof.ring.append(rec)
            prof.chunks_total += 1
        for p, v in cur["phases"].items():
            # one shared bucket grid (default SECONDS_BUCKETS) so
            # cluster-merged quantiles stay exact (merged_quantile)
            histogram("model_fit_phase_seconds", algo=prof.algo,
                      phase=p).observe(v)


@contextlib.contextmanager
def phase(name: str):
    """Charge a window to a named phase (e.g. ``checkpoint`` around
    core/recovery.py snapshot writes). The gap since the last mark
    stays host time, so the partition remains exact."""
    prof = _PROFILE.get()
    if prof is None:
        yield
        return
    t0 = time.perf_counter()
    prof._charge("host", t0 - prof.last_mark)
    prof.last_mark = t0
    try:
        yield
    finally:
        now = time.perf_counter()
        prof._charge(name, now - t0)
        prof.last_mark = now


def t_mark() -> Optional[float]:
    """Window-open timestamp for ``collective_done`` — None (free) when
    no profile is active."""
    return time.perf_counter() if _PROFILE.get() is not None else None


def collective_done(out: Any, t0: Optional[float]) -> None:
    """Charge a timed psum/frame_reduce window (parallel/map_reduce.py):
    blocks on the reduce output so the wait is observed, charges
    ``collective`` from ``t0``, host before it."""
    prof = _PROFILE.get()
    if prof is None or t0 is None:
        return
    try:
        import jax
        jax.block_until_ready(out)
    except Exception:   # noqa: BLE001
        pass
    now = time.perf_counter()
    prof._charge("host", t0 - prof.last_mark)
    prof._charge("collective", now - t0)
    prof.last_mark = now


def mark(name: str, dur: float) -> None:
    """Accumulate a wall-clock mark (transfer/fetch seconds from the
    parallel/mesh.py weave). NOT part of the phase partition — marks
    annotate where host time went, they don't re-charge it."""
    prof = _PROFILE.get()
    if prof is not None and dur > 0:
        prof.mark(name, dur)


# ----------------------------------------------------------- reads


def profile_for(model_key: str) -> Dict:
    """Completed profile for a model key; KeyError → REST 404."""
    with _reg_lock:
        d = _completed.get(str(model_key))
        if d is None:
            raise KeyError(f"no step profile for model {model_key!r}")
        return dict(d)


def last_fit_phases(algo: str) -> Dict:
    """Most recent completed fit's phase totals for an algo — the
    bench.py per-config phase-breakdown field."""
    with _reg_lock:
        for d in reversed(_completed.values()):
            if d.get("algo") == algo:
                return {"phases": dict(d.get("phases") or {}),
                        "collective_share": d.get("collective_share",
                                                  0.0),
                        "chunks": d.get("chunks", 0)}
    return {}


def snapshot() -> Dict:
    """This process's publishable block (cluster fan-in): bounded
    recent completed fits + inflight marks."""
    with _reg_lock:
        fits = [dict(d) for d in list(_completed.values())
                [-SNAPSHOT_FITS:]][::-1]
        live = list(_live)
    for f in fits:
        f["ring"] = (f.get("ring") or [])[-SNAPSHOT_RING:]
    inflight = []
    for prof in live:
        try:
            d = prof.to_dict(ring_tail=SNAPSHOT_RING)
            d["inflight"] = True
            inflight.append(d)
        except Exception:   # noqa: BLE001 - racing a finishing fit
            pass
    return {"proc": _proc_index(), "fits": fits, "inflight": inflight}


# ------------------------------------------------------- skew / cluster


def compute_skew(per_host: Dict[Any, Dict]) -> Dict:
    """Pure (jax-free) skew verdict over per-host profiles of ONE fit.

    SELF time = total − collective: a straggler does NOT wait, so its
    collective share stays low while every fast host's rises — the
    host with max self time IS the straggler, no clock sync needed."""
    hosts: Dict[str, Dict] = {}
    for node, f in (per_host or {}).items():
        ph = dict(f.get("phases") or {})
        total = sum(ph.values()) or float(f.get("seconds") or 0.0)
        coll = float(ph.get("collective", 0.0))
        self_t = max(total - coll, 0.0)
        key = str(node)
        hosts[key] = {
            "proc": int(f.get("proc", key if key.isdigit() else 0)),
            "total": round(total, 6),
            "collective": round(coll, 6),
            "self": round(self_t, 6),
            "collective_share": round(coll / total, 6)
            if total > 0 else 0.0,
            "phases": ph,
        }
    if not hosts:
        return {"hosts": {}, "skew_ratio": 0.0,
                "straggler": None, "straggler_proc": None}
    straggler = max(hosts, key=lambda n: hosts[n]["self"])
    selfs = [h["self"] for h in hosts.values()]
    ratio = min(max(selfs) / max(min(selfs), 1e-9), 1e6) \
        if max(selfs) > 0 else 1.0
    return {"hosts": hosts,
            "skew_ratio": round(ratio, 4),
            "straggler": straggler,
            "straggler_proc": hosts[straggler]["proc"]}


def cluster_profile(model_key: str) -> Dict:
    """Merge every host's profile of one pod-global fit (PR 8 fan-in)
    into the skew/straggler verdict, and publish it as the
    ``pod_step_skew_ratio`` / ``pod_straggler_host`` gauges."""
    from h2o3_tpu.telemetry import cluster
    with _reg_lock:
        local = _completed.get(str(model_key))
    algo = (local or {}).get("algo")
    snap = cluster.collect()
    per_host: Dict[str, Dict] = {}
    for node, s in (snap.get("nodes") or {}).items():
        blk = (s or {}).get("stepprof") or {}
        fits = blk.get("fits") or []
        match = next((f for f in fits
                      if f.get("model_key") == model_key), None)
        if match is None and algo:
            # pod-global fits generate per-process model keys; fall
            # back to the peer's most recent fit of the same algo
            match = next((f for f in fits if f.get("algo") == algo),
                         None)
        if match is not None:
            per_host[str(node)] = match
    skew = compute_skew(per_host)
    if skew["straggler"] is not None:
        gauge("pod_step_skew_ratio").set(float(skew["skew_ratio"]))
        gauge("pod_straggler_host").set(float(skew["straggler_proc"]))
    skew.update({"model_key": model_key,
                 "process_count": snap.get("process_count", 1),
                 "stale_nodes": snap.get("stale_nodes", [])})
    return skew
