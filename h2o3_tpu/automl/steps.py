"""AutoML modeling-step providers — the ai.h2o.automl.modeling system.

Reference: one StepsProvider per algo under
h2o-automl/src/main/java/ai/h2o/automl/modeling/ (e.g.
GBMStepsProvider.java: five prescribed defaults + a random grid;
DRFStepsProvider.java: def + XRT variant; DeepLearningStepsProvider:
def + three grids; XGBoostStepsProvider: three defaults + grid;
StackedEnsembleStepsProvider: best-of-family + all), executed by
ModelingStepsExecutor in priority groups (AutoML.java:420 planWork /
:760 learn): defaults → grids → exploitation (lr-annealing etc.) →
ensembles.

Each Step here is declarative; the executor in automl/__init__.py owns
budget accounting (max_models / max_runtime_secs / enforced
max_runtime_secs_per_model) and CV wiring.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class Step:
    provider: str                 # "GBM", "DRF", ...
    id: str                       # step name, e.g. "GBM_2"
    algo: str                     # builder algo key
    kind: str = "default"         # default | grid | exploitation | ensemble
    params: Dict = dataclasses.field(default_factory=dict)
    hyper: Optional[Dict] = None  # grid hyper space (kind == "grid")
    grid_models: int = 5          # budget share for a grid step
    group: int = 1                # execution priority group


def glm_steps(seed: int) -> List[Step]:
    """GLMStepsProvider: one default with lambda search over alphas."""
    return [Step("GLM", "GLM_1", "glm", "default",
                 {"lambda_search": True, "nlambdas": 10,
                  "alpha": 0.5, "seed": seed}, group=1)]


def gbm_steps(seed: int) -> List[Step]:
    """GBMStepsProvider: 5 prescribed defaults (depth/sample shapes),
    then one random grid, then an lr-annealing exploitation step."""
    common = {"sample_rate": 0.8, "col_sample_rate_per_tree": 0.8,
              "score_tree_interval": 5, "ntrees": 100,
              "stopping_rounds": 3}
    defs = [
        Step("GBM", "GBM_1", "gbm", "default",
             {**common, "max_depth": 6, "min_rows": 1.0, "seed": seed},
             group=1),
        Step("GBM", "GBM_2", "gbm", "default",
             {**common, "max_depth": 7, "min_rows": 10.0, "seed": seed},
             group=2),
        Step("GBM", "GBM_3", "gbm", "default",
             {**common, "max_depth": 8, "min_rows": 10.0, "seed": seed},
             group=2),
        Step("GBM", "GBM_4", "gbm", "default",
             {**common, "max_depth": 10, "min_rows": 10.0, "seed": seed},
             group=3),
        Step("GBM", "GBM_5", "gbm", "default",
             {**common, "max_depth": 15, "min_rows": 100.0, "seed": seed},
             group=3),
    ]
    grid = Step("GBM", "GBM_grid_1", "gbm", "grid",
                {"ntrees": 60, "score_tree_interval": 5,
                 "stopping_rounds": 3, "seed": seed},
                hyper={"max_depth": [3, 4, 5, 6, 7, 8, 9, 10, 12, 15],
                       "min_rows": [1.0, 5.0, 10.0, 15.0, 30.0, 100.0],
                       "learn_rate": [0.01, 0.05, 0.08, 0.1, 0.15, 0.2],
                       "sample_rate": [0.5, 0.6, 0.7, 0.8, 0.9, 1.0],
                       "col_sample_rate_per_tree":
                           [0.4, 0.7, 1.0]},
                grid_models=6, group=4)
    # exploitation: anneal the learn rate of the best GBM so far
    # (ai/h2o/automl/modeling/GBMStepsProvider lr_annealing step)
    explo = Step("GBM", "GBM_lr_annealing", "gbm", "exploitation",
                 {"seed": seed}, group=6)
    return defs + [grid, explo]


def drf_steps(seed: int) -> List[Step]:
    """DRFStepsProvider: default forest + the XRT variant (extremely
    randomized trees: random-split histograms,
    DRFStepsProvider.java XRT step)."""
    return [
        Step("DRF", "DRF_1", "drf", "default",
             {"ntrees": 50, "max_depth": 20, "seed": seed}, group=2),
        Step("DRF", "XRT_1", "drf", "default",
             {"ntrees": 50, "max_depth": 20, "seed": seed,
              "histogram_type": "random"}, group=3),
    ]


def deeplearning_steps(seed: int) -> List[Step]:
    """DeepLearningStepsProvider: one default + three grids over
    architecture/regularization."""
    return [
        Step("DeepLearning", "DeepLearning_1", "deeplearning", "default",
             {"hidden": [64, 64], "epochs": 10, "seed": seed,
              "stopping_rounds": 3}, group=3),
        Step("DeepLearning", "DeepLearning_grid_1", "deeplearning", "grid",
             {"epochs": 10, "seed": seed, "stopping_rounds": 3},
             hyper={"hidden": [[32], [64], [128], [32, 32], [64, 64],
                               [128, 128]],
                    "input_dropout_ratio": [0.0, 0.05, 0.1],
                    "rate": [0.005, 0.01, 0.02]},
             grid_models=3, group=4),
        Step("DeepLearning", "DeepLearning_grid_2", "deeplearning", "grid",
             {"epochs": 10, "seed": seed + 1, "stopping_rounds": 3},
             hyper={"hidden": [[64, 64, 64], [128, 64, 32]],
                    "activation": ["rectifier", "tanh"],
                    "l1": [0.0, 1e-4], "l2": [0.0, 1e-4]},
             grid_models=3, group=5),
    ]


def xgboost_steps(seed: int) -> List[Step]:
    """XGBoostStepsProvider: three defaults + a random grid (the
    xgboost facade maps onto native TPU trees — SURVEY §7 item 8)."""
    return [
        Step("XGBoost", "XGBoost_1", "xgboost", "default",
             {"ntrees": 100, "max_depth": 10, "min_rows": 5.0,
              "sample_rate": 0.6, "col_sample_rate_per_tree": 0.8,
              "seed": seed}, group=1),
        Step("XGBoost", "XGBoost_2", "xgboost", "default",
             {"ntrees": 100, "max_depth": 20, "min_rows": 10.0,
              "sample_rate": 0.6, "col_sample_rate_per_tree": 0.8,
              "seed": seed}, group=2),
        Step("XGBoost", "XGBoost_3", "xgboost", "default",
             {"ntrees": 100, "max_depth": 5, "min_rows": 3.0,
              "sample_rate": 0.8, "col_sample_rate_per_tree": 0.8,
              "seed": seed}, group=2),
        Step("XGBoost", "XGBoost_grid_1", "xgboost", "grid",
             {"ntrees": 60, "seed": seed},
             hyper={"max_depth": [3, 5, 7, 10, 15],
                    "min_rows": [1.0, 5.0, 10.0],
                    "sample_rate": [0.6, 0.8, 1.0],
                    "reg_lambda": [0.1, 1.0, 10.0]},
             grid_models=5, group=4),
    ]


def ensemble_steps(seed: int) -> List[Step]:
    """StackedEnsembleStepsProvider: best-of-family then all-models."""
    return [
        Step("StackedEnsemble", "StackedEnsemble_BestOfFamily",
             "stackedensemble", "ensemble", {}, group=9),
        Step("StackedEnsemble", "StackedEnsemble_AllModels",
             "stackedensemble", "ensemble", {}, group=10),
    ]


PROVIDERS = {
    "glm": glm_steps,
    "gbm": gbm_steps,
    "drf": drf_steps,
    "deeplearning": deeplearning_steps,
    "xgboost": xgboost_steps,
    "stackedensemble": ensemble_steps,
}


def modeling_plan(seed: int, include=None, exclude=None) -> List[Step]:
    """All steps from all providers, ordered by execution group —
    the planWork output (AutoML.java:420)."""
    steps: List[Step] = []
    for algo, provider in PROVIDERS.items():
        if include is not None and algo not in include:
            continue
        if exclude and algo in exclude:
            continue
        steps.extend(provider(seed))
    steps.sort(key=lambda s: s.group)
    return steps
