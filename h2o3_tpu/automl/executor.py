"""AutoML step executor — budget accounting + per-model runtime caps +
step execution (with crash recovery).

Reference: ai/h2o/automl/ModelingStepsExecutor (driven from
AutoML.java:760 learn) — runs each ModelingStep under the global
max_models / max_runtime_secs budget, with per-model
max_runtime_secs_per_model enforced by cancelling the model's Job when
the cap expires (the reference passes the cap into
Model.Parameters._max_runtime_secs; here a watchdog cancels the Job,
which every builder honours at its next progress checkpoint).

``run_step`` executes one modeling step; when the owning AutoML run has
a ``recovery_dir``, grid steps snapshot per-model into a nested
recovery dir (core/recovery.py) and resume their own partial walks, so
a kill mid-grid costs at most the model in flight.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.automl")


class Budget:
    """max_models / max_runtime_secs / per-model cap accounting
    (AutoML.java planWork time allocation)."""

    def __init__(self, max_models: int, max_runtime_secs: float,
                 per_model_secs: float):
        self.max_models = max_models or 10 ** 9
        self.deadline = (time.time() + max_runtime_secs
                         if max_runtime_secs else None)
        self.per_model_secs = per_model_secs
        self.trained = 0
        self.inflight = 0
        self._lock = threading.Lock()   # candidates train in parallel

    def add_trained(self, k: int = 1) -> None:
        with self._lock:
            self.trained += k

    def try_start(self) -> bool:
        """Reserve one model slot before training starts — parallel
        workers otherwise all pass exhausted() in the read-then-train
        window and overshoot max_models."""
        with self._lock:
            if self.trained + self.inflight >= self.max_models:
                return False
            if self.deadline is not None and time.time() > self.deadline:
                return False
            self.inflight += 1
            return True

    def finish(self, trained_count: int) -> None:
        """Release the reserved slot; count what actually trained."""
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            self.trained += trained_count

    def exhausted(self) -> bool:
        if self.trained >= self.max_models:
            return True
        return self.deadline is not None and time.time() > self.deadline

    def remaining_models(self) -> int:
        return max(0, self.max_models - self.trained)

    def remaining_secs(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.time())

    def model_cap(self) -> Optional[float]:
        """Per-model wallclock cap: the explicit cap, bounded by what is
        left of the global budget AND time-sliced so one expensive
        candidate cannot eat the whole plan (AutoML.java planWork time
        allocation role — one 361s XGBoost left 15 steps untrained)."""
        caps = []
        if self.per_model_secs:
            caps.append(self.per_model_secs)
        rem = self.remaining_secs()
        if rem is not None:
            with self._lock:
                left = max(1, self.max_models - self.trained
                           - self.inflight + 1)
            caps.append(max(60.0, rem / min(left, 8)))
            caps.append(rem)
        return min(caps) if caps else None


def train_capped(builder, frame, y, x, budget: Budget):
    """Train one model under the per-model cap.

    The builder runs as a background Job; a watchdog cancels it when the
    cap expires (Job.cancel raises JobCancelledException at the next
    job.update checkpoint — every training loop calls update at least
    once per scan chunk / IRLS lambda / DL epoch)."""
    cap = budget.model_cap()
    graceful = bool(cap) and "max_runtime_secs" in builder.accepted_params()
    if graceful:
        # builders that honor max_runtime_secs stop GRACEFULLY at a
        # chunk boundary and return the partial model (the reference
        # semantic) — the watchdog below becomes a backstop only
        builder.set_max_runtime(cap)
    job = builder.train(frame, y=y, x=x, background=True)
    timer = None
    if cap:
        # graceful builders get slack to reach their chunk boundary;
        # others are cancelled AT the cap like before
        timer = threading.Timer(cap * 1.5 + 30.0 if graceful else cap,
                                job.cancel)
        timer.daemon = True
        timer.start()
    job.join()
    if timer:
        timer.cancel()
    if job.status == "CANCELLED":
        raise TimeoutError(
            f"max_runtime_secs_per_model ({cap:.0f}s) exceeded")
    if job.status != "DONE":
        raise RuntimeError(job.exception or f"job {job.status}")
    return job.result


def run_step(aml, step, budget: Budget, training_frame, y, x) -> List:
    """Execute one modeling step; returns the trained models
    (ModelingStepsExecutor.submit role, moved from H2OAutoML._run_step).

    Runs on a worker thread — a budget SLOT is reserved up front
    (try_start) so parallel siblings cannot all pass the exhausted
    check and overshoot max_models; only the caller touches the
    leaderboard."""
    from h2o3_tpu.ml.grid import GridSearch, resume_grid
    from h2o3_tpu.models import get_builder
    if not budget.try_start():
        return []
    trained_count = 0
    try:
        if step.kind == "exploitation":
            m = aml._lr_annealing_step(budget, training_frame, y, x)
            if m is None:
                return []
            m.output["automl_step"] = step.id
            trained_count = 1
            return [m]
        cls = get_builder(step.algo)
        if step.kind == "grid":
            sub_dir = None
            if aml._recovery is not None:
                sub_dir = os.path.join(aml._recovery.dir, step.id)
            # grid combos route through the model-batched path when
            # eligible (parallel/model_batch.py via GridSearch.train):
            # shape buckets train as one vmapped program; CV folds,
            # structural knob spreads and batch failures fall back
            # per-combo inside the grid walk
            from h2o3_tpu import telemetry
            from h2o3_tpu.parallel import model_batch
            with telemetry.span("automl.grid_step", step=step.id,
                                batched=model_batch.enabled()):
                if sub_dir and os.path.exists(
                        os.path.join(sub_dir, "grid_state.json")):
                    # the previous process died inside this grid walk:
                    # its per-combo snapshots resume here — only the
                    # combo in flight at the kill retrains (the resumed
                    # walk re-plans batch buckets over what is LEFT)
                    grid = resume_grid(sub_dir, training_frame)
                else:
                    remaining = budget.remaining_models()
                    rem_s = budget.remaining_secs()
                    gs = GridSearch(
                        cls, step.hyper,
                        search_criteria={
                            "strategy": "RandomDiscrete",
                            "max_models": min(remaining, step.grid_models),
                            "max_runtime_secs": rem_s or 0,
                            "seed": aml.seed},
                        recovery_dir=sub_dir,
                        **{**step.params, "nfolds": aml.nfolds})
                    grid = gs.train(training_frame, y=y, x=x)
            for m in grid.models:
                m.output["automl_step"] = step.id
            trained_count = len(grid.models)
            return list(grid.models)
        params = {**step.params, "nfolds": aml.nfolds}
        if "stopping_rounds" in getattr(cls, "DEFAULTS", {}):
            params.setdefault("stopping_rounds", aml.stopping_rounds)
            params.setdefault("stopping_tolerance", aml.stopping_tolerance)
        params = {k: v for k, v in params.items()
                  if k in cls.accepted_params()}
        fit_dir = (os.path.join(aml._recovery.dir, "fit_state")
                   if aml._recovery is not None else None)
        m = _train_plain(cls, params, training_frame, y, x, budget,
                         fit_dir, step)
        m.output["automl_step"] = step.id
        trained_count = 1
        return [m]
    finally:
        budget.finish(trained_count)


def _train_plain(cls, params, training_frame, y, x, budget: Budget,
                 fit_dir: Optional[str], step):
    """Train one plain-model AutoML step. On a scheduled cloud
    (parallel/scheduler.py) the step becomes a 1-item scheduled run —
    the run-sequence rotation spreads successive steps across hosts,
    and a host death mid-step reassigns it (the traveling fit snapshot
    resumes mid-fit). Otherwise the step trains locally, inside the
    in-fit checkpoint scope when the run has a recovery dir (a SIGKILL
    mid-fit resumes inside the fit on the next resume_automl(), not
    from round 0 of the step)."""
    from h2o3_tpu.core import recovery as _recovery
    from h2o3_tpu.parallel import scheduler as _sched
    if _sched.active():
        def execute(_k):
            from h2o3_tpu.parallel import mesh as mesh_mod
            with mesh_mod.local_mesh_scope():
                lf = training_frame.local_copy()
                # every process holds its own SPMD timer copy; only the
                # executing host's timer can fire against its local job
                m = train_capped(cls(**params), lf, y, x, budget)
                return _sched.lower_to_bytes(_sched.detach_model(m))
        res = _sched.run(f"automl:{step.id}", 1, execute,
                         fit_dir=fit_dir, deadline=budget.deadline)
        rec = res.get(0)
        if rec is None:
            raise TimeoutError(
                "budget deadline hit before the scheduled step finished")
        if not rec["ok"]:
            if "max_runtime_secs_per_model" in rec["error"]:
                raise TimeoutError(rec["error"])
            raise RuntimeError(rec["error"])
        return _sched.install_model(_sched.from_bytes(rec["data"]))
    if fit_dir:
        with _recovery.fit_checkpoint_scope(fit_dir):
            return train_capped(cls(**params), training_frame, y, x,
                                budget)
    return train_capped(cls(**params), training_frame, y, x, budget)
