"""AutoML — step-provider modeling plan + budgeted execution.

Reference: ai/h2o/automl/AutoML.java:49 — planWork (AutoML.java:420)
allocates a budget across modeling steps from ModelingStepsProviders
(modeling/{XGBoost,GLM,GBM,DRF,DeepLearning,StackedEnsemble}
StepsProvider), learn (AutoML.java:760) executes defaults → grids →
exploitation under max_models / max_runtime_secs with per-model caps,
every model cross-validated, results ranked in
hex.leaderboard.Leaderboard, StackedEnsembles last; optional
TargetEncoding preprocessing (ai/h2o/automl/preprocessing/
TargetEncoding.java) for tree algos on high-cardinality categoricals.

The step plan lives in automl/steps.py; budget/per-model-cap
enforcement in automl/executor.py.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

from h2o3_tpu.automl.executor import Budget, run_step, train_capped
from h2o3_tpu.automl.steps import modeling_plan
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.ml.ensemble import StackedEnsembleEstimator
from h2o3_tpu.ml.leaderboard import Leaderboard
from h2o3_tpu.models import get_builder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.automl")


class H2OAutoML:
    """h2o-py H2OAutoML-compatible surface (h2o-py/h2o/automl/).

    ``keep_cross_validation_predictions`` is effectively always True here
    (holdouts are kept in-memory for stacking); ``balance_classes`` is not
    implemented and warns if set; ``verbosity`` only affects logging.
    """

    def __init__(self, max_models: int = 0, max_runtime_secs: float = 3600.0,
                 seed: int = -1, nfolds: int = 5,
                 project_name: Optional[str] = None,
                 sort_metric: Optional[str] = None,
                 include_algos: Optional[Sequence[str]] = None,
                 exclude_algos: Optional[Sequence[str]] = None,
                 stopping_rounds: int = 3, stopping_tolerance: float = 1e-3,
                 keep_cross_validation_predictions: bool = True,
                 verbosity: str = "warn", balance_classes: bool = False,
                 max_runtime_secs_per_model: float = 0.0,
                 preprocessing: Optional[Sequence[str]] = None,
                 recovery_dir: Optional[str] = None):
        self.max_models = int(max_models)
        self.max_runtime_secs = float(max_runtime_secs)
        self.seed = int(seed) if int(seed) >= 0 else 5723
        # h2o-py sends nfolds=-1 for "auto" (H2OAutoML default since
        # 3.46); the reference resolves it to 5-fold CV (AutoML.java
        # nfolds default) — builders reject a literal -1
        self.nfolds = 5 if int(nfolds) == -1 else int(nfolds)
        self.project_name = project_name or f"automl_{int(time.time())}"
        self.sort_metric = sort_metric
        self.include = ({a.lower() for a in include_algos}
                        if include_algos else None)
        self.exclude = {a.lower() for a in (exclude_algos or ())}
        self.leaderboard_obj = Leaderboard(self.project_name, sort_metric)
        self.stopping_rounds = int(stopping_rounds)
        self.stopping_tolerance = float(stopping_tolerance)
        self.max_runtime_secs_per_model = float(max_runtime_secs_per_model)
        self.preprocessing = list(preprocessing or [])
        self.event_log: List[dict] = []
        # hex/faulttolerance/Recovery.java role for AutoML: when set,
        # every trained model + per-step walk state snapshot to this dir
        # so resume_automl() can continue after a crash (core/recovery.py)
        self.recovery_dir = recovery_dir
        self._recovery = None
        if recovery_dir:
            from h2o3_tpu.core.recovery import Recovery
            self._recovery = Recovery(recovery_dir,
                                      state_name="automl_state")
        self._skip_steps: set = set()       # step ids done pre-crash
        self._prior_models: List = []       # models restored on resume
        self._step_models: dict = {}        # step id -> snapshot files
        # snapshot-dir listing cache: each nested grid-step dir is read
        # ONCE per run (one os.listdir), never one os.path.exists per
        # model per step snapshot — resume_automl on a wide leaderboard
        # paid a filesystem stat per restored model per step
        self._snapshot_listing: dict = {}   # step id -> {relative paths}
        if balance_classes:
            log.warning("balance_classes is not implemented; ignoring")

    # -- helpers -------------------------------------------------------
    def _allowed(self, algo: str) -> bool:
        a = algo.lower()
        if self.include is not None and a not in self.include:
            return False
        return a not in self.exclude

    @property
    def leader(self):
        return self.leaderboard_obj.leader

    @property
    def leaderboard(self):
        return self.leaderboard_obj

    def predict(self, frame: Frame) -> Frame:
        if getattr(self, "_te_model", None) is not None:
            # models trained on target-encoded columns; encode the
            # scoring frame the same way (TargetEncoding preprocessing)
            frame = self._te_model.transform(frame)
        return self.leader.predict(frame)

    # -- train ---------------------------------------------------------
    def _maybe_target_encode(self, frame: Frame, y: str, x):
        """Optional TargetEncoding preprocessing for tree algos
        (ai/h2o/automl/preprocessing/TargetEncoding.java): encode
        categorical predictors with cardinality >= 25 using kfold-safe
        encodings; returns (encoded_frame, te_model) or (frame, None)."""
        if "target_encoding" not in self.preprocessing:
            return frame, None
        high_card = [n for n in (x or frame.names)
                     if n != y and frame.col(n).is_categorical
                     and frame.col(n).cardinality >= 25]
        if not high_card:
            return frame, None
        from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
        te = TargetEncoderEstimator(
            data_leakage_handling="loo", noise=0.01,
            blending=True, seed=self.seed).train(frame, y=y, x=high_card)
        enc = te.transform(frame, as_training=True)
        self._log_event("preprocessing", f"target-encoded {high_card}")
        return enc, te

    def _log_event(self, stage: str, message: str):
        self.event_log.append({"timestamp": time.time(), "stage": stage,
                               "message": message})
        log.info("automl[%s]: %s", stage, message)

    def _lr_annealing_step(self, budget, training_frame, y, x):
        """Exploitation (GBMStepsProvider lr_annealing): retrain the best
        GBM so far with more trees and an annealed learn rate."""
        best_gbm = next((m for m in self.leaderboard_obj.sorted_models()
                         if m.algo == "gbm"), None)
        if best_gbm is None:
            return None
        params = {k: v for k, v in best_gbm.params.items()
                  if k in get_builder("gbm").accepted_params()}
        params.update(ntrees=max(int(params.get("ntrees", 50) * 2), 100),
                      learn_rate=float(params.get("learn_rate", 0.1)) * 0.5,
                      stopping_rounds=3, nfolds=self.nfolds)
        return train_capped(get_builder("gbm")(**params),
                            training_frame, y, x, budget)

    # -- fault tolerance (core/recovery.py; resume_automl below) -------
    def _recovery_params(self) -> dict:
        """Ctor kwargs, JSON-shaped, sufficient to rebuild this run."""
        return {
            "max_models": self.max_models,
            "max_runtime_secs": self.max_runtime_secs,
            "seed": self.seed,
            "nfolds": self.nfolds,
            "project_name": self.project_name,
            "sort_metric": self.sort_metric,
            "include_algos": sorted(self.include) if self.include else None,
            "exclude_algos": sorted(self.exclude) or None,
            "stopping_rounds": self.stopping_rounds,
            "stopping_tolerance": self.stopping_tolerance,
            "max_runtime_secs_per_model": self.max_runtime_secs_per_model,
            "preprocessing": self.preprocessing or None,
        }

    def _snapshot_state(self, y: str, x) -> None:
        self._recovery.write_state({
            "params": self._recovery_params(),
            "y": y, "x": list(x) if x else None,
            "done_steps": sorted(self._skip_steps),
            "models": self._step_models,
        })

    def _step_snapshot_files(self, step_id: str) -> set:
        """Relative snapshot paths under the step's nested recovery dir,
        read with ONE os.listdir per step per run (cached — was one
        os.path.exists per model per step snapshot)."""
        cached = self._snapshot_listing.get(step_id)
        if cached is not None:
            return cached
        sub = os.path.join(self._recovery.dir, step_id)
        files: set = set()
        if os.path.isdir(sub):
            files = {f"{step_id}/{f}" for f in os.listdir(sub)
                     if f.endswith(".bin")}
        self._snapshot_listing[step_id] = files
        return files

    def _on_step_done(self, step_id: str, models: List, y: str, x) -> None:
        """Persist leaderboard membership + step completion after every
        trained model reaches the leaderboard (Recovery.onModel role).
        Grid steps already snapshotted per-model into their nested dir;
        everything else snapshots here."""
        if self._recovery is None:
            return
        grid_files = self._step_snapshot_files(step_id)
        files = []
        for m in models:
            rel = f"{step_id}/{m.key}.bin"
            if rel in grid_files:
                files.append(rel)                    # grid snapshot
            else:
                files.append(self._recovery.save_model(m))
        self._step_models[step_id] = files
        self._skip_steps.add(step_id)
        self._snapshot_state(y, x)

    def train(self, y: str, training_frame: Frame,
              x: Optional[Sequence[str]] = None,
              validation_frame: Optional[Frame] = None,
              leaderboard_frame: Optional[Frame] = None):
        t0 = time.time()
        budget = Budget(self.max_models, self.max_runtime_secs,
                       self.max_runtime_secs_per_model)
        if self._prior_models:
            # resumed run: restored models count toward max_models —
            # the budget must not re-spend what the dead process trained
            budget.add_trained(len(self._prior_models))
        plan = modeling_plan(self.seed, include=self.include,
                             exclude=self.exclude)
        self._log_event("init", f"plan: {[st.id for st in plan]}")
        if self._skip_steps:
            self._log_event(
                "resume", f"skipping {sorted(self._skip_steps)} "
                f"({len(self._prior_models)} models restored)")
        if self._recovery is not None:
            # state exists from minute zero: a kill before the first
            # model still leaves a resumable run
            self._snapshot_state(y, x)
        training_frame, te_model = self._maybe_target_encode(
            training_frame, y, x)
        self._te_model = te_model
        if te_model is not None and x is not None:
            # explicit predictor list: the encoded columns must join it
            x = list(x) + [c for c in training_frame.names
                           if c.endswith("_te")]
        trained: List = []

        # candidates run as PARALLEL jobs within each priority group
        # (hex/ParallelModelBuilder.java; AutoML.java:760 learn walks
        # groups in order). Groups are barriers: exploitation steps
        # read the leaderboard that earlier groups produced. On one
        # chip parallelism overlaps host-side prep + compiles with
        # device execution; on a pod each job gets its own dispatch.
        import os as _os
        par = int(_os.environ.get("H2O3TPU_AUTOML_PARALLEL", "0") or 0)
        if par <= 0:
            # ONE chip: sequential by default. Parallel workers each pay
            # their own first-shape compile (~2-3 min through the tunnel
            # compile service) and contend for it — measured: 3 parallel
            # candidates ALL hit a 240s per-model cap that each clears
            # in ~15s warm sequential (0/20 models vs 3+/20). The async
            # dispatch queue already overlaps host prep with device
            # execution inside one thread; on a pod, raise via env.
            par = 1
        from h2o3_tpu.parallel import scheduler as _sched
        if par > 1 and _sched.active():
            # the cluster work scheduler already fans steps across
            # hosts, and its SPMD run() entry needs every process to
            # reach scheduled runs in the same order — thread-parallel
            # step submission would interleave differently per process
            self._log_event(
                "scheduler", "H2O3TPU_AUTOML_PARALLEL ignored on a "
                "scheduled cloud (steps fan out across hosts instead)")
            par = 1
        from concurrent.futures import ThreadPoolExecutor, as_completed
        groups = sorted({s.group for s in plan if s.kind != "ensemble"})
        for g in groups:
            if budget.exhausted():
                self._log_event("budget", "budget exhausted; stopping plan")
                break
            steps_g = [s for s in plan
                       if s.group == g and s.kind != "ensemble"
                       and s.id not in self._skip_steps]
            with ThreadPoolExecutor(max_workers=par) as ex:
                futs = {ex.submit(run_step, self, s, budget,
                                  training_frame, y, x): s
                        for s in steps_g}
                for fut in as_completed(futs):
                    step = futs[fut]
                    try:
                        models = fut.result()
                    except TimeoutError as e:
                        self._log_event("timeout", f"{step.id}: {e}")
                        continue
                    except Exception as e:
                        self._log_event("error", f"{step.id} failed: {e}")
                        continue
                    if not models:
                        continue
                    trained.extend(models)
                    self.leaderboard_obj.add(*models)
                    self._on_step_done(step.id, models, y, x)
                    self._log_event(
                        "model",
                        f"{step.id} done ({budget.trained} models, "
                        f"{time.time() - t0:.0f}s)")

        # stacked ensembles last (StackedEnsembleStepsProvider):
        # best-of-family + all-models. Resumed models participate — CV
        # holdouts ride the binary snapshots (persist pickles them).
        with_cv = [m for m in self._prior_models + trained
                   if getattr(m, "_cv_holdout", None) is not None]
        best_of_family = {}
        if self._allowed("stackedensemble") and len(with_cv) >= 2:
            for m in self.leaderboard_obj.sorted_models():
                if m in with_cv and m.algo not in best_of_family:
                    best_of_family[m.algo] = m
            if (len(best_of_family) >= 2 and
                    "StackedEnsemble_BestOfFamily" not in self._skip_steps):
                try:
                    se = StackedEnsembleEstimator(
                        base_models=list(best_of_family.values())).train(
                        training_frame, y=y, x=x)
                    se.output["automl_step"] = "StackedEnsemble_BestOfFamily"
                    self.leaderboard_obj.add(se)
                    self._on_step_done("StackedEnsemble_BestOfFamily",
                                       [se], y, x)
                except Exception as e:
                    self._log_event("error",
                                    f"best-of-family ensemble failed: {e}")
            if (len(with_cv) > max(2, len(best_of_family)) and
                    "StackedEnsemble_AllModels" not in self._skip_steps):
                try:
                    se2 = StackedEnsembleEstimator(
                        base_models=with_cv[:10]).train(
                        training_frame, y=y, x=x)
                    se2.output["automl_step"] = "StackedEnsemble_AllModels"
                    self.leaderboard_obj.add(se2)
                    self._on_step_done("StackedEnsemble_AllModels",
                                       [se2], y, x)
                except Exception as e:
                    self._log_event("error",
                                    f"all-models ensemble failed: {e}")

        if self._recovery is not None:
            # the plan completed: unconsumed in-fit snapshots under the
            # recovery dir (combo/model killed then resumed elsewhere)
            # must not leak into the next resume
            from h2o3_tpu.core import recovery as recovery_mod
            recovery_mod.clear_fit_snapshots(
                os.path.join(self._recovery.dir, "fit_state"))
        self._log_event("done",
                        f"{len(self.leaderboard_obj.models)} models in "
                        f"{time.time() - t0:.0f}s; leader="
                        f"{self.leader.key if self.leader else None}")
        return self.leader


def resume_automl(recovery_dir: str, training_frame: Frame,
                  validation_frame: Optional[Frame] = None,
                  leaderboard_frame: Optional[Frame] = None) -> H2OAutoML:
    """Resume an AutoML run killed mid-plan from its recovery snapshots
    (hex/faulttolerance/Recovery.onDone re-run path, AutoML flavor).

    Rebuilds the leaderboard from the persisted model binaries, marks the
    completed steps done so no step retrains twice, and continues the
    modeling plan from the next step. The wallclock budget restarts (the
    dead process's elapsed time is unknowable and usually irrelevant
    after a restart); ``max_models`` counts restored models. Returns the
    resumed :class:`H2OAutoML` with a complete leaderboard."""
    from h2o3_tpu.core.recovery import Recovery
    state = Recovery(recovery_dir, state_name="automl_state").read_state()
    if state is None:
        raise FileNotFoundError(
            f"no automl_state.json under {recovery_dir}")
    aml = H2OAutoML(recovery_dir=recovery_dir, **state["params"])
    rec = aml._recovery
    files = [f for fs in state["models"].values() for f in fs]
    prior = rec.load_models(files)
    aml._prior_models = prior
    aml._skip_steps = set(state["done_steps"])
    aml._step_models = dict(state["models"])
    aml.leaderboard_obj.add(*prior)
    aml._log_event("resume", f"restored {len(prior)} models, "
                   f"{len(aml._skip_steps)} steps done")
    aml.train(y=state["y"], training_frame=training_frame,
              x=state["x"], validation_frame=validation_frame,
              leaderboard_frame=leaderboard_frame)
    return aml
