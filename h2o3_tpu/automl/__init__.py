"""AutoML — step-provider modeling plan + budgeted execution.

Reference: ai/h2o/automl/AutoML.java:49 — planWork (AutoML.java:420)
allocates a budget across modeling steps from ModelingStepsProviders
(modeling/{XGBoost,GLM,GBM,DRF,DeepLearning,StackedEnsemble}
StepsProvider), learn (AutoML.java:760) executes defaults → grids →
exploitation under max_models / max_runtime_secs with per-model caps,
every model cross-validated, results ranked in
hex.leaderboard.Leaderboard, StackedEnsembles last; optional
TargetEncoding preprocessing (ai/h2o/automl/preprocessing/
TargetEncoding.java) for tree algos on high-cardinality categoricals.

The step plan lives in automl/steps.py; budget/per-model-cap
enforcement in automl/executor.py.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from h2o3_tpu.automl.executor import Budget, train_capped
from h2o3_tpu.automl.steps import Step, modeling_plan
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.ml.ensemble import StackedEnsembleEstimator
from h2o3_tpu.ml.grid import GridSearch
from h2o3_tpu.ml.leaderboard import Leaderboard
from h2o3_tpu.models import get_builder
from h2o3_tpu.utils.log import get_logger

log = get_logger("h2o3_tpu.automl")


class H2OAutoML:
    """h2o-py H2OAutoML-compatible surface (h2o-py/h2o/automl/).

    ``keep_cross_validation_predictions`` is effectively always True here
    (holdouts are kept in-memory for stacking); ``balance_classes`` is not
    implemented and warns if set; ``verbosity`` only affects logging.
    """

    def __init__(self, max_models: int = 0, max_runtime_secs: float = 3600.0,
                 seed: int = -1, nfolds: int = 5,
                 project_name: Optional[str] = None,
                 sort_metric: Optional[str] = None,
                 include_algos: Optional[Sequence[str]] = None,
                 exclude_algos: Optional[Sequence[str]] = None,
                 stopping_rounds: int = 3, stopping_tolerance: float = 1e-3,
                 keep_cross_validation_predictions: bool = True,
                 verbosity: str = "warn", balance_classes: bool = False,
                 max_runtime_secs_per_model: float = 0.0,
                 preprocessing: Optional[Sequence[str]] = None):
        self.max_models = int(max_models)
        self.max_runtime_secs = float(max_runtime_secs)
        self.seed = int(seed) if int(seed) >= 0 else 5723
        # h2o-py sends nfolds=-1 for "auto" (H2OAutoML default since
        # 3.46); the reference resolves it to 5-fold CV (AutoML.java
        # nfolds default) — builders reject a literal -1
        self.nfolds = 5 if int(nfolds) == -1 else int(nfolds)
        self.project_name = project_name or f"automl_{int(time.time())}"
        self.sort_metric = sort_metric
        self.include = ({a.lower() for a in include_algos}
                        if include_algos else None)
        self.exclude = {a.lower() for a in (exclude_algos or ())}
        self.leaderboard_obj = Leaderboard(self.project_name, sort_metric)
        self.stopping_rounds = int(stopping_rounds)
        self.stopping_tolerance = float(stopping_tolerance)
        self.max_runtime_secs_per_model = float(max_runtime_secs_per_model)
        self.preprocessing = list(preprocessing or [])
        self.event_log: List[dict] = []
        if balance_classes:
            log.warning("balance_classes is not implemented; ignoring")

    # -- helpers -------------------------------------------------------
    def _allowed(self, algo: str) -> bool:
        a = algo.lower()
        if self.include is not None and a not in self.include:
            return False
        return a not in self.exclude

    @property
    def leader(self):
        return self.leaderboard_obj.leader

    @property
    def leaderboard(self):
        return self.leaderboard_obj

    def predict(self, frame: Frame) -> Frame:
        if getattr(self, "_te_model", None) is not None:
            # models trained on target-encoded columns; encode the
            # scoring frame the same way (TargetEncoding preprocessing)
            frame = self._te_model.transform(frame)
        return self.leader.predict(frame)

    # -- train ---------------------------------------------------------
    def _maybe_target_encode(self, frame: Frame, y: str, x):
        """Optional TargetEncoding preprocessing for tree algos
        (ai/h2o/automl/preprocessing/TargetEncoding.java): encode
        categorical predictors with cardinality >= 25 using kfold-safe
        encodings; returns (encoded_frame, te_model) or (frame, None)."""
        if "target_encoding" not in self.preprocessing:
            return frame, None
        high_card = [n for n in (x or frame.names)
                     if n != y and frame.col(n).is_categorical
                     and frame.col(n).cardinality >= 25]
        if not high_card:
            return frame, None
        from h2o3_tpu.models.targetencoder import TargetEncoderEstimator
        te = TargetEncoderEstimator(
            data_leakage_handling="loo", noise=0.01,
            blending=True, seed=self.seed).train(frame, y=y, x=high_card)
        enc = te.transform(frame, as_training=True)
        self._log_event("preprocessing", f"target-encoded {high_card}")
        return enc, te

    def _log_event(self, stage: str, message: str):
        self.event_log.append({"timestamp": time.time(), "stage": stage,
                               "message": message})
        log.info("automl[%s]: %s", stage, message)

    def _lr_annealing_step(self, budget, training_frame, y, x):
        """Exploitation (GBMStepsProvider lr_annealing): retrain the best
        GBM so far with more trees and an annealed learn rate."""
        best_gbm = next((m for m in self.leaderboard_obj.sorted_models()
                         if m.algo == "gbm"), None)
        if best_gbm is None:
            return None
        params = {k: v for k, v in best_gbm.params.items()
                  if k in get_builder("gbm").accepted_params()}
        params.update(ntrees=max(int(params.get("ntrees", 50) * 2), 100),
                      learn_rate=float(params.get("learn_rate", 0.1)) * 0.5,
                      stopping_rounds=3, nfolds=self.nfolds)
        return train_capped(get_builder("gbm")(**params),
                            training_frame, y, x, budget)

    def _run_step(self, step: Step, budget: Budget, training_frame: Frame,
                  y: str, x) -> List:
        """Execute one modeling step; returns the trained models.
        Runs on a worker thread — a budget SLOT is reserved up front
        (try_start) so parallel siblings cannot all pass the exhausted
        check and overshoot max_models; only the caller touches the
        leaderboard."""
        if not budget.try_start():
            return []
        trained_count = 0
        try:
            if step.kind == "exploitation":
                m = self._lr_annealing_step(budget, training_frame, y, x)
                if m is None:
                    return []
                m.output["automl_step"] = step.id
                trained_count = 1
                return [m]
            cls = get_builder(step.algo)
            if step.kind == "grid":
                remaining = budget.remaining_models()
                rem_s = budget.remaining_secs()
                gs = GridSearch(
                    cls, step.hyper,
                    search_criteria={
                        "strategy": "RandomDiscrete",
                        "max_models": min(remaining, step.grid_models),
                        "max_runtime_secs": rem_s or 0,
                        "seed": self.seed},
                    **{**step.params, "nfolds": self.nfolds})
                grid = gs.train(training_frame, y=y, x=x)
                for m in grid.models:
                    m.output["automl_step"] = step.id
                trained_count = len(grid.models)
                return list(grid.models)
            params = {**step.params, "nfolds": self.nfolds}
            if "stopping_rounds" in getattr(cls, "DEFAULTS", {}):
                params.setdefault("stopping_rounds", self.stopping_rounds)
                params.setdefault("stopping_tolerance",
                                  self.stopping_tolerance)
            params = {k: v for k, v in params.items()
                      if k in cls.accepted_params()}
            m = train_capped(cls(**params), training_frame, y, x, budget)
            m.output["automl_step"] = step.id
            trained_count = 1
            return [m]
        finally:
            budget.finish(trained_count)

    def train(self, y: str, training_frame: Frame,
              x: Optional[Sequence[str]] = None,
              validation_frame: Optional[Frame] = None,
              leaderboard_frame: Optional[Frame] = None):
        t0 = time.time()
        budget = Budget(self.max_models, self.max_runtime_secs,
                       self.max_runtime_secs_per_model)
        plan = modeling_plan(self.seed, include=self.include,
                             exclude=self.exclude)
        self._log_event("init", f"plan: {[st.id for st in plan]}")
        training_frame, te_model = self._maybe_target_encode(
            training_frame, y, x)
        self._te_model = te_model
        if te_model is not None and x is not None:
            # explicit predictor list: the encoded columns must join it
            x = list(x) + [c for c in training_frame.names
                           if c.endswith("_te")]
        trained: List = []

        # candidates run as PARALLEL jobs within each priority group
        # (hex/ParallelModelBuilder.java; AutoML.java:760 learn walks
        # groups in order). Groups are barriers: exploitation steps
        # read the leaderboard that earlier groups produced. On one
        # chip parallelism overlaps host-side prep + compiles with
        # device execution; on a pod each job gets its own dispatch.
        import os as _os
        par = int(_os.environ.get("H2O3TPU_AUTOML_PARALLEL", "0") or 0)
        if par <= 0:
            # ONE chip: sequential by default. Parallel workers each pay
            # their own first-shape compile (~2-3 min through the tunnel
            # compile service) and contend for it — measured: 3 parallel
            # candidates ALL hit a 240s per-model cap that each clears
            # in ~15s warm sequential (0/20 models vs 3+/20). The async
            # dispatch queue already overlaps host prep with device
            # execution inside one thread; on a pod, raise via env.
            par = 1
        from concurrent.futures import ThreadPoolExecutor, as_completed
        groups = sorted({s.group for s in plan if s.kind != "ensemble"})
        for g in groups:
            if budget.exhausted():
                self._log_event("budget", "budget exhausted; stopping plan")
                break
            steps_g = [s for s in plan
                       if s.group == g and s.kind != "ensemble"]
            with ThreadPoolExecutor(max_workers=par) as ex:
                futs = {ex.submit(self._run_step, s, budget,
                                  training_frame, y, x): s
                        for s in steps_g}
                for fut in as_completed(futs):
                    step = futs[fut]
                    try:
                        models = fut.result()
                    except TimeoutError as e:
                        self._log_event("timeout", f"{step.id}: {e}")
                        continue
                    except Exception as e:
                        self._log_event("error", f"{step.id} failed: {e}")
                        continue
                    if not models:
                        continue
                    trained.extend(models)
                    self.leaderboard_obj.add(*models)
                    self._log_event(
                        "model",
                        f"{step.id} done ({budget.trained} models, "
                        f"{time.time() - t0:.0f}s)")

        # stacked ensembles last (StackedEnsembleStepsProvider):
        # best-of-family + all-models
        with_cv = [m for m in trained
                   if getattr(m, "_cv_holdout", None) is not None]
        best_of_family = {}
        if self._allowed("stackedensemble") and len(with_cv) >= 2:
            for m in self.leaderboard_obj.sorted_models():
                if m in with_cv and m.algo not in best_of_family:
                    best_of_family[m.algo] = m
            if len(best_of_family) >= 2:
                try:
                    se = StackedEnsembleEstimator(
                        base_models=list(best_of_family.values())).train(
                        training_frame, y=y, x=x)
                    se.output["automl_step"] = "StackedEnsemble_BestOfFamily"
                    self.leaderboard_obj.add(se)
                except Exception as e:
                    self._log_event("error",
                                    f"best-of-family ensemble failed: {e}")
            if len(with_cv) > max(2, len(best_of_family)):
                try:
                    se2 = StackedEnsembleEstimator(
                        base_models=with_cv[:10]).train(
                        training_frame, y=y, x=x)
                    se2.output["automl_step"] = "StackedEnsemble_AllModels"
                    self.leaderboard_obj.add(se2)
                except Exception as e:
                    self._log_event("error",
                                    f"all-models ensemble failed: {e}")

        self._log_event("done",
                        f"{len(self.leaderboard_obj.models)} models in "
                        f"{time.time() - t0:.0f}s; leader="
                        f"{self.leader.key if self.leader else None}")
        return self.leader
